# Reproducible build + run environment for processing_chain_tpu.
#
# Counterpart of the reference's Dockerfile (reference Dockerfile:1-56 +
# docker/install_ffmpeg.sh:31-67): where the reference compiles a pinned
# FFmpeg 7.0.2 CLI toolchain from source, this framework links its native
# media boundary (processing_chain_tpu/native/libpcmedia.so) against
# Debian bookworm's pinned libav 5.1 packages — the same library major
# versions the in-tree golden tests were validated against (libavcodec 59 /
# libavformat 59 / libswscale 6).
#
#   docker build -t processing-chain-tpu .
#   docker run --rm processing-chain-tpu python -m pytest tests/ -q
#
# TPU note: inside a TPU VM, base the image on your TPU-runtime image of
# choice instead and keep ONLY the apt + native-build layers below; the
# jax[tpu] wheel pin must match the host runtime. On CPU the image runs
# the full test suite on a virtual 8-device mesh out of the box.

FROM python:3.12.12-slim-bookworm

# --- native toolchain + pinned libav (Debian bookworm: FFmpeg 5.1 ABI) ---
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ \
        make \
        libavcodec-dev \
        libavformat-dev \
        libavutil-dev \
        libswscale-dev \
        libswresample-dev \
        libx264-dev \
        libx265-dev \
        libvpx-dev \
        libaom-dev \
    && rm -rf /var/lib/apt/lists/*

# --- python deps, pinned to the versions the suite is validated against ---
RUN pip install --no-cache-dir \
        "jax==0.9.0" \
        "flax==0.12.3" \
        "optax==0.2.6" \
        "chex==0.1.91" \
        "einops==0.8.2" \
        "numpy==2.0.2" \
        "scipy==1.17.0" \
        "pandas==3.0.3" \
        "matplotlib==3.10.8" \
        "pillow==12.1.0" \
        "pyyaml==6.0.3" \
        "pytest==8.4.2" \
        "hypothesis==6.142.1"

WORKDIR /chain
COPY . /chain

# --- build the native media boundary against the pinned libav ---
RUN make -C processing_chain_tpu/native \
    && python -c "from processing_chain_tpu.io import medialib; medialib.ensure_loaded(); print('libpcmedia OK')"

# tests run on a virtual 8-device CPU mesh (same partitioning/collective
# code paths XLA uses on a real v5e-8; tests/conftest.py sets this too)
ENV JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8

CMD ["python", "-m", "pytest", "tests/", "-q"]
