"""Benchmark: AVPVS hot path — 1080p→4K Lanczos upscale + SI/TI per frame.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = frames/sec/chip of the jitted device step (luma+chroma
               Lanczos resample to 4K + Sobel SI + frame-diff TI).
vs_baseline  = value / (8 × measured single-core CPU fps of the same
               work done the reference's way: libswscale Lanczos scale
               + numpy Sobel/TI). The reference publishes no numbers
               (BASELINE.md), so the 8-core baseline is measured here:
               its process pool runs single-threaded ffmpeg workers
               (reference lib/cmd_utils.py:60-129, -threads 1 at
               lib/ffmpeg.py:790), so 8 × one core is the faithful model.

Timing methodology: this environment reaches the TPU through a PJRT
tunnel whose `block_until_ready` returns before execution finishes
(measured 0.03 ms/step "latency" vs 82 ms with a forced host fetch), so
naive dispatch loops overcount by ~1000×. Instead the bench runs ITERS
steps inside ONE jitted `lax.scan` whose carry feeds back into the next
iteration's input (a data dependency, so XLA cannot hoist or CSE the
body), then fetches a scalar reduction to the host — the elapsed wall
time therefore covers ITERS full executions plus one tunnel round-trip,
which is amortized out by a measured-overhead correction.

The TPU backend is probed in a subprocess first so a wedged tunnel cannot
hang the bench; it falls back to CPU (and says so in the "platform" field).
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

H, W = 1080, 1920
DH, DW = 2160, 3840
T = int(os.environ.get("BENCH_FRAMES", "8"))
ITERS = int(os.environ.get("BENCH_ITERS", "20"))


def _tpu_usable(timeout_s: int = 60, attempts: int = 3, backoff_s: int = 30) -> bool:
    """Probe the TPU in a throwaway subprocess (a wedged tunnel blocks inside
    PJRT client creation — unkillable from within, so probe with a deadline).
    A transient tunnel outage shouldn't demote the bench to CPU: retry with
    backoff before giving up."""
    code = (
        "import jax; d=jax.devices(); import jax.numpy as jnp;"
        "x=jnp.ones((8,8)); (x@x).block_until_ready(); print(d[0].platform)"
    )
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
            if proc.returncode == 0:
                # a clean probe is definitive either way: retrying can't
                # turn a CPU-only machine into a TPU one
                return "cpu" not in proc.stdout
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < attempts:
            print(
                f"# tpu probe attempt {attempt + 1}/{attempts} failed; "
                f"retrying in {backoff_s}s",
                file=sys.stderr,
            )
            time.sleep(backoff_s)
    return False


def main() -> None:
    if not _tpu_usable():
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            from jax._src import xla_bridge as _xb

            getattr(_xb, "_backend_factories", {}).pop("axon", None)
        except Exception:
            pass

    import jax
    import jax.numpy as jnp

    try:
        jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "") or None)
    except Exception:
        pass
    platform = jax.devices()[0].platform

    from processing_chain_tpu.parallel import avpvs_siti_step

    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.integers(0, 255, size=(T, H, W), dtype=np.uint8))
    u = jnp.asarray(rng.integers(0, 255, size=(T, H // 2, W // 2), dtype=np.uint8))
    v = jnp.asarray(rng.integers(0, 255, size=(T, H // 2, W // 2), dtype=np.uint8))

    @functools.partial(jax.jit, static_argnames=("iters",))
    def bench(y, u, v, iters):
        def body(carry, _):
            # carry dependency on every input: no loop-invariant hoisting
            yy, uu, vv = y ^ carry, u ^ carry, v ^ carry
            up_y, up_u, up_v, si, ti = avpvs_siti_step(yy, uu, vv, DH, DW)
            # consume EVERY output over every frame so DCE cannot drop the
            # chroma resizes or narrow the luma resize to the frames SI/TI
            # happen to touch
            tot = (
                jnp.sum(up_y, dtype=jnp.int32)
                + jnp.sum(up_u, dtype=jnp.int32)
                + jnp.sum(up_v, dtype=jnp.int32)
            )
            nxt = (tot & 1).astype(jnp.uint8)
            return nxt, (jnp.sum(si) + jnp.sum(ti) + tot.astype(jnp.float32))
        carry, sums = jax.lax.scan(body, jnp.uint8(0), None, length=iters)
        return jnp.sum(sums) + carry.astype(jnp.float32)

    # warmup / compile both lengths; the scalar float() forces completion
    float(bench(y, u, v, 1))
    float(bench(y, u, v, ITERS))

    t0 = time.perf_counter()
    float(bench(y, u, v, 1))
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(bench(y, u, v, ITERS))
    t_many = time.perf_counter() - t0
    # subtract the fixed tunnel/dispatch overhead (one-iter run ≈ overhead +
    # one step): per-step time from the marginal cost of ITERS-1 extra steps
    per_step = max((t_many - t_one) / (ITERS - 1), 1e-9) if ITERS > 1 else t_many
    device_fps = T / per_step

    # CPU single-core baseline: swscale Lanczos + numpy Sobel SI / diff TI
    from processing_chain_tpu.io import medialib
    from scipy.ndimage import convolve

    ys = np.asarray(y[:2])
    kx = np.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], float)
    n_base = 2
    t0 = time.perf_counter()
    prev = None
    for i in range(n_base):
        up = medialib.sws_scale_plane(ys[i], DW, DH, medialib.SWS_LANCZOS)
        _ = medialib.sws_scale_plane(
            np.ascontiguousarray(ys[i][::2, ::2]), DW // 2, DH // 2,
            medialib.SWS_LANCZOS,
        )
        upf = up.astype(np.float64)
        gx = convolve(upf, kx)[1:-1, 1:-1]
        gy = convolve(upf, kx.T)[1:-1, 1:-1]
        _si = np.std(np.sqrt(gx * gx + gy * gy))
        if prev is not None:
            _ti = np.std(upf - prev)
        prev = upf
    cpu_core_fps = n_base / (time.perf_counter() - t0)
    baseline_8core = 8.0 * cpu_core_fps

    print(
        json.dumps(
            {
                "metric": "AVPVS frames/sec/chip (1080p->4K Lanczos + SI/TI)",
                "value": round(device_fps, 2),
                "unit": "frames/s/chip",
                "vs_baseline": round(device_fps / baseline_8core, 2),
                "platform": platform,
                "baseline_8core_fps": round(baseline_8core, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
