"""Benchmark: AVPVS hot path — 1080p→4K Lanczos upscale + SI/TI per frame.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

value        = frames/sec/chip of the jitted device step (luma+chroma
               Lanczos resample to 4K + Sobel SI + frame-diff TI).
vs_baseline  = value / (8 × measured single-core CPU fps of the same
               work done the reference's way: libswscale Lanczos scale
               + numpy Sobel/TI). The reference publishes no numbers
               (BASELINE.md), so the 8-core baseline is measured here:
               its process pool runs single-threaded ffmpeg workers
               (reference lib/cmd_utils.py:60-129, -threads 1 at
               lib/ffmpeg.py:790), so 8 × one core is the faithful model.

Timing methodology: this environment reaches the TPU through a PJRT
tunnel whose `block_until_ready` returns before execution finishes
(measured 0.03 ms/step "latency" vs 82 ms with a forced host fetch), so
naive dispatch loops overcount by ~1000×. Instead the bench runs ITERS
steps inside ONE jitted `lax.scan` whose carry feeds back into the next
iteration's input (a data dependency, so XLA cannot hoist or CSE the
body), then fetches a scalar reduction to the host — the elapsed wall
time therefore covers ITERS full executions plus one tunnel round-trip,
which is amortized out by a measured-overhead correction.

Robustness (round-3 rework): the process is budgeted against
BENCH_DEADLINE (default 240 s wall).  Round 2's single 30 s throwaway
probe timed out once and burned the round's TPU number while ~150 s of
budget went unused; now there is NO separate probe — the watchdogged
TPU child (`bench.py --child`) doubles as probe and measurement, so a
live tunnel is used the moment it answers.  The TPU attempt is
adaptive: a first generous attempt, then a retry while enough budget
remains for the CPU fallback (<60 s) and baseline.  Every failed
attempt's stderr tail is carried into the final JSON (`tpu_error`) so
an environment-down round is distinguishable from a code bug.  A
wedged tunnel blocks inside PJRT client creation (unkillable from
within), which is why all device work lives in killable subprocesses.
The CPU baseline uses ≥20 frames for a stable denominator,
deadline-guarded.  The banded-vs-fused method comparison runs only if
enough budget remains and lands in the same single JSON line.
"""

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

H, W = 1080, 1920
DH, DW = 2160, 3840
T = int(os.environ.get("BENCH_FRAMES", "8"))
ITERS = int(os.environ.get("BENCH_ITERS", "20"))
DEADLINE = float(os.environ.get("BENCH_DEADLINE", "240"))
_T0 = time.monotonic()

_HERE = os.path.dirname(os.path.abspath(__file__))
#: pinned single-core baseline (committed artifact; see --pin-baseline);
#: PC_BASELINE_FILE overrides for tests
BASELINE_FILE = os.environ.get(
    "PC_BASELINE_FILE", os.path.join(_HERE, "BASELINE_MEASURED.json")
)
#: latest live TPU measurement persisted across runs, so a harvest whose
#: TPU attempts hit a wedged tunnel can still report the round's real
#: number; PC_BENCH_LIVE_FILE overrides for tests
LIVE_FILE = os.environ.get(
    "PC_BENCH_LIVE_FILE", os.path.join(_HERE, "BENCH_LIVE.json")
)


def _remaining() -> float:
    return DEADLINE - (time.monotonic() - _T0)


def _host_fingerprint() -> dict:
    model = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    import platform as _plat

    return {"cpu_model": model, "cpu_count": os.cpu_count(),
            "machine": _plat.machine()}


def _measure_baseline(n_frames: int, deadline_at: float | None = None) -> tuple[float, int]:
    """One single-core baseline run: swscale Lanczos 1080p->4K (luma +
    2 chroma planes) + numpy Sobel SI / frame-diff TI per frame — the
    reference's workload done the reference's way (single-threaded ffmpeg
    workers: lib/cmd_utils.py:60-129, -threads 1 at lib/ffmpeg.py:790).
    Returns (fps, frames_done)."""
    from processing_chain_tpu.io import medialib
    from scipy.ndimage import convolve

    rng = np.random.default_rng(0)  # pinned content
    ys = rng.integers(0, 255, size=(H, W), dtype=np.uint8)
    kx = np.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], float)
    t0 = time.perf_counter()
    prev = None
    done = 0
    for _ in range(n_frames):
        up = medialib.sws_scale_plane(ys, DW, DH, medialib.SWS_LANCZOS)
        for _chroma in range(2):  # U and V, matching the device step
            _ = medialib.sws_scale_plane(
                np.ascontiguousarray(ys[::2, ::2]), DW // 2, DH // 2,
                medialib.SWS_LANCZOS,
            )
        upf = up.astype(np.float64)
        gx = convolve(upf, kx)[1:-1, 1:-1]
        gy = convolve(upf, kx.T)[1:-1, 1:-1]
        _si = np.std(np.sqrt(gx * gx + gy * gy))
        if prev is not None:
            _ti = np.std(upf - prev)
        prev = upf
        done += 1
        if done >= 2 and deadline_at and time.perf_counter() > deadline_at:
            break
    return done / (time.perf_counter() - t0), done


def _measure_metrics_baseline(n_frames: int) -> tuple[float, int]:
    """Single-core CPU PSNR+SSIM per 1080p frame pair — BASELINE config
    4's workload done host-side (vectorized numpy + scipy separable
    gaussian, the python analytics stack the reference uses for its own
    in-python features, util/complexity_classification.py; its ffmpeg
    C filters are the alternative but are not reachable as a library).
    Returns (fps, frames_done)."""
    from scipy.ndimage import convolve1d

    rng = np.random.default_rng(0)
    ref = rng.integers(0, 255, size=(H, W)).astype(np.float64)
    deg = ref[:, ::-1] * 0.97 + 3.0
    x = np.arange(11) - 5.0
    g = np.exp(-(x * x) / (2 * 1.5 * 1.5))
    g /= g.sum()
    c1, c2 = (0.01 * 255) ** 2, (0.03 * 255) ** 2
    t0 = time.perf_counter()
    done = 0
    for _ in range(n_frames):
        _psnr = 10 * np.log10(255.0 ** 2 / max(np.mean((ref - deg) ** 2), 1e-12))
        mu_r = convolve1d(convolve1d(ref, g, axis=0), g, axis=1)
        mu_d = convolve1d(convolve1d(deg, g, axis=0), g, axis=1)
        rr = convolve1d(convolve1d(ref * ref, g, axis=0), g, axis=1)
        dd = convolve1d(convolve1d(deg * deg, g, axis=0), g, axis=1)
        rd = convolve1d(convolve1d(ref * deg, g, axis=0), g, axis=1)
        s_r = rr - mu_r * mu_r
        s_d = dd - mu_d * mu_d
        s_rd = rd - mu_r * mu_d
        _ssim = np.mean(
            ((2 * mu_r * mu_d + c1) * (2 * s_rd + c2))
            / ((mu_r * mu_r + mu_d * mu_d + c1) * (s_r + s_d + c2))
        )
        done += 1
    return done / (time.perf_counter() - t0), done


def pin_baseline(runs: int = 5, frames: int = 8) -> dict:
    """Measure the pinned CPU baseline: median of `runs` independent
    single-core runs over `frames` pinned-content frames each, plus the
    host fingerprint. Writes BASELINE_MEASURED.json (VERDICT r3 #2)."""
    fps_runs = []
    for i in range(runs):
        fps, done = _measure_baseline(frames)
        fps_runs.append(round(fps, 4))
        print(f"run {i + 1}/{runs}: {fps:.3f} f/s/core ({done} frames)",
              file=sys.stderr, flush=True)
    med = sorted(fps_runs)[len(fps_runs) // 2]
    art = {
        "protocol": {
            "content": "rng PCG64 seed 0, 1080x1920 uint8 luma + 540x960 "
                       "chroma pair, identical every frame",
            "work": "swscale SWS_LANCZOS 1080p->4K (3 planes) + float64 "
                    "Sobel SI + frame-diff TI per frame",
            "frames_per_run": frames,
            "runs": runs,
            "stat": "median of per-run fps",
            "threads": 1,
        },
        "runs_fps": fps_runs,
        "cpu_core_fps": med,
        "baseline_8core_fps": round(8.0 * med, 4),
        "host": _host_fingerprint(),
        "measured_at": _utcnow(),
    }
    _dump_json_atomic(art, BASELINE_FILE)
    return art


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _load_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _dump_json_atomic(obj: dict, path: str) -> None:
    """Write via temp + os.replace so a concurrent reader (watcher vs
    harvest) never sees a truncated file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)


def _hash_files(paths) -> str:
    import hashlib

    h = hashlib.sha256()
    for path in sorted(paths):
        try:
            with open(path, "rb") as fh:
                h.update(fh.read())
        except OSError:
            pass
    return h.hexdigest()[:16]


def _compute_code_hash() -> str:
    """Hash of the device-path sources the measurement depends on; a live
    cache recorded under a different hash is rejected (it measured other
    code). Deliberately NOT the git rev (the driver's end-of-round
    snapshot commit must not invalidate a cache whose compute path is
    unchanged) and deliberately NOT bench.py itself (a comment or
    harness-plumbing edit here must not either; the measured math lives
    entirely in ops/ + parallel/)."""
    import glob

    return _hash_files(
        glob.glob(os.path.join(_HERE, "processing_chain_tpu", "ops", "*.py"))
        + glob.glob(os.path.join(_HERE, "processing_chain_tpu", "parallel", "*.py"))
    )


def _compute_e2e_code_hash() -> str:
    """The e2e number depends on the WHOLE product path (decode, device
    ops, prefetch engine, stage drivers, native boundary), so its cache
    guard hashes every package source + media.cpp."""
    import glob

    return _hash_files(
        glob.glob(
            os.path.join(_HERE, "processing_chain_tpu", "**", "*.py"),
            recursive=True,
        )
        + [os.path.join(_HERE, "processing_chain_tpu", "native", "media.cpp")]
    )


class _DeviceLock:
    """flock-based mutual exclusion for ALL axon-tunnel clients (bench
    harvest, tools/tpu_watch.sh) — concurrent clients are what wedge the
    tunnel (see memory/VERDICT r3). Lockfile lives under the 0700 cache
    dir, not /tmp."""

    def __init__(self) -> None:
        override = os.environ.get("PC_DEVICE_LOCK_FILE")
        if override:
            self.path = override  # tests: never contend with a live harvest
            self._fh = None
            return
        d = os.path.join(os.path.expanduser("~"), ".cache")
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
        except OSError:
            d = _HERE
        self.path = os.path.join(d, f"pc_tpu_device_{os.getuid()}.lock")
        self._fh = None

    def acquire(self, timeout_s: float) -> bool:
        import fcntl

        # chainlint: disable=atomic-write (flock target: the lock IS the inode, content unused — replacing it would split lockers across two inodes)
        self._fh = open(self.path, "w")
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fcntl.flock(self._fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return True
            except OSError:
                if time.monotonic() >= deadline:
                    self._fh.close()
                    self._fh = None
                    return False
                time.sleep(2.0)

    def release(self) -> None:
        if self._fh is not None:
            import fcntl

            try:
                fcntl.flock(self._fh, fcntl.LOCK_UN)
            finally:
                self._fh.close()
                self._fh = None


def force_cpu_backend_if_requested() -> bool:
    """Under JAX_PLATFORMS=cpu, deregister the axon plugin BEFORE jax is
    used (its get_backend monkeypatch initializes the tunnel even when the
    platform is pinned to cpu — same workaround as tests/conftest) and pin
    the platform. Returns True when the cpu pin is active. Shared by the
    bench child and the perf/profile tools."""
    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False
    try:
        from jax._src import xla_bridge as _xb

        getattr(_xb, "_backend_factories", {}).pop("axon", None)
    except Exception:
        pass
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True


def _min_marginal_per_step(run_fn, many: int, reps: int = 3) -> float:
    """Best-of-`reps` marginal per-step time of `run_fn(n_steps)`: warm
    both step counts (separate jit compiles), then minimize the 1-step
    and `many`-step wall times INDEPENDENTLY — a min over paired
    differences would cherry-pick a (fast many, slow one) pairing and
    overstate throughput; both minima estimate the interference-free
    mode of the same fixed-overhead + k-steps quantity, so their
    difference is the unbiased marginal cost of many-1 steps."""
    run_fn(many)
    if many > 1:
        run_fn(1)
    t_one = float("inf")
    t_many = float("inf")
    for _ in range(reps):
        if many > 1:
            t0 = time.perf_counter()
            run_fn(1)
            t_one = min(t_one, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_fn(many)
        t_many = min(t_many, time.perf_counter() - t0)
    if many <= 1:
        return max(t_many, 1e-9)
    return max((t_many - t_one) / (many - 1), 1e-9)


def _child() -> None:
    """Device measurement; prints one JSON dict {"per_step", "platform"}.

    Run as a subprocess so the parent survives a mid-run tunnel wedge."""
    force_cpu_backend_if_requested()
    import jax
    import jax.numpy as jnp
    platform = jax.devices()[0].platform
    # CPU fallback exists only so the bench always emits a line: shrink the
    # problem (per-frame fps is what's reported, so T doesn't bias it)
    t = T if platform != "cpu" else min(T, 2)
    iters = ITERS if platform != "cpu" else 2

    from processing_chain_tpu.parallel import avpvs_siti_step

    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.integers(0, 255, size=(t, H, W), dtype=np.uint8))
    u = jnp.asarray(rng.integers(0, 255, size=(t, H // 2, W // 2), dtype=np.uint8))
    v = jnp.asarray(rng.integers(0, 255, size=(t, H // 2, W // 2), dtype=np.uint8))

    @functools.partial(jax.jit, static_argnames=("iters",))
    def bench(y, u, v, iters):
        def body(carry, _):
            # carry dependency on every input: no loop-invariant hoisting
            yy, uu, vv = y ^ carry, u ^ carry, v ^ carry
            up_y, up_u, up_v, si, ti = avpvs_siti_step(yy, uu, vv, DH, DW)
            # consume EVERY output over every frame so DCE cannot drop the
            # chroma resizes or narrow the luma resize to the frames SI/TI
            # happen to touch
            tot = (
                jnp.sum(up_y, dtype=jnp.int32)
                + jnp.sum(up_u, dtype=jnp.int32)
                + jnp.sum(up_v, dtype=jnp.int32)
            )
            nxt = (tot & 1).astype(jnp.uint8)
            return nxt, (jnp.sum(si) + jnp.sum(ti) + tot.astype(jnp.float32))
        carry, sums = jax.lax.scan(body, jnp.uint8(0), None, length=iters)
        return jnp.sum(sums) + carry.astype(jnp.float32)

    # warmup / compile; the scalar float() forces completion
    float(bench(y, u, v, iters))
    if platform == "cpu":
        # no tunnel overhead to amortize on CPU: one timed run suffices
        t0 = time.perf_counter()
        float(bench(y, u, v, iters))
        per_step = (time.perf_counter() - t0) / iters
    else:
        # best-of-5: repeated measurements on this chip are bimodal
        # (~2x spread from tunnel/tenant interference and power-state
        # ramp); the minimum is the chip's actual steady-state throughput
        # (methodology in _min_marginal_per_step)
        per_step = _min_marginal_per_step(
            lambda k: float(bench(y, u, v, k)), iters, reps=5
        )

    result = {"per_step": per_step, "platform": platform, "iters": iters, "t": t}

    if platform != "cpu" and not os.environ.get("PC_BENCH_NO_EXTRAS"):
        # spinner-overlay composite at 4K (BASELINE config 3's workload:
        # stalling-event spinner compositing) — the bufferer-replacement
        # kernel, measured on the same frames-per-second basis. The
        # headline line is already assembled in `result`: print it FIRST
        # so a failure in this optional extra can never cost the round's
        # number (the parent parses the LAST JSON line).
        print(json.dumps(result), flush=True)
        from processing_chain_tpu.ops import overlay as ovl

        rng2 = np.random.default_rng(1)
        plan = ovl.plan_stalling(t, 60.0, [[0.0, t / 60.0]], skipping=False)
        bank = rng2.integers(0, 255, (128, 128, 4), dtype=np.uint8)
        sp_yuv, sp_a = ovl.prepare_spinner(bank, n_rotations=16)
        sp = jnp.asarray(sp_yuv[:, 0])
        sa = jnp.asarray(sp_a)
        # synthesize the 4K batch ON DEVICE: a 265 MB host->device f32
        # upload would take minutes through the tunnel (content is
        # irrelevant to composite timing)
        frames4k = (
            (
                jnp.arange(DH, dtype=jnp.float32)[None, :, None] * 7.0
                + jnp.arange(DW, dtype=jnp.float32)[None, None, :] * 3.0
                + jnp.arange(t, dtype=jnp.float32)[:, None, None] * 11.0
            )
            % 256.0
        )

        @functools.partial(jax.jit, static_argnames=("n",))
        def ov_bench(f, n):
            def body(c, _):
                out = ovl.render_stalled_plane(f + c, plan, sp, sa)
                tot = jnp.sum(out)
                return tot * 1e-20, tot
            c, s = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return jnp.sum(s) + c

        ov_iters = max(4, iters // 2)
        try:
            result["overlay_per_step"] = _min_marginal_per_step(
                lambda k: float(ov_bench(frames4k, k)), ov_iters
            )
            result["overlay_frames"] = plan.n_out  # played + inserted
        except Exception as exc:  # optional extra must never fail the child
            result["overlay_error"] = str(exc)[-200:]
        # each extra lands incrementally: the parent takes the LAST
        # complete line, so a window closing mid-extra keeps the rest
        print(json.dumps(result), flush=True)

        # per-frame PSNR+SSIM of 1080p pairs (BASELINE config 4's feature
        # extraction: long-test AVPVS vs SRC quality metrics — the work
        # the reference builds libvmaf for, done on the chip)
        try:
            from processing_chain_tpu.ops import metrics as mx

            ref2 = (
                jnp.arange(t * H * W, dtype=jnp.float32).reshape(t, H, W)
                % 251.0
            )
            deg2 = jnp.flip(ref2, axis=2) * 0.97 + 3.0

            @functools.partial(jax.jit, static_argnames=("n",))
            def mx_bench(a, b, n):
                def body(c, _):
                    p = mx.psnr_frames(a + c, b)
                    s = mx.ssim_frames(a + c, b)
                    tot = jnp.sum(p) + jnp.sum(s)
                    return tot * 1e-20, tot
                c, s = jax.lax.scan(body, jnp.float32(0), None, length=n)
                return jnp.sum(s) + c

            mx_iters = max(4, iters // 2)
            result["metrics_per_step"] = _min_marginal_per_step(
                lambda k: float(mx_bench(ref2, deg2, k)), mx_iters
            )
            result["metrics_frames"] = t
        except Exception as exc:
            result["metrics_error"] = str(exc)[-200:]
        print(json.dumps(result), flush=True)

        # PVS-batched step (BASELINE config 5's device shape): 4 lanes
        # stacked into one resize+SI/TI launch, as parallel/p03_batch
        # waves do — per-frame rate vs the t-frame headline shows the
        # on-chip batching win (fewer launches, fuller tiles)
        try:
            rep = (4, 1, 1)
            y4, u4, v4 = (jnp.tile(a, rep) for a in (y, u, v))
            b_iters = max(2, iters // 4)
            result["batch_per_step"] = _min_marginal_per_step(
                lambda k: float(bench(y4, u4, v4, k)), b_iters
            )
            result["batch_frames"] = 4 * t
        except Exception as exc:
            result["batch_error"] = str(exc)[-200:]

    print(json.dumps(result))


E2E_FRAMES = int(os.environ.get("BENCH_E2E_FRAMES", "96"))
#: e2e live-TPU cache (separate from the kernel cache: broader code hash)
E2E_LIVE_FILE = os.environ.get(
    "PC_BENCH_E2E_LIVE_FILE", os.path.join(_HERE, "BENCH_E2E_LIVE.json")
)


def _e2e_db_yaml(db_id: str, seconds: int) -> str:
    """BASELINE config 1's shape: one h264 960x540 PVS on a 1080p SRC,
    pc post-processing at 1080p — so p03 is decode 540p -> device upscale
    to the 1920x1080 canvas -> FFV1(+sidecar) writeback, the reference's
    create_avpvs_short product path (lib/ffmpeg.py:940-1000)."""
    return "\n".join([
        f"databaseId: {db_id}",
        "syntaxVersion: 6",
        "type: short",
        "qualityLevelList:",
        "  Q0: {index: 0, videoCodec: h264, videoBitrate: 2500, "
        "width: 960, height: 540, fps: 24}",
        "codingList:",
        "  VC01: {type: video, encoder: libx264, passes: 1, "
        "iFrameInterval: 2, preset: ultrafast}",
        "srcList:",
        "  SRC000: SRC000.avi",
        "hrcList:",
        f"  HRC000: {{videoCodingId: VC01, eventList: [[Q0, {seconds}]]}}",
        "pvsList:",
        f"  - {db_id}_SRC000_HRC000",
        "postProcessingList:",
        "  - {type: pc, displayWidth: 1920, displayHeight: 1080, "
        "codingWidth: 1920, codingHeight: 1080, displayFrameRate: 24}",
    ]) + "\n"


def _e2e_long_db_yaml(db_id: str, seconds: int) -> str:
    """BASELINE config 4's shape: a LONG test (segmented SRC, audio
    codings, concat + SRC-audio remux — reference lib/ffmpeg.py:1058-1105)
    whose AVPVS then feeds the quality-metrics tool (PSNR/SSIM vs SRC)."""
    return "\n".join([
        f"databaseId: {db_id}",
        "syntaxVersion: 6",
        "type: long",
        "segmentDuration: 2",
        "qualityLevelList:",
        "  Q0: {index: 0, videoCodec: h264, videoBitrate: 2500, "
        "width: 960, height: 540, fps: 24, audioCodec: aac, "
        "audioBitrate: 96}",
        "codingList:",
        "  VC01: {type: video, encoder: libx264, passes: 1, "
        "iFrameInterval: 2, preset: ultrafast}",
        "  AC01: {type: audio, encoder: aac}",
        "srcList:",
        "  SRC000: SRC000.avi",
        "hrcList:",
        "  HRC000: {videoCodingId: VC01, audioCodingId: AC01, "
        f"eventList: [{', '.join(['[Q0, 2]'] * (seconds // 2))}]}}",
        "pvsList:",
        f"  - {db_id}_SRC000_HRC000",
        "postProcessingList:",
        "  - {type: pc, displayWidth: 1920, displayHeight: 1080, "
        "codingWidth: 1920, codingHeight: 1080, displayFrameRate: 24}",
    ]) + "\n"


def _e2e_build_db(root: str, n_frames: int) -> str:
    """Synthesize the SRC and run p01 once (untimed setup); returns the
    database YAML path. Runs inside the measurement child."""
    import numpy as np

    from processing_chain_tpu.cli import main as cli_main
    from processing_chain_tpu.io.video import VideoWriter

    db_id = "P2SXM98"
    seconds = max(1, n_frames // 24)
    db = os.path.join(root, db_id)
    os.makedirs(os.path.join(db, "srcVid"), exist_ok=True)
    yaml_path = os.path.join(db, f"{db_id}.yaml")
    with open(yaml_path, "w") as fh:
        fh.write(_e2e_db_yaml(db_id, seconds))
    _e2e_write_src(os.path.join(db, "srcVid", "SRC000.avi"), seconds)
    rc = cli_main(["p01", "-c", yaml_path, "--skip-requirements"])
    if rc != 0:
        raise RuntimeError(f"e2e setup: p01 exited {rc}")
    return yaml_path


def _e2e_write_src(path: str, seconds: int, audio: bool = False) -> None:
    import numpy as np

    from processing_chain_tpu.io.video import VideoWriter

    rng = np.random.default_rng(0)
    w, h = 1920, 1080
    # moving gradient + noise: representative spatial/temporal complexity
    # (pure noise over-costs x264; flat frames under-cost FFV1)
    xx = np.arange(w, dtype=np.float32)[None, :]
    yy = np.arange(h, dtype=np.float32)[:, None]
    aud = (
        dict(audio_codec="flac", sample_rate=48000, channels=2)
        if audio else {}
    )
    with VideoWriter(
        path, "ffv1", w, h, "yuv420p", (24, 1), threads=1, **aud,
    ) as wr:
        if audio:
            t = np.arange(48000 * seconds)
            tone = (np.sin(2 * np.pi * 330 * t / 48000) * 7000).astype(np.int16)
            wr.write_audio(np.stack([tone, tone], axis=1))
        for i in range(seconds * 24):
            y = ((np.sin((xx + 6 * i) / 37.0) + np.cos((yy - 3 * i) / 29.0))
                 * 52 + 120).astype(np.uint8)
            y[::7] += rng.integers(0, 13, (1, w), np.uint8)  # film grain row
            u = np.full((h // 2, w // 2), 120, np.uint8)
            v = ((y[::2, ::2] >> 2) + 90).astype(np.uint8)
            wr.write(y, u, v)


def _e2e_build_long_db(root: str, n_frames: int) -> tuple[str, int]:
    """Returns (yaml path, canvas frame count) — the count is derived
    here, once, from the whole-2s-segment rounding."""
    from processing_chain_tpu.cli import main as cli_main

    db_id = "P2LXM98"
    seconds = max(2, (n_frames // 48) * 2)  # whole 2 s segments
    db = os.path.join(root, db_id)
    os.makedirs(os.path.join(db, "srcVid"), exist_ok=True)
    yaml_path = os.path.join(db, f"{db_id}.yaml")
    with open(yaml_path, "w") as fh:
        fh.write(_e2e_long_db_yaml(db_id, seconds))
    _e2e_write_src(os.path.join(db, "srcVid", "SRC000.avi"), seconds,
                   audio=True)
    rc = cli_main(["p01", "-c", yaml_path, "--skip-requirements"])
    if rc != 0:
        raise RuntimeError(f"e2e long setup: p01 exited {rc}")
    return yaml_path, seconds * 24


def _e2e_child() -> None:
    """End-to-end p03 measurement: build the config-1 DB (untimed), run
    the REAL p03 stage once for compile warmup, then time it. Prints one
    JSON dict. Separate process for the same reason as _child: a wedged
    tunnel blocks inside PJRT and only a kill recovers."""
    import tempfile

    force_cpu_backend_if_requested()
    import jax

    platform = jax.devices()[0].platform
    from processing_chain_tpu.cli import main as cli_main

    # CPU fallback exists only so a line is always emitted: shrink hard
    # (per-frame fps is what's reported). The TPU run is also capped by
    # default: the axon tunnel carries every decoded chunk up and every
    # canvas chunk down, so a large n mostly measures tunnel bandwidth —
    # raise BENCH_E2E_FRAMES on a host-attached deployment.
    n = min(E2E_FRAMES, 48) if platform != "cpu" else min(E2E_FRAMES, 24)
    out: dict = {"platform": platform}
    with tempfile.TemporaryDirectory(prefix="pc_e2e_bench_") as root:
        t0 = time.perf_counter()
        yaml_path = _e2e_build_db(root, n)
        n = max(1, n // 24) * 24  # what the DB actually holds
        out["setup_s"] = round(time.perf_counter() - t0, 2)
        out["n"] = n

        def run_p03() -> float:
            t0 = time.perf_counter()
            rc = cli_main(["p03", "-c", yaml_path, "--skip-requirements",
                           "--force"])
            if rc != 0:
                raise RuntimeError(f"p03 exited {rc}")
            return time.perf_counter() - t0

        run_p03()  # warmup: jit compile + file caches
        # one timed run (not best-of-N): the p03 product path is minutes
        # of wall through the tunnel and the window is precious; the
        # cache refreshes on every live window, so noise averages out
        # across rounds
        out["t_p03"] = run_p03()
        # headline printed BEFORE optional extras (parent parses last
        # full JSON line; a timeout mid-extra must not cost the number)
        print(json.dumps(out), flush=True)

        # the cheap-intermediate flag's measured value on this host
        try:
            # single run: only the writer changes, the jit cache is warm
            os.environ["PC_AVPVS_CODEC"] = "rawvideo"
            out["t_p03_raw"] = run_p03()
        except Exception as exc:
            out["raw_error"] = str(exc)[-200:]
        finally:
            os.environ.pop("PC_AVPVS_CODEC", None)

        # reference-way single-core baseline on the SAME segment, when the
        # parent asked for it (not yet pinned): decode h264 540p + swscale
        # bicubic to the 1080p canvas + serial FFV1 writeback — exactly
        # create_avpvs_short done the reference's way, minus our extra
        # SI/TI sidecar (a handicap WE carry, not the baseline)
        if os.environ.get("PC_E2E_NEED_BASELINE"):
            try:
                out.update(_e2e_measure_baseline(yaml_path))
            except Exception as exc:
                out["base_error"] = str(exc)[-200:]
        print(json.dumps(out), flush=True)

        # BASELINE config 4's wall-clock: the LONG product path (segment
        # renders + concat + SRC-audio remux; `-z` keeps the canvas at
        # the SRC rate so frame counts match the short phase) followed by
        # the quality-metrics tool (PSNR/SSIM/SI/TI vs SRC) over the
        # rendered AVPVS. Skipped on the CPU fallback unless forced: the
        # harvest budget is tight there and the phase is device-weighted.
        if platform != "cpu" or os.environ.get("PC_BENCH_E2E_LONG"):
            try:
                long_yaml, out["long_n"] = _e2e_build_long_db(root, n)
                t0 = time.perf_counter()
                rc = cli_main(["p03", "-c", long_yaml,
                               "--skip-requirements", "--force", "-z"])
                if rc != 0:
                    raise RuntimeError(f"long p03 exited {rc}")
                out["t_p03_long"] = time.perf_counter() - t0
                t0 = time.perf_counter()
                rc = cli_main(["tools", "metrics", "-c", long_yaml])
                if rc != 0:
                    raise RuntimeError(f"metrics tool exited {rc}")
                out["t_qm"] = time.perf_counter() - t0
            except Exception as exc:
                out["long_error"] = str(exc)[-200:]
    print(json.dumps(out))


def _e2e_measure_baseline(yaml_path: str) -> dict:
    """Single-core reference-way p03 on the generated segment. Returns
    {"base_core_fps", "base_n"}."""
    import glob

    import numpy as np

    from processing_chain_tpu.io import medialib
    from processing_chain_tpu.io.video import VideoReader, VideoWriter

    segs = glob.glob(
        os.path.join(os.path.dirname(yaml_path), "videoSegments", "*.mp4")
    )
    if not segs:
        raise RuntimeError("no segment for e2e baseline")
    out_path = segs[0] + ".base.avi"
    done = 0
    t0 = time.perf_counter()
    try:
        with VideoReader(segs[0]) as r, VideoWriter(
            out_path, "ffv1", 1920, 1080, "yuv420p", (24, 1), threads=1,
            opts="level=3:coder=1:context=1:slicecrc=1",
        ) as wr:
            for f in r:
                y = medialib.sws_scale_plane(f.planes[0], 1920, 1080,
                                             medialib.SWS_BICUBIC)
                u = medialib.sws_scale_plane(f.planes[1], 960, 540,
                                             medialib.SWS_BICUBIC)
                v = medialib.sws_scale_plane(f.planes[2], 960, 540,
                                             medialib.SWS_BICUBIC)
                wr.write(y, u, v)
                done += 1
        dt = time.perf_counter() - t0
    finally:
        if os.path.isfile(out_path):
            os.unlink(out_path)
    return {"base_core_fps": done / dt, "base_n": done}


def _e2e_flow(errors: list, try_tpu: bool) -> dict:
    """The e2e measurement orchestration: TPU child -> cached live ->
    CPU child, mirroring the kernel flow. try_tpu: this run already saw
    the tunnel answer (a live kernel measurement), so an e2e TPU attempt
    is worth the budget. Returns the e2e_* fields for the output line."""
    pinned = _load_json(BASELINE_FILE) or {}
    need_base = "e2e_baseline_8core_fps" not in pinned
    env = {"PC_E2E_NEED_BASELINE": "1"} if need_base else {}

    res = None
    if try_tpu:
        budget = _remaining() - 60
        lock = _DeviceLock()
        if budget >= 45 and lock.acquire(timeout_s=15):
            try:
                res, err = _run_child(
                    dict(env, PC_BENCH_E2E_CHILD="1"), min(budget, 200)
                )
                if res is None:
                    errors.append(f"e2e tpu: {err}")
            finally:
                lock.release()
    code_hash = _compute_e2e_code_hash()
    host_model = _host_fingerprint()["cpu_model"]
    e2e_src = None
    if res is not None and res.get("platform") == "tpu":
        rec = dict(res, measured_at=_utcnow(), code_hash=code_hash,
                   host_cpu_model=host_model)
        try:
            _dump_json_atomic(rec, E2E_LIVE_FILE)
        except OSError:
            pass
    if res is None or res.get("platform") != "tpu":
        cached = _load_json(E2E_LIVE_FILE)
        if (cached is not None and cached.get("platform") == "tpu"
                and cached.get("code_hash") == code_hash
                and cached.get("host_cpu_model") == host_model):
            res = cached
            e2e_src = cached.get("measured_at", "unknown")
        elif cached is not None:
            errors.append("e2e live cache rejected: code_hash/host mismatch")
    if res is None and _remaining() > 75:
        res, err = _run_child(
            dict(env, PC_BENCH_E2E_CHILD="1", JAX_PLATFORMS="cpu"),
            min(_remaining() - 15, 240),
        )
        if res is None:
            errors.append(f"e2e cpu: {err}")
    if res is None:
        return {"e2e_error": "no e2e measurement (see tpu_error)"}

    out: dict = {
        "e2e_platform": res["platform"],
        "e2e_frames": res.get("n", 0),
        "e2e_fps": round(res["n"] / res["t_p03"], 2),
    }
    if "t_p03_raw" in res:
        out["e2e_rawvideo_fps"] = round(res["n"] / res["t_p03_raw"], 2)
    if e2e_src:
        out["e2e_source"] = "cached_live_run"
        out["e2e_measured_at"] = e2e_src

    # pin the reference-way baseline the first time it is measured
    if "base_core_fps" in res and need_base:
        pinned.setdefault("e2e_protocol", {
            "content": "1080p FFV1 SRC -> x264 960x540 segment "
                       "(ultrafast, 2.5 Mbps), then p03: decode + bicubic "
                       "upscale to 1920x1080 + FFV1 level3 writeback",
            "work_baseline": "single-thread decode + swscale bicubic x3 "
                             "planes + serial FFV1 encode (no SI/TI - a "
                             "handicap ours carries, the baseline doesn't)",
            "model": "8 x single-core fps (reference parallelism model, "
                     "as the kernel baseline)",
            "frames": res.get("base_n", 0),
        })
        pinned["e2e_cpu_core_fps"] = round(res["base_core_fps"], 4)
        pinned["e2e_baseline_8core_fps"] = round(8 * res["base_core_fps"], 4)
        try:
            _dump_json_atomic(pinned, BASELINE_FILE)
        except OSError:
            pass
    base8 = pinned.get("e2e_baseline_8core_fps")
    if base8:
        out["e2e_baseline_8core_fps"] = round(float(base8), 2)
        out["e2e_vs_baseline"] = round(out["e2e_fps"] / float(base8), 2)
    base1 = pinned.get("e2e_cpu_core_fps")
    if base1:
        # equal-resource comparison: this run used ONE host core (+chip);
        # the 8x model credits the reference with 8 (docs/PERF.md)
        out["e2e_vs_baseline_1core"] = round(out["e2e_fps"] / float(base1), 2)
    # config 4 companions: the long product path + the quality-metrics
    # tool over its AVPVS (vs the pinned numpy single-core model x 8)
    if "t_p03_long" in res and res.get("long_n"):
        out["e2e_long_fps"] = round(res["long_n"] / res["t_p03_long"], 2)
        if base8:
            out["e2e_long_vs_baseline"] = round(
                out["e2e_long_fps"] / float(base8), 2
            )
    if "t_qm" in res and res.get("long_n"):
        out["e2e_qm_fps"] = round(res["long_n"] / res["t_qm"], 2)
        mb8 = pinned.get("metrics_baseline_8core_fps")
        if mb8:
            out["e2e_qm_vs_baseline"] = round(
                out["e2e_qm_fps"] / float(mb8), 2
            )
    return out


def _run_child(env_extra: dict, timeout_s: float) -> tuple[dict | None, str]:
    """Run the measurement child; (parsed JSON, "") on success, else
    (None, diagnostic tail) so the caller can surface WHY it failed."""
    if timeout_s < 20:
        return None, f"skipped: {timeout_s:.0f}s left is under the 20s floor"
    env = dict(os.environ, **env_extra)
    # children share a persistent XLA compilation cache dir: where the
    # backend supports local caching this lets a retried TPU attempt (same
    # traced program) — or a whole later bench run — skip its 20-40 s
    # compile. The banded child traces a DIFFERENT program, so it gains
    # nothing within a single run. Best-effort: measured no-op on this
    # image's CPU backend, and the axon tunnel may compile remotely —
    # harmless in both cases. Per-user + 0700 so another tenant can
    # neither pre-create nor tamper with deserialized executables.
    cache_dir = os.path.join(
        os.path.expanduser("~"), ".cache", f"pc_bench_jax_cache_{os.getuid()}"
    )
    try:
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    except OSError:
        pass  # unwritable home: run without a persistent cache
    try:
        # chainlint: disable=subprocess-hygiene (bench harness: salvages partial stdout from TimeoutExpired — runner.shell by design converts expiry into ChainError and discards it)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired as exc:
        # the child prints+flushes its headline BEFORE the optional extras
        # (overlay comparison): a later hang must not cost the round's
        # number, so salvage any JSON already on stdout
        partial = exc.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode("utf-8", "replace")
        salvaged = _last_json(partial)
        if salvaged is not None:
            return salvaged, ""
        tail = (exc.stderr or b"")
        if isinstance(tail, bytes):
            tail = tail.decode("utf-8", "replace")
        return None, f"timeout after {timeout_s:.0f}s; stderr: {tail[-300:]}"
    if proc.returncode != 0:
        # same salvage on a crashed child
        salvaged = _last_json(proc.stdout or "")
        if salvaged is not None:
            return salvaged, ""
        return None, f"exit {proc.returncode}; stderr: {proc.stderr[-300:]}"
    salvaged = _last_json(proc.stdout)
    if salvaged is not None:
        return salvaged, ""
    return None, f"no JSON line in child stdout: {proc.stdout[-200:]!r}"


def _last_json(text: str) -> dict | None:
    from processing_chain_tpu.utils.fsio import last_json_line

    return last_json_line(text)


def host_bench() -> dict:
    """Host frame-path microbench (`bench.py --host-bench`): batched
    chunk-granular native I/O vs the per-frame fallback on the SAME
    synthetic FFV1 clip — decode fps, encode fps, byte parity, and the
    buffer-pool hit rate. This is the tracked metric for the e2e gap
    (BENCH_r05: kernel 107x baseline, e2e 0.08x — the difference lives
    entirely in this path). CI runs it as a correctness gate (parity +
    nonzero pool recycling), not a timing gate."""
    import tempfile

    from processing_chain_tpu.io import bufpool
    from processing_chain_tpu.io.video import VideoReader, VideoWriter

    # the microbench's job is to COMPARE the two paths: an inherited
    # PC_HOST_BATCH=0 would silently turn the "batched" legs into
    # re-measurements of the per-frame path (and zero the pool hit rate)
    os.environ["PC_HOST_BATCH"] = "1"
    n = int(os.environ.get("PC_HOST_BENCH_FRAMES", "96"))
    w, h = 640, 360
    chunk = 32
    rng = np.random.default_rng(0)
    # moving gradient + grain rows (same rationale as the e2e SRC: pure
    # noise over-costs FFV1, flat frames under-cost it)
    xx = np.arange(w, dtype=np.float32)[None, :]
    yy = np.arange(h, dtype=np.float32)[:, None]
    frames = []
    for i in range(n):
        y = ((np.sin((xx + 5 * i) / 23.0) + np.cos((yy - 2 * i) / 17.0))
             * 52 + 120).astype(np.uint8)
        y[::5] += rng.integers(0, 11, (1, w), np.uint8)
        u = np.full((h // 2, w // 2), 120, np.uint8)
        v = ((y[::2, ::2] >> 2) + 90).astype(np.uint8)
        frames.append((y, u, v))
    stacked = [np.stack([f[p] for f in frames]) for p in range(3)]
    out: dict = {"metric": "host frame path (batched vs per-frame I/O)",
                 "frames": n, "chunk": chunk}

    with tempfile.TemporaryDirectory(prefix="pc_host_bench_") as root:
        def writer(path):
            return VideoWriter(path, "ffv1", w, h, "yuv420p", (24, 1),
                               threads=1,
                               opts="level=3:coder=1:context=1:slicecrc=1")

        # encode: per-frame vs one batched crossing per chunk
        p_ser = os.path.join(root, "ser.avi")
        t0 = time.perf_counter()
        with writer(p_ser) as wr:
            for y, u, v in frames:
                wr.write(y, u, v)
        out["encode_fps"] = round(n / (time.perf_counter() - t0), 2)
        p_bat = os.path.join(root, "bat.avi")
        t0 = time.perf_counter()
        with writer(p_bat) as wr:
            for k in range(0, n, chunk):
                wr.write_batch(*(s[k: k + chunk] for s in stacked))
        out["encode_batch_fps"] = round(n / (time.perf_counter() - t0), 2)
        with open(p_ser, "rb") as f1, open(p_bat, "rb") as f2:
            out["encode_parity"] = f1.read() == f2.read()

        # decode: per-frame fallback vs pooled batch chunks
        t0 = time.perf_counter()
        with VideoReader(p_ser) as r:
            ref = [
                [pl.copy() for pl in ch]
                for ch in r._iter_chunks_per_frame(chunk)
            ]
        out["decode_fps"] = round(n / (time.perf_counter() - t0), 2)
        pool = bufpool.BufferPool()
        t0 = time.perf_counter()
        with VideoReader(p_ser) as r:
            got = []
            for ch in r.iter_chunks(chunk, pool=pool):
                got.append([pl.copy() for pl in ch])
                pool.release(*ch)
        out["decode_batch_fps"] = round(n / (time.perf_counter() - t0), 2)
        out["decode_parity"] = len(got) == len(ref) and all(
            np.array_equal(a, b)
            for ca, cb in zip(got, ref) for a, b in zip(ca, cb)
        )
        stats = pool.stats()
        out["pool_hits"] = stats["hits"]
        out["pool_misses"] = stats["misses"]
        out["pool_hit_rate"] = round(stats["hit_rate"], 3)
    out["host"] = _host_fingerprint()
    return out


def complexity_bench() -> dict:
    """Complexity classification: CRF-23 proxy re-encode vs codec priors
    (`bench.py --complexity-bench`, docs/PRIORS.md). One synthetic x264
    SRC goes through both paths; the tracked number is the wall-time
    ratio `priors_vs_proxy` (how much faster proxy-free classification
    answers), gated by `tools bench-compare` as the
    `complexity.priors_vs_proxy` band. Also asserts both paths yield a
    finite complexity value so the gate can't pass on a silent no-op."""
    import tempfile

    from processing_chain_tpu.io.video import VideoWriter
    from processing_chain_tpu.tools import complexity as cx

    n, w, h = 96, 640, 360
    rng = np.random.default_rng(7)
    base = rng.integers(0, 255, (h, w * 3), np.uint8)
    base = ((base.astype(np.float32) + np.roll(base, 1, 0)
             + np.roll(base, 1, 1)) / 3.0 + 40).astype(np.uint8)
    out: dict = {"metric": "complexity: priors vs CRF-23 proxy",
                 "frames": n, "geometry": f"{w}x{h}"}
    with tempfile.TemporaryDirectory(prefix="pc_cx_bench_") as root:
        src = os.path.join(root, "src.mp4")
        with VideoWriter(src, "libx264", w, h, "yuv420p", (24, 1),
                         gop=96, bframes=0, opts="crf=23:preset=fast") as wr:
            u = np.full((h // 2, w // 2), 128, np.uint8)
            for i in range(n):
                y = np.ascontiguousarray(base[:, 3 * i:3 * i + w])
                wr.write(y, u, u)

        # min of two runs per path: the first priors pass pays the jax
        # trace/compile of the MV feature kernels (a once-per-process
        # cost a corpus amortizes away); the proxy path gets the same
        # steady-state treatment
        proxy_s, priors_s = [], []
        for k in (0, 1):
            t0 = time.perf_counter()
            proxy = os.path.join(root, f"src_crf23_{k}.avi")
            cx.proxy_encode(src, proxy)
            rec_proxy = cx.get_difficulty(proxy, src)
            proxy_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            rec_priors = cx.get_priors_difficulty(src, force=True)
            priors_s.append(time.perf_counter() - t0)
        out["proxy_s"] = round(min(proxy_s), 4)
        out["priors_s"] = round(min(priors_s), 4)

    out["proxy_complexity"] = round(float(rec_proxy["complexity"]), 4)
    out["priors_complexity"] = round(float(rec_priors["complexity"]), 4)
    out["both_finite"] = bool(
        np.isfinite(rec_proxy["complexity"])
        and np.isfinite(rec_priors["complexity"])
    )
    out["priors_vs_proxy"] = round(out["proxy_s"] / max(out["priors_s"], 1e-9), 2)
    out["host"] = _host_fingerprint()
    return out


def fused_bench() -> dict:
    """Fused vs staged p03+p04 wall time (`bench.py --fused-bench`,
    docs/PERF.md "single-decode chain"). One synthetic short database
    (one PVS, pc + mobile contexts) runs p03+p04 twice — staged
    (PC_FUSE_P04 off: stalling + every CPVS re-decode the AVPVS) and
    fused (on: everything renders from the in-memory stream) — with
    cold outputs each time. The tracked number is the wall-time ratio
    `fused_vs_unfused` (>1 = fused is faster), gated by
    `tools bench-compare` as the `e2e.fused_vs_unfused` band with a
    floor ≈ 1: the fused path must never regress below the staged one."""
    import shutil
    import tempfile
    import textwrap

    from processing_chain_tpu.cli import main as cli_main
    from processing_chain_tpu.io.video import VideoWriter

    n, w, h, fps = 96, 320, 180, 24
    out: dict = {"metric": "e2e: fused vs staged p03+p04",
                 "frames": n, "geometry": f"{w}x{h}"}
    with tempfile.TemporaryDirectory(prefix="pc_fused_bench_") as root:
        db = os.path.join(root, "P2SXM91")
        os.makedirs(os.path.join(db, "srcVid"))
        from processing_chain_tpu.utils.fsio import atomic_write_text

        yaml_path = os.path.join(db, "P2SXM91.yaml")
        atomic_write_text(yaml_path, textwrap.dedent(f"""\
                databaseId: P2SXM91
                syntaxVersion: 6
                type: short
                qualityLevelList:
                  Q0: {{index: 0, videoCodec: h264, videoBitrate: 400, width: {w}, height: {h}, fps: {fps}}}
                codingList:
                  VC01: {{type: video, encoder: libx264, passes: 1, iFrameInterval: 1, preset: ultrafast}}
                srcList:
                  SRC000: SRC000.avi
                hrcList:
                  HRC000: {{videoCodingId: VC01, eventList: [[Q0, {n // fps}]]}}
                pvsList:
                  - P2SXM91_SRC000_HRC000
                postProcessingList:
                  - {{type: pc, displayWidth: {w * 2}, displayHeight: {h * 2}, codingWidth: {w * 2}, codingHeight: {h * 2}, displayFrameRate: {fps}}}
                  - {{type: mobile, displayWidth: {w * 2}, displayHeight: {h * 2}, codingWidth: {w * 2}, codingHeight: {h * 2}, displayFrameRate: {fps}}}
            """))
        rng = np.random.default_rng(11)
        base = rng.integers(0, 255, (h, w * 3), np.uint8)
        base = ((base.astype(np.float32) + np.roll(base, 1, 0)
                 + np.roll(base, 1, 1)) / 3.0 + 40).astype(np.uint8)
        with VideoWriter(os.path.join(db, "srcVid", "SRC000.avi"),
                         "ffv1", w, h, "yuv420p", (fps, 1)) as wr:
            u = np.full((h // 2, w // 2), 128, np.uint8)
            for i in range(n):
                y = np.ascontiguousarray(base[:, 2 * i:2 * i + w])
                wr.write(y, u, u)
        rc = cli_main(["p01", "-c", yaml_path, "--skip-requirements"])
        if rc != 0:
            out["error"] = "p01 failed"
            return out

        def one(mode: str) -> float:
            for d in ("avpvs", "cpvs"):
                shutil.rmtree(os.path.join(db, d), ignore_errors=True)
            env_before = os.environ.get("PC_FUSE_P04")
            os.environ["PC_FUSE_P04"] = "1" if mode == "fused" else "0"
            try:
                t0 = time.perf_counter()
                rc3 = cli_main(
                    ["p03", "-c", yaml_path, "--skip-requirements"])
                rc4 = cli_main(
                    ["p04", "-c", yaml_path, "--skip-requirements"])
                if rc3 != 0 or rc4 != 0:
                    raise RuntimeError(f"{mode} p03/p04 failed")
                return time.perf_counter() - t0
            finally:
                if env_before is None:
                    os.environ.pop("PC_FUSE_P04", None)
                else:
                    os.environ["PC_FUSE_P04"] = env_before

        # min of two runs per mode: the first pays jax trace/compile of
        # whichever transform kernels the session has not seen yet
        staged_s, fused_s = [], []
        for _ in (0, 1):
            staged_s.append(one("staged"))
            fused_s.append(one("fused"))
    out["staged_s"] = round(min(staged_s), 4)
    out["fused_s"] = round(min(fused_s), 4)
    out["fused_vs_unfused"] = round(
        out["staged_s"] / max(out["fused_s"], 1e-9), 3
    )
    out["host"] = _host_fingerprint()
    return out


def sharedscan_bench() -> dict:
    """The shared post-encode packet scan (io/sharedscan) vs the separate
    per-consumer demux passes it replaced, on one toy written file.

    The p02/priors consumer set used to pay FOUR scan_packets walks per
    segment (src_info video+audio, then the vfi and afi tables); the
    shared path pays ONE scan_packets_all and serves the rest from the
    stat-keyed cache. `sharedscan_vs_separate` (>1 = shared is faster)
    is gated by `tools bench-compare` as the `e2e.sharedscan_vs_separate`
    band with a floor ≈ 1: sharing must at least match the separate
    passes it replaced."""
    import tempfile

    from processing_chain_tpu.io import medialib, sharedscan
    from processing_chain_tpu.io.video import VideoWriter

    n, w, h, fps, iters = 240, 320, 180, 24, 40
    out: dict = {"metric": "e2e: shared packet scan vs separate passes",
                 "frames": n, "iters": iters}
    with tempfile.TemporaryDirectory(prefix="pc_scan_bench_") as root:
        path = os.path.join(root, "seg.avi")
        rng = np.random.default_rng(7)
        with VideoWriter(path, "ffv1", w, h, "yuv420p", (fps, 1),
                         audio_codec="flac", sample_rate=48000,
                         channels=2) as wr:
            tone = (np.sin(np.arange(48000 * n // fps) / 30.0)
                    * 6000).astype(np.int16)
            wr.write_audio(np.stack([tone, tone], axis=1))
            u = np.full((h // 2, w // 2), 128, np.uint8)
            for _ in range(n):
                wr.write(rng.integers(0, 255, (h, w), np.uint8), u, u)

        def consumers_separate() -> None:
            # the historical p02 walk set: src_info (both streams) +
            # the vfi/afi table scans
            medialib.scan_packets(path, "video")
            medialib.scan_packets(path, "audio")
            medialib.scan_packets(path, "video")
            medialib.scan_packets(path, "audio")

        def consumers_shared() -> None:
            sharedscan.clear()  # cold file: ONE scan_all + three hits
            sharedscan.get_scan(path)
            sharedscan.video(path)
            sharedscan.audio(path)
            sharedscan.video(path)

        for fn in (consumers_separate, consumers_shared):
            fn()  # touch the page cache once before timing either
        t0 = time.perf_counter()
        for _ in range(iters):
            consumers_separate()
        separate_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            consumers_shared()
        shared_s = time.perf_counter() - t0
    out["separate_s"] = round(separate_s, 4)
    out["shared_s"] = round(shared_s, 4)
    out["sharedscan_vs_separate"] = round(
        separate_s / max(shared_s, 1e-9), 3
    )
    out["host"] = _host_fingerprint()
    return out


def _error_summary(errors: list) -> tuple[str, dict]:
    """Bound failed-attempt stderr for the artifact: each attempt's
    FIRST line (the exception headline), never the raw multi-line blob —
    a wedged tunnel's stack soup used to swallow the whole 600-byte
    budget and hide every earlier attempt. The structured form keeps the
    attempt count machine-readable."""
    firsts = [
        (e.strip().splitlines() or [""])[0][:160] for e in errors
    ]
    summary = f"{len(errors)} failed attempt(s): " + " | ".join(firsts)
    return summary[:600], {"count": len(errors), "errors": firsts}


def main() -> None:
    cpu_env = {"JAX_PLATFORMS": "cpu"}

    # The TPU child doubles as probe and measurement: success = the round's
    # number; failure = retry while the CPU fallback (<60 s: ~25 s child +
    # ~20 s baseline) still fits in the budget. Attempt timeouts are sized
    # so at least two tries fit: cold PJRT client creation through the
    # tunnel takes 20-40 s and a warm full child run ~15 s.
    errors: list[str] = []
    res = None
    lock = _DeviceLock()
    # If a watcher probe holds the lock, give up QUICKLY rather than
    # burning the budget waiting: a tunnel that was ever live has a
    # BENCH_LIVE.json the fallback below reports, and a wedged tunnel
    # would fail the attempts anyway. The 100 s reserve guarantees the
    # CPU-fallback child a cold-compile-sized window on this 1-core host
    # (45 s starved it in a rehearsal).
    if lock.acquire(timeout_s=min(60.0, max(_remaining() - 120, 0))):
        try:
            for attempt in (1, 2, 3):
                budget = _remaining() - 100  # reserve: CPU-fallback child
                if budget < 20:
                    break
                res, err = _run_child({}, min(budget, 100))
                if res is not None:
                    break
                errors.append(f"tpu attempt {attempt}: {err}")
        finally:
            lock.release()
    else:
        errors.append("device lock busy: another tunnel client held it")

    code_hash = _compute_code_hash()
    host_model = _host_fingerprint()["cpu_model"]
    live_used = None
    if res is not None and res.get("platform") == "tpu":
        # persist the newest live result (latest, not best-ever: a cached
        # number must be one the CURRENT code can reproduce) so a future
        # harvest whose attempts hit a wedged tunnel still reports a
        # measured-on-TPU number
        rec = dict(res, measured_at=_utcnow(),
                   code_hash=code_hash, host_cpu_model=host_model)
        try:
            _dump_json_atomic(rec, LIVE_FILE)
        except OSError:
            pass
    if res is None or res.get("platform") != "tpu":
        # no TPU measurement this run (wedged tunnel, OR a fast-failing
        # plugin that made the child silently fall back to the CPU
        # backend): a valid same-code same-host live TPU cache beats both
        cached = _load_json(LIVE_FILE)
        if cached is not None and cached.get("platform") == "tpu":
            if (cached.get("code_hash") == code_hash
                    and cached.get("host_cpu_model") == host_model):
                res = cached
                live_used = cached.get("measured_at", "unknown")
            else:
                errors.append(
                    "live cache rejected: code_hash/host mismatch "
                    f"({cached.get('code_hash')} vs {code_hash})"
                )
    if res is None:
        # at least 60 s even when the attempts overran: a cold CPU child
        # compile needs it (the deadline may stretch slightly — better a
        # late number than none)
        res, err = _run_child(cpu_env, min(max(_remaining() - 10, 60), 150))
        if res is None:
            errors.append(f"cpu fallback: {err}")
    if res is None:  # last resort: never exit without the JSON line
        res = {"per_step": float("inf"), "platform": "none", "iters": 0, "t": T}
    device_fps = res.get("t", T) / res["per_step"]

    # CPU single-core baseline: pinned protocol artifact when available
    # (BASELINE_MEASURED.json, --pin-baseline), so every bench run reports
    # vs_baseline against the SAME median-of-N denominator instead of a
    # noisy per-run remeasurement (VERDICT r3 #2). Re-measured only when
    # the artifact is missing (and then persisted).
    pinned = _load_json(BASELINE_FILE)
    if pinned and "baseline_8core_fps" in pinned:
        baseline_8core = float(pinned["baseline_8core_fps"])
        done = int(pinned.get("protocol", {}).get("frames_per_run", 0))
        base_src = "pinned"
        if "fallback" in pinned.get("protocol", {}).get("stat", ""):
            base_src = "pinned(fallback)"  # one-shot, not the median-of-N
        if pinned.get("host", {}).get("cpu_model") != host_model:
            base_src = "pinned(foreign-host)"
    else:
        cpu_core_fps, done = _measure_baseline(
            max(1, int(os.environ.get("BENCH_BASE_FRAMES", "20"))),
            deadline_at=time.perf_counter() + max(10.0, _remaining() - 5),
        )
        baseline_8core = 8.0 * cpu_core_fps
        base_src = "measured"
        if done >= 4:  # a deadline-truncated 2-frame run is too noisy to pin
            try:
                pin_art = {
                    "cpu_core_fps": round(cpu_core_fps, 4),
                    "baseline_8core_fps": round(baseline_8core, 4),
                    "protocol": {"frames_per_run": done, "runs": 1,
                                 "stat": "single run (harvest fallback)"},
                    "host": _host_fingerprint(),
                    "measured_at": _utcnow(),
                }
                _dump_json_atomic(pin_art, BASELINE_FILE)
            except OSError:
                pass

    out = {
        "metric": "AVPVS frames/sec/chip (1080p->4K Lanczos + SI/TI)",
        "value": round(device_fps, 2),
        "unit": "frames/s/chip",
        "vs_baseline": round(device_fps / baseline_8core, 2),
        "platform": res["platform"],
        "baseline_8core_fps": round(baseline_8core, 2),
        "baseline_source": base_src,
        "baseline_frames": done,
    }
    if live_used:
        # this run's own TPU attempts failed; the number is the best live
        # measurement this bench persisted earlier (same host, same code)
        out["source"] = "cached_live_run"
        out["live_measured_at"] = live_used
    if "overlay_per_step" in res:
        # 4K spinner-overlay composite (BASELINE config 3's stalling
        # workload — the bufferer replacement); each step renders
        # played + inserted frames, so fps counts the plan's full output
        out["overlay_fps"] = round(
            res.get("overlay_frames", T) / res["overlay_per_step"], 2
        )
    if "metrics_per_step" in res:
        # device PSNR+SSIM per 1080p pair (BASELINE config 4's feature
        # extraction), against a pinned single-core numpy/scipy model x 8
        out["metrics_fps"] = round(
            res.get("metrics_frames", T) / res["metrics_per_step"], 2
        )
        mb8 = (pinned or {}).get("metrics_baseline_8core_fps")
        if not mb8 and _remaining() > 25:
            m_fps, m_done = _measure_metrics_baseline(6)
            mb8 = 8.0 * m_fps
            try:
                art = _load_json(BASELINE_FILE) or {}
                art["metrics_cpu_core_fps"] = round(m_fps, 4)
                art["metrics_baseline_8core_fps"] = round(mb8, 4)
                art.setdefault("metrics_protocol", {
                    "work": "PSNR + single-scale SSIM (11-tap gaussian) "
                            "per 1080p pair, float64 numpy/scipy, 1 core",
                    "frames": m_done,
                })
                _dump_json_atomic(art, BASELINE_FILE)
            except OSError:
                pass
        if mb8:
            out["metrics_vs_baseline"] = round(
                out["metrics_fps"] / float(mb8), 2
            )
    if "batch_per_step" in res:
        # 4-lane PVS-batched step (BASELINE config 5's device shape via
        # parallel/p03_batch waves): per-frame rate with fuller tiles
        out["batch_fps"] = round(
            res.get("batch_frames", 4 * T) / res["batch_per_step"], 2
        )

    # Optional: fused-Pallas vs banded method comparison (TPU only, when
    # enough budget remains). The headline child ran method "auto" (fused
    # on TPU), so this child pins "banded"; PC_BENCH_NO_EXTRAS skips the
    # overlay re-measurement, which cuts the child's cost enough that the
    # pair usually fits the budget. Run SERIALLY after the baseline — on a
    # 1-core host an overlapped child would contend with the baseline
    # loop, deflating cpu_core_fps (inflating vs_baseline) and absorbing
    # scheduler delay into banded_fps. Skipped when the parent env pins
    # PC_RESIZE_METHOD (the headline child inherited it, so labeling the
    # pair banded-vs-fused would be wrong).
    if (
        res["platform"] == "tpu"
        and live_used is None  # a wedged tunnel would only burn the budget
        and _remaining() > 75  # cold client 20-40s + banded compile + measure
        and not os.environ.get("PC_RESIZE_METHOD")
    ):
        banded, _ = _run_child(
            {"PC_RESIZE_METHOD": "banded", "PC_BENCH_NO_EXTRAS": "1"},
            _remaining() - 10,
        )
        # a tunnel that drops between children would hand back a CPU
        # number; never record that next to a TPU fused_fps
        if banded and banded.get("platform") == "tpu":
            out["fused_fps"] = out["value"]
            out["banded_fps"] = round(
                banded.get("t", T) / banded["per_step"], 2
            )

    # End-to-end p03 product path (VERDICT r4 #1): decode -> device ->
    # FFV1 writeback on a real generated database through the real p03
    # stage — the honest companion to the kernel headline above, with its
    # own baseline and live cache. Disabled via PC_BENCH_NO_E2E for tests
    # that pin the harness flow.
    if not os.environ.get("PC_BENCH_NO_E2E"):
        out.update(_e2e_flow(
            errors,
            try_tpu=res.get("platform") == "tpu" and live_used is None,
        ))

    if errors:
        # env-down must be provable from the artifact alone
        out["tpu_error"], out["tpu_attempts"] = _error_summary(errors)
    print(json.dumps(out))


if __name__ == "__main__":
    if "--child" in sys.argv:
        if os.environ.get("PC_BENCH_E2E_CHILD"):
            _e2e_child()
        else:
            _child()
    elif "--e2e" in sys.argv:
        # standalone e2e refresh (the watcher's live_extra hook): attempt
        # the tunnel, persist/refresh BENCH_E2E_LIVE.json, print the
        # e2e_* fields as one JSON line
        _errors: list = []
        _out = _e2e_flow(_errors, try_tpu=True)
        if _errors:
            _out["e2e_errors"], _out["tpu_attempts"] = (
                _error_summary(_errors)
            )
        print(json.dumps(_out))
    elif "--host-bench" in sys.argv:
        print(json.dumps(host_bench()))
    elif "--complexity-bench" in sys.argv:
        print(json.dumps(complexity_bench()))
    elif "--fused-bench" in sys.argv:
        print(json.dumps(fused_bench()))
    elif "--sharedscan-bench" in sys.argv:
        print(json.dumps(sharedscan_bench()))
    elif "--pin-baseline" in sys.argv:
        print(json.dumps(pin_baseline(), indent=1))
    else:
        main()
