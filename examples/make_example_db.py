#!/usr/bin/env python3
"""Generate a self-contained example database, ready for the full chain.

The reference's quickstart depends on an external fixture corpus
(github.com/pnats2avhd/example-databases, reference test/build_and_test.sh:5
and README.md:87-92). This framework ships the equivalent as a generator:
synthetic SRC videos are rendered through the framework's own io layer, so
a complete, runnable database exists after one command with no downloads.

    python examples/make_example_db.py /tmp/dbs                 # short DB
    python examples/make_example_db.py /tmp/dbs --type long     # long DB
    python examples/make_example_db.py /tmp/dbs --type mixed    # h265+vp9
    python -m processing_chain_tpu -c /tmp/dbs/P2SXM99/P2SXM99.yaml -v

The short database exercises: bitrate-targeted 2-pass and CRF x264 coding,
an fps-ladder downsample, a stalling HRC (spinner overlay in p03), and two
viewing contexts (pc + mobile) in p04. The mixed database is BASELINE.json
config 3's shape: an H.265 + VP9 PVS mix whose stalling HRCs run the
spinner-overlay composite during the AVPVS upscale. The long database adds: multi-segment
planning with quality switches, AAC audio coding, a mid-stream stall, and
last-segment truncation against the SRC duration (reference
lib/test_config.py:1216-1220 semantics).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from processing_chain_tpu.io import VideoWriter  # noqa: E402

SHORT_YAML = """\
databaseId: {db_id}
syntaxVersion: 6
type: short
qualityLevelList:
  Q0: {{index: 0, videoCodec: h264, videoBitrate: 300, width: 320, height: 180, fps: 12}}
  Q1: {{index: 1, videoCodec: h264, videoBitrate: 800, width: 640, height: 360, fps: 24}}
  Q2: {{index: 2, videoCodec: h264, videoCrf: 26, width: 640, height: 360, fps: 24}}
codingList:
  VC01: {{type: video, encoder: libx264, passes: 2, iFrameInterval: 2, preset: veryfast}}
  VC02: {{type: video, encoder: libx264, crf: yes, iFrameInterval: 2, preset: veryfast}}
srcList:
  SRC000: SRC000.avi
  SRC001: SRC001.avi
hrcList:
  HRC000: {{videoCodingId: VC01, eventList: [[Q0, 4]]}}
  HRC001: {{videoCodingId: VC01, eventList: [[Q1, 4]]}}
  HRC002: {{videoCodingId: VC02, eventList: [[Q2, 4]]}}
  HRC003: {{videoCodingId: VC01, eventList: [[Q1, 4], [stall, 1.0]]}}
pvsList:
  - {db_id}_SRC000_HRC000
  - {db_id}_SRC000_HRC001
  - {db_id}_SRC000_HRC002
  - {db_id}_SRC000_HRC003
  - {db_id}_SRC001_HRC001
postProcessingList:
  - {{type: pc, displayWidth: 640, displayHeight: 360, codingWidth: 640, codingHeight: 360, displayFrameRate: 24}}
  - {{type: mobile, displayWidth: 640, displayHeight: 360, codingWidth: 640, codingHeight: 360, displayFrameRate: 24}}
"""

MIXED_YAML = """\
databaseId: {db_id}
syntaxVersion: 6
type: short
qualityLevelList:
  Q0: {{index: 0, videoCodec: h265, videoBitrate: 500, width: 640, height: 360, fps: 24}}
  Q1: {{index: 1, videoCodec: vp9, videoBitrate: 500, width: 640, height: 360, fps: 24}}
codingList:
  VC01: {{type: video, encoder: libx265, passes: 1, iFrameInterval: 2, preset: ultrafast}}
  VC02: {{type: video, encoder: libvpx-vp9, passes: 1, iFrameInterval: 2, speed: 4}}
srcList:
  SRC000: SRC000.avi
  SRC001: SRC001.avi
hrcList:
  HRC000: {{videoCodingId: VC01, eventList: [[Q0, 4], [stall, 1.0]]}}
  HRC001: {{videoCodingId: VC02, eventList: [[Q1, 4], [stall, 1.0]]}}
pvsList:
  - {db_id}_SRC000_HRC000
  - {db_id}_SRC001_HRC001
postProcessingList:
  - {{type: pc, displayWidth: 1280, displayHeight: 720, codingWidth: 1280, codingHeight: 720, displayFrameRate: 24}}
"""

LONG_YAML = """\
databaseId: {db_id}
syntaxVersion: 6
type: long
segmentDuration: 4
qualityLevelList:
  Q0: {{index: 0, videoCodec: h264, videoBitrate: 300, width: 320, height: 180, fps: 24, audioCodec: aac, audioBitrate: 96}}
  Q1: {{index: 1, videoCodec: h264, videoBitrate: 800, width: 640, height: 360, fps: 24, audioCodec: aac, audioBitrate: 128}}
codingList:
  VC01: {{type: video, encoder: libx264, passes: 1, iFrameInterval: 2, preset: veryfast}}
  AC01: {{type: audio, encoder: aac}}
srcList:
  SRC000: SRC000.avi
hrcList:
  HRC000:
    videoCodingId: VC01
    audioCodingId: AC01
    eventList:
      - [Q0, 8]
      - [stall, 2.0]
      - [Q1, 4]
pvsList:
  - {db_id}_SRC000_HRC000
postProcessingList:
  - {{type: pc, displayWidth: 640, displayHeight: 360, codingWidth: 640, codingHeight: 360, displayFrameRate: 24}}
"""


def render_src(path: str, w: int, h: int, n: int, fps: int, seed: int,
               audio: bool) -> None:
    """Synthetic SRC with real spatial detail and motion (nonzero SI/TI):
    a drifting sinusoid field plus an orbiting high-contrast block."""
    rng = np.random.default_rng(seed)
    phase = rng.uniform(0, 2 * np.pi, size=3)
    aud = dict(audio_codec="flac", sample_rate=48000, channels=2) if audio else {}
    with VideoWriter(path, "ffv1", w, h, "yuv420p", (fps, 1), **aud) as wr:
        if audio:
            t = np.arange(48000 * n // fps)
            tone = (np.sin(2 * np.pi * 330 * t / 48000) * 8000).astype(np.int16)
            wr.write_audio(np.stack([tone, tone], axis=1))
        xx, yy = np.meshgrid(np.arange(w), np.arange(h))
        for i in range(n):
            y = (
                (np.sin((xx + 3 * i) / 19 + phase[0])
                 + np.cos((yy + 2 * i) / 13 + phase[1])) * 48 + 124
            )
            bx = int((np.cos(i / fps * 2 + phase[2]) * 0.3 + 0.5) * (w - 32))
            by = int((np.sin(i / fps * 2 + phase[2]) * 0.3 + 0.5) * (h - 32))
            y[by:by + 32, bx:bx + 32] = 235 if i % 2 else 16
            u = np.full((h // 2, w // 2), 128, np.uint8)
            v = np.full((h // 2, w // 2), 118, np.uint8)
            u[by // 2:by // 2 + 16, bx // 2:bx // 2 + 16] = 180
            wr.write(y.clip(16, 235).astype(np.uint8), u, v)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("out_dir", help="directory to create the database under")
    ap.add_argument("--type", choices=("short", "long", "mixed"),
                    default="short")
    ap.add_argument("--db-id", default=None,
                    help="database id (default P2SXM99 short / P2LTR99 long "
                    "/ P2SXM98 mixed)")
    ap.add_argument("--src-seconds", type=int, default=None,
                    help="SRC duration in seconds (default: 6 short, 10 long; "
                    "the long event list totals 12 s, so the default "
                    "exercises last-segment truncation)")
    args = ap.parse_args(argv)

    db_id = args.db_id or {"short": "P2SXM99", "long": "P2LTR99",
                           "mixed": "P2SXM98"}[args.type]
    if args.src_seconds is None:
        secs = 10 if args.type == "long" else 6
    elif args.src_seconds > 0:
        secs = args.src_seconds
    else:
        ap.error(f"--src-seconds must be positive, got {args.src_seconds}")
    fps = 24
    db_dir = os.path.join(args.out_dir, db_id)
    src_dir = os.path.join(db_dir, "srcVid")
    os.makedirs(src_dir, exist_ok=True)

    tmpl = {"short": SHORT_YAML, "long": LONG_YAML,
            "mixed": MIXED_YAML}[args.type]
    yaml_path = os.path.join(db_dir, f"{db_id}.yaml")
    with open(yaml_path, "w") as f:
        f.write(tmpl.format(db_id=db_id))

    n_srcs = 1 if args.type == "long" else 2
    for s in range(n_srcs):
        render_src(
            os.path.join(src_dir, f"SRC{s:03d}.avi"),
            w=640, h=360, n=secs * fps, fps=fps, seed=s,
            audio=(args.type == "long"),
        )

    print(yaml_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
