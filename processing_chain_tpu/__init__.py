"""processing_chain_tpu — TPU-native video degradation processing chain.

A brand-new JAX/XLA/Pallas framework with the capabilities of the
AVHD-AS / P.NATS Phase 2 processing chain (reference: pnats2avhd/processing-chain):
YAML-defined test databases of SRC videos and HRC degradation conditions are
encoded into segments, metadata (.qchanges/.vfi/.afi/.buff), lossless AVPVS
renders, and context-processed CPVS outputs — with the pixel-domain hot path
(decode-fed rescale, spinner/stall compositing, concat, SI/TI + PSNR/SSIM
feature extraction) executed as batched kernels on TPU.

Layout:
    config/    domain model + YAML contract (reference: lib/test_config.py)
    models/    the four artifact pipelines as typed op graphs
               (segments / metadata / avpvs / cpvs)
    ops/       device kernel library (resize, SI/TI, overlay, metrics, pixfmt)
    parallel/  mesh + sharding strategies, host fan-out, halo exchange
    io/        host media boundary (native libav demux/decode/encode/mux)
    native/    C++ sources for the media boundary
    stages/    p00–p04 drivers + CLI (reference: p0*_*.py)
    utils/     logging, runner, version, aux tools
"""

__version__ = "0.1.0"
