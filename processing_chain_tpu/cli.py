"""Command-line entry points.

    python -m processing_chain_tpu -c DB/DB.yaml [-str 1234] …   (p00)
    python -m processing_chain_tpu.cli p01 -c …                  (single stage)

Flag surface mirrors the reference's per-script CLIs (README.md:94-127).
ConfigError and pipeline failures exit 1 like the reference's sys.exit(1)
sites.
"""

from __future__ import annotations

import sys
import time
from typing import Optional, Sequence

from . import telemetry
from .config.errors import ConfigError
from .io.medialib import MediaError
from .utils import log as log_mod
from .utils import parse_args as pa
from .utils import tracing
from .utils.runner import ChainError
from .utils.version import check_requirements


def _write_telemetry(out_dir: str, status: str, wall_s: float,
                     stamp: Optional[str] = None) -> None:
    """Persist the run's metrics/events/trace under one stamp into
    `out_dir`. Best-effort: persistence failures must never replace the
    run's own outcome (mirrors the --trace report guard below)."""
    telemetry.emit("run_end", status=status, duration_s=round(wall_s, 4))
    try:
        paths = telemetry.write_outputs(out_dir, stamp=stamp)
        tracing.get_tracer().write_report(out_dir, name=paths["stamp"])
        log_mod.get_logger().info(
            "telemetry: %s metrics_%s.{json,prom} + events + trace",
            out_dir, paths["stamp"],
        )
    except Exception as exc:  # noqa: BLE001 - runs in _dispatch's finally:
        # anything narrower would let a persistence error (unwritable dir,
        # a non-serializable emit() field) replace the propagating
        # pipeline exception
        log_mod.get_logger().warning(
            "could not write telemetry to %s: %s", out_dir, exc
        )


def _dispatch(stage: Optional[str], argv: Sequence[str]) -> int:
    script_num = {"p01": 1, "p02": 2, "p03": 3, "p04": 4}.get(stage or "")
    name = stage or "processAll"
    args = pa.parse_args(name, script_num, argv)
    log_mod.setup_custom_logger("main", verbose=args.verbose)
    if not args.skip_requirements:
        check_requirements()
    from .utils.device import ensure_backend

    ensure_backend()
    from .store import runtime as store_runtime

    store = store_runtime.configure_from_args(args)
    if store is not None:
        log_mod.get_logger().info("artifact store: %s", store.root)
    telemetry_dir = getattr(args, "telemetry", None)
    profile_dir = getattr(args, "profile", None)
    live_port = getattr(args, "live_port", None)
    status_file = getattr(args, "status_file", None)
    wd_soft = getattr(args, "watchdog_soft", None)
    wd_hard = getattr(args, "watchdog_hard", None)
    live_on = (
        telemetry_dir is not None or live_port is not None
        or status_file is not None or wd_soft is not None
        or wd_hard is not None or profile_dir is not None
    )
    run_stamp = None
    if live_on:
        # live observability IS telemetry, just served instead of (or as
        # well as) persisted: /metrics renders the same registry, the
        # watchdog's forensics land in the same event log
        telemetry.enable()
        telemetry.attach_log_handler(log_mod.get_logger())
        if telemetry_dir:
            # stream events to disk AS THEY HAPPEN under a stamp fixed
            # now: a run that crashes or is SIGKILLed leaves its event
            # history (incl. watchdog forensics) for a partial
            # run-report, instead of only an orderly-exit snapshot
            import os as os_mod

            run_stamp = telemetry.unique_stamp()
            try:
                telemetry.EVENTS.open_stream(os_mod.path.join(
                    telemetry_dir, f"events_{run_stamp}.jsonl"
                ))
            except OSError as exc:
                log_mod.get_logger().warning(
                    "cannot stream events to %s: %s", telemetry_dir, exc
                )
            # the device-plane wave journal rides the same stamp so
            # run-report can join it (parallel/meshobs.py; appends are
            # flushed per record — crash-truncation safe like the
            # event stream above)
            from .parallel import meshobs

            meshobs.attach_journal(os_mod.path.join(
                telemetry_dir, f"meshobs_{run_stamp}"
            ))
        telemetry.emit("run_start", name=name, argv=list(argv))
    # the watchdog rides the live surface or its own flags — NOT bare
    # --telemetry: coarse units of work (a long encode job) beat only on
    # completion, so a default-on watchdog would flag healthy long jobs
    # on every routine instrumented run
    watchdog_on = (
        live_port is not None or status_file is not None
        or wd_soft is not None or wd_hard is not None
    )
    live_server = status_writer = watchdog = None
    if watchdog_on:
        from .telemetry import live as live_mod
        from .telemetry import watchdog as watchdog_mod

        live_mod.set_run_meta(name=name, argv=list(argv))
        try:
            if live_port is not None:
                live_server = live_mod.LiveServer(live_port).start()
                log_mod.get_logger().info(
                    "live status: %s/{healthz,metrics,status}",
                    live_server.url,
                )
            if status_file:
                status_writer = live_mod.StatusFileWriter(status_file).start()
        except OSError as exc:
            # an unbindable port / unwritable status path is an operator
            # mistake, not a pipeline failure: clean exit 1, like ConfigError
            log_mod.get_logger().error(
                "cannot start live observability: %s", exc
            )
            if live_server is not None:
                live_server.stop()
            return 1
        watchdog = watchdog_mod.start_watchdog(
            soft_s=wd_soft if wd_soft is not None else watchdog_mod.DEFAULT_SOFT_S,
            hard_s=wd_hard,
        )
    tracing_on = getattr(args, "trace", None) is not None
    profiler = tracing.DeviceProfiler(args.trace or None) if tracing_on else None
    chain_profiler = None
    if profile_dir is not None:
        # the performance-attribution capture: resource-monitor thread +
        # merged host/device timeline, persisted under the run stamp so
        # run-report and `tools chain-profile` can join the artifacts
        from .telemetry import profiling as profiling_mod

        if run_stamp is None:
            run_stamp = telemetry.unique_stamp()
        chain_profiler = profiling_mod.Profiler(
            profile_dir,
            # jax.profiler is one process-wide session: when --trace DIR
            # requests its own device capture, it owns it — the merged
            # host timeline here is unaffected
            device_trace=False if (tracing_on and args.trace) else None,
        ).start(run_stamp)
        log_mod.get_logger().info(
            "profiling to %s (stamp %s)", profile_dir, run_stamp
        )
    test_config = None
    status = "ok"
    t0 = time.perf_counter()
    try:
        if profiler is not None:
            profiler.start()
        if stage is None:
            from .stages import p00_process_all

            test_config = p00_process_all.run(args)
        else:
            from .stages import (
                p01_generate_segments,
                p02_generate_metadata,
                p03_generate_avpvs,
                p04_generate_cpvs,
            )

            mod = {
                "p01": p01_generate_segments,
                "p02": p02_generate_metadata,
                "p03": p03_generate_avpvs,
                "p04": p04_generate_cpvs,
            }[stage]
            test_config = mod.run(args)
    except (ConfigError, ChainError, MediaError) as exc:
        # MediaError is a CLASSIFIED native-boundary failure (corrupt
        # input, injected fault — it names path + stream frame, docs/
        # ROBUSTNESS.md): a user-grade error exit, not a traceback
        status = "fail"
        log_mod.get_logger().error("%s", exc)
        return 1
    except BaseException:
        status = "fail"
        raise
    finally:
        if watchdog is not None:
            from .telemetry import watchdog as watchdog_mod

            watchdog_mod.stop_watchdog()
        if status_writer is not None:
            # writes one final snapshot so the file records how the run
            # ended, then stops the rewriter
            status_writer.stop()
        if live_server is not None:
            live_server.stop()
        if profiler is not None:
            profiler.stop()
        if chain_profiler is not None:
            paths = chain_profiler.stop(run_stamp)
            if paths.get("trace"):
                log_mod.get_logger().info(
                    "profile: %s (+ %s)%s — view in chrome://tracing / "
                    "Perfetto; `tools chain-profile %s` for the summary",
                    paths["trace"], paths.get("resources", ""),
                    f" + device trace {paths['device_trace_dir']}"
                    if paths.get("device_trace_dir") else "",
                    profile_dir,
                )
        if store is not None:
            # persist the stat-keyed input digest cache (best-effort by
            # contract) so the next run's plan hashing pays stats, not reads
            store.digests.save()
        if telemetry_dir:
            from .parallel import meshobs

            meshobs.detach_journal()
            _write_telemetry(
                telemetry_dir, status, time.perf_counter() - t0,
                stamp=run_stamp,
            )
        if tracing_on:
            tracer = tracing.get_tracer()
            tracer.log_summary()
            if test_config is not None:
                logs_dir = test_config.get_logs_path()
            else:
                # stage failed before returning its config — persist next to
                # the database anyway (default logs/ layout): failed runs are
                # exactly the ones whose timing matters
                import os

                logs_dir = os.path.join(
                    os.path.dirname(os.path.abspath(args.test_config)), "logs"
                )
            try:
                path = tracer.write_report(logs_dir)
                log_mod.get_logger().info("timing report: %s", path)
            except OSError as exc:
                # never let report persistence replace the run's own
                # outcome (exit code or original exception)
                log_mod.get_logger().warning(
                    "could not write timing report to %s: %s", logs_dir, exc
                )
    return 0


def _dispatch_tool(argv: Sequence[str]) -> int:
    """`tools <name> …` subcommands (reference util/ scripts)."""
    tools = (
        "src-analysis", "complexity", "priors", "plots", "metrics",
        "clean-logs", "run-report", "store", "chain-top", "chain-profile",
        "bench-compare", "chain-lint", "chain-serve", "serve-soak",
        "queue-crashcheck", "serve-chaos", "media-crashcheck",
        "serve-admin", "fleet-top", "trace", "store-heat",
        "store-tiers", "mesh-top", "mesh-report", "fleet-doctor",
        "bench-history",
    )
    if not argv or argv[0] not in tools:
        sys.stderr.write(f"usage: tools {{{','.join(tools)}}} …\n")
        return 2
    name, rest = argv[0], list(argv[1:])
    log_mod.setup_custom_logger("main")
    try:
        if name == "run-report":
            from .telemetry import report

            return report.main(rest)
        if name == "store":
            from .tools import store_admin

            return store_admin.main(rest)
        if name == "store-heat":
            from .tools import store_heat

            return store_heat.main(rest)
        if name == "store-tiers":
            from .tools import store_tiers

            return store_tiers.main(rest)
        if name == "mesh-top":
            from .tools import mesh_top

            return mesh_top.main(rest)
        if name == "mesh-report":
            from .tools import mesh_report

            return mesh_report.main(rest)
        if name == "chain-top":
            from .tools import chain_top

            return chain_top.main(rest)
        if name == "fleet-top":
            from .tools import fleet_top

            return fleet_top.main(rest)
        if name == "fleet-doctor":
            from .tools import fleet_doctor

            return fleet_doctor.main(rest)
        if name == "bench-history":
            from .tools import bench_history

            return bench_history.main(rest)
        if name == "trace":
            from .tools import trace_tool

            return trace_tool.main(rest)
        if name == "chain-profile":
            from .tools import chain_profile

            return chain_profile.main(rest)
        if name == "bench-compare":
            from .tools import bench_compare

            return bench_compare.main(rest)
        if name == "chain-lint":
            from .tools.chainlint import cli as chainlint_cli

            return chainlint_cli.main(rest)
        if name == "chain-serve":
            from .tools import chain_serve

            return chain_serve.main(rest)
        if name == "serve-soak":
            from .tools import serve_soak

            return serve_soak.main(rest)
        if name == "queue-crashcheck":
            from .tools import queue_crashcheck

            return queue_crashcheck.main(rest)
        if name == "serve-chaos":
            from .tools import serve_chaos

            return serve_chaos.main(rest)
        if name == "media-crashcheck":
            from .tools import media_crashcheck

            return media_crashcheck.main(rest)
        if name == "serve-admin":
            from .tools import serve_admin

            return serve_admin.main(rest)
        if name == "src-analysis":
            from .tools import src_analysis

            return src_analysis.main(rest)
        if name == "complexity":
            from .tools import complexity

            return complexity.main(rest)
        if name == "priors":
            from .tools import priors_tool

            return priors_tool.main(rest)
        if name == "metrics":
            from .utils.device import ensure_backend

            ensure_backend()
            from .tools import quality_metrics

            return quality_metrics.main(rest)
        if name == "clean-logs":
            from .tools import clean_logs

            return clean_logs.main(rest)
        from .tools import plots

        return plots.main(rest)
    except (OSError, ValueError, KeyError, ChainError, MediaError) as exc:
        # expected failure modes only (ConfigError ⊂ ValueError); anything
        # else keeps its traceback — an XLA RuntimeError is a bug, not a
        # user error
        log_mod.get_logger().error("tools %s: %s", name, exc)
        return 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "tools":
        return _dispatch_tool(argv[1:])
    stage = None
    if argv and argv[0] in ("p01", "p02", "p03", "p04", "p00"):
        head = argv.pop(0)
        stage = None if head == "p00" else head
    return _dispatch(stage, argv)


if __name__ == "__main__":
    sys.exit(main())
