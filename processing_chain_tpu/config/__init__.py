from .errors import ConfigError
from .domain import (
    Coding,
    Event,
    Hrc,
    PostProcessing,
    Pvs,
    QualityLevel,
    Segment,
    Src,
    YoutubeCoding,
)
from .probe_api import SrcProber, StaticProber
from .test_config import TestConfig

__all__ = [
    "ConfigError",
    "Coding",
    "Event",
    "Hrc",
    "PostProcessing",
    "Pvs",
    "QualityLevel",
    "Segment",
    "Src",
    "YoutubeCoding",
    "SrcProber",
    "StaticProber",
    "TestConfig",
]
