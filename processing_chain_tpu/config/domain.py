"""Domain object graph of a P.NATS Phase 2 test database.

Parity targets (semantics, not code): reference lib/test_config.py —
QualityLevel :911-944, Coding :748-899, Event :602-641, Src :644-745,
Hrc :230-372, Segment :375-599, Pvs :52-227, PostProcessing :947-979.

Deliberate fixes over the reference (documented in SURVEY.md quirks list):
  * freeze-event durations are converted to float like stall events
    (reference test_config.py:620-621 keeps the raw YAML value);
  * all invariant violations raise ConfigError instead of sys.exit(1).
"""

from __future__ import annotations

import os
import re
from fractions import Fraction
from typing import TYPE_CHECKING, Any, Optional

from ..utils.log import get_logger
from . import ids
from .errors import ConfigError

if TYPE_CHECKING:  # pragma: no cover
    from .test_config import TestConfig

ONLINE_CODERS = ["youtube", "bitmovin", "vimeo"]

#: encoders acceptable for each quality-level codec (reference :255-263)
_CODEC_ENCODERS = {
    "h264": {"libx264", "h264_nvenc"},
    "h265": {"libx265", "hevc_nvenc"},
    "vp9": {"libvpx-vp9"},
    "av1": {"libaom-av1"},
}


class QualityLevel:
    """One rung of the bitrate/resolution ladder (reference :911-944)."""

    def __init__(self, ql_id: str, test_config: "TestConfig", data: dict) -> None:
        self.ql_id = ql_id
        self.test_config = test_config
        self.index = data["index"]
        self.video_codec = data["videoCodec"]
        self.video_bitrate = data.get("videoBitrate")
        self.width = int(data["width"])
        self.height = int(data["height"])
        self.fps = data["fps"]

        if self.width % 2 or self.height % 2:
            raise ConfigError(
                f"width and height in QualityLevel {ql_id} must be divisible by 2"
            )

        self.audio_codec = data.get("audioCodec")
        self.audio_bitrate = data.get("audioBitrate")
        self.video_crf = int(data["videoCrf"]) if "videoCrf" in data else None
        self.video_qp = int(data["videoQp"]) if "videoQp" in data else None

        self.hrcs: set[Hrc] = set()

    def __repr__(self) -> str:
        return f"<QualityLevel {self.ql_id}, Index {self.index}>"


class Coding:
    """Encoder configuration shared by HRCs (reference :748-899)."""

    def __init__(self, coding_id: str, test_config: "TestConfig", data: dict) -> None:
        log = get_logger()
        self.coding_id = coding_id
        self.test_config = test_config
        self.coding_type = data["type"]

        self.is_online: Optional[bool] = None
        self.crf = None
        self.qp = None
        self.passes: Optional[int] = None
        self.cpu_used = data.get("cpuUsed", 6)
        self.forced_pix_fmt = data.get("pixFmt")

        if self.coding_type == "audio":
            self.encoder = data["encoder"]
            return
        if self.coding_type != "video":
            raise ConfigError(
                f"Wrong coding type {self.coding_type!r} in coding {coding_id}: "
                "must be 'audio' or 'video'"
            )

        self.encoder = data["encoder"]
        self.is_online = self.encoder.casefold() in ONLINE_CODERS

        if self.encoder.casefold() in ("youtube", "vimeo"):
            self.protocol = data["protocol"]
            return

        self.max_gop = data.get("maxGop")
        self.min_gop = data.get("minGop")
        if self.encoder.casefold() != "bitmovin":
            if "passes" in data:
                self.passes = int(data["passes"])
                if self.passes not in (1, 2):
                    raise ConfigError(
                        f"only 1-pass or 2-pass encoding allowed in coding {coding_id}"
                    )
            elif "crf" in data:
                self.crf = data["crf"]
            elif "qp" in data:
                self.qp = data["qp"]
            else:
                log.warning(
                    "number of passes not specified in coding %s, assuming 2", coding_id
                )
                self.passes = 2

        # rate-control / GOP knobs with reference defaults (:806-821)
        self.speed = data.get("speed", 1)
        self.quality = data.get("quality", "good")
        self.scenecut = bool(data.get("scenecut", True))
        self.iframe_interval = (
            int(data["iFrameInterval"]) if "iFrameInterval" in data else None
        )
        self.bframes: Optional[int] = None
        self.preset = data.get("preset")
        self.minrate_factor = _opt_float(data, "minrateFactor")
        self.maxrate_factor = _opt_float(data, "maxrateFactor")
        self.bufsize_factor = _opt_float(data, "bufsizeFactor")
        # absolute minrate/maxrate/bufsize: parsed for dialect parity but
        # consumed by NOTHING — faithful to the reference, which parses
        # them (test_config.py:873-880) and never reads them anywhere
        # (lib/ffmpeg.py uses only the *Factor variants, :135-140)
        self.minrate = _opt_float(data, "minrate")
        self.maxrate = _opt_float(data, "maxrate")
        self.bufsize = _opt_float(data, "bufsize")
        self.enc_options = data.get("enc_options")

        if "profile" in data:
            log.warning("Setting profile in %s is not supported anymore.", coding_id)
        if self.iframe_interval is None and not self.is_online:
            log.warning(
                "Constant iFrame-Interval not set in coding %s, not recommended!",
                coding_id,
            )
        if "bframes" in data:
            if self.encoder == "libvpx-vp9":
                log.warning(
                    "VP9 does not have B-frames, ignoring setting in coding %s",
                    coding_id,
                )
            else:
                self.bframes = int(data["bframes"])
                if self.bframes < 0:
                    raise ConfigError("bframes must be >= 0")
        if self.speed not in (0, 1, 2, 3, 4):
            raise ConfigError("speed must be between 0 and 4")
        if self.quality not in ("good", "best"):
            raise ConfigError("quality must be 'good' or 'best'")
        if self.encoder != "libvpx-vp9" and (
            bool(self.maxrate_factor) ^ bool(self.bufsize_factor)
        ):
            raise ConfigError(
                f"if either maxrateFactor or bufsizeFactor is set, both must be "
                f"specified in coding {coding_id}"
            )

    def __repr__(self) -> str:
        return f"<Coding {self.coding_id}>"


def _opt_float(data: dict, key: str) -> Optional[float]:
    return float(data[key]) if key in data else None


class YoutubeCoding:
    """Dummy coding slot for the online path (reference :902-908)."""

    def __init__(self, coding_id: str, test_config: "TestConfig") -> None:
        self.coding_id = coding_id
        self.test_config = test_config
        self.coding_type = "video"
        self.encoder = "youtube"
        self.is_online = True
        self.forced_pix_fmt = None

    def __repr__(self) -> str:
        return f"<Coding {self.coding_id}>"


class Event:
    """One playout event in an HRC's event list (reference :602-641)."""

    def __init__(self, event_type: str, quality_level: Any, duration: Any) -> None:
        self.event_type = event_type
        self.quality_level = quality_level
        self.hrc: Optional[Hrc] = None

        self.uses_src_duration = duration == "src_duration"
        if self.uses_src_duration:
            self.duration: Any = "src_duration"
        elif event_type in ("stall", "freeze"):
            # stall/freeze events may have fractional durations
            self.duration = float(duration)
        else:
            if not float(duration).is_integer():
                raise ConfigError(
                    "All non-stalling events must have an integer duration, "
                    f"got {duration!r}"
                )
            self.duration = int(duration)

    def set_duration(self, duration: Any) -> None:
        try:
            self.duration = float(duration)
        except (TypeError, ValueError) as exc:
            raise ConfigError(
                f"Tried to set duration of Event {self} to {duration!r}"
            ) from exc

    def __repr__(self) -> str:
        return f"<Event {self.event_type}, {self.quality_level}, {self.duration}s>"


class Src:
    """A source video (reference :644-745)."""

    def __init__(self, src_id: str, test_config: "TestConfig", data: Any) -> None:
        self.src_id = src_id
        self.test_config = test_config
        self.pvses: set[Pvs] = set()
        self.segments: set[Segment] = set()
        self.duration: Optional[float] = None
        self._stream_info: Optional[dict] = None
        #: deferred probe failure (docs/ROBUSTNESS.md): a SRC whose
        #: bytes the decoder rejects must not fail the WHOLE config
        #: parse — it fails the units that touch it, when they touch it
        self.probe_error: Optional[BaseException] = None
        #: stat signature (size, mtime_ns) of the bytes the deferred
        #: verdict was issued against: a REPLACED upload (the re-arm
        #: workflow) must earn a fresh probe on a long-lived parse, not
        #: inherit the old bytes' conviction
        self._probe_stat: Optional[tuple] = None

        if isinstance(data, str):
            self.filename = data
            self.is_youtube = False
            self.youtube_url = None
        else:
            self.filename = data["srcFile"]
            self.youtube_url = data["youtubeUrl"]
            self.is_youtube = True

        src_path = test_config.get_src_vid_path()
        local_path = test_config.get_src_vid_local_path()
        if isinstance(src_path, list):
            # multi-folder SRC search (reference :663-674)
            folder = next(
                (p for p in src_path if os.path.exists(os.path.join(p, self.filename))),
                src_path[-1],
            )
        else:
            folder = src_path
        self.file_path = os.path.join(folder, self.filename)
        # the probe sidecar lives next to the SRC when writable, else in the
        # database-local srcVid folder (reference :669-684)
        if _is_writable_dir(folder):
            self.info_path = os.path.join(folder, self.filename + ".yaml")
        elif _is_writable_dir(local_path):
            self.info_path = os.path.join(local_path, self.filename + ".yaml")
        else:
            raise ConfigError(
                "Not possible to write info.yaml for SRC, all directories read-only"
            )

    def locate_src_file(self) -> None:
        """Resolve file_path, falling back to the database-local srcVid folder
        (reference :708-721)."""
        if not os.path.exists(self.file_path):
            local = os.path.join(
                self.test_config.get_src_vid_local_path(), self.filename
            )
            if not os.path.exists(local):
                raise ConfigError(
                    f"SRC {self.filename} does not exist in "
                    f"{self.test_config.get_src_vid_local_path()} nor "
                    f"{self.test_config.get_src_vid_path()}"
                )
            get_logger().debug("SRC %s found in local srcVid folder", self.filename)
            self.file_path = local

    def _stat_sig(self) -> Optional[tuple]:
        try:
            st = os.stat(self.file_path)
            return (st.st_size, st.st_mtime_ns)
        except OSError:
            return None

    def locate_and_get_info(self) -> None:
        from ..io.medialib import MediaError

        if self._stream_info is not None:
            return  # one probe per Src, even across its PVSes
        if self.probe_error is not None and \
                self._stat_sig() == self._probe_stat:
            return  # same bytes, same deferred verdict
        healing = self.probe_error is not None
        self.locate_src_file()
        try:
            self._stream_info = self.test_config.prober.src_info(
                self.file_path, self.info_path
            )
            self.probe_error = None
            if healing:
                # the repaired bytes may disagree with the yuv420p
                # stand-in the parse minted for the unprobeable SRC
                # (Segment._set_pix_fmt): re-derive from the live probe
                # so plans minted after the heal carry honest knobs
                for seg in self.segments:
                    seg._set_pix_fmt()
        except MediaError as exc:
            # DEFERRED: a hostile/corrupt SRC must poison only the
            # units that reference it, not 400 every request against
            # the database (serve) or kill a whole batch run at parse.
            # Consumers hit the classified re-raise in `stream_info`.
            self.probe_error = exc
            self._probe_stat = self._stat_sig()
            get_logger().warning(
                "SRC %s is unprobeable (%s) — deferring the failure to "
                "the units that touch it", self.filename,
                str(exc)[:200],
            )

    @property
    def stream_info(self) -> dict:
        """The probed video-stream info. For an unprobeable SRC this
        raises the deferred verdict — classified `poison` (the decoder
        rejected the BYTES; retrying them is futile, serve quarantines
        the content digest) with the path forensics every media error
        carries (docs/ROBUSTNESS.md)."""
        if self._stream_info is None and self.probe_error is not None:
            from ..io.medialib import MediaError

            if self._stat_sig() != self._probe_stat:
                # the bytes changed since the verdict (repaired upload
                # on a long-lived cached parse): re-probe before
                # re-raising a conviction about bytes that are gone. A
                # re-probe that fails in a NEW way (file deleted, …)
                # falls through to the deferred-verdict raise below.
                try:
                    self.locate_and_get_info()
                except Exception:  # noqa: BLE001 - heal is best-effort
                    pass
            if self._stream_info is not None:
                return self._stream_info
            raise MediaError(
                f"SRC {self.file_path} is unprobeable: "
                f"{str(self.probe_error)[:500]}",
                kind="poison",
            ) from self.probe_error
        if self._stream_info is None:
            from ..io.medialib import MediaError

            raise MediaError(
                f"SRC {self.file_path} was never probed "
                "(locate_and_get_info not called)"
            )
        return self._stream_info

    @stream_info.setter
    def stream_info(self, value: Optional[dict]) -> None:
        self._stream_info = value

    def uses_10_bit(self) -> bool:
        pix_fmt = self.stream_info["pix_fmt"]
        return "10" in pix_fmt and pix_fmt != "yuv410p"

    def get_duration(self) -> float:
        if self.duration is None:
            if self.probe_error is not None:
                self.stream_info  # raises the deferred classified verdict
            self.duration = float(
                self.test_config.prober.duration(self.file_path, self.info_path)
            )
        return self.duration

    def get_fps(self) -> float:
        return float(Fraction(str(self.stream_info["r_frame_rate"])))

    def get_src_file_path(self) -> str:
        return self.file_path

    def get_src_file_name(self) -> str:
        return self.filename

    def exists(self) -> bool:
        return os.path.isfile(self.file_path)

    def __repr__(self) -> str:
        return f"<{self.src_id}, File: {self.filename}>"


def _is_writable_dir(path: str) -> bool:
    """Reference test_config.py:43-49 probes with a TemporaryFile; os.access
    is equivalent for our purposes and does not touch the directory."""
    return os.path.isdir(path) and os.access(path, os.W_OK)


class Hrc:
    """A hypothetical reference circuit: codec + event list (reference :230-372)."""

    def __init__(
        self,
        hrc_id: str,
        test_config: "TestConfig",
        hrc_type: str,
        video_coding: Any,
        audio_coding: Any,
        event_list: list[Event],
        segment_duration: Any,
    ) -> None:
        self.hrc_id = hrc_id
        self.test_config = test_config
        self.hrc_type = hrc_type
        self.video_coding = video_coding
        self.audio_coding = audio_coding
        self.event_list = event_list

        for event in event_list:
            if event.event_type in ("stall", "freeze", "youtube"):
                continue
            codec = event.quality_level.video_codec
            encoder = video_coding.encoder
            allowed = _CODEC_ENCODERS.get(codec)
            if allowed is None:
                raise ConfigError(
                    f"Unknown video codec {codec!r} in HRC {hrc_id}"
                )
            if encoder not in allowed and encoder.casefold() not in ONLINE_CODERS:
                raise ConfigError(
                    f"In HRC {hrc_id}, quality level {event.quality_level} and "
                    f"video coding {video_coding} specify different codecs"
                )

        # segment duration resolution (reference :271-285)
        if segment_duration == "src_duration":
            self.segment_duration: Any = "src_duration"
        elif segment_duration is not None:
            self.segment_duration = int(segment_duration)
        else:
            first = event_list[0]
            if first.event_type in ("stall", "freeze"):
                raise ConfigError(
                    f"HRC {hrc_id}: cannot take segment duration from first event "
                    "because it is a stalling/freezing event; specify a default "
                    "segmentDuration for the test"
                )
            self.segment_duration = first.duration

        self.pvses: set[Pvs] = set()
        self.quality_levels: set[QualityLevel] = set()
        self.segments: set[Segment] = set()

        self.buffer_events: list = (
            self.get_buff_events_media_time() if self.has_buffering() else []
        )

    def has_buffering(self) -> bool:
        return any(e.event_type in ("stall", "freeze") for e in self.event_list)

    has_stalling = has_buffering

    def has_framefreeze(self) -> bool:
        return any(e.event_type == "freeze" for e in self.event_list)

    def get_buff_events_media_time(self) -> list:
        """Buff events for .buff files in media time (reference :312-333):
        freezes → sorted list of durations; stalls → [media_time, duration]
        pairs where media time advances only through non-stall events."""
        if self.has_framefreeze():
            return sorted(
                e.duration for e in self.event_list if e.event_type == "freeze"
            )
        events = []
        if self.has_buffering():
            media_t: float = 0
            for e in self.event_list:
                if e.event_type == "stall":
                    events.append([media_t, e.duration])
                else:
                    media_t += e.duration
        return events

    def get_buff_events_wallclock_time(self) -> list:
        """Stall events as [wallclock_time, duration]: wallclock advances
        through every event including the stalls (reference :338-350)."""
        events = []
        if self.has_buffering():
            wall_t: float = 0
            for e in self.event_list:
                if e.event_type == "stall":
                    events.append([wall_t, e.duration])
                wall_t += e.duration
        return events

    def get_long_hrc_duration(self) -> float:
        return sum(float(e.duration) for e in self.event_list)

    def get_max_res(self) -> tuple[int, int]:
        widths = [0] + [
            e.quality_level.width
            for e in self.event_list
            if e.event_type not in ("stall", "freeze")
        ]
        heights = [0] + [
            e.quality_level.height
            for e in self.event_list
            if e.event_type not in ("stall", "freeze")
        ]
        return max(widths), max(heights)

    def __repr__(self) -> str:
        return f"<{self.hrc_id}>"


class Segment:
    """One encodeable unit: SRC × quality level × time range (reference :375-599).

    Filename grammar (the cache key of the whole chain, reference :482-512):
    <db>_<src>_<ql>_<coding>_<seq04>_<start>-<end>.<ext>
    """

    def __init__(
        self,
        index: int,
        src: Src,
        quality_level: QualityLevel,
        video_coding: Any,
        audio_coding: Any,
        start_time: float,
        duration: float,
    ) -> None:
        self.index = index
        self.src = src
        self.test_config = src.test_config
        self.quality_level = quality_level
        self.video_coding = video_coding
        self.audio_coding = audio_coding
        self.start_time = start_time
        self.duration = duration
        self.end_time = start_time + duration

        self.video_frame_info = None
        self.audio_frame_info = None
        self.segment_info = None

        self.target_pix_fmt: Optional[str] = None
        self.target_video_bitrate = None
        self._set_pix_fmt()
        if self.quality_level.video_bitrate:
            self._set_target_video_bitrate()

        self.filename = self.get_filename()
        self.file_path = os.path.join(
            self.test_config.get_video_segments_path(), self.filename
        )
        self.tmp_path = os.path.join(
            self.test_config.get_avpvs_path(), "tmp_" + self.filename + ".avi"
        )

    def _set_pix_fmt(self) -> None:
        """Harmonize the SRC pixel format to the encode target
        (reference :447-480): 444/422/rgb → yuv422p, 420 → yuv420p,
        '10le' suffix for 10-bit SRCs, forced overrides last."""
        if self.src.is_youtube:
            self.target_pix_fmt = "yuv420p"
            return
        if self.src.probe_error is not None:
            # unprobeable SRC (deferred poison, Src.stream_info): a
            # deterministic stand-in — this segment never encodes; its
            # units fail classified the moment a stage touches the
            # bytes, and the plan needs SOME total pixel format so the
            # serve front door can enqueue them (docs/ROBUSTNESS.md)
            self.target_pix_fmt = "yuv420p"
            return
        src_pix_fmt = self.src.stream_info["pix_fmt"]
        if "444" in src_pix_fmt or "422" in src_pix_fmt or "rgb" in src_pix_fmt:
            self.target_pix_fmt = "yuv422p"
        elif "420" in src_pix_fmt:
            self.target_pix_fmt = "yuv420p"
        else:
            raise ConfigError(f"Unknown SRC pixel format: {src_pix_fmt!r}")
        if self.src.uses_10_bit():
            self.target_pix_fmt += "10le"
        if (
            self.quality_level.video_codec == "h264"
            and self.video_coding.encoder.casefold() == "bitmovin"
        ):
            self.target_pix_fmt = "yuv420p"
        if self.video_coding.forced_pix_fmt:
            self.target_pix_fmt = self.video_coding.forced_pix_fmt

    def _set_target_video_bitrate(self) -> None:
        """Complexity-ladder bitrate choice (reference :426-445): with the
        complexity CSV present, a 'low/high' videoBitrate pair selects by the
        SRC's complexity class (class > 1 → high)."""
        if self.test_config.is_complex():
            rungs = sorted(
                float(b) for b in str(self.quality_level.video_bitrate).split("/")
            )
            if len(rungs) > 1:
                level = self.test_config.complexity_dict[self.src.get_src_file_name()]
                self.target_video_bitrate = rungs[1] if level > 1 else rungs[0]
            else:
                self.target_video_bitrate = rungs[0]
        else:
            self.target_video_bitrate = self.quality_level.video_bitrate

    def uses_10_bit(self) -> Optional[bool]:
        if not self.target_pix_fmt:
            return None
        return "10" in self.target_pix_fmt and self.target_pix_fmt != "yuv410p"

    def get_filename(self) -> str:
        codec = self.quality_level.video_codec
        encoder = self.video_coding.encoder
        if codec in ("h264", "h265"):
            self.ext = "mp4"
        elif encoder == "youtube" and codec == "vp9":
            self.ext = "webm"
        elif encoder.casefold() == "bitmovin" and codec == "vp9":
            self.ext = "mkv"
        elif codec in ("vp9", "av1"):
            self.ext = "mp4"
        else:
            raise ConfigError(
                f"Wrong video codec for quality level {self.quality_level}"
            )
        return (
            "_".join(
                [
                    self.test_config.database_id,
                    self.src.src_id,
                    self.quality_level.ql_id,
                    self.video_coding.coding_id,
                    format(self.index, "04"),
                    f"{int(self.start_time)}-{int(self.end_time)}",
                ]
            )
            + "."
            + self.ext
        )

    def get_segment_file_path(self) -> str:
        return self.file_path

    def get_tmp_path(self) -> str:
        return self.tmp_path

    def get_logfile_name(self) -> str:
        return os.path.splitext(self.filename)[0] + ".log"

    def get_logfile_path(self) -> str:
        return os.path.join(self.test_config.get_logs_path(), self.get_logfile_name())

    def get_hash(self) -> str:
        return _sha1(self.file_path)

    def get_logfile_hash(self) -> str:
        return _sha1(self.get_logfile_path())

    def get_segment_duration(self) -> float:
        return self.duration

    def exists(self) -> bool:
        return os.path.isfile(self.file_path)

    def get_video_frame_info(self):
        if self.video_frame_info is None:
            from ..io import probe

            self.video_frame_info = probe.get_video_frame_info(self.file_path)
        return self.video_frame_info

    def get_audio_frame_info(self):
        if self.audio_frame_info is None:
            from ..io import probe

            self.audio_frame_info = probe.get_audio_frame_info(self.file_path)
        return self.audio_frame_info

    def get_segment_info(self):
        if self.segment_info is None:
            from ..io import probe

            self.segment_info = probe.get_segment_info(
                self.file_path, target_video_bitrate=self.target_video_bitrate
            )
        return self.segment_info

    def _key(self) -> tuple:
        # `index` is part of identity: it is in the FILENAME, so two
        # content-equal segments with different indexes are distinct
        # artifacts (each referenced by its own HRC's plan). The
        # reference dedups by full command string — filename included —
        # so its cross-HRC dedup also merges only equal-index segments.
        # (Found by a randomized planner sweep: two HRCs with different
        # segmentDuration histories both truncating against SRC end
        # produce the same (src, ql, coding, start, duration) at
        # DIFFERENT indexes; deduping them left one HRC's segment file
        # never encoded.)
        return (
            self.src,
            self.quality_level,
            self.video_coding,
            self.audio_coding,
            self.index,
            self.start_time,
            self.duration,
        )

    def __hash__(self) -> int:
        return hash(self._key())

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Segment) and self._key() == other._key()

    def __lt__(self, other: "Segment") -> bool:
        return (
            self.src.src_id,
            self.start_time,
            self.quality_level.ql_id,
            self.duration,
        ) < (other.src.src_id, other.start_time, other.quality_level.ql_id, other.duration)

    def __repr__(self) -> str:
        return (
            f"<Segment {self.index:04d} of {self.src.src_id}, "
            f"{self.start_time}-{self.end_time}, {self.quality_level.ql_id}>"
        )


def _sha1(path: str) -> str:
    import hashlib

    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class Pvs:
    """A processed video sequence: SRC × HRC (reference :52-227)."""

    def __init__(
        self, pvs_id: str, test_config: "TestConfig", src: Src, hrc: Hrc
    ) -> None:
        self.pvs_id = pvs_id
        self.test_config = test_config
        self.src = src
        self.hrc = hrc
        self.segments: list[Segment] = []

        # the upscale gate needs probed geometry; an unprobeable SRC
        # (deferred poison, see Src.stream_info) skips it — the units
        # fail classified when a stage touches the bytes instead of
        # failing the whole parse here
        if not src.is_youtube and src.probe_error is None:
            max_width, _ = hrc.get_max_res()
            src_width = src.stream_info["width"]
            if src_width < max_width:
                raise ConfigError(
                    f"PVS {pvs_id} uses {hrc.hrc_id}, which specifies a quality "
                    f"level with maximum width {max_width}, but {src} is only "
                    f"{src_width} wide and would have to be upscaled."
                )

    def is_online(self) -> bool:
        return any(s.video_coding.is_online for s in self.segments)

    def has_buffering(self) -> bool:
        return self.hrc.has_buffering()

    has_stalling = has_buffering

    def has_framefreeze(self) -> bool:
        return self.hrc.has_framefreeze()

    def get_buff_events_media_time(self):
        return self.hrc.get_buff_events_media_time()

    def get_buff_events_wallclock_time(self):
        return self.hrc.get_buff_events_wallclock_time()

    # --- artifact paths (reference :77-146) ---

    def get_avpvs_wo_buffer_file_path(self) -> str:
        return os.path.join(
            self.test_config.get_avpvs_path(), self.pvs_id + "_concat_wo_buffer.avi"
        )

    def get_tmp_wo_audio_path(self) -> str:
        return os.path.join(
            self.test_config.get_avpvs_path(), self.pvs_id + "_concat_wo_audio.avi"
        )

    def get_avpvs_file_path(self) -> str:
        return os.path.join(self.test_config.get_avpvs_path(), self.pvs_id + ".avi")

    def get_avpvs_file_list(self) -> str:
        return os.path.join(
            self.test_config.get_avpvs_path(), self.pvs_id + "_tmp_filelist.txt"
        )

    def get_cpvs_file_path(self, context: str = "pc", rawvideo: bool = False) -> str:
        if context == "pc":
            ext = ".mkv" if rawvideo else ".avi"
        else:
            ext = ".mp4"
        cpvs_name = self.pvs_id + "_" + context[0:2].upper() + ext
        if not re.match(ids.REGEX_CPVS_ID, cpvs_name):
            raise ConfigError(f"CPVS ID {cpvs_name} does not match regex")
        return os.path.join(self.test_config.get_cpvs_path(), cpvs_name)

    def get_preview_file_path(self) -> str:
        return os.path.join(
            self.test_config.get_cpvs_path(), self.pvs_id + "_preview.mov"
        )

    def get_logfile_name(self) -> str:
        return self.pvs_id + ".log"

    def get_logfile_path(self) -> str:
        return os.path.join(self.test_config.get_logs_path(), self.get_logfile_name())

    # --- pixel-format plumbing (reference :172-227) ---

    def get_pix_fmt_for_avpvs(self) -> str:
        fmts = {seg.target_pix_fmt for seg in self.segments}
        if len(fmts) > 1:
            raise ConfigError(
                f"Segments for PVS {self} use different target pixel formats"
            )
        return next(iter(fmts))

    _CPVS_FORMAT_MAP = {
        "yuv420p": ("rawvideo", "uyvy422"),
        "yuv422p": ("rawvideo", "uyvy422"),
        "yuv420p10le": ("v210", "yuv422p10le"),
        "yuv422p10le": ("v210", "yuv422p10le"),
    }

    def get_vcodec_and_pix_fmt_for_cpvs(self, rawvideo: bool = False) -> tuple[str, str]:
        avpvs_format = self.get_pix_fmt_for_avpvs()
        if rawvideo:
            return ("rawvideo", avpvs_format)
        if avpvs_format not in self._CPVS_FORMAT_MAP:
            raise ConfigError(
                f"Cannot use input pixel format {avpvs_format!r} for CPVS {self}"
            )
        return self._CPVS_FORMAT_MAP[avpvs_format]

    def __repr__(self) -> str:
        return f"<PVS {self.pvs_id}>"


class PostProcessing:
    """A viewing-context render target for CPVS (reference :947-979)."""

    TYPES = ("pc", "tablet", "mobile", "hd-pc-home", "uhd-pc-home")

    def __init__(self, test_config: "TestConfig", data: dict) -> None:
        self.test_config = test_config
        self.processing_type = data["type"]
        if self.processing_type not in self.TYPES:
            raise ConfigError(
                f"Wrong post processing type {self.processing_type!r}, must be "
                f"one of {self.TYPES}"
            )
        try:
            self.display_width = int(data["displayWidth"])
            self.display_height = int(data["displayHeight"])
            self.coding_width = int(data["codingWidth"])
            self.coding_height = int(data["codingHeight"])
        except (KeyError, TypeError, ValueError) as exc:
            raise ConfigError(f"Missing or wrong data in post processing: {exc}") from exc

        if self.display_width != self.coding_width:
            raise ConfigError("Post processing must have same coding and display width")
        if self.processing_type == "pc" and (
            self.display_height != self.coding_height
            or self.display_width != self.coding_width
        ):
            raise ConfigError(
                "PC post processing must have same coding and display width/height"
            )
        self.display_frame_rate = data.get("displayFrameRate", 60)

    def __repr__(self) -> str:
        return f"<PostProcessing {self.processing_type.upper()}>"
