"""Config-layer errors.

The reference (lib/test_config.py) calls sys.exit(1) at ~50 validation sites;
here every invariant violation raises ConfigError so the domain model is
usable as a library. The CLI layer converts ConfigError to exit code 1.
"""


class ConfigError(ValueError):
    """A database YAML (or its environment) violates a chain invariant."""
