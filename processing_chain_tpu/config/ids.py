"""ID grammar of the P.NATS Phase 2 database contract.

Parity target: reference lib/test_config.py:1012-1018. These regexes are the
public naming contract with existing databases and must not drift.
"""

from __future__ import annotations

import re

from .errors import ConfigError

REGEX_DATABASE_ID = r"P2(S|L)(TR|PT|IT|VL|XM)[\d]{2,3}"
REGEX_QL_ID = r"Q[\d]+"
REGEX_CODING_ID = r"(A|V)C[\d]+"
REGEX_SRC_ID = r"SRC[\d]{3,5}"
REGEX_HRC_ID = r"HRC[\d]{3,4}"
REGEX_PVS_ID = r"P2(S|L)(TR|PT|IT|VL|XM)[\d]{2,3}_SRC[\d]{3,5}_HRC[\d]{3,4}"
REGEX_CPVS_ID = (
    r"P2(S|L)(TR|PT|IT|VL|XM)[\d]{2,3}_SRC[\d]{3,5}_HRC[\d]{3,4}_(PC|MO|TA|HD|UH)"
)


def validate(kind: str, value: str, pattern: str) -> str:
    """Check `value` against the ID `pattern` (anchored at the start, like the
    reference's re.match) and return it; raise ConfigError otherwise."""
    if not re.match(pattern, value):
        raise ConfigError(f"{kind} ID {value!r} does not match syntax {pattern}")
    return value


def src_id_of_pvs(pvs_id: str) -> str:
    """Extract the SRC id embedded in a PVS id (reference :1420)."""
    m = re.findall(r"SRC\d+", pvs_id)
    if not m:
        raise ConfigError(f"PVS ID {pvs_id!r} contains no SRC id")
    return m[0]


def hrc_id_of_pvs(pvs_id: str) -> str:
    """Extract the HRC id embedded in a PVS id (reference :1421)."""
    m = re.findall(r"HRC\d+", pvs_id)
    if not m:
        raise ConfigError(f"PVS ID {pvs_id!r} contains no HRC id")
    return m[0]
