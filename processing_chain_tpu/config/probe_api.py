"""Prober interface between the config layer and the media I/O layer.

The reference probes SRCs with ffprobe subprocesses during YAML parsing
(test_config.py:1444-1445 → ffmpeg.get_src_info :566-633, with .yaml sidecar
caching). Here probing is an injected interface so the domain model is
testable without media files, and the real implementation (io/probe.py) uses
the native libav boundary instead of a subprocess.
"""

from __future__ import annotations

from typing import Optional, Protocol


class SrcProber(Protocol):
    def src_info(self, file_path: str, sidecar_path: Optional[str] = None) -> dict:
        """Stream info for a SRC: at least width, height, pix_fmt,
        r_frame_rate, video_duration. Cached in a .yaml sidecar when
        sidecar_path is given (reference ffmpeg.py:604-632)."""
        ...

    def duration(self, file_path: str, sidecar_path: Optional[str] = None) -> float:
        """Video duration in seconds (reference ffmpeg.py get_segment_info
        'video_duration')."""
        ...


class StaticProber:
    """In-memory prober for tests and dry runs: {path or basename: info dict}.

    Each info dict needs width/height/pix_fmt/r_frame_rate/video_duration.
    """

    def __init__(self, table: dict[str, dict], default: Optional[dict] = None) -> None:
        self.table = table
        self.default = default

    def _lookup(self, file_path: str) -> dict:
        import os

        info = self.table.get(file_path) or self.table.get(os.path.basename(file_path))
        if info is None:
            if self.default is not None:
                return self.default
            raise KeyError(f"StaticProber has no info for {file_path}")
        return info

    def src_info(self, file_path: str, sidecar_path: Optional[str] = None) -> dict:
        return self._lookup(file_path)

    def duration(self, file_path: str, sidecar_path: Optional[str] = None) -> float:
        return float(self._lookup(file_path)["video_duration"])


def default_prober() -> SrcProber:
    """The real prober backed by the native libav boundary."""
    from ..io import probe

    return probe.LibavProber()
