"""TestConfig: load a database YAML into the domain object graph.

Parity target: reference lib/test_config.py:982-1573 (TestConfig). The YAML
dialect is the public contract with existing databases: databaseId,
syntaxVersion (>= 6), type short|long, segmentDuration, qualityLevelList,
codingList, srcList, hrcList, pvsList, postProcessingList — plus the
database folder layout and the processingchain_defaults.yaml override file.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional

import yaml

from ..utils.log import get_logger
from . import ids
from .domain import (
    ONLINE_CODERS,
    Coding,
    Event,
    Hrc,
    PostProcessing,
    Pvs,
    QualityLevel,
    Segment,
    Src,
    YoutubeCoding,
)
from .errors import ConfigError
from .probe_api import SrcProber, default_prober

REQUIRED_YAML_SYNTAX_VERSION = 6

#: database subfolders, the filesystem contract (reference :1095-1107)
_LAYOUT = (
    "avpvs",
    "cpvs",
    "videoSegments",
    "buffEventFiles",
    "qualityChangeEventFiles",
    "audioFrameInformation",
    "videoFrameInformation",
    "sideInformation",
    "logs",
)


class TestConfig:
    """A parsed test database: quality_levels / codings / srcs / hrcs /
    pvses dicts, post_processings list, and the derived `segments` set."""

    __test__ = False  # not a pytest class despite the name

    REGEX_DATABASE_ID = ids.REGEX_DATABASE_ID
    REGEX_QL_ID = ids.REGEX_QL_ID
    REGEX_CODING_ID = ids.REGEX_CODING_ID
    REGEX_SRC_ID = ids.REGEX_SRC_ID
    REGEX_HRC_ID = ids.REGEX_HRC_ID
    REGEX_PVS_ID = ids.REGEX_PVS_ID
    REGEX_CPVS_ID = ids.REGEX_CPVS_ID
    ONLINE_CODERS = ONLINE_CODERS

    def __init__(
        self,
        yaml_filename: str,
        filter_srcs: Optional[str] = None,
        filter_hrcs: Optional[str] = None,
        filter_pvses: Optional[str] = None,
        prober: Optional[SrcProber] = None,
        defaults_file: Optional[str] = None,
        complexity_csv_dir: Optional[str] = None,
    ) -> None:
        # abspath first: a bare relative filename run from inside the
        # database folder (`-c DB.yaml`) would otherwise see an empty
        # dirname and fail the folder-name gate (the reference has the
        # same flaw at :1080-1083; fixed here, outputs unaffected)
        yaml_filename = os.path.abspath(yaml_filename)
        self.yaml_file = yaml_filename
        self.filter_srcs = filter_srcs.split("|") if filter_srcs else []
        self.filter_hrcs = filter_hrcs.split("|") if filter_hrcs else []
        self.filter_pvses = filter_pvses.split("|") if filter_pvses else []
        self.prober = prober if prober is not None else default_prober()
        self.database_dir = os.path.dirname(yaml_filename)
        self.complex_bitrates = False
        # complexity CSVs live in util/complexityAnalysis at the repo root
        # (reference :1086, :1251-1253); overridable for tests
        self._complexity_dir = complexity_csv_dir or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            "util",
            "complexityAnalysis",
        )
        self._defaults_file = defaults_file

        self._check_names()
        with open(self.yaml_file) as f_in:
            self.data = yaml.safe_load(f_in)
        self._load_paths()
        self._parse_data_from_yaml()
        if self.complex_bitrates:
            self._parse_complexity()
        self._create_required_segments()

    # ------------------------------------------------------------------ names

    def _check_names(self) -> None:
        """Filename/ID gate (reference :1063-1087)."""
        if not os.path.exists(self.yaml_file):
            raise ConfigError(f"YAML file {self.yaml_file} does not exist")
        self.yaml_basename = os.path.splitext(os.path.basename(self.yaml_file))[0]
        ids.validate("Database", self.yaml_basename, ids.REGEX_DATABASE_ID)
        self.db_dirname = os.path.basename(os.path.dirname(self.yaml_file))
        if (
            "P2STR00" not in self.yaml_basename
            and "P2LTR00" not in self.yaml_basename
            and self.yaml_basename != self.db_dirname
        ):
            raise ConfigError(
                "Database folder must have the same name as the YAML config "
                f"file; rename your database folder to {self.yaml_basename!r}"
            )
        if os.path.isfile(
            os.path.join(self._complexity_dir, "complexity_classification.csv")
        ):
            self.complex_bitrates = True

    # ------------------------------------------------------------------ paths

    def _load_paths(self) -> None:
        """Database folder layout + overrides (reference :1089-1160)."""
        log = get_logger()
        d = self.database_dir
        self.path_mapping: dict[str, Any] = {
            "srcVid": os.path.abspath(os.path.join(d, "../srcVid")),
            "srcVidLocal": os.path.join(d, "srcVid"),
            **{key: os.path.join(d, key) for key in _LAYOUT},
        }
        if ".." in self.path_mapping["avpvs"]:
            self.path_mapping["avpvs"] = str(
                (Path.cwd() / self.path_mapping["avpvs"]).resolve()
            )

        if not os.path.isdir(self.path_mapping["srcVid"]):
            log.warning(
                "Joint 'srcVid' folder %s does not exist; falling back to the "
                "'srcVid' folder inside %s",
                self.path_mapping["srcVid"],
                d,
            )
            self.path_mapping["srcVid"] = os.path.join(d, "srcVid")

        override_file = self._defaults_file
        if override_file is None:
            override_file = os.path.join(
                os.path.dirname(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
                ),
                "processingchain_defaults.yaml",
            )
        if os.path.isfile(override_file):
            with open(override_file) as f:
                overrides = yaml.safe_load(f)
            for key, path in (overrides or {}).items():
                if key not in self.path_mapping:
                    log.warning("%s is not a valid path identifier, ignoring", key)
                    continue
                paths = path if isinstance(path, list) else [path]
                for p in paths:
                    if not os.path.isdir(p):
                        raise ConfigError(
                            f"path {p}, as specified in {override_file}, does not exist"
                        )
                    if key != "srcVid" and not os.access(p, os.W_OK):
                        raise ConfigError(
                            f"path {p}, as specified in {override_file}, "
                            "is not writable"
                        )
                self.path_mapping[key] = path

        for key, path in self.path_mapping.items():
            if key != "srcVid" and not isinstance(path, list) and not os.path.isdir(path):
                log.debug("path %s does not exist; creating empty folder", path)
                os.makedirs(path, exist_ok=True)

    # ------------------------------------------------------------------ parse

    def _parse_data_from_yaml(self) -> None:
        """Build the object graph (reference :1259-1457)."""
        log = get_logger()
        self.database_id = self.data["databaseId"]

        if "syntaxVersion" in self.data:
            if self.data["syntaxVersion"] < REQUIRED_YAML_SYNTAX_VERSION:
                raise ConfigError(
                    "YAML syntaxVersion is outdated; required: "
                    f"{REQUIRED_YAML_SYNTAX_VERSION}"
                )
        else:
            log.warning("YAML file does not specify 'syntaxVersion'")

        ids.validate("Database", self.database_id, ids.REGEX_DATABASE_ID)
        if self.yaml_basename != self.database_id:
            raise ConfigError("Database ID and YAML filename do not match")

        self.type = self.data["type"]
        if self.type not in ("short", "long"):
            raise ConfigError("Database type must be 'short' or 'long'")

        if "segmentDuration" in self.data:
            self.default_segment_duration = self.data["segmentDuration"]
        elif self.type == "long":
            raise ConfigError(
                "A default segment duration must be defined for long tests "
                "using the 'segmentDuration' key (overridable per HRC)"
            )
        else:
            self.default_segment_duration = None

        self.quality_levels: dict[str, QualityLevel] = {}
        self.codings: dict[str, Any] = {}
        self.srcs: dict[str, Src] = {}
        self.hrcs: dict[str, Hrc] = {}
        self.pvses: dict[str, Pvs] = {}
        self.post_processings: list[PostProcessing] = []

        for ql_id, qdata in self.data["qualityLevelList"].items():
            ids.validate("Quality Level", ql_id, ids.REGEX_QL_ID)
            self.quality_levels[ql_id] = QualityLevel(ql_id, self, qdata)

        for coding_id, cdata in self.data["codingList"].items():
            ids.validate("Coding", coding_id, ids.REGEX_CODING_ID)
            self.codings[coding_id] = Coding(coding_id, self, cdata)
        if self.data["codingList"]:
            self.codings["youtube"] = YoutubeCoding("youtube", self)

        for src_id, sdata in self.data["srcList"].items():
            ids.validate("SRC", src_id, ids.REGEX_SRC_ID)
            if self.filter_srcs and src_id not in self.filter_srcs:
                log.info("skipping SRC %s", src_id)
                continue
            self.srcs[src_id] = Src(src_id, self, sdata)

        for hrc_id, hdata in self.data["hrcList"].items():
            ids.validate("HRC", hrc_id, ids.REGEX_HRC_ID)
            if self.filter_hrcs and hrc_id not in self.filter_hrcs:
                log.info("skipping HRC %s", hrc_id)
                continue
            self.hrcs[hrc_id] = self._parse_hrc(hrc_id, hdata)

        for pvs_id in self.data["pvsList"]:
            ids.validate("PVS", pvs_id, ids.REGEX_PVS_ID)
            if self.filter_pvses and pvs_id not in self.filter_pvses:
                log.info("skipping PVS %s", pvs_id)
                continue
            src_id = ids.src_id_of_pvs(pvs_id)
            hrc_id = ids.hrc_id_of_pvs(pvs_id)
            if (self.filter_srcs and src_id not in self.filter_srcs) or (
                self.filter_hrcs and hrc_id not in self.filter_hrcs
            ):
                log.info("skipping PVS %s (skipped SRC/HRC)", pvs_id)
                continue
            if src_id not in self.srcs:
                raise ConfigError(
                    f"PVS {pvs_id} specifies SRC {src_id} but it is not in srcList"
                )
            if hrc_id not in self.hrcs:
                raise ConfigError(
                    f"PVS {pvs_id} specifies HRC {hrc_id} but it is not in hrcList"
                )
            src, hrc = self.srcs[src_id], self.hrcs[hrc_id]
            src.locate_and_get_info()
            pvs = Pvs(pvs_id, self, src, hrc)
            self.pvses[pvs_id] = pvs
            src.pvses.add(pvs)
            hrc.pvses.add(pvs)

        for pdata in self.data["postProcessingList"]:
            self.post_processings.append(PostProcessing(self, pdata))
        if len(self.post_processings) > 1:
            log.warning("More than one post processing is not really supported!")

    def _parse_hrc(self, hrc_id: str, data: dict) -> Hrc:
        """One hrcList entry → Hrc (reference :1333-1408)."""
        def _coding(field: str):
            try:
                coding_id = data[field]
            except KeyError as exc:
                raise ConfigError(
                    f"HRC {hrc_id} is missing {field}"
                ) from exc
            try:
                return self.codings[coding_id]
            except KeyError as exc:
                # clean error where the reference crashes with a raw KeyError
                raise ConfigError(
                    f"HRC {hrc_id} references unknown coding {coding_id!r}"
                ) from exc

        video_coding = _coding("videoCodingId")
        audio_coding = _coding("audioCodingId") if self.type == "long" else None

        if "segmentDuration" in data:
            if "src_duration" in [e[1] for e in data["eventList"]]:
                raise ConfigError(
                    f"Cannot specify both segmentDuration and src_duration as "
                    f"event length in HRC {hrc_id}"
                )
            hrc_segment_duration = data["segmentDuration"]
        else:
            hrc_segment_duration = self.default_segment_duration

        event_list: list[Event] = []
        quality_level_list: list[Any] = []
        for event_data in data["eventList"]:
            if len(event_data) != 2:
                raise ConfigError(
                    f"Event data must consist of two elements: {event_data}"
                )
            if "youtube" in data["videoCodingId"]:
                hrc_type = "youtube"
                event_type = "youtube"
                quality_level: Any = event_data[0]  # YouTube itag
            else:
                hrc_type = "normal"
                name = str(event_data[0])
                if "Q" in name:
                    event_type = "quality_level"
                    try:
                        quality_level = self.quality_levels[name]
                    except KeyError as exc:
                        raise ConfigError(
                            f"HRC {hrc_id} event references unknown "
                            f"quality level {name!r}"
                        ) from exc
                elif "stall" in name:
                    event_type, quality_level = "stall", None
                elif "freeze" in name:
                    event_type, quality_level = "freeze", None
                else:
                    raise ConfigError(
                        f"Wrong event type {event_data[0]!r}: must be a quality "
                        "level ID, 'stall', or 'freeze'"
                    )
            event_duration = event_data[1]
            if event_duration == "src_duration":
                hrc_segment_duration = "src_duration"
            event_list.append(Event(event_type, quality_level, event_duration))
            quality_level_list.append(quality_level)

        if hrc_segment_duration == "src_duration" and any(
            e.event_type == "quality_level" and e.duration != "src_duration"
            for e in event_list
        ):
            raise ConfigError(
                f"HRC {hrc_id} mixes numeric event durations with "
                "src_duration segmenting; use src_duration for all events or "
                "set an explicit segmentDuration"
            )
        hrc = Hrc(
            hrc_id, self, hrc_type, video_coding, audio_coding, event_list,
            hrc_segment_duration,
        )
        for e in event_list:
            e.hrc = hrc
        for q in set(quality_level_list):
            hrc.quality_levels.add(q)
        for q in {q for q in quality_level_list if isinstance(q, QualityLevel)}:
            q.hrcs.add(hrc)
        return hrc

    # ------------------------------------------------------------- complexity

    def _parse_complexity(self) -> None:
        """Load the complexity CSVs into {src filename: class} (reference
        :1250-1257)."""
        import csv

        complexity: dict[str, int] = {}
        for name in (
            "complexity_classification.csv",
            "complexity_classification_validation.csv",
        ):
            path = os.path.join(self._complexity_dir, name)
            if not os.path.isfile(path):
                continue
            with open(path, newline="") as f:
                for row in csv.DictReader(f):
                    complexity[row["file"]] = int(row["complexity_class"])
        self.complexity_dict = complexity

    # ---------------------------------------------------------------- planner

    def _create_required_segments(self) -> None:
        """The segment planner (reference :1162-1248): expand each PVS's event
        list into the deduplicated set of segments to encode, with
        divisibility checks, last-segment truncation against SRC length, and
        the short-database single-segment rule."""
        log = get_logger()
        self.segments: set[Segment] = set()

        for pvs in self.pvses.values():
            src_length: Optional[float] = None
            if not pvs.src.is_youtube:
                # an unprobeable SRC (deferred poison, config/domain.py
                # Src.stream_info) skips the advisory duration check —
                # its units fail classified at execution instead of
                # failing the whole parse here
                if pvs.hrc.event_list[0].duration != "src_duration" \
                        and pvs.src.probe_error is None:
                    src_length = float(pvs.src.get_duration())
                    total = sum(
                        e.duration
                        for e in pvs.hrc.event_list
                        if e.event_type == "quality_level"
                    )
                    if src_length < total:
                        log.warning(
                            "%s has a length of only %s, but events in %s sum "
                            "up to %s. Last event(s) will be cut.",
                            pvs.src, src_length, pvs, total,
                        )
                    elif src_length > total:
                        log.warning(
                            "%s is longer than the events specified in %s; "
                            "trimming will occur.",
                            pvs.src, pvs,
                        )
            else:
                log.warning(
                    "Cannot check duration of YouTube videos; make sure events "
                    "in %s sum up to the right duration.",
                    pvs,
                )

            t: float = 0
            seg_index = 0
            for event in pvs.hrc.event_list:
                if event.event_type != "quality_level":
                    continue
                if event.duration == "src_duration":
                    n_segments = 1
                else:
                    if pvs.hrc.segment_duration == "src_duration":
                        raise ConfigError(
                            f"HRC {pvs.hrc.hrc_id} mixes a numeric event "
                            f"duration ({event.duration}) with src_duration "
                            "segmenting; use src_duration for all events or "
                            "set an explicit segmentDuration"
                        )
                    if event.duration % pvs.hrc.segment_duration != 0:
                        raise ConfigError(
                            f"event duration {event.duration} does not match "
                            f"segment duration {pvs.hrc.segment_duration} in "
                            f"{pvs.hrc.hrc_id}"
                        )
                    n_segments = event.duration / pvs.hrc.segment_duration
                if self.type == "short" and n_segments > 1:
                    raise ConfigError(
                        f"Short databases only allow one segment, HRC "
                        f"{pvs.hrc} does not comply."
                    )

                for _ in range(int(n_segments)):
                    if pvs.hrc.segment_duration != "src_duration":
                        seg_duration = pvs.hrc.segment_duration
                        if src_length is not None and t + seg_duration > src_length:
                            seg_duration = src_length - t
                    else:
                        seg_duration = pvs.src.get_duration()
                    if seg_duration <= 0:
                        log.warning(
                            "Got a segment with duration <= 0 in PVS %s, skipping",
                            pvs,
                        )
                        continue
                    segment = Segment(
                        index=seg_index,
                        src=pvs.src,
                        quality_level=event.quality_level,
                        video_coding=pvs.hrc.video_coding,
                        audio_coding=pvs.hrc.audio_coding,
                        start_time=t,
                        duration=seg_duration,
                    )
                    t += seg_duration
                    seg_index += 1
                    pvs.segments.append(segment)
                    pvs.src.segments.add(segment)
                    pvs.hrc.segments.add(segment)
                    self.segments.add(segment)

    # ---------------------------------------------------------------- helpers

    def is_complex(self) -> bool:
        return self.complex_bitrates

    def is_short(self) -> bool:
        return self.type == "short"

    def is_long(self) -> bool:
        return self.type == "long"

    def get_pvs_ids(self):
        return self.pvses.keys()

    def get_required_segments(self) -> set[Segment]:
        return self.segments

    def get_bitrate(self, hrc: str) -> list:
        """Per-chunk bitrates for an HRC id (plotter helper, reference
        :1471-1482); with complexity ladders, the low rung."""
        q_levels = [e[0] for e in self.data["hrcList"][hrc]["eventList"]]
        if self.complex_bitrates:
            return [
                str(self.data["qualityLevelList"][q]["videoBitrate"]).split("/")[0]
                for q in q_levels
            ]
        return [self.data["qualityLevelList"][q]["videoBitrate"] for q in q_levels]

    def get_height(self, hrc: str) -> list:
        q_levels = [e[0] for e in self.data["hrcList"][hrc]["eventList"]]
        return [self.data["qualityLevelList"][q]["height"] for q in q_levels]

    # path accessors (reference :1502-1573)
    def get_src_vid_path(self):
        return self.path_mapping["srcVid"]

    def get_src_vid_local_path(self) -> str:
        return self.path_mapping["srcVidLocal"]

    def get_avpvs_path(self) -> str:
        return self.path_mapping["avpvs"]

    def get_cpvs_path(self) -> str:
        return self.path_mapping["cpvs"]

    def get_video_segments_path(self) -> str:
        return self.path_mapping["videoSegments"]

    def get_buff_event_files_path(self) -> str:
        return self.path_mapping["buffEventFiles"]

    def get_quality_change_event_files_path(self) -> str:
        return self.path_mapping["qualityChangeEventFiles"]

    def get_audio_frame_information_path(self) -> str:
        return self.path_mapping["audioFrameInformation"]

    def get_video_frame_information_path(self) -> str:
        return self.path_mapping["videoFrameInformation"]

    def get_side_information_path(self) -> str:
        return self.path_mapping["sideInformation"]

    def get_logs_path(self) -> str:
        return self.path_mapping["logs"]

    def __repr__(self) -> str:
        return repr(self.data)
