from .jobs import Job, JobRunner

__all__ = ["Job", "JobRunner"]
