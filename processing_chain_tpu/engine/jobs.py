"""Job model: memoized, provenance-logged units of pipeline work.

Replaces the reference's command-string + exists-check idiom (every
operator returns None when its output exists and --force is unset,
reference lib/ffmpeg.py:786-788, :964-970, :1022-1028, :1067-1073,
:1126-1132, :1271-1277) with a typed Job: the filesystem stays the
checkpoint/resume system (SURVEY.md §5), deterministic output paths are
the cache keys, and each job can write a provenance log capturing what
produced the artifact (reference p01:89-92, p03:41-59).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .. import telemetry as tm
from ..telemetry.heartbeat import HEARTBEATS
from ..store import runtime as store_runtime
from ..store.store import (
    STORE_ADOPTIONS,
    STORE_HITS,
    STORE_MISSES,
    StoreCorruption,
)
from ..utils import tracing
from ..utils.log import get_logger
from ..utils.runner import ChainError, ParallelRunner
from ..utils.version import get_processing_chain_version

# Job accounting (docs/TELEMETRY.md): every planning decision and run
# outcome is counted per runner — except redos, decided inside
# Job.should_run where no runner context exists, counted chain-wide —
# and mirrored into the event log, so a report can answer "which PVSes
# were skipped vs. rebuilt and why".
_JOBS_PLANNED = tm.counter(
    "chain_jobs_planned_total", "jobs accepted for execution", ("runner",)
)
_JOBS_SKIPPED = tm.counter(
    "chain_jobs_skipped_total", "jobs skipped (output exists)", ("runner",)
)
_JOBS_DEDUPED = tm.counter(
    "chain_jobs_deduped_total", "identical plans submitted twice", ("runner",)
)
_JOBS_FAILED = tm.counter(
    "chain_jobs_failed_total", "jobs whose fn raised", ("runner",)
)
_JOBS_REDONE = tm.counter(
    "chain_jobs_redone_total",
    "jobs re-run over an existing output (crash sentinel)",
)
_JOB_SECONDS = tm.histogram(
    "chain_job_duration_seconds", "wall time of each executed job"
)


def mark_inprogress(output_path: str) -> bool:
    """Best-effort crash sentinel next to an output file: a run killed
    mid-write leaves it behind, and should_run then redoes the artifact
    instead of trusting a possibly-truncated file. Returns whether the
    sentinel was created (a missing parent dir degrades to the
    reference's plain skip-existing behavior)."""
    if not output_path:
        return False
    try:
        # CAS safety: a store-materialized output is HARDLINKED to its
        # object. The encoders open output paths with truncation, which
        # would destroy the shared inode — the store's bytes — while its
        # manifest still vouches for them. Breaking the link first makes
        # every rewrite copy-on-write with respect to the store. This is
        # the one choke point every about-to-write site already passes
        # through (Job.run and the p03 batch lanes).
        if os.path.isfile(output_path) and os.stat(output_path).st_nlink > 1:
            os.unlink(output_path)
        # chainlint: disable=atomic-write (crash sentinel: only its EXISTENCE is the signal — a zero-byte .inprogress is exactly as meaningful as any other)
        with open(output_path + ".inprogress", "w"):
            pass
        return True
    except OSError:
        return False


def clear_inprogress(output_path: str) -> None:
    if not output_path:
        return
    try:
        os.unlink(output_path + ".inprogress")
    except FileNotFoundError:
        pass


@dataclass
class Job:
    """One unit of work producing `output_path`.

    With `plan` set and a store active (store/runtime), stale-vs-fresh is
    plan-hash equality against the store instead of the reference's
    "output exists" bit: one changed HRC parameter invalidates exactly
    the artifacts downstream of it, and a corrupted cached object is
    detected on read and transparently rebuilt. Jobs without a plan (or
    runs without a store) keep the legacy skip-existing semantics.
    """

    label: str
    output_path: str
    fn: Callable[[], Any]
    provenance: dict = field(default_factory=dict)
    logfile_path: Optional[str] = None
    #: plan payload (store/keys schema; file inputs via keys.file_ref)
    plan: Optional[dict] = None
    #: `output_path + suffix` files committed/materialized with the artifact
    sidecar_suffixes: tuple = ()
    #: companion files at their own absolute paths (multi-output jobs,
    #: e.g. p02's vfi/afi/buff next to the qchanges main output)
    extra_outputs: tuple = ()
    #: serve-layer provenance: the request IDs this unit of work answers
    #: (chain-serve attaches overlapping requests to ONE execution, so a
    #: job may satisfy many); folded into provenance, store commits and
    #: job_* events so an artifact can always be traced back to who
    #: asked for it
    request_ids: tuple = ()
    #: distributed-trace ids riding with request_ids (serve requests
    #: mint one per POST; docs/TELEMETRY.md "Fleet observability &
    #: tracing"): folded into the same provenance/event surfaces so a
    #: trace can be stitched from job events alone
    trace_ids: tuple = ()
    #: why should_run returned False
    #: ("output_exists" | "store_hit" | "store_adopted")
    skip_reason: Optional[str] = None

    @property
    def _sentinel_path(self) -> str:
        return self.output_path + ".inprogress"

    def _resolve_plan_hash(self, store) -> Optional[str]:
        """Hash this job's plan against the store; None (with a debug log)
        when an input file is unreadable — e.g. a removed intermediate —
        which degrades that one decision to the legacy exists-check."""
        try:
            return store.plan_hash(self.plan)
        except OSError as exc:
            get_logger().debug(
                "store: cannot resolve plan for %s (%s); using legacy "
                "skip-existing", self.label, exc,
            )
            return None

    def _store_should_run(
        self, store, force: bool, dry_run: bool, runner: str
    ) -> bool:
        """Plan-hash decision: hit → verify + materialize + skip;
        corrupt/miss → run. Only called when the plan hash resolved."""
        if force:
            return True
        manifest = store.lookup(self._plan_hash)
        if manifest is not None:
            if store.serve_hit(manifest, self.output_path,
                               materialize=not dry_run):
                STORE_HITS.labels(runner=runner).inc()
                self.skip_reason = "store_hit"
                if not dry_run:
                    clear_inprogress(self.output_path)
                return False
            return True  # corruption converted to a miss; rebuild
        STORE_MISSES.labels(runner=runner).inc()
        if not os.path.isfile(self.output_path):
            return True
        if os.path.isfile(self._sentinel_path):
            # crashed writer: never adopt a truncated output. Same redo
            # forensics as the legacy path — the sentinel story in the
            # event log must not disappear when --store is on.
            get_logger().warning(
                "output %s exists but its producing run never completed "
                "(crashed?); re-running", self.output_path,
            )
            _JOBS_REDONE.inc()
            tm.emit(
                "job_redo", job=self.label,
                output=os.path.basename(self.output_path),
                reason="crash_sentinel",
            )
            return True
        if not all(os.path.isfile(p) for p in self.extra_outputs):
            return True  # partial multi-output set: rebuild, never adopt
        if store.should_adopt(self.output_path):
            # pre-store artifact on its first store-enabled run: keep the
            # legacy skip-existing trust, but bind it to the current plan
            # hash (with the commit-time integrity probe) so every LATER
            # change is detected by hash inequality. A failed probe means
            # the existing file is corrupt — rebuild it now.
            if dry_run:  # planning must not mutate the store
                self.skip_reason = "store_adopted"
                return False
            try:
                store.commit(
                    self._plan_hash, self.output_path, producer=self.label,
                    provenance=self.provenance,
                    sidecar_suffixes=self.sidecar_suffixes,
                    extra_outputs=self.extra_outputs, adopted=True,
                )
            except (StoreCorruption, OSError) as exc:
                get_logger().warning(
                    "output %s exists but cannot be adopted into the store "
                    "(%s); rebuilding", self.output_path, exc,
                )
                return True
            STORE_ADOPTIONS.inc()
            self.skip_reason = "store_adopted"
            get_logger().info(
                "output %s adopted into the artifact store (pre-store "
                "artifact, first sight)", self.output_path,
            )
            return False
        # the legacy idiom would have trusted this file; hash inequality
        # against the plans that previously produced it says its plan
        # changed under it
        get_logger().info(
            "output %s exists but its plan hash changed; rebuilding",
            self.output_path,
        )
        _JOBS_REDONE.inc()
        tm.emit(
            "job_redo", job=self.label,
            output=os.path.basename(self.output_path),
            reason="plan_changed",
        )
        return True

    def should_run(self, force: bool, dry_run: bool = False,
                   runner: str = "") -> bool:
        self.skip_reason = None
        self._plan_hash = None
        store = store_runtime.active()
        if store is not None and self.plan is not None and self.output_path:
            self._plan_hash = self._resolve_plan_hash(store)
        if self._plan_hash is not None:
            return self._store_should_run(store, force, dry_run, runner)
        if force or not self.output_path:
            return True
        if any(not os.path.isfile(p) for p in self.extra_outputs):
            # a missing companion file must regenerate even when the main
            # output exists (p02's tables are one artifact set; the model
            # layer's per-file guards keep existing files untouched)
            return True
        if os.path.isfile(self.output_path):
            if os.path.isfile(self._sentinel_path):
                # crash consistency: a SIGKILLed/power-lost run leaves a
                # possibly-truncated output that plain skip-existing (the
                # reference's idiom — it shares this hole) would wrongly
                # accept. The sentinel marks an unfinished run; databases
                # produced elsewhere carry no sentinels and are untouched.
                get_logger().warning(
                    "output %s exists but its producing run never "
                    "completed (crashed?); re-running",
                    self.output_path,
                )
                _JOBS_REDONE.inc()
                tm.emit(
                    "job_redo", job=self.label,
                    output=os.path.basename(self.output_path),
                    reason="crash_sentinel",
                )
                return True
            get_logger().warning(
                "output %s already exists, will not convert. Use --force to "
                "force overwriting.",
                self.output_path,
            )
            self.skip_reason = "output_exists"
            return False
        return True

    def commit_to_store(self) -> None:
        """Bind the freshly-produced artifact to its plan hash. The hash
        is ALWAYS re-resolved here: an input produced earlier in the same
        run (p03's stalling pass reads the wo_buffer render) makes any
        plan-time hash stale, and committing under it would bind the new
        bytes to the old inputs. Store I/O failures degrade to a warning
        (the artifact itself is complete); a failed container read-back
        probe raises — an output that does not decode must fail HERE, not
        when something consumes it."""
        store = store_runtime.active()
        if store is None or self.plan is None or not self.output_path:
            return
        self._plan_hash = self._resolve_plan_hash(store)
        if self._plan_hash is None or not os.path.isfile(self.output_path):
            return
        provenance = dict(self.provenance)
        if self.request_ids:
            provenance["requests"] = list(self.request_ids)
        if self.trace_ids:
            provenance["traces"] = list(self.trace_ids)
        try:
            store.commit(
                self._plan_hash, self.output_path, producer=self.label,
                provenance=provenance,
                sidecar_suffixes=self.sidecar_suffixes,
                extra_outputs=self.extra_outputs,
            )
        except StoreCorruption:
            raise
        except OSError as exc:
            get_logger().warning(
                "store: could not commit %s (%s); artifact left uncached",
                self.output_path, exc,
            )

    def complete_externally(self) -> None:
        """Finalize an output whose bytes were produced OUTSIDE run() —
        the p03 batch waves and the fused p03+p04 driver (models/fused)
        render many member artifacts in one pass, then bind each to its
        own existing plan hash through this: provenance, the store
        commit (plan hash re-resolved against the final input bytes,
        exactly as run()'s tail does), and only then the crash-sentinel
        clear — a crash inside the commit leaves the sentinel, so the
        next run redoes the artifact instead of trusting bytes the
        store never vouched for."""
        self.write_provenance()
        self.commit_to_store()
        clear_inprogress(self.output_path)

    def write_provenance(self) -> None:
        if not self.logfile_path:
            return
        record = {
            "output": os.path.basename(self.output_path),
            "processingChain": get_processing_chain_version(),
            "job": self.label,
            **self.provenance,
        }
        if self.request_ids:
            record["requests"] = list(self.request_ids)
        if self.trace_ids:
            record["traces"] = list(self.trace_ids)
        os.makedirs(os.path.dirname(self.logfile_path), exist_ok=True)
        from ..utils.fsio import atomic_write_text

        atomic_write_text(self.logfile_path, "".join(
            f"{key}: {json.dumps(value) if not isinstance(value, str) else value}\n"
            for key, value in record.items()
        ))

    def run(self) -> Any:
        marked = mark_inprogress(self.output_path)
        req_fields: dict = {}
        if self.request_ids:
            req_fields["request_ids"] = list(self.request_ids)
        if self.trace_ids:
            req_fields["trace_id"] = self.trace_ids[0]
            if len(self.trace_ids) > 1:
                req_fields["trace_ids"] = list(self.trace_ids)
        tm.emit("job_start", job=self.label,
                output=os.path.basename(self.output_path), **req_fields)
        # live view: this job is in flight from here; its completion also
        # advances the enclosing stage's jobs-done progress (stage_span)
        hb = HEARTBEATS.register(self.label, kind="job")
        t0 = time.perf_counter()
        with tracing.span(self.label, output=os.path.basename(self.output_path)):
            try:
                result = self.fn()
            except BaseException as exc:
                # streaming jobs surface decode errors mid-write: a partial
                # artifact must never survive to satisfy a later run's
                # skip-existing check (enforced here once, for every job)
                if self.output_path and os.path.isfile(self.output_path):
                    os.unlink(self.output_path)
                if marked:
                    clear_inprogress(self.output_path)
                hb.finish("fail")
                HEARTBEATS.stage_advance(1)
                tm.emit(
                    "job_end", job=self.label, status="fail",
                    duration_s=round(time.perf_counter() - t0, 4),
                    error=repr(exc)[:300], **req_fields,
                )
                raise
        dur = time.perf_counter() - t0
        hb.finish("ok")
        HEARTBEATS.stage_advance(1)
        _JOB_SECONDS.observe(dur)
        tm.emit("job_end", job=self.label, status="ok",
                duration_s=round(dur, 4), **req_fields)
        self.write_provenance()
        # commit before the sentinel clears: a crash inside the commit
        # leaves the sentinel, so the next run redoes the job instead of
        # trusting an output the store never vouched for
        self.commit_to_store()
        # removed only after the output (and its provenance) are complete:
        # a crash anywhere above leaves the sentinel and the next run redoes
        # the job instead of trusting a possibly-truncated artifact
        if marked:
            clear_inprogress(self.output_path)
        return result


def device_stage_parallelism(requested: int, stage: str, cap: int = 4) -> int:
    """Clamp a device stage's `-p` to `cap`, telling the user when it bites.

    Device-stage jobs pipeline decode→device→encode internally
    (engine/prefetch) in O(CHUNK) memory, so extra width buys host
    decode/encode overlap across PVSes at ~CHUNK×depth frames of RAM each;
    compiled-graph executions still serialize through the chip's queue, so
    past the reference's own pool width (4, lib/parse_args.py:67-72) more
    workers only queue."""
    capped = max(1, min(requested, cap))
    if requested > capped:
        get_logger().info(
            "%s: capping parallelism %d -> %d (device jobs pipeline "
            "decode/compute/encode internally; wider only costs host RAM)",
            stage, requested, capped,
        )
    return capped


class JobRunner:
    """Plans and executes jobs with skip-existing / force / dry-run
    semantics and fail-fast parallel execution."""

    def __init__(self, force: bool = False, dry_run: bool = False,
                 parallelism: int = 4, name: str = "jobs") -> None:
        self.force = force
        self.dry_run = dry_run
        self.parallelism = parallelism
        self.name = name
        self.jobs: list[Job] = []
        #: output path -> (label, plan fingerprint | None) of its writer
        self._writers: dict[str, tuple] = {}

    @staticmethod
    def _plan_fingerprint(job: Job) -> Optional[str]:
        """Canonical serialization of the UNRESOLVED plan (no file I/O):
        cheap, deterministic, and exactly what distinguishes two plans
        submitted under one label."""
        if job.plan is None:
            return None
        from ..store import keys

        try:
            return keys.canonical_json(job.plan)
        except keys.PlanError:
            # an unhashable plan will surface at should_run/commit time;
            # the dedup decision degrades to the legacy label compare
            return None

    def add(self, job: Optional[Job]) -> None:
        """Plan a job. Two *different* jobs targeting one output file is a
        write-write race the reference could silently hit (its pool dedups
        only identical command strings, reference cmd_utils.py:73-79, and
        concurrency safety rests on task independence — SURVEY.md §5);
        here it fails loudly at plan time. The same job added twice (the
        reference's dedup case, e.g. one segment shared by many PVSes)
        stays a silent dedup — but "same" means same label AND same plan:
        two different plans under one label targeting one output used to
        dedup silently, hiding a real divergence."""
        if job is None:
            return
        if job.output_path:
            fp = self._plan_fingerprint(job)
            prior = self._writers.get(job.output_path)
            if prior is not None:
                prior_label, prior_fp = prior
                if prior_label == job.label and (
                    fp is None or prior_fp is None or fp == prior_fp
                ):
                    _JOBS_DEDUPED.labels(runner=self.name).inc()
                    return  # same plan submitted again: dedup
                if prior_label == job.label:
                    raise ChainError(
                        f"{self.name}: job '{job.label}' submitted twice "
                        f"with DIFFERENT plans for {job.output_path} — "
                        "write-write race hidden under one label"
                    )
                raise ChainError(
                    f"{self.name}: jobs '{prior_label}' and '{job.label}' "
                    f"both write {job.output_path} — write-write race"
                )
            self._writers[job.output_path] = (job.label, fp)
        if job.should_run(self.force, self.dry_run, runner=self.name):
            _JOBS_PLANNED.labels(runner=self.name).inc()
            # the live per-stage denominator: every planned job is one
            # unit of the enclosing stage's progress (stage_span)
            HEARTBEATS.stage_add_planned(1)
            tm.emit("job_planned", job=job.label, runner=self.name,
                    output=os.path.basename(job.output_path))
            self.jobs.append(job)
        else:
            _JOBS_SKIPPED.labels(runner=self.name).inc()
            tm.emit("job_skip", job=job.label, runner=self.name,
                    output=os.path.basename(job.output_path),
                    reason=job.skip_reason or "output_exists")

    def _run_job(self, job: Job) -> Any:
        """Execute one job, attributing a failure to this runner's
        telemetry series before the error propagates."""
        try:
            return job.run()
        except BaseException:
            _JOBS_FAILED.labels(runner=self.name).inc()
            raise

    def run(self) -> dict[str, Any]:
        log = get_logger()
        if self.dry_run:
            for job in self.jobs:
                log.info("[dry-run] %s -> %s", job.label, job.output_path)
            planned = self.jobs
            self.jobs = []
            self._writers.clear()
            return {j.label: None for j in planned}
        runner = ParallelRunner(max_parallel=self.parallelism, name=self.name)
        for job in self.jobs:
            runner.add(self._run_job, job, label=job.label)
        self.jobs = []
        self._writers.clear()
        return runner.run()

    def run_serial(self) -> dict[str, Any]:
        """Run jobs one by one in order (for device-bound stages — one chip,
        serialized device queue). Failures become ChainError so the CLI can
        map them to a clean exit 1."""
        log = get_logger()
        results = {}
        jobs, self.jobs = self.jobs, []
        self._writers.clear()
        for job in jobs:
            if self.dry_run:
                log.info("[dry-run] %s -> %s", job.label, job.output_path)
                results[job.label] = None
            else:
                try:
                    results[job.label] = self._run_job(job)
                except Exception as exc:
                    raise ChainError(
                        f"{self.name}: job '{job.label}' failed: {exc!r}"
                    ) from exc
        return results
