"""Async host↔device prefetch pipeline (SURVEY.md §7.4).

The reference overlaps nothing: each pool worker is one blocking ffmpeg
process that decodes, scales, and encodes serially inside libav
(reference lib/cmd_utils.py:60-129). Here the three phases live on
different execution resources — host decode (native, GIL-released),
device compute (async XLA dispatch), host encode (native, GIL-released)
— so a bounded-queue pipeline overlaps them:

    decode thread ──chunks──▶ [queue] ──▶ main loop: device compute
                                              │
                                        [queue] ──▶ encode thread

`Prefetcher` runs any chunk iterator ahead on a worker thread (decode
prefetch); `AsyncWriter` drains device results onto a `VideoWriter` from
a second thread (encode writeback). Long PVSes stream through bounded
host memory instead of the full-clip materialization the reference's
tmp-segment files imply (reference p03:88-136).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

_SENTINEL = object()


class Prefetcher:
    """Iterate `source` on a background thread, keeping up to `depth`
    items ready. Exceptions raised by the source (or by `transform`,
    which also runs on the worker thread) surface at the consumer's next
    pull, preserving fail-fast semantics."""

    def __init__(
        self,
        source: Iterable[Any],
        depth: int = 2,
        transform: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None

        def worker() -> None:
            try:
                for item in source:
                    if self._stop.is_set():
                        return
                    if transform is not None:
                        item = transform(item)
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as exc:  # noqa: BLE001 - re-raised in consumer
                self._err = exc
            finally:
                while not self._stop.is_set():
                    try:
                        self._q.put(_SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self) -> Iterator[Any]:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                if self._err is not None:
                    err, self._err = self._err, None
                    raise err
                return
            yield item

    def close(self) -> None:
        """Abandon the stream (e.g. on a downstream error). Blocks until the
        worker has actually exited: callers close the underlying source
        (e.g. a VideoReader the worker decodes from) right after this, so
        returning with the thread alive would race native teardown. The
        worker checks the stop flag between items, so the wait is bounded
        by one in-flight item."""
        self._stop.set()
        while self._thread.is_alive():
            try:
                while True:  # keep the queue drained so puts can't block
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncWriter:
    """Background writeback onto a `VideoWriter`: `put` enqueues a chunk of
    stacked planes ([T, H, W] per plane, host arrays or device arrays —
    device arrays are fetched on the writer thread so the main loop never
    blocks on a transfer); the worker writes frame-by-frame. `close()`
    drains the queue, closes the writer, and re-raises any worker error."""

    def __init__(self, writer, depth: int = 4) -> None:
        self._writer = writer
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: Optional[BaseException] = None

        def worker() -> None:
            while True:
                item = self._q.get()
                if item is _SENTINEL:
                    return
                if self._err is not None:
                    continue  # drain without writing after a failure
                try:
                    planes = [np.asarray(p) for p in item]
                    for i in range(planes[0].shape[0]):
                        self._writer.write(*(p[i] for p in planes))
                except BaseException as exc:  # noqa: BLE001 - re-raised in close
                    self._err = exc

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def put(self, planes_chunk) -> None:
        if self._err is not None:
            self._finish()
        self._q.put(list(planes_chunk))

    def write_audio(self, samples: np.ndarray) -> None:
        """Audio goes straight through (written once, before video)."""
        self._writer.write_audio(samples)

    def _finish(self) -> None:
        self._q.put(_SENTINEL)
        self._thread.join()
        self._writer.close()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self) -> None:
        self._finish()

    def __enter__(self) -> "AsyncWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()
        else:  # don't mask the original error; still stop the thread
            try:
                self.close()
            except Exception:
                pass


def iter_plane_chunks(reader, chunk: int = 64) -> Iterator[list[np.ndarray]]:
    """Stream a `VideoReader` as per-plane [T, H, W] stacks of up to
    `chunk` frames, without materializing the whole clip."""
    buf: list = []
    for frame in reader:
        buf.append(frame)
        if len(buf) == chunk:
            yield [
                np.stack([f.planes[p] for f in buf])
                for p in range(len(buf[0].planes))
            ]
            buf = []
    if buf:
        yield [
            np.stack([f.planes[p] for f in buf])
            for p in range(len(buf[0].planes))
        ]


def stream_monotonic_gather(
    frames: Iterable,
    out_index: Callable[[int], int],
    n_out: Optional[int],
    chunk: int = 64,
) -> Iterator[list[np.ndarray]]:
    """Streaming version of `planes[idx]` for a nondecreasing index map.

    `out_index(k)` gives the (unclamped) source-frame index of output k;
    frames beyond the end of the stream clamp to the last decoded frame
    (the reference's repeat-last-frame behavior in create_avpvs_segment,
    lib/ffmpeg.py:1037-1038 nullsrc canvas). When `n_out` is None the
    output length follows ffmpeg `fps=` semantics against the true frame
    count, resolved once decode finishes via `n_out_fn`.
    """
    return _stream_gather_impl(frames, out_index, n_out, None, chunk)


def stream_fps_resample(
    frames: Iterable,
    src_fps: float,
    dst_fps: float,
    chunk: int = 64,
) -> Iterator[list[np.ndarray]]:
    """Streaming ffmpeg `fps=` filter (ops/fps.fps_resample_indices
    semantics): output k at time k/dst_fps takes source frame
    floor(t*src_fps + 0.5); total output length round(n/src_fps*dst_fps)
    resolved when the source ends."""
    def out_index(k: int) -> int:
        return int(np.floor(k / dst_fps * src_fps + 0.5))

    def n_out_fn(n_src: int) -> int:
        return int(round(n_src / src_fps * dst_fps))

    return _stream_gather_impl(frames, out_index, None, n_out_fn, chunk)


def _stream_gather_impl(
    frames: Iterable,
    out_index: Callable[[int], int],
    n_out: Optional[int],
    n_out_fn: Optional[Callable[[int], int]],
    chunk: int,
) -> Iterator[list[np.ndarray]]:
    buf: list[list[np.ndarray]] = []

    def flush():
        nonlocal buf
        if buf:
            stacked = [
                np.stack([planes[p] for planes in buf])
                for p in range(len(buf[0]))
            ]
            buf = []
            return stacked
        return None

    k = 0  # next output index
    cur = -1  # index of the last decoded frame
    last_planes: Optional[list[np.ndarray]] = None
    it = iter(frames)
    exhausted = False
    while n_out is None or k < n_out:
        # decode forward until the current frame is the one output k wants
        target = out_index(k)
        while not exhausted and cur < target:
            try:
                frame = next(it)
            except StopIteration:
                exhausted = True
                if n_out is None:
                    n_out = n_out_fn(cur + 1) if n_out_fn is not None else k
                break
            cur += 1
            last_planes = list(frame.planes)
        if n_out is not None and k >= n_out:
            break
        if last_planes is None:  # empty source
            break
        # past-the-end outputs repeat the last decoded frame (clamp)
        buf.append(last_planes)
        k += 1
        if len(buf) == chunk:
            yield flush()
    tail = flush()
    if tail is not None:
        yield tail
