"""Async host↔device prefetch pipeline (SURVEY.md §7.4).

The reference overlaps nothing: each pool worker is one blocking ffmpeg
process that decodes, scales, and encodes serially inside libav
(reference lib/cmd_utils.py:60-129). Here the three phases live on
different execution resources — host decode (native, GIL-released),
device compute (async XLA dispatch), host encode (native, GIL-released)
— so a bounded-queue pipeline overlaps them:

    decode thread ──chunks──▶ [queue] ──▶ main loop: device compute
                                              │
                                        [queue] ──▶ encode thread

`Prefetcher` runs any chunk iterator ahead on a worker thread (decode
prefetch); `AsyncWriter` drains device results onto a `VideoWriter` from
a second thread (encode writeback). Long PVSes stream through bounded
host memory instead of the full-clip materialization the reference's
tmp-segment files imply (reference p03:88-136).
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from .. import telemetry as tm
from ..io import bufpool
from ..telemetry import profiling
from ..telemetry.heartbeat import HEARTBEATS, NULL_HEARTBEAT, TaskCancelled
from ..utils import lockdebug

_SENTINEL = object()
_EXHAUSTED = object()

# Live bounded-queue registry: the resource monitor samples current
# depths by NAME (telemetry/profiling.sample_resources) without holding
# any pipeline object alive. Entries self-prune via the weakref callback
# when their queue dies — a run that never reads the depths must not
# leak one entry per finished pipeline object for the process lifetime.
_QUEUE_REGISTRY: dict[int, tuple[str, "weakref.ref"]] = {}
_QUEUE_REG_LOCK = lockdebug.make_lock("queue_registry")


def _register_queue(name: str, q: queue.Queue) -> None:
    key = id(q)

    def _gone(_ref, *, _key=key):
        # lock-free like bufpool's weakref callback: a GC cycle sweep can
        # fire this on a thread already holding the registry lock, and a
        # single-key dict.pop is GIL-atomic
        _QUEUE_REGISTRY.pop(_key, None)

    with _QUEUE_REG_LOCK:
        _QUEUE_REGISTRY[key] = (name, weakref.ref(q, _gone))


def live_queue_depths() -> dict[str, dict]:
    """{queue name: {"queues": live instances, "depth": summed qsize}} of
    every registered pipeline queue still alive."""
    out: dict[str, dict] = {}
    with _QUEUE_REG_LOCK:
        # the lock-free callback can pop mid-iteration — retry the (rare)
        # race instead of excluding it
        for _ in range(4):
            try:
                entries = list(_QUEUE_REGISTRY.values())
                break
            except RuntimeError:
                continue
        else:
            entries = []
    for name, ref in entries:
        q = ref()
        if q is None:
            continue  # callback will prune it
        entry = out.setdefault(name, {"queues": 0, "depth": 0})
        entry["queues"] += 1
        entry["depth"] += q.qsize()
    return out

# Telemetry handles, bound once at import: every mutation below starts
# with the registry's enabled check, and the hot loops additionally
# guard with `tm.enabled()` so a disabled run never calls qsize() or
# perf_counter(). Granularity is per CHUNK (≈64 frames), never per frame.
_Q_DEPTH = tm.histogram(
    "chain_queue_depth",
    "bounded-queue depth sampled at each consumer pull / producer push",
    ("queue",),
    buckets=tm.DEFAULT_DEPTH_BUCKETS,
)
_Q_DECODE = _Q_DEPTH.labels(queue="decode")
_Q_ENCODE = _Q_DEPTH.labels(queue="encode")
_WAIT = tm.counter(
    "chain_pipeline_wait_seconds_total",
    "time the pipeline spent blocked on a bounded queue, by side",
    ("side",),
)
_WAIT_CONSUMER = _WAIT.labels(side="consumer")
_WAIT_PRODUCER = _WAIT.labels(side="producer")
_FRAMES_DECODED = tm.FRAMES_DECODED
_FRAMES_ENCODED = tm.FRAMES_ENCODED
_BYTES_ENCODED = tm.BYTES_ENCODED
_EVENT_SAMPLE_EVERY = 64  # every Nth depth sample also lands in the event log


class _DepthSampler:
    """Per-pipeline-object sampling helper: histogram every sample, event
    log every Nth (events are for forensics; the histogram carries the
    distribution)."""

    __slots__ = ("_bound", "_queue_name", "_n")

    def __init__(self, bound, queue_name: str) -> None:
        self._bound = bound
        self._queue_name = queue_name
        self._n = 0

    def sample(self, depth: int) -> None:
        self._bound.observe(depth)
        self._n += 1
        if self._n % _EVENT_SAMPLE_EVERY == 1:
            tm.emit("queue_depth", queue=self._queue_name, depth=depth)


def _put_until_stop(q: queue.Queue, item: Any, stop: threading.Event,
                    hb=NULL_HEARTBEAT) -> bool:
    """Blocking put that a concurrent close() can always interrupt: close()
    sets `stop` and keeps the queue drained, so either the put lands or the
    worker observes stop within one timeout tick — never a hung put. A
    watchdog hard timeout (`hb.cancelled`) interrupts the same way, so a
    put blocked on a wedged consumer cannot outlive its kill. Returns
    whether the item landed."""
    while not stop.is_set():
        if hb.cancelled:
            return False
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _drain_join(queues: list, threads: list) -> None:
    """Shutdown tail shared by the prefetchers: with the stop flag already
    set, keep every queue drained (so no worker put can block) until every
    worker thread has exited. Blocking until exit matters — callers tear
    down native sources (VideoReaders) right after, which must not race a
    live decode thread."""
    while any(t.is_alive() for t in threads):
        for q in queues:
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        for t in threads:
            t.join(timeout=0.1)


class Prefetcher:
    """Iterate `source` on a background thread, keeping up to `depth`
    items ready. Exceptions raised by the source (or by `transform`,
    which also runs on the worker thread) surface at the consumer's next
    pull, preserving fail-fast semantics."""

    def __init__(
        self,
        source: Iterable[Any],
        depth: int = 2,
        transform: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        _register_queue("decode", self._q)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None

        def worker() -> None:
            # the heartbeat beats once per prefetched item: a healthy
            # stream keeps it fresh, a wedged decode or a blocked put
            # ages it for the watchdog; a hard timeout lands here as
            # TaskCancelled and surfaces at the consumer's next pull
            hb = HEARTBEATS.register("decode-prefetch", kind="prefetch")
            status = "ok"
            try:
                src = iter(source)
                while True:
                    # under --profile each pull (the decode of one chunk)
                    # lands in the span timeline as the decode lane
                    with profiling.maybe_span("prefetch:decode"):
                        item = next(src, _EXHAUSTED)
                    if item is _EXHAUSTED:
                        break
                    if self._stop.is_set():
                        return
                    hb.check_cancelled()
                    if transform is not None:
                        item = transform(item)
                    if _put_until_stop(self._q, item, self._stop, hb):
                        hb.beat(advance=1)
                    hb.check_cancelled()
            except BaseException as exc:  # noqa: BLE001 - re-raised in consumer
                status = "fail"
                self._err = exc
            finally:
                hb.finish(status)
                # the sentinel put is interruptible by close() only, NOT
                # by cancellation: the consumer's blocking get() needs the
                # sentinel to learn about the stored TaskCancelled at all
                _put_until_stop(self._q, _SENTINEL, self._stop)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._depth_sampler = _DepthSampler(_Q_DECODE, "decode")

    def __iter__(self) -> Iterator[Any]:
        while True:
            if tm.enabled():
                self._depth_sampler.sample(self._q.qsize())
                t0 = time.perf_counter()
                item = self._q.get()
                _WAIT_CONSUMER.inc(time.perf_counter() - t0)
            else:
                item = self._q.get()
            if item is _SENTINEL:
                if self._err is not None:
                    err, self._err = self._err, None
                    raise err
                return
            yield item

    def close(self) -> None:
        """Abandon the stream (e.g. on a downstream error). Blocks until the
        worker has actually exited: callers close the underlying source
        (e.g. a VideoReader the worker decodes from) right after this, so
        returning with the thread alive would race native teardown. The
        worker checks the stop flag between items, so the wait is bounded
        by one in-flight item."""
        self._stop.set()
        _drain_join([self._q], [self._thread])

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncWriter:
    """Background writeback onto a `VideoWriter`: `put` enqueues a chunk of
    stacked planes ([T, H, W] per plane, host arrays or device arrays —
    device arrays are fetched on the writer thread so the main loop never
    blocks on a transfer); the worker hands whole chunks to the writer's
    batched encode (one native crossing) when it has one, else writes
    frame-by-frame. `close()` drains the queue, closes the writer, and
    re-raises any worker error.

    `put(..., recycle=blocks)` returns the given pooled host blocks to
    `pool` (default: the shared bufpool.DEFAULT_POOL — pass the same pool
    the blocks were acquired from, or the release is a no-op) AFTER the
    chunk is encoded — the fetch of the device outputs forces completion
    of the computation that consumed those blocks, so this is the
    earliest point reuse is provably safe (a device_put may alias host
    memory on the CPU backend)."""

    def __init__(self, writer, depth: int = 4, pool=None) -> None:
        self._writer = writer
        self._pool = pool or bufpool.DEFAULT_POOL
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        _register_queue("encode", self._q)
        self._err: Optional[BaseException] = None

        def worker() -> None:
            # beats once per written chunk (progress, not liveness): a
            # writer starved by a slow producer ages alongside it, a
            # wedged native write ages alone — the stack dump tells
            # which. A hard timeout turns further work into a drain.
            hb = HEARTBEATS.register("encode-writeback", kind="writeback")
            status = "ok"
            # PC_HOST_BATCH=0 must bypass the batched encode too — the
            # switch is the whole-path kill switch AND the per-frame
            # parity baseline the chain-level tests diff against
            write_batch = (
                getattr(self._writer, "write_batch", None)
                if bufpool.host_batch_enabled() else None
            )
            while True:
                try:
                    item = self._q.get(timeout=0.5)
                except queue.Empty:
                    if hb.cancelled and self._err is None:
                        status = "fail"
                        self._err = TaskCancelled(
                            "writeback 'encode-writeback' cancelled by the "
                            "watchdog hard timeout"
                        )
                    continue
                if item is _SENTINEL:
                    hb.finish(status)
                    return
                chunk, recycle = item
                if self._err is not None:
                    # drain without writing after a failure; recycle
                    # blocks are DROPPED, not released — their consuming
                    # computation was never synced, so handing them out
                    # again could alias in-flight device reads (the run
                    # is aborting; weakref bookkeeping reclaims them)
                    continue
                try:
                    with profiling.maybe_span("writeback:encode"):
                        planes = [np.asarray(p) for p in chunk]
                        if write_batch is not None:
                            write_batch(*planes)
                        else:
                            for i in range(planes[0].shape[0]):
                                self._writer.write(*(p[i] for p in planes))
                    # outputs are on the host now, so any computation that
                    # read the recycled input blocks has completed
                    if recycle:
                        self._pool.release(*recycle)
                    hb.beat(advance=1)
                    if tm.enabled():
                        _FRAMES_ENCODED.inc(planes[0].shape[0])
                        _BYTES_ENCODED.inc(sum(p.nbytes for p in planes))
                except BaseException as exc:  # noqa: BLE001 - re-raised in close
                    status = "fail"
                    self._err = exc

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._depth_sampler = _DepthSampler(_Q_ENCODE, "encode")

    def put(self, planes_chunk, recycle=None) -> None:
        if self._err is not None:
            self._finish()
        item = (list(planes_chunk), list(recycle) if recycle else None)
        if tm.enabled():
            self._depth_sampler.sample(self._q.qsize())
            t0 = time.perf_counter()
            self._q.put(item)
            _WAIT_PRODUCER.inc(time.perf_counter() - t0)
        else:
            self._q.put(item)

    def write_audio(self, samples: np.ndarray) -> None:
        """Audio goes straight through (written once, before video)."""
        self._writer.write_audio(samples)

    def _finish(self) -> None:
        self._q.put(_SENTINEL)
        self._thread.join()
        self._writer.close()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self) -> None:
        self._finish()

    def __enter__(self) -> "AsyncWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()
        else:  # don't mask the original error; still stop the thread
            try:
                self.close()
            except Exception:
                pass


class MultiSegmentPrefetcher:
    """Decode several segment chunk-streams concurrently, yielding chunks
    strictly in stream order (stream 0's chunks, then stream 1's, ...).

    The serial long path (reference p03:88-136 decodes tmp segments with a
    process pool, then concatenates files) has a host-side analog here:
    `factories[i]` is a zero-arg callable returning segment i's chunk
    iterator; up to `workers` of them run on worker threads at once, each
    buffering into its own bounded queue of `depth` chunks. The consumer
    sees exactly the serially-chained stream, but decode overlaps segment
    boundaries and runs `workers` wide — the "decode throughput feeding
    the chips" knob (SURVEY §7 hard part #2) without files or processes:
    native decode releases the GIL, so threads scale on a multi-core host.

    Failure semantics match the serial chain: an error in stream k is
    raised when the consumer reaches stream k's position (earlier streams'
    chunks still flow), and `close()` tears all workers down promptly.
    """

    def __init__(self, factories, workers: int = 2, depth: int = 2) -> None:
        self._n = len(factories)
        self._factories = list(factories)
        self._queues = [
            queue.Queue(maxsize=max(1, depth)) for _ in range(self._n)
        ]
        for q in self._queues:
            _register_queue("decode", q)
        self._errs: list[Optional[BaseException]] = [None] * self._n
        self._stop = threading.Event()
        self._next = 0  # next unclaimed stream index
        self._claim_lock = lockdebug.make_lock("prefetch_claim")

        def worker() -> None:
            # planned stays None: streams are CLAIMED across workers, so
            # a per-worker denominator of n would double-count in /status
            # (units_done still says how many streams this worker finished)
            hb = HEARTBEATS.register("decode-multiseg", kind="prefetch")
            status = "ok"
            try:
                while not self._stop.is_set() and not hb.cancelled:
                    with self._claim_lock:
                        idx = self._next
                        if idx >= self._n:
                            return
                        self._next = idx + 1
                    q = self._queues[idx]
                    try:
                        src = iter(self._factories[idx]())
                        while True:
                            # same decode-lane span as Prefetcher: the
                            # multiseg path must not read as an idle
                            # decode lane in a --profile timeline
                            with profiling.maybe_span("prefetch:decode"):
                                item = next(src, _EXHAUSTED)
                            if item is _EXHAUSTED:
                                break
                            if _put_until_stop(q, item, self._stop, hb):
                                hb.beat()  # chunk-level liveness
                            if self._stop.is_set():
                                return
                            hb.check_cancelled()
                    except BaseException as exc:  # noqa: BLE001 - consumer re-raises
                        status = "fail"
                        self._errs[idx] = exc
                    else:
                        hb.beat(advance=1)  # one unit = one finished stream
                    # sentinel interruptible by close() only (see Prefetcher)
                    _put_until_stop(q, _SENTINEL, self._stop)
            finally:
                if hb.cancelled:
                    # hard-killed: fail every stream this worker would
                    # still have claimed, so a consumer that gets past the
                    # current stream meets an error, never a silent hang
                    while True:
                        with self._claim_lock:
                            idx = self._next
                            if idx >= self._n:
                                break
                            self._next = idx + 1
                        self._errs[idx] = TaskCancelled(
                            "prefetch 'decode-multiseg' cancelled by the "
                            "watchdog hard timeout"
                        )
                        _put_until_stop(self._queues[idx], _SENTINEL, self._stop)
                hb.finish(status)

        self._threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(max(1, min(workers, self._n)))
        ]
        for t in self._threads:
            t.start()
        self._depth_sampler = _DepthSampler(_Q_DECODE, "decode")

    def __iter__(self) -> Iterator[Any]:
        for idx in range(self._n):
            q = self._queues[idx]
            while True:
                if tm.enabled():
                    self._depth_sampler.sample(q.qsize())
                    t0 = time.perf_counter()
                    item = q.get()
                    _WAIT_CONSUMER.inc(time.perf_counter() - t0)
                else:
                    item = q.get()
                if item is _SENTINEL:
                    err = self._errs[idx]
                    if err is not None:
                        self._errs[idx] = None
                        raise err
                    break
                yield item

    def close(self) -> None:
        """Abandon all streams; blocks until every worker has exited (they
        own native readers whose teardown must not race the caller's)."""
        self._stop.set()
        with self._claim_lock:
            self._next = self._n  # no new claims
        _drain_join(self._queues, self._threads)

    def __enter__(self) -> "MultiSegmentPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def iter_plane_chunks(
    reader, chunk: int = 64, pool=None,
) -> Iterator[list[np.ndarray]]:
    """Stream a `VideoReader` as per-plane [T, H, W] stacks of up to
    `chunk` frames, without materializing the whole clip.

    Batch-capable readers (VideoReader.iter_chunks) decode each chunk
    through ONE native crossing into pooled blocks — the chunks arrive
    already stacked, so the per-frame allocation + np.stack copy of the
    fallback path never happens. Consumers hand pooled chunks back via
    `AsyncWriter.put(..., recycle=chunk)` or `bufpool` release; a chunk
    that is never released costs one allocation, not correctness.
    Any other iterable of Frames takes the per-frame fallback."""
    it = getattr(reader, "iter_chunks", None)
    if it is not None and bufpool.host_batch_enabled():
        chunks = it(chunk, pool=pool)
    else:
        from ..io.video import iter_stacked_frame_chunks

        chunks = iter_stacked_frame_chunks(reader, chunk)
    for planes in chunks:
        _FRAMES_DECODED.inc(planes[0].shape[0])
        yield planes


def stream_monotonic_gather(
    frames: Iterable,
    out_index: Callable[[int], int],
    n_out: Optional[int],
    chunk: int = 64,
) -> Iterator[list[np.ndarray]]:
    """Streaming version of `planes[idx]` for a nondecreasing index map.

    `out_index(k)` gives the (unclamped) source-frame index of output k;
    frames beyond the end of the stream clamp to the last decoded frame
    (the reference's repeat-last-frame behavior in create_avpvs_segment,
    lib/ffmpeg.py:1037-1038 nullsrc canvas). When `n_out` is None the
    output length follows ffmpeg `fps=` semantics against the true frame
    count, resolved once decode finishes via `n_out_fn`.
    """
    return _stream_gather_impl(frames, out_index, n_out, None, chunk)


def stream_fps_resample(
    frames: Iterable,
    src_fps: float,
    dst_fps: float,
    chunk: int = 64,
) -> Iterator[list[np.ndarray]]:
    """Streaming ffmpeg `fps=` filter (ops/fps.fps_resample_indices
    semantics): output k at time k/dst_fps takes source frame
    floor(t*src_fps + 0.5); total output length round(n/src_fps*dst_fps)
    resolved when the source ends."""
    def out_index(k: int) -> int:
        return int(np.floor(k / dst_fps * src_fps + 0.5))

    def n_out_fn(n_src: int) -> int:
        return int(round(n_src / src_fps * dst_fps))

    return _stream_gather_impl(frames, out_index, None, n_out_fn, chunk)


def _stream_gather_impl(
    frames: Iterable,
    out_index: Callable[[int], int],
    n_out: Optional[int],
    n_out_fn: Optional[Callable[[int], int]],
    chunk: int,
) -> Iterator[list[np.ndarray]]:
    buf: list[list[np.ndarray]] = []

    def flush():
        nonlocal buf
        if buf:
            stacked = [
                np.stack([planes[p] for planes in buf])
                for p in range(len(buf[0]))
            ]
            buf = []
            return stacked
        return None

    k = 0  # next output index
    cur = -1  # index of the last decoded frame
    last_planes: Optional[list[np.ndarray]] = None
    it = iter(frames)
    exhausted = False
    try:
        while n_out is None or k < n_out:
            # decode forward until the current frame is the one output k wants
            target = out_index(k)
            while not exhausted and cur < target:
                try:
                    frame = next(it)
                except StopIteration:
                    exhausted = True
                    if n_out is None:
                        n_out = n_out_fn(cur + 1) if n_out_fn is not None else k
                    break
                cur += 1
                last_planes = list(frame.planes)
            if n_out is not None and k >= n_out:
                break
            if last_planes is None:  # empty source
                break
            # past-the-end outputs repeat the last decoded frame (clamp)
            buf.append(last_planes)
            k += 1
            if len(buf) == chunk:
                yield flush()
        tail = flush()
        if tail is not None:
            yield tail
    finally:
        # decoded-frame accounting in one batch (never per frame); the
        # finally also covers a consumer that closes the generator early
        _FRAMES_DECODED.inc(cur + 1)
