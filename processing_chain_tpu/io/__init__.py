from . import framesizes, medialib, probe
from .medialib import MediaError
from .video import Frame, VideoReader, VideoWriter

__all__ = [
    "framesizes",
    "medialib",
    "probe",
    "MediaError",
    "Frame",
    "VideoReader",
    "VideoWriter",
]
