from . import bufpool, framesizes, medialib, probe
from .bufpool import BufferPool
from .medialib import MediaError
from .video import Frame, VideoReader, VideoWriter

__all__ = [
    "bufpool",
    "framesizes",
    "medialib",
    "probe",
    "BufferPool",
    "MediaError",
    "Frame",
    "VideoReader",
    "VideoWriter",
]
