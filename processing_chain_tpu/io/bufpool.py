"""Recycling pool of pre-allocated host plane blocks.

The host frame path moves [T, H, W] chunk blocks between the native
decoder, the device, and the native encoder (BENCH_r05: the e2e chain is
host-bound, not device-bound). Allocating those blocks fresh per chunk
costs an mmap + page-fault sweep per ~100 MB block on the hot path; this
pool recycles them: `acquire` hands back a previously-released block of
the same (shape, dtype) when one is free, else allocates.

Ownership protocol (deliberately simple):

  * `acquire(shape, dtype)` transfers ownership to the caller.
  * `release(*arrays)` returns ownership; ONLY the exact array object
    returned by `acquire` recycles (views are ignored), so a producer
    that hands a consumer a trimmed tail view `block[:n]` never has the
    backing block yanked while other views of it are still alive.
  * Releasing a foreign or already-released array is a safe no-op —
    consumers may call `release` on mixed pooled/unpooled chunks.
  * Dropping a pooled block without releasing it is a leak of one
    allocation, not of pool bookkeeping: outstanding blocks are tracked
    by weakref, so the entry vanishes with the array.

Thread-safe; the default pool is shared by the decode prefetch threads,
the main device loop, and the encode writeback thread.
"""

from __future__ import annotations

import os
import weakref

import numpy as np

from .. import telemetry as tm
from ..utils import lockdebug

_HITS = tm.counter(
    "chain_bufpool_hits_total", "pool acquisitions served from a recycled block"
)
_MISSES = tm.counter(
    "chain_bufpool_misses_total", "pool acquisitions that had to allocate"
)
_RECYCLED_BYTES = tm.counter(
    "chain_bufpool_recycled_bytes_total",
    "bytes served from recycled blocks instead of fresh allocations",
)


def host_batch_enabled() -> bool:
    """Master switch for the batched host frame path (chunked native I/O +
    buffer pooling). PC_HOST_BATCH=0 restores the per-frame fallback —
    the parity baseline, and the escape hatch for anything the batch
    path misbehaves on."""
    # plan-exempt: (batched host I/O is byte-identical to the per-frame fallback; host-path-smoke CI parity gate)
    return os.environ.get("PC_HOST_BATCH", "1").strip().lower() not in (
        "0", "off", "false",
    )


class BufferPool:
    """Keyed free lists of C-contiguous ndarrays. See module docstring
    for the ownership protocol."""

    def __init__(self, max_free_per_key: int = 4) -> None:
        # cap per (shape, dtype): chunk blocks run ~100 MB at 1080p×64f,
        # so an unbounded free list would quietly pin the high-water mark
        self._max_free = max_free_per_key
        self._lock = lockdebug.make_lock("bufpool")
        self._free: dict[tuple, list[np.ndarray]] = {}  # guarded-by: _lock
        self._outstanding: dict[int, weakref.ref] = {}  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    @staticmethod
    def _key(shape, dtype) -> tuple:
        return (tuple(int(s) for s in shape), np.dtype(dtype).str)

    def acquire(self, shape, dtype=np.uint8) -> np.ndarray:
        key = self._key(shape, dtype)
        with self._lock:
            free = self._free.get(key)
            arr = free.pop() if free else None
            if arr is not None:
                self.hits += 1
            else:
                self.misses += 1
        if arr is None:
            arr = np.empty(shape, dtype)  # allocate outside the lock
            if tm.enabled():
                _MISSES.inc()
        elif tm.enabled():
            _HITS.inc()
            _RECYCLED_BYTES.inc(arr.nbytes)
        self._track(arr)
        return arr

    def _track(self, arr: np.ndarray) -> None:
        key = id(arr)

        def _dropped(_ref, *, _self=weakref.ref(self), _key=key):  # noqa: B008 - definition-time capture is the point (GC-safe weakref, no cycle through self)
            # deliberately LOCK-FREE: a GC cycle collection can fire this
            # callback on any allocation — including ones made while this
            # same thread already holds the pool lock (e.g. inside
            # release()) — and the plain Lock would then deadlock the
            # whole pipeline. dict.pop on a single key is GIL-atomic, and
            # no other path touches this key while the weakref is live
            # (release() holds a strong ref to the array it resolves).
            pool = _self()
            if pool is not None:
                # chainlint: disable=lock-guard (GC-reentrant callback: taking _lock here can deadlock — dict.pop on one key is GIL-atomic and no other path touches a live weakref's key; see comment above)
                pool._outstanding.pop(_key, None)

        with self._lock:
            self._outstanding[key] = weakref.ref(arr, _dropped)

    def release(self, *arrays: np.ndarray) -> None:
        for arr in arrays:
            if not isinstance(arr, np.ndarray):
                continue
            with self._lock:
                ref = self._outstanding.get(id(arr))
                if ref is None or ref() is not arr:
                    continue  # foreign array, a view, or double release
                del self._outstanding[id(arr)]
                free = self._free.setdefault(
                    self._key(arr.shape, arr.dtype), []
                )
                if len(free) < self._max_free:
                    free.append(arr)

    def owns(self, arr) -> bool:
        """True when `arr` is exactly an outstanding block of this pool
        (views and foreign arrays are not owned — same identity rule as
        release). Lets producers decide whether slicing an array would
        strand a recyclable block."""
        if not isinstance(arr, np.ndarray):
            return False
        with self._lock:
            ref = self._outstanding.get(id(arr))
            return ref is not None and ref() is arr

    def stats(self) -> dict:
        with self._lock:
            # outstanding bytes resolve the weakrefs on demand (a ~1 Hz
            # resource-monitor call, never a hot path): refs whose arrays
            # were dropped without release count as gone, matching the
            # pool's leak-of-one-allocation accounting. The lock excludes
            # acquire/release, but the deliberately LOCK-FREE weakref
            # callback can still pop concurrently — retry the iteration
            # the (rare) time it mutates the dict under us.
            for _ in range(4):
                try:
                    live = [ref() for ref in list(self._outstanding.values())]
                    break
                except RuntimeError:
                    continue
            else:
                live = []
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / max(1, self.hits + self.misses),
                "free_blocks": sum(len(v) for v in self._free.values()),
                "free_bytes": sum(
                    a.nbytes for v in self._free.values() for a in v
                ),
                "outstanding": len(self._outstanding),
                "outstanding_bytes": sum(
                    a.nbytes for a in live if a is not None
                ),
            }


#: process-wide default pool, shared by the decode/compute/encode stages
DEFAULT_POOL = BufferPool()
