"""Deterministic media-fault injection + decode/encode deadlines.

The native boundary (io/medialib, io/video) is where hostile bytes
meet the chain: a truncated SRC surfaces as a mid-stream decode error,
a decompression bomb as a hang, a full disk as a failed encode write.
Those paths are exactly the ones ordinary tests never exercise —
real corrupt files are fiddly to author and hangs are untestable
without a clock. This module makes every one of those failures a
DETERMINISTIC, scriptable event, the same way PC_LOCK_DEBUG makes lock
inversions observable and PC_PLAN_DEBUG makes cache poisoning
observable (docs/ROBUSTNESS.md):

  * ``PC_MEDIA_FAULTS`` — a fault spec consulted when a decoder or
    encoder OPENS (never per frame): zero cost when unset, one dict
    lookup per open when set. Tests, CI (`media-fault-smoke`) and the
    chaos harnesses drive it; production never sets it.
  * ``PC_MEDIA_DEADLINE_S`` — a wall-clock budget for every native
    decode/encode crossing. Python cannot interrupt a hung native
    call, so the guarded call runs on a daemon thread and an expiry
    ABANDONS it (handle deliberately leaked — closing a handle another
    thread is still inside would be a use-after-free), records
    watchdog-grade forensics (all-thread stack dump, the PR 3
    `dump_all_stacks`), and raises ``MediaDeadlineExpired``
    (kind="transient") — the worker dies, the replica keeps serving.

Fault spec grammar (semicolon-separated clauses)::

    PC_MEDIA_FAULTS="kind[@param=value[,param=value...]][;kind@...]"

    decode-error   @ frame=N [,match=SUBSTR] [,times=K]
        the decode crossing that would produce frame N raises a
        MediaError instead (the truncated-mid-GOP shape)
    short-read     @ frame=N [,match=SUBSTR] [,times=K]
        the decoder reports EOF at frame N with NO error — the silent
        truncation shape (container promised more; decoder just ends)
    hang           @ seconds=S [,op=decode|encode] [,frame=N]
                     [,match=SUBSTR] [,times=K]
        the native crossing sleeps S seconds (uninterruptible from the
        caller's thread, exactly like a real wedged decoder) — the
        deadline self-test's trigger
    geometry-flip  @ frame=N [,match=SUBSTR] [,times=K]
        raises the native boundary's own mid-stream geometry-change
        rejection shape (media.cpp fails loudly on w/h/format flips)
    enospc         @ [frame=N] [,match=SUBSTR] [,times=K]
        the encode write raises OSError(ENOSPC) — the full-disk shape
        the store-commit and fused-fan-out degrade paths must survive

``match`` filters by path substring (absent = every path); ``times``
caps how often the clause fires process-wide (default 1 — a fault that
fired once lets the retry succeed, which is what the staged-fallback
and transient-retry tests need; 0 = unlimited). Every fault surfaces
as an exception or an early EOF — never a silently altered committed
artifact — which is what keeps the knob plan-exempt
(store/plan_schema.py): an aborted execution commits nothing.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import telemetry as tm
from ..utils import lockdebug
from .medialib import MediaError

_FAULTS_INJECTED = tm.counter(
    "chain_media_faults_injected_total",
    "PC_MEDIA_FAULTS clauses fired, by fault kind",
    ("kind",),
)
_DEADLINE_EXPIRED = tm.counter(
    "chain_media_deadline_expired_total",
    "native decode/encode crossings abandoned past PC_MEDIA_DEADLINE_S",
)

_KINDS = ("decode-error", "short-read", "hang", "geometry-flip", "enospc")

#: per-clause fire counts, process-wide (keyed by (spec, clause index))
#: so `times=1` semantics survive re-parsing the same spec at every
#: decoder open
_FIRED_LOCK = lockdebug.make_lock("media_faults")
_FIRED: dict[tuple, int] = {}  # guarded-by: _FIRED_LOCK


class FaultSpecError(ValueError):
    """A malformed PC_MEDIA_FAULTS value. Raised at the first decoder/
    encoder open so a typo'd chaos run fails loudly instead of running
    faultless and 'proving' robustness it never tested."""


@dataclass(frozen=True)
class FaultClause:
    kind: str
    frame: Optional[int] = None
    seconds: float = 0.0
    op: str = "any"            # decode | encode | any (hang only)
    match: str = ""
    times: int = 1             # 0 = unlimited
    index: int = 0             # position in the spec (fire-count key)
    spec: str = field(default="", compare=False)

    def matches_path(self, path: str) -> bool:
        return self.match in path if self.match else True

    def fire(self) -> bool:
        """Consume one firing; False when the times budget is spent."""
        key = (self.spec, self.index)
        with _FIRED_LOCK:
            fired = _FIRED.get(key, 0)
            if self.times and fired >= self.times:
                return False
            _FIRED[key] = fired + 1
        _FAULTS_INJECTED.labels(kind=self.kind).inc()
        return True


def reset_fire_counts() -> None:
    """Test hook: forget which clauses already fired."""
    with _FIRED_LOCK:
        _FIRED.clear()


def _parse_clause(text: str, index: int, spec: str) -> FaultClause:
    kind, _, params_text = text.partition("@")
    kind = kind.strip()
    if kind not in _KINDS:
        raise FaultSpecError(
            f"PC_MEDIA_FAULTS: unknown fault kind {kind!r} "
            f"(known: {', '.join(_KINDS)})"
        )
    params: dict = {}
    for part in filter(None, (p.strip() for p in params_text.split(","))):
        key, eq, value = part.partition("=")
        if not eq:
            raise FaultSpecError(
                f"PC_MEDIA_FAULTS: clause {text!r}: parameter {part!r} "
                "is not key=value"
            )
        params[key.strip()] = value.strip()
    try:
        frame = int(params.pop("frame")) if "frame" in params else None
        seconds = float(params.pop("seconds", 0.0))
        times = int(params.pop("times", 1))
    except ValueError as exc:
        raise FaultSpecError(
            f"PC_MEDIA_FAULTS: clause {text!r}: {exc}"
        ) from exc
    op = params.pop("op", "any")
    match = params.pop("match", "")
    if params:
        raise FaultSpecError(
            f"PC_MEDIA_FAULTS: clause {text!r}: unknown parameter(s) "
            f"{sorted(params)}"
        )
    if kind == "hang" and seconds <= 0:
        raise FaultSpecError(
            f"PC_MEDIA_FAULTS: clause {text!r}: hang needs seconds=S > 0"
        )
    if op not in ("decode", "encode", "any"):
        raise FaultSpecError(
            f"PC_MEDIA_FAULTS: clause {text!r}: op must be decode|encode"
        )
    if kind in ("decode-error", "short-read", "geometry-flip") \
            and frame is None:
        frame = 0
    return FaultClause(kind=kind, frame=frame, seconds=seconds, op=op,
                       match=match, times=times, index=index, spec=spec)


_PARSE_LOCK = lockdebug.make_lock("media_faults_parse")
_PARSED: dict[str, tuple] = {}  # guarded-by: _PARSE_LOCK


def parse_spec(spec: str) -> tuple[FaultClause, ...]:
    with _PARSE_LOCK:
        cached = _PARSED.get(spec)
    if cached is not None:
        return cached
    clauses = tuple(
        _parse_clause(part, i, spec)
        for i, part in enumerate(
            filter(None, (p.strip() for p in spec.split(";")))
        )
    )
    with _PARSE_LOCK:
        _PARSED[spec] = clauses
    return clauses


def _active_spec() -> tuple[FaultClause, ...]:
    # plan-exempt: (test/CI/chaos fault injection — every clause aborts the consuming execution (exception or EOF-kill) before any artifact commits; production never sets it. docs/ROBUSTNESS.md)
    spec = os.environ.get("PC_MEDIA_FAULTS", "").strip()
    if not spec:
        return ()
    return parse_spec(spec)


def _emit_injected(clause: FaultClause, path: str,
                   frame: Optional[int]) -> None:
    tm.emit("media_fault_injected", kind=clause.kind,
            path=os.path.basename(path), frame=frame)


class _PathFaults:
    """Clauses matching one open path, with a stream frame cursor."""

    def __init__(self, path: str, clauses: tuple) -> None:
        self.path = path
        self.clauses = clauses
        self.pos = 0  # frames already delivered/consumed

    def hang(self, op: str) -> None:
        """Injected native hang. Call this INSIDE the deadline-guarded
        crossing (io/video wraps it with the native call): a real
        wedged native call does not poll cancellation flags, so neither
        does this one — only the deadline (or the isolation
        subprocess's kill) gets past it."""
        for clause in self.clauses:
            if clause.kind != "hang" or clause.op not in (op, "any"):
                continue
            if clause.frame is not None and self.pos < clause.frame:
                continue
            if clause.fire():
                _emit_injected(clause, self.path, self.pos)
                time.sleep(clause.seconds)


class DecoderFaults(_PathFaults):
    """Decode-side injection. `check` runs before the native crossing:
    a decode-error/geometry-flip whose frame falls inside the requested
    window raises THERE (a real mid-stream error also eats the frames
    the codec had buffered past the damage); a short-read reports EOF
    once its frame is reached — the silent truncation shape — with the
    window capped so exactly `frame` frames are ever delivered."""

    def cap_frames(self, want: int) -> int:
        for clause in self.clauses:
            if clause.kind == "short-read" and \
                    self.pos < clause.frame < self.pos + want:
                want = clause.frame - self.pos
        return want

    def check(self, want: int) -> Optional[int]:
        """Raise/EOF per the spec; returns 0 to short-circuit the
        native call with an injected EOF, or None to proceed (then
        call `advance(n)` with the real decoded count)."""
        for clause in self.clauses:
            if clause.kind in ("decode-error", "geometry-flip") and \
                    clause.frame < self.pos + want:
                if clause.fire():
                    _emit_injected(clause, self.path, clause.frame)
                    if clause.kind == "decode-error":
                        raise MediaError(
                            f"decode {self.path} @frame {clause.frame}: "
                            "injected decode error (PC_MEDIA_FAULTS) — "
                            "Invalid data found when processing input"
                        )
                    # the exact rejection shape media.cpp raises when a
                    # hostile stream flips geometry mid-stream
                    raise MediaError(
                        f"decode {self.path} @frame {clause.frame}: "
                        "injected mid-stream geometry change "
                        "(PC_MEDIA_FAULTS): frame geometry/format "
                        "changed mid-stream"
                    )
            elif clause.kind == "short-read" and self.pos >= clause.frame:
                if clause.fire():
                    _emit_injected(clause, self.path, clause.frame)
                    return 0  # silent early EOF: the nasty shape
        return None

    def advance(self, n: int) -> None:
        self.pos += n


class EncoderFaults(_PathFaults):
    def check(self, frames: int) -> None:
        for clause in self.clauses:
            if clause.kind != "enospc":
                continue
            if clause.frame is not None and \
                    not (self.pos <= clause.frame < self.pos + max(1, frames)):
                continue
            if clause.fire():
                _emit_injected(clause, self.path, self.pos)
                raise OSError(
                    errno.ENOSPC,
                    "No space left on device (injected: PC_MEDIA_FAULTS)",
                    self.path,
                )
        self.pos += frames


def decoder_faults(path: str) -> Optional[DecoderFaults]:
    """The decode-side fault plan for one open, or None (the common
    case — one env lookup per OPEN, nothing per frame)."""
    clauses = tuple(
        c for c in _active_spec()
        if c.matches_path(path)
        and (c.kind != "enospc")
        and (c.kind != "hang" or c.op in ("decode", "any"))
    )
    return DecoderFaults(path, clauses) if clauses else None


def encoder_faults(path: str) -> Optional[EncoderFaults]:
    clauses = tuple(
        c for c in _active_spec()
        if c.matches_path(path)
        and c.kind in ("enospc", "hang")
        and (c.kind != "hang" or c.op in ("encode", "any"))
    )
    return EncoderFaults(path, clauses) if clauses else None


# ------------------------------------------------------------ deadlines


class MediaDeadlineExpired(MediaError):
    """A native decode/encode crossing exceeded PC_MEDIA_DEADLINE_S and
    was abandoned. kind="transient" by construction: the input MAY be a
    decompression bomb, but a loaded host produces the same symptom —
    the serve taxonomy retries under the attempts budget, and the
    PC_ISOLATE_DECODE first-contact gate is what upgrades repeat
    offenders to poison (docs/ROBUSTNESS.md)."""

    def __init__(self, *args) -> None:
        super().__init__(*args, kind="transient")


def media_deadline_s() -> Optional[float]:
    """The per-crossing wall-clock budget, read at decoder/encoder OPEN
    (None = unlimited, the default — zero added cost). A malformed
    value fails LOUDLY (same philosophy as FaultSpecError): silently
    running with no deadline while the operator believes hang
    protection is on is the exact failure the knob exists to prevent."""
    # plan-exempt: (wall-clock budget only: an expiry aborts the crossing with MediaDeadlineExpired before any artifact commits; the frames delivered by surviving crossings are identical at any budget)
    raw = os.environ.get("PC_MEDIA_DEADLINE_S", "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise FaultSpecError(
            f"PC_MEDIA_DEADLINE_S: {raw!r} is not a number of seconds"
        ) from None
    return value if value > 0 else None


class GuardWorker:
    """One persistent daemon worker for a reader/writer's guarded
    crossings. `write()` crosses per FRAME — spawning a thread per
    crossing would tax exactly the hot path the deadline protects, so
    the owner keeps ONE worker for its lifetime. A deadline expiry
    abandons the worker mid-call (the owner poisons itself and never
    submits again — same leak semantics as the abandoned handle); a
    clean close() stops it. Deliberately NOT a ThreadPoolExecutor: its
    atexit hook JOINS workers, so a wedged native call would block
    interpreter exit — the one thing the deadline exists to prevent."""

    def __init__(self, name: str) -> None:
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, name=name[:60], daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, result, error, done = item
            try:
                result.append(fn())
            except BaseException as exc:  # noqa: BLE001 - relayed to the caller
                error.append(exc)
            finally:
                done.set()
                # drop every task reference BEFORE blocking on the next
                # get(): a worker abandoned after an expiry parks here
                # forever, and locals still pinning the crossing's
                # closure would pin its pooled destination blocks with
                # it (the per-call thread died and dropped them; the
                # persistent worker must shed them explicitly)
                del fn, result, error, done, item

    def submit(self, fn: Callable) -> tuple:
        result: list = []
        error: list = []
        done = threading.Event()
        self._q.put((fn, result, error, done))
        return result, error, done

    def stop(self) -> None:
        """Clean shutdown (owner close). Never call after an expiry —
        the sentinel would queue behind the wedged call forever, which
        is harmless but pointless; abandoned workers just leak."""
        self._q.put(None)


def guarded_call(fn: Callable, deadline_s: Optional[float], *, op: str,
                 path: str, frame: Optional[int] = None,
                 worker: Optional[GuardWorker] = None):
    """Run one native crossing under a wall-clock deadline. With no
    deadline this is a direct call (the production path). With one, the
    call runs on a DAEMON thread (a hung native call must never block
    interpreter exit) — the caller's persistent `worker` when provided
    (io/video owners reuse one across their per-frame/per-chunk
    crossings), else a fresh thread — and an expiry abandons it:
    forensics recorded through the watchdog's stack-dump surface, the
    heartbeat finished as "timeout", MediaDeadlineExpired raised to the
    caller — whose owner must then poison the handle (io/video marks
    the reader/writer closed; the native handle is deliberately leaked,
    because closing it under a thread still inside the call is a
    use-after-free)."""
    if deadline_s is None:
        return fn()
    from ..telemetry.heartbeat import HEARTBEATS
    from ..telemetry.watchdog import dump_all_stacks

    hb = HEARTBEATS.register(
        f"media:{op}:{os.path.basename(path)}"[:120], kind="task"
    )
    if worker is not None:
        result, error, done = worker.submit(fn)
    else:
        result = []
        error = []
        done = threading.Event()

        def _run() -> None:
            try:
                result.append(fn())
            except BaseException as exc:  # noqa: BLE001 - relayed to the caller
                error.append(exc)
            finally:
                done.set()

        threading.Thread(
            target=_run, name=f"media-{op}-deadline", daemon=True
        ).start()
    if not done.wait(timeout=deadline_s):
        hb.finish("timeout")
        _DEADLINE_EXPIRED.inc()
        tm.emit(
            "media_deadline_expired", op=op, path=os.path.basename(path),
            frame=frame, deadline_s=deadline_s, stacks=dump_all_stacks(),
        )
        raise MediaDeadlineExpired(
            f"{op} {path}"
            + (f" @frame {frame}" if frame is not None else "")
            + f": no progress within the {deadline_s:g}s media deadline "
            "(PC_MEDIA_DEADLINE_S) — native call abandoned, handle "
            "leaked; forensics in the event log"
        )
    if error:
        hb.finish("fail")
        raise error[0]
    hb.finish("ok")
    return result[0]
