"""Exact per-frame byte sizes straight from the bitstream.

Parity target: reference lib/get_framesize.py — an Annex-B NAL start-code
state machine for H.264 (:144-201) / H.265 (:204-263), an IVF container walk
for VP9 (:87-141), and ffprobe pkt_size fallback for AV1 (:266-274). The
reference reads the file one byte at a time in Python (its only Python-side
hot loop); here the scan is vectorized numpy over the whole buffer.

Size semantics match the reference exactly: a "frame" is a slice/VCL NAL;
its size runs from the byte after its start code's 0x01 to the 0x01 of the
next start code, minus 3 (or 5 when two extra zero bytes precede the next
start code); trailing frame sizes get the reference's end-of-file adjustments
(+3 for H.264, +0 for H.265).
"""

from __future__ import annotations

import os
import struct
import tempfile

import numpy as np

from .. import telemetry as tm
from ..utils import lockdebug
from . import medialib, sharedscan


def _start_code_positions(data: np.ndarray) -> np.ndarray:
    """Positions of the 0x01 byte of every 00 00 01 start-code trio."""
    if data.size < 3:
        return np.empty(0, np.int64)
    hits = (data[2:] == 1) & (data[1:-1] == 0) & (data[:-2] == 0)
    return np.nonzero(hits)[0] + 2


def _annexb_frame_sizes(
    data: np.ndarray, is_slice_nal
) -> tuple[list[int], np.ndarray, np.ndarray]:
    """Shared Annex-B scan: is_slice_nal(hdr_bytes) -> bool mask.
    Returns (sizes for all but the last slice NAL, start-code positions,
    slice mask) — empty when the stream has no start codes."""
    pos = _start_code_positions(data)
    if pos.size == 0:
        return [], pos, np.empty(0, bool)
    hdr_idx = pos + 1
    valid = hdr_idx < data.size
    pos = pos[valid]
    hdr = data[hdr_idx[valid]]
    slice_mask = is_slice_nal(hdr)
    sizes: list[int] = []
    nxt = np.roll(pos, -1)
    # prefix adjustment for the *next* start code (reference :163-169):
    # -5 when two extra zero bytes precede it, else -3
    for k in range(pos.size - 1):
        if not slice_mask[k]:
            continue
        end = nxt[k]
        extra = 5 if (end >= 4 and data[end - 3] == 0 and data[end - 4] == 0) else 3
        sizes.append(int(end - pos[k] - extra))
    return sizes, pos, slice_mask


def get_framesize_h264(filename: str, force: bool = False) -> list[int]:
    """H.264 slice sizes from the Annex-B stream (reference :144-201)."""
    data = _extract(filename, "h264", force)
    def is_slice(hdr):
        return np.isin(hdr & 0x1F, (1, 5)) & ((hdr & 0x10) == 0)
    sizes, pos, slice_mask = _annexb_frame_sizes(data, is_slice)
    if slice_mask.size and slice_mask[-1]:
        # reference end-of-file rule (:193-196): remaining bytes + 3
        sizes.append(int(data.size - 1 - pos[-1] + 3))
    return sizes


def get_framesize_h265(filename: str, force: bool = False) -> list[int]:
    """H.265 VCL NAL sizes (reference :204-263): NAL types 0-9 and 16-21."""
    data = _extract(filename, "h265", force)
    def is_slice(hdr):
        t = (hdr.astype(np.int64) >> 1) & 0x3F
        return (t <= 9) | ((t >= 16) & (t <= 21))
    sizes, pos, slice_mask = _annexb_frame_sizes(data, is_slice)
    if slice_mask.size and slice_mask[-1]:
        # reference end-of-file rule (:254-257): remaining bytes, no +3
        sizes.append(int(data.size - 1 - pos[-1]))
    return sizes


def get_framesize_vp9(filename: str, force: bool = False) -> list[int]:
    """VP9 frame sizes from the IVF frame headers (reference :87-141).

    The reference reads only 3 of the 4 size bytes (frames < 16 MiB); we
    read the full little-endian uint32."""
    with tempfile.TemporaryDirectory() as tmp:
        ivf = os.path.join(tmp, os.path.basename(filename) + "_tmp.ivf")
        medialib.extract_ivf(filename, ivf)
        raw = open(ivf, "rb").read()
    sizes = []
    off = 32  # IVF file header
    n = len(raw)
    while off + 12 <= n:
        (size,) = struct.unpack_from("<I", raw, off)
        sizes.append(int(size))
        off += 12 + size
    return sizes


def ffprobe_av1_frame_info(filename: str, timeout: float = 300.0) -> dict:
    """ffprobe fallback for AV1 frame metadata, routed through the
    chain's one subprocess door (`utils.runner.shell` — list argv,
    bounded wall time, ChainError on failure; the subprocess-hygiene
    rule). ONE `-show_frames` pass yields `{"size": [...],
    "pict_type": [...]}` so priors consumers get AV1 frame types without
    a second probe. Raises ChainError when ffprobe is absent/failing."""
    from ..utils.runner import shell

    proc = shell(
        [
            "ffprobe", "-v", "error", "-select_streams", "v:0",
            "-show_frames", "-show_entries", "frame=pkt_size,pict_type",
            "-of", "csv=p=0", filename,
        ],
        timeout=timeout,
    )
    sizes: list[int] = []
    picts: list[str] = []
    for line in proc.stdout.splitlines():
        if not line.strip():
            continue
        size, pict = None, "?"
        for tok in line.strip().split(","):
            tok = tok.strip()
            if tok.isdigit():
                size = int(tok)
            elif tok:
                pict = tok
        # one csv line == one frame: a frame whose pkt_size prints as
        # N/A must still occupy its slot (size 0), or every consumer
        # indexing frames by position desyncs past it
        if size is not None or pict != "?":
            sizes.append(size if size is not None else 0)
            picts.append(pict if pict != "N/A" else "?")
    return {"size": sizes, "pict_type": picts}


def get_framesize_av1(filename: str, force: bool = False) -> list[int]:
    """AV1: packet sizes from the native demuxer (reference :266-274 falls
    back to ffprobe pkt_size — kept here as the degrade path when the
    native boundary cannot load, via `ffprobe_av1_frame_info`). Served
    from the shared post-encode scan (io/sharedscan.py) so a p01-primed
    file costs no extra demux pass. `force` is unused (the demuxer scan
    is always exact); the default matches the three sibling parsers so a
    keyword caller sees uniform behavior."""
    try:
        return [int(s) for s in sharedscan.video(filename)["size"]]
    except medialib.MediaError:
        return ffprobe_av1_frame_info(filename)["size"]


#: bounded result memo with the DigestCache stat-signature trust model
#: (store/keys.py): repeat get_framesizes calls on an unchanged file —
#: p02 rebuilds, priors difficulty, serve cost features — stop re-reading
#: and re-parsing the whole bitstream. `force=True` bypasses AND refreshes.
_CACHE_MAX = 256
_cache_lock = lockdebug.make_lock("framesizes_cache")
_cache: dict[str, list] = {}  # guarded-by: _cache_lock (insertion = LRU)

_CACHE_HITS = tm.counter(
    "chain_io_framesizes_cache_hits_total",
    "get_framesizes served from the stat-keyed memo — a full bitstream "
    "re-parse a consumer did NOT pay",
)


def get_framesizes(filename: str, codec: str, force: bool = False) -> list[int]:
    try:
        st = os.stat(filename)
        key = f"{os.path.abspath(filename)}|{st.st_size}|{st.st_mtime_ns}|{codec}"
    except OSError:
        key = None  # let the parser raise its own error
    if key is not None and not force:
        with _cache_lock:
            hit = _cache.get(key)
            if hit is not None:
                _cache.pop(key)
                _cache[key] = hit
        if hit is not None:
            _CACHE_HITS.inc()
            return list(hit)
    if codec == "h264":
        sizes = get_framesize_h264(filename, force)
    elif codec in ("h265", "hevc"):
        sizes = get_framesize_h265(filename, force)
    elif codec == "vp9":
        sizes = get_framesize_vp9(filename, force)
    elif codec == "av1":
        sizes = get_framesize_av1(filename, force)
    else:
        raise ValueError(f"no exact frame-size parser for codec {codec!r}")
    if key is not None:
        with _cache_lock:
            _cache[key] = sizes
            while len(_cache) > _CACHE_MAX:
                _cache.pop(next(iter(_cache)))
    return list(sizes)


def merge_superframes(vfi, sizes_col="size", dts_col="dts"):
    """Merge VP9 superframe packets whose DTS differ by < 1.1 ms: the later
    packet's size is added to the earlier and the row dropped (reference
    delete_packets, get_framesize.py:27-51). Operates on a pandas DataFrame,
    returns a new one with reindexed `index` per segment."""
    df = vfi.reset_index(drop=True)
    dts = df[dts_col].to_numpy(dtype=np.float64)
    close = np.abs(np.diff(dts)) < 0.0011
    drop = np.zeros(len(df), dtype=bool)
    sizes = df[sizes_col].to_numpy().copy()
    target = np.arange(len(df))
    for i in np.nonzero(close)[0]:
        # row i+1 merges into the most recent kept row
        t = target[i]
        sizes[t] += sizes[i + 1]
        drop[i + 1] = True
        target[i + 1] = t
    df = df.assign(**{sizes_col: sizes})[~drop].reset_index(drop=True)
    if "segment" in df.columns:
        df["index"] = df.groupby("segment").cumcount()
    return df


def _extract(filename: str, codec: str, force: bool) -> np.ndarray:
    """Remux to Annex-B into a temp file and load as a numpy byte array
    (reference convert_file, :54-77)."""
    bsf = "h264_mp4toannexb" if codec == "h264" else "hevc_mp4toannexb"
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, os.path.basename(filename) + f"_tmp.{codec}")
        medialib.extract_annexb(filename, bsf, out)
        return np.fromfile(out, dtype=np.uint8)
