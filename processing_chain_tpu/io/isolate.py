"""Supervised-subprocess SRC validation: first-contact hostile-input gate.

A SRC upload is the one input the chain cannot trust: a truncated or
garbage stream surfaces as a native error (contained), but a hostile
one can WEDGE the decoder (decompression bomb) or crash it outright —
and a native crash takes the whole replica with it, not just the unit.
``PC_ISOLATE_DECODE=1`` (docs/ROBUSTNESS.md) moves first-contact
decodes into a supervised child process:

    parent (replica)                       child (this module's __main__)
      validate_src(path) ──runner.shell──▶  probe + full decode of path
      ├─ rc 0          → ok {frames, geometry}  (PC_MEDIA_FAULTS rides
      ├─ rc 3          → ChainError kind="poison"   the inherited env,
      ├─ crash signal  → ChainError kind="poison"   so the CI hang
      │   (SEGV/ABRT/…: the decoder died ON the     self-test injects
      │    bytes)                                   into the child)
      ├─ other death   → ChainError kind="transient"
      │   (OOM SIGKILL, rc 1 traceback, broken env — the bytes were
      │    never judged; a healthy digest must not quarantine)
      └─ timeout       → ChainError kind="transient"

The verdict mapping is the serve failure taxonomy's front line: a
stream the decoder rejects or dies on is POISON (serve quarantines its
content digest fleet-wide — retrying hostile bytes on another replica
just crashes another replica), while a timeout stays TRANSIENT (a
loaded host produces the same symptom; the attempts budget bounds the
retries and a genuine bomb ends terminal `failed`).

The child is a full process, so a hang is KILLED (runner.shell's
timeout kills the child group), an abandoned native thread leaks
nothing in the parent, and a SIGSEGV in third-party codec internals is
an exit status instead of a replica obituary.
"""

from __future__ import annotations

import json
import os
import signal
import sys
from typing import Optional

from .. import telemetry as tm
from ..utils.runner import ChainError, shell

_ISOLATED = tm.counter(
    "chain_isolated_decodes_total",
    "supervised first-contact SRC validations, by verdict",
    ("verdict",),
)

#: child exit code for a contained media rejection (vs. an uncaught
#: crash, which the kernel reports as a signal)
_RC_MEDIA_ERROR = 3

#: default wall budget for one first-contact validation when
#: PC_MEDIA_DEADLINE_S is unset: generous (a long clean SRC must pass)
#: but finite (a bomb must not own the worker forever)
DEFAULT_DEADLINE_S = 300.0


def isolate_decode_enabled() -> bool:
    """The PC_ISOLATE_DECODE gate (off by default: the subprocess costs
    one interpreter start per first-contact SRC)."""
    # plan-exempt: (validation-only routing: the child decodes and DISCARDS frames — it never produces artifact bytes, it only decides whether the replica may touch the SRC at all)
    return os.environ.get("PC_ISOLATE_DECODE", "").strip().lower() in (
        "1", "true", "yes", "on"
    )


#: signals that mean the DECODER CRASHED on the bytes (a verdict about
#: the input) — as opposed to environmental deaths (SIGKILL from the
#: OOM killer, SIGTERM from a supervisor…) which say nothing about the
#: SRC and must never durably quarantine a healthy digest
_CRASH_SIGNALS = frozenset(
    getattr(signal, name)
    for name in ("SIGSEGV", "SIGBUS", "SIGILL", "SIGFPE", "SIGABRT",
                 "SIGTRAP", "SIGSYS")
    if hasattr(signal, name)
)


def classify_isolation_result(returncode: int, stdout: str,
                              stderr: str) -> dict:
    """Pure verdict mapping for one finished child (unit-testable
    without spawning): {"verdict": ok|poison|transient, "detail": …,
    report fields…}. Timeouts never reach here — runner.shell raises
    before a returncode exists. Only verdicts ABOUT THE BYTES are
    poison: a contained media rejection (rc 3) or a native-crash
    signal. An environmental child death — OOM SIGKILL, a Python
    traceback (rc 1), a broken child env — is transient: the bytes
    were never judged, and poisoning the digest would park a healthy
    upload fleet-wide behind an operator re-arm."""
    from ..utils.fsio import last_json_line

    report = last_json_line(stdout) or {}
    if returncode == 0 and report.get("ok"):
        return {"verdict": "ok", **report}
    if returncode < 0:
        if -returncode in _CRASH_SIGNALS:
            return {
                "verdict": "poison",
                "detail": (
                    f"decoder subprocess crashed with signal {-returncode} "
                    "(native crash contained by PC_ISOLATE_DECODE)"
                ),
            }
        return {
            "verdict": "transient",
            "detail": (
                f"validator child died with signal {-returncode} "
                "(environmental — OOM kill/supervisor, not a byte "
                "verdict)"
            ),
        }
    detail = report.get("error") or (stderr or "").strip()[-500:] or \
        f"validator exited {returncode} with no report"
    if returncode == _RC_MEDIA_ERROR:
        return {"verdict": "poison", "detail": detail}
    return {"verdict": "transient", "detail": detail}


def validate_src(path: str, deadline_s: Optional[float] = None) -> dict:
    """Run one supervised first-contact validation of `path`. Returns
    the child's report on success; raises ChainError(kind="poison") for
    rejected/crashing streams and ChainError(kind="transient") for a
    timeout (see module doc). The PC_MEDIA_FAULTS/PC_MEDIA_DEADLINE_S
    environment rides into the child unchanged."""
    if deadline_s is None:
        from .faults import media_deadline_s

        deadline_s = media_deadline_s() or DEFAULT_DEADLINE_S
    try:
        proc = shell(
            [sys.executable, "-m", "processing_chain_tpu.io.isolate", path],
            check=False, timeout=deadline_s,
        )
    except ChainError as exc:
        # runner.shell killed a child that blew the budget: the decoder
        # HUNG on this input. Transient by policy (module doc).
        _ISOLATED.labels(verdict="timeout").inc()
        raise ChainError(
            f"first-contact validation of {path} exceeded "
            f"{deadline_s:g}s (decoder hang; child killed)",
            kind="transient",
        ) from exc
    result = classify_isolation_result(
        proc.returncode, proc.stdout, proc.stderr
    )
    _ISOLATED.labels(verdict=result["verdict"]).inc()
    if result["verdict"] == "ok":
        return result
    raise ChainError(
        f"first-contact validation rejected {path}: {result['detail']}",
        kind=result["verdict"],
    )


# ----------------------------------------------------------- child side


def _promised_frames(info: dict) -> int:
    """The container's own frame-count promise for the video stream —
    nb_frames when the muxer recorded it, else duration × avg fps. 0 =
    no promise (VFR/stream formats); the frame-count check then stays
    silent rather than guessing."""
    video = next(
        (s for s in info.get("streams", ())
         if s.get("codec_type") == "video"), None,
    )
    if video is None:
        return 0
    promised = int(video.get("nb_frames") or 0)
    if promised > 0:
        return promised
    duration = float(video.get("duration") or 0.0) or \
        float(info.get("format", {}).get("duration") or 0.0)
    try:
        num, den = (int(x) for x in
                    str(video.get("avg_frame_rate", "0/0")).split("/"))
        fps = num / den if den else 0.0
    except (TypeError, ValueError, ZeroDivisionError):
        fps = 0.0
    if duration > 0 and fps > 0:
        return int(round(duration * fps))
    return 0


def _child_main(path: str) -> int:
    """Probe + decode EVERY frame of `path`, discarding pixels (pooled
    chunks released as they stream — constant memory at any length).
    One JSON report line on stdout; exit 0 ok / 3 contained rejection;
    anything the native layer crashes on becomes our exit signal.

    The frame-count check is what upgrades the SILENT truncation shape
    to a verdict: some libav builds tolerate a mid-GOP cut as an early
    EOF with no error, and a chain fed such a stream would encode fewer
    frames than the event list promises. A decode that falls well short
    of the container's own frame count (tolerance: >3 frames AND >10%,
    so metadata rounding and B-frame delay never convict a clean file)
    is a contained rejection, exactly like a loud decode error."""
    from . import medialib
    from .bufpool import DEFAULT_POOL
    from .video import VideoReader

    try:
        info = medialib.probe(path)
        frames = 0
        with VideoReader(path) as reader:
            geometry = (reader.width, reader.height)
            for chunk in reader.iter_chunks():
                frames += int(chunk[0].shape[0])
                DEFAULT_POOL.release(*chunk)
        promised = _promised_frames(info)
        if promised > 0 and promised - frames > 3 and \
                frames < promised * 0.9:
            print(json.dumps({
                "ok": False,
                "error": (
                    f"silent truncation: container promises ~{promised} "
                    f"frames, decoder delivered {frames} with no error "
                    f"({path})"
                ),
            }))
            return _RC_MEDIA_ERROR
        print(json.dumps({
            "ok": True,
            "frames": frames,
            "width": geometry[0],
            "height": geometry[1],
            "format": info["format"]["format_name"],
        }))
        return 0
    except medialib.MediaError as exc:
        print(json.dumps({"ok": False, "error": str(exc)[:800]}))
        return _RC_MEDIA_ERROR


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(json.dumps({"ok": False, "error": "usage: isolate <path>"}))
        sys.exit(2)
    sys.exit(_child_main(sys.argv[1]))
