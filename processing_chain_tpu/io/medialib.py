"""ctypes bindings for the native media boundary (native/media.cpp).

Auto-builds libpcmedia.so from source on first use if missing (the native
analog of the reference's Docker-built ffmpeg, Dockerfile:1-56).
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
from typing import Optional

import numpy as np
from .. import telemetry as tm
from ..utils import lockdebug

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
#: PC_MEDIA_LIB points the loader at an alternate build flavor — the CI
#: sanitizer jobs load libpcmedia.asan.so / libpcmedia.tsan.so this way
#: (native/Makefile; the process must LD_PRELOAD the matching runtime).
_SO_PATH = os.environ.get(
    "PC_MEDIA_LIB",
    os.path.join(_NATIVE_DIR, "libpcmedia.so"),
)

_lock = lockdebug.make_lock("medialib")
_lib: Optional[ct.CDLL] = None  # guarded-by: _lock

#: every bitstream walk over a written file that is NOT a decoder open —
#: the decode-once invariant's second axis: chain_io_decoder_opens_total
#: counts pixel decodes, this counts demux/parse passes. A cold run's
#: packets/packets_all total should equal one pass per written file; more
#: means a consumer bypassed the shared scan (io/sharedscan.py).
_SCAN_PASSES = tm.counter(
    "chain_io_scan_passes_total",
    "bitstream demux/parse passes over a file "
    "(op=packets|packets_all|annexb|ivf|priors)",
    ("op",),
)

# swscale flag constants (libswscale/swscale.h)
SWS_FAST_BILINEAR = 1
SWS_BILINEAR = 2
SWS_BICUBIC = 4
SWS_POINT = 0x10
SWS_AREA = 0x20
SWS_BICUBLIN = 0x40
SWS_SINC = 0x100
SWS_LANCZOS = 0x200
SWS_SPLINE = 0x400
SWS_ACCURATE_RND = 0x40000
SWS_BITEXACT = 0x80000
SWS_FULL_CHR_H_INT = 0x2000
SWS_FULL_CHR_H_INP = 0x4000


class MPStreamInfo(ct.Structure):
    _fields_ = [
        ("stream_index", ct.c_int32),
        ("codec_type", ct.c_int32),
        ("codec_name", ct.c_char * 32),
        ("width", ct.c_int32),
        ("height", ct.c_int32),
        ("coded_width", ct.c_int32),
        ("coded_height", ct.c_int32),
        ("pix_fmt", ct.c_char * 32),
        ("fps_num", ct.c_int32),
        ("fps_den", ct.c_int32),
        ("avg_fps_num", ct.c_int32),
        ("avg_fps_den", ct.c_int32),
        ("tb_num", ct.c_int32),
        ("tb_den", ct.c_int32),
        ("duration", ct.c_double),
        ("nb_frames", ct.c_int64),
        ("bit_rate", ct.c_int64),
        ("sample_rate", ct.c_int32),
        ("channels", ct.c_int32),
        ("sample_fmt", ct.c_char * 32),
        ("profile", ct.c_char * 64),
    ]


class MPFormatInfo(ct.Structure):
    _fields_ = [
        ("format_name", ct.c_char * 64),
        ("duration", ct.c_double),
        ("bit_rate", ct.c_int64),
        ("file_size", ct.c_int64),
        ("nb_streams", ct.c_int32),
    ]


class MPVideoDesc(ct.Structure):
    _fields_ = [
        ("width", ct.c_int32),
        ("height", ct.c_int32),
        ("pix_fmt", ct.c_char * 32),
        ("fps_num", ct.c_int32),
        ("fps_den", ct.c_int32),
        ("duration", ct.c_double),
        ("planes", ct.c_int32),
        ("plane_w", ct.c_int32 * 4),
        ("plane_h", ct.c_int32 * 4),
        ("bytes_per_sample", ct.c_int32),
    ]


class MPPriorsFrame(ct.Structure):
    """Per-frame codec-prior record (native MPPriorsFrame). Field layout is
    triple-mirrored — C struct, this ctypes Structure, and PRIORS_DTYPE —
    with mp_priors_record_size as the ABI handshake."""

    _fields_ = [
        ("pts", ct.c_double),
        ("pkt_size", ct.c_int64),
        ("pict_type", ct.c_int32),
        ("key_frame", ct.c_int32),
        ("mv_count", ct.c_int32),
        ("qp_blocks", ct.c_int32),
        ("qp_mean", ct.c_double),
        ("qp_var", ct.c_double),
        ("width", ct.c_int32),
        ("height", ct.c_int32),
    ]


#: numpy view of MPPriorsFrame, so a batch of records IS a structured array
#: (no per-record Python unpacking on the hot path)
PRIORS_DTYPE = np.dtype(
    {
        "names": ["pts", "pkt_size", "pict_type", "key_frame", "mv_count",
                  "qp_blocks", "qp_mean", "qp_var", "width", "height"],
        "formats": ["<f8", "<i8", "<i4", "<i4", "<i4", "<i4", "<f8", "<f8",
                    "<i4", "<i4"],
    },
    align=True,
)

#: int32 fields per MV row (native PC_MV_FIELDS):
#: src_x, src_y, dst_x, dst_y, w, h, source
MV_FIELDS = 7


class MediaError(RuntimeError):
    """A native media-boundary failure. `kind` is the serve failure
    taxonomy's surface (docs/SERVE.md "Failure taxonomy"): raisers that
    KNOW the failure class tag it "transient" (full disk, wedged host),
    "permanent" (bad parameters) or "poison" (hostile input bytes — the
    SRC itself is the problem; serve quarantines its content digest
    fleet-wide). None = no claim; serve/scheduler.classify_failure
    falls back to exception-type heuristics."""

    def __init__(self, *args, kind: Optional[str] = None) -> None:
        super().__init__(*args)
        self.kind = kind


def _build(force: bool = False) -> None:
    cmd = ["make", "-C", _NATIVE_DIR] + (["-B"] if force else [])
    # a PC_MEDIA_LIB override selecting a sanitizer flavor in our own
    # native dir gets ITS target rebuilt (make's default target only
    # covers the production .so)
    if os.path.dirname(os.path.abspath(_SO_PATH)) == _NATIVE_DIR and \
            os.path.basename(_SO_PATH) != "libpcmedia.so":
        cmd.append(os.path.basename(_SO_PATH))
    # chainlint: disable=subprocess-hygiene (native bootstrap: the loader's degrade ladder keys on raw CalledProcessError vs OSError — runner.shell folds both into ChainError and would erase the distinction)
    subprocess.run(
        cmd,
        check=True,
        capture_output=True,
        text=True,
    )


def _compile_error(exc: subprocess.CalledProcessError) -> MediaError:
    return MediaError(
        f"native build failed:\n{(exc.stderr or str(exc))[-800:]}"
    )


def _build_or_raise(force: bool = False) -> None:
    """_build with every failure mapped onto MediaError, so callers that
    degrade on the documented exception type (`except MediaError`) never
    see a raw FileNotFoundError/CalledProcessError from the loader."""
    try:
        _build(force)
    except subprocess.CalledProcessError as exc:
        raise _compile_error(exc) from exc
    except OSError as exc:
        raise MediaError(
            f"native toolchain unavailable ({exc}) and no loadable "
            f"libpcmedia.so at {_SO_PATH}"
        ) from exc


def ensure_loaded() -> ct.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        # always run make: it no-ops when the .so is current and rebuilds
        # when media.cpp changed, so a checkout carrying a prebuilt binary
        # from before a struct change never loads at the wrong stride
        lib = None
        try:
            _build()
        except subprocess.CalledProcessError as exc:
            # make RAN and failed: the sources are newer than the .so (an
            # up-to-date tree no-ops even without a compiler), so loading
            # a prebuilt binary here would silently run pre-edit native
            # code while the compile error never surfaces. Fail loudly
            # WITH the compiler's message (make ran output-captured).
            raise _compile_error(exc) from exc
        except OSError:
            # make itself is missing (a deploy host without a toolchain):
            # a prebuilt .so is still loadable — the ABI handshake below
            # rejects a stale layout, which is the hazard the always-make
            # policy targets.
            if os.path.isfile(_SO_PATH):
                try:
                    lib = ct.CDLL(_SO_PATH)
                except OSError:
                    pass
            if lib is None:
                # nothing loadable: retry the build so the REAL problem
                # surfaces — as a MediaError, the loader's documented type
                _build_or_raise(force=True)
        if lib is None:
            try:
                lib = ct.CDLL(_SO_PATH)
            except OSError:
                # a stale or foreign-platform binary (e.g. a checkout moved
                # between architectures): force a rebuild for THIS host once
                # (-B: the broken .so may look up-to-date to make)
                _build_or_raise(force=True)
                lib = ct.CDLL(_SO_PATH)
        # ABI handshake: mtime-equal edge cases can survive the make; a
        # layout mismatch must fail loudly, never probe at the wrong stride
        try:
            so_size = lib.mp_stream_info_size()
        except AttributeError:
            so_size = -1
        if so_size != ct.sizeof(MPStreamInfo):
            raise MediaError(
                f"libpcmedia.so ABI mismatch (struct size {so_size} != "
                f"{ct.sizeof(MPStreamInfo)}); rebuild with "
                f"`make -B -C {_NATIVE_DIR}`"
            )

        u8p = ct.POINTER(ct.c_uint8)
        i16p = ct.POINTER(ct.c_int16)
        lib.mp_probe.restype = ct.c_int
        lib.mp_probe.argtypes = [
            ct.c_char_p, ct.POINTER(MPFormatInfo), ct.POINTER(MPStreamInfo),
            ct.c_int, ct.c_int, ct.c_char_p, ct.c_int,
        ]
        lib.mp_scan_packets.restype = ct.c_long
        lib.mp_scan_packets.argtypes = [
            ct.c_char_p, ct.c_int, ct.POINTER(ct.c_int64),
            ct.POINTER(ct.c_double), ct.POINTER(ct.c_double),
            ct.POINTER(ct.c_double), ct.POINTER(ct.c_int8), ct.c_long,
            ct.c_char_p, ct.c_int,
        ]
        try:
            # single-demux dual-stream scan: absent from prebuilt .so
            # files older than the shared-scan boundary (toolchain-less
            # hosts); scan_packets_all falls back to two passes then
            lib.mp_scan_packets_all.restype = ct.c_int
            lib.mp_scan_packets_all.argtypes = [
                ct.c_char_p,
                ct.POINTER(ct.c_int64), ct.POINTER(ct.c_double),
                ct.POINTER(ct.c_double), ct.POINTER(ct.c_double),
                ct.POINTER(ct.c_int8), ct.c_long, ct.POINTER(ct.c_long),
                ct.POINTER(ct.c_int64), ct.POINTER(ct.c_double),
                ct.POINTER(ct.c_double), ct.POINTER(ct.c_double),
                ct.POINTER(ct.c_int8), ct.c_long, ct.POINTER(ct.c_long),
                ct.c_char_p, ct.c_int,
            ]
        except AttributeError:
            pass
        lib.mp_decoder_open.restype = ct.c_void_p
        lib.mp_decoder_open.argtypes = [
            ct.c_char_p, ct.c_double, ct.c_double, ct.c_char_p, ct.c_int,
        ]
        lib.mp_decoder_desc.restype = ct.c_int
        lib.mp_decoder_desc.argtypes = [ct.c_void_p, ct.POINTER(MPVideoDesc)]
        lib.mp_decoder_next.restype = ct.c_int
        lib.mp_decoder_next.argtypes = [
            ct.c_void_p, u8p, u8p, u8p, u8p, ct.POINTER(ct.c_double),
            ct.c_char_p, ct.c_int,
        ]
        lib.mp_decoder_close.restype = None
        lib.mp_decoder_close.argtypes = [ct.c_void_p]
        try:
            # the chunk-granular host-path symbols land together: a .so
            # missing one is from before the batch boundary existed
            lib.mp_decoder_open_t.restype = ct.c_void_p
            lib.mp_decoder_open_t.argtypes = [
                ct.c_char_p, ct.c_double, ct.c_double, ct.c_int,
                ct.c_char_p, ct.c_int,
            ]
            lib.mp_decoder_next_batch.restype = ct.c_long
            lib.mp_decoder_next_batch.argtypes = [
                ct.c_void_p, u8p, u8p, u8p, u8p, ct.c_long,
                ct.POINTER(ct.c_double), ct.c_char_p, ct.c_int,
            ]
            lib.mp_encoder_write_video_batch.restype = ct.c_long
            lib.mp_encoder_write_video_batch.argtypes = [
                ct.c_void_p, u8p, u8p, u8p, u8p, ct.c_long,
                ct.c_char_p, ct.c_int,
            ]
            lib.mp_sws_scale_frames.restype = ct.c_int
            lib.mp_sws_scale_frames.argtypes = [
                u8p, ct.c_int, ct.c_int, u8p, ct.c_int, ct.c_int,
                ct.c_long, ct.c_int, ct.c_char_p, ct.c_int,
            ]
        except AttributeError as exc:
            raise MediaError(
                f"libpcmedia.so predates the batched frame I/O boundary; "
                f"rebuild with `make -B -C {_NATIVE_DIR}`"
            ) from exc
        try:
            lib.mp_decode_audio_s16_ch.restype = ct.c_long
            lib.mp_decode_audio_s16_ch.argtypes = [
                ct.c_char_p, ct.c_double, ct.c_double, ct.c_int, i16p,
                ct.c_long, ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32),
                ct.c_char_p, ct.c_int,
            ]
        except AttributeError as exc:
            # a prebuilt .so from before this symbol existed: reject it
            # loudly (the struct-size handshake can't see function ABI)
            raise MediaError(
                f"libpcmedia.so predates mp_decode_audio_s16_ch; rebuild "
                f"with `make -B -C {_NATIVE_DIR}`"
            ) from exc
        try:
            # the codec-prior boundary (docs/PRIORS.md) lands as one unit
            prec_size = lib.mp_priors_record_size()
            lib.mp_decoder_open_priors.restype = ct.c_void_p
            lib.mp_decoder_open_priors.argtypes = [
                ct.c_char_p, ct.c_int, ct.c_char_p, ct.c_int,
            ]
            lib.mp_priors_next_batch.restype = ct.c_long
            lib.mp_priors_next_batch.argtypes = [
                ct.c_void_p, ct.POINTER(MPPriorsFrame), ct.c_long,
                ct.POINTER(ct.c_int32), ct.c_long, ct.c_char_p, ct.c_int,
            ]
            lib.mp_priors_close.restype = None
            lib.mp_priors_close.argtypes = [ct.c_void_p]
        except AttributeError as exc:
            raise MediaError(
                f"libpcmedia.so predates the codec-prior boundary; rebuild "
                f"with `make -B -C {_NATIVE_DIR}`"
            ) from exc
        if prec_size != ct.sizeof(MPPriorsFrame) or \
                prec_size != PRIORS_DTYPE.itemsize:
            raise MediaError(
                f"libpcmedia.so priors-record ABI mismatch (native "
                f"{prec_size} != ctypes {ct.sizeof(MPPriorsFrame)} / numpy "
                f"{PRIORS_DTYPE.itemsize}); rebuild with "
                f"`make -B -C {_NATIVE_DIR}`"
            )
        lib.mp_encoder_open.restype = ct.c_void_p
        lib.mp_encoder_open.argtypes = [
            ct.c_char_p, ct.c_char_p, ct.c_int, ct.c_int, ct.c_char_p,
            ct.c_int, ct.c_int, ct.c_int64, ct.c_int64, ct.c_int64, ct.c_int64,
            ct.c_int, ct.c_int, ct.c_int, ct.c_char_p, ct.c_int, ct.c_char_p,
            ct.c_char_p, ct.c_int, ct.c_int, ct.c_int64, ct.c_char_p, ct.c_int,
        ]
        lib.mp_encoder_write_video.restype = ct.c_int
        lib.mp_encoder_write_video.argtypes = [
            ct.c_void_p, u8p, u8p, u8p, u8p, ct.c_char_p, ct.c_int,
        ]
        lib.mp_encoder_write_audio.restype = ct.c_int
        lib.mp_encoder_write_audio.argtypes = [
            ct.c_void_p, i16p, ct.c_long, ct.c_char_p, ct.c_int,
        ]
        lib.mp_encoder_close.restype = ct.c_int
        lib.mp_encoder_close.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_int]
        lib.mp_sws_scale_plane.restype = ct.c_int
        lib.mp_sws_scale_plane.argtypes = [
            u8p, ct.c_int, ct.c_int, u8p, ct.c_int, ct.c_int, ct.c_int,
            ct.c_double, ct.c_double, ct.c_char_p, ct.c_int,
        ]
        lib.mp_sws_scale_yuv.restype = ct.c_int
        lib.mp_sws_scale_yuv.argtypes = [
            u8p, u8p, u8p, ct.c_int, ct.c_int, ct.c_char_p,
            u8p, u8p, u8p, ct.c_int, ct.c_int, ct.c_char_p,
            ct.c_int, ct.c_char_p, ct.c_int,
        ]
        lib.mp_extract_annexb.restype = ct.c_int
        lib.mp_extract_annexb.argtypes = [
            ct.c_char_p, ct.c_char_p, ct.c_char_p, ct.c_char_p, ct.c_int,
        ]
        lib.mp_extract_ivf.restype = ct.c_int
        lib.mp_extract_ivf.argtypes = [
            ct.c_char_p, ct.c_char_p, ct.c_char_p, ct.c_int,
        ]
        lib.mp_remux.restype = ct.c_int
        lib.mp_remux.argtypes = [
            ct.c_char_p, ct.c_char_p, ct.c_char_p, ct.c_char_p, ct.c_int,
        ]
        lib.mp_concat.restype = ct.c_int
        lib.mp_concat.argtypes = [
            ct.POINTER(ct.c_char_p), ct.c_int, ct.c_char_p, ct.c_char_p,
            ct.c_int,
        ]
        lib.mp_version.restype = ct.c_char_p
        _lib = lib
        return lib


def _err_buf() -> ct.Array:
    return ct.create_string_buffer(512)


def _np_u8p(arr: np.ndarray):
    """Raw byte pointer to a contiguous array (any dtype — the native side
    addresses planes in bytes)."""
    if arr is None:
        return None
    assert arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data_as(ct.POINTER(ct.c_uint8))


def version() -> str:
    lib = ensure_loaded()
    return lib.mp_version().decode()


def probe(path: str, coded_dims: bool = False) -> dict:
    """Container + stream info (the ffprobe -show_streams/-show_format
    replacement). `coded_dims=True` additionally resolves the first video
    stream's decoder coded_width/coded_height (costs a first-frame
    decode — the SRC sidecar path wants it, per-segment probes don't);
    otherwise coded dims mirror the display dims."""
    lib = ensure_loaded()
    fmt = MPFormatInfo()
    cap = 64
    want = 1 if coded_dims else 0
    streams = (MPStreamInfo * cap)()
    err = _err_buf()
    n = lib.mp_probe(path.encode(), ct.byref(fmt), streams, cap, want, err, 512)
    if n < 0:
        raise MediaError(f"probe({path}): {err.value.decode()}")
    if fmt.nb_streams > cap:
        cap = int(fmt.nb_streams)
        streams = (MPStreamInfo * cap)()
        n = lib.mp_probe(
            path.encode(), ct.byref(fmt), streams, cap, want, err, 512
        )
        if n < 0:
            raise MediaError(f"probe({path}): {err.value.decode()}")
    out_streams = []
    for i in range(n):
        s = streams[i]
        d = {
            "index": s.stream_index,
            "codec_type": "video" if s.codec_type == 0 else "audio",
            "codec_name": s.codec_name.decode(),
            "duration": s.duration,
            "nb_frames": s.nb_frames,
            "bit_rate": s.bit_rate,
            "time_base": (s.tb_num, s.tb_den),
            "profile": s.profile.decode(),
        }
        if s.codec_type == 0:
            d.update(
                width=s.width,
                height=s.height,
                coded_width=s.coded_width,
                coded_height=s.coded_height,
                pix_fmt=s.pix_fmt.decode(),
                r_frame_rate=f"{s.fps_num}/{s.fps_den}",
                avg_frame_rate=f"{s.avg_fps_num}/{s.avg_fps_den}",
            )
        else:
            d.update(
                sample_rate=s.sample_rate,
                channels=s.channels,
                sample_fmt=s.sample_fmt.decode(),
            )
        out_streams.append(d)
    return {
        "format": {
            "format_name": fmt.format_name.decode(),
            "duration": fmt.duration,
            "bit_rate": fmt.bit_rate,
            "size": fmt.file_size,
            "nb_streams": fmt.nb_streams,
        },
        "streams": out_streams,
    }


def scan_packets(path: str, codec_type: str = "video") -> dict:
    """Per-packet size/pts/dts/duration/keyflag arrays (the ffprobe
    -show_packets replacement; reference lib/ffmpeg.py:636-769)."""
    lib = ensure_loaded()
    _SCAN_PASSES.labels(op="packets").inc()
    ctype = 0 if codec_type == "video" else 1
    cap = 1 << 16
    while True:
        sizes = np.zeros(cap, np.int64)
        pts = np.zeros(cap, np.float64)
        dts = np.zeros(cap, np.float64)
        dur = np.zeros(cap, np.float64)
        key = np.zeros(cap, np.int8)
        err = _err_buf()
        n = lib.mp_scan_packets(
            path.encode(), ctype,
            sizes.ctypes.data_as(ct.POINTER(ct.c_int64)),
            pts.ctypes.data_as(ct.POINTER(ct.c_double)),
            dts.ctypes.data_as(ct.POINTER(ct.c_double)),
            dur.ctypes.data_as(ct.POINTER(ct.c_double)),
            key.ctypes.data_as(ct.POINTER(ct.c_int8)),
            cap, err, 512,
        )
        if n < 0:
            raise MediaError(f"scan_packets({path}): {err.value.decode()}")
        if n <= cap:
            return {
                "size": sizes[:n].copy(),
                "pts_time": pts[:n].copy(),
                "dts_time": dts[:n].copy(),
                "duration_time": dur[:n].copy(),
                "key": key[:n].copy(),
            }
        cap = int(n) + 1024


def scan_packets_all(path: str) -> dict:
    """Both streams' packet arrays from ONE demux pass: {"video": <same
    dict shape as scan_packets>, "audio": <same, or None when the
    container has no audio stream>}. The shared post-encode scan's
    native leg (io/sharedscan.py); falls back to two scan_packets
    passes when the loaded .so predates the symbol."""
    lib = ensure_loaded()
    if not hasattr(lib, "mp_scan_packets_all"):
        out = {"video": scan_packets(path, "video")}
        try:
            out["audio"] = scan_packets(path, "audio")
        except MediaError:
            out["audio"] = None
        return out
    _SCAN_PASSES.labels(op="packets_all").inc()
    v_cap = a_cap = 1 << 16
    while True:
        v = {k: np.zeros(v_cap, dt) for k, dt in _PACKET_FIELDS}
        a = {k: np.zeros(a_cap, dt) for k, dt in _PACKET_FIELDS}
        nv = ct.c_long(0)
        na = ct.c_long(0)
        err = _err_buf()
        ret = lib.mp_scan_packets_all(
            path.encode(),
            v["size"].ctypes.data_as(ct.POINTER(ct.c_int64)),
            v["pts_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            v["dts_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            v["duration_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            v["key"].ctypes.data_as(ct.POINTER(ct.c_int8)),
            v_cap, ct.byref(nv),
            a["size"].ctypes.data_as(ct.POINTER(ct.c_int64)),
            a["pts_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            a["dts_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            a["duration_time"].ctypes.data_as(ct.POINTER(ct.c_double)),
            a["key"].ctypes.data_as(ct.POINTER(ct.c_int8)),
            a_cap, ct.byref(na),
            err, 512,
        )
        if ret < 0:
            raise MediaError(f"scan_packets_all({path}): {err.value.decode()}")
        if nv.value <= v_cap and na.value <= a_cap:
            return {
                "video": {k: arr[: nv.value].copy() for k, arr in v.items()},
                "audio": None if na.value < 0 else {
                    k: arr[: na.value].copy() for k, arr in a.items()
                },
            }
        v_cap = max(v_cap, int(nv.value) + 1024)
        a_cap = max(a_cap, int(na.value) + 1024)


_PACKET_FIELDS = (
    ("size", np.int64),
    ("pts_time", np.float64),
    ("dts_time", np.float64),
    ("duration_time", np.float64),
    ("key", np.int8),
)


def sws_scale_plane(
    src: np.ndarray, dw: int, dh: int, flags: int = SWS_LANCZOS,
    param0: float = 0.0, param1: float = 0.0,
) -> np.ndarray:
    """Scale a single 8-bit plane through libswscale — the golden oracle the
    TPU resize kernels are tested against."""
    lib = ensure_loaded()
    assert src.dtype == np.uint8 and src.ndim == 2
    src = np.ascontiguousarray(src)
    dst = np.zeros((dh, dw), np.uint8)
    err = _err_buf()
    ret = lib.mp_sws_scale_plane(
        _np_u8p(src), src.shape[1], src.shape[0], _np_u8p(dst), dw, dh,
        flags, param0, param1, err, 512,
    )
    if ret < 0:
        raise MediaError(f"sws_scale_plane: {err.value.decode()}")
    return dst


def sws_scale_frames(
    src: np.ndarray, dw: int, dh: int, flags: int = SWS_LANCZOS,
) -> np.ndarray:
    """Scale a [N, H, W] uint8 plane stack in ONE native call through one
    shared SwsContext (filter tables built once per chunk)."""
    lib = ensure_loaded()
    assert src.dtype == np.uint8 and src.ndim == 3
    src = np.ascontiguousarray(src)
    out = np.empty((src.shape[0], dh, dw), np.uint8)
    err = _err_buf()
    ret = lib.mp_sws_scale_frames(
        _np_u8p(src), src.shape[2], src.shape[1], _np_u8p(out), dw, dh,
        src.shape[0], flags, err, 512,
    )
    if ret < 0:
        raise MediaError(f"sws_scale_frames: {err.value.decode()}")
    return out


def sws_scale_yuv(
    planes: tuple, sw: int, sh: int, src_fmt: str,
    dw: int, dh: int, dst_fmt: str, flags: int = SWS_LANCZOS,
) -> tuple:
    """Full planar-YUV rescale via swscale (reference `scale=` filter)."""
    lib = ensure_loaded()
    sy, su, sv = (np.ascontiguousarray(p) if p is not None else None for p in planes)
    sub_w = 2 if "420" in dst_fmt or "422" in dst_fmt else 1
    sub_h = 2 if "420" in dst_fmt else 1
    dst_dtype = np.uint16 if "10" in dst_fmt and dst_fmt != "yuv410p" else np.uint8
    dy = np.zeros((dh, dw), dst_dtype)
    du = np.zeros((dh // sub_h, dw // sub_w), dst_dtype)
    dv = np.zeros_like(du)
    err = _err_buf()
    ret = lib.mp_sws_scale_yuv(
        _np_u8p(sy), _np_u8p(su), _np_u8p(sv), sw, sh, src_fmt.encode(),
        _np_u8p(dy), _np_u8p(du), _np_u8p(dv), dw, dh, dst_fmt.encode(),
        flags, err, 512,
    )
    if ret < 0:
        raise MediaError(f"sws_scale_yuv: {err.value.decode()}")
    return dy, du, dv


def remux(video_path: str, out_path: str, audio_path: str = "") -> None:
    """Stream-copy remux: video stream from `video_path` (+ audio stream from
    `audio_path`, which may equal `video_path`) into `out_path` — no
    transcoding (reference `ffmpeg -i V [-i A] -c copy OUT`,
    lib/downloader.py:786-871)."""
    lib = ensure_loaded()
    err = _err_buf()
    ret = lib.mp_remux(
        video_path.encode(), audio_path.encode(), out_path.encode(), err, 512
    )
    if ret < 0:
        raise MediaError(f"remux {video_path} -> {out_path}: {err.value.decode()}")


def concat_video(paths: list, out_path: str) -> None:
    """Sequential stream-copy concat of the video streams of `paths` with
    timestamp offsetting — the reference's concat-demuxer pass
    (`ffmpeg -f concat -c copy`, lib/ffmpeg.py:1094-1100) as one native
    call. Inputs must share codec parameters (the per-segment AVPVS tmp
    renders do). Audio is merged afterwards with remux()."""
    lib = ensure_loaded()
    err = _err_buf()
    arr = (ct.c_char_p * len(paths))(*[p.encode() for p in paths])
    if lib.mp_concat(arr, len(paths), out_path.encode(), err, 512) < 0:
        raise MediaError(f"concat -> {out_path}: {err.value.decode()}")


def extract_annexb(path: str, bsf_name: str, out_path: str) -> None:
    lib = ensure_loaded()
    _SCAN_PASSES.labels(op="annexb").inc()
    err = _err_buf()
    if lib.mp_extract_annexb(path.encode(), bsf_name.encode(), out_path.encode(), err, 512) < 0:
        raise MediaError(f"extract_annexb({path}): {err.value.decode()}")


def extract_ivf(path: str, out_path: str) -> None:
    lib = ensure_loaded()
    _SCAN_PASSES.labels(op="ivf").inc()
    err = _err_buf()
    if lib.mp_extract_ivf(path.encode(), out_path.encode(), err, 512) < 0:
        raise MediaError(f"extract_ivf({path}): {err.value.decode()}")


class PriorsBufferTooSmall(MediaError):
    """A single frame carries more MV rows than the caller's buffer holds;
    the frame is parked natively — grow the buffer and call again, nothing
    is lost."""


def priors_open(path: str, threads: int = 0) -> int:
    """Open `path` for codec-prior extraction (MV/QP/frame-type side data;
    docs/PRIORS.md). Returns an opaque handle for priors_next_batch /
    priors_close."""
    lib = ensure_loaded()
    _SCAN_PASSES.labels(op="priors").inc()
    err = _err_buf()
    handle = lib.mp_decoder_open_priors(path.encode(), threads, err, 512)
    if not handle:
        raise MediaError(f"open_priors({path}): {err.value.decode()}")
    return handle


def priors_next_batch(handle: int, records: np.ndarray,
                      mv_rows: np.ndarray) -> int:
    """Fill up to len(records) per-frame prior records (PRIORS_DTYPE) and
    their MV rows ([cap, MV_FIELDS] int32, frame order — records'
    `mv_count` delimits per-frame spans) in ONE native call / one GIL
    release. Returns frames filled; 0 = EOF. Raises PriorsBufferTooSmall
    when one frame alone overflows `mv_rows` (retry with a bigger block)."""
    lib = ensure_loaded()
    assert records.dtype == PRIORS_DTYPE and records.flags["C_CONTIGUOUS"]
    assert mv_rows.dtype == np.int32 and mv_rows.ndim == 2 \
        and mv_rows.shape[1] == MV_FIELDS and mv_rows.flags["C_CONTIGUOUS"]
    err = _err_buf()
    n = lib.mp_priors_next_batch(
        handle,
        records.ctypes.data_as(ct.POINTER(MPPriorsFrame)), records.shape[0],
        mv_rows.ctypes.data_as(ct.POINTER(ct.c_int32)), mv_rows.shape[0],
        err, 512,
    )
    if n == -2:
        raise PriorsBufferTooSmall(err.value.decode())
    if n < 0:
        raise MediaError(f"priors_next_batch: {err.value.decode()}")
    return int(n)


def priors_close(handle: int) -> None:
    lib = ensure_loaded()
    if handle:
        lib.mp_priors_close(handle)


def decode_audio_s16(path: str, start: float = 0.0, duration: float = 0.0,
                     channels: int = 0):
    """Decode best audio stream to (samples[n, channels] int16, sample_rate).

    channels > 0 remixes to that count inside libswresample with the
    ffmpeg CLI's `-ac N` default matrix — e.g. channels=2 reproduces the
    reference's stereo downmix (audio_mux `-ac 2`, lib/ffmpeg.py:1284)
    exactly, 5.1 center/surround mixing and normalization included.
    0 keeps the file's native layout."""
    lib = ensure_loaded()
    err = _err_buf()
    rate = ct.c_int32()
    chans = ct.c_int32()
    n = lib.mp_decode_audio_s16_ch(
        path.encode(), start, duration, channels, None, 0, ct.byref(rate),
        ct.byref(chans), err, 512,
    )
    if n < 0:
        raise MediaError(f"decode_audio({path}): {err.value.decode()}")
    buf = np.zeros((int(n), max(1, chans.value)), np.int16)
    n2 = lib.mp_decode_audio_s16_ch(
        path.encode(), start, duration, channels,
        buf.ctypes.data_as(ct.POINTER(ct.c_int16)), n,
        ct.byref(rate), ct.byref(chans), err, 512,
    )
    if n2 < 0:
        raise MediaError(f"decode_audio({path}): {err.value.decode()}")
    return buf[: int(n2)], rate.value
