"""Probing built on the native boundary, with reference-compatible outputs.

Parity targets: reference lib/ffmpeg.py get_segment_info (:433-563),
get_src_info + .yaml sidecar cache (:566-633), get_video_frame_info /
get_audio_frame_info (:636-769), fix_durations VP9 patch (:718-741).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from fractions import Fraction
from typing import Optional

import numpy as np
import pandas as pd
import yaml

from . import medialib, sharedscan


def _select(info: dict, codec_type: str) -> Optional[dict]:
    return next(
        (s for s in info["streams"] if s["codec_type"] == codec_type), None
    )


def fix_video_profile_string(video_profile: str) -> str:
    """Normalize codec profile names for the .qchanges column exactly as
    the reference does (lib/ffmpeg.py:420-431): drop spaces/"Profile"/
    colons, High->Hi, Predictive->P (e.g. "Constrained Baseline" ->
    "ConstrainedBaseline", "High 4:4:4 Predictive" -> "Hi444P")."""
    for old, new in (
        (" ", ""),
        ("Profile", ""),
        ("High", "Hi"),
        (":", ""),
        ("Predictive", "P"),
    ):
        video_profile = video_profile.replace(old, new)
    return video_profile


class LibavProber:
    """The SrcProber implementation used outside tests (config/probe_api)."""

    def src_info(self, file_path: str, sidecar_path: Optional[str] = None) -> dict:
        """Video stream info with .yaml sidecar caching (reference
        ffmpeg.py:604-632; the sidecar is also written by util SRC analysis).
        """
        if sidecar_path and os.path.isfile(sidecar_path):
            with open(sidecar_path) as f:
                ydata = yaml.safe_load(f)
            if ydata and "get_src_info" in ydata:
                return ydata["get_src_info"]
        info = medialib.probe(file_path, coded_dims=True)
        v = _select(info, "video")
        if v is None:
            raise medialib.MediaError(f"no video stream in {file_path}")
        data = dict(v)
        data["video_duration"] = v["duration"]
        if sidecar_path:
            scan = sharedscan.get_scan(file_path)
            sizes = {
                "v": int(np.sum(scan["video"]["size"])),
                "a": int(np.sum(scan["audio"]["size"]))
                if scan["audio"] is not None else 0,
            }
            from ..utils.fsio import atomic_write_text

            atomic_write_text(sidecar_path, yaml.safe_dump(
                {"md5sum": "-", "get_stream_size": sizes,
                 "get_src_info": data},
                default_flow_style=False,
            ))
        return data

    def duration(self, file_path: str, sidecar_path: Optional[str] = None) -> float:
        info = self.src_info(file_path, sidecar_path)
        return float(info.get("video_duration") or info.get("duration") or 0.0)


def get_segment_info(
    file_path: str,
    filename: Optional[str] = None,
    target_video_bitrate=None,
) -> OrderedDict:
    """Segment info for .qchanges rows (reference :433-563, same keys)."""
    info = medialib.probe(file_path)
    v = _select(info, "video")
    a = _select(info, "audio")
    if v is None:
        raise medialib.MediaError(f"No video stream found in {file_path}")

    video_pk = None  # lazily demuxed at most once

    video_duration = float(v["duration"]) if v["duration"] else 0.0
    if not video_duration:
        # derive from packet timing (reference :487-498)
        video_pk = sharedscan.video(file_path)
        dts = video_pk["dts_time"]
        dur = video_pk["duration_time"]
        valid = ~np.isnan(dts)
        if valid.any():
            last = np.nonzero(valid)[0][-1]
            d = dur[last] if not np.isnan(dur[last]) else 0.0
            video_duration = float(dts[last] + d)
    if not video_duration:
        raise medialib.MediaError(f"Video duration of {file_path} is zero")

    if v["bit_rate"]:
        video_bitrate = round(float(v["bit_rate"]) / 1024.0, 2)
    else:
        if video_pk is None:
            video_pk = sharedscan.video(file_path)
        stream_size = int(np.sum(video_pk["size"]))
        video_bitrate = round((stream_size * 8 / 1024.0) / video_duration, 2)

    ret = OrderedDict(
        [
            ("segment_filename", filename or os.path.basename(file_path)),
            ("file_size", info["format"]["size"]),
            ("video_duration", video_duration),
            ("video_frame_rate", float(Fraction(v["r_frame_rate"]))),
            ("video_bitrate", video_bitrate),
            ("video_target_bitrate", target_video_bitrate if target_video_bitrate is not None else 0),
            ("video_width", v["width"]),
            ("video_height", v["height"]),
            ("video_codec", v["codec_name"]),
            ("video_profile", fix_video_profile_string(v.get("profile", ""))),
        ]
    )
    if a is not None:
        audio_duration = float(a["duration"]) if a["duration"] else 0.0
        if a["bit_rate"]:
            audio_bitrate = round(float(a["bit_rate"]) / 1024.0, 2)
        else:
            stream_size = int(np.sum(sharedscan.audio(file_path)["size"]))
            audio_bitrate = (
                round((stream_size * 8 / 1024.0) / audio_duration, 2)
                if audio_duration
                else 0.0
            )
        ret.update(
            OrderedDict(
                [
                    ("audio_duration", audio_duration),
                    ("audio_sample_rate", a["sample_rate"]),
                    ("audio_codec", a["codec_name"]),
                    ("audio_bitrate", audio_bitrate),
                ]
            )
        )
    return ret


def _fix_durations(dts: np.ndarray, duration: np.ndarray) -> np.ndarray:
    """Estimate missing packet durations from DTS deltas (the VP9 fix,
    reference :718-741), vectorized."""
    out = duration.copy()
    missing = np.isnan(out)
    if not missing.any():
        return out
    deltas = np.round(np.diff(dts), 6)
    fill = missing[:-1]
    out[:-1][fill] = deltas[fill]
    if np.isnan(out[-1]):
        prev = out[~np.isnan(out)]
        if prev.size:
            out[-1] = prev[-1]
    return out


def get_video_frame_info(file_path: str, segment_name: Optional[str] = None) -> pd.DataFrame:
    """Per-packet frame table in decoding order (reference :636-715):
    columns segment/index/frame_type/dts/size/duration. Routed through
    the shared post-encode scan: when p01 primed the file this costs no
    bitstream pass (io/sharedscan.py)."""
    pk = sharedscan.video(file_path)
    n = len(pk["size"])
    duration = _fix_durations(pk["dts_time"], pk["duration_time"])
    return pd.DataFrame(
        {
            "segment": [segment_name or os.path.basename(file_path)] * n,
            "index": np.arange(n),
            "frame_type": np.where(pk["key"] == 1, "I", "Non-I"),
            "dts": pk["dts_time"],
            "size": pk["size"],
            "duration": duration,
        }
    )


def get_audio_frame_info(file_path: str, segment_name: Optional[str] = None) -> pd.DataFrame:
    """Audio packet table (reference :744-769): segment/index/dts/size/
    duration. Shared-scan routed like get_video_frame_info."""
    pk = sharedscan.audio(file_path)
    n = len(pk["size"])
    return pd.DataFrame(
        {
            "segment": [segment_name or os.path.basename(file_path)] * n,
            "index": np.arange(n),
            "dts": pk["dts_time"],
            "size": pk["size"],
            "duration": _fix_durations(pk["dts_time"], pk["duration_time"]),
        }
    )
