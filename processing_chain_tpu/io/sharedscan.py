"""One shared post-encode packet scan per written file.

p02 metadata (frame sizes/types via `io.probe`), priors extraction
bookkeeping, and the serve-plane complexity features all need the same
per-packet facts — size, pts/dts, duration, keyflag — about segments p01
just wrote. Before this module each consumer paid its own demux walk
(`medialib.scan_packets` twice per segment for video+audio, again for
bitrates, again in `tools.complexity`). Here every consumer shares ONE
`medialib.scan_packets_all` pass per (path, size, mtime_ns) signature:
p01's encode tail primes the cache the moment a segment lands
(models/segments.py, PC_SCAN_PRIME) and p02/priors/serve read it back
without touching the bitstream.

The stat-signature trust model is the same as store.keys.DigestCache
(make/ninja-style: a rewrite preserving size and mtime_ns is
indistinguishable by design). The cache is bounded and process-local —
it is a decode-once accelerator, not a store; cold reads simply scan.

Byte-determinism: consumers receive exactly the arrays
`medialib.scan_packets` would have produced (one demux visits the same
packets in the same order), so p02 outputs and priors sidecars hash
identically with or without a warm cache — PC_PLAN_DEBUG holds.
"""

from __future__ import annotations

import os

from .. import telemetry as tm
from ..utils import lockdebug
from . import medialib

#: one entry per written segment in flight; a full database pass over
#: far more segments degrades to LRU misses, never unbounded memory
_MAX_ENTRIES = 256

_lock = lockdebug.make_lock("sharedscan")
_cache: dict[str, dict] = {}  # guarded-by: _lock (insertion order = LRU)

_HITS = tm.counter(
    "chain_io_sharedscan_hits_total",
    "shared packet-scan cache hits — a demux pass a consumer did NOT pay",
)
_MISSES = tm.counter(
    "chain_io_sharedscan_misses_total",
    "shared packet-scan cache misses — one scan_packets_all pass each",
)


def _stat_key(path: str, st: os.stat_result) -> str:
    return f"{path}|{st.st_size}|{st.st_mtime_ns}"


def get_scan(path: str) -> dict:
    """The file's full packet map from one demux pass: ``{"video":
    {size, pts_time, dts_time, duration_time, key}, "audio": <same or
    None>}``. Served from the stat-keyed cache when the file is
    unchanged since the last scan; raises MediaError like scan_packets
    when the file has no video stream."""
    path = os.path.abspath(path)
    try:
        key = _stat_key(path, os.stat(path))
    except OSError:
        # unstattable path: let the native open raise its MediaError —
        # consumers see exactly the error scan_packets would have given
        _MISSES.inc()
        return medialib.scan_packets_all(path)
    with _lock:
        hit = _cache.get(key)
        if hit is not None:
            _cache.pop(key)
            _cache[key] = hit  # refresh LRU position
    if hit is not None:
        _HITS.inc()
        return hit
    _MISSES.inc()
    scan = medialib.scan_packets_all(path)  # outside the lock: demux is slow
    with _lock:
        _cache[key] = scan
        while len(_cache) > _MAX_ENTRIES:
            _cache.pop(next(iter(_cache)))
    return scan


def prime(path: str) -> None:
    """Scan `path` into the cache now (p01's encode tail calls this right
    after a segment lands, while the file is still in page cache)."""
    get_scan(path)


def video(path: str) -> dict:
    """The video stream's packet arrays (scan_packets parity)."""
    return get_scan(path)["video"]


def audio(path: str) -> dict:
    """The audio stream's packet arrays; raises MediaError when the
    container has no audio stream (scan_packets parity)."""
    out = get_scan(path)["audio"]
    if out is None:
        raise medialib.MediaError(f"scan_packets({path}): no such stream")
    return out


def invalidate(path: str) -> None:
    """Drop every cached entry for `path` (any stat signature)."""
    path = os.path.abspath(path)
    prefix = f"{path}|"
    with _lock:
        for key in [k for k in _cache if k.startswith(prefix)]:
            _cache.pop(key)


def clear() -> None:
    """Drop the whole cache (tests)."""
    with _lock:
        _cache.clear()
