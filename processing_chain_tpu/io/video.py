"""High-level video decode/encode on top of the native boundary.

Frames cross this boundary as numpy planar YUV (dict of 2-D plane arrays),
which is the host-side staging format for device transfer: the ops layer
stacks them into (T, H, W) tensors per plane.
"""

from __future__ import annotations

import ctypes as ct
import os
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from .. import telemetry as tm
from . import bufpool, faults, medialib
from .medialib import MediaError, MPVideoDesc

_IO_BATCH = tm.counter(
    "chain_io_batch_calls_total",
    "chunk-granular native I/O crossings (one GIL release per chunk)",
    ("op",),
)
_IO_BATCH_DECODE = _IO_BATCH.labels(op="decode")
_IO_BATCH_ENCODE = _IO_BATCH.labels(op="encode")
_DECODER_OPENS = tm.counter(
    "chain_io_decoder_opens_total",
    "VideoReader decoder opens — each is one full decode pass over a "
    "container, so the fused chain's 'one decode per SRC' claim "
    "(PC_FUSE_P04, models/fused) is a measurable invariant, not a "
    "code-review assertion",
)


@dataclass
class Frame:
    """One decoded frame: PLANAR planes in native bit depth (uint8 or
    uint16), each [h, w] samples of one component. Packed container
    formats (PACKED_FORMATS) are deinterleaved by VideoReader before a
    Frame is built, so `.y` is always pure luma."""

    planes: tuple[np.ndarray, ...]
    pts: float
    pix_fmt: str

    @property
    def y(self) -> np.ndarray:
        return self.planes[0]

    @property
    def u(self) -> Optional[np.ndarray]:
        return self.planes[1] if len(self.planes) > 1 else None

    @property
    def v(self) -> Optional[np.ndarray]:
        return self.planes[2] if len(self.planes) > 2 else None


def iter_stacked_frame_chunks(
    frames, chunk: int,
) -> Iterator[list[np.ndarray]]:
    """Per-frame fallback chunker: accumulate Frames and np.stack each
    plane into [T, H, W] blocks of up to `chunk`. The single definition
    behind VideoReader's PC_HOST_BATCH=0 path AND engine.prefetch's
    generic-iterable path — the parity baseline the batched decode is
    tested against."""
    buf: list = []
    for frame in frames:
        buf.append(frame)
        if len(buf) == chunk:
            yield [
                np.stack([f.planes[p] for f in buf])
                for p in range(len(buf[0].planes))
            ]
            buf = []
    if buf:
        yield [
            np.stack([f.planes[p] for f in buf])
            for p in range(len(buf[0].planes))
        ]


#: single-plane interleaved formats the chain can encounter (the PC CPVS
#: default is uyvy422) mapped to their (y, u, v) byte offsets within each
#: 4-byte macropixel (y repeats every 2 bytes, u/v every 4); gray etc.
#: are single-plane but planar. VideoReader deinterleaves these on read.
PACKED_FORMATS = {
    "uyvy422": (1, 0, 2),   # U Y V Y
    "yuyv422": (0, 1, 3),   # Y U Y V
    "yvyu422": (0, 3, 1),   # Y V Y U
}


class VideoReader:
    """Sequential decoder with [start, start+duration) trim — the native
    replacement for the reference's `ffmpeg -ss X -t D -i …` decode commands
    (lib/ffmpeg.py:877, :948, :1037)."""

    def __init__(self, path: str, start: float = 0.0, duration: float = 0.0,
                 threads: int = 0) -> None:
        """threads: decoder thread_count (0 = auto = one per core). Frame
        threading overlaps the codec's per-frame work inside the batched
        decode loop; pin to 1 for strictly serial decode."""
        self.path = path
        self._start = float(start)
        self._window = float(duration)
        #: media-fault hooks (io/faults, docs/ROBUSTNESS.md): one env
        #: lookup per OPEN; None in production — nothing per frame
        self._faults = faults.decoder_faults(path)
        self._deadline = faults.media_deadline_s()
        #: lazy persistent deadline worker (faults.GuardWorker); only
        #: ever created when a deadline is set
        self._guard_worker = None
        #: stream frame cursor — every decode error names the frame it
        #: died at, not just the file
        self._frames_out = 0
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        # the OPEN is a native crossing too: a hostile container can
        # wedge the demuxer's probe before a single frame exists
        self._h = self._guard(
            lambda: lib.mp_decoder_open_t(
                path.encode(), start, duration, threads, err, 512
            ),
            op="decoder-open",
        )
        if not self._h:
            raise MediaError(f"open {path}: {err.value.decode()}")
        desc = MPVideoDesc()
        if lib.mp_decoder_desc(self._h, ct.byref(desc)) < 0:
            lib.mp_decoder_close(self._h)
            self._h = None
            raise MediaError(f"{path}: could not probe decoder geometry")
        self.width = desc.width
        self.height = desc.height
        #: the container/decoder pixel format as probed (e.g. uyvy422)
        self.container_pix_fmt = desc.pix_fmt.decode()
        # packed formats deinterleave AT THIS BOUNDARY: every consumer
        # downstream (resize, SI/TI, metrics, complexity, re-encode)
        # holds a planar contract, exactly as the reference's consumers
        # see planar frames because ffmpeg converts transparently. The
        # reader therefore presents packed 422 as yuv422p planes and
        # reports the PLANAR view as pix_fmt.
        self._packed_offsets = PACKED_FORMATS.get(self.container_pix_fmt)
        if self._packed_offsets is not None and self.width % 2:
            # an odd-width packed row carries a ceil'd half macropixel;
            # deinterleaving it would yield planes wider than reported
            lib.mp_decoder_close(self._h)
            self._h = None
            raise MediaError(
                f"{path}: odd-width packed {self.container_pix_fmt} is "
                "unsupported (chain invariant: even dims)"
            )
        self.pix_fmt = (
            "yuv422p" if self._packed_offsets is not None
            else self.container_pix_fmt
        )
        self.fps = desc.fps_num / max(1, desc.fps_den)
        self.fps_fraction = (desc.fps_num, desc.fps_den)
        self.duration = desc.duration
        # raw (native) plane geometry used for the decode buffers; plane_w
        # is SAMPLES per row (2x pixel width for packed 422 rows)
        self._raw_plane_shapes = [
            (desc.plane_h[p], desc.plane_w[p]) for p in range(desc.planes)
        ]
        if self._packed_offsets is not None:
            self.n_planes = 3
            self.plane_shapes = [
                (self.height, self.width),
                (self.height, self.width // 2),
                (self.height, self.width // 2),
            ]
        elif desc.planes >= 3 or self.container_pix_fmt.startswith("gray"):
            # fully planar (Y/U/V separate) or single-component
            self.n_planes = desc.planes
            self.plane_shapes = list(self._raw_plane_shapes)
        else:
            # 1-2 plane multi-component layouts (nv12 semi-planar, rgb24
            # packed, ...) would silently violate the planar Frame
            # contract downstream — fail loudly at the boundary
            lib.mp_decoder_close(self._h)
            self._h = None
            raise MediaError(
                f"{path}: unsupported non-planar pixel format "
                f"{self.container_pix_fmt!r} (planar YUV/gray or packed "
                f"422 expected)"
            )
        self.dtype = np.uint16 if desc.bytes_per_sample == 2 else np.uint8
        if self._packed_offsets is not None and self.dtype != np.uint8:
            # _deinterleave's ::2/::4 offsets are BYTE positions within a
            # 4-byte macropixel; a 16-bit packed format (e.g. y210) would
            # silently shear planes instead of deinterleaving them
            lib.mp_decoder_close(self._h)
            self._h = None
            raise MediaError(
                f"{path}: packed format {self.container_pix_fmt!r} with "
                f"{desc.bytes_per_sample} bytes/sample unsupported (packed "
                f"deinterleave is 8-bit only)"
            )
        if tm.enabled():
            _DECODER_OPENS.inc()

    def _guard(self, fn, op: str, frame: Optional[int] = None):
        """Run one native crossing under the PC_MEDIA_DEADLINE_S budget
        (direct call when unset). Crossings reuse ONE persistent guard
        worker per reader (faults.GuardWorker — a thread per crossing
        would tax the per-chunk hot path). An expiry POISONS this
        reader: the abandoned worker may still be inside the native
        call, so the handle is deliberately leaked — close() becomes a
        no-op — and the reader refuses further use."""
        if self._deadline is None:
            return fn()
        if self._guard_worker is None:
            self._guard_worker = faults.GuardWorker(
                f"media-guard:{os.path.basename(self.path)}")
        try:
            return faults.guarded_call(
                fn, self._deadline, op=op, path=self.path, frame=frame,
                worker=self._guard_worker,
            )
        except faults.MediaDeadlineExpired:
            self._h = None
            self._guard_worker = None  # wedged: abandoned with the call
            raise

    def _deinterleave(self, raw: np.ndarray) -> tuple[np.ndarray, ...]:
        """Packed 422 row bytes [h, 2w] → planar (y, u, v) copies,
        table-driven from PACKED_FORMATS."""
        y_off, u_off, v_off = self._packed_offsets
        return (
            np.ascontiguousarray(raw[..., y_off::2]),
            np.ascontiguousarray(raw[..., u_off::4]),
            np.ascontiguousarray(raw[..., v_off::4]),
        )

    def _deinterleave_chunk(self, raw: np.ndarray, out: list) -> None:
        """Chunk-wise packed-422 deinterleave: one strided pass per plane
        over the whole [N, h, 2w] block into pre-allocated planar blocks
        (the per-frame path pays 3 allocations + 3 passes per FRAME)."""
        y_off, u_off, v_off = self._packed_offsets
        np.copyto(out[0], raw[..., y_off::2])
        np.copyto(out[1], raw[..., u_off::4])
        np.copyto(out[2], raw[..., v_off::4])

    def _decode_batch_into(self, blocks: list, max_frames: int):
        """ONE native crossing: decode up to `max_frames` frames into the
        caller's raw-geometry plane blocks ([N, h, w] C-contiguous, one
        per decoder plane). Returns (n_decoded, pts[n_decoded])."""
        if not self._h:
            raise MediaError(f"{self.path}: reader is closed")
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        u8p = ct.POINTER(ct.c_uint8)
        for b, shape in zip(blocks, self._raw_plane_shapes):
            assert b.flags["C_CONTIGUOUS"] and b.dtype == self.dtype
            assert b.shape[0] >= max_frames and b.shape[1:] == shape
        if self._faults is not None:
            injected_eof = self._faults.check(max_frames)
            if injected_eof is not None:  # injected short read: silent EOF
                return 0, np.zeros(0, np.float64)
            # bound the window so a short-read delivers exactly its
            # promised frames before the injected EOF
            max_frames = self._faults.cap_frames(max_frames)
        pts = np.zeros(max_frames, np.float64)
        ptrs = [b.ctypes.data_as(u8p) for b in blocks]
        ptrs += [None] * (4 - len(ptrs))
        # the handle is BOUND before the crossing: a deadline expiry
        # nulls self._h to poison the reader, and the abandoned thread
        # must keep using the (deliberately leaked) live handle, not
        # discover a NULL mid-flight
        h = self._h

        def _native() -> int:
            if self._faults is not None:
                # the injected hang runs INSIDE the guarded crossing,
                # exactly where a real wedged decoder would sit
                self._faults.hang("decode")
            return lib.mp_decoder_next_batch(
                h, ptrs[0], ptrs[1], ptrs[2], ptrs[3], max_frames,
                pts.ctypes.data_as(ct.POINTER(ct.c_double)), err, 512,
            )

        n = self._guard(_native, op="decode", frame=self._frames_out)
        if n < 0:
            # forensics contract (docs/ROBUSTNESS.md): source path +
            # stream frame index + the native av_errstr text, bounded
            raise MediaError(
                f"decode {self.path} @frame {self._frames_out}: "
                f"{err.value.decode()[:500]}"
            )
        self._frames_out += int(n)
        if self._faults is not None:
            self._faults.advance(int(n))
        if tm.enabled():
            _IO_BATCH_DECODE.inc()
        return int(n), pts[: int(n)]

    def iter_chunks(
        self, chunk: int = 64, pool: Optional[bufpool.BufferPool] = None,
    ) -> Iterator[list]:
        """Stream the window as per-plane planar [T, H, W] stacks of up to
        `chunk` frames, decoded chunk-at-a-time through ONE native call
        each, into blocks from `pool`. Ownership of full blocks passes to
        the consumer (release via `pool.release(*chunk)` when the
        frames have been consumed — bufpool module docstring); the tail
        chunk yields trimmed views, which release ignores."""
        if not bufpool.host_batch_enabled():
            yield from self._iter_chunks_per_frame(chunk)
            return
        pool = pool or bufpool.DEFAULT_POOL
        packed = self._packed_offsets is not None
        while True:
            raw_blocks = [
                pool.acquire((chunk,) + shape, self.dtype)
                for shape in self._raw_plane_shapes
            ]
            try:
                n, _pts = self._decode_batch_into(raw_blocks, chunk)
            except faults.MediaDeadlineExpired:
                # the abandoned native call may still WRITE into these
                # blocks whenever it unwedges: recycling them would hand
                # scribble-prone memory to the next consumer.
                del raw_blocks  # chainlint: ownership-transfer (leaked deliberately with the poisoned handle — the abandoned native thread can still scribble into the blocks whenever it unwedges; docs/ROBUSTNESS.md)
                raise
            except BaseException:
                # a mid-stream decode failure (corrupt input, injected
                # fault) must not strand pooled blocks: the
                # media-crashcheck matrix asserts zero leaked blocks
                # across the whole corrupt corpus
                pool.release(*raw_blocks)
                raise
            if n == 0:
                pool.release(*raw_blocks)
                return
            if packed:
                planar = [
                    pool.acquire((n,) + shape, self.dtype)
                    for shape in self.plane_shapes
                ]
                self._deinterleave_chunk(raw_blocks[0][:n], planar)
                pool.release(*raw_blocks)
                yield planar
            else:
                yield raw_blocks if n == chunk else [
                    b[:n] for b in raw_blocks
                ]
            if n < chunk:
                return

    def _iter_chunks_per_frame(self, chunk: int) -> Iterator[list]:
        """Per-frame fallback (PC_HOST_BATCH=0): the parity baseline the
        batch path is tested against."""
        yield from iter_stacked_frame_chunks(self, chunk)

    def __iter__(self) -> Iterator[Frame]:
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        u8p = ct.POINTER(ct.c_uint8)
        while True:
            if not self._h:
                raise MediaError(f"{self.path}: reader is closed")
            if self._faults is not None and \
                    self._faults.check(1) is not None:
                return  # injected short read: silent EOF
            planes = tuple(
                np.zeros(shape, self.dtype) for shape in self._raw_plane_shapes
            )
            ptrs = [p.ctypes.data_as(u8p) for p in planes] + [None] * (4 - len(planes))
            pts = ct.c_double()

            def _native(pl=ptrs, pt=pts, h=self._h) -> int:
                # handle bound at definition: an expiry nulls self._h
                # (reader poisoned) while the abandoned thread keeps
                # the leaked live handle
                if self._faults is not None:
                    self._faults.hang("decode")
                return lib.mp_decoder_next(
                    h, pl[0], pl[1], pl[2], pl[3], ct.byref(pt),
                    err, 512,
                )

            ret = self._guard(_native, op="decode", frame=self._frames_out)
            if ret == 0:
                return
            if ret < 0:
                raise MediaError(
                    f"decode {self.path} @frame {self._frames_out}: "
                    f"{err.value.decode()[:500]}"
                )
            self._frames_out += 1
            if self._faults is not None:
                self._faults.advance(1)
            if self._packed_offsets is not None:
                planes = self._deinterleave(planes[0])
            yield Frame(planes=planes, pts=pts.value, pix_fmt=self.pix_fmt)

    def _estimated_frames(self) -> int:
        """Best-effort frame count of the decode window (sizes read_all's
        output stacks; wrong estimates only cost a rare grow-copy)."""
        if self.fps <= 0:
            return 0
        window = self._window
        if window <= 0:
            window = max(0.0, self.duration - self._start)
        return int(round(window * self.fps)) if window > 0 else 0

    def read_all(self) -> tuple[list[np.ndarray], list[float]]:
        """Decode every frame in the window; returns (per-plane stacked
        [T, H, W] arrays, pts list). Streams chunk-wise native decodes
        STRAIGHT into pre-sized output stacks — the old implementation
        held every per-frame array AND the stacked copies simultaneously
        (2x peak RSS for long windows)."""
        if not bufpool.host_batch_enabled():
            return self._read_all_per_frame()
        est = self._estimated_frames()
        # never trust container metadata with the whole allocation: a
        # corrupt/overstated duration header would drive a multi-GB
        # upfront np.empty (and a hard MemoryError under strict
        # overcommit) for a file the per-frame path reads fine — cap the
        # pre-size and let the grow path extend for genuinely long reads
        cap = min(est + 2, 1024) if est > 0 else 64
        step = 64
        packed = self._packed_offsets is not None
        out = [
            np.empty((cap,) + shape, self.dtype)
            for shape in self.plane_shapes
        ]
        scratch = (
            [np.empty((step,) + self._raw_plane_shapes[0], self.dtype)]
            if packed else None
        )
        total = 0
        pts_parts: list[np.ndarray] = []
        while True:
            if total == cap:  # estimate fell short: grow by half
                cap += max(step, cap // 2)
                out = [
                    np.concatenate([o, np.empty((cap - total,) + o.shape[1:],
                                                self.dtype)])
                    for o in out
                ]
            take = min(step, cap - total)
            if packed:
                n, pts = self._decode_batch_into(scratch, take)
                if n:
                    self._deinterleave_chunk(
                        scratch[0][:n], [o[total: total + n] for o in out]
                    )
            else:
                n, pts = self._decode_batch_into(
                    [o[total: total + take] for o in out], take
                )
            if n == 0:
                break
            pts_parts.append(pts)
            total += n
            if n < take:
                break
        if total == 0:
            return [], []
        return (
            [o[:total] for o in out],
            list(np.concatenate(pts_parts)),
        )

    def _read_all_per_frame(self) -> tuple[list[np.ndarray], list[float]]:
        """Per-frame fallback (PC_HOST_BATCH=0): the parity baseline."""
        frames = list(self)
        if not frames:
            return [], []
        stacked = [
            np.stack([f.planes[p] for f in frames])
            for p in range(len(frames[0].planes))
        ]
        return stacked, [f.pts for f in frames]

    def close(self) -> None:
        if self._h:
            medialib.ensure_loaded().mp_decoder_close(self._h)
            self._h = None
        if self._guard_worker is not None:
            self._guard_worker.stop()
            self._guard_worker = None

    def __enter__(self) -> "VideoReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass


class VideoWriter:
    """Encoder + muxer. Codec/rate-control knobs mirror the reference's
    encoder command builders (lib/ffmpeg.py:61-318): bitrate or crf/qp,
    two-pass via pass_num + stats_path, gop/bframes, and an ffmpeg-style
    `opts` string ("preset=fast:crf=23:x265-params=...") applied to the
    codec context."""

    def __init__(
        self,
        path: str,
        codec: str,
        width: int,
        height: int,
        pix_fmt: str = "yuv420p",
        fps: tuple[int, int] = (24, 1),
        bitrate_kbps: float = 0,
        minrate_kbps: float = 0,
        maxrate_kbps: float = 0,
        bufsize_kbps: float = 0,
        gop: int = -1,
        bframes: int = -1,
        threads: int = -1,
        opts: str = "",
        pass_num: int = 0,
        stats_path: str = "",
        audio_codec: str = "",
        sample_rate: int = 48000,
        channels: int = 2,
        audio_bitrate_kbps: float = 0,
    ) -> None:
        self.path = path
        #: media-fault hooks (io/faults): one env lookup per OPEN
        self._faults = faults.encoder_faults(path)
        self._deadline = faults.media_deadline_s()
        #: lazy persistent deadline worker (faults.GuardWorker); only
        #: ever created when a deadline is set
        self._guard_worker = None
        self._frames_in = 0
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        self._h = lib.mp_encoder_open(
            path.encode(), codec.encode(), width, height, pix_fmt.encode(),
            fps[0], fps[1], int(bitrate_kbps * 1000), int(minrate_kbps * 1000),
            int(maxrate_kbps * 1000), int(bufsize_kbps * 1000), gop, bframes,
            threads, opts.encode(), pass_num, stats_path.encode(),
            audio_codec.encode(), sample_rate, channels,
            int(audio_bitrate_kbps * 1000), err, 512,
        )
        if not self._h:
            raise MediaError(f"encoder open {path} ({codec}): {err.value.decode()}")
        self._closed = False

    def _guard(self, fn, op: str):
        """Deadline guard, mirroring VideoReader._guard (one persistent
        GuardWorker — write() crosses per FRAME): an expiry poisons the
        writer (handle leaked — a thread is still inside the native
        call) so close() is a no-op."""
        if self._deadline is None:
            return fn()
        if self._guard_worker is None:
            self._guard_worker = faults.GuardWorker(
                f"media-guard:{os.path.basename(self.path)}")
        try:
            return faults.guarded_call(
                fn, self._deadline, op=op, path=self.path,
                frame=self._frames_in, worker=self._guard_worker,
            )
        except faults.MediaDeadlineExpired:
            self._h = None
            self._closed = True
            self._guard_worker = None  # wedged: abandoned with the call
            raise

    def write(self, *planes: np.ndarray) -> None:
        if not self._h:
            raise MediaError(f"{self.path}: writer is closed")
        if self._faults is not None:
            self._faults.check(1)
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        u8p = ct.POINTER(ct.c_uint8)
        arrs = [np.ascontiguousarray(p) for p in planes if p is not None]
        ptrs = [a.ctypes.data_as(u8p) for a in arrs] + [None] * (4 - len(arrs))
        h = self._h  # bound pre-crossing: expiry nulls self._h

        def _native() -> int:
            if self._faults is not None:
                self._faults.hang("encode")
            return lib.mp_encoder_write_video(
                h, ptrs[0], ptrs[1], ptrs[2], ptrs[3], err, 512
            )

        ret = self._guard(_native, op="encode")
        if ret < 0:
            raise MediaError(
                f"encode {self.path} @frame {self._frames_in}: "
                f"{err.value.decode()[:500]}"
            )
        self._frames_in += 1

    def write_batch(self, *planes: np.ndarray) -> None:
        """Encode a [T, h, w] stack per plane in ONE native crossing (one
        GIL release per chunk instead of per frame; in fp mode the whole
        chunk streams through the worker pool without Python in the
        loop). Byte-identical to T calls of `write` — the encoder walks
        the same per-frame path."""
        if not self._h:
            raise MediaError(f"{self.path}: writer is closed")
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        u8p = ct.POINTER(ct.c_uint8)
        arrs = [np.ascontiguousarray(p) for p in planes if p is not None]
        if not arrs:
            return
        t = int(arrs[0].shape[0])
        if any(int(a.shape[0]) != t for a in arrs):
            raise MediaError(
                f"{self.path}: write_batch plane stacks disagree on frame "
                f"count: {[a.shape[0] for a in arrs]}"
            )
        if t == 0:
            return
        if self._faults is not None:
            self._faults.check(t)
        ptrs = [a.ctypes.data_as(u8p) for a in arrs] + [None] * (4 - len(arrs))
        h = self._h  # bound pre-crossing: expiry nulls self._h

        def _native() -> int:
            if self._faults is not None:
                self._faults.hang("encode")
            return lib.mp_encoder_write_video_batch(
                h, ptrs[0], ptrs[1], ptrs[2], ptrs[3], t, err, 512,
            )

        ret = self._guard(_native, op="encode")
        if ret < 0:
            raise MediaError(
                f"encode {self.path} @frame {self._frames_in}: "
                f"{err.value.decode()[:500]}"
            )
        self._frames_in += t
        if tm.enabled():
            _IO_BATCH_ENCODE.inc()

    def write_audio(self, samples: np.ndarray) -> None:
        """samples: int16 [n, channels] interleaved."""
        if not self._h:
            raise MediaError(f"{self.path}: writer is closed")
        lib = medialib.ensure_loaded()
        err = ct.create_string_buffer(512)
        samples = np.ascontiguousarray(samples, dtype=np.int16)
        n = samples.shape[0]
        if lib.mp_encoder_write_audio(
            self._h, samples.ctypes.data_as(ct.POINTER(ct.c_int16)), n, err, 512
        ) < 0:
            raise MediaError(f"audio encode {self.path}: {err.value.decode()}")

    def close(self) -> None:
        try:
            if self._h and not self._closed:
                self._closed = True
                err = ct.create_string_buffer(512)
                h, self._h = self._h, None
                # the close flushes delayed frames + finalizes the
                # container: a crossing that can hang like any other
                ret = self._guard(
                    lambda: medialib.ensure_loaded().mp_encoder_close(
                        h, err, 512
                    ),
                    op="encoder-close",
                )
                if ret < 0:
                    raise MediaError(
                        f"close {self.path} after {self._frames_in} "
                        f"frames: {err.value.decode()[:500]}"
                    )
        finally:
            # a deadline expiry nulled the worker (abandoned, wedged);
            # any other exit stops the idle worker cleanly
            if self._guard_worker is not None:
                self._guard_worker.stop()
                self._guard_worker = None

    def __enter__(self) -> "VideoWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass
