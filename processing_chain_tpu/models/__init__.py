from . import avpvs, cpvs, frames, metadata, segments

__all__ = ["avpvs", "cpvs", "frames", "metadata", "segments"]
