"""AVPVS model — the p03 pixel-domain core (reference p03_generateAvPvs.py
+ lib/ffmpeg.py:940-1105, :1262-1289; bufferer pass p03:216-260).

Short tests: decode the single segment → device rescale to the AVPVS canvas
(bicubic, reference create_avpvs_short :940-1000) → FFV1(+FLAC) AVI.

Long tests: per segment, decode → device rescale → resample onto the canvas
frame rate (the nullsrc-overlay trick of create_avpvs_segment :1003-1055:
exactly duration×rate frames, last frame repeated when short) → streamed
into one FFV1 writer (the file-based tmp-segment + concat demuxer of the
reference, :1058-1105, collapses into an in-process stream) → SRC audio
muxed as pcm_s16le 2ch (audio_mux :1262-1289).

Stalling pass (both): a StallPlan from the PVS buff events drives the
device gather + spinner composite (ops/overlay — the bufferer
re-implementation), with silence inserted into the audio during stalls.
Frame-freeze HRCs use skipping mode (no spinner, length preserved).

Execution model (engine/prefetch, SURVEY.md §7.4): decode runs ahead on a
worker thread, the main loop does device resizes, and FFV1 encode drains on
a writer thread — three-stage host↔device overlap in bounded memory, where
the reference serializes decode→scale→encode inside one ffmpeg process per
segment. CHUNK-frame batches bound both HBM and host RAM for arbitrarily
long PVSes.
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config.domain import Pvs
from ..engine import prefetch as pf
from ..engine.jobs import Job
from ..io import medialib
from ..io.video import VideoReader, VideoWriter
from ..ops import overlay as ov
from ..store import keys as store_keys
from ..utils import fsio
from ..utils.log import get_logger
from . import frames as fr

CHUNK = 64  # frames per device batch (accelerator default; see chunk_frames)


def _env_int(name: str) -> Optional[int]:
    """Integer env knob, loudly rejected on a typo (a silently-ignored
    value would erase the advertised behavior with no signal); None when
    unset/empty."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer") from None


def chunk_frames() -> int:
    """Effective frames per pipeline chunk. PC_CHUNK_FRAMES pins it;
    default CHUNK (64) on accelerator backends (launch efficiency and
    transfer amortization dominate), 16 on the CPU backend — there the
    decode → compute → encode pipeline only overlaps at chunk
    granularity, and a short clip in one 64-frame chunk serializes the
    whole run (the BENCH_r05 e2e shape: 24 frames = 1 chunk = zero
    overlap), while per-chunk dispatch costs ~nothing on host."""
    # plan-exempt: (chunk granularity batches the identical frame stream; pinned by the batch-vs-single parity tests)
    pinned = _env_int("PC_CHUNK_FRAMES")
    if pinned is not None:
        return max(1, pinned)
    import jax

    return CHUNK if jax.default_backend() != "cpu" else 16


def _decode_workers() -> int:
    """Concurrent segment decoders for the long path (engine/prefetch
    MultiSegmentPrefetcher). Default 2: overlaps decode across segment
    boundaries with bounded memory; raise on multi-core hosts where host
    decode is the bottleneck feeding the chips (SURVEY §7 hard part #2).
    1 restores strictly serial per-segment decode."""
    try:
        # plan-exempt: (prefetch width; MultiSegmentPrefetcher preserves segment order, identical stream at any width)
        return max(1, int(os.environ.get("PC_DECODE_WORKERS", "2")))
    except ValueError:
        return 2


def avpvs_dimensions(pvs: Pvs, post_proc_id: int = 0) -> tuple[int, int]:
    """(width, height) of the AVPVS canvas: aspect-aware dims vs the
    post-processing coding size, overridden upward when the encoded segment
    is taller (reference create_avpvs_short :976-986).

    Documented deviation: the reference feeds the SRC's CODED dims into
    this math (stream_info['coded_width'/'coded_height'], :975-976) — for
    a non-mod-16 h264 master (e.g. 1920x1080, coded 1920x1088) that
    distorts the canvas aspect. We use the display dims; for the usual
    lossless (FFV1/rawvideo) masters the two are identical. Interop of
    the sidecars carrying both is oracle-tested
    (tests/test_reference_oracle.py::test_src_sidecar_interop_with_reference)."""
    pp = pvs.test_config.post_processings[post_proc_id]
    w, h = fr.calculate_avpvs_video_dimensions(
        pvs.src.stream_info["width"],
        pvs.src.stream_info["height"],
        pp.coding_width,
        pp.coding_height,
    )
    ql = pvs.segments[0].quality_level
    if ql.height > h:
        w, h = ql.width, ql.height
    return w, h


def canvas_fps(pvs: Pvs, avpvs_src_fps: bool = False) -> float:
    """AVPVS canvas frame rate: 60 by default, SRC fps with -z
    (reference create_avpvs_segment :1030-1033, p03 flags)."""
    return pvs.src.get_fps() if avpvs_src_fps else 60.0


def avpvs_codec() -> str:
    """AVPVS intermediate codec: `ffv1` (reference parity, default) or
    `rawvideo` (PC_AVPVS_CODEC=rawvideo: a cheaper lossless intermediate
    for hosts where FFV1 compression — not decode or device work — is the
    p03 bottleneck; ~6x the disk footprint, near-memcpy writeback).
    Decoded frames are identical either way; provenance records which
    codec produced each artifact."""
    codec = os.environ.get("PC_AVPVS_CODEC", "ffv1").strip().lower()
    if codec not in ("ffv1", "rawvideo"):
        raise ValueError(
            f"PC_AVPVS_CODEC={codec!r}: expected 'ffv1' or 'rawvideo'"
        )
    return codec


def effective_avpvs_codec(pix_fmt: str) -> str:
    """The codec that will actually be written for this pix_fmt: the
    requested intermediate codec, except that 10-bit rawvideo degrades to
    ffv1 (`_ffv1_writer`'s AVI-fourcc fallback). Provenance and plan
    payloads record THIS, so artifacts stay attributable to the encoder
    that really produced them."""
    codec = avpvs_codec()
    if codec == "rawvideo" and "10" in pix_fmt:
        return "ffv1"
    return codec


def ffv1_workers() -> int:
    """Frame-parallel FFV1 encoder contexts (native/media.cpp fp mode).
    PC_FFV1_WORKERS=N pins it; default: one worker per spare core, capped
    at 8 (0 on a 1-2 core host — the pool only adds queue overhead when
    there is no core for it to run on). The p03 stage refines the default
    to (spare cores)/(job-pool width) so `-p` runs don't oversubscribe
    (stages/p03_generate_avpvs). FFV1 is intra-only, so frames encode
    independently on private contexts and scale with cores where slice
    threading (the reference's `-threads 4`, lib/ffmpeg.py:1047) tops
    out at slices-per-frame."""
    # plan-exempt: (worker count schedules whole-frame encodes; the slices=0 regime it selects is recorded as ffv1_slices in the plan)
    raw = os.environ.get("PC_FFV1_WORKERS", "").strip()
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            # loud, like PC_AVPVS_CODEC: a typo'd value silently running
            # serial would erase the advertised scaling with no signal
            raise ValueError(
                f"PC_FFV1_WORKERS={raw!r}: expected an integer"
            ) from None
    ncpu = os.cpu_count() or 1
    return 0 if ncpu <= 2 else min(ncpu - 1, 8)


def set_default_fp_workers(pool_width: int) -> None:
    """Install the POOL-AWARE fp-worker default into the env (no-op when
    PC_FFV1_WORKERS is already pinned by the operator or a flag):
    `pool_width` concurrent jobs each opening (cores-1) contexts would
    oversubscribe the host, so the spare cores are divided across the
    pool. Called by every stage that runs intra writebacks `-p`-wide
    (p03 renders, p04 previews)."""
    # plan-exempt: (presence probe for the pool-aware default; the byte-relevant outcome is the recorded ffv1_slices)
    if "PC_FFV1_WORKERS" not in os.environ:
        ncpu = os.cpu_count() or 1
        per_job = (ncpu - 1) // max(1, pool_width) if ncpu > 2 else 0
        os.environ["PC_FFV1_WORKERS"] = str(max(0, min(per_job, 8)))
    # plan-exempt: (presence probe for the pool-aware default; the byte-relevant outcome is the recorded ffv1_slices)
    if "PC_FFV1_THREADS" not in os.environ:
        # the serial writers' slice-threading default (one thread per
        # core) must also divide across the pool: when the fp default
        # resolves to 0, `pool_width` concurrent serial writers each
        # opening cpu_count() codec threads would thrash the scheduler
        ncpu = os.cpu_count() or 1
        os.environ["PC_FFV1_THREADS"] = str(
            max(1, ncpu // max(1, pool_width))
        )


#: slice counts the FFV1 encoder accepts (ffv1enc slice tiling table)
FFV1_SLICE_COUNTS = (4, 6, 9, 12, 16, 24, 30)


def ffv1_coding_threads() -> int:
    """Slice-threading width for serial (non-fp) FFV1 writes. Default:
    one per core (the reference pins `-threads 4`, lib/ffmpeg.py:1047 —
    which WASTES cores above 4 and oversubscribes below);
    PC_FFV1_THREADS pins it."""
    # plan-exempt: (thread count does not alter encoded bytes; its effect on the default slice count is captured by the recorded ffv1_slices)
    pinned = _env_int("PC_FFV1_THREADS")
    if pinned is not None:
        return max(1, pinned)
    return os.cpu_count() or 1


def ffv1_slices(threads: int) -> int:
    """Slices per FFV1 frame: the smallest count the encoder accepts that
    keeps every slice thread busy (slice threading tops out at
    slices-per-frame). PC_FFV1_SLICES pins it (must be a valid count)."""
    pinned = _env_int("PC_FFV1_SLICES")
    if pinned is not None:
        if pinned not in FFV1_SLICE_COUNTS:
            raise ValueError(
                f"PC_FFV1_SLICES={pinned}: ffv1 accepts {FFV1_SLICE_COUNTS}"
            )
        return pinned
    for s in FFV1_SLICE_COUNTS:
        if s >= threads:
            return s
    return FFV1_SLICE_COUNTS[-1]


def ffv1_effective_coding() -> dict:
    """The FFV1 writeback configuration `_ffv1_writer` will actually use,
    resolved once so the writer, the plan payload and store provenance
    cannot drift. The SLICE layout shapes the bitstream (decoded frames
    stay identical), so the effective slice count is part of every
    ffv1-writing plan hash via `ffv1_effective_slices` — the store
    serves BYTES by plan hash, and two slice layouts are two byte
    streams (store/plan_schema.py). Thread and fp-worker counts only
    parallelize the layout the plan already records; they stay out of
    the hash and land in provenance for attributability."""
    workers = ffv1_workers()
    if workers > 0:
        return {"fp_workers": workers, "threads": 1, "slices": 0}
    threads = ffv1_coding_threads()
    return {"fp_workers": 0, "threads": threads,
            "slices": ffv1_slices(threads)}


def ffv1_effective_slices() -> int:
    """The byte-relevant projection of the writeback knobs, for plan
    payloads: the slice layout `_ffv1_writer` will emit (0 = the
    frame-parallel single-slice regime). PC_FFV1_SLICES and the
    PC_FFV1_THREADS-derived default both flow into cache keys through
    THIS value — fold it into any plan whose artifact is FFV1-encoded."""
    return ffv1_effective_coding()["slices"]


def _ffv1_writer(path: str, w: int, h: int, pix_fmt: str, rate: float,
                 with_audio: bool, sample_rate: int = 48000,
                 audio_codec: str = "pcm_s16le") -> VideoWriter:
    frac = Fraction(rate).limit_denominator(1001)
    audio = dict(audio_codec=audio_codec, sample_rate=sample_rate, channels=2) if with_audio else {}
    if avpvs_codec() == "rawvideo":
        if "10" in pix_fmt:
            # AVI has no fourcc for planar 10-bit rawvideo: the muxer
            # writes the tag-less stream anyway and every later read
            # decodes garbage (silent corruption, round-5 advisor repro).
            # FFV1 carries 10-bit losslessly, so fall back rather than
            # produce bytes that cannot round-trip.
            get_logger().warning(
                "%s: rawvideo cannot carry 10-bit %s in AVI (no fourcc; "
                "reads back as garbage) — falling back to ffv1",
                path, pix_fmt,
            )
        else:
            return VideoWriter(
                path, "rawvideo", w, h, pix_fmt,
                (frac.numerator, frac.denominator), **audio,
            )
    # FFV1 level 3 + slicecrc stream integrity (reference :1047: -level 3
    # -coder 1 -context 1 -slicecrc 1). Serial writes get real codec
    # threading (slices sized to the thread count — the reference's
    # fixed `-threads 4` with the default single slice never scaled).
    # With fp workers, parallelism moves from slices to whole frames
    # (gop=1) and per-context threading drops to 1.
    eff = ffv1_effective_coding()
    opts = "level=3:coder=1:context=1:slicecrc=1"
    if eff["fp_workers"] > 0:
        opts += f":pc_fp_workers={eff['fp_workers']}"
    else:
        opts += f":slices={eff['slices']}"
    return VideoWriter(
        path, "ffv1", w, h, pix_fmt, (frac.numerator, frac.denominator),
        threads=eff["threads"], opts=opts, **audio,
    )


def _segment_canvas_chunks(seg, rate: float):
    """Decode one encoded segment and yield raw [T,H,W] plane chunks on the
    canvas time grid (exactly round(duration*rate) frames; trailing outputs
    repeat the last decoded frame — the reference's nullsrc-canvas
    semantics, lib/ffmpeg.py:1037-1038). Streaming: never holds more than
    CHUNK decoded frames."""
    with VideoReader(seg.file_path) as reader:
        seg_fps = reader.fps
        n_out = int(round(seg.duration * rate))
        got_any = False
        for chunk in pf.stream_monotonic_gather(
            reader,
            lambda k: int(np.floor(k / rate * seg_fps + 0.5)),
            n_out,
            chunk_frames(),
        ):
            got_any = True
            yield chunk
    # a segment whose duration rounds to zero canvas frames legitimately
    # yields nothing; only a truly frameless source is an error
    if not got_any and n_out > 0:
        raise medialib.MediaError(f"no frames in segment {seg.file_path}")


def _short_rate_chunks(
    pvs: Pvs, reader: VideoReader, avpvs_src_fps: bool, force_60_fps: bool
):
    """(canvas rate, decoded chunk stream) for the short path: native
    segment frame rate unless -z/-f60 (reference create_avpvs_short
    :940-1000). Shared by the per-PVS job and the sharded batch path."""
    seg_fps = reader.fps
    rate = pvs.src.get_fps() if avpvs_src_fps else (
        60.0 if force_60_fps else seg_fps
    )
    chunks = (
        pf.stream_fps_resample(reader, seg_fps, rate, chunk_frames())
        if rate != seg_fps
        else pf.iter_plane_chunks(reader, chunk_frames())
    )
    return rate, chunks


def _decode_stereo(path: str, start: float = 0.0, duration: float = 0.0):
    """(samples[n, 2] int16, rate): decode with libswresample's stereo
    remix — the ffmpeg `-ac 2` the reference applies in audio_mux
    (lib/ffmpeg.py:1284), so a 5.1 SRC downmixes with the proper
    center/surround matrix instead of the front-pair truncation the
    round-4 advisor flagged; mono upmixes with ffmpeg's matrix too."""
    return medialib.decode_audio_s16(path, start, duration, channels=2)


def _short_segment_audio(seg):
    """The short path carries the encoded segment's audio into the AVPVS
    as FLAC (reference create_avpvs_short's bare `-i segment ... -c:a
    flac`, lib/ffmpeg.py:995). (samples, rate) or (None, rate)."""
    try:
        samples, srate = _decode_stereo(seg.file_path)
    except medialib.MediaError as exc:
        # no-audio-stream and decode-failure are one exception type; the
        # warning keeps a real failure from silently shipping an
        # audio-less AVPVS (the reference's -c:a flac would hard-fail)
        get_logger().warning(
            "%s: no audio carried into AVPVS (%s)", seg.filename, exc
        )
        return None, 48000
    if samples.size == 0:
        return None, srate
    return samples, srate


def siti_sidecar_path(avpvs_path: str) -> str:
    """Per-frame feature sidecar written by the p03 device pass."""
    return avpvs_path + ".siti.csv"


class SiTiAccumulator:
    """Per-frame SI/TI of the upscaled luma, computed ON DEVICE during the
    AVPVS render while the frames are already in HBM — the "device-side
    feature tensors" of the north star (BASELINE.json), so downstream
    consumers (tools/quality_metrics, complexity work) read a sidecar
    instead of decoding the AVPVS again. Features are computed on the
    QUANTIZED luma (container bit depth): exactly what a tool decoding the
    file would see. TI[0] = 0; TI carries across chunk boundaries."""

    def __init__(self) -> None:
        # device arrays until write(): the [T]-sized features must not
        # force a device->host sync inside the pump loop (AsyncWriter's
        # whole point is that the main loop never blocks on the device)
        self.si: list = []
        self.ti: list = []
        self._prev = None  # device luma f32 of the previous chunk's last frame

    def update(self, y_quant) -> None:
        from ..ops import siti as siti_ops

        yq = jnp.asarray(y_quant)
        # container-depth input: the TPU path streams u8/u16 through the
        # fused Pallas kernels without materializing an f32 batch
        si = siti_ops.si_frames(yq)
        ti, self._prev = siti_ops.ti_frames_continued(yq, self._prev)
        self.si.append(si)
        self.ti.append(ti)

    def extend(self, si: np.ndarray, ti: np.ndarray) -> None:
        """Batch-path entry: features already computed by the sharded step."""
        self.si.append(si)
        self.ti.append(ti)

    def write(self, avpvs_path: str) -> Optional[str]:
        if not self.si:
            return None
        path = siti_sidecar_path(avpvs_path)
        si = np.concatenate([np.asarray(s) for s in self.si])
        ti = np.concatenate([np.asarray(t) for t in self.ti])
        # atomic: an interrupted write must never leave a truncated
        # sidecar next to a complete AVPVS
        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                f.write("frame,si,ti\n")
                for k, (s, t) in enumerate(zip(si, ti)):
                    f.write(f"{k},{s:.6f},{t:.6f}\n")

        fsio.atomic_write(path, _write)
        return path

    @staticmethod
    def discard(avpvs_path: str) -> None:
        """Remove a (possibly stale) sidecar: called before re-rendering
        and on render failure, so a sidecar can never describe an AVPVS
        from a different render."""
        p = siti_sidecar_path(avpvs_path)
        if os.path.isfile(p):
            os.unlink(p)


def _wo_buffer_out_path(pvs: Pvs) -> str:
    return (
        pvs.get_avpvs_wo_buffer_file_path()
        if pvs.has_buffering()
        else pvs.get_avpvs_file_path()
    )


def _wo_buffer_plan(
    pvs: Pvs, w: int, h: int, pix_fmt: str,
    avpvs_src_fps: bool, force_60_fps: bool,
) -> dict:
    """Plan payload for the wo_buffer render: encoded segment digests,
    the SRC (long tests mux its audio), canvas geometry, and every
    byte-affecting knob — the effective codec, its slice layout (FFV1
    bitstream structure; store/plan_schema.py) and the resize-method
    identity. fp-worker and thread COUNTS stay out: they parallelize
    the recorded layout without changing the bytes (plan-exempt)."""
    from ..ops import resize as resize_ops

    tc = pvs.test_config
    codec = effective_avpvs_codec(pix_fmt)
    return {
        "op": "avpvs_wo_buffer",
        "segments": [store_keys.file_ref(s.file_path) for s in pvs.segments],
        "src_audio": (
            store_keys.file_ref(pvs.src.file_path) if tc.is_long() else None
        ),
        "canvas": [w, h],
        "pix_fmt": pix_fmt,
        "codec": codec,
        "ffv1_slices": ffv1_effective_slices() if codec == "ffv1" else None,
        "resize": resize_ops.plan_resize_method(),
        "rate": {
            "avpvs_src_fps": bool(avpvs_src_fps),
            "force_60_fps": bool(force_60_fps),
        },
        "durations": [float(s.get_segment_duration()) for s in pvs.segments]
        if tc.is_long() else None,
    }


def _wo_buffer_provenance(pvs: Pvs, w: int, h: int, pix_fmt: str) -> dict:
    codec = effective_avpvs_codec(pix_fmt)
    if codec == "ffv1":
        # record the EFFECTIVE codec-threading knobs (fp workers, slice
        # threading, slices): they shape the byte stream, so an artifact
        # must stay attributable to the writer configuration that
        # produced it — while plan hashes keep tracking semantic content
        # only (decoded frames are identical across these knobs)
        eff = ffv1_effective_coding()
        tuning = (
            f"fp_workers={eff['fp_workers']}" if eff["fp_workers"]
            else f"threads={eff['threads']},slices={eff['slices']}"
        )
        codec_desc = f"ffv1(level3,slicecrc,{tuning})"
    else:
        codec_desc = "rawvideo"
    return {
        "pvs": pvs.pvs_id,
        "pipeline": {
            "canvas": [w, h],
            "pix_fmt": pix_fmt,
            "segments": [s.filename for s in pvs.segments],
            "codec": codec_desc,
        },
    }


def _pump_ready(ready, writer: pf.AsyncWriter, feat: SiTiAccumulator,
                h: int, w: int, pix_fmt: str, tap=None) -> None:
    """Already-prefetched host chunks → device resize (+ on-device
    SI/TI features) → async encode. Transfers are double-buffered
    (pipeline.iter_device_ahead): chunk k+1's device_put is issued
    while chunk k's compute is in flight, and the pooled decode
    blocks ride to the AsyncWriter, which recycles them once the
    encoded outputs prove the compute consumed them.

    With `tap` set (the fused p04 fan-out, models/fused), the quantized
    chunk is fetched to host ON THIS LOOP — proving the compute that
    read the pooled decode blocks finished before they recycle — and
    handed to both the AVPVS writer and the tap."""
    import jax

    from ..parallel.pipeline import iter_device_ahead

    sub = fr.chroma_subsampling(pix_fmt)
    ten_bit = "10" in pix_fmt
    for chunk, dev in iter_device_ahead(
        ready, lambda c: [jax.device_put(p) for p in c]
    ):
        scaled = fr.scale_yuv_frames(dev, h, w, "bicubic", sub)
        quant = fr.quantize_device(scaled, ten_bit)
        feat.update(quant[0])
        if tap is None:
            writer.put(quant, recycle=chunk)
        else:
            host = [np.asarray(q) for q in quant]
            writer.put(host, recycle=chunk)
            tap(host)


def _render_wo_buffer(
    pvs: Pvs, out_path: str, w: int, h: int, pix_fmt: str,
    avpvs_src_fps: bool, force_60_fps: bool, feat: SiTiAccumulator,
    fanout=None,
) -> None:
    """The decode → device rescale → FFV1(+audio) render body shared by
    the per-PVS job and the fused driver. With `fanout` set
    (models/fused.FusedFanout), `fanout.start(...)` is called once rate
    and audio are known and every quantized chunk is fed to the fan-out
    after the AVPVS writer — ONE SRC decode feeding the AVPVS, the
    staged stalling pass, and every CPVS/preview render."""
    tc = pvs.test_config

    def _pump(chunks, writer, tap):
        with pf.Prefetcher(chunks, depth=2) as pre:
            _pump_ready(pre, writer, feat, h, w, pix_fmt, tap)

    if tc.is_short():
        # single segment, native segment frame rate unless -z/-f60
        seg = pvs.segments[0]
        audio, srate = _short_segment_audio(seg)
        with VideoReader(seg.file_path) as reader:
            rate, chunks = _short_rate_chunks(
                pvs, reader, avpvs_src_fps, force_60_fps
            )
            tap = (
                fanout.start(rate, audio, srate, w, h, pix_fmt)
                if fanout is not None else None
            )
            with pf.AsyncWriter(
                _ffv1_writer(
                    out_path, w, h, pix_fmt, rate,
                    with_audio=audio is not None, sample_rate=srate,
                    audio_codec="flac",
                )
            ) as writer:
                if audio is not None:
                    writer.write_audio(audio)
                _pump(chunks, writer, tap)
    else:
        rate = canvas_fps(pvs, avpvs_src_fps)
        total = float(sum(s.get_segment_duration() for s in pvs.segments))
        samples, srate = _decode_stereo(pvs.src.file_path, 0.0, total)
        tap = (
            fanout.start(rate, samples, srate, w, h, pix_fmt)
            if fanout is not None else None
        )
        with pf.AsyncWriter(
            _ffv1_writer(
                out_path, w, h, pix_fmt, rate, with_audio=True,
                sample_rate=srate,
            )
        ) as writer:
            writer.write_audio(samples)
            factories = [
                (lambda s=seg: _segment_canvas_chunks(s, rate))
                for seg in pvs.segments
            ]
            with pf.MultiSegmentPrefetcher(
                factories, workers=_decode_workers(), depth=2
            ) as pre:
                _pump_ready(pre, writer, feat, h, w, pix_fmt, tap)


def create_avpvs_wo_buffer(
    pvs: Pvs,
    avpvs_src_fps: bool = False,
    force_60_fps: bool = False,
    fanout=None,
) -> Optional[Job]:
    """The decode+rescale(+concat+audio) stage producing the pre-stalling
    AVPVS (or the final one when the HRC has no buffering). `fanout`
    (models/fused.FusedFanout) rides the same decode to render the
    stalling pass + every CPVS context in the same job — PC_FUSE_P04."""
    out_path = _wo_buffer_out_path(pvs)
    w, h = avpvs_dimensions(pvs)
    pix_fmt = pvs.get_pix_fmt_for_avpvs()

    def run() -> str:
        SiTiAccumulator.discard(out_path)  # never leave a stale sidecar
        feat = SiTiAccumulator()
        try:
            _render_wo_buffer(
                pvs, out_path, w, h, pix_fmt, avpvs_src_fps, force_60_fps,
                feat, fanout,
            )
            feat.write(out_path)
        except BaseException:
            if fanout is not None:
                fanout.abort()
            raise
        if fanout is not None:
            # flush + finalize the fan-out artifacts (stalled AVPVS,
            # CPVS contexts, preview): commits ride each member job's
            # existing plan hash (models/fused)
            fanout.close()
        return out_path

    return Job(
        label=f"avpvs {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        logfile_path=pvs.get_logfile_path(),
        plan=_wo_buffer_plan(pvs, w, h, pix_fmt, avpvs_src_fps, force_60_fps),
        sidecar_suffixes=(".siti.csv",),
        provenance=_wo_buffer_provenance(pvs, w, h, pix_fmt),
    )


class _BoundarySink:
    """Forwards scaled blocks to the writer while keeping the lane's first
    and last luma frames (for TI stitching at long-test segment joins)."""

    def __init__(self, writer) -> None:
        self._writer = writer
        self.first = None
        self.last = None

    def emit(self, planes) -> None:
        if self.first is None:
            self.first = np.asarray(planes[0][0]).copy()
        self.last = np.asarray(planes[0][-1]).copy()
        self._writer.put(planes)


def _write_wav(path: str, samples: np.ndarray, rate: int) -> None:
    """pcm_s16le stereo .wav — the audio side-file mp_remux merges into
    the concatenated long-test AVPVS (pure-python: the wave module)."""
    import wave

    with wave.open(path, "wb") as f:
        f.setnchannels(samples.shape[1])
        f.setsampwidth(2)
        f.setframerate(rate)
        f.writeframes(np.ascontiguousarray(samples, np.int16).tobytes())


def create_avpvs_wo_buffer_batch(
    pvses: list,
    avpvs_src_fps: bool = False,
    force_60_fps: bool = False,
    fanouts: Optional[dict] = None,
) -> Optional[Job]:
    """Multi-device p03: ONE job running the PVS batch through the
    (pvs × time) device mesh (parallel/p03_batch), instead of one device
    job per PVS.

    Short tests: one lane per PVS, straight into the final FFV1(+FLAC)
    writer — byte-identical to the single-device path (proven in
    tests/test_pipeline_e2e.py).

    Long tests: one lane per (PVS, segment) rendering an FFV1 tmp file —
    the reference's own parallel-tmp design (p03:88-104) with device lanes
    instead of ffmpeg processes — then per PVS a native stream-copy concat
    (medialib.concat_video, the concat-demuxer pass :1094-1100) + SRC
    audio remux. Decoded frames are identical to the single-device render
    (FFV1 is lossless; the byte stream differs because per-segment encoder
    contexts reset where the single continuous encode adapts across
    segments). SI/TI sidecars are stitched with the segment-join TI fixed
    from the captured boundary frames, matching the single path's carry.

    Skip-existing/--force filtering happens in the stage (per-PVS), so
    every pvs passed here is due for (re)generation.

    `fanouts` maps pvses to their fused-p04 fan-outs
    (models/fused.FusedFanout, PC_FUSE_P04). Short: each lane's emit
    also feeds the fan-out, the wave driver's Lane.on_done flushes it
    the moment the lane exhausts, and its member artifacts commit right
    after the lane's wave drains. Long: the wave schedule pins each
    PVS's per-segment lanes to sequential waves in segment order
    (parallel/p03_batch.plan_waves), so the fan-out consumes the same
    continuous stream the single-device path feeds it — SRC audio is
    decoded ONCE at fan-out start and reused by the assembly remux, and
    a SegmentOrderedTap (models/fused) enforces the ordering contract
    instead of buffering frames."""
    if not pvses:
        return None
    from contextlib import ExitStack

    from ..io import probe
    from ..parallel import p03_batch
    from ..parallel.mesh import make_mesh

    def run() -> str:
        specs = []
        assembly: dict = {}
        try:
            return _run(specs, assembly)
        except BaseException:
            # sweep EVERY long-test tmp render, not just the failing
            # wave/PVS's: completed waves' full-resolution FFV1 tmps
            # (potentially many GB) must not outlive a failed batch.
            # abort() on an already-closed fan-out is a no-op, so this
            # catches fan-outs the inner sweeps never reached.
            for spec in specs:
                fan = spec.get("fanout")
                if fan is not None:
                    fan.abort()
                if spec["kind"] == "long_seg" and os.path.isfile(spec["out"]):
                    os.unlink(spec["out"])
            for pvs_specs in assembly.values():
                final = pvs_specs[0]["final"]
                for p in (final + ".cat.tmp.avi", final + ".audio.tmp.wav"):
                    if os.path.isfile(p):
                        os.unlink(p)
            raise

    def _run(specs, assembly) -> str:
        import jax

        devs = jax.devices()
        mesh = make_mesh(
            devs,
            time_parallel=2 if len(devs) > 1 and len(devs) % 2 == 0 else 1,
        )
        n_pvs = mesh.shape["pvs"]
        log = get_logger()

        # lane specs: one per short PVS, one per long (PVS, segment) —
        # probe-only here; decoders/encoders open later, per wave, so a
        # 300-PVS database never holds 300 open codec contexts at once.
        # (specs/assembly are the caller's lists so the outer failure
        # sweep sees everything planned so far.)
        from ..engine.jobs import clear_inprogress, mark_inprogress

        for pvs in pvses:
            tc = pvs.test_config
            w, h = avpvs_dimensions(pvs)
            pix_fmt = pvs.get_pix_fmt_for_avpvs()
            out_path = _wo_buffer_out_path(pvs)
            SiTiAccumulator.discard(out_path)
            # batch finals are written outside Job.run: same crash
            # sentinel discipline as single-device jobs (engine/jobs)
            mark_inprogress(out_path)
            if tc.is_short():
                seg = pvs.segments[0]
                info = probe.get_segment_info(seg.file_path)
                specs.append(dict(
                    kind="short", pvs=pvs, seg=seg, out=out_path,
                    final=out_path, w=w, h=h, pix_fmt=pix_fmt,
                    key=(info["video_height"], info["video_width"], h, w,
                         pix_fmt),
                ))
            else:
                rate = canvas_fps(pvs, avpvs_src_fps)
                pvs_specs = []
                for idx, seg in enumerate(pvs.segments):
                    info = probe.get_segment_info(seg.file_path)
                    spec = dict(
                        kind="long_seg", pvs=pvs, seg=seg, idx=idx,
                        rate=rate, final=out_path,
                        out=f"{out_path}.seg{idx:04d}.tmp.avi",
                        w=w, h=h, pix_fmt=pix_fmt,
                        key=(info["video_height"], info["video_width"], h, w,
                             pix_fmt),
                    )
                    specs.append(spec)
                    pvs_specs.append(spec)
                assembly[pvs] = pvs_specs

        buckets: dict = {}
        for spec in specs:
            buckets.setdefault(spec["key"], []).append(spec)
        for (sh, sw, dh, dw, pix_fmt), entries in buckets.items():
            log.info(
                "p03 batch: %d lane(s) %dx%d->%dx%d %s over mesh %s",
                len(entries), sw, sh, dw, dh, pix_fmt, dict(mesh.shape),
            )
            # longest-first so each wave groups similar lengths
            entries.sort(key=lambda e: -e["seg"].duration)

        def group_of(spec):
            # fan-out-attached long tests are ordered groups: their
            # per-segment lanes must reach the fan-out in stream order.
            # Everything else schedules freely (tmp renders are
            # order-independent — assembly happens after the waves).
            if spec["kind"] != "long_seg" or (fanouts or {}).get(spec["pvs"]) is None:
                return None
            return (spec["pvs"].pvs_id, spec["idx"])

        # per-PVS fused state for long tests: the SegmentOrderedTap and
        # the ONE SRC audio decode shared with the assembly remux below
        fan_state: dict = {}
        for (sh, sw, dh, dw, pix_fmt), wave in p03_batch.plan_waves(
            buckets, n_pvs, group_of=group_of
        ):
            try:
                with ExitStack() as stack:
                    lanes = []
                    for spec in wave:
                        pvs, out_path = spec["pvs"], spec["out"]
                        w, h = spec["w"], spec["h"]
                        tap = None
                        on_done = None
                        if spec["kind"] == "short":
                            audio, srate = _short_segment_audio(spec["seg"])
                            reader = stack.enter_context(
                                VideoReader(spec["seg"].file_path)
                            )
                            rate, chunks = _short_rate_chunks(
                                pvs, reader, avpvs_src_fps, force_60_fps
                            )
                            fan = (fanouts or {}).get(pvs)
                            if fan is not None:
                                # the fused p04 fan-out rides this
                                # lane's emits (PC_FUSE_P04);
                                # registered before start() so the
                                # wave's failure sweep aborts a
                                # fan-out that died mid-open
                                spec["fanout"] = fan
                                tap = fan.start(
                                    rate, audio, srate, w, h, pix_fmt
                                )
                                on_done = fan.finish_streams
                            writer = stack.enter_context(
                                pf.AsyncWriter(_ffv1_writer(
                                    out_path, w, h, pix_fmt, rate,
                                    with_audio=audio is not None,
                                    sample_rate=srate, audio_codec="flac",
                                ))
                            )
                            if audio is not None:
                                writer.write_audio(audio)
                        else:
                            rate = spec["rate"]
                            chunks = _segment_canvas_chunks(
                                spec["seg"], rate
                            )
                            fan = (fanouts or {}).get(pvs)
                            if fan is not None:
                                spec["fanout"] = fan
                                st = fan_state.get(pvs)
                                if st is None:
                                    # first lane of this PVS — segment 0
                                    # by the plan_waves contract: decode
                                    # SRC audio ONCE, start the fan-out,
                                    # and order every later lane through
                                    # the tap
                                    total = float(sum(
                                        s.get_segment_duration()
                                        for s in pvs.segments
                                    ))
                                    samples, srate = _decode_stereo(
                                        pvs.src.file_path, 0.0, total
                                    )
                                    from . import fused as fused_model

                                    st = dict(
                                        tap=fused_model.SegmentOrderedTap(
                                            fan,
                                            fan.start(rate, samples, srate,
                                                      w, h, pix_fmt),
                                            len(pvs.segments),
                                        ),
                                        fan=fan, audio=samples, srate=srate,
                                    )
                                    fan_state[pvs] = st
                                tap = st["tap"].lane(spec["idx"])
                                on_done = st["tap"].lane_done(spec["idx"])
                            writer = stack.enter_context(
                                pf.AsyncWriter(_ffv1_writer(
                                    out_path, w, h, pix_fmt, rate,
                                    with_audio=False,
                                ))
                            )
                        sink = _BoundarySink(writer)
                        feat = SiTiAccumulator()
                        spec["feat"] = feat
                        spec["sink"] = sink
                        if tap is None:
                            emit = sink.emit
                        else:
                            def emit(planes, _sink=sink, _tap=tap):
                                _sink.emit(planes)
                                _tap(planes)
                        lanes.append(p03_batch.Lane(
                            chunks=chunks,
                            emit=emit,
                            n_frames_hint=int(
                                round(spec["seg"].duration * rate)
                            ),
                            emit_features=feat.extend,
                            on_done=on_done,
                            # wave-journal identity (meshobs): the
                            # PVS, plus the segment index for long
                            # tests split into per-segment lanes
                            name=(
                                pvs.pvs_id if spec["kind"] == "short"
                                else f"{pvs.pvs_id}.seg{spec['idx']:04d}"
                            ),
                        ))
                    p03_batch.run_bucket(
                        lanes, mesh, dh, dw, "bicubic",
                        fr.chroma_subsampling(pix_fmt),
                        ten_bit="10" in pix_fmt,
                        chunk=chunk_frames(),
                        bucket=p03_batch.bucket_label(
                            dh, dw, "10" in pix_fmt, sh, sw),
                    )
            except BaseException:
                # the writers were opened (files created/truncated): a
                # partial artifact must never survive to satisfy a
                # later run's skip-existing check. Abort EVERY started
                # fan-out, not only this wave's — a long fan-out spans
                # waves and its members are partial too.
                for spec in wave:
                    fan = spec.get("fanout")
                    if fan is not None:
                        fan.abort()
                for st in fan_state.values():
                    st["fan"].abort()
                for spec in wave:
                    for p in (spec["out"], spec["final"]):
                        if os.path.isfile(p):
                            os.unlink(p)
                    clear_inprogress(spec["final"])
                    SiTiAccumulator.discard(spec["final"])
                raise
            # short lanes are final the moment their wave drains
            for spec in wave:
                if spec["kind"] == "short":
                    spec["feat"].write(spec["out"])
                    Job(
                        label=f"avpvs {spec['pvs'].pvs_id}",
                        output_path=spec["out"],
                        fn=lambda: None,
                        logfile_path=spec["pvs"].get_logfile_path(),
                        provenance=_wo_buffer_provenance(
                            spec["pvs"], spec["w"], spec["h"],
                            spec["pix_fmt"],
                        ),
                    ).complete_externally()
                    fan = spec.get("fanout")
                    if fan is not None:
                        # fan-out members (stalled AVPVS, CPVS,
                        # preview) commit under their own plan
                        # hashes now that the lane's wave drained
                        fan.close()

        # long-test assembly: native stream-copy concat of the tmp
        # renders + SRC audio remux + stitched feature sidecar
        for pvs, pvs_specs in assembly.items():
            out_path = pvs_specs[0]["final"]
            cat_tmp = out_path + ".cat.tmp.avi"
            wav_tmp = out_path + ".audio.tmp.wav"
            st = fan_state.get(pvs)
            try:
                medialib.concat_video([s["out"] for s in pvs_specs], cat_tmp)
                if st is not None:
                    # the fan-out's start already decoded the full SRC
                    # stereo span — the remux reuses it (decode-once)
                    samples, srate = st["audio"], st["srate"]
                else:
                    total = float(
                        sum(s.get_segment_duration() for s in pvs.segments)
                    )
                    samples, srate = _decode_stereo(
                        pvs.src.file_path, 0.0, total
                    )
                _write_wav(wav_tmp, samples, srate)
                medialib.remux(cat_tmp, out_path, audio_path=wav_tmp)

                # stitch features: TI at each segment join diffs the next
                # segment's first frame against the previous one's last
                # (the single path's accumulator carry)
                stitched = SiTiAccumulator()
                prev_last = None
                for spec in pvs_specs:
                    if not spec["feat"].si:
                        # a segment whose duration rounds to zero canvas
                        # frames legitimately emits nothing
                        # (_segment_canvas_chunks); continuity carries
                        # over it untouched
                        continue
                    si = np.concatenate(
                        [np.asarray(x) for x in spec["feat"].si]
                    )
                    ti = np.concatenate(
                        [np.asarray(x) for x in spec["feat"].ti]
                    )
                    if prev_last is not None:
                        ti = ti.copy()
                        ti[0] = float(jnp.std(
                            jnp.asarray(spec["sink"].first, jnp.float32)
                            - jnp.asarray(prev_last, jnp.float32)
                        ))
                    prev_last = spec["sink"].last
                    stitched.extend(si, ti)
                stitched.write(out_path)
                Job(
                    label=f"avpvs {pvs.pvs_id}",
                    output_path=out_path,
                    fn=lambda: None,
                    logfile_path=pvs.get_logfile_path(),
                    provenance=_wo_buffer_provenance(
                        pvs, pvs_specs[0]["w"], pvs_specs[0]["h"],
                        pvs_specs[0]["pix_fmt"],
                    ),
                ).complete_externally()
                if st is not None:
                    # fan-out members commit now that the PVS's own
                    # artifact landed (same order as the short path:
                    # AVPVS first, members after)
                    st["fan"].close()
            except BaseException:
                if st is not None:
                    st["fan"].abort()
                if os.path.isfile(out_path):
                    os.unlink(out_path)
                clear_inprogress(out_path)
                SiTiAccumulator.discard(out_path)
                raise
            finally:
                for p in [cat_tmp, wav_tmp] + [s["out"] for s in pvs_specs]:
                    if os.path.isfile(p):
                        os.unlink(p)
        return f"{len(pvses)} AVPVS"

    return Job(
        label=f"avpvs-batch[{len(pvses)}] " + " ".join(p.pvs_id for p in pvses),
        output_path="",
        fn=run,
    )


#: Versioned record of the bufferer-kinematics ASSUMPTIONS baked into
#: every spinner-stalled AVPVS (VERDICT r4 #5). The upstream bufferer's
#: pip source is unreachable from this offline environment, so these are
#: pinned, not cited (ops/overlay.py header); they are calibratable from
#: a real bufferer clip via tools/bufferer_calibrate. If calibration ever
#: lands different constants, BUMP THE VERSION — artifacts rendered under
#: the old assumptions are then identifiable from provenance logs alone.
SPINNER_KINEMATICS = {
    "version": 1,
    "status": "ASSUMED",
    "rps": 1.0,  # mirrors ops/overlay.plan_stalling's spinner_rps default
    "direction": "clockwise",
    "phase": "continuous-across-events",
    "basis": "bufferer source unreachable offline; "
             "calibrate with tools/bufferer_calibrate",
}


def load_spinner(path: str) -> np.ndarray:
    """Load a spinner image as [H, W, 4] RGBA uint8."""
    from PIL import Image

    img = Image.open(path).convert("RGBA")
    return np.asarray(img, dtype=np.uint8)


def insert_stall_silence(audio: np.ndarray, srate: int, events) -> np.ndarray:
    """Insert stall-length silence at the wallclock event positions —
    the audio half of the bufferer pass, shared by `apply_stalling` and
    the fused driver (models/fused) so the two cannot drift."""
    pieces = []
    cursor = 0
    for t, d in sorted((float(e[0]), float(e[1])) for e in events):
        cut = int(round(t * srate))
        pieces.append(audio[cursor:cut])
        pieces.append(np.zeros((int(round(d * srate)), audio.shape[1]), np.int16))
        cursor = cut
    pieces.append(audio[cursor:])
    return np.concatenate([p for p in pieces if len(p)])


def make_stall_compositor(pix_fmt: str, spinner_path: Optional[str],
                          skipping: bool, n_rotations: int):
    """`fn(gathered_planes, stall, black, phase) -> quantized planes` —
    the per-chunk stall composite of `apply_stalling` (spinner bank
    prep + the sharded-vs-single-device routing), extracted so the
    fused driver (models/fused) runs the SAME math on the SAME code
    path. Inputs are the gathered source planes of one output chunk and
    its per-frame plan slices; the return value goes straight to the
    writer."""
    import jax

    ten_bit = "10" in pix_fmt
    depth_scale = 4.0 if ten_bit else 1.0
    sub_h, sub_w = fr.chroma_subsampling(pix_fmt)
    sp_y = sp_u = sp_v = sa = sa_c = None
    if not skipping and spinner_path:
        bank_yuv, bank_a = ov.prepare_spinner(
            load_spinner(spinner_path), n_rotations
        )
        # spinner bank is on the 8-bit scale; lift for 10-bit AVPVS
        sp_y = bank_yuv[:, 0] * depth_scale
        # chroma bank on the AVPVS chroma grid (420: half both dims,
        # 422: half width only)
        sp_u = bank_yuv[:, 1][:, ::sub_h, ::sub_w] * depth_scale
        sp_v = bank_yuv[:, 2][:, ::sub_h, ::sub_w] * depth_scale
        sa = bank_a
        if (sub_h, sub_w) == (2, 2):
            sa_c = ov.downsample_alpha(bank_a)
        else:
            sa_c = bank_a[:, ::sub_h, ::sub_w]

    black_values = (
        16.0 * depth_scale, 128.0 * depth_scale, 128.0 * depth_scale
    )
    devs = jax.devices()
    sharded = None
    grain = 1
    if len(devs) > 1:
        # the composite is frame-local: shard each chunk's frames
        # across every visible device (ops/overlay sharded path)
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(devs)
        sharded = ov.make_sharded_stall_renderer(
            mesh,
            (None,) * 5 if skipping or sp_y is None
            else (jnp.asarray(sp_y), jnp.asarray(sa),
                  jnp.asarray(sp_u), jnp.asarray(sp_v),
                  jnp.asarray(sa_c)),
            black_values, ten_bit, (sub_h, sub_w),
        )
        grain = mesh.shape["pvs"]

    def composite(gathered, stall, black, phase):
        sel_len = gathered[0].shape[0]
        if sharded is not None:
            pad = (-sel_len) % grain

            def padded(a, pad=pad):
                a = np.asarray(a)
                if pad:
                    a = np.concatenate(
                        [a, np.repeat(a[-1:], pad, axis=0)]
                    )
                return a

            outs = sharded(
                jnp.asarray(padded(gathered[0]), jnp.float32),
                jnp.asarray(padded(gathered[1]), jnp.float32),
                jnp.asarray(padded(gathered[2]), jnp.float32),
                jnp.asarray(padded(stall), jnp.float32),
                jnp.asarray(padded(black), jnp.float32),
                jnp.asarray(padded(phase), jnp.int32),
            )
            return [o[:sel_len] for o in outs]
        # single device: host-planned composite
        sub = ov.StallPlan(
            src_idx=np.arange(sel_len, dtype=np.int32),
            stall_mask=np.asarray(stall),
            black_mask=np.asarray(black),
            phase=np.asarray(phase),
        )
        y = jnp.asarray(gathered[0], jnp.float32)
        u = jnp.asarray(gathered[1], jnp.float32)
        v = jnp.asarray(gathered[2], jnp.float32)
        oy = ov.render_stalled_plane(
            y, sub, sp_y, sa, black_value=black_values[0],
            crop_align=(sub_h, sub_w),
        )
        ou = ov.render_stalled_plane(
            u, sub, sp_u, sa_c, black_value=black_values[1],
            crop_align=(sub_h, sub_w), grid_scale=(sub_h, sub_w),
        )
        ovv = ov.render_stalled_plane(
            v, sub, sp_v, sa_c, black_value=black_values[2],
            crop_align=(sub_h, sub_w), grid_scale=(sub_h, sub_w),
        )
        return fr.quantize_device([oy, ou, ovv], ten_bit)

    return composite


def apply_stalling(
    pvs: Pvs,
    spinner_path: Optional[str] = None,
    n_rotations: int = 64,
) -> Optional[Job]:
    """The bufferer pass (reference p03:216-260): re-render the
    wo_buffer AVPVS with stall insertions (spinner over black frames) or
    frame-freeze skipping."""
    if not pvs.has_buffering():
        return None
    in_path = pvs.get_avpvs_wo_buffer_file_path()
    out_path = pvs.get_avpvs_file_path()
    skipping = pvs.has_framefreeze()
    events = pvs.get_buff_events_media_time()

    def run() -> str:
        with VideoReader(in_path) as probe_reader:
            rate = probe_reader.fps
            pix_fmt = probe_reader.pix_fmt
            w, hgt = probe_reader.width, probe_reader.height
        # frame count without a decode pass: container metadata, else a
        # packet scan (FFV1 is intra-only: one packet per frame)
        vstreams = [
            s for s in medialib.probe(in_path)["streams"]
            if s["codec_type"] == "video"
        ]
        n = int(vstreams[0].get("nb_frames") or 0) if vstreams else 0
        if n <= 0:
            n = len(medialib.scan_packets(in_path, "video")["size"])
        plan = ov.plan_stalling(
            n, rate, events, skipping=skipping, black_frame=True,
            n_rotations=n_rotations,
        )
        composite = make_stall_compositor(
            pix_fmt, spinner_path, skipping, n_rotations
        )

        # audio: decode, insert stall silence at wallclock positions
        audio = None
        srate = 48000
        try:
            # the wo_buffer AVPVS is stereo by construction; channels=2
            # just pins the writer contract against a surprise layout
            audio, srate = _decode_stereo(in_path)
        except medialib.MediaError:
            audio = None
        if audio is not None and audio.size and not skipping:
            audio = insert_stall_silence(audio, srate, events)

        # stream the output timeline: the plan's source indices are
        # monotonic nondecreasing (play/freeze/repeat), so one decode pass
        # feeds the gather in CHUNK-frame batches — decode prefetched
        # ahead, spinner composite on device, FFV1 writeback on the
        # writer thread (bounded memory for arbitrarily long PVSes)
        with VideoReader(in_path) as reader, pf.AsyncWriter(
            _ffv1_writer(
                out_path, w, hgt, pix_fmt, rate,
                with_audio=audio is not None and audio.size > 0,
                sample_rate=srate,
            )
        ) as writer:
            if audio is not None and audio.size:
                writer.write_audio(audio)
            chunk = chunk_frames()
            chunks = pf.stream_monotonic_gather(
                reader, lambda k: int(plan.src_idx[k]), plan.n_out, chunk
            )
            with pf.Prefetcher(chunks, depth=2) as pre:
                for chunk_no, gathered in enumerate(pre):
                    start = chunk_no * chunk
                    sel_len = gathered[0].shape[0]
                    writer.put(composite(
                        gathered,
                        plan.stall_mask[start: start + sel_len],
                        plan.black_mask[start: start + sel_len],
                        plan.phase[start: start + sel_len],
                    ))
        return out_path

    lf = pvs.get_logfile_path()
    prov = {
        "pvs": pvs.pvs_id,
        "mode": "skipping" if skipping else "spinner-stall",
        "events": events,
    }
    if not skipping:
        prov["spinner_kinematics"] = dict(
            SPINNER_KINEMATICS, n_rotations=n_rotations
        )
    # plan: the wo_buffer render is THE input (its digest covers every
    # upstream knob transitively), plus the stall schedule and spinner.
    # NOTE the input file is produced earlier in the same p03 run, so the
    # stage plans stalling only after phase one executed (commit_to_store
    # re-resolves the hash at commit time regardless).
    plan = {
        "op": "avpvs_stalling",
        "input": store_keys.file_ref(in_path),
        "events": [[float(e[0]), float(e[1])] for e in events],
        "mode": "skipping" if skipping else "spinner-stall",
        "spinner": (
            store_keys.file_ref(spinner_path)
            if not skipping and spinner_path else None
        ),
        "kinematics": (
            dict(SPINNER_KINEMATICS, n_rotations=n_rotations)
            if not skipping else None
        ),
        # requested, not effective: the input's pix_fmt is unknown until
        # run time, so a 10-bit ffv1 fallback over-invalidates on codec
        # flips rather than under-invalidating
        "codec": avpvs_codec(),
        # unconditional (even for a requested rawvideo codec, whose
        # 10-bit fallback writes ffv1): over-invalidating a rawvideo
        # plan on a slice-knob flip is cheap; under-keying the fallback
        # would poison the byte-addressed cache
        "ffv1_slices": ffv1_effective_slices(),
    }
    return Job(
        label=f"stalling {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        plan=plan,
        # own provenance file: the wo_buffer render already owns
        # logs/<pvs>.log and a shared path would overwrite it
        logfile_path=(lf[:-4] if lf.endswith(".log") else lf) + "_stalling.log",
        provenance=prov,
    )
