"""AVPVS model — the p03 pixel-domain core (reference p03_generateAvPvs.py
+ lib/ffmpeg.py:940-1105, :1262-1289; bufferer pass p03:216-260).

Short tests: decode the single segment → device rescale to the AVPVS canvas
(bicubic, reference create_avpvs_short :940-1000) → FFV1(+FLAC) AVI.

Long tests: per segment, decode → device rescale → resample onto the canvas
frame rate (the nullsrc-overlay trick of create_avpvs_segment :1003-1055:
exactly duration×rate frames, last frame repeated when short) → streamed
into one FFV1 writer (the file-based tmp-segment + concat demuxer of the
reference, :1058-1105, collapses into an in-process stream) → SRC audio
muxed as pcm_s16le 2ch (audio_mux :1262-1289).

Stalling pass (both): a StallPlan from the PVS buff events drives the
device gather + spinner composite (ops/overlay — the bufferer
re-implementation), with silence inserted into the audio during stalls.
Frame-freeze HRCs use skipping mode (no spinner, length preserved).

Device work is chunked over CHUNK-frame batches so arbitrarily long PVSes
stream through bounded HBM.
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config.domain import Pvs
from ..engine.jobs import Job
from ..io import medialib
from ..io.video import VideoReader, VideoWriter
from ..ops import fps as fps_ops
from ..ops import overlay as ov
from ..utils.log import get_logger
from . import frames as fr

CHUNK = 64  # frames per device batch


def avpvs_dimensions(pvs: Pvs, post_proc_id: int = 0) -> tuple[int, int]:
    """(width, height) of the AVPVS canvas: aspect-aware dims vs the
    post-processing coding size, overridden upward when the encoded segment
    is taller (reference create_avpvs_short :976-986)."""
    pp = pvs.test_config.post_processings[post_proc_id]
    w, h = fr.calculate_avpvs_video_dimensions(
        pvs.src.stream_info["width"],
        pvs.src.stream_info["height"],
        pp.coding_width,
        pp.coding_height,
    )
    ql = pvs.segments[0].quality_level
    if ql.height > h:
        w, h = ql.width, ql.height
    return w, h


def canvas_fps(pvs: Pvs, avpvs_src_fps: bool = False) -> float:
    """AVPVS canvas frame rate: 60 by default, SRC fps with -z
    (reference create_avpvs_segment :1030-1033, p03 flags)."""
    return pvs.src.get_fps() if avpvs_src_fps else 60.0


def _ffv1_writer(path: str, w: int, h: int, pix_fmt: str, rate: float,
                 with_audio: bool, sample_rate: int = 48000) -> VideoWriter:
    frac = Fraction(rate).limit_denominator(1001)
    audio = dict(audio_codec="pcm_s16le", sample_rate=sample_rate, channels=2) if with_audio else {}
    # FFV1 level 3 + slicecrc stream integrity (reference :1047: -level 3
    # -coder 1 -context 1 -slicecrc 1); -threads 4 parity
    return VideoWriter(
        path, "ffv1", w, h, pix_fmt, (frac.numerator, frac.denominator),
        threads=4, opts="level=3:coder=1:context=1:slicecrc=1", **audio,
    )


def _segment_to_canvas(seg, w: int, h: int, rate: float, pix_fmt: str):
    """Decode one encoded segment and yield [T,H,W] uint8 plane chunks on
    the canvas grid/rate (exactly round(duration*rate) frames)."""
    with VideoReader(seg.file_path) as reader:
        planes = fr.stack_planes(list(reader))
        seg_fps = reader.fps
    if not planes:
        raise medialib.MediaError(f"no frames in segment {seg.file_path}")
    n = planes[0].shape[0]
    n_out = int(round(seg.duration * rate))
    t_out = np.arange(n_out) / rate
    idx = np.clip(np.floor(t_out * seg_fps + 0.5).astype(np.int64), 0, n - 1)
    sub = fr.chroma_subsampling(pix_fmt)
    for start in range(0, n_out, CHUNK):
        sel = idx[start : start + CHUNK]
        chunk = [p[sel] for p in planes]
        scaled = fr.scale_yuv_frames(chunk, h, w, "bicubic", sub)
        yield fr.to_uint8(scaled, ten_bit="10" in pix_fmt)


def create_avpvs_wo_buffer(
    pvs: Pvs,
    avpvs_src_fps: bool = False,
    force_60_fps: bool = False,
) -> Optional[Job]:
    """The decode+rescale(+concat+audio) stage producing the pre-stalling
    AVPVS (or the final one when the HRC has no buffering)."""
    tc = pvs.test_config
    out_path = (
        pvs.get_avpvs_wo_buffer_file_path()
        if pvs.has_buffering()
        else pvs.get_avpvs_file_path()
    )
    w, h = avpvs_dimensions(pvs)
    pix_fmt = pvs.get_pix_fmt_for_avpvs()

    def run() -> str:
        if tc.is_short():
            # single segment, native segment frame rate unless -z/-f60
            seg = pvs.segments[0]
            with VideoReader(seg.file_path) as reader:
                planes = fr.stack_planes(list(reader))
                seg_fps = reader.fps
            rate = pvs.src.get_fps() if avpvs_src_fps else (60.0 if force_60_fps else seg_fps)
            n = planes[0].shape[0]
            if rate != seg_fps:
                idx = fps_ops.fps_resample_indices(n, seg_fps, rate)
                planes = [p[idx] for p in planes]
            sub = fr.chroma_subsampling(pix_fmt)
            with _ffv1_writer(out_path, w, h, pix_fmt, rate, with_audio=False) as writer:
                for start in range(0, planes[0].shape[0], CHUNK):
                    chunk = [p[start : start + CHUNK] for p in planes]
                    scaled = fr.scale_yuv_frames(chunk, h, w, "bicubic", sub)
                    for out in zip(*(np.asarray(p) for p in fr.to_uint8(scaled, "10" in pix_fmt))):
                        writer.write(*out)
        else:
            rate = canvas_fps(pvs, avpvs_src_fps)
            total = float(sum(s.get_segment_duration() for s in pvs.segments))
            samples, srate = medialib.decode_audio_s16(
                pvs.src.file_path, 0.0, total
            )
            if samples.ndim != 2 or samples.shape[1] != 2:
                samples = np.repeat(samples.reshape(-1, 1), 2, axis=1)
            with _ffv1_writer(
                out_path, w, h, pix_fmt, rate, with_audio=True, sample_rate=srate
            ) as writer:
                writer.write_audio(samples)
                for seg in pvs.segments:
                    for chunk in _segment_to_canvas(seg, w, h, rate, pix_fmt):
                        for out in zip(*(np.asarray(p) for p in chunk)):
                            writer.write(*out)
        return out_path

    return Job(
        label=f"avpvs {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        logfile_path=pvs.get_logfile_path(),
        provenance={
            "pvs": pvs.pvs_id,
            "pipeline": {
                "canvas": [w, h],
                "pix_fmt": pix_fmt,
                "segments": [s.filename for s in pvs.segments],
                "codec": "ffv1(level3,slicecrc)",
            },
        },
    )


def load_spinner(path: str) -> np.ndarray:
    """Load a spinner image as [H, W, 4] RGBA uint8."""
    from PIL import Image

    img = Image.open(path).convert("RGBA")
    return np.asarray(img, dtype=np.uint8)


def apply_stalling(
    pvs: Pvs,
    spinner_path: Optional[str] = None,
    n_rotations: int = 64,
) -> Optional[Job]:
    """The bufferer pass (reference p03:216-260): re-render the
    wo_buffer AVPVS with stall insertions (spinner over black frames) or
    frame-freeze skipping."""
    if not pvs.has_buffering():
        return None
    in_path = pvs.get_avpvs_wo_buffer_file_path()
    out_path = pvs.get_avpvs_file_path()
    skipping = pvs.has_framefreeze()
    events = pvs.get_buff_events_media_time()

    def run() -> str:
        with VideoReader(in_path) as reader:
            planes = fr.stack_planes(list(reader))  # host uint8/uint16
            rate = reader.fps
            pix_fmt = reader.pix_fmt
            w, hgt = reader.width, reader.height
        n = planes[0].shape[0]
        ten_bit = "10" in pix_fmt
        plan = ov.plan_stalling(
            n, rate, events, skipping=skipping, black_frame=True,
            n_rotations=n_rotations,
        )
        depth_scale = 4.0 if ten_bit else 1.0
        sub_h, sub_w = fr.chroma_subsampling(pix_fmt)
        sp_y = sp_u = sp_v = sa = sa_c = None
        if not skipping and spinner_path:
            bank_yuv, bank_a = ov.prepare_spinner(
                load_spinner(spinner_path), n_rotations
            )
            # spinner bank is on the 8-bit scale; lift for 10-bit AVPVS
            sp_y = bank_yuv[:, 0] * depth_scale
            # chroma bank on the AVPVS chroma grid (420: half both dims,
            # 422: half width only)
            sp_u = bank_yuv[:, 1][:, ::sub_h, ::sub_w] * depth_scale
            sp_v = bank_yuv[:, 2][:, ::sub_h, ::sub_w] * depth_scale
            sa = bank_a
            if (sub_h, sub_w) == (2, 2):
                sa_c = ov.downsample_alpha(bank_a)
            else:
                sa_c = bank_a[:, ::sub_h, ::sub_w]

        # audio: decode, insert stall silence at wallclock positions
        audio = None
        srate = 48000
        try:
            audio, srate = medialib.decode_audio_s16(in_path)
        except medialib.MediaError:
            audio = None
        if audio is not None and audio.size and not skipping:
            pieces = []
            cursor = 0
            for t, d in sorted((float(e[0]), float(e[1])) for e in events):
                cut = int(round(t * srate))
                pieces.append(audio[cursor:cut])
                pieces.append(np.zeros((int(round(d * srate)), audio.shape[1]), np.int16))
                cursor = cut
            pieces.append(audio[cursor:])
            audio = np.concatenate([p for p in pieces if len(p)])

        with _ffv1_writer(
            out_path, w, hgt, pix_fmt, rate,
            with_audio=audio is not None and audio.size > 0, sample_rate=srate,
        ) as writer:
            if audio is not None and audio.size:
                writer.write_audio(audio)
            # stream the output timeline in CHUNK-frame device batches so
            # long PVSes stay within bounded HBM (input stays host uint8;
            # each batch gathers its own source frames)
            for start in range(0, plan.n_out, CHUNK):
                sel = plan.src_idx[start : start + CHUNK]
                # gather source frames on host; batch-local plan indices
                sub = ov.StallPlan(
                    src_idx=np.arange(len(sel), dtype=np.int32),
                    stall_mask=plan.stall_mask[start : start + CHUNK],
                    black_mask=plan.black_mask[start : start + CHUNK],
                    phase=plan.phase[start : start + CHUNK],
                )
                y = jnp.asarray(planes[0][sel], jnp.float32)
                u = jnp.asarray(planes[1][sel], jnp.float32)
                v = jnp.asarray(planes[2][sel], jnp.float32)
                oy = ov.render_stalled_plane(
                    y, sub, sp_y, sa, black_value=16.0 * depth_scale
                )
                ou = ov.render_stalled_plane(
                    u, sub, sp_u, sa_c, black_value=128.0 * depth_scale
                )
                ovv = ov.render_stalled_plane(
                    v, sub, sp_v, sa_c, black_value=128.0 * depth_scale
                )
                outs = fr.to_uint8([oy, ou, ovv], ten_bit)
                for i in range(outs[0].shape[0]):
                    writer.write(*(np.asarray(p[i]) for p in outs))
        return out_path

    return Job(
        label=f"stalling {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        provenance={
            "pvs": pvs.pvs_id,
            "mode": "skipping" if skipping else "spinner-stall",
            "events": events,
        },
    )
