"""CPVS model — the p04 stage (reference p04_generateCpvs.py +
lib/ffmpeg.py create_cpvs :1149-1247, create_preview :1250-1259).

PC context: AVPVS → display frame rate → centered pad to the display
canvas when the AVPVS is shorter → rawvideo/UYVY422 AVI (8-bit) or
v210/yuv422p10le (10-bit); audio none (short) or pcm_s16le 2ch trimmed to
the HRC duration (long). Mobile/tablet: x264 CRF mp4 (high profile,
faststart) with scale/pad to the display dims; AAC 512k for long tests.
Long tests get RMS loudness normalization to -23 dBFS (the reference's
ffmpeg-normalize step, lib/ffmpeg.py:1233-1245) applied in-process.
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config.domain import PostProcessing, Pvs
from ..engine.jobs import Job
from ..io import medialib
from ..io.video import VideoReader, VideoWriter
from ..ops import fps as fps_ops
from ..ops import pad as pad_ops
from ..ops import pixfmt as pf
from ..utils.log import get_logger
from . import frames as fr
from .avpvs import avpvs_dimensions

CHUNK = 64


def normalize_rms(samples: np.ndarray, target_dbfs: float = -23.0) -> np.ndarray:
    """RMS loudness normalization (ffmpeg-normalize `-nt rms` equivalent)."""
    if samples.size == 0:
        return samples
    x = samples.astype(np.float64) / 32768.0
    rms = np.sqrt(np.mean(x * x))
    if rms <= 0:
        return samples
    gain = 10.0 ** ((target_dbfs - 20.0 * np.log10(rms)) / 20.0)
    return np.clip(x * gain * 32768.0, -32768, 32767).astype(np.int16)


def _read_avpvs(pvs: Pvs):
    path = pvs.get_avpvs_file_path()
    with VideoReader(path) as r:
        planes = fr.stack_planes(list(r))
        return planes, r.fps, r.pix_fmt, r.width, r.height


def _audio_for_long(pvs: Pvs, normalize: bool):
    try:
        samples, rate = medialib.decode_audio_s16(pvs.get_avpvs_file_path())
    except medialib.MediaError:
        return None, 48000
    total = pvs.hrc.get_long_hrc_duration()
    samples = samples[: int(round(total * rate))]
    if normalize:
        samples = normalize_rms(samples)
    return samples, rate


def create_cpvs(
    pvs: Pvs,
    post_processing: PostProcessing,
    rawvideo: bool = False,
    nonraw_crf: int = 17,
    mobile_vprofile: str = "high",
    mobile_preset: str = "fast",
) -> Optional[Job]:
    tc = pvs.test_config
    pp = post_processing
    out_path = pvs.get_cpvs_file_path(context=pp.processing_type, rawvideo=rawvideo)
    is_pc = pp.processing_type in ("pc", "hd-pc-home", "uhd-pc-home")

    def run() -> str:
        planes, rate, pix_fmt, w, h = _read_avpvs(pvs)
        n = planes[0].shape[0]
        # display frame rate resample (reference fps=displayFrameRate filter)
        if rate != pp.display_frame_rate:
            idx = fps_ops.fps_resample_indices(n, rate, float(pp.display_frame_rate))
            planes = [p[idx] for p in planes]
        out_rate = Fraction(pp.display_frame_rate).limit_denominator(1001)
        ten_bit = "10" in pix_fmt

        audio = None
        srate = 48000
        if tc.is_long():
            audio, srate = _audio_for_long(pvs, normalize=True)

        if is_pc:
            vcodec, target_pix_fmt = pvs.get_vcodec_and_pix_fmt_for_cpvs(rawvideo)
            need_pad = h < pp.coding_height
            dw, dh = pp.display_width, pp.display_height
            aud = (
                dict(audio_codec="pcm_s16le", sample_rate=srate, channels=2)
                if (tc.is_long() and audio is not None and audio.size)
                else {}
            )
            with VideoWriter(
                out_path, vcodec, dw if need_pad else w, dh if need_pad else h,
                target_pix_fmt, (out_rate.numerator, out_rate.denominator), **aud,
            ) as writer:
                if aud:
                    writer.write_audio(audio)
                for start in range(0, planes[0].shape[0], CHUNK):
                    y = jnp.asarray(planes[0][start : start + CHUNK])
                    u = jnp.asarray(planes[1][start : start + CHUNK])
                    v = jnp.asarray(planes[2][start : start + CHUNK])
                    if "420" in pix_fmt and not rawvideo:
                        # packed/uyvy and v210 outputs are 422-based: lift
                        # chroma; rawvideo passes through the AVPVS layout
                        u, v = pf.chroma_420_to_422(u, v)
                    if need_pad:
                        # chroma pads on its own grid: full height for 422
                        # layouts, half height for raw 420 passthrough
                        c_h = dh // 2 if (rawvideo and "420" in pix_fmt) else dh
                        y = pad_ops.pad_center(y, dh, dw, 16.0 if not ten_bit else 64.0)
                        u = pad_ops.pad_center(u, c_h, dw // 2, 128.0 if not ten_bit else 512.0)
                        v = pad_ops.pad_center(v, c_h, dw // 2, 128.0 if not ten_bit else 512.0)
                    if rawvideo:
                        # raw passthrough in the AVPVS pix_fmt
                        outs = fr.to_uint8([y, u, v], ten_bit)
                        for i in range(outs[0].shape[0]):
                            writer.write(*(np.asarray(p[i]) for p in outs))
                    elif not ten_bit:
                        # packed UYVY422 via the rawvideo encoder
                        yq, uq, vq = fr.to_uint8([y, u, v], False)
                        packed = pf.pack_uyvy422(
                            jnp.asarray(yq), jnp.asarray(uq), jnp.asarray(vq)
                        )
                        for i in range(packed.shape[0]):
                            writer.write(np.asarray(packed[i]))
                    else:
                        # v210 encoder takes planar yuv422p10le input
                        outs = fr.to_uint8([y, u, v], True)
                        for i in range(outs[0].shape[0]):
                            writer.write(*(np.asarray(p[i]) for p in outs))
        else:
            # mobile / tablet: x264 CRF mp4, scale (+pad) to display dims;
            # output is always 8-bit yuv420p, so 10-bit AVPVS planes are
            # depth-converted first
            if ten_bit:
                planes = [
                    np.asarray(pf.depth_10_to_8(jnp.asarray(p))) for p in planes
                ]
            dw, dh = pp.display_width, pp.display_height
            aud = (
                dict(audio_codec="aac", sample_rate=srate, channels=2,
                     audio_bitrate_kbps=512)
                if (tc.is_long() and audio is not None and audio.size)
                else {}
            )
            opts = (
                f"crf={nonraw_crf}:preset={mobile_preset}:"
                f"profile={mobile_vprofile}:movflags=+faststart"
            )
            need_pad = (pp.display_height != pp.coding_height) or (h < pp.coding_height)
            with VideoWriter(
                out_path, "libx264", dw, dh, "yuv420p",
                (out_rate.numerator, out_rate.denominator), opts=opts, **aud,
            ) as writer:
                if aud:
                    writer.write_audio(audio)
                for start in range(0, planes[0].shape[0], CHUNK):
                    chunk = [p[start : start + CHUNK] for p in planes]
                    if need_pad:
                        # pad-only at native AVPVS size (letterbox), the
                        # reference's padding branch applies no scale
                        # (lib/ffmpeg.py:1207-1210)
                        y, u, v = pad_ops.pad_yuv(
                            tuple(jnp.asarray(p) for p in chunk), dh, dw, "yuv420p"
                        )
                    else:
                        scaled = fr.scale_yuv_frames(chunk, dh, dw, "bicubic", (2, 2))
                        y, u, v = scaled
                    outs = fr.to_uint8([y, u, v], False)
                    for i in range(outs[0].shape[0]):
                        writer.write(*(np.asarray(p[i]) for p in outs))
        return out_path

    return Job(
        label=f"cpvs {pvs.pvs_id} {pp.processing_type}",
        output_path=out_path,
        fn=run,
        provenance={
            "pvs": pvs.pvs_id,
            "context": pp.processing_type,
            "display": [pp.display_width, pp.display_height],
            "rawvideo": rawvideo,
        },
    )


def create_preview(pvs: Pvs) -> Optional[Job]:
    """ProRes + AAC preview (reference create_preview :1250-1259)."""
    out_path = pvs.get_preview_file_path()

    def run() -> str:
        planes, rate, pix_fmt, w, h = _read_avpvs(pvs)
        frac = Fraction(rate).limit_denominator(1001)
        audio = None
        srate = 48000
        try:
            audio, srate = medialib.decode_audio_s16(pvs.get_avpvs_file_path())
        except medialib.MediaError:
            audio = None
        aud = (
            dict(audio_codec="aac", sample_rate=srate, channels=2)
            if audio is not None and audio.size
            else {}
        )
        with VideoWriter(
            out_path, "prores_ks", w, h, "yuv422p10le",
            (frac.numerator, frac.denominator), **aud,
        ) as writer:
            if aud:
                writer.write_audio(audio)
            for start in range(0, planes[0].shape[0], CHUNK):
                y = jnp.asarray(planes[0][start : start + CHUNK])
                u = jnp.asarray(planes[1][start : start + CHUNK])
                v = jnp.asarray(planes[2][start : start + CHUNK])
                if "420" in pix_fmt:
                    u, v = pf.chroma_420_to_422(u, v)
                if "10" not in pix_fmt:
                    y, u, v = (pf.depth_8_to_10(q.astype(jnp.uint8)) for q in fr_round(y, u, v))
                outs = [np.asarray(q) for q in (y, u, v)]
                for i in range(outs[0].shape[0]):
                    writer.write(*(p[i] for p in outs))
        return out_path

    def fr_round(*planes):
        return tuple(
            jnp.clip(jnp.floor(p.astype(jnp.float32) + 0.5), 0, 255).astype(jnp.uint8)
            for p in planes
        )

    return Job(
        label=f"preview {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        provenance={"pvs": pvs.pvs_id, "codec": "prores_ks"},
    )
