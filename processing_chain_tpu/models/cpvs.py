"""CPVS model — the p04 stage (reference p04_generateCpvs.py +
lib/ffmpeg.py create_cpvs :1149-1247, create_preview :1250-1259).

PC context: AVPVS → display frame rate → centered pad to the display
canvas when the AVPVS is shorter → rawvideo/UYVY422 AVI (8-bit) or
v210/yuv422p10le (10-bit); audio none (short) or pcm_s16le 2ch trimmed to
the HRC duration (long). Mobile/tablet: x264 CRF mp4 (high profile,
faststart) with scale/pad to the display dims; AAC 512k for long tests.
Long tests get RMS loudness normalization to -23 dBFS (the reference's
ffmpeg-normalize step, lib/ffmpeg.py:1233-1245) applied in-process.
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config.domain import PostProcessing, Pvs
from ..engine import prefetch as pfe
from ..engine.jobs import Job
from ..io import medialib
from ..io.video import VideoReader, VideoWriter
from ..ops import pad as pad_ops
from ..ops import pixfmt as pf
from ..store import keys as store_keys
from . import avpvs
from . import frames as fr


def normalize_rms(samples: np.ndarray, target_dbfs: float = -23.0) -> np.ndarray:
    """RMS loudness normalization — ffmpeg-normalize 1.28.3 `-nt rms`
    semantics, reproduced step for step (reference lib/ffmpeg.py:1233-1245
    shells out to the tool; oracle-pinned by tests/test_ops.py):

    1. measure: ffmpeg volumedetect accumulates an exact power sum over
       every sample of every channel (s16 values / 32768) and PRINTS
       mean_volume at 0.1 dB; ffmpeg-normalize parses that printed value,
       so the measured level is quantized to 0.1 dB before use.
    2. gain: adjustment_db = target - mean_volume; no limiter — the tool
       only warns when the gain would clip.
    3. apply: the volume filter's s16 path is
       av_clip_int16(lrintf(x * gain)) — round to nearest (ties to even),
       clamp to [-32768, 32767].
    """
    if samples.size == 0:
        return samples
    x = samples.astype(np.float64)
    power = np.mean((x / 32768.0) ** 2)
    if power <= 0:
        return samples
    mean_volume_db = round(10.0 * np.log10(power), 1)  # volumedetect print
    gain = 10.0 ** ((target_dbfs - mean_volume_db) / 20.0)
    return np.clip(np.rint(x * gain), -32768, 32767).astype(np.int16)


def _avpvs_chunks(reader: VideoReader, dst_rate: Optional[float] = None):
    """Stream an open AVPVS reader as chunk_frames()-sized plane stacks,
    resampled to dst_rate when it differs (ffmpeg `fps=` semantics,
    streaming). O(chunk) memory for arbitrarily long PVSes — never the
    whole AVPVS (a 3-min 1080p60 10-bit one is ~30 GB stacked)."""
    chunk = avpvs.chunk_frames()
    if dst_rate is not None and dst_rate != reader.fps:
        return pfe.stream_fps_resample(reader, reader.fps, dst_rate, chunk)
    return pfe.iter_plane_chunks(reader, chunk)


def _limit_frames(chunks, n_max: int):
    """Cap a plane-chunk stream at n_max frames (the reference's `-t`
    output-duration trim, applied to the video stream)."""
    left = n_max
    if left <= 0:
        return
    for chunk in chunks:
        t = chunk[0].shape[0]
        yield [p[:left] for p in chunk] if t > left else chunk
        left -= min(t, left)
        if left <= 0:
            return


def trim_normalize_long_audio(
    samples: np.ndarray, rate: int, pvs: Pvs, normalize: bool
) -> np.ndarray:
    """The long-test audio treatment (reference `-t` trim + the
    ffmpeg-normalize step): shared by the decode-driven path and the
    fused driver so the two cannot drift."""
    total = pvs.hrc.get_long_hrc_duration()
    samples = samples[: int(round(total * rate))]
    if normalize:
        samples = normalize_rms(samples)
    return samples


def _audio_for_long(pvs: Pvs, normalize: bool):
    try:
        samples, rate = medialib.decode_audio_s16(pvs.get_avpvs_file_path())
    except medialib.MediaError:
        return None, 48000
    return trim_normalize_long_audio(samples, rate, pvs, normalize), rate


def cpvs_plan(
    pvs: Pvs,
    post_processing: PostProcessing,
    avpvs_height: int,
    rawvideo: bool = False,
    nonraw_crf: int = 17,
    mobile_vprofile: str = "high",
    mobile_preset: str = "fast",
) -> dict:
    """Pure decision record for one CPVS render — codec/pix_fmt, display
    fps, pad-vs-scale geometry, audio handling, loudness step — matching
    the reference's command construction (lib/ffmpeg.py:1149-1249: the
    pc rawvideo/v210 branch with its smaller-height padding rule, the
    mobile x264 CRF branch whose padding case applies NO scale, short
    tests' -an, long tests' -t total duration + ffmpeg-normalize step).
    `create_cpvs.run` executes exactly this plan; the reference-oracle
    suite compares it against the reference's own command strings."""
    tc = pvs.test_config
    pp = post_processing
    # the reference's pc branch matches only ["pc", "tv"] (create_cpvs
    # :1177) and "tv" is not a legal post-processing type (:953), so
    # hd-pc-home / uhd-pc-home take the x264 branch there — consistent
    # with their .mp4 output name (get_cpvs_file_path :124-130)
    is_pc = pp.processing_type == "pc"
    plan: dict = {
        "context": "pc" if is_pc else "mobile",
        # the display-rate resample applies to the pc branch only: the
        # reference's mobile branch carries NO fps filter (its fps line is
        # commented out, lib/ffmpeg.py:1205), so mobile/tablet CPVS keep
        # the AVPVS frame rate
        "fps": float(pp.display_frame_rate) if is_pc else None,
        "normalize": tc.is_long(),
        "t": float(pvs.hrc.get_long_hrc_duration()) if tc.is_long() else None,
    }
    if is_pc:
        vcodec, pix_fmt = pvs.get_vcodec_and_pix_fmt_for_cpvs(rawvideo)
        need_pad = avpvs_height < pp.coding_height
        plan.update(
            vcodec=vcodec,
            pix_fmt=pix_fmt,
            pad=(pp.display_width, pp.display_height) if need_pad else None,
            scale=None,
            audio=(
                dict(codec="pcm_s16le", channels=2) if tc.is_long() else None
            ),
        )
    else:
        need_pad = (
            pp.display_height != pp.coding_height
            or avpvs_height < pp.coding_height
        )
        plan.update(
            vcodec="libx264",
            pix_fmt="yuv420p",
            crf=nonraw_crf,
            preset=mobile_preset,
            profile=mobile_vprofile,
            pad=(pp.display_width, pp.display_height) if need_pad else None,
            scale=None if need_pad else (pp.display_width, pp.display_height),
            audio=(
                dict(codec="aac", bitrate_kbps=512, channels=2)
                if tc.is_long() else None
            ),
        )
    return plan


def t_cap_frames(t: float, rate: Fraction) -> int:
    """Frame count of ffmpeg's `-t <t>` output cap: every frame with
    pts < t, i.e. frames k with k/fps < t — ceil(t*fps) for fractional
    rates (29.97 fps, t=60 -> 1799, not round(1798.2)=1798) and exactly
    t*fps when the product lands on an integer.

    `t` is quantized the way the value reaches ffmpeg in the reference
    (`-t {total_duration}` at lib/ffmpeg.py:1191 pc / :1221 mobile):
    Python's shortest-repr
    decimal, parsed by ffmpeg at microsecond precision — NOT the raw
    binary float (Fraction(0.1+0.2) would carry the 4e-17 fuzz across
    the ceil and emit one extra frame when t*fps lands on an integer)."""
    t_us = round(Fraction(str(t)) * 1_000_000)
    return math.ceil(Fraction(t_us, 1_000_000) * rate)


def make_cpvs_transform(plan: dict, post_processing: PostProcessing,
                        pix_fmt: str, rawvideo: bool):
    """The per-chunk device transform one CPVS render applies, built
    from the pure decision record (`cpvs_plan`). ONE definition serves
    the decode-driven path (`create_cpvs`) and the fused in-memory path
    (models/fused) — fused-vs-unfused parity is by construction, not by
    parallel maintenance."""
    pp = post_processing
    ten_bit = "10" in pix_fmt
    dw, dh = pp.display_width, pp.display_height
    need_pad = plan["pad"] is not None

    if plan["context"] == "pc":
        def pc_chunk(chunk):
            y, u, v = (jnp.asarray(p) for p in chunk[:3])
            if "420" in pix_fmt and not rawvideo:
                # packed/uyvy and v210 outputs are 422-based: lift
                # chroma; rawvideo passes through the AVPVS layout
                u, v = pf.chroma_420_to_422(u, v)
            if need_pad:
                # chroma pads on its own grid: full height for 422
                # layouts, half height for raw 420 passthrough
                c_h = dh // 2 if (rawvideo and "420" in pix_fmt) else dh
                y = pad_ops.pad_center(y, dh, dw, 16.0 if not ten_bit else 64.0)
                u = pad_ops.pad_center(u, c_h, dw // 2, 128.0 if not ten_bit else 512.0)
                v = pad_ops.pad_center(v, c_h, dw // 2, 128.0 if not ten_bit else 512.0)
            if rawvideo:
                # raw passthrough in the AVPVS pix_fmt
                return fr.to_uint8([y, u, v], ten_bit)
            if not ten_bit:
                # packed UYVY422 via the rawvideo encoder
                yq, uq, vq = fr.to_uint8([y, u, v], False)
                return [pf.pack_uyvy422(
                    jnp.asarray(yq), jnp.asarray(uq), jnp.asarray(vq)
                )]
            # v210 encoder takes planar yuv422p10le input
            return fr.to_uint8([y, u, v], True)

        return pc_chunk

    def mobile_chunk(chunk):
        # mobile / tablet: output is always 8-bit yuv420p, so 10-bit
        # AVPVS chunks are depth-converted first
        chunk = list(chunk[:3])
        if ten_bit:
            chunk = [pf.depth_10_to_8(jnp.asarray(p)) for p in chunk]
        if need_pad:
            # pad-only at native AVPVS size (letterbox), the
            # reference's padding branch applies no scale
            # (lib/ffmpeg.py:1207-1210)
            y, u, v = pad_ops.pad_yuv(
                tuple(jnp.asarray(p) for p in chunk), dh, dw, "yuv420p"
            )
        else:
            y, u, v = fr.scale_yuv_frames(chunk, dh, dw, "bicubic", (2, 2))
        return fr.to_uint8([y, u, v], False)

    return mobile_chunk


def open_cpvs_writer(out_path: str, plan: dict,
                     post_processing: PostProcessing, w: int, h: int,
                     out_rate: Fraction, audio, srate: int):
    """(VideoWriter, has_audio) for one CPVS render, plan-directed —
    the other half of the shared execution surface (see
    `make_cpvs_transform`)."""
    pp = post_processing
    dw, dh = pp.display_width, pp.display_height
    need_pad = plan["pad"] is not None
    if plan["context"] == "pc":
        aud = (
            dict(audio_codec=plan["audio"]["codec"], sample_rate=srate,
                 channels=plan["audio"]["channels"])
            if (plan["audio"] and audio is not None and audio.size)
            else {}
        )
        writer = VideoWriter(
            out_path, plan["vcodec"], dw if need_pad else w,
            dh if need_pad else h, plan["pix_fmt"],
            (out_rate.numerator, out_rate.denominator), **aud,
        )
        return writer, bool(aud)
    aud = (
        dict(audio_codec=plan["audio"]["codec"], sample_rate=srate,
             channels=plan["audio"]["channels"],
             audio_bitrate_kbps=plan["audio"]["bitrate_kbps"])
        if (plan["audio"] and audio is not None and audio.size)
        else {}
    )
    opts = (
        f"crf={plan['crf']}:preset={plan['preset']}:"
        f"profile={plan['profile']}:movflags=+faststart"
    )
    writer = VideoWriter(
        out_path, "libx264", dw, dh, "yuv420p",
        (out_rate.numerator, out_rate.denominator), opts=opts, **aud,
    )
    return writer, bool(aud)


def cpvs_out_rate(plan: dict, avpvs_fps: float) -> Fraction:
    """Output frame rate of one CPVS render: the plan's display rate
    (pc branch) or the AVPVS rate (mobile), rationalized exactly as the
    writer consumes it."""
    return Fraction(
        plan["fps"] if plan["fps"] is not None else avpvs_fps
    ).limit_denominator(1001)


def create_cpvs(
    pvs: Pvs,
    post_processing: PostProcessing,
    rawvideo: bool = False,
    nonraw_crf: int = 17,
    mobile_vprofile: str = "high",
    mobile_preset: str = "fast",
) -> Optional[Job]:
    tc = pvs.test_config
    pp = post_processing
    out_path = pvs.get_cpvs_file_path(context=pp.processing_type, rawvideo=rawvideo)

    def run() -> str:
        with VideoReader(pvs.get_avpvs_file_path()) as reader:
            pix_fmt = reader.pix_fmt
            w, h = reader.width, reader.height
            plan = cpvs_plan(
                pvs, pp, h, rawvideo, nonraw_crf, mobile_vprofile,
                mobile_preset,
            )
            # display frame rate resample, streaming (reference
            # fps=displayFrameRate filter; pc branch only — mobile keeps
            # the AVPVS rate, see cpvs_plan)
            chunks = _avpvs_chunks(reader, plan["fps"])
            out_rate = cpvs_out_rate(plan, reader.fps)
            if plan["t"] is not None:
                # the reference's long-test `-t total_duration` cap
                chunks = _limit_frames(chunks, t_cap_frames(plan["t"], out_rate))

            audio = None
            srate = 48000
            if tc.is_long():
                audio, srate = _audio_for_long(pvs, normalize=plan["normalize"])

            transform = make_cpvs_transform(plan, pp, pix_fmt, rawvideo)
            vw, has_audio = open_cpvs_writer(
                out_path, plan, pp, w, h, out_rate, audio, srate
            )
            with pfe.AsyncWriter(vw) as writer:
                if has_audio:
                    writer.write_audio(audio)
                with pfe.Prefetcher(chunks, depth=2) as pre:
                    for chunk in pre:
                        writer.put(transform(chunk))
        return out_path

    # plan: the AVPVS digest covers every upstream knob transitively;
    # the rest is this render's own decision surface (cpvs_plan's
    # inputs) plus the resize-method identity — the scale/pad path's
    # pixel values depend on it (plan-purity, store/plan_schema.py)
    from ..ops import resize as resize_ops

    plan = {
        "op": "cpvs",
        "input": store_keys.file_ref(pvs.get_avpvs_file_path()),
        "resize": resize_ops.plan_resize_method(),
        "context": pp.processing_type,
        "display": [pp.display_width, pp.display_height],
        "coding": [pp.coding_width, pp.coding_height],
        "display_fps": float(pp.display_frame_rate)
        if pp.display_frame_rate is not None else None,
        "rawvideo": bool(rawvideo),
        "crf": int(nonraw_crf),
        "profile": mobile_vprofile,
        "preset": mobile_preset,
        "t": float(pvs.hrc.get_long_hrc_duration())
        if tc.is_long() else None,
    }

    return Job(
        label=f"cpvs {pvs.pvs_id} {pp.processing_type}",
        output_path=out_path,
        fn=run,
        plan=plan,
        provenance={
            "pvs": pvs.pvs_id,
            "context": pp.processing_type,
            "display": [pp.display_width, pp.display_height],
            "rawvideo": rawvideo,
        },
    )


def make_preview_transform(pix_fmt: str):
    """The per-chunk ProRes-preview transform; shared by the
    decode-driven path and the fused driver (see make_cpvs_transform)."""
    def fr_round(*planes):
        return tuple(
            jnp.clip(jnp.floor(p.astype(jnp.float32) + 0.5), 0, 255).astype(jnp.uint8)
            for p in planes
        )

    def preview_chunk(chunk):
        y, u, v = (jnp.asarray(p) for p in chunk[:3])
        if "420" in pix_fmt:
            u, v = pf.chroma_420_to_422(u, v)
        if "10" not in pix_fmt:
            y, u, v = (
                pf.depth_8_to_10(q.astype(jnp.uint8))
                for q in fr_round(y, u, v)
            )
        return [y, u, v]

    return preview_chunk


def open_preview_writer(out_path: str, w: int, h: int, fps: float,
                        audio, srate: int):
    """(VideoWriter, has_audio) for the ProRes preview. ProRes is
    all-intra: the same frame-parallel pool as the FFV1 writeback
    applies (PC_FFV1_WORKERS names the host intra-writeback pool, not
    one codec)."""
    from .avpvs import ffv1_workers

    aud = (
        dict(audio_codec="aac", sample_rate=srate, channels=2)
        if audio is not None and audio.size
        else {}
    )
    frac = Fraction(fps).limit_denominator(1001)
    workers = ffv1_workers()
    writer = VideoWriter(
        out_path, "prores_ks", w, h,
        "yuv422p10le", (frac.numerator, frac.denominator),
        opts=f"pc_fp_workers={workers}" if workers > 0 else "",
        **aud,
    )
    return writer, bool(aud)


def create_preview(pvs: Pvs) -> Optional[Job]:
    """ProRes + AAC preview (reference create_preview :1250-1259)."""
    out_path = pvs.get_preview_file_path()

    def run() -> str:
        audio = None
        srate = 48000
        try:
            audio, srate = medialib.decode_audio_s16(pvs.get_avpvs_file_path())
        except medialib.MediaError:
            audio = None
        with VideoReader(pvs.get_avpvs_file_path()) as reader:
            transform = make_preview_transform(reader.pix_fmt)
            vw, has_audio = open_preview_writer(
                out_path, reader.width, reader.height, reader.fps,
                audio, srate,
            )
            with pfe.AsyncWriter(vw) as writer:
                if has_audio:
                    writer.write_audio(audio)
                with pfe.Prefetcher(
                    pfe.iter_plane_chunks(reader, avpvs.chunk_frames()),
                    depth=2
                ) as pre:
                    for chunk in pre:
                        writer.put(transform(chunk))
        return out_path

    return Job(
        label=f"preview {pvs.pvs_id}",
        output_path=out_path,
        fn=run,
        plan={
            "op": "preview",
            "input": store_keys.file_ref(pvs.get_avpvs_file_path()),
            "codec": "prores_ks",
        },
        provenance={"pvs": pvs.pvs_id, "codec": "prores_ks"},
    )
