"""Shared frame-pipeline helpers for the artifact models."""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np

from ..ops import resize


def calculate_avpvs_video_dimensions(
    src_width: int, src_height: int, postproc_w: int, postproc_h: int
) -> tuple[int, int]:
    """AVPVS canvas dimensions (reference lib/ffmpeg.py:33-58).

    Same-size SRC → post-processing dims. Mobile-style targets narrower
    than the SRC adapt height to the SRC aspect ratio (rounded up to even);
    otherwise a (3-decimal) aspect-ratio mismatch keeps the SRC height.
    The reference's `&`-for-`and` precedence slip at ffmpeg.py:45 is on the
    do-not-copy list (SURVEY.md §7); this implements the intended check.
    """
    if src_width == postproc_w and src_height == postproc_h:
        return postproc_w, postproc_h
    src_ar = src_width / src_height
    post_ar = postproc_w / postproc_h
    w, h = postproc_w, postproc_h
    if postproc_w < src_width:
        if src_ar != post_ar:
            h = int(postproc_w / src_ar)
            if h % 2:
                h += 1
    else:
        if int(1000 * src_ar) != int(1000 * post_ar):
            h = src_height
    return w, h


def scale_to_width_keep_ar(
    src_h: int, src_w: int, target_w: int
) -> tuple[int, int]:
    """ffmpeg `scale=W:-2` semantics (reference encode filter,
    lib/ffmpeg.py:800): fixed width, proportional height rounded to the
    nearest even number."""
    h = int(round(target_w * src_h / src_w / 2.0)) * 2
    return h, target_w


def scale_yuv_frames(
    planes: list,
    dst_h: int,
    dst_w: int,
    kernel: str = "bicubic",
    chroma_sub: tuple[int, int] = (2, 2),
) -> list[jnp.ndarray]:
    """Device-resize stacked planar YUV [T, H, W] to a new luma size with
    chroma on its subsampled grid. chroma_sub = (sub_h, sub_w)."""
    import jax

    sub_h, sub_w = chroma_sub
    y = resize.resize_frames(jnp.asarray(planes[0]), dst_h, dst_w, kernel)
    u, v = (jnp.asarray(p) for p in planes[1:3])
    if (
        u.ndim == 3
        and u.shape == v.shape
        and isinstance(u, jax.core.Tracer)
    ):
        # Inside a trace (the sharded/jitted steps): one kernel call for
        # both chroma planes, stacked on the FRAME axis — per-frame resize
        # makes the outputs identical to two calls, and XLA owns the
        # concat/split so the saving is a real launch. Eagerly (the
        # streaming model paths) the concat + two slices would each be
        # their own dispatch + chroma-sized copy, costing more than the
        # saved call — keep per-plane calls there. 2-D [H, W] planes must
        # also stay per-plane (stacking them would merge on HEIGHT).
        uv = resize.resize_frames(
            jnp.concatenate([u, v], axis=0),
            dst_h // sub_h, dst_w // sub_w, kernel,
        )
        return [y, uv[: u.shape[0]], uv[u.shape[0]:]]
    return [
        y,
        resize.resize_frames(u, dst_h // sub_h, dst_w // sub_w, kernel),
        resize.resize_frames(v, dst_h // sub_h, dst_w // sub_w, kernel),
    ]


def chroma_subsampling(pix_fmt: str) -> tuple[int, int]:
    """(sub_h, sub_w) for a planar yuv pix_fmt."""
    if "420" in pix_fmt:
        return (2, 2)
    if "422" in pix_fmt:
        return (1, 2)
    return (1, 1)


def quantize_device(planes: list, ten_bit: bool = False) -> list[jnp.ndarray]:
    """Round/clip device float planes to the container bit depth *on
    device*, so the host transfer moves uint8/uint16 (¼ the bytes of
    float32) and can happen off-thread (engine.prefetch.AsyncWriter)."""
    hi, dt = (1023.0, jnp.uint16) if ten_bit else (255.0, jnp.uint8)
    out = []
    for p in planes:
        if p.dtype == dt:
            out.append(p)
        elif p.dtype in (jnp.uint8, jnp.uint16):
            # saturate, never wrap, on a narrowing integer cast
            out.append(jnp.clip(p.astype(jnp.int32), 0, int(hi)).astype(dt))
        else:
            out.append(jnp.clip(jnp.floor(p + 0.5), 0, hi).astype(dt))
    return out


def to_uint8(planes: list, ten_bit: bool = False) -> list[np.ndarray]:
    """Device float/int planes → host numpy in the container bit depth."""
    out = []
    for p in planes:
        arr = np.asarray(p)
        if ten_bit:
            if arr.dtype != np.uint16:
                arr = np.clip(np.floor(arr.astype(np.float64) + 0.5), 0, 1023).astype(np.uint16)
            out.append(arr)
        else:
            if arr.dtype != np.uint8:
                arr = np.clip(np.floor(arr.astype(np.float64) + 0.5), 0, 255).astype(np.uint8)
            out.append(arr)
    return out
