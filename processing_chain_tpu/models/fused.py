"""Fused p03+p04 driver (``PC_FUSE_P04``): single-decode chain.

The shipping chain decoded the committed AVPVS once per downstream
consumer — the stalling pass re-decoded the wo_buffer render, and every
PostProcessing CPVS (plus the preview) re-decoded the final AVPVS.
FAST's doctrine (arXiv:1603.08968, PAPERS.md) is to exploit structure
already computed upstream instead of re-doing it per stage: the AVPVS
frames the p03 device pass just quantized ARE the frames every one of
those decodes would produce (FFV1/rawvideo are lossless), so this
module renders everything downstream from the in-memory stream —

    SRC decode ─▶ device resize ─▶ quantized AVPVS chunks
                                       ├─▶ AVPVS writer        (as today)
                                       ├─▶ StallStream ─▶ composite
                                       │        ├─▶ stalled-AVPVS writer
                                       │        └─▶ (final stream)
                                       └─▶ per-PostProcessing CPVS
                                           pipelines + preview

ONE SRC decode feeds the AVPVS, the staged stalling pass and all CPVS
renders (`chain_io_decoder_opens_total` makes the invariant measurable).

Parity discipline — the whole feature is gated on the fused path
producing decoded-identical artifacts (the plan hashes are unchanged,
so the store serves fused and unfused runs interchangeably):

  * the CPVS/preview transforms and writer construction are the SAME
    functions the decode-driven path runs (models/cpvs
    make_cpvs_transform / open_cpvs_writer / make_preview_transform);
  * the stall composite is the SAME function apply_stalling runs
    (models/avpvs.make_stall_compositor), fed by `StallStream` — an
    incremental replay of ov.plan_stalling + the monotonic gather that
    needs no a-priori frame count (`streamed_stall_plan` pins record
    parity against plan_stalling over an (n × events) matrix);
  * audio rides from memory through the same helpers
    (insert_stall_silence, trim_normalize_long_audio) the file-decoding
    paths use — the intermediates are lossless, so the samples are the
    bytes a decode of the artifact would return.

Memoization contract: the fused fan-out only engages when the AVPVS
itself is due for (re)generation — a warm AVPVS with a stale CPVS keeps
today's exact partial-invalidation behavior (legacy p04 rebuilds just
that context from the materialized artifact). Every member artifact is
committed under its own existing plan hash via Job.complete_externally,
with the same crash-sentinel discipline as the batch waves.
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Callable, Optional

import numpy as np

from .. import telemetry as tm
from ..engine import prefetch as pfe
from ..engine.jobs import clear_inprogress, mark_inprogress
from ..ops import overlay as ov
from ..utils.log import get_logger
from ..utils.runner import ChainError
from . import avpvs as av
from . import cpvs as cp

_MEMBERS_DEGRADED = tm.counter(
    "chain_fused_members_degraded_total",
    "fused fan-out members aborted mid-stream and left to the staged "
    "partial path",
)


def fused_p04_enabled() -> bool:
    """The PC_FUSE_P04 gate. Routing only: the fused path renders
    decoded-identical artifacts under unchanged plan hashes, so the
    flag never reaches a plan payload."""
    # plan-exempt: (fused-vs-unfused CPVS/AVPVS bytes are decoded-identical and plan hashes unchanged; pinned by tests/test_fused.py parity suite + the fused-smoke CI job)
    return os.environ.get("PC_FUSE_P04", "").strip().lower() in (
        "1", "true", "yes", "on"
    )


# --------------------------------------------------------- stall replay


class _StallSchedule:
    """plan_stalling's spinner/black insertion mode, replayed
    incrementally: events fire as the source position reaches them,
    with trailing (past-stream-end) events flushed by finish() — the
    min(n, event_frame) clamp of the batch formulation, without
    knowing n up front. emit(src_idx, stall, black, phase)."""

    def __init__(self, fps: float, events, emit: Callable,
                 black_frame: bool = True, spinner_rps: float = 1.0,
                 n_rotations: int = 64) -> None:
        self._fps = float(fps)
        self._events = sorted((float(e[0]), float(e[1])) for e in events)
        self._emit = emit
        self._black = 1 if black_frame else 0
        self._rps = spinner_rps
        self._n_rot = n_rotations
        self._ei = 0
        self._spin = 0
        self._next_src = 0
        #: stall backgrounds are always the previous played frame; no
        #: long-range retention needed (StallStream contract)
        self.anchors: frozenset = frozenset()

    def _emit_stalls(self, ei: int) -> None:
        n_stall = int(round(self._events[ei][1] * self._fps))
        bg = max(0, self._next_src - 1)
        for _ in range(n_stall):
            phase = int(
                self._spin * self._rps * self._n_rot / self._fps
            ) % self._n_rot
            self._emit(bg, 1, self._black, phase)
            self._spin += 1

    def on_source(self, k: int) -> None:
        while self._ei < len(self._events) and int(round(
            self._events[self._ei][0] * self._fps
        )) <= self._next_src:
            self._emit_stalls(self._ei)
            self._ei += 1
        self._emit(self._next_src, 0, 0, 0)
        self._next_src += 1

    def finish(self) -> None:
        while self._ei < len(self._events):
            self._emit_stalls(self._ei)
            self._ei += 1


class _SkipSchedule:
    """plan_stalling's frame-freeze (skipping) mode, replayed
    incrementally. The batch form mutates src_idx sequentially
    (`src_idx[start:end] = src_idx[start]` per event, in the given
    order); `anchors[i]` is the value that assignment reads — the
    array state after events < i — so per-position resolution needs no
    array. Length-preserving: one record per source frame."""

    def __init__(self, fps: float, events, emit: Callable) -> None:
        fps = float(fps)
        norm = []
        t_cursor = 0.0
        for ev in events:
            # bare durations freeze back-to-back from t=0 (the .buff
            # freeze format carries no positions) — plan_stalling parity
            if isinstance(ev, (list, tuple)):
                norm.append((float(ev[0]), float(ev[1])))
            else:
                norm.append((t_cursor, float(ev)))
                t_cursor += float(ev)
        self._ranges = [
            (int(round(t * fps)), int(round((t + d) * fps))) for t, d in norm
        ]
        self._emit = emit
        anchors: list[int] = []
        for i, (s, _e) in enumerate(self._ranges):
            v = s
            for j in range(i):
                sj, ej = self._ranges[j]
                if sj <= s < ej:
                    v = anchors[j]
            anchors.append(v)
        self._anchors = anchors
        self.anchors = frozenset(anchors)

    def on_source(self, k: int) -> None:
        v = k
        stall = 0
        for i, (s, e) in enumerate(self._ranges):
            if s <= k < e:
                v = self._anchors[i]
                stall = 1
        self._emit(v, stall, 0, 0)

    def finish(self) -> None:
        pass


def streamed_stall_plan(
    n_frames: int,
    fps: float,
    buff_events: list,
    skipping: bool = False,
    black_frame: bool = True,
    spinner_rps: float = 1.0,
    n_rotations: int = 64,
) -> ov.StallPlan:
    """Run the incremental schedule over `n_frames` sources and return
    the records as a StallPlan — the parity surface tests diff against
    ov.plan_stalling(n_frames, ...) field by field."""
    recs: list[tuple] = []
    emit = lambda *r: recs.append(r)  # noqa: E731 - record capture
    sched = (
        _SkipSchedule(fps, buff_events, emit) if skipping
        else _StallSchedule(fps, buff_events, emit, black_frame=black_frame,
                            spinner_rps=spinner_rps, n_rotations=n_rotations)
    )
    for k in range(n_frames):
        sched.on_source(k)
    sched.finish()
    return ov.StallPlan(
        src_idx=np.array([r[0] for r in recs], np.int32),
        stall_mask=np.array([r[1] for r in recs], np.int8),
        black_mask=np.array([r[2] for r in recs], np.int8),
        phase=np.array([r[3] for r in recs], np.int32),
    )


class StallStream:
    """Bind the incremental schedule to pushed frames: feed() source
    frames in order, receive output records via
    emit(frame_planes, stall, black, phase). Bounded retention: the
    previous frame (stall backgrounds) plus the freeze anchors the
    schedule precomputed — never the whole stream."""

    def __init__(self, fps: float, events, skipping: bool, emit: Callable,
                 n_rotations: int = 64) -> None:
        self._emit = emit
        self._sched = (
            _SkipSchedule(fps, events, self._on_record) if skipping
            else _StallSchedule(fps, events, self._on_record,
                                n_rotations=n_rotations)
        )
        self._retain = self._sched.anchors
        self._k = -1
        self._cur = None
        self._prev = None
        self._retained: dict[int, list] = {}

    def feed(self, planes: list) -> None:
        self._k += 1
        self._cur = planes
        if self._k in self._retain:
            self._retained[self._k] = planes
        self._sched.on_source(self._k)
        self._prev = planes

    def finish(self) -> None:
        # an empty source emits nothing, trailing events included —
        # stream_monotonic_gather parity (no frames, no gather output)
        if self._k >= 0:
            self._sched.finish()

    def _on_record(self, src: int, stall: int, black: int, phase: int) -> None:
        if src == self._k:
            planes = self._cur
        elif src == self._k - 1:
            planes = self._prev
        else:
            planes = self._retained.get(src)
        if planes is None:
            raise ChainError(
                f"fused stalling: source frame {src} not retained at "
                f"position {self._k} (schedule/retention bug)"
            )
        self._emit(planes, stall, black, phase)


# ------------------------------------------------------ fan-out pipelines


class _ContextPipeline:
    """One CPVS render fed from the in-memory final-AVPVS stream:
    optional display-rate resample (push-based stream_fps_resample, the
    same index math), the `-t` output cap, the SHARED per-chunk
    transform, and an AsyncWriter encoder."""

    def __init__(self, out_path: str, plan: dict, pp, w: int, h: int,
                 pix_fmt: str, avpvs_fps: float, audio, srate: int,
                 rawvideo: bool, chunk: int) -> None:
        self.out_path = out_path
        self._transform = cp.make_cpvs_transform(plan, pp, pix_fmt, rawvideo)
        out_rate = cp.cpvs_out_rate(plan, avpvs_fps)
        vw, has_audio = cp.open_cpvs_writer(
            out_path, plan, pp, w, h, out_rate, audio, srate
        )
        self._writer = pfe.AsyncWriter(vw)
        if has_audio:
            self._writer.write_audio(audio)
        dst = plan["fps"]
        self._resample = dst is not None and dst != avpvs_fps
        self._src_fps = avpvs_fps
        self._dst_fps = dst
        self._cap = (
            cp.t_cap_frames(plan["t"], out_rate)
            if plan["t"] is not None else None
        )
        self._chunk = chunk
        self._out_n = 0       # output frames emitted (cap accounting)
        self._buf: list = []  # pending frames on the resample path
        self._gather_k = 0    # next output index (resample)
        self._cur = -1        # last source frame index seen
        self._last = None
        self._finished = False

    # -- chunk fast path (no rate change: frames map 1:1)

    def _put_chunk(self, planes: list) -> None:
        if self._cap is not None:
            left = self._cap - self._out_n
            if left <= 0:
                return
            if planes[0].shape[0] > left:
                planes = [p[:left] for p in planes]
        if planes[0].shape[0] == 0:
            return
        self._out_n += planes[0].shape[0]
        self._writer.put(self._transform(planes))

    # -- frame path (display-rate resample)

    def _out_index(self, k: int) -> int:
        # stream_fps_resample's ffmpeg `fps=` index math, verbatim
        return int(np.floor(k / self._dst_fps * self._src_fps + 0.5))

    def _emit_frame(self, planes: list) -> None:
        if self._cap is not None and self._out_n >= self._cap:
            return
        self._out_n += 1
        self._buf.append(planes)
        if len(self._buf) >= self._chunk:
            self._flush_buf()

    def _flush_buf(self) -> None:
        if not self._buf:
            return
        stacked = [
            np.stack([f[p] for f in self._buf]) for p in range(3)
        ]
        self._buf = []
        self._writer.put(self._transform(stacked))

    def feed(self, planes: list) -> None:
        if not self._resample:
            self._put_chunk(planes)
            return
        t = planes[0].shape[0]
        for i in range(t):
            frame = [p[i] for p in planes]
            self._cur += 1
            self._last = frame
            while self._out_index(self._gather_k) <= self._cur:
                self._emit_frame(frame)
                self._gather_k += 1

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        if self._resample and self._last is not None:
            # fps= output length: round(n_src / src_fps * dst_fps);
            # past-the-end outputs repeat the last frame (clamp)
            n_out = int(round(
                (self._cur + 1) / self._src_fps * self._dst_fps
            ))
            while self._gather_k < n_out:
                self._emit_frame(self._last)
                self._gather_k += 1
        self._flush_buf()
        self._writer.close()

    def abort(self) -> None:
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - teardown on the failure path
            pass


class _PreviewPipeline:
    """The ProRes preview fed from the in-memory final stream (no
    resample, no cap — preview parity with create_preview)."""

    def __init__(self, out_path: str, w: int, h: int, pix_fmt: str,
                 avpvs_fps: float, audio, srate: int) -> None:
        self.out_path = out_path
        self._transform = cp.make_preview_transform(pix_fmt)
        vw, has_audio = cp.open_preview_writer(
            out_path, w, h, avpvs_fps, audio, srate
        )
        self._writer = pfe.AsyncWriter(vw)
        if has_audio:
            self._writer.write_audio(audio)
        self._finished = False

    def feed(self, planes: list) -> None:
        self._writer.put(self._transform(planes))

    def finish(self) -> None:
        if not self._finished:
            self._finished = True
            self._writer.close()

    def abort(self) -> None:
        try:
            self._writer.close()
        except Exception:  # noqa: BLE001 - teardown on the failure path
            pass


class FusedFanout:
    """Per-PVS fused p04 fan-out. Built by the stage/executor with the
    run's knobs; `start()` is called by the render body once rate/audio
    are known (returns the chunk tap), `feed()` receives every
    quantized AVPVS chunk, `finish_streams()` flushes and closes the
    downstream encoders (the wave driver calls it via Lane.on_done as
    lanes exhaust, bounding open codec contexts), and `close()` commits
    every member artifact under its existing plan hash. `abort()`
    removes partial outputs and clears their crash sentinels."""

    def __init__(self, pvs, *, spinner_path: Optional[str] = None,
                 n_rotations: int = 64, rawvideo: bool = False,
                 nonraw_crf: int = 17, mobile_vprofile: str = "high",
                 mobile_preset: str = "fast", preview: bool = False) -> None:
        self.pvs = pvs
        self._spinner = spinner_path
        self._n_rot = n_rotations
        self._rawvideo = rawvideo
        self._crf = nonraw_crf
        self._vprofile = mobile_vprofile
        self._preset = mobile_preset
        self._buffering = pvs.has_buffering()
        self._skipping = pvs.has_framefreeze() if self._buffering else False
        self._events = (
            pvs.get_buff_events_media_time() if self._buffering else []
        )
        self.engaged = False
        self._finished = False
        self._closed = False
        self._pipelines: list = []
        self._marked: list[str] = []
        #: output path -> error summary for members that failed MID-
        #: STREAM (encoder write/close error, injected ENOSPC, …): the
        #: member is aborted and dropped — its partial output removed,
        #: its sentinel cleared, its job NOT completed — while every
        #: healthy member keeps streaming and settles normally. The
        #: staged partial path rebuilds exactly the degraded members
        #: (p04/stalling warm-skip sees them as due), which is the
        #: graceful-degrade contract of docs/ROBUSTNESS.md.
        self.degraded: dict[str, str] = {}
        self._stall_writer = None
        self._stall_stream = None
        self._compositor = None
        self._srec: list = []
        self._schunk = 0
        # member jobs: the EXISTING per-artifact jobs — never run; they
        # carry the plan identity, provenance and commit surface, so
        # warm hits and partial invalidation behave exactly as today
        self.stall_job = av.apply_stalling(
            pvs, spinner_path=spinner_path, n_rotations=n_rotations
        )
        self.cpvs_jobs = [
            cp.create_cpvs(pvs, pp, rawvideo, nonraw_crf,
                           mobile_vprofile, mobile_preset)
            for pp in pvs.test_config.post_processings
        ]
        self.preview_job = cp.create_preview(pvs) if preview else None
        outs = [j.output_path for j in self.member_jobs()]
        dups = sorted({o for o in outs if outs.count(o) > 1})
        if dups:
            raise ChainError(
                f"fused p04 fan-out for {pvs.pvs_id}: multiple contexts "
                f"write {dups} — write-write race"
            )

    def member_jobs(self) -> list:
        jobs = []
        if self.stall_job is not None:
            jobs.append(self.stall_job)
        jobs.extend(self.cpvs_jobs)
        if self.preview_job is not None:
            jobs.append(self.preview_job)
        return jobs

    def stall_settled(self) -> bool:
        """True when the staged stalling pass has nothing to redo for
        this PVS: either there is no stalling member, or the fused
        render carried it to completion. False = the member degraded
        mid-stream and the orchestrator must plan the staged
        apply_stalling instead of skipping it."""
        return self.stall_job is None or \
            self.stall_job.output_path not in self.degraded

    # ------------------------------------------------------------ start

    def start(self, rate: float, audio, srate: int, w: int, h: int,
              pix_fmt: str) -> Callable:
        """Open every downstream writer (audio first, exactly like the
        decode-driven paths) and return the chunk tap. `rate` is the
        AVPVS canvas rate; it is rationalized the way the writer muxes
        it so the resample decisions match what a reader of the
        artifact would see."""
        frac = Fraction(rate).limit_denominator(1001)
        avpvs_fps = frac.numerator / frac.denominator
        tc = self.pvs.test_config
        self.engaged = True
        chunk = av.chunk_frames()
        self._schunk = chunk

        final_audio = audio
        if self._buffering:
            if audio is not None and audio.size and not self._skipping:
                final_audio = av.insert_stall_silence(
                    audio, srate, self._events
                )
            stall_out = self.stall_job.output_path
            mark_inprogress(stall_out)
            self._marked.append(stall_out)
            has_audio = final_audio is not None and final_audio.size > 0
            self._stall_writer = pfe.AsyncWriter(av._ffv1_writer(
                stall_out, w, h, pix_fmt, avpvs_fps,
                with_audio=has_audio, sample_rate=srate,
            ))
            if has_audio:
                self._stall_writer.write_audio(final_audio)
            self._compositor = av.make_stall_compositor(
                pix_fmt, self._spinner, self._skipping, self._n_rot
            )
            self._stall_stream = StallStream(
                avpvs_fps, self._events, self._skipping,
                emit=self._on_stall_record, n_rotations=self._n_rot,
            )

        for job, pp in zip(self.cpvs_jobs, tc.post_processings):
            plan = cp.cpvs_plan(
                self.pvs, pp, h, self._rawvideo, self._crf,
                self._vprofile, self._preset,
            )
            ctx_audio = None
            if tc.is_long() and final_audio is not None and final_audio.size:
                ctx_audio = cp.trim_normalize_long_audio(
                    final_audio, srate, self.pvs, plan["normalize"]
                )
            mark_inprogress(job.output_path)
            self._marked.append(job.output_path)
            try:
                self._pipelines.append(_ContextPipeline(
                    job.output_path, plan, pp, w, h, pix_fmt, avpvs_fps,
                    ctx_audio, srate, self._rawvideo, chunk,
                ))
            except Exception as exc:  # noqa: BLE001 - member containment
                # a member whose WRITER cannot even open (ENOSPC on the
                # third context) degrades like a mid-stream failure:
                # dropped to the staged partial path, siblings unharmed
                self._drop_member(job.output_path, exc)
        if self.preview_job is not None:
            mark_inprogress(self.preview_job.output_path)
            self._marked.append(self.preview_job.output_path)
            try:
                self._pipelines.append(_PreviewPipeline(
                    self.preview_job.output_path, w, h, pix_fmt,
                    avpvs_fps, final_audio, srate,
                ))
            except Exception as exc:  # noqa: BLE001 - member containment
                self._drop_member(self.preview_job.output_path, exc)
        return self.feed

    # ------------------------------------------------------------- flow

    def feed(self, planes: list) -> None:
        """One quantized AVPVS chunk ([T, H, W] host stacks)."""
        if self._stall_stream is not None:
            t = planes[0].shape[0]
            for i in range(t):
                self._stall_stream.feed([p[i] for p in planes])
        else:
            self._feed_final(planes)

    def _feed_final(self, planes: list) -> None:
        for pipe in list(self._pipelines):
            try:
                pipe.feed(planes)
            except Exception as exc:  # noqa: BLE001 - member containment
                self._degrade_member(pipe, exc)

    def _degrade_member(self, pipe, exc: BaseException) -> None:
        """Contain one CPVS/preview member failure: abort THAT member
        (partial output removed, sentinel cleared, job left un-run for
        the staged partial path) and keep every other member streaming.
        A failure in the shared machinery (stall compositor, the AVPVS
        lane itself) still aborts the whole fan-out via the wave's
        abort sweep — containment is per-member by construction."""
        self._pipelines.remove(pipe)
        pipe.abort()
        self._drop_member(pipe.out_path, exc)

    def _drop_member(self, out: str, exc: BaseException) -> None:
        self.degraded[out] = f"{type(exc).__name__}: {exc}"[:500]
        if out in self._marked:
            self._marked.remove(out)
        if os.path.isfile(out):
            try:
                os.unlink(out)
            except OSError:
                pass
        clear_inprogress(out)
        _MEMBERS_DEGRADED.inc()
        tm.emit("fused_member_degraded", output=os.path.basename(out),
                pvs=self.pvs.pvs_id, error=self.degraded[out])
        get_logger().warning(
            "fused fan-out %s: member %s aborted mid-stream (%s) — "
            "falling back to the staged partial path; %d member(s) "
            "still streaming",
            self.pvs.pvs_id, os.path.basename(out), self.degraded[out],
            len(self._pipelines),
        )

    def _degrade_stall(self, exc: BaseException) -> None:
        """The stalled-AVPVS member failed mid-stream: drop ITS writer
        and output, but keep compositing — the context pipelines
        consume the composited frames from memory regardless."""
        writer, self._stall_writer = self._stall_writer, None
        try:
            writer.close()
        except Exception:  # noqa: BLE001 - teardown on the failure path
            pass
        self._drop_member(self.stall_job.output_path, exc)

    def _on_stall_record(self, frame_planes, stall, black, phase) -> None:
        self._srec.append((frame_planes, stall, black, phase))
        if len(self._srec) >= self._schunk:
            self._flush_stall_chunk()

    def _flush_stall_chunk(self) -> None:
        if not self._srec:
            return
        recs, self._srec = self._srec, []
        gathered = [
            np.stack([np.asarray(r[0][p]) for r in recs]) for p in range(3)
        ]
        stall = np.array([r[1] for r in recs], np.int8)
        black = np.array([r[2] for r in recs], np.int8)
        phase = np.array([r[3] for r in recs], np.int32)
        outs = self._compositor(gathered, stall, black, phase)
        # fetched ONCE: the stalled writer takes host arrays and the
        # same arrays fan out to every context pipeline — what a decoder
        # of the stalled artifact would produce (lossless writeback)
        host = [np.asarray(o) for o in outs]
        if self._stall_writer is not None:
            try:
                self._stall_writer.put(host)
            except Exception as exc:  # noqa: BLE001 - member containment
                self._degrade_stall(exc)
        self._feed_final(host)

    # -------------------------------------------------------- lifecycle

    def finish_streams(self) -> None:
        """Flush tails and close every downstream encoder (idempotent).
        Commits stay in close(): the wave driver calls this from
        Lane.on_done the moment a lane exhausts, so encoder contexts
        free up while other lanes still stream."""
        if self._finished or not self.engaged:
            return
        self._finished = True
        if self._stall_stream is not None:
            self._stall_stream.finish()
            self._flush_stall_chunk()
            if self._stall_writer is not None:
                try:
                    self._stall_writer.close()
                except Exception as exc:  # noqa: BLE001 - member containment
                    self._degrade_stall(exc)
        for pipe in list(self._pipelines):
            try:
                pipe.finish()
            except Exception as exc:  # noqa: BLE001 - member containment
                self._degrade_member(pipe, exc)

    def close(self) -> None:
        """Finalize: flush + commit every member artifact under its own
        plan hash (provenance, store commit, sentinel clear — the same
        tail a standalone job run has)."""
        if self._closed:
            return
        try:
            self.finish_streams()
        except BaseException:
            self.abort()
            raise
        self._closed = True
        if not self.engaged:
            return
        for job in self.member_jobs():
            # degraded members commit NOTHING: their jobs stay un-run,
            # so the staged partial path (p04 / the stalling pass) sees
            # them as due and rebuilds exactly those artifacts
            if job.output_path in self.degraded:
                continue
            job.complete_externally()

    def abort(self) -> None:
        """Failure path: no partial artifact may survive to satisfy a
        later run's skip-existing check."""
        if self._closed:
            return
        self._closed = True
        if self._stall_writer is not None:
            try:
                self._stall_writer.close()
            except Exception:  # noqa: BLE001 - teardown on the failure path
                pass
        for pipe in self._pipelines:
            pipe.abort()
        for out in self._marked:
            if os.path.isfile(out):
                os.unlink(out)
            clear_inprogress(out)


class SegmentOrderedTap:
    """The multi-lane → single-stream adapter for long tests on the batch
    mesh path (models/avpvs.create_avpvs_wo_buffer_batch).

    A long test renders one wave LANE PER SEGMENT, but a FusedFanout
    consumes ONE continuous stream (exactly what the single-device path
    feeds it from MultiSegmentPrefetcher). The wave scheduler
    (parallel/p03_batch.plan_waves) pins a PVS's segment lanes to
    sequential waves in segment order, so simply forwarding each lane's
    emits yields the continuous stream with zero reorder buffering —
    this class is the ENFORCEMENT point, not a buffer: an emit from any
    lane other than the current segment means the scheduler's ordering
    contract broke, and silently forwarding it would interleave segments
    inside committed artifacts. It raises instead.

    `lane(idx)` / `lane_done(idx)` hand each segment lane its emit tap
    and its Lane.on_done; the last segment's on_done fires
    `fanout.finish_streams()` — the same point in the stream where the
    single-device path stops feeding. No locking: wave lanes emit from
    the driver thread, and waves are sequential by construction."""

    def __init__(self, fanout, feed, n_segments: int) -> None:
        self._fanout = fanout
        self._feed = feed
        self._n = n_segments
        self._current = 0

    def _check(self, idx: int, what: str) -> None:
        if idx != self._current:
            raise ChainError(
                f"fused lane ordering violated: {what} from segment "
                f"{idx} while segment {self._current} is current "
                f"(plan_waves contract)"
            )

    def lane(self, idx: int):
        """Emit tap for segment `idx`'s wave lane."""
        def emit(planes) -> None:
            self._check(idx, "frames")
            self._feed(planes)
        return emit

    def lane_done(self, idx: int):
        """Lane.on_done for segment `idx`: advance; after the LAST
        segment, flush + close the fan-out's downstream encoders."""
        def done() -> None:
            self._check(idx, "on_done")
            self._current += 1
            if self._current == self._n:
                self._fanout.finish_streams()
        return done
