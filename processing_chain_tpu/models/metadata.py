"""Metadata model — the p02 stage (reference p02_generateMetadata.py:33-152).

Per PVS:
  * .qchanges — one row per segment from probe.get_segment_info, with
    video_bitrate recomputed from the exact bitstream frame sizes
    (reference :112-116);
  * .buff — stall/freeze events in media time, one python-repr per line
    (reference :59-71);
  * .vfi / .afi — per-packet frame tables with ffprobe sizes replaced by
    the exact parsed sizes, frame-count consistency enforced
    (reference :119-124 hard-exits on mismatch; here it raises);
  * VP9 superframe packets merged before size replacement (reference :100-104).

Design note — why p02 parses files instead of consuming device tensors
(BASELINE.json's north star routes "device-side feature tensors" to the
stages that handle PIXELS: p03's SI/TI sidecars, tools/quality_metrics,
src-analysis --siti): p02's artifacts are BITSTREAM metadata, and their
value contract is the reference's exact annexb/IVF frame sizes
(reference get_framesize.py). Those differ from what any in-memory
shortcut could supply — encoder-mux packet sizes diverge from annexb
sizes (start-code vs length-prefix framing, parameter-set placement on
keyframes), which is the very discrepancy the reference built its parsers
to avoid (vs ffprobe, :119-124). Re-parsing the written file is therefore
load-bearing for parity; the hot loop is native demux + vectorized numpy
NAL/IVF scanning (io/framesizes.py), not the reference's byte-at-a-time
Python state machine.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

from ..config.domain import Pvs
from ..engine.jobs import Job
from ..io import framesizes, probe
from ..io.medialib import MediaError
from ..store import keys as store_keys
from ..utils.fsio import atomic_write
from ..utils.log import get_logger


class MetadataError(RuntimeError):
    pass


def metadata_paths(pvs: Pvs) -> dict:
    """The four p02 artifact paths for one PVS (buff only for buffering
    HRCs)."""
    tc = pvs.test_config
    paths = {
        "qchanges": os.path.join(
            tc.get_quality_change_event_files_path(), pvs.pvs_id + ".qchanges"
        ),
        "vfi": os.path.join(
            tc.get_video_frame_information_path(), pvs.pvs_id + ".vfi"
        ),
        "afi": os.path.join(
            tc.get_audio_frame_information_path(), pvs.pvs_id + ".afi"
        ),
    }
    if pvs.has_buffering():
        paths["buff"] = os.path.join(
            tc.get_buff_event_files_path(), pvs.pvs_id + ".buff"
        )
    return paths


def metadata_job(pvs: Pvs, force: bool = False) -> Job:
    """p02 as a Job: qchanges is the main output, vfi/afi/buff ride as
    extra outputs, and the plan is the segment digests + stall schedule
    (everything the four tables derive from). With a store active the
    inner per-file force is unconditional — the job only runs when the
    plan says these tables are stale, and a rebuild must refresh ALL of
    them; without one, the legacy per-file `_maybe_write` semantics are
    preserved bit for bit."""
    paths = metadata_paths(pvs)
    extras = tuple(p for k, p in paths.items() if k != "qchanges")

    def run() -> str:
        from ..store import runtime as store_runtime

        generate_pvs_metadata(
            pvs, force=force or store_runtime.active() is not None
        )
        return paths["qchanges"]

    return Job(
        label=f"metadata {pvs.pvs_id}",
        output_path=paths["qchanges"],
        fn=run,
        plan={
            "op": "pvs_metadata",
            "segments": [
                store_keys.file_ref(s.file_path) for s in pvs.segments
            ],
            "events": (
                [[float(e[0]), float(e[1])] for e in
                 pvs.get_buff_events_media_time()]
                if pvs.has_buffering() else None
            ),
        },
        extra_outputs=extras,
        provenance={"pvs": pvs.pvs_id, "artifacts": sorted(paths)},
    )


def _maybe_write(path: str, force: bool, write_fn) -> None:
    log = get_logger()
    if not force and os.path.isfile(path):
        log.warning(
            "file %s already exists, not overwriting. Use -f/--force to "
            "force overwriting", path,
        )
        return
    log.info("writing %s", path)
    # atomic: a run killed mid-write must never leave a truncated table
    # (the sibling of engine/jobs' .inprogress discipline, for these
    # small multi-file outputs)
    atomic_write(path, write_fn)


def generate_pvs_metadata(pvs: Pvs, force: bool = False) -> dict:
    """Produce all four metadata artifacts for one PVS. Returns the frames
    tables for downstream use (device feature extraction in p03/bench)."""
    tc = pvs.test_config

    qchanges_rows = []
    vfi_parts = []
    afi_parts = []
    for segment in pvs.segments:
        if not segment.exists():
            raise MetadataError(f"segment {segment.filename} does not exist!")
        qchanges_rows.append(dict(segment.get_segment_info()))
        vfi_parts.append(
            probe.get_video_frame_info(segment.file_path, segment.filename)
        )
        try:
            afi_parts.append(
                probe.get_audio_frame_info(segment.file_path, segment.filename)
            )
        except MediaError as exc:
            # short tests have no audio stream; anything else propagates
            get_logger().debug("no audio frame info for %s: %s", segment.filename, exc)
    vfi = pd.concat(vfi_parts, ignore_index=True)
    afi = (
        pd.concat(afi_parts, ignore_index=True)
        if afi_parts
        else pd.DataFrame(columns=["segment", "index", "dts", "size", "duration"])
    )

    # exact frame sizes per segment; recompute qchanges video_bitrate.
    # VP9 superframe packets are merged first, restricted to each VP9
    # segment's own rows (reference :100-104 merges before size replacement)
    vp9_segments = {
        pvs.segments[i].filename
        for i in range(len(pvs.segments))
        if str(qchanges_rows[i]["video_codec"]).lower() == "vp9"
    }
    if vp9_segments:
        is_vp9 = vfi["segment"].isin(vp9_segments)
        merged = framesizes.merge_superframes(vfi[is_vp9])
        vfi = pd.concat([vfi[~is_vp9], merged], ignore_index=True)
        # restore the PVS's segment playout order (not lexicographic)
        order = {s.filename: i for i, s in enumerate(pvs.segments)}
        vfi = (
            vfi.assign(_seg_order=vfi["segment"].map(order))
            .sort_values(["_seg_order", "index"], kind="stable")
            .drop(columns="_seg_order")
            .reset_index(drop=True)
        )
    all_sizes: list[int] = []
    for i, segment in enumerate(pvs.segments):
        codec = str(qchanges_rows[i]["video_codec"]).lower()
        seg_sizes = framesizes.get_framesizes(
            segment.file_path, "h265" if codec == "hevc" else codec, force
        )
        all_sizes.extend(seg_sizes)
        qchanges_rows[i]["video_bitrate"] = round(
            sum(seg_sizes) / 1024 * 8 / qchanges_rows[i]["video_duration"], 2
        )

    if len(vfi) != len(all_sizes):
        raise MetadataError(
            f"Number of frames detected for {pvs.pvs_id} does not match: "
            f"vfi={len(vfi)} exact={len(all_sizes)}"
        )
    vfi = vfi.assign(size=np.asarray(all_sizes, dtype=np.int64))

    qchanges_file = os.path.join(
        tc.get_quality_change_event_files_path(), pvs.pvs_id + ".qchanges"
    )
    _maybe_write(
        qchanges_file, force,
        lambda p: pd.DataFrame(qchanges_rows).to_csv(p, index=False),
    )

    if pvs.has_buffering():
        buff_file = os.path.join(
            tc.get_buff_event_files_path(), pvs.pvs_id + ".buff"
        )
        events = pvs.get_buff_events_media_time()
        _maybe_write(
            buff_file, force,
            lambda p: open(p, "w").write("\n".join(str(b) for b in events) + "\n"),
        )

    vfi_file = os.path.join(
        tc.get_video_frame_information_path(), pvs.pvs_id + ".vfi"
    )
    afi_file = os.path.join(
        tc.get_audio_frame_information_path(), pvs.pvs_id + ".afi"
    )
    _maybe_write(vfi_file, force, lambda p: vfi.to_csv(p, index=False))
    _maybe_write(afi_file, force, lambda p: afi.to_csv(p, index=False))

    return {"qchanges": qchanges_rows, "vfi": vfi, "afi": afi}
