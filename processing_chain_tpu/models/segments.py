"""Segment encoding model — the p01 compute (reference encode path,
lib/ffmpeg.py:772-937 + _get_video_encoder_command :61-318).

Where the reference builds an ffmpeg command string per segment, this model
is a typed pipeline: host decode of the SRC window → device scale
(`scale=W:-2` bicubic) + frame-rate select (the reference's drop tables) →
host x264/x265/libvpx/libaom encode with the same rate-control surface
(bitrate/CRF/QP, min/max/bufsize factors, GOP from iFrameInterval × fps,
bframes, scenecut, preset, speed/quality/cpu-used, enc_options, 2-pass)."""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Optional

from ..config.domain import Segment
from ..engine import prefetch as pfe
from ..engine.jobs import Job
from ..io.video import VideoReader, VideoWriter
from ..io import medialib, sharedscan
from ..ops import fps as fps_ops
from ..store import keys as store_keys
from ..utils.log import get_logger
from . import avpvs
from . import frames as fr

#: encoder name → libav encoder + default private options
_ENCODERS = {
    "libx264": "libx264",
    "h264_nvenc": "libx264",   # no NVENC on this host; transparent fallback
    "libx265": "libx265",
    "hevc_nvenc": "libx265",
    "libvpx-vp9": "libvpx-vp9",
    "libaom-av1": "libaom-av1",
}

#: requested encoders already warned about this run (warn once, not per job)
_warned_substitutions: set = set()


def reset_run_state() -> None:
    """Start-of-run reset (stage drivers call this): substitution warnings
    fire once per RUN, not once per process lifetime."""
    _warned_substitutions.clear()


def _encoder_opts(
    segment: Segment, current_pass: int, total_passes: int,
    stats_path: str = "",
) -> str:
    """Private-option string mirroring _get_video_encoder_command semantics
    (reference lib/ffmpeg.py:61-318), minus what VideoWriter takes as
    first-class arguments (bitrate/min/max/bufsize/gop/bframes)."""
    coding = segment.video_coding
    encoder = _ENCODERS[coding.encoder]
    opts: list[str] = []

    if coding.crf is not None:
        opts.append(f"crf={segment.quality_level.video_crf}")
    elif coding.qp is not None:
        opts.append(f"qp={segment.quality_level.video_qp}")

    if coding.preset and encoder in ("libx264", "libx265"):
        opts.append(f"preset={coding.preset}")

    if encoder == "libx264":
        params = []
        if not coding.scenecut:
            params.append("scenecut=-1")
        if params:
            opts.append("x264-params=" + _escape_opt_value(":".join(params)))
    elif encoder == "libx265":
        params = ["log-level=error"]
        # reference quirk (do-not-copy list): x265 scenecut=0 was appended
        # whenever scenecut was NOT False (inverted vs x264,
        # ffmpeg.py:213-214). Intended semantics: disable on scenecut=False.
        if not coding.scenecut:
            params.append("scenecut=0")
        if total_passes == 2:
            # libx265 has no "stats" AVOption (x264's route): pass AND the
            # stats path both travel inside x265-params, else x265 writes
            # ./x265_2pass.log into the process cwd
            params.append(f"pass={current_pass}")
            if stats_path:
                params.append(f"stats={stats_path}")
        opts.append("x265-params=" + _escape_opt_value(":".join(params)))
    elif encoder == "libvpx-vp9":
        speed = coding.speed
        # first pass runs at speed 4 (reference :100-102)
        if total_passes == 2 and current_pass == 1:
            speed = 4
        opts.append(f"quality={coding.quality}")
        opts.append(f"speed={speed}")
        opts.append("row-mt=1")
    elif encoder == "libaom-av1":
        opts.append(f"cpu-used={coding.cpu_used}")
        opts.append("usage=realtime")

    if coding.enc_options:
        opts.append(enc_options_to_opts(coding.enc_options))
    return ":".join(o for o in opts if o)


def _escape_opt_value(value: str) -> str:
    """Escape an option VALUE for the ':'-joined opts string the native
    boundary parses with av_dict_parse_string(.., "=", ":", 0): a bare ':'
    in a value (x265-params=a=1:b=2, x264opts keyint=48:min-keyint=48)
    would otherwise split the value into bogus extra options that fall
    through to the muxer and are silently dropped. av_get_token honors
    backslash escapes."""
    return value.replace("\\", "\\\\").replace(":", "\\:")


def enc_options_to_opts(enc_options: str) -> str:
    """Translate a database's `enc_options` into codec-context options.

    The reference splices enc_options RAW into its ffmpeg command line
    (reference lib/ffmpeg.py:122-124 spliced at :169/:238), so databases
    carry flag syntax like `-tune zerolatency -bf 0`. Here encoder options
    are AVOptions on the codec context, so `-k v` pairs map to `k=v` (a
    valueless flag becomes `k=1`, AVOption bool style); `k=v:k=v` strings
    pass through unchanged. ffmpeg *stream-specifier* flags (`-b:v` etc.)
    belong to the rate-control surface, which is first-class on the
    Coding — a specifier key here raises rather than misconfiguring the
    encoder silently."""
    s = str(enc_options).strip()
    if not s.startswith("-"):
        return s

    def is_flag(tok: str) -> bool:
        return tok.startswith("-") and len(tok) > 1 and not (
            tok[1].isdigit() or tok[1] == "."
        )

    toks = s.split()
    pairs = []
    i = 0
    while i < len(toks):
        tok = toks[i]
        if not is_flag(tok):
            raise ValueError(
                f"enc_options: cannot parse {tok!r} in {s!r} (expected a "
                f"-flag)"
            )
        key = tok.lstrip("-")
        if ":" in key:
            raise ValueError(
                f"enc_options: stream-specifier flag {tok!r} is not a codec "
                "option; use the Coding's first-class rate-control fields"
            )
        if i + 1 < len(toks) and not is_flag(toks[i + 1]):
            pairs.append(f"{key}={_escape_opt_value(toks[i + 1])}")
            i += 2
        else:
            pairs.append(f"{key}=1")
            i += 1
    return ":".join(pairs)


def plan_segment_frames(segment: Segment):
    """Decode + filter plan: (target_h, target_w, keep_indices|None,
    out_fps_fraction). Mirrors the reference's filter chain
    scale=W:-2,select,fps (lib/ffmpeg.py:794-834)."""
    src_fps = segment.src.get_fps()
    target_fps = fps_ops.resolve_fps_spec(segment.quality_level.fps, src_fps)
    width = segment.quality_level.width
    src_info = segment.src.stream_info
    target_h, target_w = fr.scale_to_width_keep_ar(
        src_info["height"], src_info["width"], width
    )
    out_fps = target_fps if target_fps is not None else src_fps
    return target_h, target_w, target_fps, out_fps


def rate_control_kwargs(segment: Segment, out_fps: float | None = None) -> dict:
    """Numeric rate-control/GOP writer arguments, shared by
    encode_segment.run and the reference-oracle parity tests (reference
    lib/ffmpeg.py: bitrate :414-445 via target_video_bitrate, vbv/min/max
    rate factors :188-201/:249-259/:287-291, keyframe interval
    :203-210/:260-266/:293-299, bframes :216-218). Pass `out_fps` when
    plan_segment_frames was already run for this segment."""
    coding = segment.video_coding
    if out_fps is None:
        _, _, _, out_fps = plan_segment_frames(segment)
    bitrate = 0.0
    if coding.crf is None and coding.qp is None:
        bitrate = float(segment.target_video_bitrate or 0)
    return dict(
        bitrate_kbps=bitrate,
        maxrate_kbps=(coding.maxrate_factor or 0) * bitrate,
        minrate_kbps=(coding.minrate_factor or 0) * bitrate,
        bufsize_kbps=(coding.bufsize_factor or 0) * bitrate,
        gop=(
            int(out_fps * coding.iframe_interval)
            if coding.iframe_interval else -1
        ),
        bframes=coding.bframes if coding.bframes is not None else -1,
    )


def encode_segment(segment: Segment) -> Optional[Job]:
    """Build the encode Job for a segment; skip/--force semantics live in
    Job.should_run / JobRunner (engine/jobs.py)."""
    out_path = segment.file_path
    tc = segment.test_config
    log = get_logger()

    coding = segment.video_coding
    encoder = _ENCODERS.get(coding.encoder)
    if encoder is None:
        raise ValueError(f"wrong encoder: {coding.encoder}")
    if encoder != coding.encoder and coding.encoder not in _warned_substitutions:
        # once per requested encoder per run; the per-segment record lives
        # in provenance below (reference asks nvenc via -gpu N splice,
        # lib/parse_args.py:88-94, p01:64-68 — no NVENC on this host)
        _warned_substitutions.add(coding.encoder)
        log.warning(
            "encoder %s unavailable on this host; substituting %s "
            "(recorded in segment provenance)",
            coding.encoder, encoder,
        )

    target_h, target_w, target_fps, out_fps = plan_segment_frames(segment)
    passes = 2 if coding.passes == 2 else 1
    rc = rate_control_kwargs(segment, out_fps)
    bitrate = rc["bitrate_kbps"]

    def run() -> str:
        src_fps = segment.src.get_fps()
        sub = fr.chroma_subsampling(segment.target_pix_fmt)
        ten_bit = bool(segment.uses_10_bit())
        # drop-table ratio check up front, not first-chunk-deep into decode
        if target_fps is not None and target_fps != src_fps:
            fps_ops.select_table(src_fps, target_fps)

        def scaled_chunks():
            """Decode window → fps select → device scale, in
            chunk_frames()-sized batches (O(chunk) memory for any window
            length; the reference's
            ffmpeg process streams the same way). 2-pass encodes consume
            this twice — two decodes, exactly like the reference's two
            ffmpeg invocations."""
            with VideoReader(
                segment.src.file_path, segment.start_time, segment.duration
            ) as reader:
                decoded_any = False
                stream = pfe.iter_plane_chunks(reader, avpvs.chunk_frames())
                if target_fps is not None and target_fps != src_fps:
                    stream = fps_ops.stream_select(stream, src_fps, target_fps)
                for chunk in stream:
                    decoded_any = True
                    scaled = fr.scale_yuv_frames(
                        chunk, target_h, target_w, "bicubic", sub
                    )
                    yield fr.to_uint8(scaled, ten_bit)
            if not decoded_any:
                raise medialib.MediaError(
                    f"no frames decoded for {segment} from {segment.src.file_path}"
                )

        fps_frac = Fraction(out_fps).limit_denominator(1001)

        audio = {}
        if tc.is_long() and segment.audio_coding is not None:
            samples, rate = medialib.decode_audio_s16(
                segment.src.file_path, segment.start_time, segment.duration
            )
            audio = dict(
                audio_codec="aac"
                if segment.audio_coding.encoder in ("libfdk_aac", "aac")
                else segment.audio_coding.encoder,
                sample_rate=rate,
                channels=samples.shape[1] if samples.size else 2,
                audio_bitrate_kbps=float(segment.quality_level.audio_bitrate or 128),
            )
        elif tc.is_short():
            # the reference emits neither -c:a nor -an for short tests
            # (ffmpeg.py:839-845 "only for long"), so ffmpeg's default
            # encodes SRC audio with the container's default codec —
            # aac for .mp4, opus for .webm; 128k stands in for the
            # codec-default bitrate
            try:
                samples, rate = medialib.decode_audio_s16(
                    segment.src.file_path, segment.start_time, segment.duration
                )
            except medialib.MediaError as exc:
                # audio-less SRCs land here by design; the warning keeps a
                # real decode failure from silently dropping audio
                log.warning(
                    "%s: segment will carry no audio (%s)",
                    segment.filename, exc,
                )
                samples = None
            if samples is not None and samples.size:
                is_webm = segment.filename.endswith(".webm")
                if is_webm and rate not in (8000, 12000, 16000, 24000, 48000):
                    # opus accepts only these rates; default-audio parity
                    # is not worth a resampler here
                    log.warning(
                        "%s: SRC audio rate %d unsupported by opus; "
                        "segment will carry no audio", segment.filename, rate,
                    )
                else:
                    audio = dict(
                        audio_codec="libopus" if is_webm else "aac",
                        sample_rate=rate,
                        channels=samples.shape[1],
                        audio_bitrate_kbps=128.0,
                    )

        stats = os.path.join(
            tc.get_logs_path(),
            "passlogfile_" + os.path.splitext(segment.filename)[0],
        )

        def encode_pass(pass_num: int, path: str) -> None:
            kw = dict(
                codec=encoder,
                width=target_w,
                height=target_h,
                pix_fmt=segment.target_pix_fmt,
                fps=(fps_frac.numerator, fps_frac.denominator),
                **rc,
                threads=1,  # determinism (reference -threads 1, :790)
                opts=_encoder_opts(segment, pass_num, passes, stats),
                pass_num=pass_num if passes == 2 else 0,
                stats_path=stats if passes == 2 else "",
            )
            with pfe.AsyncWriter(VideoWriter(
                path, **kw, **(audio if pass_num != 1 or passes == 1 else {})
            )) as w:
                if audio and (pass_num != 1 or passes == 1):
                    w.write_audio(samples)
                with pfe.Prefetcher(scaled_chunks(), depth=2) as pre:
                    for chunk in pre:
                        w.put(chunk)

        null_out = out_path + ".pass1.tmp" + os.path.splitext(out_path)[1]
        try:
            if passes == 2:
                encode_pass(1, null_out)
                os.unlink(null_out)
                encode_pass(2, out_path)
            else:
                encode_pass(1, out_path)
        except BaseException:
            # Job.run cleans out_path; the pass-1 tmp is ours to clean
            if os.path.isfile(null_out):
                os.unlink(null_out)
            raise
        # shared-scan priming: the finished segment is still hot in page
        # cache, so pay its one demux pass NOW — p02 frame tables, segment
        # bitrates and serve cost features then read the cached arrays
        # instead of re-walking the bitstream (io/sharedscan.py). Priming
        # is an accelerator, never a gate: a scan failure surfaces where a
        # consumer actually needs the data, with that consumer's context.
        if os.environ.get("PC_SCAN_PRIME", "1") != "0":
            try:
                sharedscan.prime(out_path)
            except (OSError, medialib.MediaError):
                pass
        return out_path

    # plan payload (store/keys schema): everything that determines the
    # encoded bytes — the SRC's content digest, the decode window, the
    # resolved scale/fps/encode surface. One flipped quality-level or
    # coding field changes the hash and invalidates exactly this segment.
    from ..ops import resize as resize_ops

    plan = {
        "op": "encode_segment",
        "src": store_keys.file_ref(segment.src.file_path),
        "window": [segment.start_time, segment.duration],
        "scale": [target_w, target_h, "bicubic"],
        # the resize-method identity: the decoded-then-rescaled pixels
        # feeding the encoder depend on it (plan-purity)
        "resize": resize_ops.plan_resize_method(),
        "fps": out_fps,
        "pix_fmt": segment.target_pix_fmt,
        "encoder": encoder,
        "passes": passes,
        "rate_control": rc,
        "coding": {
            "crf": segment.quality_level.video_crf
            if coding.crf is not None else None,
            "qp": segment.quality_level.video_qp
            if coding.qp is not None else None,
            "preset": coding.preset,
            "scenecut": bool(coding.scenecut),
            "speed": getattr(coding, "speed", None),
            "quality": getattr(coding, "quality", None),
            "cpu_used": getattr(coding, "cpu_used", None),
            "enc_options": coding.enc_options or None,
        },
        "audio": {
            "long": bool(tc.is_long()),
            "encoder": segment.audio_coding.encoder
            if tc.is_long() and segment.audio_coding is not None else None,
            "bitrate_kbps": float(segment.quality_level.audio_bitrate or 0)
            if tc.is_long() else None,
        },
    }

    job = Job(
        label=f"encode {segment.filename}",
        output_path=out_path,
        fn=run,
        logfile_path=segment.get_logfile_path(),
        plan=plan,
        provenance={
            "segmentFilename": segment.filename,
            "pipeline": {
                "decode": segment.src.filename,
                "window": [segment.start_time, segment.duration],
                "scale": [target_w, target_h, "bicubic"],
                "fps": out_fps,
                "encoder": encoder,
                # present exactly when a requested encoder was unavailable
                # and substituted — the provenance record of the
                # nvenc→libx264/x265 fallback; grep for this key to find
                # substituted segments
                **({"encoder_requested": coding.encoder}
                   if encoder != coding.encoder else {}),
                "passes": passes,
                "rate_control": (
                    {"crf": segment.quality_level.video_crf}
                    if coding.crf is not None
                    else {"qp": segment.quality_level.video_qp}
                    if coding.qp is not None
                    else {"bitrate_kbps": bitrate}
                ),
            },
        },
    )
    return job
