// media.cpp — native media I/O boundary for processing_chain_tpu.
//
// Wraps the system libavformat/libavcodec/libswscale/libswresample (FFmpeg 5.x)
// behind a small C API loaded from Python via ctypes. This replaces the
// reference chain's ffmpeg/ffprobe *subprocess* boundary (reference
// lib/cmd_utils.py shell_call, lib/ffmpeg.py command builders) with an
// in-process boundary that hands decoded frames directly to device staging
// buffers and accepts frames back for host-side encoding.
//
// Covered reference operators:
//   * get_src_info / get_segment_info probing   (lib/ffmpeg.py:433-633)
//   * get_video_frame_info / get_audio_frame_info packet scans
//                                               (lib/ffmpeg.py:636-769)
//   * decode for AVPVS                          (lib/ffmpeg.py:940-1055)
//   * encode_segment codecs x264/x265/vp9/av1   (lib/ffmpeg.py:61-318)
//   * FFV1/FLAC/PCM/v210/rawvideo/prores writeback (lib/ffmpeg.py:988-995,
//     :1177-1259)
//   * mp4->annexb / ivf extraction feeding exact frame-size parsing
//                                               (lib/get_framesize.py:54-77)
//
// All functions return 0 (or a count >= 0) on success and a negative number
// on failure; when an `err` buffer is provided the failure reason is written
// into it.

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavcodec/bsf.h>
#include <libavformat/avformat.h>
#include <libavutil/imgutils.h>
#include <libavutil/motion_vector.h>
#include <libavutil/opt.h>
#include <libavutil/pixdesc.h>
#include <libswresample/swresample.h>
#include <libswscale/swscale.h>
// AVVideoEncParams (per-block QP export) landed in FFmpeg 4.3 (lavu 56.45);
// older 4.x hosts compile the QP aggregation away and report qp_blocks = 0.
#if LIBAVUTIL_VERSION_MAJOR > 56 || \
    (LIBAVUTIL_VERSION_MAJOR == 56 && LIBAVUTIL_VERSION_MINOR >= 45)
#define PC_HAVE_VIDEO_ENC_PARAMS 1
#include <libavutil/video_enc_params.h>
#endif
}

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#define EXPORT extern "C" __attribute__((visibility("default")))

namespace {

void set_err(char* err, int errlen, const std::string& msg) {
    if (err && errlen > 0) {
        snprintf(err, (size_t)errlen, "%s", msg.c_str());
    }
}

std::string av_errstr(int code) {
    char buf[AV_ERROR_MAX_STRING_SIZE] = {0};
    av_strerror(code, buf, sizeof(buf));
    return std::string(buf);
}

double ts_to_sec(int64_t ts, AVRational tb) {
    if (ts == AV_NOPTS_VALUE) return NAN;
    return ts * av_q2d(tb);
}

// ---------------------------------------------------------------------------
// FFmpeg 4.x/5.x compatibility. The AVChannelLayout API landed in lavc 59;
// on lavc 58 hosts (FFmpeg 4.x) the same call sites map onto the legacy
// channels/channel_layout fields. CI pins lavc 59 (python:3.10-bookworm),
// where the < 59 branches compile away entirely.
// ---------------------------------------------------------------------------

int pc_find_best_stream(AVFormatContext* fmt, enum AVMediaType type,
                        const AVCodec** out_codec) {
#if LIBAVFORMAT_VERSION_MAJOR < 59
    AVCodec* c = nullptr;
    int idx = av_find_best_stream(fmt, type, -1, -1,
                                  out_codec ? &c : nullptr, 0);
    if (out_codec) *out_codec = c;
    return idx;
#else
    return av_find_best_stream(fmt, type, -1, -1, out_codec, 0);
#endif
}

#if LIBAVCODEC_VERSION_MAJOR < 59

int pc_par_channels(const AVCodecParameters* par) { return par->channels; }
int pc_ctx_channels(const AVCodecContext* c) { return c->channels; }

void pc_ctx_default_layout(AVCodecContext* c, int channels) {
    c->channels = channels;
    c->channel_layout = (uint64_t)av_get_default_channel_layout(channels);
}

void pc_frame_copy_layout(AVFrame* f, const AVCodecContext* c) {
    f->channels = c->channels;
    f->channel_layout = c->channel_layout;
}

// Allocate + configure an SwrContext: input layout/fmt from `in_ctx`,
// output = default layout of `out_channels` (0 = same layout as input).
int pc_swr_setup(SwrContext** swr, AVCodecContext* in_ctx, int out_channels,
                 AVSampleFormat out_fmt, AVSampleFormat in_fmt, int rate) {
    uint64_t in_layout =
        in_ctx->channel_layout
            ? in_ctx->channel_layout
            : (uint64_t)av_get_default_channel_layout(in_ctx->channels);
    uint64_t out_layout =
        out_channels > 0 ? (uint64_t)av_get_default_channel_layout(out_channels)
                         : in_layout;
    *swr = swr_alloc_set_opts(nullptr, (int64_t)out_layout, out_fmt, rate,
                              (int64_t)in_layout, in_fmt, rate, 0, nullptr);
    return *swr ? 0 : -1;
}

#else

int pc_par_channels(const AVCodecParameters* par) {
    return par->ch_layout.nb_channels;
}
int pc_ctx_channels(const AVCodecContext* c) {
    return c->ch_layout.nb_channels;
}

void pc_ctx_default_layout(AVCodecContext* c, int channels) {
    av_channel_layout_default(&c->ch_layout, channels);
}

void pc_frame_copy_layout(AVFrame* f, const AVCodecContext* c) {
    av_channel_layout_copy(&f->ch_layout, &c->ch_layout);
}

int pc_swr_setup(SwrContext** swr, AVCodecContext* in_ctx, int out_channels,
                 AVSampleFormat out_fmt, AVSampleFormat in_fmt, int rate) {
    AVChannelLayout out_layout;
    if (out_channels > 0) {
        av_channel_layout_default(&out_layout, out_channels);
    } else if (av_channel_layout_copy(&out_layout, &in_ctx->ch_layout) < 0) {
        return -1;
    }
    int ret = swr_alloc_set_opts2(swr, &out_layout, out_fmt, rate,
                                  &in_ctx->ch_layout, in_fmt, rate, 0, nullptr);
    av_channel_layout_uninit(&out_layout);
    return ret;
}

#endif

}  // namespace

// ---------------------------------------------------------------------------
// Probing
// ---------------------------------------------------------------------------

struct MPStreamInfo {
    int32_t stream_index;
    int32_t codec_type;  // 0 video, 1 audio
    char codec_name[32];
    int32_t width, height;
    int32_t coded_width, coded_height;  // decoder coded dims (mb-aligned)
    char pix_fmt[32];
    int32_t fps_num, fps_den;        // r_frame_rate
    int32_t avg_fps_num, avg_fps_den;
    int32_t tb_num, tb_den;          // stream time base
    double duration;                 // seconds (stream, else container)
    int64_t nb_frames;               // container-reported, 0 if unknown
    int64_t bit_rate;                // stream bitrate, 0 if unknown
    int32_t sample_rate;             // audio
    int32_t channels;                // audio
    char sample_fmt[32];             // audio
    char profile[64];                // codec profile name ("" if unknown)
};

struct MPFormatInfo {
    char format_name[64];
    double duration;    // container duration seconds
    int64_t bit_rate;
    int64_t file_size;
    int32_t nb_streams;
};

// ABI handshake: the ctypes side refuses a .so whose struct layout
// differs from its own mirror (a stale binary would otherwise be read at
// the wrong stride — silent garbage, not an error).
EXPORT int mp_stream_info_size(void) { return (int)sizeof(MPStreamInfo); }

EXPORT int mp_probe(const char* path, MPFormatInfo* fmt_out,
                    MPStreamInfo* streams_out, int max_streams,
                    int want_coded_dims, char* err, int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    ret = avformat_find_stream_info(fmt, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    if (fmt_out) {
        memset(fmt_out, 0, sizeof(*fmt_out));
        snprintf(fmt_out->format_name, sizeof(fmt_out->format_name), "%s",
                 fmt->iformat ? fmt->iformat->name : "?");
        fmt_out->duration =
            fmt->duration != AV_NOPTS_VALUE ? (double)fmt->duration / AV_TIME_BASE : 0.0;
        fmt_out->bit_rate = fmt->bit_rate;
        AVIOContext* pb = fmt->pb;
        fmt_out->file_size = pb ? avio_size(pb) : 0;
        fmt_out->nb_streams = (int32_t)fmt->nb_streams;
    }
    int n = 0;
    for (unsigned i = 0; i < fmt->nb_streams && n < max_streams; i++) {
        AVStream* st = fmt->streams[i];
        AVCodecParameters* par = st->codecpar;
        if (par->codec_type != AVMEDIA_TYPE_VIDEO &&
            par->codec_type != AVMEDIA_TYPE_AUDIO)
            continue;
        MPStreamInfo* si = &streams_out[n++];
        memset(si, 0, sizeof(*si));
        si->stream_index = (int32_t)i;
        si->codec_type = par->codec_type == AVMEDIA_TYPE_VIDEO ? 0 : 1;
        const AVCodecDescriptor* desc = avcodec_descriptor_get(par->codec_id);
        snprintf(si->codec_name, sizeof(si->codec_name), "%s",
                 desc ? desc->name : "?");
        si->width = par->width;
        si->height = par->height;
        if (par->codec_type == AVMEDIA_TYPE_VIDEO) {
            const char* pf = av_get_pix_fmt_name((AVPixelFormat)par->format);
            snprintf(si->pix_fmt, sizeof(si->pix_fmt), "%s", pf ? pf : "?");
            // filled below by the coded-dims pass; default = display dims
            si->coded_width = par->width;
            si->coded_height = par->height;
            AVRational r = st->r_frame_rate;
            si->fps_num = r.num;
            si->fps_den = r.den;
            si->avg_fps_num = st->avg_frame_rate.num;
            si->avg_fps_den = st->avg_frame_rate.den;
        } else {
            si->sample_rate = par->sample_rate;
            si->channels = pc_par_channels(par);
            const char* sf =
                av_get_sample_fmt_name((AVSampleFormat)par->format);
            snprintf(si->sample_fmt, sizeof(si->sample_fmt), "%s", sf ? sf : "?");
        }
        si->tb_num = st->time_base.num;
        si->tb_den = st->time_base.den;
        si->duration = st->duration != AV_NOPTS_VALUE
                           ? ts_to_sec(st->duration, st->time_base)
                           : (fmt->duration != AV_NOPTS_VALUE
                                  ? (double)fmt->duration / AV_TIME_BASE
                                  : 0.0);
        si->nb_frames = st->nb_frames;
        si->bit_rate = par->bit_rate;
        const char* prof = avcodec_profile_name(par->codec_id, par->profile);
        snprintf(si->profile, sizeof(si->profile), "%s", prof ? prof : "");
    }

    // Coded-dims pass (opt-in: costs a decoder open + first-frame
    // decode, so per-segment probes skip it): what ffprobe reports as
    // coded_width/coded_height — mb-aligned for h264/h265, known only
    // after the decoder has seen a frame. The reference's sidecar
    // contract and its AVPVS dims math consume these
    // (lib/ffmpeg.py:975-976/:1013-1014/:1173-1174). Sidecar caching
    // makes this a once-per-SRC cost.
    for (int k = 0; want_coded_dims && k < n; k++) {
        if (streams_out[k].codec_type != 0) continue;
        int si_idx = streams_out[k].stream_index;
        AVStream* st = fmt->streams[si_idx];
        const AVCodec* cdec = avcodec_find_decoder(st->codecpar->codec_id);
        if (!cdec) break;
        AVCodecContext* cctx = avcodec_alloc_context3(cdec);
        if (!cctx) break;
        if (avcodec_parameters_to_context(cctx, st->codecpar) < 0 ||
            avcodec_open2(cctx, cdec, nullptr) < 0) {
            avcodec_free_context(&cctx);
            break;
        }
        AVPacket* pkt = av_packet_alloc();
        AVFrame* frm = av_frame_alloc();
        int fed = 0;
        bool got = false;
        while (pkt && frm && !got && fed < 64 &&
               av_read_frame(fmt, pkt) >= 0) {
            if (pkt->stream_index == si_idx) {
                fed++;
                if (avcodec_send_packet(cctx, pkt) >= 0 &&
                    avcodec_receive_frame(cctx, frm) >= 0)
                    got = true;
            }
            av_packet_unref(pkt);
        }
        if (pkt && frm && !got) {
            // drain: short streams with reorder delay only emit their
            // frames at EOF flush
            avcodec_send_packet(cctx, nullptr);
            if (avcodec_receive_frame(cctx, frm) >= 0) got = true;
        }
        if (got && cctx->coded_width > 0) {
            streams_out[k].coded_width = cctx->coded_width;
            streams_out[k].coded_height = cctx->coded_height;
        }
        av_frame_free(&frm);
        av_packet_free(&pkt);
        avcodec_free_context(&cctx);
        break;  // first video stream only
    }
    avformat_close_input(&fmt);
    return n;
}

// ---------------------------------------------------------------------------
// Packet scan (feeds .vfi/.afi/.qchanges metadata; reference ffprobe
// -show_packets, lib/ffmpeg.py:636-769)
// ---------------------------------------------------------------------------

// Fills parallel arrays (caller-allocated, capacity `cap`):
//   sizes (bytes), pts_time, dts_time, duration_time (seconds; NaN if unset),
//   key flags (1/0). Returns number of packets, or < 0 on error.
EXPORT long mp_scan_packets(const char* path, int codec_type /*0 v, 1 a*/,
                            int64_t* sizes, double* pts_time, double* dts_time,
                            double* dur_time, int8_t* keyflags, long cap,
                            char* err, int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    if ((ret = avformat_find_stream_info(fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    enum AVMediaType want =
        codec_type == 0 ? AVMEDIA_TYPE_VIDEO : AVMEDIA_TYPE_AUDIO;
    int sidx = av_find_best_stream(fmt, want, -1, -1, nullptr, 0);
    if (sidx < 0) {
        set_err(err, errlen, "no such stream");
        avformat_close_input(&fmt);
        return -2;
    }
    AVRational tb = fmt->streams[sidx]->time_base;
    AVPacket* pkt = av_packet_alloc();
    long n = 0;
    while (av_read_frame(fmt, pkt) >= 0) {
        if (pkt->stream_index == sidx) {
            if (n < cap) {
                sizes[n] = pkt->size;
                pts_time[n] = ts_to_sec(pkt->pts, tb);
                dts_time[n] = ts_to_sec(pkt->dts, tb);
                dur_time[n] = pkt->duration > 0 ? pkt->duration * av_q2d(tb) : NAN;
                keyflags[n] = (pkt->flags & AV_PKT_FLAG_KEY) ? 1 : 0;
            }
            n++;
        }
        av_packet_unref(pkt);
    }
    av_packet_free(&pkt);
    avformat_close_input(&fmt);
    return n;  // may exceed cap: caller re-allocates and re-scans
}

// One demux pass recording BOTH the best video and best audio stream's
// packets (the shared post-encode scan: io/sharedscan.py). Array
// semantics per stream match mp_scan_packets. Writes packet counts to
// *n_video / *n_audio; either may exceed its cap (caller re-allocates
// and re-scans). *n_audio is -1 when the container has no audio stream;
// a missing video stream is an error to match mp_scan_packets(video).
// Returns 0 on success, < 0 on error.
EXPORT int mp_scan_packets_all(
    const char* path,
    int64_t* v_sizes, double* v_pts, double* v_dts, double* v_dur,
    int8_t* v_key, long v_cap, long* n_video,
    int64_t* a_sizes, double* a_pts, double* a_dts, double* a_dur,
    int8_t* a_key, long a_cap, long* n_audio,
    char* err, int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    if ((ret = avformat_find_stream_info(fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    int vidx = av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
    if (vidx < 0) {
        set_err(err, errlen, "no such stream");
        avformat_close_input(&fmt);
        return -2;
    }
    int aidx = av_find_best_stream(fmt, AVMEDIA_TYPE_AUDIO, -1, -1, nullptr, 0);
    AVRational vtb = fmt->streams[vidx]->time_base;
    AVRational atb = aidx >= 0 ? fmt->streams[aidx]->time_base : AVRational{1, 1};
    AVPacket* pkt = av_packet_alloc();
    long nv = 0, na = 0;
    while (av_read_frame(fmt, pkt) >= 0) {
        if (pkt->stream_index == vidx) {
            if (nv < v_cap) {
                v_sizes[nv] = pkt->size;
                v_pts[nv] = ts_to_sec(pkt->pts, vtb);
                v_dts[nv] = ts_to_sec(pkt->dts, vtb);
                v_dur[nv] = pkt->duration > 0 ? pkt->duration * av_q2d(vtb) : NAN;
                v_key[nv] = (pkt->flags & AV_PKT_FLAG_KEY) ? 1 : 0;
            }
            nv++;
        } else if (aidx >= 0 && pkt->stream_index == aidx) {
            if (na < a_cap) {
                a_sizes[na] = pkt->size;
                a_pts[na] = ts_to_sec(pkt->pts, atb);
                a_dts[na] = ts_to_sec(pkt->dts, atb);
                a_dur[na] = pkt->duration > 0 ? pkt->duration * av_q2d(atb) : NAN;
                a_key[na] = (pkt->flags & AV_PKT_FLAG_KEY) ? 1 : 0;
            }
            na++;
        }
        av_packet_unref(pkt);
    }
    av_packet_free(&pkt);
    avformat_close_input(&fmt);
    *n_video = nv;
    *n_audio = aidx >= 0 ? na : -1;
    return 0;
}

// ---------------------------------------------------------------------------
// Video decoding
// ---------------------------------------------------------------------------

struct MPDecoder {
    AVFormatContext* fmt = nullptr;
    AVCodecContext* dec = nullptr;
    int sidx = -1;
    AVPacket* pkt = nullptr;
    AVFrame* frame = nullptr;
    bool draining = false;
    double start_s = 0.0, end_s = -1.0;  // trim window; end < 0 = unbounded
    // plane geometry the caller's buffers were sized with (captured at
    // open time; the dec context's width/pix_fmt can change mid-stream
    // on a parameter-set switch and must then never drive a memcpy past
    // the open-time buffer size)
    int buf_rows[4] = {0, 0, 0, 0};
    int buf_row_bytes[4] = {0, 0, 0, 0};
    int open_w = 0, open_h = 0;
    int open_fmt = AV_PIX_FMT_NONE;
};

struct MPVideoDesc {
    int32_t width, height;
    char pix_fmt[32];
    int32_t fps_num, fps_den;
    double duration;
    int32_t planes;             // number of planes
    int32_t plane_w[4], plane_h[4];
    int32_t bytes_per_sample;   // 1 or 2
};

// Exact byte width of one row of plane p at the given pixel width.
// av_image_get_linesize handles packed formats (uyvy422 carries two
// samples per pixel in one plane — per-plane pixel count undercounts
// them by 2x); the pw*bps fallback covers formats it rejects.
static int plane_row_bytes(AVPixelFormat pf, int width, int p,
                           const AVPixFmtDescriptor* desc, int bps) {
    int lsz = av_image_get_linesize(pf, width, p);
    if (lsz > 0) return lsz;
    int pw = (p == 1 || p == 2) ? AV_CEIL_RSHIFT(width, desc->log2_chroma_w)
                                : width;
    return pw * bps;
}

static int fill_video_desc(MPDecoder* d, MPVideoDesc* out) {
    memset(out, 0, sizeof(*out));
    out->width = d->dec->width;
    out->height = d->dec->height;
    AVPixelFormat pf = d->dec->pix_fmt;
    const char* pfn = av_get_pix_fmt_name(pf);
    snprintf(out->pix_fmt, sizeof(out->pix_fmt), "%s", pfn ? pfn : "?");
    AVStream* st = d->fmt->streams[d->sidx];
    out->fps_num = st->r_frame_rate.num;
    out->fps_den = st->r_frame_rate.den;
    out->duration = st->duration != AV_NOPTS_VALUE
                        ? ts_to_sec(st->duration, st->time_base)
                        : (d->fmt->duration != AV_NOPTS_VALUE
                               ? (double)d->fmt->duration / AV_TIME_BASE
                               : 0.0);
    const AVPixFmtDescriptor* desc = av_pix_fmt_desc_get(pf);
    if (!desc) return -1;
    int planes = av_pix_fmt_count_planes(pf);
    out->planes = planes;
    out->bytes_per_sample = desc->comp[0].depth > 8 ? 2 : 1;
    for (int p = 0; p < planes && p < 4; p++) {
        int is_chroma = (p == 1 || p == 2);
        // row width exposed in SAMPLES so plane_w*plane_h*bytes_per_sample
        // sizes the Python-side buffer exactly (packed formats included)
        out->plane_w[p] = plane_row_bytes(pf, out->width, p, desc,
                                          out->bytes_per_sample)
                          / out->bytes_per_sample;
        out->plane_h[p] =
            is_chroma ? AV_CEIL_RSHIFT(out->height, desc->log2_chroma_h) : out->height;
    }
    return 0;
}

// threads: decoder thread_count (0 = auto = one per core; 1 = serial).
// Frame threading hides the codec's per-frame latency behind the batch
// loop in mp_decoder_next_batch — the decode-side analog of the
// encoder's slice/frame threading knobs.
EXPORT MPDecoder* mp_decoder_open_t(const char* path, double start_s,
                                    double dur_s, int threads, char* err,
                                    int errlen) {
    auto* d = new MPDecoder();
    int ret = avformat_open_input(&d->fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        delete d;
        return nullptr;
    }
    if ((ret = avformat_find_stream_info(d->fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    const AVCodec* codec = nullptr;
    d->sidx = pc_find_best_stream(d->fmt, AVMEDIA_TYPE_VIDEO, &codec);
    if (d->sidx < 0 || !codec) {
        set_err(err, errlen, "no video stream");
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    d->dec = avcodec_alloc_context3(codec);
    avcodec_parameters_to_context(d->dec, d->fmt->streams[d->sidx]->codecpar);
    d->dec->thread_count = threads >= 0 ? threads : 0;
    if ((ret = avcodec_open2(d->dec, codec, nullptr)) < 0) {
        set_err(err, errlen, "avcodec_open2: " + av_errstr(ret));
        avcodec_free_context(&d->dec);
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    d->pkt = av_packet_alloc();
    d->frame = av_frame_alloc();
    {   // capture the open-time plane geometry from the SAME computation
        // that sizes the caller's buffers (fill_video_desc), so the
        // decoder clamp and the Python allocation can never drift apart
        MPVideoDesc vd;
        if (fill_video_desc(d, &vd) == 0) {
            for (int p = 0; p < vd.planes && p < 4; p++) {
                d->buf_rows[p] = vd.plane_h[p];
                d->buf_row_bytes[p] = vd.plane_w[p] * vd.bytes_per_sample;
            }
        }
        d->open_w = d->dec->width;
        d->open_h = d->dec->height;
        d->open_fmt = d->dec->pix_fmt;
    }
    d->start_s = start_s > 0 ? start_s : 0.0;
    d->end_s = dur_s > 0 ? d->start_s + dur_s : -1.0;
    if (d->start_s > 0) {
        AVRational tb = d->fmt->streams[d->sidx]->time_base;
        int64_t ts = (int64_t)(d->start_s / av_q2d(tb));
        // seek to the keyframe at/before start; trailing frames are dropped
        // in mp_decoder_next (the -ss accurate-seek semantics of the
        // reference's ffmpeg commands, lib/ffmpeg.py:877)
        avformat_seek_file(d->fmt, d->sidx, INT64_MIN, ts, ts, 0);
    }
    return d;
}

// Legacy entry point (auto threading), kept so an OLDER Python package
// keeps loading a .so built from this newer source (the reverse —
// newer Python on a pre-batch .so — fails loudly at symbol bind in
// medialib.ensure_loaded, same policy as mp_decode_audio_s16_ch).
EXPORT MPDecoder* mp_decoder_open(const char* path, double start_s, double dur_s,
                                  char* err, int errlen) {
    return mp_decoder_open_t(path, start_s, dur_s, 0, err, errlen);
}

EXPORT int mp_decoder_desc(MPDecoder* d, MPVideoDesc* out) {
    return fill_video_desc(d, out);
}

// Decode the next frame inside the trim window into caller-provided plane
// buffers (contiguous, sized plane_w*plane_h*bytes_per_sample each; pass
// nullptr for unused planes). Returns 1 on frame, 0 on EOF, < 0 on error.
static int decoder_next_into(MPDecoder* d, uint8_t* planes[4],
                             double* pts_out, char* err, int errlen) {
    AVRational tb = d->fmt->streams[d->sidx]->time_base;
    const AVPixFmtDescriptor* desc = av_pix_fmt_desc_get(d->dec->pix_fmt);
    for (;;) {
        int ret = avcodec_receive_frame(d->dec, d->frame);
        if (ret == 0) {
            double pts = ts_to_sec(
                d->frame->best_effort_timestamp != AV_NOPTS_VALUE
                    ? d->frame->best_effort_timestamp
                    : d->frame->pts,
                tb);
            if (!std::isnan(pts) && pts < d->start_s - 1e-9) {
                av_frame_unref(d->frame);
                continue;  // pre-roll frame before trim start
            }
            if (d->end_s > 0 && !std::isnan(pts) && pts >= d->end_s - 1e-9) {
                av_frame_unref(d->frame);
                return 0;  // past trim end
            }
            // a mid-stream parameter switch (resolution, bit depth,
            // format) breaks the open-time buffer contract: fail loudly
            // — a clamped copy would hand downstream partially-zeroed
            // "valid" frames. Compared against the OPEN-time capture
            // (the dec context's own fields track the stream and would
            // mask the switch). The clamps below stay as the
            // memory-safety backstop.
            if (d->frame->width != d->open_w ||
                d->frame->height != d->open_h ||
                d->frame->format != d->open_fmt) {
                set_err(err, errlen,
                        "mid-stream parameter switch: frame " +
                            std::to_string(d->frame->width) + "x" +
                            std::to_string(d->frame->height) +
                            " differs from open-time " +
                            std::to_string(d->open_w) + "x" +
                            std::to_string(d->open_h));
                av_frame_unref(d->frame);
                return -1;
            }
            const AVPixFmtDescriptor* fdesc =
                av_pix_fmt_desc_get((AVPixelFormat)d->frame->format);
            if (!fdesc) fdesc = desc;
            int nplanes = av_pix_fmt_count_planes(
                (AVPixelFormat)d->frame->format);
            if (nplanes <= 0) nplanes = av_pix_fmt_count_planes(d->dec->pix_fmt);
            for (int p = 0; p < nplanes && p < 4; p++) {
                if (!planes[p]) continue;
                int is_chroma = (p == 1 || p == 2);
                // the caller's buffers were sized from the OPEN-time
                // geometry (buf_rows/buf_row_bytes); a mid-stream
                // parameter switch (taller/wider frames, format change)
                // must neither overrun them nor overread the AVFrame, so
                // both the row count and the copy width clamp to the
                // smaller of the two geometries
                int fr_rows = is_chroma
                                  ? AV_CEIL_RSHIFT(d->frame->height,
                                                   fdesc->log2_chroma_h)
                                  : d->frame->height;
                int rows = d->buf_rows[p] < fr_rows ? d->buf_rows[p] : fr_rows;
                int row_bytes = d->buf_row_bytes[p];
                int ls = d->frame->linesize[p];
                // copy width: the frame's REAL row bytes (not linesize —
                // that includes alignment padding a narrower mid-stream
                // frame would leak into the output), clamped to the
                // open-time buffer width
                int fr_bytes = plane_row_bytes(
                    (AVPixelFormat)d->frame->format, d->frame->width, p,
                    fdesc, (fdesc->comp[0].depth > 8 ? 2 : 1));
                int copy = fr_bytes < row_bytes ? fr_bytes : row_bytes;
                if (ls <= 0) {
                    // negative linesize (vertically flipped layout) is
                    // legal FFmpeg but the row arithmetic below would
                    // wrap (size_t)y * ls into an out-of-bounds read;
                    // fail loudly like the other geometry rejections
                    set_err(err, errlen,
                            "decoder produced non-positive linesize " +
                                std::to_string(ls) + " on plane " +
                                std::to_string(p));
                    av_frame_unref(d->frame);
                    return -1;
                }
                if (ls < copy) copy = ls;
                for (int y = 0; y < rows; y++) {
                    memcpy(planes[p] + (size_t)y * row_bytes,
                           d->frame->data[p] + (size_t)y * (size_t)ls,
                           (size_t)copy);
                }
            }
            if (pts_out) *pts_out = pts;
            av_frame_unref(d->frame);
            return 1;
        }
        if (ret == AVERROR_EOF) return 0;
        if (ret != AVERROR(EAGAIN)) {
            set_err(err, errlen, "receive_frame: " + av_errstr(ret));
            return -1;
        }
        // need more input
        if (d->draining) return 0;
        int rret = av_read_frame(d->fmt, d->pkt);
        if (rret < 0) {
            d->draining = true;
            avcodec_send_packet(d->dec, nullptr);
            continue;
        }
        if (d->pkt->stream_index == d->sidx) {
            int sret = avcodec_send_packet(d->dec, d->pkt);
            if (sret < 0 && sret != AVERROR(EAGAIN)) {
                av_packet_unref(d->pkt);
                set_err(err, errlen, "send_packet: " + av_errstr(sret));
                return -1;
            }
        }
        av_packet_unref(d->pkt);
    }
}

EXPORT int mp_decoder_next(MPDecoder* d, uint8_t* p0, uint8_t* p1, uint8_t* p2,
                           uint8_t* p3, double* pts_out, char* err, int errlen) {
    uint8_t* planes[4] = {p0, p1, p2, p3};
    return decoder_next_into(d, planes, pts_out, err, errlen);
}

// Batched decode: up to `max_frames` frames in ONE call (one ctypes
// crossing, one GIL release) into caller-provided contiguous plane BLOCKS
// laid out [N, plane_h, plane_w] — frame i's plane p lands at
// base_p + i * plane_h[p] * row_bytes[p] (the open-time geometry, so the
// blocks a Python [N, h, w] ndarray describes are addressed exactly).
// pts_out receives one timestamp per decoded frame. Returns the number of
// frames decoded (0 = EOF / window end), or < 0 on error.
EXPORT long mp_decoder_next_batch(MPDecoder* d, uint8_t* p0, uint8_t* p1,
                                  uint8_t* p2, uint8_t* p3, long max_frames,
                                  double* pts_out, char* err, int errlen) {
    uint8_t* bases[4] = {p0, p1, p2, p3};
    size_t fsize[4];
    for (int p = 0; p < 4; p++)
        fsize[p] = (size_t)d->buf_rows[p] * (size_t)d->buf_row_bytes[p];
    long n = 0;
    while (n < max_frames) {
        uint8_t* planes[4];
        for (int p = 0; p < 4; p++)
            planes[p] = bases[p] ? bases[p] + (size_t)n * fsize[p] : nullptr;
        double pts = 0.0;
        int ret = decoder_next_into(d, planes, &pts, err, errlen);
        if (ret < 0) return ret;
        if (ret == 0) break;
        if (pts_out) pts_out[n] = pts;
        n++;
    }
    return n;
}

EXPORT void mp_decoder_close(MPDecoder* d) {
    if (!d) return;
    av_packet_free(&d->pkt);
    av_frame_free(&d->frame);
    avcodec_free_context(&d->dec);
    avformat_close_input(&d->fmt);
    delete d;
}

// ---------------------------------------------------------------------------
// Codec-prior extraction (docs/PRIORS.md): the decode the chain already pays
// for also computes motion vectors and per-block QP — this decoder mode
// exports them as frame side data (AV_CODEC_FLAG2_EXPORT_MVS +
// AVVideoEncParams) instead of discarding them. No pixel planes cross the
// boundary: one batch call returns fixed-size per-frame records plus the
// frames' ragged MV rows, one GIL release per chunk like
// mp_decoder_next_batch. MV export covers the mpegvideo/h264 decoder
// families; codecs whose native decoders do not export MVs (hevc, vp9,
// av1) still yield frame types / packet sizes / QP-when-available.
// ---------------------------------------------------------------------------

// Per-frame prior record. Mirrored as a ctypes Structure AND a numpy
// structured dtype in io/medialib.py; mp_priors_record_size is the ABI
// handshake that keeps the three layouts from drifting.
struct MPPriorsFrame {
    double pts;          // seconds (best-effort), NaN when unset
    int64_t pkt_size;    // compressed bytes of this frame's packet (0 unknown)
    int32_t pict_type;   // AV_PICTURE_TYPE_*: 1 I, 2 P, 3 B, 0 unknown
    int32_t key_frame;
    int32_t mv_count;    // MV rows emitted for this frame
    int32_t qp_blocks;   // QP samples aggregated (0 = no QP side data)
    double qp_mean;      // mean per-block QP, -1 when absent
    double qp_var;       // population variance of per-block QP, -1 when absent
    int32_t width, height;
};

//: int32 fields per MV row: src_x, src_y, dst_x, dst_y, w, h, source
#define PC_MV_FIELDS 7

struct MPPriorsDec {
    AVFormatContext* fmt = nullptr;
    AVCodecContext* dec = nullptr;
    int sidx = -1;
    AVPacket* pkt = nullptr;
    AVFrame* frame = nullptr;
    bool draining = false;
    // pts/dts -> packet size, so records carry compressed frame sizes
    // without depending on the deprecated AVFrame.pkt_size (bounded: the
    // decoder's reorder depth keeps this to a handful of entries)
    std::map<int64_t, int64_t> pkt_sizes;
    // a decoded frame whose MV rows did not fit the caller's buffer is
    // parked here and re-emitted first on the next call — streaming stays
    // exact under any caller buffer size
    bool have_pending = false;
    MPPriorsFrame pending{};
    std::vector<int32_t> pending_mv;
};

EXPORT int mp_priors_record_size(void) { return (int)sizeof(MPPriorsFrame); }

EXPORT MPPriorsDec* mp_decoder_open_priors(const char* path, int threads,
                                           char* err, int errlen) {
    auto* d = new MPPriorsDec();
    int ret = avformat_open_input(&d->fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        delete d;
        return nullptr;
    }
    if ((ret = avformat_find_stream_info(d->fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    const AVCodec* codec = nullptr;
    d->sidx = pc_find_best_stream(d->fmt, AVMEDIA_TYPE_VIDEO, &codec);
    if (d->sidx < 0 || !codec) {
        set_err(err, errlen, "no video stream");
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    d->dec = avcodec_alloc_context3(codec);
    avcodec_parameters_to_context(d->dec, d->fmt->streams[d->sidx]->codecpar);
    d->dec->thread_count = threads >= 0 ? threads : 0;
    // the whole point of this mode: ask the decoder to keep what it
    // already computed
    d->dec->flags2 |= AV_CODEC_FLAG2_EXPORT_MVS;
#if defined(PC_HAVE_VIDEO_ENC_PARAMS) && defined(AV_CODEC_EXPORT_DATA_VIDEO_ENC_PARAMS)
    d->dec->export_side_data |= AV_CODEC_EXPORT_DATA_VIDEO_ENC_PARAMS;
#endif
    if ((ret = avcodec_open2(d->dec, codec, nullptr)) < 0) {
        set_err(err, errlen, "avcodec_open2: " + av_errstr(ret));
        avcodec_free_context(&d->dec);
        avformat_close_input(&d->fmt);
        delete d;
        return nullptr;
    }
    d->pkt = av_packet_alloc();
    d->frame = av_frame_alloc();
    return d;
}

// Decode the next frame and fill (rec, mv). Returns 1 frame, 0 EOF, <0 error.
static int priors_next_frame(MPPriorsDec* d, MPPriorsFrame* rec,
                             std::vector<int32_t>& mv, char* err, int errlen) {
    AVRational tb = d->fmt->streams[d->sidx]->time_base;
    for (;;) {
        int ret = avcodec_receive_frame(d->dec, d->frame);
        if (ret == 0) {
            memset(rec, 0, sizeof(*rec));
            int64_t ts = d->frame->best_effort_timestamp != AV_NOPTS_VALUE
                             ? d->frame->best_effort_timestamp
                             : d->frame->pts;
            rec->pts = ts_to_sec(ts, tb);
            rec->pict_type = (int32_t)d->frame->pict_type;
#if LIBAVCODEC_VERSION_MAJOR >= 60
            rec->key_frame = (d->frame->flags & AV_FRAME_FLAG_KEY) ? 1 : 0;
#else
            rec->key_frame = d->frame->key_frame ? 1 : 0;
#endif
            rec->width = d->frame->width;
            rec->height = d->frame->height;
            rec->qp_mean = -1.0;
            rec->qp_var = -1.0;
            if (ts != AV_NOPTS_VALUE) {
                auto it = d->pkt_sizes.find(ts);
                if (it != d->pkt_sizes.end()) {
                    rec->pkt_size = it->second;
                    d->pkt_sizes.erase(it);
                }
            }
            if (const AVFrameSideData* sd = av_frame_get_side_data(
                    d->frame, AV_FRAME_DATA_MOTION_VECTORS)) {
                const AVMotionVector* mvs = (const AVMotionVector*)sd->data;
                size_t n = sd->size / sizeof(*mvs);
                mv.reserve(mv.size() + n * PC_MV_FIELDS);
                for (size_t i = 0; i < n; i++) {
                    mv.push_back((int32_t)mvs[i].src_x);
                    mv.push_back((int32_t)mvs[i].src_y);
                    mv.push_back((int32_t)mvs[i].dst_x);
                    mv.push_back((int32_t)mvs[i].dst_y);
                    mv.push_back((int32_t)mvs[i].w);
                    mv.push_back((int32_t)mvs[i].h);
                    mv.push_back((int32_t)mvs[i].source);
                }
                rec->mv_count = (int32_t)n;
            }
#ifdef PC_HAVE_VIDEO_ENC_PARAMS
            if (const AVFrameSideData* sd = av_frame_get_side_data(
                    d->frame, AV_FRAME_DATA_VIDEO_ENC_PARAMS)) {
                AVVideoEncParams* par = (AVVideoEncParams*)sd->data;
                double sum = 0.0, sumsq = 0.0;
                long nq = 0;
                if (par->nb_blocks > 0) {
                    for (unsigned i = 0; i < par->nb_blocks; i++) {
                        const AVVideoBlockParams* b =
                            av_video_enc_params_block(par, i);
                        double q = (double)par->qp + (double)b->delta_qp;
                        sum += q;
                        sumsq += q * q;
                        nq++;
                    }
                } else {
                    sum = (double)par->qp;
                    sumsq = sum * sum;
                    nq = 1;
                }
                if (nq > 0) {
                    double mean = sum / nq;
                    double var = sumsq / nq - mean * mean;
                    rec->qp_mean = mean;
                    rec->qp_var = var > 0.0 ? var : 0.0;
                    rec->qp_blocks = (int32_t)nq;
                }
            }
#endif
            av_frame_unref(d->frame);
            return 1;
        }
        if (ret == AVERROR_EOF) return 0;
        if (ret != AVERROR(EAGAIN)) {
            set_err(err, errlen, "receive_frame: " + av_errstr(ret));
            return -1;
        }
        if (d->draining) return 0;
        int rret = av_read_frame(d->fmt, d->pkt);
        if (rret < 0) {
            d->draining = true;
            avcodec_send_packet(d->dec, nullptr);
            continue;
        }
        if (d->pkt->stream_index == d->sidx) {
            int64_t key = d->pkt->pts != AV_NOPTS_VALUE ? d->pkt->pts
                                                        : d->pkt->dts;
            // bound the map: a stream whose timestamps never match its
            // frames (breaking the erase-on-hit) must not grow unbounded
            if (key != AV_NOPTS_VALUE && d->pkt_sizes.size() < 4096)
                d->pkt_sizes[key] = d->pkt->size;
            int sret = avcodec_send_packet(d->dec, d->pkt);
            if (sret < 0 && sret != AVERROR(EAGAIN)) {
                av_packet_unref(d->pkt);
                set_err(err, errlen, "send_packet: " + av_errstr(sret));
                return -1;
            }
        }
        av_packet_unref(d->pkt);
    }
}

// Up to `max_frames` per-frame records in ONE call. MV rows land
// contiguously in mv_buf ([mv_cap_rows, PC_MV_FIELDS] int32, frame order;
// frame i's rows start after the rows of frames 0..i-1 of THIS call —
// recs[i].mv_count delimits them). Returns frames filled (0 = EOF), -1 on
// decode error, or -2 when a single frame carries more MV rows than
// mv_cap_rows (the frame is parked; the caller grows its buffer and
// retries with nothing lost).
EXPORT long mp_priors_next_batch(MPPriorsDec* d, MPPriorsFrame* recs,
                                 long max_frames, int32_t* mv_buf,
                                 long mv_cap_rows, char* err, int errlen) {
    long n = 0, rows = 0;
    if (max_frames <= 0) return 0;
    if (d->have_pending) {
        long need = d->pending.mv_count;
        if (need > mv_cap_rows) {
            set_err(err, errlen,
                    "mv buffer too small: frame carries " +
                        std::to_string(need) + " motion vectors");
            return -2;
        }
        recs[n] = d->pending;
        if (!d->pending_mv.empty())
            memcpy(mv_buf, d->pending_mv.data(),
                   d->pending_mv.size() * sizeof(int32_t));
        rows = need;
        n = 1;
        d->have_pending = false;
        d->pending_mv.clear();
    }
    std::vector<int32_t> mv;
    while (n < max_frames) {
        MPPriorsFrame rec;
        mv.clear();
        int ret = priors_next_frame(d, &rec, mv, err, errlen);
        if (ret < 0) return ret;
        if (ret == 0) break;
        if (rows + rec.mv_count > mv_cap_rows) {
            d->pending = rec;
            d->pending_mv = mv;
            d->have_pending = true;
            if (n == 0) {
                set_err(err, errlen,
                        "mv buffer too small: frame carries " +
                            std::to_string(rec.mv_count) +
                            " motion vectors");
                return -2;
            }
            break;
        }
        recs[n] = rec;
        if (!mv.empty())
            memcpy(mv_buf + (size_t)rows * PC_MV_FIELDS, mv.data(),
                   mv.size() * sizeof(int32_t));
        rows += rec.mv_count;
        n++;
    }
    return n;
}

EXPORT void mp_priors_close(MPPriorsDec* d) {
    if (!d) return;
    av_packet_free(&d->pkt);
    av_frame_free(&d->frame);
    avcodec_free_context(&d->dec);
    avformat_close_input(&d->fmt);
    delete d;
}

// ---------------------------------------------------------------------------
// Audio decoding (SRC audio for AVPVS mux; reference lib/ffmpeg.py:1262-1289)
// ---------------------------------------------------------------------------

// Decodes the best audio stream to interleaved s16 within [start_s,
// start_s+dur_s). Two-phase: call with buf == nullptr to get the required
// sample count (per channel), then with a buffer of size
// samples*channels*2 bytes. Returns samples (per channel) or < 0.
//
// out_channels > 0 remixes to that channel count's default layout INSIDE
// libswresample — byte-for-byte the ffmpeg CLI's `-ac N` semantics (the
// reference's stereo downmix in audio_mux, lib/ffmpeg.py:1284: `-ac 2`),
// including its 5.1->stereo matrix and normalization. 0 keeps the native
// layout. channels_out reports the OUTPUT channel count.
EXPORT long mp_decode_audio_s16_ch(const char* path, double start_s,
                                   double dur_s, int out_channels,
                                   int16_t* buf, long buf_samples,
                                   int32_t* sample_rate_out,
                                   int32_t* channels_out, char* err,
                                   int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    if ((ret = avformat_find_stream_info(fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    const AVCodec* codec = nullptr;
    int sidx = pc_find_best_stream(fmt, AVMEDIA_TYPE_AUDIO, &codec);
    if (sidx < 0 || !codec) {
        set_err(err, errlen, "no audio stream");
        avformat_close_input(&fmt);
        return -2;
    }
    AVCodecContext* dec = avcodec_alloc_context3(codec);
    avcodec_parameters_to_context(dec, fmt->streams[sidx]->codecpar);
    if ((ret = avcodec_open2(dec, codec, nullptr)) < 0) {
        set_err(err, errlen, "avcodec_open2: " + av_errstr(ret));
        avcodec_free_context(&dec);
        avformat_close_input(&fmt);
        return -1;
    }
    int channels = out_channels > 0 ? out_channels
                                    : pc_ctx_channels(dec);
    int rate = dec->sample_rate;
    if (sample_rate_out) *sample_rate_out = rate;
    if (channels_out) *channels_out = channels;

    SwrContext* swr = nullptr;
    ret = pc_swr_setup(&swr, dec, out_channels, AV_SAMPLE_FMT_S16,
                       dec->sample_fmt, rate);
    if (ret < 0 || swr_init(swr) < 0) {
        set_err(err, errlen, "swr_init failed");
        avcodec_free_context(&dec);
        avformat_close_input(&fmt);
        return -1;
    }

    AVRational tb = fmt->streams[sidx]->time_base;
    double end_s = dur_s > 0 ? start_s + dur_s : -1.0;
    AVPacket* pkt = av_packet_alloc();
    AVFrame* frame = av_frame_alloc();
    long total = 0;
    bool draining = false;
    std::vector<int16_t> tmp;
    for (;;) {
        ret = avcodec_receive_frame(dec, frame);
        if (ret == 0) {
            double pts = ts_to_sec(frame->pts, tb);
            bool keep = true;
            if (!std::isnan(pts)) {
                if (pts + (double)frame->nb_samples / rate <= start_s) keep = false;
                if (end_s > 0 && pts >= end_s) keep = false;
            }
            if (keep) {
                tmp.resize((size_t)frame->nb_samples * channels);
                uint8_t* outp = (uint8_t*)tmp.data();
                int got = swr_convert(swr, &outp, frame->nb_samples,
                                      (const uint8_t**)frame->extended_data,
                                      frame->nb_samples);
                if (got > 0) {
                    if (buf && total + got <= buf_samples) {
                        memcpy(buf + (size_t)total * channels, tmp.data(),
                               (size_t)got * channels * 2);
                    }
                    total += got;
                }
            }
            av_frame_unref(frame);
            continue;
        }
        if (ret == AVERROR_EOF) break;
        if (ret != AVERROR(EAGAIN)) break;
        if (draining) break;
        int rret = av_read_frame(fmt, pkt);
        if (rret < 0) {
            draining = true;
            avcodec_send_packet(dec, nullptr);
            continue;
        }
        if (pkt->stream_index == sidx) avcodec_send_packet(dec, pkt);
        av_packet_unref(pkt);
    }
    av_packet_free(&pkt);
    av_frame_free(&frame);
    swr_free(&swr);
    avcodec_free_context(&dec);
    avformat_close_input(&fmt);
    return total;
}

// Back-compat shim: native channel layout (out_channels = 0).
EXPORT long mp_decode_audio_s16(const char* path, double start_s, double dur_s,
                                int16_t* buf, long buf_samples,
                                int32_t* sample_rate_out, int32_t* channels_out,
                                char* err, int errlen) {
    return mp_decode_audio_s16_ch(path, start_s, dur_s, 0, buf, buf_samples,
                                  sample_rate_out, channels_out, err, errlen);
}

// ---------------------------------------------------------------------------
// Encoding / muxing
// ---------------------------------------------------------------------------

struct MPEncoder {
    AVFormatContext* fmt = nullptr;
    AVCodecContext* venc = nullptr;
    AVCodecContext* aenc = nullptr;
    AVStream* vstream = nullptr;
    AVStream* astream = nullptr;
    SwrContext* swr = nullptr;  // s16 interleaved -> aenc sample_fmt
    AVFrame* vframe = nullptr;
    AVFrame* aframe = nullptr;
    int64_t vpts = 0;
    int64_t apts = 0;  // in samples
    std::vector<int16_t> abuf;  // pending audio (interleaved s16)
    int64_t last_dts[2] = {INT64_MIN, INT64_MIN};  // per-stream mux fixup
    FILE* stats_file = nullptr;       // two-pass: pass 1 stats out
    std::string stats_out_path;       // lazy pass-1 fallback target
    std::string stats_in;             // two-pass: pass 2 stats
    bool header_written = false;
    char errbuf[512] = {0};

    // Frame-parallel encode mode ("pc_fp_workers=N" in the vopts string):
    // FFV1 is intra-only, and with gop_size=1 every frame is a keyframe
    // whose range-coder contexts reset — frames are therefore fully
    // independent, so N worker threads each own a private AVCodecContext
    // and the caller thread muxes finished packets back in sequence
    // order. This is the native attack on the host-side FFV1 writeback
    // bottleneck (reference: single `-threads 4` slice threading at
    // lib/ffmpeg.py:1047); unlike slice threading it scales with frames
    // in flight, not slices per frame. venc stays the parameter/extradata
    // reference for the muxer and is never fed frames in this mode.
    int fp_workers = 0;
    std::vector<AVCodecContext*> fp_ctxs;   // one per worker thread
    std::vector<std::thread> fp_threads;
    std::deque<std::pair<int64_t, AVFrame*>> fp_q;       // seq -> frame
    std::map<int64_t, std::vector<AVPacket*>> fp_done;   // seq -> packets
    int64_t fp_next_mux = 0;   // next seq the muxer will write
    int64_t fp_inflight = 0;   // queued or encoding, not yet muxed
    bool fp_stop = false;
    bool fp_error = false;
    std::string fp_error_msg;
    std::mutex fp_mu;
    std::condition_variable fp_cv_work;  // workers: queue non-empty / stop
    std::condition_variable fp_cv_done;  // caller: a seq finished
};

static int enc_write_packets(MPEncoder* e, AVCodecContext* ctx, AVStream* st) {
    AVPacket* pkt = av_packet_alloc();
    int ret;
    while ((ret = avcodec_receive_packet(ctx, pkt)) == 0) {
        // video encoders emit duration 0; one tick in codec tb = one frame,
        // without it the container track loses the last frame's duration
        if (ctx == e->venc && pkt->duration == 0) pkt->duration = 1;
        av_packet_rescale_ts(pkt, ctx->time_base, st->time_base);
        pkt->stream_index = st->index;
        // non-monotonic DTS fixup, as the ffmpeg CLI mux layer does: coarse
        // container timebases (e.g. AVI audio) can collapse distinct
        // timestamps onto the same tick
        int si = st->index < 2 ? st->index : 1;
        if (pkt->dts != AV_NOPTS_VALUE && e->last_dts[si] != INT64_MIN &&
            pkt->dts <= e->last_dts[si]) {
            pkt->dts = e->last_dts[si] + 1;
            if (pkt->pts != AV_NOPTS_VALUE && pkt->pts < pkt->dts)
                pkt->pts = pkt->dts;
        }
        if (pkt->dts != AV_NOPTS_VALUE) e->last_dts[si] = pkt->dts;
        int wret = av_interleaved_write_frame(e->fmt, pkt);
        av_packet_unref(pkt);
        if (wret < 0) {
            av_packet_free(&pkt);
            return wret;
        }
        if (ctx == e->venc && ctx->stats_out && !e->stats_out_path.empty()) {
            if (!e->stats_file)
                e->stats_file = fopen(e->stats_out_path.c_str(), "w");
            if (e->stats_file) fputs(ctx->stats_out, e->stats_file);
        }
    }
    av_packet_free(&pkt);
    return (ret == AVERROR(EAGAIN) || ret == AVERROR_EOF) ? 0 : ret;
}

// --------------------------- frame-parallel encode -------------------------

// Copy contiguous caller plane buffers into an (already allocated, sized)
// AVFrame, honoring the frame's linesize padding.
static int fill_vframe(AVFrame* f, const uint8_t* const planes[4]) {
    int ret = av_frame_make_writable(f);
    if (ret < 0) return ret;
    const AVPixFmtDescriptor* desc =
        av_pix_fmt_desc_get((AVPixelFormat)f->format);
    int nplanes = av_pix_fmt_count_planes((AVPixelFormat)f->format);
    int bps = desc->comp[0].depth > 8 ? 2 : 1;
    for (int p = 0; p < nplanes && p < 4; p++) {
        if (!planes[p]) continue;
        int is_chroma = (p == 1 || p == 2);
        int ph = is_chroma ? AV_CEIL_RSHIFT(f->height, desc->log2_chroma_h)
                           : f->height;
        int row_bytes =
            plane_row_bytes((AVPixelFormat)f->format, f->width, p, desc, bps);
        for (int y = 0; y < ph; y++) {
            memcpy(f->data[p] + (size_t)y * f->linesize[p],
                   planes[p] + (size_t)y * row_bytes, (size_t)row_bytes);
        }
    }
    return 0;
}

// Worker thread: pull frames off the shared queue, encode on a PRIVATE
// context (legal because fp mode forces gop_size=1: every FFV1 frame is a
// keyframe with fresh range-coder state, so no cross-frame context exists),
// park the packets under the frame's sequence number.
static void fp_worker_main(MPEncoder* e, AVCodecContext* ctx) {
    for (;;) {
        int64_t seq;
        AVFrame* frame;
        {
            std::unique_lock<std::mutex> lk(e->fp_mu);
            e->fp_cv_work.wait(lk,
                               [&] { return e->fp_stop || !e->fp_q.empty(); });
            if (e->fp_q.empty()) break;  // fp_stop and drained
            seq = e->fp_q.front().first;
            frame = e->fp_q.front().second;
            e->fp_q.pop_front();
        }
        std::vector<AVPacket*> pkts;
        int ret = avcodec_send_frame(ctx, frame);
        while (ret >= 0) {
            AVPacket* pkt = av_packet_alloc();
            ret = avcodec_receive_packet(ctx, pkt);
            if (ret == 0) {
                pkts.push_back(pkt);
                continue;
            }
            av_packet_free(&pkt);
            if (ret == AVERROR(EAGAIN) || ret == AVERROR_EOF) ret = 0;
            break;
        }
        av_frame_free(&frame);
        {
            std::lock_guard<std::mutex> lk(e->fp_mu);
            if (ret < 0) {
                for (auto* p : pkts) av_packet_free(&p);
                if (!e->fp_error) {
                    e->fp_error = true;
                    e->fp_error_msg = "fp encode: " + av_errstr(ret);
                }
                // the seq must still resolve or the in-order mux stalls
                e->fp_done[seq] = {};
            } else {
                e->fp_done[seq] = std::move(pkts);
            }
        }
        e->fp_cv_done.notify_all();
    }
    // Drain the context. A sync intra encoder (ffv1) emits one packet per
    // send, so this is normally empty — but any stragglers carry their
    // frame's pts (== seq) and are parked under it for the in-order mux.
    avcodec_send_frame(ctx, nullptr);
    for (;;) {
        AVPacket* pkt = av_packet_alloc();
        if (avcodec_receive_packet(ctx, pkt) != 0) {
            av_packet_free(&pkt);
            break;
        }
        std::lock_guard<std::mutex> lk(e->fp_mu);
        e->fp_done[pkt->pts].push_back(pkt);
    }
    e->fp_cv_done.notify_all();
}

// Mux every finished sequence that is next in order. Caller-thread only
// (the muxer and the audio path share last_dts and the format context).
// Called with fp_mu held via lk; drops the lock around the actual writes.
static int fp_mux_ready_locked(MPEncoder* e, std::unique_lock<std::mutex>& lk) {
    for (;;) {
        auto it = e->fp_done.begin();
        if (it == e->fp_done.end() || it->first != e->fp_next_mux) return 0;
        std::vector<AVPacket*> pkts = std::move(it->second);
        e->fp_done.erase(it);
        lk.unlock();
        int ret = 0;
        for (auto* pkt : pkts) {
            if (ret >= 0) {
                if (pkt->duration == 0) pkt->duration = 1;
                av_packet_rescale_ts(pkt, e->venc->time_base,
                                     e->vstream->time_base);
                pkt->stream_index = e->vstream->index;
                int si = e->vstream->index < 2 ? e->vstream->index : 1;
                if (pkt->dts != AV_NOPTS_VALUE &&
                    e->last_dts[si] != INT64_MIN &&
                    pkt->dts <= e->last_dts[si]) {
                    pkt->dts = e->last_dts[si] + 1;
                    if (pkt->pts != AV_NOPTS_VALUE && pkt->pts < pkt->dts)
                        pkt->pts = pkt->dts;
                }
                if (pkt->dts != AV_NOPTS_VALUE) e->last_dts[si] = pkt->dts;
                ret = av_interleaved_write_frame(e->fmt, pkt);
            }
            av_packet_free(&pkt);
        }
        lk.lock();
        e->fp_next_mux++;
        e->fp_inflight--;
        e->fp_cv_done.notify_all();
        if (ret < 0) {
            if (!e->fp_error) {
                e->fp_error = true;
                e->fp_error_msg = "fp mux: " + av_errstr(ret);
            }
            return ret;
        }
    }
}

// Open an encoder+muxer. Video is configured from explicit arguments plus an
// ffmpeg-style options string "k=v:k=v" applied to the codec context (private
// options included, e.g. preset/crf/x265-params/speed/row-mt). Audio is
// optional (acodec == nullptr to disable).
//   pass: 0 = single pass, 1/2 = two-pass with stats at stats_path.
//   vopts may carry "pc_fp_workers=N" (consumed here, never passed on):
//   frame-parallel encode across N private contexts — intra-only codecs
//   (ffv1 with gop=1 forced, prores).
EXPORT MPEncoder* mp_encoder_open(
    const char* path, const char* vcodec, int width, int height,
    const char* pix_fmt, int fps_num, int fps_den, int64_t bit_rate,
    int64_t min_rate, int64_t max_rate, int64_t buf_size, int gop_size,
    int bframes, int threads, const char* vopts, int pass,
    const char* stats_path, const char* acodec, int sample_rate, int channels,
    int64_t audio_bit_rate, char* err, int errlen) {
    auto* e = new MPEncoder();
    int ret = avformat_alloc_output_context2(&e->fmt, nullptr, nullptr, path);
    if (ret < 0 || !e->fmt) {
        set_err(err, errlen, "alloc_output: " + av_errstr(ret));
        delete e;
        return nullptr;
    }
    const AVCodec* vc = avcodec_find_encoder_by_name(vcodec);
    if (!vc) {
        set_err(err, errlen, std::string("no encoder: ") + vcodec);
        avformat_free_context(e->fmt);
        delete e;
        return nullptr;
    }
    e->venc = avcodec_alloc_context3(vc);
    // the reference's encode/mux commands carry `-strict -2`
    // (lib/downloader.py:859) — also what FFmpeg 4.x needs to open
    // libaom-av1, which it still marks experimental
    e->venc->strict_std_compliance = FF_COMPLIANCE_EXPERIMENTAL;
    e->fmt->strict_std_compliance = FF_COMPLIANCE_EXPERIMENTAL;
    e->venc->width = width;
    e->venc->height = height;
    e->venc->time_base = AVRational{fps_den, fps_num};
    e->venc->framerate = AVRational{fps_num, fps_den};
    AVPixelFormat pf = av_get_pix_fmt(pix_fmt);
    if (pf == AV_PIX_FMT_NONE) {
        set_err(err, errlen, std::string("bad pix_fmt: ") + pix_fmt);
        avcodec_free_context(&e->venc);
        avformat_free_context(e->fmt);
        delete e;
        return nullptr;
    }
    e->venc->pix_fmt = pf;
    if (bit_rate > 0) e->venc->bit_rate = bit_rate;
    if (min_rate > 0) e->venc->rc_min_rate = min_rate;
    if (max_rate > 0) e->venc->rc_max_rate = max_rate;
    if (buf_size > 0) e->venc->rc_buffer_size = (int)buf_size;
    if (gop_size >= 0) e->venc->gop_size = gop_size;
    if (bframes >= 0) e->venc->max_b_frames = bframes;
    if (threads >= 0) e->venc->thread_count = threads;
    if (e->fmt->oformat->flags & AVFMT_GLOBALHEADER)
        e->venc->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;

    if (pass == 1) {
        e->venc->flags |= AV_CODEC_FLAG_PASS1;
        // x264 writes the stats file itself via its private "stats" option
        // (what the ffmpeg CLI's -passlogfile maps to); libvpx-style
        // encoders emit ctx->stats_out instead, which we collect into the
        // file ourselves — LAZILY, on the first stats_out, because an
        // encoder that handles stats fully internally (x265 via
        // x265-params stats=...) never emits stats_out and must not be
        // left an empty junk file.
        if (av_opt_set(e->venc, "stats", stats_path,
                       AV_OPT_SEARCH_CHILDREN) != 0) {
            e->stats_out_path = stats_path;
        }
    } else if (pass == 2) {
        e->venc->flags |= AV_CODEC_FLAG_PASS2;
        if (av_opt_set(e->venc, "stats", stats_path,
                       AV_OPT_SEARCH_CHILDREN) != 0) {
            // a missing file is not an error here: encoders that manage
            // stats fully internally (x265 via x265-params stats=...)
            // leave nothing at stats_path; encoders that truly need
            // stats_in (libvpx) will themselves fail at open/encode
            FILE* f = fopen(stats_path, "r");
            if (f) {
            fseek(f, 0, SEEK_END);
            long sz = ftell(f);
            fseek(f, 0, SEEK_SET);
            e->stats_in.resize(sz);
            if (fread(&e->stats_in[0], 1, sz, f) != (size_t)sz) { /* best effort */ }
            fclose(f);
            e->venc->stats_in = av_strdup(e->stats_in.c_str());
            }
        }
    }

    AVDictionary* opts = nullptr;
    auto fail_cleanup = [&]() {
        av_dict_free(&opts);
        if (e->stats_file) fclose(e->stats_file);
        for (auto*& wc : e->fp_ctxs) avcodec_free_context(&wc);
        e->fp_ctxs.clear();  // worker threads only start once open succeeds
        avcodec_free_context(&e->venc);
        if (e->aenc) avcodec_free_context(&e->aenc);
        swr_free(&e->swr);
        avformat_free_context(e->fmt);
        delete e;
    };
    if (vopts && vopts[0]) {
        ret = av_dict_parse_string(&opts, vopts, "=", ":", 0);
        if (ret < 0) {
            set_err(err, errlen, "bad vopts string");
            fail_cleanup();
            return nullptr;
        }
    }
    // pc_fp_workers is OURS, not an AVOption: consume it before the codec
    // sees the dict. Frame-parallel mode is only sound for intra-only
    // codecs whose frames can be made independent (gate below).
    if (AVDictionaryEntry* fpw = av_dict_get(opts, "pc_fp_workers", nullptr, 0)) {
        e->fp_workers = atoi(fpw->value);
        av_dict_set(&opts, "pc_fp_workers", nullptr, 0);
        // intra-only codecs whose frames are independent by construction:
        // FFV1 (with gop=1 forced below) and ProRes (always all-intra)
        if (e->fp_workers > 0 && vc->id != AV_CODEC_ID_FFV1 &&
            vc->id != AV_CODEC_ID_PRORES) {
            set_err(err, errlen,
                    "pc_fp_workers requires an intra-only codec (ffv1/prores)");
            fail_cleanup();
            return nullptr;
        }
        if (e->fp_workers > 64) e->fp_workers = 64;
        if (e->fp_workers > 0) {
            // every frame a keyframe: resets the range-coder contexts, so
            // frames encoded on different worker contexts are exactly the
            // frames a single gop=1 context would produce
            e->venc->gop_size = 1;
            if (pass != 0) {
                set_err(err, errlen, "pc_fp_workers is single-pass only");
                fail_cleanup();
                return nullptr;
            }
        }
    }
    // entries avcodec_open2 does not consume stay in `opts` and are handed
    // to the muxer below — so e.g. "movflags=+frag_keyframe" in the same
    // option string reaches avformat_write_header (ffmpeg-CLI-like split)
    ret = avcodec_open2(e->venc, vc, &opts);
    if (ret < 0) {
        set_err(err, errlen, "video avcodec_open2: " + av_errstr(ret));
        fail_cleanup();
        return nullptr;
    }
    e->vstream = avformat_new_stream(e->fmt, nullptr);
    e->vstream->time_base = e->venc->time_base;
    avcodec_parameters_from_context(e->vstream->codecpar, e->venc);

    if (e->fp_workers > 0) {
        // one private context per worker, configured IDENTICALLY to venc
        // (same explicit fields, same remaining option string re-parsed
        // per context) — verified below by comparing extradata, since the
        // muxer's codecpar carries venc's FFV1 configuration record and a
        // worker producing a different one would corrupt the stream.
        for (int wi = 0; wi < e->fp_workers; wi++) {
            AVCodecContext* c = avcodec_alloc_context3(vc);
            c->width = width;
            c->height = height;
            c->time_base = e->venc->time_base;
            c->framerate = e->venc->framerate;
            c->pix_fmt = pf;
            c->gop_size = 1;
            c->max_b_frames = 0;
            // rate-control fields mirror venc: for ProRes there is no
            // extradata for the equality check below to compare, so any
            // field NOT copied here would silently diverge from the
            // serial encode
            c->bit_rate = e->venc->bit_rate;
            c->rc_min_rate = e->venc->rc_min_rate;
            c->rc_max_rate = e->venc->rc_max_rate;
            c->rc_buffer_size = e->venc->rc_buffer_size;
            c->thread_count = threads >= 0 ? threads : 1;
            c->flags = e->venc->flags & ~AV_CODEC_FLAG_PASS1 &
                       ~AV_CODEC_FLAG_PASS2;
            AVDictionary* wopts = nullptr;
            if (vopts && vopts[0]) {
                av_dict_parse_string(&wopts, vopts, "=", ":", 0);
                av_dict_set(&wopts, "pc_fp_workers", nullptr, 0);
            }
            ret = avcodec_open2(c, vc, &wopts);
            av_dict_free(&wopts);
            bool extradata_ok =
                ret >= 0 &&
                c->extradata_size == e->venc->extradata_size &&
                (c->extradata_size == 0 ||
                 memcmp(c->extradata, e->venc->extradata,
                        (size_t)c->extradata_size) == 0);
            if (!extradata_ok) {
                set_err(err, errlen,
                        ret < 0 ? "fp worker avcodec_open2: " + av_errstr(ret)
                                : std::string("fp worker extradata mismatch"));
                avcodec_free_context(&c);
                fail_cleanup();
                return nullptr;
            }
            e->fp_ctxs.push_back(c);
        }
    }

    if (acodec && acodec[0]) {
        const AVCodec* ac = avcodec_find_encoder_by_name(acodec);
        if (!ac) {
            set_err(err, errlen, std::string("no audio encoder: ") + acodec);
            fail_cleanup();
            return nullptr;
        }
        e->aenc = avcodec_alloc_context3(ac);
        e->aenc->sample_rate = sample_rate;
        pc_ctx_default_layout(e->aenc, channels);
        e->aenc->sample_fmt = ac->sample_fmts ? ac->sample_fmts[0] : AV_SAMPLE_FMT_S16;
        // prefer s16 when the codec supports it (flac/pcm)
        if (ac->sample_fmts) {
            for (int i = 0; ac->sample_fmts[i] != AV_SAMPLE_FMT_NONE; i++) {
                if (ac->sample_fmts[i] == AV_SAMPLE_FMT_S16) {
                    e->aenc->sample_fmt = AV_SAMPLE_FMT_S16;
                    break;
                }
            }
        }
        e->aenc->time_base = AVRational{1, sample_rate};
        if (audio_bit_rate > 0) e->aenc->bit_rate = audio_bit_rate;
        if (e->fmt->oformat->flags & AVFMT_GLOBALHEADER)
            e->aenc->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
        if ((ret = avcodec_open2(e->aenc, ac, nullptr)) < 0) {
            set_err(err, errlen, "audio avcodec_open2: " + av_errstr(ret));
            fail_cleanup();
            return nullptr;
        }
        e->astream = avformat_new_stream(e->fmt, nullptr);
        e->astream->time_base = e->aenc->time_base;
        avcodec_parameters_from_context(e->astream->codecpar, e->aenc);
        if (e->aenc->sample_fmt != AV_SAMPLE_FMT_S16) {
            ret = pc_swr_setup(&e->swr, e->aenc, 0, e->aenc->sample_fmt,
                               AV_SAMPLE_FMT_S16, sample_rate);
            if (ret < 0 || swr_init(e->swr) < 0) {
                set_err(err, errlen, "audio swr_init failed");
                fail_cleanup();
                return nullptr;
            }
        }
        e->aframe = av_frame_alloc();
    }

    if (!(e->fmt->oformat->flags & AVFMT_NOFILE)) {
        ret = avio_open(&e->fmt->pb, path, AVIO_FLAG_WRITE);
        if (ret < 0) {
            set_err(err, errlen, "avio_open: " + av_errstr(ret));
            fail_cleanup();
            return nullptr;
        }
    }
    // mp4 only: fixed video track timescale, like the reference's
    // `-video_track_timescale 90000` on every SEGMENT encode (its pass
    // commands, lib/ffmpeg.py:851-877). Deliberately NOT applied to the
    // mov muxer: the reference's .mov previews (create_preview) carry no
    // timescale flag. Explicit vopts still override.
    if (e->fmt->oformat && e->fmt->oformat->name &&
        strstr(e->fmt->oformat->name, "mp4") &&
        !av_dict_get(opts, "video_track_timescale", nullptr, 0))
        av_dict_set(&opts, "video_track_timescale", "90000", 0);
    ret = avformat_write_header(e->fmt, &opts);
    if (ret < 0) {
        set_err(err, errlen, "write_header: " + av_errstr(ret));
        fail_cleanup();
        return nullptr;
    }
    av_dict_free(&opts);
    e->header_written = true;
    e->vframe = av_frame_alloc();
    e->vframe->format = pf;
    e->vframe->width = width;
    e->vframe->height = height;
    av_frame_get_buffer(e->vframe, 0);
    for (auto* c : e->fp_ctxs)  // workers start only on a fully-open encoder
        e->fp_threads.emplace_back(fp_worker_main, e, c);
    return e;
}

// Encode one video frame from contiguous plane buffers.
static int write_video_frame(MPEncoder* e, const uint8_t* planes[4],
                             char* err, int errlen) {
    int ret;
    if (e->fp_workers > 0) {
        // frame-parallel path: hand the frame to the worker pool; mux
        // whatever finished, in order, on this (caller) thread. ctypes
        // released the GIL for this call, so workers and the Python
        // producer genuinely overlap.
        //
        // Any error on this path must ALSO latch fp_error: a caller that
        // keeps writing after a -1 would otherwise enqueue later seqs and
        // park on fp_cv_done waiting for a seq that was never enqueued;
        // with the flag latched, every subsequent write fails fast at the
        // fp_error checks below instead of hanging behind the gap.
        auto fp_fail = [&](const std::string& msg) {
            set_err(err, errlen, msg);
            std::lock_guard<std::mutex> flk(e->fp_mu);
            if (!e->fp_error) {
                e->fp_error = true;
                e->fp_error_msg = msg;
            }
            e->fp_cv_done.notify_all();
            return -1;
        };
        AVFrame* f = av_frame_alloc();
        if (!f)
            return fp_fail("fp frame alloc: out of memory");
        f->format = e->vframe->format;
        f->width = e->vframe->width;
        f->height = e->vframe->height;
        if ((ret = av_frame_get_buffer(f, 0)) < 0 ||
            (ret = fill_vframe(f, planes)) < 0) {
            av_frame_free(&f);
            return fp_fail("fp frame alloc/fill: " + av_errstr(ret));
        }
        f->pts = e->vpts++;
        f->pict_type = AV_PICTURE_TYPE_I;
        std::unique_lock<std::mutex> lk(e->fp_mu);
        // backpressure: bound in-flight frames (raw 4K frames are ~12 MB;
        // 2 per worker + 2 keeps every worker fed without unbounded RAM)
        while (!e->fp_error &&
               e->fp_inflight >= 2 * (int64_t)e->fp_workers + 2) {
            if (fp_mux_ready_locked(e, lk) < 0) break;
            if (e->fp_inflight >= 2 * (int64_t)e->fp_workers + 2 &&
                !e->fp_error)
                e->fp_cv_done.wait(lk);
        }
        if (e->fp_error) {
            av_frame_free(&f);
            set_err(err, errlen, e->fp_error_msg);
            return -1;
        }
        e->fp_q.emplace_back(f->pts, f);
        e->fp_inflight++;
        lk.unlock();
        e->fp_cv_work.notify_one();
        lk.lock();
        if (fp_mux_ready_locked(e, lk) < 0 || e->fp_error) {
            set_err(err, errlen, e->fp_error_msg);
            return -1;
        }
        return 0;
    }
    if ((ret = fill_vframe(e->vframe, planes)) < 0) {
        set_err(err, errlen, "frame not writable");
        return -1;
    }
    e->vframe->pts = e->vpts++;
    ret = avcodec_send_frame(e->venc, e->vframe);
    if (ret < 0) {
        set_err(err, errlen, "send_frame: " + av_errstr(ret));
        return -1;
    }
    ret = enc_write_packets(e, e->venc, e->vstream);
    if (ret < 0) {
        set_err(err, errlen, "write packets: " + av_errstr(ret));
        return -1;
    }
    return 0;
}

EXPORT int mp_encoder_write_video(MPEncoder* e, const uint8_t* p0,
                                  const uint8_t* p1, const uint8_t* p2,
                                  const uint8_t* p3, char* err, int errlen) {
    const uint8_t* planes[4] = {p0, p1, p2, p3};
    return write_video_frame(e, planes, err, errlen);
}

// Batched encode: `n` frames from contiguous [N, plane_h, plane_w] plane
// blocks in ONE call (one ctypes crossing, one GIL release per chunk
// instead of per frame). Frame i's plane p is read at
// base_p + i * plane_h[p] * row_bytes[p] of the encoder's open geometry.
// In fp mode the whole chunk streams through the worker pool with the
// caller thread muxing — Python stays out of the loop entirely. Returns n
// on success, < 0 on error (err describes the failing frame).
EXPORT long mp_encoder_write_video_batch(MPEncoder* e, const uint8_t* p0,
                                         const uint8_t* p1, const uint8_t* p2,
                                         const uint8_t* p3, long n, char* err,
                                         int errlen) {
    const uint8_t* bases[4] = {p0, p1, p2, p3};
    const AVPixFmtDescriptor* desc = av_pix_fmt_desc_get(e->venc->pix_fmt);
    if (!desc) {
        set_err(err, errlen, "batch encode: unknown encoder pix_fmt");
        return -1;
    }
    int nplanes = av_pix_fmt_count_planes(e->venc->pix_fmt);
    int bps = desc->comp[0].depth > 8 ? 2 : 1;
    size_t fsize[4] = {0, 0, 0, 0};
    for (int p = 0; p < nplanes && p < 4; p++) {
        int is_chroma = (p == 1 || p == 2);
        int ph = is_chroma
                     ? AV_CEIL_RSHIFT(e->venc->height, desc->log2_chroma_h)
                     : e->venc->height;
        fsize[p] = (size_t)ph * (size_t)plane_row_bytes(
                                    e->venc->pix_fmt, e->venc->width, p, desc,
                                    bps);
    }
    for (long i = 0; i < n; i++) {
        const uint8_t* planes[4];
        for (int p = 0; p < 4; p++)
            planes[p] = bases[p] ? bases[p] + (size_t)i * fsize[p] : nullptr;
        if (write_video_frame(e, planes, err, errlen) < 0) return -1;
    }
    return n;
}

// Append interleaved s16 audio samples (n per channel).
EXPORT int mp_encoder_write_audio(MPEncoder* e, const int16_t* samples, long n,
                                  char* err, int errlen) {
    if (!e->aenc) {
        set_err(err, errlen, "no audio stream configured");
        return -1;
    }
    int channels = pc_ctx_channels(e->aenc);
    e->abuf.insert(e->abuf.end(), samples, samples + (size_t)n * channels);
    int frame_size = e->aenc->frame_size > 0 ? e->aenc->frame_size : 4096;
    while ((long)(e->abuf.size() / channels) >= frame_size) {
        e->aframe->nb_samples = frame_size;
        e->aframe->format = e->aenc->sample_fmt;
        pc_frame_copy_layout(e->aframe, e->aenc);
        av_frame_get_buffer(e->aframe, 0);
        if (e->swr) {
            const uint8_t* in = (const uint8_t*)e->abuf.data();
            swr_convert(e->swr, e->aframe->extended_data, frame_size, &in,
                        frame_size);
        } else {
            memcpy(e->aframe->data[0], e->abuf.data(),
                   (size_t)frame_size * channels * 2);
        }
        e->aframe->pts = e->apts;
        e->apts += frame_size;
        int ret = avcodec_send_frame(e->aenc, e->aframe);
        av_frame_unref(e->aframe);
        if (ret < 0) {
            set_err(err, errlen, "audio send_frame: " + av_errstr(ret));
            return -1;
        }
        ret = enc_write_packets(e, e->aenc, e->astream);
        if (ret < 0) {
            set_err(err, errlen, "audio write packets: " + av_errstr(ret));
            return -1;
        }
        e->abuf.erase(e->abuf.begin(),
                      e->abuf.begin() + (size_t)frame_size * channels);
    }
    return 0;
}

EXPORT int mp_encoder_close(MPEncoder* e, char* err, int errlen) {
    int rc = 0;
    if (!e) return 0;
    if (!e->fp_threads.empty()) {
        // stop the pool: workers drain the queue, flush their contexts,
        // and exit; then mux everything left in order on this thread
        {
            std::lock_guard<std::mutex> lk(e->fp_mu);
            e->fp_stop = true;
        }
        e->fp_cv_work.notify_all();
        for (auto& t : e->fp_threads) t.join();
        e->fp_threads.clear();
        {
            std::unique_lock<std::mutex> lk(e->fp_mu);
            if (fp_mux_ready_locked(e, lk) < 0) rc = -1;
            // anything still parked is unreachable (a gap from a failed
            // frame): free, never write out of order
            for (auto& kv : e->fp_done)
                for (auto* p : kv.second) av_packet_free(&p);
            e->fp_done.clear();
            if (e->fp_error) rc = -1;
        }
        for (auto*& c : e->fp_ctxs) avcodec_free_context(&c);
        e->fp_ctxs.clear();
    }
    if (e->header_written) {
        // flush video (fp mode: venc was never fed frames — its flush is
        // an immediate EOF, harmless)
        avcodec_send_frame(e->venc, nullptr);
        if (enc_write_packets(e, e->venc, e->vstream) < 0) rc = -1;
        if (e->aenc) {
            // flush remaining partial audio frame
            int channels = pc_ctx_channels(e->aenc);
            long rem = e->abuf.size() / channels;
            if (rem > 0) {
                e->aframe->nb_samples = (int)rem;
                e->aframe->format = e->aenc->sample_fmt;
                pc_frame_copy_layout(e->aframe, e->aenc);
                av_frame_get_buffer(e->aframe, 0);
                if (e->swr) {
                    const uint8_t* in = (const uint8_t*)e->abuf.data();
                    swr_convert(e->swr, e->aframe->extended_data, (int)rem, &in,
                                (int)rem);
                } else {
                    memcpy(e->aframe->data[0], e->abuf.data(),
                           (size_t)rem * channels * 2);
                }
                e->aframe->pts = e->apts;
                avcodec_send_frame(e->aenc, e->aframe);
                av_frame_unref(e->aframe);
            }
            avcodec_send_frame(e->aenc, nullptr);
            if (enc_write_packets(e, e->aenc, e->astream) < 0) rc = -1;
        }
        if (e->venc->stats_out && !e->stats_out_path.empty()) {
            if (!e->stats_file)
                e->stats_file = fopen(e->stats_out_path.c_str(), "w");
            if (e->stats_file) fputs(e->venc->stats_out, e->stats_file);
        }
        av_write_trailer(e->fmt);
    }
    if (e->stats_file) fclose(e->stats_file);
    if (e->fmt && !(e->fmt->oformat->flags & AVFMT_NOFILE) && e->fmt->pb)
        avio_closep(&e->fmt->pb);
    av_frame_free(&e->vframe);
    av_frame_free(&e->aframe);
    swr_free(&e->swr);
    avcodec_free_context(&e->venc);
    if (e->aenc) avcodec_free_context(&e->aenc);
    avformat_free_context(e->fmt);
    if (rc < 0)
        set_err(err, errlen, e->fp_error_msg.empty()
                                 ? "failures while flushing encoder"
                                 : e->fp_error_msg);
    delete e;
    return rc;
}

// ---------------------------------------------------------------------------
// swscale (CPU reference for kernel golden tests + host fallback; the TPU
// kernels in ops/resize.py are validated against this output)
// ---------------------------------------------------------------------------

// flags: 4 = bicubic (SWS_BICUBIC), 0x200 = lanczos (SWS_LANCZOS)
EXPORT int mp_sws_scale_plane(const uint8_t* src, int sw, int sh, uint8_t* dst,
                              int dw, int dh, int flags, double param0,
                              double param1, char* err, int errlen) {
    double params[2] = {param0, param1};
    SwsContext* ctx = sws_getContext(sw, sh, AV_PIX_FMT_GRAY8, dw, dh,
                                     AV_PIX_FMT_GRAY8, flags, nullptr, nullptr,
                                     (param0 != 0 || param1 != 0) ? params : nullptr);
    if (!ctx) {
        set_err(err, errlen, "sws_getContext failed");
        return -1;
    }
    const uint8_t* src_planes[1] = {src};
    int src_stride[1] = {sw};
    uint8_t* dst_planes[1] = {dst};
    int dst_stride[1] = {dw};
    sws_scale(ctx, src_planes, src_stride, 0, sh, dst_planes, dst_stride);
    sws_freeContext(ctx);
    return 0;
}

// Batched single-plane scale: n gray8 frames from one contiguous
// [N, sh, sw] block into a contiguous [N, dh, dw] block through ONE
// SwsContext (filter tables built once per chunk, one ctypes crossing,
// one GIL release). This is the CPU-backend resize fast path
// (ops/resize.resize_frames): with SWS_ACCURATE_RND|SWS_BITEXACT it runs
// the same deterministic C reference the XLA _swscale_exact path
// emulates — identical bytes, SIMD-free but still ~10x the XLA
// emulation's throughput on the host.
EXPORT int mp_sws_scale_frames(const uint8_t* src, int sw, int sh,
                               uint8_t* dst, int dw, int dh, long n,
                               int flags, char* err, int errlen) {
    SwsContext* ctx = sws_getContext(sw, sh, AV_PIX_FMT_GRAY8, dw, dh,
                                     AV_PIX_FMT_GRAY8, flags, nullptr,
                                     nullptr, nullptr);
    if (!ctx) {
        set_err(err, errlen, "sws_getContext failed");
        return -1;
    }
    for (long i = 0; i < n; i++) {
        const uint8_t* src_planes[1] = {src + (size_t)i * sw * sh};
        int src_stride[1] = {sw};
        uint8_t* dst_planes[1] = {dst + (size_t)i * dw * dh};
        int dst_stride[1] = {dw};
        sws_scale(ctx, src_planes, src_stride, 0, sh, dst_planes, dst_stride);
    }
    sws_freeContext(ctx);
    return 0;
}

// Full-frame planar YUV rescale through swscale (the reference's
// `scale=W:H:flags=bicubic/lanczos` filter, lib/ffmpeg.py:948, :1037).
EXPORT int mp_sws_scale_yuv(const uint8_t* sy, const uint8_t* su,
                            const uint8_t* sv, int sw, int sh,
                            const char* src_fmt, uint8_t* dy, uint8_t* du,
                            uint8_t* dv, int dw, int dh, const char* dst_fmt,
                            int flags, char* err, int errlen) {
    AVPixelFormat spf = av_get_pix_fmt(src_fmt);
    AVPixelFormat dpf = av_get_pix_fmt(dst_fmt);
    if (spf == AV_PIX_FMT_NONE || dpf == AV_PIX_FMT_NONE) {
        set_err(err, errlen, "bad pix fmt");
        return -1;
    }
    SwsContext* ctx = sws_getContext(sw, sh, spf, dw, dh, dpf, flags, nullptr,
                                     nullptr, nullptr);
    if (!ctx) {
        set_err(err, errlen, "sws_getContext failed");
        return -1;
    }
    const AVPixFmtDescriptor* sdesc = av_pix_fmt_desc_get(spf);
    const AVPixFmtDescriptor* ddesc = av_pix_fmt_desc_get(dpf);
    // this entry point's contract is PLANAR YUV (or single-component)
    // buffers on both sides — the Python wrapper sizes dst planes as
    // [h, w]; a packed multi-component format would need 2x-wide rows
    // and silently overrun them, so reject it loudly instead
    auto planar_ok = [](const AVPixFmtDescriptor* de) {
        // FULLY planar: one component per plane, checked by comparing
        // the components' plane indices (deliberately NOT the PLANAR
        // flag — nv12/p010 set it yet interleave UV in one plane, which
        // would overrun [h, w]-sized chroma buffers like packed formats).
        if (de->nb_components == 1) return true;
        for (int i = 1; i < de->nb_components; i++)
            if (de->comp[i].plane == de->comp[0].plane) return false;
        return de->nb_components <= 3 &&
               de->comp[1].plane != de->comp[2].plane;
    };
    if (!planar_ok(sdesc) || !planar_ok(ddesc)) {
        sws_freeContext(ctx);
        set_err(err, errlen,
                "sws_scale_yuv supports planar formats only (packed rows "
                "would overrun the caller's [h, w] plane buffers)");
        return -1;
    }
    // odd dims on a chroma-subsampled axis: swscale uses ceil chroma
    // widths while the Python wrapper allocates floor-sized planes — a
    // 1-byte-per-row overrun. The chain's domain model enforces even
    // dims (config/domain.py:51); reject loudly rather than corrupt.
    if ((sdesc->log2_chroma_w && (sw & 1)) ||
        (sdesc->log2_chroma_h && (sh & 1)) ||
        (ddesc->log2_chroma_w && (dw & 1)) ||
        (ddesc->log2_chroma_h && (dh & 1))) {
        sws_freeContext(ctx);
        set_err(err, errlen,
                "sws_scale_yuv: odd dimension on a chroma-subsampled axis "
                "(chain invariant: even dims)");
        return -1;
    }
    int sbps = sdesc->comp[0].depth > 8 ? 2 : 1;
    int dbps = ddesc->comp[0].depth > 8 ? 2 : 1;
    // plane_row_bytes == pw*bps for every planar format; keeping the
    // shared helper here means one definition of row geometry repo-wide
    const uint8_t* src_planes[4] = {sy, su, sv, nullptr};
    int src_stride[4] = {plane_row_bytes(spf, sw, 0, sdesc, sbps),
                         plane_row_bytes(spf, sw, 1, sdesc, sbps),
                         plane_row_bytes(spf, sw, 2, sdesc, sbps), 0};
    uint8_t* dst_planes[4] = {dy, du, dv, nullptr};
    int dst_stride[4] = {plane_row_bytes(dpf, dw, 0, ddesc, dbps),
                         plane_row_bytes(dpf, dw, 1, ddesc, dbps),
                         plane_row_bytes(dpf, dw, 2, ddesc, dbps), 0};
    sws_scale(ctx, src_planes, src_stride, 0, sh, dst_planes, dst_stride);
    sws_freeContext(ctx);
    return 0;
}

// ---------------------------------------------------------------------------
// Bitstream extraction for exact frame-size parsing (reference
// lib/get_framesize.py:54-77 remuxes; the byte parsing itself is vectorized
// numpy in io/framesizes.py)
// ---------------------------------------------------------------------------

// Run the named bitstream filter (h264_mp4toannexb / hevc_mp4toannexb) over
// the video stream and write raw filtered bytes to out_path.
EXPORT int mp_extract_annexb(const char* path, const char* bsf_name,
                             const char* out_path, char* err, int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    if ((ret = avformat_find_stream_info(fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    int sidx = av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
    if (sidx < 0) {
        set_err(err, errlen, "no video stream");
        avformat_close_input(&fmt);
        return -1;
    }
    const AVBitStreamFilter* bsf = av_bsf_get_by_name(bsf_name);
    if (!bsf) {
        set_err(err, errlen, std::string("no bsf: ") + bsf_name);
        avformat_close_input(&fmt);
        return -1;
    }
    AVBSFContext* bctx = nullptr;
    av_bsf_alloc(bsf, &bctx);
    avcodec_parameters_copy(bctx->par_in, fmt->streams[sidx]->codecpar);
    bctx->time_base_in = fmt->streams[sidx]->time_base;
    if ((ret = av_bsf_init(bctx)) < 0) {
        set_err(err, errlen, "bsf_init: " + av_errstr(ret));
        av_bsf_free(&bctx);
        avformat_close_input(&fmt);
        return -1;
    }
    FILE* out = fopen(out_path, "wb");
    if (!out) {
        set_err(err, errlen, "cannot open output");
        av_bsf_free(&bctx);
        avformat_close_input(&fmt);
        return -1;
    }
    AVPacket* pkt = av_packet_alloc();
    while (av_read_frame(fmt, pkt) >= 0) {
        if (pkt->stream_index == sidx) {
            if (av_bsf_send_packet(bctx, pkt) == 0) {
                AVPacket* fpkt = av_packet_alloc();
                while (av_bsf_receive_packet(bctx, fpkt) == 0) {
                    fwrite(fpkt->data, 1, fpkt->size, out);
                    av_packet_unref(fpkt);
                }
                av_packet_free(&fpkt);
            }
        } else {
            av_packet_unref(pkt);
        }
    }
    av_bsf_send_packet(bctx, nullptr);
    AVPacket* fpkt = av_packet_alloc();
    while (av_bsf_receive_packet(bctx, fpkt) == 0) {
        fwrite(fpkt->data, 1, fpkt->size, out);
        av_packet_unref(fpkt);
    }
    av_packet_free(&fpkt);
    av_packet_free(&pkt);
    fclose(out);
    av_bsf_free(&bctx);
    avformat_close_input(&fmt);
    return 0;
}

// Write the video stream as an IVF file (for VP9 exact frame sizes,
// reference get_framesize.py:87-141 parses IVF).
EXPORT int mp_extract_ivf(const char* path, const char* out_path, char* err,
                          int errlen) {
    AVFormatContext* fmt = nullptr;
    int ret = avformat_open_input(&fmt, path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, "open_input: " + av_errstr(ret));
        return -1;
    }
    if ((ret = avformat_find_stream_info(fmt, nullptr)) < 0) {
        set_err(err, errlen, "find_stream_info: " + av_errstr(ret));
        avformat_close_input(&fmt);
        return -1;
    }
    int sidx = av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
    if (sidx < 0) {
        set_err(err, errlen, "no video stream");
        avformat_close_input(&fmt);
        return -1;
    }
    AVStream* st = fmt->streams[sidx];
    AVCodecParameters* par = st->codecpar;
    FILE* out = fopen(out_path, "wb");
    if (!out) {
        set_err(err, errlen, "cannot open output");
        avformat_close_input(&fmt);
        return -1;
    }
    // IVF header (32 bytes)
    uint8_t hdr[32] = {0};
    memcpy(hdr, "DKIF", 4);
    hdr[4] = 0; hdr[5] = 0;       // version
    hdr[6] = 32; hdr[7] = 0;      // header size
    const char* fourcc = par->codec_id == AV_CODEC_ID_VP9   ? "VP90"
                         : par->codec_id == AV_CODEC_ID_VP8 ? "VP80"
                                                            : "AV01";
    memcpy(hdr + 8, fourcc, 4);
    hdr[12] = par->width & 0xff; hdr[13] = (par->width >> 8) & 0xff;
    hdr[14] = par->height & 0xff; hdr[15] = (par->height >> 8) & 0xff;
    uint32_t tb_den = (uint32_t)st->time_base.den, tb_num = (uint32_t)st->time_base.num;
    memcpy(hdr + 16, &tb_den, 4);
    memcpy(hdr + 20, &tb_num, 4);
    fwrite(hdr, 1, 32, out);
    AVPacket* pkt = av_packet_alloc();
    uint32_t nframes = 0;
    while (av_read_frame(fmt, pkt) >= 0) {
        if (pkt->stream_index == sidx) {
            uint8_t fh[12];
            uint32_t sz = (uint32_t)pkt->size;
            uint64_t pts = pkt->pts != AV_NOPTS_VALUE ? (uint64_t)pkt->pts : nframes;
            memcpy(fh, &sz, 4);
            memcpy(fh + 4, &pts, 8);
            fwrite(fh, 1, 12, out);
            fwrite(pkt->data, 1, pkt->size, out);
            nframes++;
        }
        av_packet_unref(pkt);
    }
    av_packet_free(&pkt);
    // back-patch frame count
    fseek(out, 24, SEEK_SET);
    fwrite(&nframes, 4, 1, out);
    fclose(out);
    avformat_close_input(&fmt);
    return 0;
}

// ---------------------------------------------------------------------------
// Stream-copy remux: video stream from `video_path` plus (optionally) the
// audio stream from `audio_path` into `out_path`, no transcoding — the
// native replacement for the reference's `ffmpeg -i V [-i A] -c copy OUT`
// reassembly commands (reference lib/downloader.py:786-871). `audio_path`
// may be NULL/empty; when equal to `video_path` both streams are taken from
// the one file.

// One input being merged into the output: reads ahead a single packet of
// its wanted stream so the remux loop can always write the earliest-dts
// packet next (proper interleaving without buffering whole streams).
struct RemuxSource {
    AVFormatContext* ctx = nullptr;
    int in_idx = -1;
    int out_idx = -1;
    AVPacket* pkt = nullptr;
    bool have = false;
    bool eof = false;

    // returns 0 ok / <0 error; sets have/eof
    int advance() {
        have = false;
        int ret;
        while ((ret = av_read_frame(ctx, pkt)) >= 0) {
            if (pkt->stream_index == in_idx) {
                have = true;
                return 0;
            }
            av_packet_unref(pkt);
        }
        if (ret == AVERROR_EOF) {
            eof = true;
            return 0;
        }
        return ret;
    }

    double next_time() const {
        int64_t ts = pkt->dts != AV_NOPTS_VALUE ? pkt->dts : pkt->pts;
        if (ts == AV_NOPTS_VALUE) return 0.0;
        return ts * av_q2d(ctx->streams[in_idx]->time_base);
    }
};

static int remux_merge(RemuxSource* sources, int n_sources,
                       AVFormatContext* out, char* err, int errlen) {
    for (int i = 0; i < n_sources; i++) {
        int ret = sources[i].advance();
        if (ret < 0) {
            set_err(err, errlen, "read packet: " + av_errstr(ret));
            return -1;
        }
    }
    for (;;) {
        RemuxSource* next = nullptr;
        for (int i = 0; i < n_sources; i++) {
            RemuxSource& s = sources[i];
            if (!s.have) continue;
            if (!next || s.next_time() < next->next_time()) next = &s;
        }
        if (!next) break;  // all sources drained
        AVPacket* pkt = next->pkt;
        AVRational in_tb = next->ctx->streams[next->in_idx]->time_base;
        pkt->stream_index = next->out_idx;
        av_packet_rescale_ts(pkt, in_tb, out->streams[next->out_idx]->time_base);
        pkt->pos = -1;
        int ret = av_interleaved_write_frame(out, pkt);
        if (ret < 0) {
            set_err(err, errlen, "write packet: " + av_errstr(ret));
            return -1;
        }
        if ((ret = next->advance()) < 0) {
            set_err(err, errlen, "read packet: " + av_errstr(ret));
            return -1;
        }
    }
    return 0;
}

EXPORT int mp_remux(const char* video_path, const char* audio_path,
                    const char* out_path, char* err, int errlen) {
    AVFormatContext* vin = nullptr;
    AVFormatContext* ain = nullptr;
    AVFormatContext* out = nullptr;
    int ret = avformat_open_input(&vin, video_path, nullptr, nullptr);
    if (ret < 0) {
        set_err(err, errlen, std::string(video_path) + ": " + av_errstr(ret));
        return -1;
    }
    auto fail = [&](const std::string& msg) {
        set_err(err, errlen, msg);
        if (vin) avformat_close_input(&vin);
        if (ain) avformat_close_input(&ain);
        if (out) {
            if (!(out->oformat->flags & AVFMT_NOFILE) && out->pb) avio_closep(&out->pb);
            avformat_free_context(out);
        }
        return -1;
    };
    if ((ret = avformat_find_stream_info(vin, nullptr)) < 0)
        return fail("stream info: " + av_errstr(ret));
    int v_idx = av_find_best_stream(vin, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
    if (v_idx < 0) return fail(std::string(video_path) + ": no video stream");

    bool same_file = audio_path && *audio_path && !strcmp(audio_path, video_path);
    int a_idx = -1;
    if (audio_path && *audio_path) {
        if (same_file) {
            a_idx = av_find_best_stream(vin, AVMEDIA_TYPE_AUDIO, -1, -1, nullptr, 0);
        } else {
            if ((ret = avformat_open_input(&ain, audio_path, nullptr, nullptr)) < 0)
                return fail(std::string(audio_path) + ": " + av_errstr(ret));
            if ((ret = avformat_find_stream_info(ain, nullptr)) < 0)
                return fail("audio stream info: " + av_errstr(ret));
            a_idx = av_find_best_stream(ain, AVMEDIA_TYPE_AUDIO, -1, -1, nullptr, 0);
        }
        if (a_idx < 0) return fail(std::string(audio_path) + ": no audio stream");
    }

    if ((ret = avformat_alloc_output_context2(&out, nullptr, nullptr, out_path)) < 0)
        return fail(std::string(out_path) + ": " + av_errstr(ret));
    // reference reassembly commands pass `-strict -2` (lib/downloader.py:859,
    // :868) — e.g. FLAC-in-MP4 is gated behind experimental compliance
    out->strict_std_compliance = FF_COMPLIANCE_EXPERIMENTAL;

    AVStream* vs = avformat_new_stream(out, nullptr);
    if (!vs || avcodec_parameters_copy(vs->codecpar, vin->streams[v_idx]->codecpar) < 0)
        return fail("copy video params failed");
    vs->codecpar->codec_tag = 0;
    vs->time_base = vin->streams[v_idx]->time_base;

    if (a_idx >= 0) {
        AVFormatContext* asrc = same_file ? vin : ain;
        AVStream* as = avformat_new_stream(out, nullptr);
        if (!as || avcodec_parameters_copy(as->codecpar, asrc->streams[a_idx]->codecpar) < 0)
            return fail("copy audio params failed");
        as->codecpar->codec_tag = 0;
        as->time_base = asrc->streams[a_idx]->time_base;
    }

    if (!(out->oformat->flags & AVFMT_NOFILE) &&
        (ret = avio_open(&out->pb, out_path, AVIO_FLAG_WRITE)) < 0)
        return fail(std::string(out_path) + ": " + av_errstr(ret));
    if ((ret = avformat_write_header(out, nullptr)) < 0)
        return fail("write header: " + av_errstr(ret));

    if (same_file && a_idx >= 0) {
        // single pass over the one input, copying both streams
        AVPacket* pkt = av_packet_alloc();
        while ((ret = av_read_frame(vin, pkt)) >= 0) {
            int out_idx = pkt->stream_index == v_idx ? 0
                        : pkt->stream_index == a_idx ? 1 : -1;
            if (out_idx < 0) {
                av_packet_unref(pkt);
                continue;
            }
            AVRational in_tb = vin->streams[pkt->stream_index]->time_base;
            pkt->stream_index = out_idx;
            av_packet_rescale_ts(pkt, in_tb, out->streams[out_idx]->time_base);
            pkt->pos = -1;
            if ((ret = av_interleaved_write_frame(out, pkt)) < 0) {
                av_packet_free(&pkt);
                return fail("write packet: " + av_errstr(ret));
            }
        }
        av_packet_free(&pkt);
        if (ret != AVERROR_EOF) return fail("read packet: " + av_errstr(ret));
    } else {
        RemuxSource sources[2];
        int n_sources = 0;
        AVPacket* p0 = av_packet_alloc();
        AVPacket* p1 = av_packet_alloc();
        sources[n_sources++] = RemuxSource{vin, v_idx, 0, p0};
        if (a_idx >= 0) sources[n_sources++] = RemuxSource{ain, a_idx, 1, p1};
        int ret2 = remux_merge(sources, n_sources, out, err, errlen);
        av_packet_free(&p0);
        av_packet_free(&p1);
        if (ret2 < 0) return fail(err && err[0] ? err : "remux merge failed");
    }

    if ((ret = av_write_trailer(out)) < 0)
        return fail("write trailer: " + av_errstr(ret));
    avformat_close_input(&vin);
    if (ain) avformat_close_input(&ain);
    if (!(out->oformat->flags & AVFMT_NOFILE) && out->pb) avio_closep(&out->pb);
    avformat_free_context(out);
    return 0;
}


// ---------------------------------------------------------------------------
// Sequential stream-copy concat: the video streams of `paths[0..n)` into
// `out_path`, no transcoding, timestamps offset so segment k starts where
// k-1 ended — the native equivalent of the reference's concat demuxer pass
// (reference lib/ffmpeg.py:1094-1100, `ffmpeg -f concat -c copy`). All
// inputs must share codec parameters (the per-segment AVPVS tmp renders
// do: same encoder, geometry, rate). Audio is merged separately via
// mp_remux.

EXPORT int mp_concat(const char* const* paths, int n, const char* out_path,
                     char* err, int errlen) {
    if (n <= 0) {
        set_err(err, errlen, "mp_concat: no inputs");
        return -1;
    }
    AVFormatContext* out = nullptr;
    int ret = avformat_alloc_output_context2(&out, nullptr, nullptr, out_path);
    if (ret < 0 || !out) {
        set_err(err, errlen, std::string(out_path) + ": " + av_errstr(ret));
        return -1;
    }
    auto fail = [&](const std::string& msg) {
        set_err(err, errlen, msg);
        if (out) {
            if (!(out->oformat->flags & AVFMT_NOFILE) && out->pb) avio_closep(&out->pb);
            avformat_free_context(out);
        }
        return -1;
    };

    AVStream* vs = nullptr;
    int64_t offset = 0;           // in the OUTPUT stream's time_base
    AVRational out_tb{0, 1};
    AVPacket* pkt = av_packet_alloc();

    for (int i = 0; i < n; i++) {
        AVFormatContext* in = nullptr;
        if ((ret = avformat_open_input(&in, paths[i], nullptr, nullptr)) < 0) {
            av_packet_free(&pkt);
            return fail(std::string(paths[i]) + ": " + av_errstr(ret));
        }
        if ((ret = avformat_find_stream_info(in, nullptr)) < 0) {
            avformat_close_input(&in);
            av_packet_free(&pkt);
            return fail("stream info: " + av_errstr(ret));
        }
        int v_idx = av_find_best_stream(in, AVMEDIA_TYPE_VIDEO, -1, -1, nullptr, 0);
        if (v_idx < 0) {
            avformat_close_input(&in);
            av_packet_free(&pkt);
            return fail(std::string(paths[i]) + ": no video stream");
        }
        AVStream* src = in->streams[v_idx];
        if (i == 0) {
            vs = avformat_new_stream(out, nullptr);
            if (!vs || avcodec_parameters_copy(vs->codecpar, src->codecpar) < 0) {
                avformat_close_input(&in);
                av_packet_free(&pkt);
                return fail("copy video params failed");
            }
            vs->codecpar->codec_tag = 0;
            vs->time_base = src->time_base;
            vs->avg_frame_rate = src->avg_frame_rate;
            out_tb = src->time_base;
            if (!(out->oformat->flags & AVFMT_NOFILE) &&
                (ret = avio_open(&out->pb, out_path, AVIO_FLAG_WRITE)) < 0) {
                avformat_close_input(&in);
                av_packet_free(&pkt);
                return fail(std::string(out_path) + ": " + av_errstr(ret));
            }
            if ((ret = avformat_write_header(out, nullptr)) < 0) {
                avformat_close_input(&in);
                av_packet_free(&pkt);
                return fail("write header: " + av_errstr(ret));
            }
            // the muxer may have adjusted the stream time_base
            out_tb = out->streams[0]->time_base;
        }
        // per-frame duration fallback when packets carry none
        AVRational fr = src->avg_frame_rate.num ? src->avg_frame_rate
                                                : src->r_frame_rate;
        int64_t frame_dur = fr.num
            ? av_rescale_q(1, AVRational{fr.den, fr.num}, out_tb)
            : 0;
        int64_t seg_end = offset;
        while ((ret = av_read_frame(in, pkt)) >= 0) {
            if (pkt->stream_index != v_idx) {
                av_packet_unref(pkt);
                continue;
            }
            av_packet_rescale_ts(pkt, src->time_base, out_tb);
            int64_t dur = pkt->duration > 0 ? pkt->duration : frame_dur;
            if (pkt->pts != AV_NOPTS_VALUE) pkt->pts += offset;
            if (pkt->dts != AV_NOPTS_VALUE) pkt->dts += offset;
            int64_t end = (pkt->pts != AV_NOPTS_VALUE ? pkt->pts
                           : pkt->dts != AV_NOPTS_VALUE ? pkt->dts : seg_end)
                          + dur;
            if (end > seg_end) seg_end = end;
            pkt->stream_index = 0;
            pkt->pos = -1;
            if ((ret = av_interleaved_write_frame(out, pkt)) < 0) {
                avformat_close_input(&in);
                av_packet_free(&pkt);
                return fail("write packet: " + av_errstr(ret));
            }
        }
        avformat_close_input(&in);
        if (ret != AVERROR_EOF) {
            av_packet_free(&pkt);
            return fail("read packet: " + av_errstr(ret));
        }
        offset = seg_end;
    }
    av_packet_free(&pkt);
    if ((ret = av_write_trailer(out)) < 0)
        return fail("write trailer: " + av_errstr(ret));
    if (!(out->oformat->flags & AVFMT_NOFILE) && out->pb) avio_closep(&out->pb);
    avformat_free_context(out);
    return 0;
}

EXPORT const char* mp_version() {
    static char buf[128];
    snprintf(buf, sizeof(buf), "lavf %d.%d lavc %d.%d sws %d.%d",
             LIBAVFORMAT_VERSION_MAJOR, LIBAVFORMAT_VERSION_MINOR,
             LIBAVCODEC_VERSION_MAJOR, LIBAVCODEC_VERSION_MINOR,
             LIBSWSCALE_VERSION_MAJOR, LIBSWSCALE_VERSION_MINOR);
    return buf;
}
