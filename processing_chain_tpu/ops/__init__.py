from . import fps, metrics, overlay, pad, pixfmt, resize, siti

__all__ = ["fps", "metrics", "overlay", "pad", "pixfmt", "resize", "siti"]
