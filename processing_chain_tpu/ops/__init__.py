from . import fps, metrics, overlay, pad, pixfmt, resize, siti

# Import the Pallas TPU kernels at package-import time, NOT lazily: the
# pallas.tpu import registers MLIR lowerings for platform "tpu", and in a
# CPU-only process (tests, virtual-mesh runs) that registration is only
# accepted while JAX's backends are still uninitialized. A deferred import
# after the first jax.devices()/jit call raises NotImplementedError
# ("unknown platform tpu") and would make resize method="fused" (and its
# interpreter-mode tests) fail depending on what ran first.
from . import pallas_kernels  # noqa: E402  (import-order is the point)

__all__ = [
    "fps", "metrics", "overlay", "pad", "pallas_kernels", "pixfmt",
    "resize", "siti",
]
