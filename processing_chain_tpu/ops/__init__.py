from . import fps, metrics, overlay, pad, pallas_kernels, pixfmt, resize, siti

__all__ = [
    "fps", "metrics", "overlay", "pad", "pallas_kernels", "pixfmt",
    "resize", "siti",
]
