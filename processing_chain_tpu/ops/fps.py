"""Frame-rate conversion as gather index plans.

Parity targets: the reference's fps spec grammar (lib/ffmpeg.py:321-396 —
number, fraction, "original", "auto", "50/60", "24/25/30") and its
hand-built `select=` drop tables for each supported ratio
(lib/ffmpeg.py:806-832). Where the reference emits an ffmpeg select
expression evaluated per frame, we emit the equivalent index array once on
host; on device the conversion is a single gather over the frame axis.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Optional

import numpy as np

from ..config.errors import ConfigError

#: the reference's select tables, keyed by int(100 * dst/src) — each entry is
#: the set of source-frame phases kept per cycle (cycle_len, kept_phases)
#: (lib/ffmpeg.py:806-832). E.g. 60→24 keeps frames 0 and 3 of every 5.
_SELECT_TABLES: dict[float, tuple[int, tuple[int, ...]]] = {
    50.0: (2, (0,)),                    # mod(n+1,2): keeps even n
    40.0: (5, (0, 3)),                  # 60->24
    33.0: (3, (0,)),                    # 60->20, 24->8
    25.0: (4, (0,)),                    # 60->15, 24->6
    80.0: (5, (0, 1, 2, 3)),            # 30->24: mod(n+1,5) keeps n%5 != 4
    30.0: (10, (0, 3, 7)),              # 50->15
    60.0: (5, (0, 2, 3)),               # 25->15
    62.5: (8, (0, 2, 3, 5, 6)),         # 24->15
}


def resolve_fps_spec(fps_spec, src_fps: float) -> Optional[float]:
    """The reference's fps grammar (lib/ffmpeg.py:321-396). Returns the
    target fps, or None for keep-as-is."""
    if fps_spec in ("original", "auto"):
        return None
    if fps_spec == "24/25/30":
        if src_fps in (24, 25, 30):
            return None
        if src_fps == 50:
            return 25.0
        if src_fps in (60, 120):
            return 30.0
        raise ConfigError(f"unsupported SRC frame rate {src_fps} for 24/25/30")
    if fps_spec == "50/60":
        if src_fps in (50, 60):
            return None
        if src_fps < 50:
            raise ConfigError(f"fps requested as 50/60 but SRC has only {src_fps}")
        if src_fps == 120:
            return 60.0
        raise ConfigError(f"unsupported SRC frame rate {src_fps} for 50/60")
    if "/" in str(fps_spec):
        return src_fps * float(Fraction(str(fps_spec)))
    # the reference coerces with int() (lib/ffmpeg.py:388), silently
    # flooring a numeric 29.97 to 29 — a do-not-copy bug; non-integer
    # specs keep their value here (integer specs behave identically)
    return float(fps_spec)


def select_table(src_fps: float, dst_fps: float) -> tuple[int, tuple[int, ...]]:
    """(cycle_len, kept_phases) of the reference's drop table for
    src_fps → dst_fps; raises ConfigError for unsupported ratios exactly
    like the reference (lib/ffmpeg.py:827-829)."""
    perc = 100.0 * dst_fps / src_fps
    key = perc if perc in _SELECT_TABLES else float(int(perc))
    if key not in _SELECT_TABLES:
        raise ConfigError(
            f"Frame rate conversion from {src_fps} to {dst_fps} is not supported"
        )
    return _SELECT_TABLES[key]


def select_indices(n_frames: int, src_fps: float, dst_fps: float) -> np.ndarray:
    """Indices of source frames to keep for src_fps → dst_fps, using the
    reference's drop tables."""
    if dst_fps == src_fps:
        return np.arange(n_frames)
    cycle, phases = select_table(src_fps, dst_fps)
    n = np.arange(n_frames)
    mask = np.isin(n % cycle, phases)
    return n[mask]


def stream_select(chunks, src_fps: float, dst_fps: float):
    """Streaming select_indices: the drop mask is periodic in the SOURCE
    frame index, so it applies chunk-by-chunk with a running offset —
    O(chunk) memory for arbitrarily long windows. Chunks are per-plane
    [T, H, W] stacks; emitted chunks shrink to the kept frames (empty ones
    are dropped)."""
    if dst_fps == src_fps:
        yield from chunks
        return
    cycle, phases = select_table(src_fps, dst_fps)
    off = 0
    for chunk in chunks:
        n = chunk[0].shape[0]
        mask = np.isin((np.arange(n) + off) % cycle, phases)
        off += n
        if mask.any():
            yield [p[mask] for p in chunk]


def fps_resample_indices(n_frames: int, src_fps: float, dst_fps: float) -> np.ndarray:
    """General ffmpeg `fps=` filter semantics (used where the reference
    applies a bare fps filter, e.g. AVPVS -z/-f60 paths): output frame k at
    time k/dst_fps duplicates/drops to the last source frame with
    pts <= k/dst_fps (+ half-tick rounding)."""
    duration = n_frames / src_fps
    n_out = int(round(duration * dst_fps))
    t_out = np.arange(n_out) / dst_fps
    idx = np.floor(t_out * src_fps + 0.5).astype(np.int64)
    return np.clip(idx, 0, n_frames - 1)
