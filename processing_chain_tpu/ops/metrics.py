"""Full-reference per-frame quality metrics on device: PSNR and SSIM.

The reference builds libvmaf into its ffmpeg (Dockerfile:38-43,
install_ffmpeg.sh:61) though chain code never invokes it; BASELINE config 4
calls for per-frame PSNR/SSIM feature extraction vs SRC as part of the long
test. vmapped over the frame axis; inputs are luma (or any single plane).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def psnr_frame(ref: jnp.ndarray, deg: jnp.ndarray, peak: float = 255.0) -> jnp.ndarray:
    """PSNR of one [H, W] plane pair, dB (inf-free: clamped to 100 dB for
    identical frames, ffmpeg's psnr filter convention caps similarly)."""
    r = ref.astype(jnp.float32)
    d = deg.astype(jnp.float32)
    mse = jnp.mean((r - d) ** 2)
    psnr = 10.0 * jnp.log10((peak * peak) / jnp.maximum(mse, 1e-10))
    return jnp.minimum(psnr, 100.0)


@jax.jit
def psnr_frames(ref: jnp.ndarray, deg: jnp.ndarray) -> jnp.ndarray:
    """Per-frame PSNR for [T, H, W] pairs."""
    return jax.vmap(psnr_frame)(ref, deg)


def _gaussian_kernel(size: int = 11, sigma: float = 1.5) -> jnp.ndarray:
    x = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-(x * x) / (2.0 * sigma * sigma))
    return g / jnp.sum(g)


def _filter2_sep(img: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Separable valid-mode gaussian filter of [H, W]."""
    size = k.shape[0]
    h, w = img.shape
    out = jnp.zeros((h - size + 1, w), img.dtype)
    for i in range(size):
        out = out + k[i] * img[i : h - size + 1 + i, :]
    out2 = jnp.zeros((out.shape[0], w - size + 1), img.dtype)
    for i in range(size):
        out2 = out2 + k[i] * out[:, i : w - size + 1 + i]
    return out2


def ssim_frame(
    ref: jnp.ndarray,
    deg: jnp.ndarray,
    peak: float = 255.0,
    k1: float = 0.01,
    k2: float = 0.03,
) -> jnp.ndarray:
    """Mean SSIM of one [H, W] plane pair (Wang et al. 2004: 11x11 gaussian
    window sigma 1.5, valid borders)."""
    r = ref.astype(jnp.float32)
    d = deg.astype(jnp.float32)
    kern = _gaussian_kernel()
    c1 = (k1 * peak) ** 2
    c2 = (k2 * peak) ** 2
    mu_r = _filter2_sep(r, kern)
    mu_d = _filter2_sep(d, kern)
    mu_rr = mu_r * mu_r
    mu_dd = mu_d * mu_d
    mu_rd = mu_r * mu_d
    var_r = _filter2_sep(r * r, kern) - mu_rr
    var_d = _filter2_sep(d * d, kern) - mu_dd
    cov = _filter2_sep(r * d, kern) - mu_rd
    num = (2.0 * mu_rd + c1) * (2.0 * cov + c2)
    den = (mu_rr + mu_dd + c1) * (var_r + var_d + c2)
    return jnp.mean(num / den)


@jax.jit
def ssim_frames(ref: jnp.ndarray, deg: jnp.ndarray) -> jnp.ndarray:
    """Per-frame SSIM for [T, H, W] pairs."""
    return jax.vmap(ssim_frame)(ref, deg)
