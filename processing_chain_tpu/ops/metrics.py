"""Full-reference per-frame quality metrics on device: PSNR and SSIM.

The reference builds libvmaf into its ffmpeg (Dockerfile:38-43,
install_ffmpeg.sh:61) though chain code never invokes it; BASELINE config 4
calls for per-frame PSNR/SSIM feature extraction vs SRC as part of the long
test. vmapped over the frame axis; inputs are luma (or any single plane).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp


def psnr_frame(ref: jnp.ndarray, deg: jnp.ndarray, peak: float = 255.0) -> jnp.ndarray:
    """PSNR of one [H, W] plane pair, dB (inf-free: clamped to 100 dB for
    identical frames, ffmpeg's psnr filter convention caps similarly)."""
    r = ref.astype(jnp.float32)
    d = deg.astype(jnp.float32)
    mse = jnp.mean((r - d) ** 2)
    psnr = 10.0 * jnp.log10((peak * peak) / jnp.maximum(mse, 1e-10))
    return jnp.minimum(psnr, 100.0)


@jax.jit
def psnr_frames(ref: jnp.ndarray, deg: jnp.ndarray) -> jnp.ndarray:
    """Per-frame PSNR for [T, H, W] pairs."""
    return jax.vmap(psnr_frame)(ref, deg)


def _gaussian_kernel(size: int = 11, sigma: float = 1.5) -> jnp.ndarray:
    x = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-(x * x) / (2.0 * sigma * sigma))
    return g / jnp.sum(g)


def _filter2_sep(img: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Separable valid-mode gaussian filter of [H, W]."""
    size = k.shape[0]
    h, w = img.shape
    out = jnp.zeros((h - size + 1, w), img.dtype)
    for i in range(size):
        out = out + k[i] * img[i : h - size + 1 + i, :]
    out2 = jnp.zeros((out.shape[0], w - size + 1), img.dtype)
    for i in range(size):
        out2 = out2 + k[i] * out[:, i : w - size + 1 + i]
    return out2


def ssim_frame(
    ref: jnp.ndarray,
    deg: jnp.ndarray,
    peak: float = 255.0,
    k1: float = 0.01,
    k2: float = 0.03,
) -> jnp.ndarray:
    """Mean SSIM of one [H, W] plane pair (Wang et al. 2004: 11x11 gaussian
    window sigma 1.5, valid borders)."""
    # mean(lum·cs) == mean(num/den): single statistics pipeline shared
    # with MS-SSIM (see _ssim_cs_means)
    return _ssim_cs_means(
        ref.astype(jnp.float32), deg.astype(jnp.float32), peak, k1, k2
    )[1]


@jax.jit
def ssim_frames(ref: jnp.ndarray, deg: jnp.ndarray) -> jnp.ndarray:
    """Per-frame SSIM for [T, H, W] pairs."""
    return jax.vmap(ssim_frame)(ref, deg)


def _ssim_cs_means(r, d, peak, k1, k2):
    """(mean contrast·structure, mean full SSIM) of one f32 plane pair —
    the per-scale components of MS-SSIM (Wang/Simoncelli/Bovik 2003)."""
    kern = _gaussian_kernel()
    c1 = (k1 * peak) ** 2
    c2 = (k2 * peak) ** 2
    mu_r = _filter2_sep(r, kern)
    mu_d = _filter2_sep(d, kern)
    mu_rr = mu_r * mu_r
    mu_dd = mu_d * mu_d
    mu_rd = mu_r * mu_d
    var_r = _filter2_sep(r * r, kern) - mu_rr
    var_d = _filter2_sep(d * d, kern) - mu_dd
    cov = _filter2_sep(r * d, kern) - mu_rd
    cs = (2.0 * cov + c2) / (var_r + var_d + c2)
    lum = (2.0 * mu_rd + c1) / (mu_rr + mu_dd + c1)
    return jnp.mean(cs), jnp.mean(lum * cs)


def _avgpool2(x: jnp.ndarray) -> jnp.ndarray:
    """2x2 average downsample (MS-SSIM's dyadic pyramid step); odd tails
    are dropped, matching the original implementation's lpf+decimate."""
    h, w = x.shape
    x = x[: h - h % 2, : w - w % 2]
    return (x[0::2, 0::2] + x[1::2, 0::2] + x[0::2, 1::2] + x[1::2, 1::2]) / 4.0


#: Wang/Simoncelli/Bovik 2003 scale exponents
_MSSSIM_WEIGHTS = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333)


MSSSIM_MIN_SIDE = 11 * 2 ** (len(_MSSSIM_WEIGHTS) - 1)  # 176


def _msssim_pair(ref, deg, peak, k1, k2):
    """(MS-SSIM, scale-1 full SSIM) of one [H, W] pair. The scale-1 full
    value IS plain SSIM — returned so callers wanting both never filter
    the full-resolution plane twice."""
    h, w = ref.shape
    if min(h, w) < MSSSIM_MIN_SIDE:
        raise ValueError(
            f"MS-SSIM needs frames >= {MSSSIM_MIN_SIDE} px per side for "
            f"the {len(_MSSSIM_WEIGHTS)}-scale pyramid; got {h}x{w}"
        )
    r = ref.astype(jnp.float32)
    d = deg.astype(jnp.float32)
    out = jnp.float32(1.0)
    ssim1 = None
    n = len(_MSSSIM_WEIGHTS)
    for i, wgt in enumerate(_MSSSIM_WEIGHTS):
        cs, full = _ssim_cs_means(r, d, peak, k1, k2)
        if i == 0:
            ssim1 = full
        val = full if i == n - 1 else cs
        # negative cs (anticorrelated structure) would NaN the fractional
        # power; clamp like the common public implementations
        out = out * jnp.maximum(val, 1e-6) ** wgt
        if i != n - 1:
            r = _avgpool2(r)
            d = _avgpool2(d)
    return out, ssim1


def msssim_frame(
    ref: jnp.ndarray,
    deg: jnp.ndarray,
    peak: float = 255.0,
    k1: float = 0.01,
    k2: float = 0.03,
) -> jnp.ndarray:
    """Multi-scale SSIM of one [H, W] plane pair (Wang/Simoncelli/Bovik
    2003): contrast·structure at 5 dyadic scales, luminance only at the
    coarsest, combined as Π cs_j^w_j · (l·cs)_5^w_5. The device analog of
    the libvmaf ms_ssim feature the reference's Docker build enables but
    never invokes (reference Dockerfile:38-43) — beyond-parity scope.
    Raises ValueError under MSSSIM_MIN_SIDE (176) px per side."""
    return _msssim_pair(ref, deg, peak, k1, k2)[0]


@jax.jit
def msssim_frames(ref: jnp.ndarray, deg: jnp.ndarray) -> jnp.ndarray:
    """Per-frame MS-SSIM for [T, H, W] pairs."""
    return jax.vmap(msssim_frame)(ref, deg)


@jax.jit
def msssim_ssim_frames(
    ref: jnp.ndarray, deg: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(MS-SSIM[T], SSIM[T]) for [T, H, W] pairs in one pass — callers
    wanting both metrics pay the full-resolution filtering once."""
    return jax.vmap(lambda r, d: _msssim_pair(r, d, 255.0, 0.01, 0.03))(
        ref, deg
    )
