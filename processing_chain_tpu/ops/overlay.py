"""Stalling / freeze rendering: the device-side replacement for `bufferer`.

The reference shells out to the external `bufferer` CLI to re-render a PVS
with stalling (reference p03_generateAvPvs.py:216-260, invocation contract
`bufferer -i in -o out -b [[t,d],…] --force-framerate --black-frame -v ffv1
-a pcm_s16le -x pixfmt (-s spinner.png | -e --skipping)`). Here the same
behavior is a host-side timeline plan plus a device-side gather + alpha
blend:

  * stall mode: at each buffer event [media_t, dur], insert round(dur*fps)
    frames showing a black frame (--black-frame) or the last played frame,
    composited with a rotating spinner; output length grows.
  * skipping mode (frame freeze): the frame at the event start repeats for
    the event duration while content underneath is skipped; output length
    is unchanged and no spinner is drawn.

Behavioral spec, by provenance:

  CITED (reference invocation, p03:242-243, and the .buff media-time
  contract, test_config.py:312-333):
    * stall events are [[media_time_s, duration_s], ...]; each inserts
      round(duration*fps) frames at round(media_time*fps) — output grows;
    * --black-frame: inserted frames show black, not the frozen frame;
    * -e --skipping (frame-freeze HRCs): no spinner, content frames are
      *replaced* by the freeze — output length is unchanged;
    * --force-framerate: output CFR at the input rate (our writer is CFR
      by construction);
    * -v ffv1 -a pcm_s16le: FFV1 video, pcm_s16le audio out.

  ASSUMED (upstream bufferer's pip source is unreachable from this
  offline build environment, so its exact spinner kinematics cannot be
  cited): angular rate = `spinner_rps` (default 1.0 rev/s), clockwise,
  phase continuous across consecutive stall events. These are pinned in
  ONE place (plan_stalling's spinner_rps/phase logic) and are
  *calibratable*: `estimate_spinner_rps` recovers the rate from any
  rendered clip, and `tools/bufferer_calibrate.py` runs it against a real
  bufferer output to produce replacement constants (tested round-trip on
  our own renders in tests/test_ops.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.device import shard_map as _shard_map


# ---------------------------------------------------------------------------
# Host: timeline planning
# ---------------------------------------------------------------------------


@dataclass
class StallPlan:
    """Device-executable stalling timeline.

    src_idx[k]    source frame shown at output frame k (int32)
    stall_mask[k] 1 where frame k is an inserted stall frame
    black_mask[k] 1 where the background is a black frame
    phase[k]      spinner rotation phase index (into the rotation bank)
    """

    src_idx: np.ndarray
    stall_mask: np.ndarray
    black_mask: np.ndarray
    phase: np.ndarray

    @property
    def n_out(self) -> int:
        return len(self.src_idx)


def plan_stalling(
    n_frames: int,
    fps: float,
    buff_events: list,
    skipping: bool = False,
    black_frame: bool = True,
    spinner_rps: float = 1.0,
    n_rotations: int = 64,
) -> StallPlan:
    """Expand buffer events into a per-output-frame plan.

    buff_events: [[media_time_s, duration_s], ...] for stalls, or a bare
    list of durations for freezes in skipping mode (the .buff freeze format,
    reference test_config.py:318-322) — bare durations freeze back-to-back
    from t=0 since the freeze format carries no positions.
    """
    if skipping:
        # normalize bare durations to [[t, d]] back-to-back
        events = []
        t_cursor = 0.0
        for ev in buff_events:
            if isinstance(ev, (list, tuple)):
                events.append((float(ev[0]), float(ev[1])))
            else:
                events.append((t_cursor, float(ev)))
                t_cursor += float(ev)
        src_idx = np.arange(n_frames, dtype=np.int32)
        stall = np.zeros(n_frames, np.int8)
        for t, d in events:
            start = int(round(t * fps))
            end = min(n_frames, int(round((t + d) * fps)))
            if start >= n_frames:
                continue
            src_idx[start:end] = src_idx[start]
            stall[start:end] = 1
        return StallPlan(
            src_idx=src_idx,
            stall_mask=stall,
            black_mask=np.zeros(n_frames, np.int8),
            phase=np.zeros(n_frames, np.int32),
        )

    events = sorted((float(e[0]), float(e[1])) for e in buff_events)
    src_idx: list[int] = []
    stall: list[int] = []
    black: list[int] = []
    phase: list[int] = []
    spin_count = 0
    next_src = 0
    for t, d in events:
        event_frame = min(n_frames, int(round(t * fps)))
        while next_src < event_frame:
            src_idx.append(next_src)
            stall.append(0)
            black.append(0)
            phase.append(0)
            next_src += 1
        n_stall = int(round(d * fps))
        for _ in range(n_stall):
            # background: black frame or the last played frame
            src_idx.append(max(0, next_src - 1))
            stall.append(1)
            black.append(1 if black_frame else 0)
            phase.append(
                int(spin_count * spinner_rps * n_rotations / fps) % n_rotations
            )
            spin_count += 1
    while next_src < n_frames:
        src_idx.append(next_src)
        stall.append(0)
        black.append(0)
        phase.append(0)
        next_src += 1
    return StallPlan(
        src_idx=np.asarray(src_idx, np.int32),
        stall_mask=np.asarray(stall, np.int8),
        black_mask=np.asarray(black, np.int8),
        phase=np.asarray(phase, np.int32),
    )


def prepare_spinner(
    spinner_rgba: np.ndarray, n_rotations: int = 64
) -> tuple[np.ndarray, np.ndarray]:
    """Precompute the rotation bank for a spinner image.

    spinner_rgba: [H, W, 4] uint8 (e.g. the reference's
    util/spinner-128-white.png). Returns (yuv [R, 3, H, W] float32 in 0-255,
    alpha [R, H, W] float32 in 0-1), rotated counterclockwise per phase.
    """
    import scipy.ndimage as ndi

    # even dimensions are an invariant downstream: the chroma bank is the
    # ::2 decimation of this bank, and render_core's chroma-grid crop
    # alignment (crop_align) relies on bank dims dividing evenly — trim a
    # stray odd row/column from user-supplied PNGs here, at the single
    # bank entry point
    h, w = spinner_rgba.shape[:2]
    spinner_rgba = spinner_rgba[: h - (h % 2), : w - (w % 2)]

    r, g, b = (spinner_rgba[..., c].astype(np.float32) for c in range(3))
    a = spinner_rgba[..., 3].astype(np.float32) / 255.0
    # BT.601 limited-range YUV (matches ffmpeg overlay of RGBA onto yuv420p)
    y = 0.257 * r + 0.504 * g + 0.098 * b + 16.0
    u = -0.148 * r - 0.291 * g + 0.439 * b + 128.0
    v = 0.439 * r - 0.368 * g - 0.071 * b + 128.0
    yuvs, alphas = [], []
    for k in range(n_rotations):
        angle = -360.0 * k / n_rotations  # clockwise spin
        rot = lambda img, cval: ndi.rotate(
            img, angle, reshape=False, order=1, mode="constant", cval=cval
        )
        ak = np.clip(rot(a, 0.0), 0.0, 1.0)
        yuvs.append(np.stack([rot(y, 16.0), rot(u, 128.0), rot(v, 128.0)]))
        alphas.append(ak)
    return np.stack(yuvs), np.stack(alphas)


# ---------------------------------------------------------------------------
# Device: gather + composite
# ---------------------------------------------------------------------------


def _blend_plane(
    bg: jnp.ndarray, fg: jnp.ndarray, alpha: jnp.ndarray, y0: int, x0: int
) -> jnp.ndarray:
    """Alpha-composite fg (with alpha) onto bg at (y0, x0)."""
    h, w = fg.shape[-2], fg.shape[-1]
    region = jax.lax.dynamic_slice_in_dim(
        jax.lax.dynamic_slice_in_dim(bg, y0, h, axis=-2), x0, w, axis=-1
    )
    blended = region * (1.0 - alpha) + fg * alpha
    return jax.lax.dynamic_update_slice(
        bg, blended.astype(bg.dtype), (y0, x0)
    )


def _clip_crop_origin(
    frame_dim: int, spinner_dim: int, align: int, grid_scale: int = 1
) -> int:
    """Crop origin for a spinner larger than the frame, matching ffmpeg's
    overlay clipping exactly. ffmpeg computes the placement coordinate on
    the LUMA grid — (luma_frame - luma_spinner)/2 truncated toward zero (C
    integer division), then masked toward -inf on the chroma grid
    (normalize_xy: x &= ~((1<<hsub)-1)) — and shifts it down by hsub/vsub
    for chroma planes; the crop keeps the pixels at -placement. Callers on
    a subsampled plane pass grid_scale=sub so the SAME luma coordinate is
    reconstructed and divided back (exact: the mask makes it a multiple of
    sub), keeping chroma locked to luma. E.g. luma frame 90, spinner 128,
    align 2: trunc(-19) & ~1 = -20 -> crop origin 20 (not 18, which a
    positive floor-to-grid would give); the 420 chroma plane (45 under 64,
    grid_scale 2) lands on 10 == 20/2."""
    if spinner_dim <= frame_dim:  # fits on this axis: nothing to crop
        return 0
    lf, ls = frame_dim * grid_scale, spinner_dim * grid_scale
    place = -((ls - lf) // 2)  # trunc toward 0: place <= 0
    place &= ~(align - 1)  # Python & on negatives == two's-complement mask
    return -place // grid_scale


def render_core(
    frames: jnp.ndarray,
    stall: jnp.ndarray,
    black: jnp.ndarray,
    phase: jnp.ndarray,
    spinner: Optional[jnp.ndarray],
    spinner_alpha: Optional[jnp.ndarray],
    black_value: float,
    crop_align: tuple[int, int] = (1, 1),
    grid_scale: tuple[int, int] = (1, 1),
) -> jnp.ndarray:
    """Traceable composite of pre-gathered frames [T, H, W] with per-frame
    stall/black masks [T] and spinner phase indices [T] — the shared body
    of the host-planned path (render_stalled_plane) and the mesh-sharded
    batch path (make_sharded_stall_renderer).

    crop_align is the ffmpeg normalize_xy mask on the LUMA grid (the
    content's chroma subsampling); grid_scale relates THIS plane's grid to
    the luma grid (1 for luma, sub for chroma planes), so all planes
    derive their crop/placement from the same masked luma coordinate."""
    h, w = frames.shape[-2], frames.shape[-1]
    stall_b = stall.astype(jnp.float32)[:, None, None]
    black_b = black.astype(jnp.float32)[:, None, None]
    out = frames * (1.0 - black_b) + black_value * black_b
    if spinner is not None:
        # phases are modulo the actual rotation-bank size, so a plan built
        # with a different n_rotations still indexes in range
        phases = phase % spinner.shape[0]
        # a spinner larger than the frame is center-cropped to fit — the
        # same pixels ffmpeg's overlay keeps when a centered overlay
        # extends past the main frame (clipping); without this the
        # dynamic_slice below is out of range for small renders (e.g. a
        # 90-px-tall AVPVS under the default 128-px spinner). Static
        # Python arithmetic: shapes are trace-time constants.
        # crop_align: LUMA callers pass their content's per-axis chroma
        # subsampling ((2,2) for 420, (1,2) for 422) so the luma crop
        # offset stays on the chroma grid — the chroma plane's own
        # natural offset ((sh_c-ch_c)//2) is then exactly offset/sub and
        # the composited color stays locked to its luma (ffmpeg's
        # overlay aligns placement the same way via hsub/vsub).
        align_h, align_w = crop_align
        gs_h, gs_w = grid_scale
        if (h * gs_h) % align_h or (w * gs_w) % align_w:
            # the chroma-lock arithmetic needs the luma dims on the
            # chroma grid; the domain model guarantees even dims
            # (config/domain.py:51) — fail loudly instead of fringing
            raise ValueError(
                f"render_core: luma-grid plane {h * gs_h}x{w * gs_w} not "
                f"divisible by crop_align {crop_align}"
            )
        sh, sw = spinner.shape[-2], spinner.shape[-1]
        ch, cw = min(sh, h), min(sw, w)
        if (ch, cw) != (sh, sw):
            cy = _clip_crop_origin(h, sh, align_h, gs_h)
            cx = _clip_crop_origin(w, sw, align_w, gs_w)
            spinner = spinner[..., cy:cy + ch, cx:cx + cw]
            spinner_alpha = spinner_alpha[..., cy:cy + ch, cx:cx + cw]
        sp = jnp.take(jnp.asarray(spinner), phases, axis=0)
        sa = jnp.take(jnp.asarray(spinner_alpha), phases, axis=0)
        sa = sa * stall_b  # only composite on stall frames
        # placement offsets come off the same masked luma coordinate as
        # the crop (ffmpeg overlay masks x/y via hsub/vsub then shifts by
        # the plane's subsampling); positive mask == floor-to-grid
        y0 = (((h - ch) * gs_h // 2) & ~(align_h - 1)) // gs_h
        x0 = (((w - cw) * gs_w // 2) & ~(align_w - 1)) // gs_w
        blend = jax.vmap(_blend_plane, in_axes=(0, 0, 0, None, None))
        out = blend(out, sp, sa, y0, x0)
    return out


def render_stalled_plane(
    frames: jnp.ndarray,
    plan: StallPlan,
    spinner: Optional[jnp.ndarray] = None,
    spinner_alpha: Optional[jnp.ndarray] = None,
    black_value: float = 16.0,
    crop_align: tuple[int, int] = (1, 1),
    grid_scale: tuple[int, int] = (1, 1),
) -> jnp.ndarray:
    """Apply a StallPlan to one plane tensor [T, H, W] (float32 0-255).

    spinner: [R, h, w] rotation bank for THIS plane (chroma callers pass the
    subsampled bank), spinner_alpha likewise [R, h, w]. All callers of
    subsampled content pass crop_align=(sub_h, sub_w); chroma callers
    additionally pass grid_scale=(sub_h, sub_w) (see render_core).
    Returns [T_out, H, W]."""
    gathered = jnp.take(frames, jnp.asarray(plan.src_idx), axis=0)
    return render_core(
        gathered,
        jnp.asarray(plan.stall_mask, jnp.float32),
        jnp.asarray(plan.black_mask, jnp.float32),
        jnp.asarray(plan.phase),
        spinner, spinner_alpha, black_value, crop_align, grid_scale,
    )


def make_sharded_stall_renderer(
    mesh, banks: tuple, black_values: tuple, ten_bit: bool,
    chroma_sub: tuple[int, int] = (1, 1),
):
    """Jit the stall composite over a (pvs=N,) frame-parallel mesh: the
    blend is frame-local, so the chunked stalling pass shards its frames
    across every visible device (like tools/quality_metrics does for
    PSNR/SSIM). `banks` = (sp_y, sa_y, sp_u, sp_v, sa_c) or Nones
    (skipping mode) — U and V carry DISTINCT banks, a colored spinner has
    different chroma per plane; `black_values` = per-plane background
    levels. Inputs arrive padded to a multiple of the device count;
    outputs are quantized to container depth on device."""
    import jax
    from jax.sharding import PartitionSpec as P

    sp_y, sa_y, sp_u, sp_v, sa_c = banks
    hi, dt = (1023.0, jnp.uint16) if ten_bit else (255.0, jnp.uint8)

    def shard_fn(y, u, v, stall, black, phase):
        outs = []
        for p, sp, sa, bv, gs in (
            (y, sp_y, sa_y, black_values[0], (1, 1)),   # luma grid
            (u, sp_u, sa_c, black_values[1], chroma_sub),
            (v, sp_v, sa_c, black_values[2], chroma_sub),
        ):
            # all planes mask on the luma grid (crop_align=chroma_sub)
            # and divide back by their own grid scale — chroma stays
            # locked to luma even in the oversized-spinner clip case
            r = render_core(p, stall, black, phase, sp, sa, bv,
                            chroma_sub, gs)
            outs.append(jnp.clip(jnp.floor(r + 0.5), 0, hi).astype(dt))
        return tuple(outs)

    frame_spec = P("pvs", None, None)
    mask_spec = P("pvs")
    mapped = _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(frame_spec, frame_spec, frame_spec,
                  mask_spec, mask_spec, mask_spec),
        out_specs=(frame_spec, frame_spec, frame_spec),
    )
    return jax.jit(mapped)


def downsample_alpha(alpha: np.ndarray) -> np.ndarray:
    """[R, H, W] alpha → chroma-grid alpha [R, H/2, W/2] (2x2 mean)."""
    return alpha.reshape(alpha.shape[0], alpha.shape[1] // 2, 2,
                         alpha.shape[2] // 2, 2).mean(axis=(2, 4))


# ---------------------------------------------------------------------------
# Calibration: recover spinner kinematics from a rendered clip
# ---------------------------------------------------------------------------


def estimate_spinner_rps(
    frames: np.ndarray, fps: float
) -> tuple[float, float]:
    """Estimate the spinner's angular rate from stall-zone luma frames.

    frames: [T, H, W] luma of consecutive stall frames, cropped roughly to
    the spinner region (dark background). Method: the luminance-weighted
    centroid of a rotationally-asymmetric spinner (the reference spinner's
    gradient tail) traces a circle; the unwrapped centroid angle against
    frame index gives rad/frame, hence revolutions/second.

    Returns (rps, residual): rps > 0 means clockwise on screen (image y
    points down); residual is the RMS of the linear-fit error in radians —
    large residual means the clip wasn't a steadily rotating spinner.
    """
    t = frames.shape[0]
    if t < 3:
        raise ValueError("need at least 3 stall frames to estimate a rate")
    h, w = frames.shape[1:]
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    angles = np.empty(t)
    for k, f in enumerate(np.asarray(frames, np.float64)):
        wgt = np.clip(f - f.min(), 0, None)
        s = wgt.sum()
        if s <= 0:
            raise ValueError(f"stall frame {k} is uniform; cannot locate spinner")
        angles[k] = np.arctan2(
            (wgt * yy).sum() / s - cy, (wgt * xx).sum() / s - cx
        )
    ang = np.unwrap(angles)
    n = np.arange(t)
    slope, intercept = np.polyfit(n, ang, 1)
    resid = float(np.sqrt(np.mean((ang - (slope * n + intercept)) ** 2)))
    return float(slope * fps / (2.0 * np.pi)), resid
