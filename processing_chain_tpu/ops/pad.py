"""Letterbox / pad to a display canvas (the CPVS `pad=` step, reference
lib/ffmpeg.py:1177-1231: scale to coding dims then pad to display dims,
centered, black fill)."""

from __future__ import annotations

import jax.numpy as jnp


def pad_center(
    plane: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    fill: float = 16.0,
) -> jnp.ndarray:
    """Pad [..., H, W] to [..., dst_h, dst_w] with the content centered
    (ffmpeg pad=W:H:(ow-iw)/2:(oh-ih)/2 semantics: offsets floor)."""
    h, w = plane.shape[-2], plane.shape[-1]
    if (h, w) == (dst_h, dst_w):
        return plane
    y0 = (dst_h - h) // 2
    x0 = (dst_w - w) // 2
    pad_widths = [(0, 0)] * (plane.ndim - 2) + [
        (y0, dst_h - h - y0),
        (x0, dst_w - w - x0),
    ]
    return jnp.pad(plane, pad_widths, constant_values=plane.dtype.type(fill) if hasattr(plane.dtype, "type") else fill)


def pad_yuv(
    planes: tuple,
    dst_h: int,
    dst_w: int,
    pix_fmt: str = "yuv420p",
    luma_fill: float = 16.0,
    chroma_fill: float = 128.0,
) -> tuple:
    """Pad planar YUV to a display canvas; chroma planes pad on their
    subsampled grid."""
    sub_w = 2 if ("420" in pix_fmt or "422" in pix_fmt) else 1
    sub_h = 2 if "420" in pix_fmt else 1
    out = [pad_center(planes[0], dst_h, dst_w, luma_fill)]
    for p in planes[1:3]:
        out.append(pad_center(p, dst_h // sub_h, dst_w // sub_w, chroma_fill))
    return tuple(out)
