"""Pallas TPU kernels for the hot pixel ops.

`resize_plane_fused` is the flagship kernel: both polyphase resample passes
(vertical, horizontal) of the AVPVS upscale fused in VMEM per frame, so the
[dst_h, src_w] intermediate never touches HBM — the XLA path (ops/resize.py)
materializes it, costing an extra write+read of ~4 B/px. The banded-matmul
formulation (ops/resize.py `make_banded_plan`) maps both passes onto the
MXU: each 128-row / 128-col output block is a small dense matmul against a
contiguous band of the source, with the per-block band starts delivered as
scalar-prefetch so the kernel can dynamic-slice its VMEM-resident frame.

Replaces the decode+upscale inner loop of the reference's AVPVS stage
(reference lib/ffmpeg.py:948, :1037 — swscale `scale=W:H:flags=...`).

Layout per grid step (t, cb) — horizontal pass first, matching swscale's
stage order so the 15-bit intermediate top-clamp sits between H and V like
the golden integer path (ops/resize._swscale_exact):
  in    u8 [src_h, src_w]       whole frame, VMEM-resident across cb steps
  wv    f32 [nrb, 128, band_v]      vertical weights, resident
  wh    f32 [1, block_w, band_h]    horizontal weights for col stripe cb
  out   u8/f32 [1, dst_h, block_w]  one output column stripe
  mid   f32 [src_h, block_w]        scratch: horizontal pass result

block_w defaults to 128; wh/out/mid (and their pipeline double-buffers)
scale linearly with it. VMEM @ 1080p→4K, block_w=128 ≈ 2 MB (in) +
0.7 MB (wv) + 0.6 MB (mid) + 0.5 MB (out): well under the 16 MB/core
budget; block_w=512 measures over it once double-buffering is counted.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .resize import make_banded_plan

# Importing jax.experimental.pallas registers MLIR lowerings for platform
# "tpu", which jax only accepts once its plugin discovery has made "tpu" a
# known platform — i.e. AFTER the first backend initialization. At package
# import time (CPU-only test processes, CLI startup before any device
# touch) that registration raises NotImplementedError("unknown platform
# tpu"). So: attempt the import, and on failure retry lazily at first
# kernel use (failed module imports are removed from sys.modules, so the
# retry re-executes them — by then a backend exists and it succeeds).
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # retried in _pallas()
    pl = None
    pltpu = None


def _pallas():
    global pl, pltpu
    if pl is None or pltpu is None:
        jax.devices()  # force plugin discovery so "tpu" is a known platform
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu

        pl, pltpu = _pl, _pltpu
    return pl, pltpu


BLOCK = 128


def _fused_resize_kernel(
    starts_v_ref,   # SMEM [nrb]    (scalar prefetch; 8-aligned)
    starts_h_ref,   # SMEM [ncb]    (scalar prefetch; 128-aligned)
    in_ref,         # VMEM [1, src_h, src_w_pad] u8
    wv_ref,         # VMEM [nrb, BLOCK, band_v_pad]
    wh_ref,         # VMEM [1, block_w, band_h_pad]
    out_ref,        # VMEM [1, nrb * BLOCK, block_w]
    mid_ref,        # VMEM scratch [src_h_pad, block_w] f32
    *,
    band_v: int,
    band_h: int,
    nrb: int,
    src_h: int,
    quantize: bool,
    maxval: int,
):
    """One (frame, column-block) step: horizontal pass for this column
    stripe first — matching swscale's stage order so the 15-bit
    intermediate top-clamp lands between H and V exactly like the golden
    integer path (resize._swscale_exact) — then all vertical row blocks
    of the stripe from VMEM scratch.

    Mosaic constraints shape the layout: dynamic slices must start at
    multiples of 128 on the lane axis and 8 on the sublane axis — and the
    compiler must be able to PROVE it statically, so the prefetch arrays
    carry start/align and the kernel multiplies the alignment back in.
    Weight rows are shifted to compensate (zero-padded bands), and u8
    loads widen through int32 (u8->f32 has no direct lowering)."""
    pl, _ = _pallas()
    cb = pl.program_id(1)
    sh = starts_h_ref[cb] * 128
    src = in_ref[0, :, pl.ds(sh, band_h)].astype(jnp.int32).astype(jnp.float32)
    mid = jax.lax.dot(
        src, wh_ref[0].T, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    if quantize and maxval == 255:
        # swscale's hScale8To15 top-clamp in normalized units
        mid = jnp.minimum(mid, 32767.0 / 128.0)
    mid_ref[:src_h, :] = mid
    if mid_ref.shape[0] > src_h:
        # scratch rows past src_h are read through zero weights; NaN
        # garbage × 0 is NaN, so they must actually BE zero
        mid_ref[src_h:, :] = jnp.zeros(
            (mid_ref.shape[0] - src_h, mid_ref.shape[1]), jnp.float32
        )
    for rb in range(nrb):  # static unroll: nrb is small (dst_h / 128)
        sv = starts_v_ref[rb] * 8
        tile = jax.lax.dot(
            wv_ref[rb], mid_ref[pl.ds(sv, band_v), :],
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if quantize:
            tile = jnp.clip(jnp.floor(tile + 0.5), 0, maxval)
        if out_ref.dtype in (jnp.uint8, jnp.uint16):
            # f32 -> narrow unsigned also needs the int32 intermediate
            tile = tile.astype(jnp.int32)
        out_ref[0, rb * BLOCK : (rb + 1) * BLOCK, :] = tile.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("dst_h", "dst_w", "kernel", "interpret", "block_w"),
)
def resize_frames_fused(
    frames: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    interpret: bool = False,
    block_w: int = BLOCK,
) -> jnp.ndarray:
    """Fused two-pass resize of [T, src_h, src_w] u8 planes on TPU.

    Output u8 [T, dst_h, dst_w] with swscale round-half-up quantization —
    the Pallas counterpart of `resize.resize_frames(..., method="banded")`.
    `interpret=True` runs the kernel in the Pallas interpreter (CPU tests).

    `block_w` is the horizontal output-stripe width. Wider stripes
    amortize the fixed ~127-col alignment padding of the source band but
    measured SLOWER on v5e at 1080p->4K (3.43/3.55/3.64 ms for
    128/256/384; 512 exceeds the 16 MB VMEM budget with pipeline
    double-buffering) — the kernel is pipeline-bound, not MXU-bound, so
    the default stays 128.
    """
    pl, pltpu = _pallas()
    t, src_h, src_w = frames.shape
    if block_w <= 0 or block_w % 128:
        raise ValueError(f"block_w must be a positive multiple of 128, got {block_w}")
    if (src_h, src_w) == (dst_h, dst_w):
        return frames
    # clamp to the (128-rounded) output width: an over-wide stripe would
    # still make a 1-block grid, but its padded out/weight buffers would
    # waste VMEM proportionally
    block_w = min(block_w, -(-dst_w // 128) * 128)
    starts_v, wv, band_v = make_banded_plan(src_h, dst_h, kernel, BLOCK)
    starts_h, wh, band_h = make_banded_plan(src_w, dst_w, kernel, block_w)
    # Mosaic dynamic-slice alignment: 128 on the lane axis (horizontal
    # bands slice the frame's width), 8 on the sublane axis (vertical
    # bands slice the f32 scratch's height). Shift each start down to
    # alignment and shift its weight row up by the same offset inside a
    # zero-padded band.
    starts_h, wh, band_h = _align_band(starts_h, wh, band_h, 128)
    starts_v, wv, band_v = _align_band(starts_v, wv, band_v, 8)
    nrb = wv.shape[0]
    ncb = wh.shape[0]
    pad_h = nrb * BLOCK
    # aligned loads may extend past src_w; pad the frame so they stay in
    # bounds (zero weights cover the padding)
    src_w_pad = src_w + band_h
    frames = jnp.pad(frames, ((0, 0), (0, 0), (0, src_w_pad - src_w)))
    src_h_pad = src_h + band_v

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(t, ncb),
        in_specs=[
            pl.BlockSpec((1, src_h, src_w_pad), lambda ti, cb, *_: (ti, 0, 0)),
            pl.BlockSpec((nrb, BLOCK, band_v), lambda ti, cb, *_: (0, 0, 0)),
            pl.BlockSpec((1, block_w, band_h), lambda ti, cb, *_: (cb, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, pad_h, block_w), lambda ti, cb, *_: (ti, 0, cb)
        ),
        scratch_shapes=[pltpu.VMEM((src_h_pad, block_w), jnp.float32)],
    )
    kernel_fn = functools.partial(
        _fused_resize_kernel,
        band_v=band_v,
        band_h=band_h,
        nrb=nrb,
        src_h=src_h,
        quantize=True,
        maxval=255 if frames.dtype == jnp.uint8 else 1023,
    )
    out = pl.pallas_call(
        kernel_fn,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (t, pad_h, ncb * block_w), frames.dtype
        ),
        interpret=interpret,
    )(jnp.asarray(starts_v) // 8, jnp.asarray(starts_h) // 128, frames,
      jnp.asarray(wv), jnp.asarray(wh))
    return out[:, :dst_h, :dst_w]


def _align_band(starts, w, band: int, align: int):
    """Re-express a banded plan with `align`-multiple starts.

    Each block's start rounds DOWN to alignment and its weight row shifts
    right by the rounding offset inside a wider zero-padded band, so the
    weighted sum is unchanged. New band = band + align - 1, rounded up to
    a multiple of `align` (slice extents share the alignment rule)."""
    starts = np.asarray(starts)
    nb, blk, _ = w.shape
    new_band = -(-(band + align - 1) // align) * align
    off = starts % align
    w2 = np.zeros((nb, blk, new_band), w.dtype)
    for i in range(nb):
        w2[i, :, off[i]: off[i] + band] = w[i]
    return starts - off, w2, new_band


def pallas_available() -> bool:
    """True when the default backend can run compiled Pallas TPU kernels."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Fused SI / TI feature kernels
# ---------------------------------------------------------------------------
#
# The XLA formulation of SI (Sobel magnitude -> stddev) materializes the
# gradient/magnitude tensors in HBM between the elementwise pass and the
# reductions (~4.3 ms for 8 4K frames measured on v5e); these kernels keep
# everything in VMEM per 128-column stripe and emit per-stripe partial
# sums (Σm, Σm²), so each frame is read ~twice and nothing else touches
# HBM. Final sufficient-stats combine (σ = sqrt(E[m²] − E[m]²)) happens in
# XLA on the tiny partials. The overlap needed for the horizontal Sobel
# halo is built by passing the SAME padded array through two BlockSpecs,
# one shifted a block right — a Pallas idiom for stencil halos.


def _rows01(s1: jnp.ndarray, s2: jnp.ndarray) -> jnp.ndarray:
    """[8, 128] with row 0 = s1, row 1 = s2, rest 0."""
    return _rows0123((s1, s2))


def _sobel_stripe_stats(a, b, w: int, ci_axis: int = 1):
    """Shared SI stripe body: from stripe a (cols [c0, c0+128)) and its
    right-halo stripe b, the row-reduced (Σ|∇|, Σ|∇|²) per lane, masked
    past the frame's valid gradient columns. Integer luma casts in VMEM
    (u8/u16 input quarters/halves the HBM traffic vs pre-cast f32).
    ci_axis: which grid axis walks the column stripes (1 for the [T]
    kernels, 2 for the batched [B, T] kernel)."""
    f = jnp.concatenate([a, b], axis=1)[:, :136]
    if f.dtype != jnp.float32:
        f = f.astype(jnp.int32).astype(jnp.float32)
    sv = f[:-2] + 2.0 * f[1:-1] + f[2:]          # vertical smooth  [H-2, 136]
    gx = sv[:, 2:130] - sv[:, :128]              # horizontal diff  [H-2, 128]
    sh = f[:, :-2] + 2.0 * f[:, 1:-1] + f[:, 2:]  # horizontal smooth [H, 134]
    gy = sh[2:, :128] - sh[:-2, :128]            # vertical diff    [H-2, 128]
    m2 = gx * gx + gy * gy
    m = jnp.sqrt(m2)
    ci = pl.program_id(ci_axis)
    # gradient column kk maps to source col ci*128 + 1 + kk; valid < w-1
    col = ci * 128 + 1 + jax.lax.broadcasted_iota(jnp.int32, m.shape, 1)
    ok = (col < w - 1).astype(jnp.float32)
    return jnp.sum(m * ok, axis=0), jnp.sum(m2 * ok, axis=0), f


def _si_partial_kernel(a_ref, b_ref, out_ref, *, w: int):
    """One (frame, column-stripe) step: a = cols [c0, c0+128), b = the next
    stripe. Emits row-reduced Σ|∇| and Σ|∇|² per lane."""
    s1, s2, _ = _sobel_stripe_stats(a_ref[0], b_ref[0], w)
    out_ref[0, 0] = _rows01(s1, s2)


def si_frames_fused(y: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """SI per frame for [T, H, W] luma (f32 or integer container depth) —
    the Pallas TPU path of ops.siti.si_frames (identical sufficient-stats
    math; integer input casts in VMEM)."""
    pl_, _ = _pallas()
    t, h, w = y.shape
    n_ct = -(-w // 128)
    pad_w = (n_ct + 1) * 128
    yp = jnp.pad(y, ((0, 0), (0, 0), (0, pad_w - w)))
    out = pl_.pallas_call(
        functools.partial(_si_partial_kernel, w=w),
        grid=(t, n_ct),
        in_specs=[
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti, 0, ci)),
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti, 0, ci + 1)),
        ],
        out_specs=pl_.BlockSpec((1, 1, 8, 128), lambda ti, ci: (ti, ci, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t, n_ct, 8, 128), jnp.float32),
        interpret=interpret,
    )(yp, yp)
    return _std_from_partials(out, 0, 1, (h - 2) * (w - 2), (1, 2))


def _rows0123(rows_vals) -> jnp.ndarray:
    """[8, 128] with rows 0..3 = the four given [128] vectors, rest 0."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    out = jnp.zeros((8, 128), jnp.float32)
    for i, v in enumerate(rows_vals):
        out = jnp.where(rows == i, jnp.broadcast_to(v[None], (8, 128)), out)
    return out


def _siti_stripe_rows(a, b, prev, w: int, ci_axis: int) -> jnp.ndarray:
    """Shared body of the combined SI+TI kernels: the [8, 128] partials
    block (rows 0,1 = Σ|∇|, Σ|∇|² masked to valid gradient cols; rows 2,3
    = Σd, Σd² vs the prev stripe, zero-padded width self-masking)."""
    s1, s2, f = _sobel_stripe_stats(a, b, w, ci_axis)
    if prev.dtype != jnp.float32:
        prev = prev.astype(jnp.int32).astype(jnp.float32)
    d = f[:, :128] - prev
    return _rows0123((s1, s2, jnp.sum(d, axis=0), jnp.sum(d * d, axis=0)))


def _std_from_partials(out, s1_row: int, s2_row: int, n: int, axes):
    """σ from per-stripe sufficient-stats partials: rows s1_row/s2_row of
    the [..., 8, 128] blocks hold Σx and Σx²; reduce over `axes`,
    normalize by n, σ = sqrt(max(E[x²] − E[x]², 0))."""
    s1 = jnp.sum(out[..., s1_row, :], axis=axes) / n
    s2 = jnp.sum(out[..., s2_row, :], axis=axes) / n
    return jnp.sqrt(jnp.maximum(s2 - s1 * s1, 0.0))


def _siti_partial_kernel(a_ref, b_ref, p_ref, out_ref, *, w: int):
    """One (frame, column-stripe) step of the COMBINED SI+TI pass: a = this
    frame's stripe, b = the next stripe (horizontal Sobel halo), p = the
    PREVIOUS frame's stripe (clamped to frame 0 at t=0, making d == 0 and
    thus TI[0] == 0 with no special case). One fused pass reads each
    stripe ~3x total where the separate SI and TI kernels read ~4x, and
    saves a kernel launch + a second u8->f32 cast of the whole batch."""
    out_ref[0, 0] = _siti_stripe_rows(a_ref[0], b_ref[0], p_ref[0], w, 1)


def siti_frames_fused(
    y: jnp.ndarray, interpret: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(SI[T], TI[T]) for [T, H, W] luma in ONE fused pass — the Pallas
    TPU path of ops.siti.siti. Same sufficient-stats math as the separate
    si_frames_fused/ti_frames_fused, at ~3/4 the HBM traffic and half the
    kernel launches."""
    pl_, _ = _pallas()
    t, h, w = y.shape
    n_ct = -(-w // 128)
    pad_w = (n_ct + 1) * 128
    yp = jnp.pad(y, ((0, 0), (0, 0), (0, pad_w - w)))
    out = pl_.pallas_call(
        functools.partial(_siti_partial_kernel, w=w),
        grid=(t, n_ct),
        in_specs=[
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti, 0, ci)),
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti, 0, ci + 1)),
            pl_.BlockSpec(
                (1, h, 128), lambda ti, ci: (jnp.maximum(ti - 1, 0), 0, ci)
            ),
        ],
        out_specs=pl_.BlockSpec((1, 1, 8, 128), lambda ti, ci: (ti, ci, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t, n_ct, 8, 128), jnp.float32),
        interpret=interpret,
    )(yp, yp, yp)
    si = _std_from_partials(out, 0, 1, (h - 2) * (w - 2), (1, 2))
    ti = _std_from_partials(out, 2, 3, h * w, (1, 2))
    return si, ti


def _siti_batch_kernel(a_ref, b_ref, p_ref, out_ref, *, w: int):
    """Batched [B, T] variant of _siti_partial_kernel: refs are
    [1, 1, h, 128] blocks of the prev-prepended [B, T+1, H, Wp] array;
    grid (B, T, n_ct). a = frame (b, t+1), b = its right halo, p = frame
    (b, t) — the per-lane predecessor, which for t=0 is the halo slot the
    caller filled (previous time-shard's last frame, or the lane's own
    first frame making TI[0] = 0)."""
    out_ref[0, 0, 0] = _siti_stripe_rows(
        a_ref[0, 0], b_ref[0, 0], p_ref[0, 0], w, 2
    )


def siti_frames_fused_batch(
    y: jnp.ndarray, prev_last: jnp.ndarray, interpret: bool = False
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(SI[B, T], TI[B, T]) for [B, T, H, W] luma lanes with an explicit
    per-lane predecessor frame prev_last [B, H, W] (same dtype) — the
    sharded step's feature pass: TI[b, 0] diffs against prev_last[b] (a
    time-shard halo, or the lane's own first frame for a global TI[0]=0).
    One fused pass; nothing f32 ever materializes in HBM."""
    pl_, _ = _pallas()
    bsz, t, h, w = y.shape
    n_ct = -(-w // 128)
    pad_w = (n_ct + 1) * 128
    seq = jnp.concatenate([prev_last[:, None], y], axis=1)
    seq = jnp.pad(seq, ((0, 0), (0, 0), (0, 0), (0, pad_w - w)))
    out = pl_.pallas_call(
        functools.partial(_siti_batch_kernel, w=w),
        grid=(bsz, t, n_ct),
        in_specs=[
            pl_.BlockSpec((1, 1, h, 128), lambda bi, ti, ci: (bi, ti + 1, 0, ci)),
            pl_.BlockSpec((1, 1, h, 128), lambda bi, ti, ci: (bi, ti + 1, 0, ci + 1)),
            pl_.BlockSpec((1, 1, h, 128), lambda bi, ti, ci: (bi, ti, 0, ci)),
        ],
        out_specs=pl_.BlockSpec(
            (1, 1, 1, 8, 128), lambda bi, ti, ci: (bi, ti, ci, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((bsz, t, n_ct, 8, 128), jnp.float32),
        interpret=interpret,
    )(seq, seq, seq)
    si = _std_from_partials(out, 0, 1, (h - 2) * (w - 2), (2, 3))
    ti = _std_from_partials(out, 2, 3, h * w, (2, 3))
    return si, ti


def _ti_partial_kernel(a_ref, b_ref, out_ref):
    """One (frame-pair, column-stripe) step: Σd and Σd² of the inter-frame
    difference, row-reduced per lane. Frames are zero-padded past the true
    width, so pad lanes contribute 0 − 0 = 0 to both sums."""
    a, b = a_ref[0], b_ref[0]
    if a.dtype != jnp.float32:
        a = a.astype(jnp.int32).astype(jnp.float32)
        b = b.astype(jnp.int32).astype(jnp.float32)
    d = a - b
    out_ref[0, 0] = _rows01(jnp.sum(d, axis=0), jnp.sum(d * d, axis=0))


def ti_frames_fused(y: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """TI per frame for [T, H, W] f32 luma (TI[0] = 0) — the Pallas TPU
    path of ops.siti.ti_frames."""
    pl_, _ = _pallas()
    t, h, w = y.shape
    if t < 2:
        return jnp.zeros((t,), jnp.float32)
    n_ct = -(-w // 128)
    pad_w = n_ct * 128
    yp = jnp.pad(y, ((0, 0), (0, 0), (0, pad_w - w)))
    out = pl_.pallas_call(
        _ti_partial_kernel,
        grid=(t - 1, n_ct),
        in_specs=[
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti + 1, 0, ci)),
            pl_.BlockSpec((1, h, 128), lambda ti, ci: (ti, 0, ci)),
        ],
        out_specs=pl_.BlockSpec((1, 1, 8, 128), lambda ti, ci: (ti, ci, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t - 1, n_ct, 8, 128), jnp.float32),
        interpret=interpret,
    )(yp, yp)
    ti = _std_from_partials(out, 0, 1, h * w, (1, 2))
    return jnp.concatenate([jnp.zeros((1,), jnp.float32), ti])
