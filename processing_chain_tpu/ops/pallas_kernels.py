"""Pallas TPU kernels for the hot pixel ops.

`resize_plane_fused` is the flagship kernel: both polyphase resample passes
(vertical, horizontal) of the AVPVS upscale fused in VMEM per frame, so the
[dst_h, src_w] intermediate never touches HBM — the XLA path (ops/resize.py)
materializes it, costing an extra write+read of ~4 B/px. The banded-matmul
formulation (ops/resize.py `make_banded_plan`) maps both passes onto the
MXU: each 128-row / 128-col output block is a small dense matmul against a
contiguous band of the source, with the per-block band starts delivered as
scalar-prefetch so the kernel can dynamic-slice its VMEM-resident frame.

Replaces the decode+upscale inner loop of the reference's AVPVS stage
(reference lib/ffmpeg.py:948, :1037 — swscale `scale=W:H:flags=...`).

Layout per grid step (t, rb):
  in    u8 [src_h, src_w]      whole frame, VMEM-resident across rb steps
  wv    f32 [1, 128, band_v]   vertical weights for row block rb (streamed)
  wh    f32 [ncb, 128, band_h] horizontal weights, resident
  out   u8/f32 [1, 128, dst_w] one output row block
  mid   f32 [128, src_w]       scratch: vertical pass result

VMEM @ 1080p→4K ≈ 2 MB (in) + 1.2 MB (wh) + 1 MB (mid) + 0.5 MB (out):
well under the ~16 MB/core budget; a 4K source (8.3 MB u8) still fits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .resize import make_banded_plan

BLOCK = 128


def _fused_resize_kernel(
    starts_v_ref,   # SMEM [nrb]    (scalar prefetch)
    starts_h_ref,   # SMEM [ncb]    (scalar prefetch)
    in_ref,         # VMEM [1, src_h, src_w] u8
    wv_ref,         # VMEM [1, BLOCK, band_v]
    wh_ref,         # VMEM [ncb, BLOCK, band_h]
    out_ref,        # VMEM [1, BLOCK, ncb * BLOCK]
    mid_ref,        # VMEM scratch [BLOCK, src_w] f32
    *,
    band_v: int,
    band_h: int,
    ncb: int,
    quantize: bool,
    maxval: int,
):
    rb = pl.program_id(1)
    sv = starts_v_ref[rb]
    src = in_ref[0, pl.ds(sv, band_v), :].astype(jnp.float32)
    mid_ref[:, :] = jax.lax.dot(
        wv_ref[0], src, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    for cb in range(ncb):  # static unroll: ncb is small (dst_w / 128)
        sh = starts_h_ref[cb]
        tile = jax.lax.dot(
            mid_ref[:, pl.ds(sh, band_h)],
            wh_ref[cb].T,
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if quantize:
            tile = jnp.clip(jnp.floor(tile + 0.5), 0, maxval)
        out_ref[0, :, cb * BLOCK : (cb + 1) * BLOCK] = tile.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("dst_h", "dst_w", "kernel", "interpret")
)
def resize_frames_fused(
    frames: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused two-pass resize of [T, src_h, src_w] u8 planes on TPU.

    Output u8 [T, dst_h, dst_w] with swscale round-half-up quantization —
    the Pallas counterpart of `resize.resize_frames(..., method="banded")`.
    `interpret=True` runs the kernel in the Pallas interpreter (CPU tests).
    """
    t, src_h, src_w = frames.shape
    if (src_h, src_w) == (dst_h, dst_w):
        return frames
    starts_v, wv, band_v = make_banded_plan(src_h, dst_h, kernel, BLOCK)
    starts_h, wh, band_h = make_banded_plan(src_w, dst_w, kernel, BLOCK)
    nrb = wv.shape[0]
    ncb = wh.shape[0]
    pad_w = ncb * BLOCK

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(t, nrb),
        in_specs=[
            pl.BlockSpec((1, src_h, src_w), lambda ti, rb, *_: (ti, 0, 0)),
            pl.BlockSpec((1, BLOCK, band_v), lambda ti, rb, *_: (rb, 0, 0)),
            pl.BlockSpec((ncb, BLOCK, band_h), lambda ti, rb, *_: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, BLOCK, pad_w), lambda ti, rb, *_: (ti, rb, 0)
        ),
        scratch_shapes=[pltpu.VMEM((BLOCK, src_w), jnp.float32)],
    )
    kernel_fn = functools.partial(
        _fused_resize_kernel,
        band_v=band_v,
        band_h=band_h,
        ncb=ncb,
        quantize=True,
        maxval=255 if frames.dtype == jnp.uint8 else 1023,
    )
    out = pl.pallas_call(
        kernel_fn,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, nrb * BLOCK, pad_w), frames.dtype),
        interpret=interpret,
    )(jnp.asarray(starts_v), jnp.asarray(starts_h), frames,
      jnp.asarray(wv), jnp.asarray(wh))
    return out[:, :dst_h, :dst_w]


def pallas_available() -> bool:
    """True when the default backend can run compiled Pallas TPU kernels."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
