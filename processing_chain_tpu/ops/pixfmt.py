"""Pixel-format conversions on device.

Covers the chain's format plumbing (reference lib/test_config.py:447-480
harmonization targets and lib/ffmpeg.py CPVS maps): planar 420/422/444
chroma resampling, 8↔10-bit depth conversion, and UYVY422 packing for the
PC-context CPVS (reference Pvs.get_vcodec_and_pix_fmt_for_cpvs,
test_config.py:188-227). All functions take/return jnp arrays and are
jit/vmap friendly.
"""

from __future__ import annotations

import jax.numpy as jnp

from .resize import resize_plane


def chroma_to_444(u: jnp.ndarray, v: jnp.ndarray, luma_h: int, luma_w: int,
                  kernel: str = "bilinear") -> tuple[jnp.ndarray, jnp.ndarray]:
    """Upsample subsampled chroma planes to the luma grid."""
    return (
        resize_plane(u, luma_h, luma_w, kernel),
        resize_plane(v, luma_h, luma_w, kernel),
    )


def chroma_420_to_422(u: jnp.ndarray, v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """yuv420p → yuv422p: double the chroma height (vertical bilinear)."""
    h, w = u.shape[-2], u.shape[-1]
    return (
        resize_plane(u, h * 2, w, "bilinear"),
        resize_plane(v, h * 2, w, "bilinear"),
    )


def chroma_422_to_420(u: jnp.ndarray, v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """yuv422p → yuv420p: halve the chroma height."""
    h, w = u.shape[-2], u.shape[-1]
    return (
        resize_plane(u, h // 2, w, "bilinear"),
        resize_plane(v, h // 2, w, "bilinear"),
    )


def depth_8_to_10(plane: jnp.ndarray) -> jnp.ndarray:
    """uint8 → 10-bit in uint16 (left shift, ffmpeg's scale semantics)."""
    return (plane.astype(jnp.uint16) << 2)


def depth_10_to_8(plane: jnp.ndarray) -> jnp.ndarray:
    """10-bit uint16 → uint8 with round-half-up."""
    p = plane.astype(jnp.int32)
    return jnp.clip((p + 2) >> 2, 0, 255).astype(jnp.uint8)


def pack_uyvy422(y: jnp.ndarray, u: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Planar yuv422 (u/v at half width) → packed UYVY bytes [H, W*2]
    (the rawvideo CPVS layout for the PC context)."""
    h, w = y.shape[-2], y.shape[-1]
    out = jnp.zeros(y.shape[:-2] + (h, w * 2), jnp.uint8)
    out = out.at[..., 0::4].set(u)
    out = out.at[..., 2::4].set(v)
    out = out.at[..., 1::2].set(y)
    return out


def planes_to_float(planes: tuple, ten_bit: bool = False) -> tuple:
    """Native-depth planes → float32 in [0, 255] (10-bit scaled to 8-bit
    range so kernels are depth-agnostic)."""
    scale = 1.0 / 4.0 if ten_bit else 1.0
    return tuple(p.astype(jnp.float32) * scale for p in planes)


def float_to_planes(planes: tuple, ten_bit: bool = False) -> tuple:
    """float32 [0,255] range → uint8 or 10-bit uint16 with round-half-up."""
    if ten_bit:
        return tuple(
            jnp.clip(jnp.floor(p * 4.0 + 0.5), 0, 1023).astype(jnp.uint16)
            for p in planes
        )
    return tuple(
        jnp.clip(jnp.floor(p + 0.5), 0, 255).astype(jnp.uint8) for p in planes
    )
