"""Separable polyphase resampling on device — the chain's hottest op.

TPU-native replacement for the reference's swscale `scale=W:H:flags=bicubic`
/ `flags=lanczos` filters (reference lib/ffmpeg.py:948, :1037, :1196).
Filter construction mirrors libswscale's: align-centers source mapping,
BC-spline bicubic with the swscale default (B=0, C=0.6), Lanczos-3, support
widening + renormalization for downscale. The tap plan (indices + weights)
is precomputed on host per (src, dst, kernel) and cached; the device side is
K fused multiply-adds over gathered rows/columns — bandwidth-bound, VPU
friendly, vmappable over frames and planes.

Golden-tested against libswscale output (io.medialib.sws_scale_plane).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Host-side filter construction
# ---------------------------------------------------------------------------


def _bicubic_kernel(d: np.ndarray, b: float = 0.0, c: float = 0.6) -> np.ndarray:
    """Mitchell-Netravali BC-spline; swscale's bicubic uses B=0, C=0.6 by
    default (libswscale/utils.c initFilter)."""
    d = np.abs(d)
    d2, d3 = d * d, d * d * d
    p0 = (6.0 - 2.0 * b) / 6.0
    p2 = (-18.0 + 12.0 * b + 6.0 * c) / 6.0
    p3 = (12.0 - 9.0 * b - 6.0 * c) / 6.0
    q0 = (8.0 * b + 24.0 * c) / 6.0
    q1 = (-12.0 * b - 48.0 * c) / 6.0
    q2 = (6.0 * b + 30.0 * c) / 6.0
    q3 = (-b - 6.0 * c) / 6.0
    return np.where(
        d < 1.0,
        p0 + p2 * d2 + p3 * d3,
        np.where(d < 2.0, q0 + q1 * d + q2 * d2 + q3 * d3, 0.0),
    )


def _lanczos_kernel(d: np.ndarray, a: int = 3) -> np.ndarray:
    d = np.abs(d)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.sinc(d) * np.sinc(d / a)
    return np.where(d < a, np.where(d == 0, 1.0, out), 0.0)


_KERNELS = {
    "bicubic": (_bicubic_kernel, 2.0),
    "lanczos": (_lanczos_kernel, 3.0),
    "bilinear": (lambda d: np.maximum(0.0, 1.0 - np.abs(d)), 1.0),
}


@functools.lru_cache(maxsize=256)
def make_plan(
    src_size: int, dst_size: int, kernel: str = "lanczos", quantize: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Tap plan for one axis: (indices [dst, K] int32, weights [dst, K] f32).

    Align-centers mapping: src_pos(i) = (i + 0.5) * src/dst - 0.5. For
    downscales the kernel support widens by the scale ratio and weights are
    renormalized (swscale's filter stretching). With quantize=True weights
    are rounded to swscale's 14-bit fixed-point grid, which is what makes
    8-bit outputs land on the same integers as libswscale.
    """
    if kernel not in _KERNELS:
        raise ValueError(f"unknown resize kernel {kernel!r}")
    fn, support = _KERNELS[kernel]
    ratio = src_size / dst_size
    fscale = max(1.0, ratio)
    radius = support * fscale
    ntaps = max(2, int(math.ceil(radius * 2)))
    # even tap counts keep the window symmetric around the center
    if ntaps % 2:
        ntaps += 1

    i = np.arange(dst_size, dtype=np.float64)
    center = (i + 0.5) * ratio - 0.5
    left = np.floor(center).astype(np.int64) - ntaps // 2 + 1
    k = np.arange(ntaps, dtype=np.int64)
    idx = left[:, None] + k[None, :]                   # [dst, K]
    dist = (center[:, None] - idx) / fscale
    w = fn(dist)
    wsum = w.sum(axis=1, keepdims=True)
    w = w / np.where(wsum == 0, 1.0, wsum)
    if quantize:
        # swscale stores coefficients as int16 with 1<<14 == 1.0 and
        # redistributes the rounding remainder so each row sums to 1<<14
        one = 1 << 14
        wq = np.floor(w * one + 0.5).astype(np.int64)
        err = one - wq.sum(axis=1)
        # add the remainder to the largest tap (swscale puts it on the
        # center tap; largest == center for our symmetric windows)
        main = np.argmax(wq, axis=1)
        wq[np.arange(dst_size), main] += err
        w = wq.astype(np.float64) / one
    # clamp taps to the valid range; out-of-range taps replicate the edge
    # (swscale clips filterPos and folds edge weights)
    idx = np.clip(idx, 0, src_size - 1)
    return idx.astype(np.int32), w.astype(np.float32)


# ---------------------------------------------------------------------------
# Block-banded matmul plan (MXU path)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def make_banded_plan(
    src_size: int, dst_size: int, kernel: str = "lanczos", block: int = 128
) -> tuple[np.ndarray, np.ndarray, int]:
    """Re-express the tap plan as block-banded dense matrices for the MXU.

    Tap windows are contiguous and their left edge is monotone in the output
    index, so a block of `block` consecutive output rows only reads a
    contiguous band of input rows. Returns (starts [nblocks] int32,
    weights [nblocks, block, band] f32, band): output block b is
    `weights[b] @ x[starts[b] : starts[b]+band]` — a batched dense matmul
    XLA tiles straight onto the MXU, instead of K per-tap gathers that run
    on the VPU. Weights of taps clipped to the same edge row accumulate, so
    edge replication is preserved exactly.
    """
    idx, w = make_plan(src_size, dst_size, kernel)
    ntaps = idx.shape[1]
    ratio = src_size / dst_size
    nblocks = (dst_size + block - 1) // block
    band = min(int(math.ceil(block * ratio)) + ntaps + 1, src_size)
    starts = np.empty(nblocks, np.int64)
    weights = np.zeros((nblocks, block, band), np.float32)
    for b in range(nblocks):
        i0 = b * block
        i1 = min(i0 + block, dst_size)
        start = max(0, min(int(idx[i0:i1].min()), src_size - band))
        starts[b] = start
        rows = np.repeat(np.arange(i1 - i0), ntaps)
        cols = (idx[i0:i1] - start).reshape(-1)
        np.add.at(weights[b], (rows, cols), w[i0:i1].reshape(-1))
    return starts.astype(np.int32), weights, band


def _banded_axis_last(x: jnp.ndarray, src: int, dst: int, kernel: str) -> jnp.ndarray:
    """[..., src] -> [..., dst] via per-block band gather + batched matmul."""
    starts, weights, band = make_banded_plan(src, dst, kernel)
    nblocks, block, _ = weights.shape
    band_idx = jnp.asarray(starts)[:, None] + jnp.arange(band)[None, :]
    xb = x[..., band_idx]                                  # [..., n, band]
    out = jnp.einsum(
        "...nk,nbk->...nb", xb, jnp.asarray(weights),
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out.reshape(x.shape[:-1] + (nblocks * block,))
    return out[..., :dst]


def _banded_axis_rows(x: jnp.ndarray, src: int, dst: int, kernel: str) -> jnp.ndarray:
    """[..., src, W] -> [..., dst, W]: band gather of whole rows + matmul."""
    starts, weights, band = make_banded_plan(src, dst, kernel)
    nblocks, block, _ = weights.shape
    band_idx = jnp.asarray(starts)[:, None] + jnp.arange(band)[None, :]
    xb = jnp.take(x, band_idx.reshape(-1), axis=-2)
    xb = xb.reshape(x.shape[:-2] + (nblocks, band, x.shape[-1]))
    out = jnp.einsum(
        "nbk,...nkw->...nbw", jnp.asarray(weights), xb,
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out.reshape(x.shape[:-2] + (nblocks * block, x.shape[-1]))
    return out[..., :dst, :]


# ---------------------------------------------------------------------------
# Device-side resampling
# ---------------------------------------------------------------------------


def _apply_axis(x: jnp.ndarray, idx: jnp.ndarray, w: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Weighted gather along one axis: out[..., i, ...] = Σ_k w[i,k] ·
    x[..., idx[i,k], ...]. K is static → unrolled into K fused FMAs."""
    ntaps = idx.shape[1]
    out = None
    for k in range(ntaps):
        sl = jnp.take(x, idx[:, k], axis=axis)
        wk = w[:, k]
        shape = [1] * x.ndim
        shape[axis] = wk.shape[0]
        term = sl * wk.reshape(shape)
        out = term if out is None else out + term
    return out


def resize_plane(
    x: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    quantize_output: bool = True,
    method: str = "auto",
) -> jnp.ndarray:
    """Resize [..., H, W] planes to [..., dst_h, dst_w].

    Input uint8/uint16 or float; output uint8 quantized with swscale's
    round-half-up when quantize_output and input was integer, else float32.

    method:
      "gather" — K per-tap gathers + FMAs (VPU; bit-exact vs libswscale,
                 the golden-test reference path).
      "banded" — block-banded dense matmuls (MXU; see make_banded_plan).
                 f32 accumulation order differs, so round-half-up ties can
                 land one code value away (measured ≤1 LSB on ~4 px per
                 million vs "gather").
      "fused"  — the Pallas two-pass kernel (pallas_kernels.resize_frames_
                 fused): both passes in VMEM, no HBM intermediate. TPU only,
                 [T, H, W] integer input, quantized output.
      "auto"   — "banded" on TPU (where the MXU pays for it), "gather"
                 elsewhere; override with PC_RESIZE_METHOD=gather|banded|fused.
    """
    if method == "auto":
        method = os.environ.get("PC_RESIZE_METHOD") or (
            "banded" if jax.default_backend() == "tpu" else "gather"
        )
    src_h, src_w = x.shape[-2], x.shape[-1]
    integer_in = jnp.issubdtype(x.dtype, jnp.integer)
    if method == "fused" and (src_h, src_w) != (dst_h, dst_w):
        if x.ndim != 3 or not integer_in or not quantize_output:
            raise ValueError(
                "method='fused' needs [T, H, W] integer input with "
                "quantize_output (got shape %r, dtype %s)" % (x.shape, x.dtype)
            )
        from . import pallas_kernels  # deferred: pallas_kernels imports us

        return pallas_kernels.resize_frames_fused(
            x, dst_h, dst_w, kernel,
            interpret=not pallas_kernels.pallas_available(),
        )
    xf = x.astype(jnp.float32)
    if (src_h, src_w) != (dst_h, dst_w):
        if method == "banded":
            xf = _banded_axis_rows(xf, src_h, dst_h, kernel)
            xf = _banded_axis_last(xf, src_w, dst_w, kernel)
        elif method != "gather":
            raise ValueError(f"unknown resize method {method!r}")
        else:
            idx_v, w_v = make_plan(src_h, dst_h, kernel)
            idx_h, w_h = make_plan(src_w, dst_w, kernel)
            xf = _apply_axis(xf, jnp.asarray(idx_v), jnp.asarray(w_v), x.ndim - 2)
            xf = _apply_axis(xf, jnp.asarray(idx_h), jnp.asarray(w_h), x.ndim - 1)
    if integer_in and quantize_output:
        maxval = 255 if x.dtype == jnp.uint8 else 1023
        out = jnp.clip(jnp.floor(xf + 0.5), 0, maxval)
        return out.astype(x.dtype)
    return xf


@functools.partial(jax.jit, static_argnames=("dst_h", "dst_w", "kernel", "method"))
def resize_frames(
    frames: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    method: str = "auto",
) -> jnp.ndarray:
    """Batched resize of [T, H, W] (or [H, W]) planes — the jitted entry the
    AVPVS pipeline uses per plane."""
    return resize_plane(frames, dst_h, dst_w, kernel, method=method)


def resize_yuv(
    planes: tuple[jnp.ndarray, ...],
    dst_h: int,
    dst_w: int,
    pix_fmt: str = "yuv420p",
    kernel: str = "lanczos",
    method: str = "auto",
) -> tuple[jnp.ndarray, ...]:
    """Resize a planar YUV frame set: luma to (dst_h, dst_w), chroma planes
    to the subsampled grid of `pix_fmt`."""
    sub_w = 2 if ("420" in pix_fmt or "422" in pix_fmt) else 1
    sub_h = 2 if "420" in pix_fmt else 1
    out = [resize_plane(planes[0], dst_h, dst_w, kernel, method=method)]
    for p in planes[1:3]:
        out.append(
            resize_plane(p, dst_h // sub_h, dst_w // sub_w, kernel, method=method)
        )
    return tuple(out)
