"""Separable polyphase resampling on device — the chain's hottest op.

TPU-native replacement for the reference's swscale `scale=W:H:flags=bicubic`
/ `flags=lanczos` filters (reference lib/ffmpeg.py:948, :1037, :1196).
Filter construction mirrors libswscale's: align-centers source mapping,
BC-spline bicubic with the swscale default (B=0, C=0.6), Lanczos-3, support
widening + renormalization for downscale. The tap plan (indices + weights)
is precomputed on host per (src, dst, kernel) and cached; the device side is
K fused multiply-adds over gathered rows/columns — bandwidth-bound, VPU
friendly, vmappable over frames and planes.

Golden-tested against libswscale output (io.medialib.sws_scale_plane).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Host-side filter construction
# ---------------------------------------------------------------------------


def _bicubic_kernel(d: np.ndarray, b: float = 0.0, c: float = 0.6) -> np.ndarray:
    """Mitchell-Netravali BC-spline; swscale's bicubic uses B=0, C=0.6 by
    default (libswscale/utils.c initFilter)."""
    d = np.abs(d)
    d2, d3 = d * d, d * d * d
    p0 = (6.0 - 2.0 * b) / 6.0
    p2 = (-18.0 + 12.0 * b + 6.0 * c) / 6.0
    p3 = (12.0 - 9.0 * b - 6.0 * c) / 6.0
    q0 = (8.0 * b + 24.0 * c) / 6.0
    q1 = (-12.0 * b - 48.0 * c) / 6.0
    q2 = (6.0 * b + 30.0 * c) / 6.0
    q3 = (-b - 6.0 * c) / 6.0
    return np.where(
        d < 1.0,
        p0 + p2 * d2 + p3 * d3,
        np.where(d < 2.0, q0 + q1 * d + q2 * d2 + q3 * d3, 0.0),
    )


def _lanczos_kernel(d: np.ndarray, a: int = 3) -> np.ndarray:
    d = np.abs(d)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.sinc(d) * np.sinc(d / a)
    return np.where(d < a, np.where(d == 0, 1.0, out), 0.0)


_KERNELS = {
    "bicubic": (_bicubic_kernel, 2.0),
    "lanczos": (_lanczos_kernel, 3.0),
    "bilinear": (lambda d: np.maximum(0.0, 1.0 - np.abs(d)), 1.0),
}


@functools.lru_cache(maxsize=256)
def make_plan(
    src_size: int, dst_size: int, kernel: str = "lanczos", quantize: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Tap plan for one axis: (indices [dst, K] int32, weights [dst, K] f32).

    Align-centers mapping: src_pos(i) = (i + 0.5) * src/dst - 0.5. For
    downscales the kernel support widens by the scale ratio and weights are
    renormalized (swscale's filter stretching). With quantize=True weights
    are rounded to swscale's 14-bit fixed-point grid, which is what makes
    8-bit outputs land on the same integers as libswscale.
    """
    if kernel not in _KERNELS:
        raise ValueError(f"unknown resize kernel {kernel!r}")
    fn, support = _KERNELS[kernel]
    ratio = src_size / dst_size
    fscale = max(1.0, ratio)
    radius = support * fscale
    ntaps = max(2, int(math.ceil(radius * 2)))
    # even tap counts keep the window symmetric around the center
    if ntaps % 2:
        ntaps += 1

    i = np.arange(dst_size, dtype=np.float64)
    center = (i + 0.5) * ratio - 0.5
    left = np.floor(center).astype(np.int64) - ntaps // 2 + 1
    k = np.arange(ntaps, dtype=np.int64)
    idx = left[:, None] + k[None, :]                   # [dst, K]
    dist = (center[:, None] - idx) / fscale
    w = fn(dist)
    wsum = w.sum(axis=1, keepdims=True)
    w = w / np.where(wsum == 0, 1.0, wsum)
    if quantize:
        # swscale stores coefficients as int16 with 1<<14 == 1.0 and
        # redistributes the rounding remainder so each row sums to 1<<14
        one = 1 << 14
        wq = np.floor(w * one + 0.5).astype(np.int64)
        err = one - wq.sum(axis=1)
        # add the remainder to the largest tap (swscale puts it on the
        # center tap; largest == center for our symmetric windows)
        main = np.argmax(wq, axis=1)
        wq[np.arange(dst_size), main] += err
        w = wq.astype(np.float64) / one
    # clamp taps to the valid range; out-of-range taps replicate the edge
    # (swscale clips filterPos and folds edge weights)
    idx = np.clip(idx, 0, src_size - 1)
    return idx.astype(np.int32), w.astype(np.float32)


# ---------------------------------------------------------------------------
# Device-side resampling
# ---------------------------------------------------------------------------


def _apply_axis(x: jnp.ndarray, idx: jnp.ndarray, w: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Weighted gather along one axis: out[..., i, ...] = Σ_k w[i,k] ·
    x[..., idx[i,k], ...]. K is static → unrolled into K fused FMAs."""
    ntaps = idx.shape[1]
    out = None
    for k in range(ntaps):
        sl = jnp.take(x, idx[:, k], axis=axis)
        wk = w[:, k]
        shape = [1] * x.ndim
        shape[axis] = wk.shape[0]
        term = sl * wk.reshape(shape)
        out = term if out is None else out + term
    return out


def resize_plane(
    x: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    quantize_output: bool = True,
) -> jnp.ndarray:
    """Resize [..., H, W] planes to [..., dst_h, dst_w].

    Input uint8/uint16 or float; output uint8 quantized with swscale's
    round-half-up when quantize_output and input was integer, else float32.
    """
    src_h, src_w = x.shape[-2], x.shape[-1]
    integer_in = jnp.issubdtype(x.dtype, jnp.integer)
    xf = x.astype(jnp.float32)
    if (src_h, src_w) != (dst_h, dst_w):
        idx_v, w_v = make_plan(src_h, dst_h, kernel)
        idx_h, w_h = make_plan(src_w, dst_w, kernel)
        xf = _apply_axis(xf, jnp.asarray(idx_v), jnp.asarray(w_v), x.ndim - 2)
        xf = _apply_axis(xf, jnp.asarray(idx_h), jnp.asarray(w_h), x.ndim - 1)
    if integer_in and quantize_output:
        maxval = 255 if x.dtype == jnp.uint8 else 1023
        out = jnp.clip(jnp.floor(xf + 0.5), 0, maxval)
        return out.astype(x.dtype)
    return xf


@functools.partial(jax.jit, static_argnames=("dst_h", "dst_w", "kernel"))
def resize_frames(
    frames: jnp.ndarray, dst_h: int, dst_w: int, kernel: str = "lanczos"
) -> jnp.ndarray:
    """Batched resize of [T, H, W] (or [H, W]) planes — the jitted entry the
    AVPVS pipeline uses per plane."""
    return resize_plane(frames, dst_h, dst_w, kernel)


def resize_yuv(
    planes: tuple[jnp.ndarray, ...],
    dst_h: int,
    dst_w: int,
    pix_fmt: str = "yuv420p",
    kernel: str = "lanczos",
) -> tuple[jnp.ndarray, ...]:
    """Resize a planar YUV frame set: luma to (dst_h, dst_w), chroma planes
    to the subsampled grid of `pix_fmt`."""
    sub_w = 2 if ("420" in pix_fmt or "422" in pix_fmt) else 1
    sub_h = 2 if "420" in pix_fmt else 1
    out = [resize_plane(planes[0], dst_h, dst_w, kernel)]
    for p in planes[1:3]:
        out.append(resize_plane(p, dst_h // sub_h, dst_w // sub_w, kernel))
    return tuple(out)
