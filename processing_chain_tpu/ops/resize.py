"""Separable polyphase resampling on device — the chain's hottest op.

TPU-native replacement for the reference's swscale `scale=W:H:flags=bicubic`
/ `flags=lanczos` filters (reference lib/ffmpeg.py:948, :1037, :1196).
Filter construction mirrors libswscale's: align-centers source mapping,
BC-spline bicubic with the swscale default (B=0, C=0.6), Lanczos-3, support
widening + renormalization for downscale. The tap plan (indices + weights)
is precomputed on host per (src, dst, kernel) and cached; the device side is
K fused multiply-adds over gathered rows/columns — bandwidth-bound, VPU
friendly, vmappable over frames and planes.

Golden-tested against libswscale output (io.medialib.sws_scale_plane).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Host-side filter construction
# ---------------------------------------------------------------------------


def _bicubic_kernel(d: np.ndarray, b: float = 0.0, c: float = 0.6) -> np.ndarray:
    """Mitchell-Netravali BC-spline; swscale's bicubic uses B=0, C=0.6 by
    default (libswscale/utils.c initFilter)."""
    d = np.abs(d)
    d2, d3 = d * d, d * d * d
    p0 = (6.0 - 2.0 * b) / 6.0
    p2 = (-18.0 + 12.0 * b + 6.0 * c) / 6.0
    p3 = (12.0 - 9.0 * b - 6.0 * c) / 6.0
    q0 = (8.0 * b + 24.0 * c) / 6.0
    q1 = (-12.0 * b - 48.0 * c) / 6.0
    q2 = (6.0 * b + 30.0 * c) / 6.0
    q3 = (-b - 6.0 * c) / 6.0
    return np.where(
        d < 1.0,
        p0 + p2 * d2 + p3 * d3,
        np.where(d < 2.0, q0 + q1 * d + q2 * d2 + q3 * d3, 0.0),
    )


def _lanczos_kernel(d: np.ndarray, a: int = 3) -> np.ndarray:
    d = np.abs(d)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = np.sinc(d) * np.sinc(d / a)
    return np.where(d < a, np.where(d == 0, 1.0, out), 0.0)


_KERNELS = {
    "bicubic": (_bicubic_kernel, 2.0),
    "lanczos": (_lanczos_kernel, 3.0),
    "bilinear": (lambda d: np.maximum(0.0, 1.0 - np.abs(d)), 1.0),
}


@functools.lru_cache(maxsize=256)
def make_plan(
    src_size: int, dst_size: int, kernel: str = "lanczos", quantize: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Tap plan for one axis: (indices [dst, K] int32, weights [dst, K] f32).

    Align-centers mapping: src_pos(i) = (i + 0.5) * src/dst - 0.5. For
    downscales the kernel support widens by the scale ratio and weights are
    renormalized (swscale's filter stretching). With quantize=True weights
    are rounded to swscale's 14-bit fixed-point grid, which is what makes
    8-bit outputs land on the same integers as libswscale.
    """
    if kernel not in _KERNELS:
        raise ValueError(f"unknown resize kernel {kernel!r}")
    if (
        quantize
        and kernel in _SWSCALE_EXACT_KERNELS
        and src_size != dst_size
        and src_size / dst_size <= _SWSCALE_EXACT_MAX_RATIO
    ):
        # share the exact libswscale geometry (positions, edge-tap
        # reduction, border folding, 14-bit error-diffused weights) so the
        # float paths (banded/fused) differ from the golden integer path
        # only by float accumulation rounding — including at borders
        idx, co = _swscale_tap_matrix(src_size, dst_size, kernel, 1 << 14)
        return idx, (co.astype(np.float64) / (1 << 14)).astype(np.float32)
    fn, support = _KERNELS[kernel]
    ratio = src_size / dst_size
    fscale = max(1.0, ratio)
    radius = support * fscale
    ntaps = max(2, int(math.ceil(radius * 2)))
    # even tap counts keep the window symmetric around the center
    if ntaps % 2:
        ntaps += 1

    i = np.arange(dst_size, dtype=np.float64)
    center = (i + 0.5) * ratio - 0.5
    left = np.floor(center).astype(np.int64) - ntaps // 2 + 1
    k = np.arange(ntaps, dtype=np.int64)
    idx = left[:, None] + k[None, :]                   # [dst, K]
    dist = (center[:, None] - idx) / fscale
    w = fn(dist)
    wsum = w.sum(axis=1, keepdims=True)
    w = w / np.where(wsum == 0, 1.0, wsum)
    if quantize:
        # swscale stores coefficients as int16 with 1<<14 == 1.0 and
        # redistributes the rounding remainder so each row sums to 1<<14
        one = 1 << 14
        wq = np.floor(w * one + 0.5).astype(np.int64)
        err = one - wq.sum(axis=1)
        # add the remainder to the largest tap (swscale puts it on the
        # center tap; largest == center for our symmetric windows)
        main = np.argmax(wq, axis=1)
        wq[np.arange(dst_size), main] += err
        w = wq.astype(np.float64) / one
    # clamp taps to the valid range; out-of-range taps replicate the edge
    # (swscale clips filterPos and folds edge weights)
    idx = np.clip(idx, 0, src_size - 1)
    return idx.astype(np.int32), w.astype(np.float32)


# ---------------------------------------------------------------------------
# Exact libswscale integer plans (golden path)
# ---------------------------------------------------------------------------
#
# Reconstruction of libswscale's initFilter (libswscale/utils.c) +
# hScale8To15 + yuv2planeX_8 integer pipeline, validated bit-exact against
# the installed libswscale under SWS_ACCURATE_RND|SWS_BITEXACT (its
# deterministic C reference path) on noise inputs across up/downscales
# including the 1080p->4K north-star ratio (tests/test_ops.py).
#
# Spec note (why ACCURATE_RND is the oracle): without SWS_ACCURATE_RND,
# libswscale dispatches CPU-dependent SIMD kernels (SSE/AVX pmulhw-style
# per-tap truncation in the vertical pass) whose output differs from its
# own C reference by ±1 LSB and is not stable across hosts — measured here:
# default-flags output vs ACCURATE_RND output deviates by exactly <=1 on
# noise. "Bit-exact vs libswscale" is therefore only well-defined against
# the C path; vs default flags the contract is <=1 LSB.

_SWSCALE_EXACT_KERNELS = ("lanczos", "bicubic")
_SWSCALE_EXACT_MAX_RATIO = 16.0  # validated envelope; chain max is ~8x


def _trunc_div(a: int, b: int) -> int:
    q, r = divmod(a, b)
    if r != 0 and (a < 0) != (b < 0):
        q += 1
    return q


@functools.lru_cache(maxsize=256)
def make_swscale_plan(
    src_size: int, dst_size: int, kernel: str, one: int
) -> tuple[np.ndarray, np.ndarray]:
    """libswscale initFilter reconstruction for one axis.

    Returns (pos [dst] int32, coeffs [dst, K] int32) where output i is
    sum_k src[clip(pos[i]+k)] * coeffs[i, k] at `one` fixed-point scale
    (1<<14 horizontal, 1<<12 vertical — swscale's hLumFilter/vLumFilter
    scales). Mirrors utils.c: 16.16 xInc source mapping, double-precision
    kernel eval scaled to fone=2^(54-min(log2(ratio),8)), cumulative-cutoff
    edge-tap reduction (SWS_MAX_REDUCE_CUTOFF=0.002), border folding onto
    edge taps, and sum-preserving error-diffusion quantization
    (ROUNDED_DIV with carried remainder).
    """
    x_inc = ((src_size << 16) + (dst_size >> 1)) // dst_size
    if abs(x_inc - 0x10000) < 10:  # identity
        pos = np.arange(dst_size, dtype=np.int32)
        return pos, np.full((dst_size, 1), one, dtype=np.int32)

    srcW, dstW = src_size, dst_size
    ratio_log2 = (srcW // dstW).bit_length() - 1 if srcW // dstW > 0 else 0
    fone = 1 << (54 - min(ratio_log2, 8))
    size_factor = {"lanczos": 6, "bicubic": 4}[kernel]
    if x_inc <= 1 << 16:
        filter_size = 1 + size_factor
    else:
        filter_size = 1 + (size_factor * srcW + dstW - 1) // dstW
    filter_size = max(min(filter_size, srcW - 2), 1)

    filt = np.zeros((dstW, filter_size), dtype=np.int64)
    fpos = np.zeros(dstW, dtype=np.int64)
    # center_i = (i+0.5)*ratio - 0.5 tracked in 1/2^17 px (utils.c xDstInSrc)
    xDstInSrc = x_inc - 65536
    for i in range(dstW):
        xx = _trunc_div(xDstInSrc - (filter_size - 2) * 65536, 131072)
        fpos[i] = xx
        for j in range(filter_size):
            d = abs((xx + j) * 131072 - xDstInSrc) << 13  # 1/2^30 px
            if x_inc > 1 << 16:
                d = d * dstW // srcW  # downscale kernel stretch
            floatd = d * (1.0 / (1 << 30))
            if kernel == "bicubic":
                B, C = 0, int(0.6 * (1 << 24))
                if d >= 1 << 31:
                    coeff = 0
                else:
                    dd = (d * d) >> 30
                    ddd = (dd * d) >> 30
                    if d < 1 << 30:
                        coeff = (
                            (12 * (1 << 24) - 9 * B - 6 * C) * ddd
                            + (-18 * (1 << 24) + 12 * B + 6 * C) * dd
                            + (6 * (1 << 24) - 2 * B) * (1 << 30)
                        )
                    else:
                        coeff = (
                            (-B - 6 * C) * ddd
                            + (6 * B + 30 * C) * dd
                            + (-12 * B - 48 * C) * d
                            + (8 * B + 24 * C) * (1 << 30)
                        )
                    coeff = coeff // ((1 << 54) // fone)
            else:  # lanczos, p=3
                if floatd == 0.0:
                    coeff = int(fone)
                elif floatd > 3.0:
                    coeff = 0
                else:
                    v = (
                        math.sin(floatd * math.pi)
                        * math.sin(floatd * math.pi / 3.0)
                        / (floatd * floatd * math.pi * math.pi / 3.0)
                    )
                    coeff = int(v * fone)  # C double->int64 truncates
            filt[i, j] = coeff
        xDstInSrc += 2 * x_inc

    # reduce: trim near-zero edge taps (cumulative |coeff| cutoff 0.002)
    cutoff = int(0.002 * fone)
    min_filter_size = 0
    for i in range(dstW - 1, -1, -1):
        mn = filter_size
        cut = 0
        # bounded like initFilter's C loop: an all-zero coefficient row on
        # the last output index would otherwise never hit either break
        for _ in range(filter_size):
            cut += abs(int(filt[i, 0]))
            if cut > cutoff:
                break
            if i < dstW - 1 and fpos[i] >= fpos[i + 1]:
                break
            filt[i, :-1] = filt[i, 1:]
            filt[i, -1] = 0
            fpos[i] += 1
        cut = 0
        for j in range(filter_size - 1, 0, -1):
            cut += abs(int(filt[i, j]))
            if cut > cutoff:
                break
            mn -= 1
        min_filter_size = max(min_filter_size, mn)
    filt = filt[:, :min_filter_size]
    filter_size = min_filter_size

    # fix borders: fold out-of-range taps onto the edge samples
    for i in range(dstW):
        if fpos[i] < 0:
            g = np.zeros(filter_size, dtype=np.int64)
            for j in range(filter_size):
                g[max(j + int(fpos[i]), 0)] += filt[i, j]
            filt[i] = g
            fpos[i] = 0
        if fpos[i] + filter_size > srcW:
            shift = int(fpos[i] + min(filter_size - srcW, 0))
            g = filt[i].copy()
            acc = 0
            for j in range(filter_size - 1, -1, -1):
                if fpos[i] + j >= srcW:
                    acc += g[j]
                    g[j] = 0
            g2 = np.zeros(filter_size, dtype=np.int64)
            g2[shift:] = g[: filter_size - shift] if shift > 0 else g
            fpos[i] -= shift
            g2[srcW - 1 - int(fpos[i])] += acc
            filt[i] = g2

    # normalize + quantize with error diffusion (sum preserved per row)
    out = np.zeros((dstW, filter_size), dtype=np.int32)
    for i in range(dstW):
        s = (int(filt[i].sum()) + one // 2) // one
        if s == 0:
            s = 1
        err = 0
        for j in range(filter_size):
            v = int(filt[i, j]) + err
            iv = _trunc_div(v + (s >> 1) if v >= 0 else v - (s >> 1), s)
            out[i, j] = iv
            err = v - iv * s
    return fpos.astype(np.int32), out


def _swscale_tap_matrix(
    src_size: int, dst_size: int, kernel: str, one: int
) -> tuple[np.ndarray, np.ndarray]:
    """Expand a make_swscale_plan into a clipped [dst, K] index matrix +
    int32 coeffs, the _apply_axis input shape. Out-of-range taps (always
    zero-coefficient after border folding) clip to the edge sample."""
    pos, co = make_swscale_plan(src_size, dst_size, kernel, one)
    k = co.shape[1]
    idx = np.clip(
        pos[:, None].astype(np.int64) + np.arange(k)[None, :], 0, src_size - 1
    )
    return idx.astype(np.int32), co


def swscale_exact_applicable(
    src_h: int, src_w: int, dst_h: int, dst_w: int, kernel: str
) -> bool:
    return (
        kernel in _SWSCALE_EXACT_KERNELS
        and src_h / dst_h <= _SWSCALE_EXACT_MAX_RATIO
        and src_w / dst_w <= _SWSCALE_EXACT_MAX_RATIO
    )


def _swscale_exact(
    x: jnp.ndarray, dst_h: int, dst_w: int, kernel: str
) -> jnp.ndarray:
    """uint8 [..., H, W] -> uint8 [..., dst_h, dst_w], bit-exact vs the
    libswscale C reference path (SWS_ACCURATE_RND|SWS_BITEXACT).

    Integer pipeline, horizontal first like swscale: hScale8To15
    (int32 MAC of 14-bit coeffs, >>7 arithmetic, clip top to 32767), then
    yuv2planeX_8 (int32 MAC of 12-bit coeffs + dither 64<<12, >>19, clip
    to u8). The identity-axis case degenerates to the same formulas
    (yuv2plane1's (v+64)>>7 == (v<<12 + 64<<12)>>19).
    """
    src_h, src_w = x.shape[-2], x.shape[-1]
    idx_h, hco = _swscale_tap_matrix(src_w, dst_w, kernel, 1 << 14)
    idx_v, vco = _swscale_tap_matrix(src_h, dst_h, kernel, 1 << 12)
    xi = x.astype(jnp.int32)
    inter = _apply_axis(xi, jnp.asarray(idx_h), jnp.asarray(hco), x.ndim - 1)
    inter = jnp.minimum(jnp.right_shift(inter, 7), 32767)
    val = _apply_axis(inter, jnp.asarray(idx_v), jnp.asarray(vco), x.ndim - 2)
    out = jnp.right_shift(val + (64 << 12), 19)
    return jnp.clip(out, 0, 255).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Block-banded matmul plan (MXU path)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def make_banded_plan(
    src_size: int, dst_size: int, kernel: str = "lanczos", block: int = 128
) -> tuple[np.ndarray, np.ndarray, int]:
    """Re-express the tap plan as block-banded dense matrices for the MXU.

    Tap windows are contiguous and their left edge is monotone in the output
    index, so a block of `block` consecutive output rows only reads a
    contiguous band of input rows. Returns (starts [nblocks] int32,
    weights [nblocks, block, band] f32, band): output block b is
    `weights[b] @ x[starts[b] : starts[b]+band]` — a batched dense matmul
    XLA tiles straight onto the MXU, instead of K per-tap gathers that run
    on the VPU. Weights of taps clipped to the same edge row accumulate, so
    edge replication is preserved exactly.
    """
    idx, w = make_plan(src_size, dst_size, kernel)
    ntaps = idx.shape[1]
    ratio = src_size / dst_size
    nblocks = (dst_size + block - 1) // block
    band = min(int(math.ceil(block * ratio)) + ntaps + 1, src_size)
    starts = np.empty(nblocks, np.int64)
    weights = np.zeros((nblocks, block, band), np.float32)
    for b in range(nblocks):
        i0 = b * block
        i1 = min(i0 + block, dst_size)
        start = max(0, min(int(idx[i0:i1].min()), src_size - band))
        starts[b] = start
        rows = np.repeat(np.arange(i1 - i0), ntaps)
        cols = (idx[i0:i1] - start).reshape(-1)
        np.add.at(weights[b], (rows, cols), w[i0:i1].reshape(-1))
    return starts.astype(np.int32), weights, band


def _banded_axis_last(x: jnp.ndarray, src: int, dst: int, kernel: str) -> jnp.ndarray:
    """[..., src] -> [..., dst] via per-block band gather + batched matmul."""
    starts, weights, band = make_banded_plan(src, dst, kernel)
    nblocks, block, _ = weights.shape
    band_idx = jnp.asarray(starts)[:, None] + jnp.arange(band)[None, :]
    xb = x[..., band_idx]                                  # [..., n, band]
    out = jnp.einsum(
        "...nk,nbk->...nb", xb, jnp.asarray(weights),
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out.reshape(x.shape[:-1] + (nblocks * block,))
    return out[..., :dst]


def _banded_axis_rows(x: jnp.ndarray, src: int, dst: int, kernel: str) -> jnp.ndarray:
    """[..., src, W] -> [..., dst, W]: band gather of whole rows + matmul."""
    starts, weights, band = make_banded_plan(src, dst, kernel)
    nblocks, block, _ = weights.shape
    band_idx = jnp.asarray(starts)[:, None] + jnp.arange(band)[None, :]
    xb = jnp.take(x, band_idx.reshape(-1), axis=-2)
    xb = xb.reshape(x.shape[:-2] + (nblocks, band, x.shape[-1]))
    out = jnp.einsum(
        "nbk,...nkw->...nbw", jnp.asarray(weights), xb,
        precision=jax.lax.Precision.HIGHEST,
    )
    out = out.reshape(x.shape[:-2] + (nblocks * block, x.shape[-1]))
    return out[..., :dst, :]


# ---------------------------------------------------------------------------
# Device-side resampling
# ---------------------------------------------------------------------------


def _apply_axis(x: jnp.ndarray, idx: jnp.ndarray, w: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Weighted gather along one axis: out[..., i, ...] = Σ_k w[i,k] ·
    x[..., idx[i,k], ...]. K is static → unrolled into K fused FMAs."""
    ntaps = idx.shape[1]
    out = None
    for k in range(ntaps):
        sl = jnp.take(x, idx[:, k], axis=axis)
        wk = w[:, k]
        shape = [1] * x.ndim
        shape[axis] = wk.shape[0]
        term = sl * wk.reshape(shape)
        out = term if out is None else out + term
    return out


def plan_resize_method() -> str:
    """The resize-method identity plan payloads record (plan-purity
    rule, store/plan_schema.py). "gather" is bit-exact against the
    swscale reference; "banded"/"fused" differ from it by up to one code
    value per pixel — so the method IS a byte-affecting input and must
    split cache keys. Returns the PC_RESIZE_METHOD override when set,
    else "auto:<backend>": the auto default resolves per backend (TPU →
    fused/banded, elsewhere gather), so artifacts built on different
    backends must not share a plan hash either."""
    env = os.environ.get("PC_RESIZE_METHOD")
    if env:
        return env.strip().lower()
    import jax

    return "auto:" + jax.default_backend()


def resize_plane(
    x: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    quantize_output: bool = True,
    method: str = "auto",
) -> jnp.ndarray:
    """Resize [..., H, W] planes to [..., dst_h, dst_w].

    Input uint8/uint16 or float; output uint8 quantized with swscale's
    round-half-up when quantize_output and input was integer, else float32.

    method:
      "gather" — for u8 lanczos/bicubic: the exact libswscale integer
                 pipeline (_swscale_exact; bit-exact vs the C reference
                 path, the golden-test contract). Otherwise K per-tap
                 float gathers + FMAs (VPU).
      "banded" — block-banded dense matmuls (MXU; see make_banded_plan).
                 Same geometry + intermediate clamp as the golden path but
                 f32 arithmetic with 14-bit weights on both axes (the exact
                 path's vertical stage is 12-bit), so ~1-2% of noise pixels
                 land one code value away (measured; never more than 1).
      "fused"  — the Pallas two-pass kernel (pallas_kernels.resize_frames_
                 fused): both passes in VMEM, no HBM intermediate. TPU only,
                 [T, H, W] integer input, quantized output. Same tolerance
                 class as "banded" vs the golden path (≤1 code value,
                 measured on TPU); differs from "banded" itself only on
                 rounding-tie pixels (different f32 accumulation order).
      "auto"   — on TPU: "fused" where eligible ([T, H, W] integer input,
                 quantized, actually resizing), else "banded"; "gather"
                 elsewhere; override with PC_RESIZE_METHOD=gather|banded|fused.
    """
    src_h, src_w = x.shape[-2], x.shape[-1]
    integer_in = jnp.issubdtype(x.dtype, jnp.integer)
    if method == "auto":
        env = os.environ.get("PC_RESIZE_METHOD")
        if env:
            method = env
        elif jax.default_backend() == "tpu":
            fused_ok = (
                x.ndim == 3 and integer_in and quantize_output
                and (src_h, src_w) != (dst_h, dst_w)
            )
            method = "fused" if fused_ok else "banded"
        else:
            method = "gather"
    if method == "fused" and (src_h, src_w) != (dst_h, dst_w):
        if x.ndim != 3 or not integer_in or not quantize_output:
            raise ValueError(
                "method='fused' needs [T, H, W] integer input with "
                "quantize_output (got shape %r, dtype %s)" % (x.shape, x.dtype)
            )
        from . import pallas_kernels  # deferred: pallas_kernels imports us

        return pallas_kernels.resize_frames_fused(
            x, dst_h, dst_w, kernel,
            interpret=not pallas_kernels.pallas_available(),
        )
    if (
        method == "gather"
        and (src_h, src_w) != (dst_h, dst_w)
        and x.dtype == jnp.uint8
        and quantize_output
        and swscale_exact_applicable(src_h, src_w, dst_h, dst_w, kernel)
    ):
        # golden path: bit-exact vs libswscale's C reference (see
        # make_swscale_plan); float gather remains for 10-bit/float inputs
        return _swscale_exact(x, dst_h, dst_w, kernel)
    xf = x.astype(jnp.float32)
    if (src_h, src_w) != (dst_h, dst_w):
        if method == "banded":
            # swscale order: horizontal first, then its 15-bit intermediate
            # top-clamp (32767/128 in normalized units) — without it Lanczos
            # overshoot on noise diverges from the golden path by dozens of
            # code values (the oracle clamps in hScale8To15)
            xf = _banded_axis_last(xf, src_w, dst_w, kernel)
            if x.dtype == jnp.uint8:
                xf = jnp.minimum(xf, 32767.0 / 128.0)
            xf = _banded_axis_rows(xf, src_h, dst_h, kernel)
        elif method != "gather":
            raise ValueError(f"unknown resize method {method!r}")
        else:
            idx_v, w_v = make_plan(src_h, dst_h, kernel)
            idx_h, w_h = make_plan(src_w, dst_w, kernel)
            xf = _apply_axis(xf, jnp.asarray(idx_v), jnp.asarray(w_v), x.ndim - 2)
            xf = _apply_axis(xf, jnp.asarray(idx_h), jnp.asarray(w_h), x.ndim - 1)
    if integer_in and quantize_output:
        maxval = 255 if x.dtype == jnp.uint8 else 1023
        out = jnp.clip(jnp.floor(xf + 0.5), 0, maxval)
        return out.astype(x.dtype)
    return xf


@functools.partial(jax.jit, static_argnames=("dst_h", "dst_w", "kernel", "method"))
def _resize_frames_jit(
    frames: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    method: str = "auto",
) -> jnp.ndarray:
    return resize_plane(frames, dst_h, dst_w, kernel, method=method)


_SWS_KERNEL_FLAGS = {"lanczos": "SWS_LANCZOS", "bicubic": "SWS_BICUBIC"}


def _native_swscale_eligible(frames, dst_h: int, dst_w: int, kernel: str) -> bool:
    """True when the CONCRETE u8 stack can take the native libswscale
    fast path: CPU backend only (on an accelerator the device kernels
    win), eager callers only (inside a trace the array is abstract and
    native code unreachable), and within the bit-exactness envelope the
    XLA golden path itself honors. PC_RESIZE_METHOD pins a method — the
    operator asked to measure THAT path, so native stays out; the
    PC_HOST_BATCH=0 fallback switch disables it too (the pooled-vs-
    per-frame parity tests diff the two whole pipelines)."""
    import jax.core

    if isinstance(frames, jax.core.Tracer):
        return False
    if getattr(frames, "ndim", 0) != 3 or frames.dtype != jnp.uint8:
        return False
    src_h, src_w = frames.shape[-2], frames.shape[-1]
    if (src_h, src_w) == (dst_h, dst_w):
        return False
    if kernel not in _SWS_KERNEL_FLAGS:
        return False
    if not swscale_exact_applicable(src_h, src_w, dst_h, dst_w, kernel):
        return False
    if os.environ.get("PC_RESIZE_METHOD"):
        return False
    if jax.default_backend() != "cpu":
        return False
    from ..io import bufpool

    if not bufpool.host_batch_enabled():
        return False
    try:
        from ..io import medialib

        medialib.ensure_loaded()
    except Exception:
        return False
    return True


def _native_swscale_frames(
    frames, dst_h: int, dst_w: int, kernel: str
) -> np.ndarray:
    """[T, H, W] u8 resize through in-process libswscale with
    SWS_ACCURATE_RND|SWS_BITEXACT — the very C reference path the XLA
    `_swscale_exact` emulation is golden-tested bit-exact against
    (tests/test_ops.py::test_resize_golden_vs_swscale_noise_bitexact), so
    swapping it in changes no output byte. One native crossing per chunk,
    one SwsContext (filter tables amortized over the stack); ~10x the
    XLA emulation's host throughput, which BENCH_r05 showed gating the
    whole e2e chain on CPU-backend hosts."""
    from ..io import medialib

    flags = (
        getattr(medialib, _SWS_KERNEL_FLAGS[kernel])
        | medialib.SWS_ACCURATE_RND
        | medialib.SWS_BITEXACT
    )
    return medialib.sws_scale_frames(
        np.asarray(frames), dst_w, dst_h, flags
    )


def resize_frames(
    frames: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    kernel: str = "lanczos",
    method: str = "auto",
) -> jnp.ndarray:
    """Batched resize of [T, H, W] (or [H, W]) planes — the entry the
    AVPVS pipeline uses per plane. method="auto" on the CPU backend
    routes concrete u8 stacks through in-process libswscale (bit-exact
    with the XLA golden path, ~10x faster on host); everything else goes
    through the jitted device path."""
    if method == "auto" and _native_swscale_eligible(
        frames, dst_h, dst_w, kernel
    ):
        return _native_swscale_frames(frames, dst_h, dst_w, kernel)
    return _resize_frames_jit(frames, dst_h, dst_w, kernel, method)


def resize_yuv(
    planes: tuple[jnp.ndarray, ...],
    dst_h: int,
    dst_w: int,
    pix_fmt: str = "yuv420p",
    kernel: str = "lanczos",
    method: str = "auto",
) -> tuple[jnp.ndarray, ...]:
    """Resize a planar YUV frame set: luma to (dst_h, dst_w), chroma planes
    to the subsampled grid of `pix_fmt`."""
    sub_w = 2 if ("420" in pix_fmt or "422" in pix_fmt) else 1
    sub_h = 2 if "420" in pix_fmt else 1
    out = [resize_plane(planes[0], dst_h, dst_w, kernel, method=method)]
    for p in planes[1:3]:
        out.append(
            resize_plane(p, dst_h // sub_h, dst_w // sub_w, kernel, method=method)
        )
    return tuple(out)
