"""SI / TI spatial-temporal complexity features on device (ITU-T P.910).

SI = stddev over pixels of the Sobel gradient magnitude (border excluded);
TI = stddev over pixels of the inter-frame luma difference.

The reference chain ships a CRF-23 normalized-bitrate *proxy* for complexity
(reference util/complexity_classification.py:50-69) rather than Sobel SI/TI;
this module is the device-side feature extractor called for by the north
star (BASELINE.json), and `norm_bitrate_complexity` provides the proxy's
formula for parity with the shipped classifier.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

# plain numpy at import time: creating device arrays on import would force
# backend initialization for anyone importing the package
SOBEL_X = np.array([[-1.0, 0.0, 1.0], [-2.0, 0.0, 2.0], [-1.0, 0.0, 1.0]])
SOBEL_Y = SOBEL_X.T


def _conv3x3(img: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """3x3 valid convolution of [H, W] via shifted adds (cheaper than a
    conv call for a fixed tiny kernel; XLA fuses the 9 FMAs)."""
    h, w = img.shape
    out = jnp.zeros((h - 2, w - 2), img.dtype)
    for dy in range(3):
        for dx in range(3):
            out = out + k[dy, dx] * img[dy : h - 2 + dy, dx : w - 2 + dx]
    return out


def sobel_magnitude(y: jnp.ndarray) -> jnp.ndarray:
    """Gradient magnitude of a [H, W] luma plane, valid region [H-2, W-2]."""
    yf = y.astype(jnp.float32)
    gx = _conv3x3(yf, SOBEL_X)
    gy = _conv3x3(yf, SOBEL_Y)
    return jnp.sqrt(gx * gx + gy * gy)


def si_frame(y: jnp.ndarray) -> jnp.ndarray:
    """Spatial information of one frame (population stddev, P.910)."""
    return jnp.std(sobel_magnitude(y))


def _use_pallas() -> bool:
    from . import pallas_kernels as pk

    return pk.pallas_available()


@jax.jit
def si_frames(y: jnp.ndarray) -> jnp.ndarray:
    """SI per frame for [T, H, W] luma (integer container depth or f32).

    On TPU this routes through the fused Pallas kernel
    (pallas_kernels.si_frames_fused): the XLA formulation materializes the
    gradient/magnitude tensors in HBM between passes (~6 ms for 8 4K
    frames measured on v5e), the kernel keeps them in VMEM per column
    stripe (~1 ms, integer input streamed at container depth). The kernel
    uses sufficient-stats σ = sqrt(E[m²]−E[m]²); cross-implementation
    deviation is ≤1e-3 absolute on 4K noise (measured), far inside the
    feature tolerance."""
    if _use_pallas():
        from . import pallas_kernels as pk

        return pk.si_frames_fused(y)
    return jax.vmap(si_frame)(y)


@jax.jit
def ti_frames(y: jnp.ndarray) -> jnp.ndarray:
    """TI per frame for [T, H, W] luma: TI[0] = 0 (undefined for the first
    frame), TI[t] = std(y[t] - y[t-1]). TPU: fused Pallas path (see
    si_frames)."""
    if _use_pallas():
        from . import pallas_kernels as pk

        return pk.ti_frames_fused(y)
    yf = y.astype(jnp.float32)
    diff = yf[1:] - yf[:-1]
    ti = jax.vmap(jnp.std)(diff)
    return jnp.concatenate([jnp.zeros((1,), ti.dtype), ti])


def siti_batch(y: jnp.ndarray, prev_last: jnp.ndarray):
    """(SI[B, T], TI[B, T]) for [B, T, H, W] luma lanes with an explicit
    per-lane predecessor frame prev_last [B, H, W] (same dtype) — the
    sharded step's feature pass (TI[b, 0] diffs against prev_last[b]).
    TPU: one fused Pallas pass, nothing f32 in HBM; elsewhere the XLA
    formulation. Dispatch lives HERE so parallel/ callers never touch the
    kernel module directly."""
    if _use_pallas():
        from . import pallas_kernels as pk

        return pk.siti_frames_fused_batch(y, prev_last)
    b, t = y.shape[0], y.shape[1]
    flat = y.reshape((-1,) + y.shape[2:])
    si = si_frames(flat).reshape(b, t)
    yf = y.astype(jnp.float32)
    prev = jnp.concatenate(
        [prev_last[:, None].astype(jnp.float32), yf[:, :-1]], axis=1
    )
    ti = jnp.std(yf - prev, axis=(2, 3))
    return si, ti


def ti_frames_continued(y: jnp.ndarray, prev_last):
    """(TI[T], new prev_last) for one chunk of a streamed clip: TI[0]
    diffs against the previous chunk's last luma frame (f32) when given,
    else stays 0 (clip start). The single boundary-continuity idiom shared
    by every streaming SI/TI consumer (p03 sidecars, SRC analysis,
    quality metrics)."""
    ti = ti_frames(y)
    if prev_last is not None:
        ti = ti.at[0].set(jnp.std(y[0].astype(jnp.float32) - prev_last))
    return ti, y[-1].astype(jnp.float32)


@jax.jit
def siti(y: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(SI[T], TI[T]) for a [T, H, W] luma tensor — the batched feature
    extractor behind p02/complexity classification. On TPU both features
    come from ONE fused Pallas pass (pallas_kernels.siti_frames_fused):
    ~3/4 the HBM traffic and half the launches of the separate kernels."""
    if _use_pallas():
        from . import pallas_kernels as pk

        return pk.siti_frames_fused(y)
    return si_frames(y), ti_frames(y)


#: reference util/complexity_classification.py:34 — "arbitrarily chosen in
#: order to get a maximum difficulty of around 10"
REFERENCE_BITRATE = 2.75


def norm_bitrate_complexity(
    size_bytes: float, framerate: float, duration: float, width: int, height: int,
) -> tuple[float, float]:
    """The reference's complexity proxy (util/complexity_classification.py:50-69):
    norm_bitrate = file_size / framerate / duration / (pixels/1000);
    complexity = 20 * log10(norm_bitrate) / REFERENCE_BITRATE.
    Returns (norm_bitrate, complexity)."""
    import math

    norm_bitrate = size_bytes / framerate / duration / (width * height / 1000.0)
    return norm_bitrate, 20.0 * math.log10(norm_bitrate) / REFERENCE_BITRATE
