from . import p03_batch
from .mesh import batch_sharding, make_mesh, scalar_sharding
from .pipeline import avpvs_siti_step, make_batch_metrics_step, make_sharded_step

__all__ = [
    "batch_sharding",
    "make_mesh",
    "scalar_sharding",
    "avpvs_siti_step",
    "make_batch_metrics_step",
    "make_sharded_step",
    "p03_batch",
]
