"""Multi-host distributed runtime.

The reference is single-host (no MPI/NCCL/Gloo; "communication" is the
filesystem + SFTP, SURVEY.md §2.3/§5). The TPU-native equivalent is
`jax.distributed` + XLA collectives: within a pod slice, collectives ride
ICI; across hosts, DCN. The host-level fan-out of the PVS list (the process
pool analog) becomes per-process shards of the PVS batch feeding the global
mesh.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.log import get_logger


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID). Returns
    True when running distributed, False for single-process operation."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return False
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0"))
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    get_logger().info(
        "distributed: process %d/%d, %d global devices",
        process_id, num_processes, jax.device_count(),
    )
    return True


def shard_pvs_list(pvs_ids: list, process_id: int, num_processes: int) -> list:
    """Deterministic per-host shard of the PVS work list (the multi-host
    replacement for the reference's single-host pool fan-out)."""
    return [p for i, p in enumerate(sorted(pvs_ids)) if i % num_processes == process_id]
