"""Multi-host distributed runtime.

The reference is single-host (no MPI/NCCL/Gloo; "communication" is the
filesystem + SFTP, SURVEY.md §2.3/§5). The TPU-native equivalent is
`jax.distributed` + XLA collectives: within a pod slice, collectives ride
ICI; across hosts, DCN. The host-level fan-out of the PVS list (the process
pool analog) becomes per-process shards of the PVS batch feeding the global
mesh.
"""

from __future__ import annotations

import os
import re
import time as _time
from typing import Optional

from .. import telemetry as tm
from ..config.errors import ConfigError
from ..utils.log import get_logger

_COLLECTIVE_BYTES = tm.counter(
    "chain_dist_collective_bytes_total",
    "payload bytes of explicitly-recorded cross-process collectives "
    "(record_collective — the DCN dryrun and the distributed stage "
    "drivers), by op",
    ("op",),
)
_BARRIER_SECONDS = tm.counter(
    "chain_dist_barrier_seconds_total",
    "seconds each host spent waiting in the filesystem stage barrier, "
    "by stage",
    ("stage",),
)


def record_collective(op: str, nbytes: int,
                      seconds: Optional[float] = None) -> None:
    """One cross-process collective, recorded by the caller that knows
    the payload (jax gives no per-collective hook): bytes land in the
    chain_dist_collective_bytes_total counter and a `dist_collective`
    event — the multi-process lane was telemetry-silent before this."""
    _COLLECTIVE_BYTES.labels(op=op).inc(int(nbytes))
    fields = {"op": op, "bytes": int(nbytes)}
    if seconds is not None:
        fields["seconds"] = round(seconds, 6)
    tm.emit("dist_collective", **fields)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID). Returns
    True when running distributed, False for single-process operation."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return False
    # plan-exempt: (process topology shards which host renders each lane; per-artifact bytes are topology-invariant)
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        # plan-exempt: (process topology shards which host renders each lane; per-artifact bytes are topology-invariant)
        else int(os.environ.get("JAX_PROCESS_ID", "0"))
    )
    t0 = _time.perf_counter()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    init_s = _time.perf_counter() - t0
    get_logger().info(
        "distributed: process %d/%d, %d global devices",
        process_id, num_processes, jax.device_count(),
    )
    tm.emit(
        "dist_init", process_id=process_id, processes=num_processes,
        devices=jax.device_count(),
        local_devices=jax.local_device_count(),
        seconds=round(init_s, 3),
    )
    return True


def shard_pvs_list(pvs_ids: list, process_id: int, num_processes: int) -> list:
    """Deterministic per-host shard of the PVS work list (the multi-host
    replacement for the reference's single-host pool fan-out)."""
    return [p for i, p in enumerate(sorted(pvs_ids)) if i % num_processes == process_id]


def process_topology() -> tuple[int, int]:
    """(process_id, num_processes) of this host — (0, 1) when not running
    distributed. Reads the same env vars `initialize` consumes so stage
    drivers can shard without forcing jax.distributed setup."""
    # plan-exempt: (process topology shards which host renders each lane; per-artifact bytes are topology-invariant)
    num = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    # plan-exempt: (process topology shards which host renders each lane; per-artifact bytes are topology-invariant)
    pid = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    if num <= 1:
        return 0, 1
    if not 0 <= pid < num:
        raise ConfigError(f"JAX_PROCESS_ID {pid} out of range for {num} processes")
    return pid, num


def barrier_run_id() -> str:
    """The multi-host run namespace. Multi-host mode REQUIRES a fresh
    `PC_RUN_ID` per run (same value on every host): heuristics like marker
    mtimes cannot distinguish a stale marker from a host that simply
    launched earlier, so the id is the single source of truth. The
    orchestrator that already distributes JAX_PROCESS_ID per host sets it
    (e.g. a launch timestamp)."""
    # plan-exempt: (multi-host barrier namespace; no artifact byte depends on it)
    run_id = os.environ.get("PC_RUN_ID", "")
    if not run_id:
        raise ConfigError(
            "multi-host runs require PC_RUN_ID (a fresh shared id per run, "
            "e.g. a launch timestamp) so stage barriers can tell this "
            "run's markers from a previous run's"
        )
    if not re.fullmatch(r"[A-Za-z0-9._-]+", run_id):
        raise ConfigError(
            f"PC_RUN_ID {run_id!r} must be filename-safe ([A-Za-z0-9._-])"
        )
    return run_id


def fs_barrier_init(sync_dir: str) -> None:
    """Call once per host before the first stage: removes this host's own
    markers for the current run id, so an operator who reuses a PC_RUN_ID
    after a crash gets a clean slate for their own markers. (A reused id
    is still unsafe if other hosts lag — use a fresh id per run.)"""
    import glob as glob_mod

    pid, num = process_topology()
    if num == 1:
        return
    run_id = barrier_run_id()
    for old in glob_mod.glob(
        os.path.join(sync_dir, f".barrier_{run_id}_*.host{pid}")
    ):
        try:
            os.unlink(old)
        except OSError:
            pass


def fs_barrier(
    stage: str, sync_dir: str, timeout_s: float = 24 * 3600.0,
    poll_s: float = 2.0, report_every_s: float = 60.0,
) -> None:
    """Filesystem barrier between pipeline stages on a shared filesystem.

    The stages communicate through files (the reference's design, SURVEY.md
    §1), so the barrier does too: each host drops
    `<sync_dir>/.barrier_<run_id>_<stage>.host<i>` when it finishes the
    stage and waits until all `num_processes` markers of its run id exist.
    Needed because the p01 shard is keyed by segment filename (segments are
    shared across PVSes) while p02-p04 shard by pvs_id — a host's PVS may
    need segments another host encoded. No-op single-host.

    Never waits silently: every `report_every_s` it logs + emits a
    `barrier_wait` event naming the hosts still missing, its heartbeat
    beats only when a new peer arrives (so the watchdog sees a barrier
    stuck on a dead host as stalled, and a hard timeout cancels it), and
    the final TimeoutError names the missing peers.

    Correctness rests entirely on PC_RUN_ID freshness (see barrier_run_id):
    markers of other run ids are never read nor deleted, so concurrent runs
    on one database can't interfere."""
    import time

    from .. import telemetry as tm
    from ..telemetry.heartbeat import HEARTBEATS

    pid, num = process_topology()
    if num == 1:
        return
    run_id = barrier_run_id()
    os.makedirs(sync_dir, exist_ok=True)
    own = os.path.join(sync_dir, f".barrier_{run_id}_{stage}.host{pid}")
    from ..utils.fsio import atomic_write_text

    # atomic: a peer polling for this marker must never observe a
    # half-written file as an arrival (NFS sync dirs especially)
    atomic_write_text(own, str(time.time()))
    want = [
        os.path.join(sync_dir, f".barrier_{run_id}_{stage}.host{i}")
        for i in range(num)
    ]
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    next_report = t0 + report_every_s
    log = get_logger()
    log.info("barrier %s: host %d/%d waiting", stage, pid, num)
    hb = HEARTBEATS.register(
        f"barrier:{stage}", kind="barrier", planned=num
    )

    def _missing_names(missing):
        return [os.path.basename(m) for m in missing]

    while True:
        missing = [p for p in want if not os.path.isfile(p)]
        # beats only on arrivals (beat() refreshes the liveness stamp
        # unconditionally, so an every-poll beat would blind the
        # watchdog): a barrier waiting on a dead host makes no progress
        # and its beat age keeps growing
        if num - len(missing) > hb.units_done:
            hb.beat(done=num - len(missing))
        if not missing:
            hb.finish("ok")
            waited = time.monotonic() - t0
            _BARRIER_SECONDS.labels(stage=stage).inc(waited)
            # completion record: the waiting-side reports above fire only
            # every report_every_s, so a fast barrier would otherwise
            # leave no trace at all in the event log
            tm.emit("barrier_wait", stage=stage, host=pid,
                    waited_s=round(waited, 3), missing=[], done=True)
            return
        now = time.monotonic()
        if hb.cancelled:
            hb.finish("timeout")
            raise TimeoutError(
                f"barrier {stage}: cancelled by the watchdog hard timeout "
                f"after {now - t0:.0f}s; still missing "
                f"{_missing_names(missing)} in {sync_dir}"
            )
        if now > deadline:
            hb.finish("fail")
            raise TimeoutError(
                f"barrier {stage}: timed out after {now - t0:.0f}s waiting "
                f"for {len(missing)}/{num} hosts — missing "
                f"{_missing_names(missing)} in {sync_dir}"
            )
        if now >= next_report:
            next_report = now + report_every_s
            names = _missing_names(missing)
            log.warning(
                "barrier %s: host %d still waiting after %.0fs for %d/%d "
                "peers: %s", stage, pid, now - t0, len(missing), num, names,
            )
            tm.emit(
                "barrier_wait", stage=stage, host=pid,
                waited_s=round(now - t0, 1), missing=names,
            )
        time.sleep(poll_s)


def local_shard(keyed_items: dict) -> list:
    """Shard a {key: item} work dict across hosts: each host takes every
    num_processes-th key (sorted, deterministic). The filesystem stays the
    synchronization point exactly as in single-host mode — each item writes
    distinct files (reference's task-independence model, SURVEY.md §5)."""
    pid, num = process_topology()
    if num == 1:
        return list(keyed_items.items())
    keep = set(shard_pvs_list(list(keyed_items), pid, num))
    get_logger().info(
        "distributed shard: host %d/%d takes %d of %d items",
        pid, num, len(keep), len(keyed_items),
    )
    return [(k, v) for k, v in keyed_items.items() if k in keep]
