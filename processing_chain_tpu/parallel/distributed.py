"""Multi-host distributed runtime.

The reference is single-host (no MPI/NCCL/Gloo; "communication" is the
filesystem + SFTP, SURVEY.md §2.3/§5). The TPU-native equivalent is
`jax.distributed` + XLA collectives: within a pod slice, collectives ride
ICI; across hosts, DCN. The host-level fan-out of the PVS list (the process
pool analog) becomes per-process shards of the PVS batch feeding the global
mesh.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.log import get_logger


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID). Returns
    True when running distributed, False for single-process operation."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return False
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0"))
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    get_logger().info(
        "distributed: process %d/%d, %d global devices",
        process_id, num_processes, jax.device_count(),
    )
    return True


def shard_pvs_list(pvs_ids: list, process_id: int, num_processes: int) -> list:
    """Deterministic per-host shard of the PVS work list (the multi-host
    replacement for the reference's single-host pool fan-out)."""
    return [p for i, p in enumerate(sorted(pvs_ids)) if i % num_processes == process_id]


def process_topology() -> tuple[int, int]:
    """(process_id, num_processes) of this host — (0, 1) when not running
    distributed. Reads the same env vars `initialize` consumes so stage
    drivers can shard without forcing jax.distributed setup."""
    num = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    pid = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    if num <= 1:
        return 0, 1
    if not 0 <= pid < num:
        raise ValueError(f"JAX_PROCESS_ID {pid} out of range for {num} processes")
    return pid, num


def fs_barrier(
    stage: str, sync_dir: str, timeout_s: float = 24 * 3600.0,
    poll_s: float = 2.0, min_mtime: Optional[float] = None,
) -> None:
    """Filesystem barrier between pipeline stages on a shared filesystem.

    The stages communicate through files (the reference's design, SURVEY.md
    §1), so the barrier does too: each host drops
    `<sync_dir>/.barrier_<run>_<stage>.host<i>` when it finishes the stage
    and waits until all `num_processes` markers exist. Needed because the
    p01 shard is keyed by segment filename (segments are shared across
    PVSes) while p02-p04 shard by pvs_id — a host's PVS may need segments
    another host encoded. No-op single-host.

    Stale markers from a previous invocation must not satisfy a new
    barrier: each host deletes its own leftovers before writing, and with
    `min_mtime` set (p00 passes its own start time) a marker only counts
    when written after that instant — roughly-synced host clocks (NTP)
    are assumed, with slack applied by the caller. `PC_RUN_ID` additionally
    namespaces concurrent runs sharing one database."""
    import glob as glob_mod
    import time

    pid, num = process_topology()
    if num == 1:
        return
    os.makedirs(sync_dir, exist_ok=True)
    run_id = os.environ.get("PC_RUN_ID", "run")
    # clear this host's leftovers from older runs (any run_id, any stage
    # marker older than the gate)
    for old in glob_mod.glob(os.path.join(sync_dir, f".barrier_*.host{pid}")):
        try:
            if min_mtime is None or os.path.getmtime(old) < min_mtime:
                os.unlink(old)
        except OSError:
            pass
    own = os.path.join(sync_dir, f".barrier_{run_id}_{stage}.host{pid}")
    with open(own, "w") as f:
        f.write(str(time.time()))
    want = [
        os.path.join(sync_dir, f".barrier_{run_id}_{stage}.host{i}")
        for i in range(num)
    ]

    def present(path: str) -> bool:
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return False
        return min_mtime is None or mtime >= min_mtime

    deadline = time.monotonic() + timeout_s
    log = get_logger()
    log.info("barrier %s: host %d/%d waiting", stage, pid, num)
    warned_old = set()
    while True:
        missing = [p for p in want if not present(p)]
        if not missing:
            return
        for p in missing:
            # a marker that exists but predates the gate is ambiguous:
            # stale leftovers, or a host that started >slack earlier in
            # THIS run. Surface it so the operator can set PC_RUN_ID
            # instead of silently passing (corruption) or opaquely
            # timing out.
            if os.path.isfile(p) and p not in warned_old:
                warned_old.add(p)
                log.warning(
                    "barrier %s: ignoring marker %s older than this run's "
                    "start; if hosts launched far apart, set a shared "
                    "PC_RUN_ID per run", stage, os.path.basename(p),
                )
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"barrier {stage}: timed out waiting for "
                f"{[os.path.basename(m) for m in missing]}"
            )
        time.sleep(poll_s)


def local_shard(keyed_items: dict) -> list:
    """Shard a {key: item} work dict across hosts: each host takes every
    num_processes-th key (sorted, deterministic). The filesystem stays the
    synchronization point exactly as in single-host mode — each item writes
    distinct files (reference's task-independence model, SURVEY.md §5)."""
    pid, num = process_topology()
    if num == 1:
        return list(keyed_items.items())
    keep = set(shard_pvs_list(list(keyed_items), pid, num))
    get_logger().info(
        "distributed shard: host %d/%d takes %d of %d items",
        pid, num, len(keep), len(keyed_items),
    )
    return [(k, v) for k, v in keyed_items.items() if k in keep]
