"""Device mesh construction for the chain's two parallel axes.

The reference's parallelism is a process pool over independent shell
commands (reference lib/cmd_utils.py:60-129, SURVEY.md §2.3). The TPU-native
mapping is a 2-D `jax.sharding.Mesh`:

  * "pvs"  — data parallelism over the PVS batch (the `-p` flag / pool
    fan-out analog);
  * "time" — sequence/context parallelism over the frame-time axis (the
    long-video segment-partitioning strategy, reference
    test_config.py:1162-1248, mapped onto devices with halo exchange
    instead of files — see parallel/pipeline.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    devices: Optional[Sequence] = None,
    time_parallel: int = 1,
) -> Mesh:
    """Mesh over (pvs, time). time_parallel must divide the device count."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if n % time_parallel:
        raise ValueError(
            f"time_parallel={time_parallel} does not divide {n} devices"
        )
    grid = np.array(devs).reshape(n // time_parallel, time_parallel)
    return Mesh(grid, ("pvs", "time"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [B, T, H, W] frame tensors: PVS batch over "pvs",
    frame time over "time", spatial dims replicated."""
    return NamedSharding(mesh, P("pvs", "time", None, None))


def scalar_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-frame feature outputs [B, T]."""
    return NamedSharding(mesh, P("pvs", "time"))
