"""Device-plane flight recorder: wave occupancy accounting + compile ledger.

The compute plane that justifies "TPU-native" was dark: the wave driver
(parallel/p03_batch.py) materializes padding waste (`dst[i] = 0` for
exhausted lanes, tail-repeat pads) and then throws the accounting away,
and XLA recompiles are invisible. The FAST doctrine applies to telemetry
too — the driver already KNOWS every valid/pad slot per dispatched step;
this module records it instead of re-measuring it:

  * **Per-wave occupancy** — every dispatched wave-step (one
    [n_pvs, t_step] block through the sharded step) records its bucket,
    lanes, and frame-slot breakdown:
      - `valid`          slots carrying real frames,
      - `pad_tail`       tail-repeat padding of a partial block,
      - `pad_exhausted`  slots burned by exhausted lanes riding the wave
                         until the longest lane finishes,
      - `pad_mesh`       batch-axis padding up to the mesh "pvs" size.
    By construction valid + pads == n_pvs × t_step (the dispatched slot
    count) — the invariant the readers and the mesh-obs-smoke CI job
    re-check per record.
  * **Compile ledger** — the step builder is `functools.cache`d per
    (mesh, geometry), so one geometry flip costs exactly one recompile;
    every first dispatch records its bucket, triggering geometry, and
    compile-inclusive seconds (the same first-call split
    pipeline._instrument_step flags on the features steps).
  * **One journal file per replica** (`<dir>/<replica>.jsonl`), the
    spans.py/heat.py discipline verbatim: appends are flushed (not
    fsynced), O_APPEND with the predecessor's torn tail sealed before
    the first append, readers tolerate a torn final line, and a disk
    fault degrades to a logged warning — the recorder observes the wave
    loop, it must never sink it. Wave records carry the lane names in
    wave order: the lane→wave ordering evidence ROADMAP item 1(a)'s
    lane-ordered fused delivery needs.

Metrics (`chain_mesh_*`, telemetry/catalog.py) update whether or not a
journal is attached; the journal is attached per run (`--telemetry DIR`
runs write `DIR/meshobs_<stamp>/`) or per serve root (`<root>/meshobs`).
Readers (`aggregate`, `journal_stats`) serve `tools mesh-top`, the
run-report "mesh efficiency" section, and the /status "mesh" section.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from .. import telemetry as tm
from ..utils import lockdebug
from ..utils.log import get_logger

WAVES = tm.counter(
    "chain_mesh_waves_total",
    "dispatched device wave-steps (one [n_pvs, t_step] block through the "
    "sharded step), per geometry bucket",
    ("bucket",),
)
SLOTS = tm.counter(
    "chain_mesh_wave_slots_total",
    "frame-slots of dispatched wave-steps by occupancy kind (valid = real "
    "frames; pad_tail = tail-repeat padding; pad_exhausted = exhausted "
    "lanes riding the wave; pad_mesh = batch-axis padding) — the kinds "
    "sum to the dispatched slot count",
    ("bucket", "kind"),
)
WAVE_SECONDS = tm.histogram(
    "chain_mesh_wave_seconds",
    "wall seconds per dispatched wave-step, dispatch to outputs ready "
    "(the overlapped next-block host assembly is excluded)",
    ("bucket",),
)
WASTE = tm.gauge(
    "chain_mesh_waste_fraction",
    "running padded-slot fraction of all dispatched slots per bucket "
    "(0 = every slot carried a real frame)",
    ("bucket",),
)
RECOMPILES = tm.counter(
    "chain_mesh_recompiles_total",
    "XLA compiles of device steps per geometry bucket — one geometry "
    "flip costs exactly one recompile (the step builder is cached per "
    "(mesh, geometry); revisiting a bucket is a cache hit)",
    ("bucket",),
)
COMPILE_SECONDS = tm.counter(
    "chain_mesh_compile_seconds_total",
    "compile-inclusive seconds of first dispatches per bucket (trace + "
    "XLA compile + the first step's compute)",
    ("bucket",),
)

#: occupancy kinds of one dispatched frame-slot, in render order
SLOT_KINDS = ("valid", "pad_tail", "pad_exhausted", "pad_mesh")

_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]+")


def mesh_dir(root: str) -> str:
    """The journal directory convention of one serve root."""
    return os.path.join(os.path.abspath(root), "meshobs")


def _journal_name(replica: str) -> str:
    return _SAFE_NAME.sub("_", replica) + ".jsonl"


def _new_agg() -> dict:
    return {"waves": 0, "valid": 0, "pad_tail": 0, "pad_exhausted": 0,
            "pad_mesh": 0, "dispatched": 0, "step_s": 0.0,
            "recompiles": 0, "compile_s": 0.0}


class MeshRecorder:
    """The process-wide wave/compile recorder. Metrics and the in-memory
    per-bucket aggregate (the /status "mesh" section) always update;
    journal lines are written only while a journal is attached.

    Thread-safe: the wave driver, the serve executor pool and /status
    reads all go through one recorder. Appends are flushed per record
    and never raise (heat.py discipline)."""

    def __init__(self) -> None:
        self._lock = lockdebug.make_lock("meshobs")
        self._dir: Optional[str] = None   # guarded-by: _lock
        self._replica = "host0"           # guarded-by: _lock
        self._path: Optional[str] = None  # guarded-by: _lock
        self._f = None                    # guarded-by: _lock
        self._seq = 0                     # guarded-by: _lock
        self._buckets: dict = {}          # guarded-by: _lock

    # -------------------------------------------------------- journal

    def attach_journal(self, journal_dir: str,
                       replica: str = "host0") -> None:
        """Point the recorder at a per-run/per-root journal directory.
        Idempotent per (dir, replica); re-attaching elsewhere closes the
        previous journal stream."""
        with self._lock:
            path = os.path.join(os.path.abspath(journal_dir),
                                _journal_name(replica))
            if path == self._path:
                return
            f, self._f = self._f, None
            self._dir = os.path.abspath(journal_dir)
            self._replica = replica
            self._path = path
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def detach_journal(self) -> None:
        with self._lock:
            f, self._f = self._f, None
            self._dir = self._path = None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def _seal_torn_tail(self) -> None:
        """A predecessor SIGKILLed mid-write leaves a torn final line;
        terminate it before O_APPEND glues our first record onto it
        (store/heat.py discipline)."""
        try:
            with open(self._path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        except FileNotFoundError:
            return
        except OSError:
            pass  # the append itself will surface a real disk fault

    # holds-lock: _lock
    def _append_locked(self, record: dict) -> None:
        """One journal record (spans.py discipline). Never raises; a
        no-op while no journal is attached."""
        if self._path is None:
            return
        record.setdefault("ts", round(time.time(), 6))
        record["replica"] = self._replica
        record["pid"] = os.getpid()
        self._seq += 1
        record["seq"] = self._seq
        try:
            if self._f is None:
                os.makedirs(self._dir, exist_ok=True)
                self._seal_torn_tail()
                self._f = open(self._path, "a")
            self._f.write(json.dumps(record, sort_keys=True) + "\n")
            self._f.flush()
        except (OSError, ValueError):
            get_logger().warning(
                "meshobs: could not append %s record",
                record.get("kind"), exc_info=True)
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:
                pass
            self._f = None

    # --------------------------------------------------------- writes

    def record_wave(self, bucket: str, *, wave: int, block: int,
                    lanes: list, n_pvs: int, t_step: int, valid: int,
                    pad_tail: int, pad_exhausted: int, pad_mesh: int,
                    step_s: float, first: bool = False) -> None:
        """One dispatched wave-step with its full slot breakdown.
        `lanes` is the lane names in wave order (the lane→wave ordering
        evidence); `first` flags the compile-inclusive first dispatch of
        the bucket's step."""
        dispatched = n_pvs * t_step
        WAVES.labels(bucket=bucket).inc()
        SLOTS.labels(bucket=bucket, kind="valid").inc(valid)
        SLOTS.labels(bucket=bucket, kind="pad_tail").inc(pad_tail)
        SLOTS.labels(bucket=bucket, kind="pad_exhausted").inc(pad_exhausted)
        SLOTS.labels(bucket=bucket, kind="pad_mesh").inc(pad_mesh)
        WAVE_SECONDS.labels(bucket=bucket).observe(step_s)
        record = {
            "kind": "wave", "bucket": bucket, "wave": wave,
            "block": block, "lanes": list(lanes), "n_pvs": n_pvs,
            "t_step": t_step, "valid": valid, "pad_tail": pad_tail,
            "pad_exhausted": pad_exhausted, "pad_mesh": pad_mesh,
            "dispatched": dispatched, "step_s": round(step_s, 6),
        }
        if first:
            record["first"] = True
        with self._lock:
            agg = self._buckets.setdefault(bucket, _new_agg())
            agg["waves"] += 1
            agg["valid"] += valid
            agg["pad_tail"] += pad_tail
            agg["pad_exhausted"] += pad_exhausted
            agg["pad_mesh"] += pad_mesh
            agg["dispatched"] += dispatched
            agg["step_s"] += step_s
            waste = waste_fraction(agg)
            self._append_locked(record)
        WASTE.labels(bucket=bucket).set(waste)
        tm.emit("mesh_wave", bucket=bucket, wave=wave, block=block,
                lanes=len(lanes), valid=valid, pad_tail=pad_tail,
                pad_exhausted=pad_exhausted, pad_mesh=pad_mesh,
                step_s=round(step_s, 6))

    def record_compile(self, bucket: str, *, step: str, geometry: dict,
                       seconds: float) -> None:
        """One first dispatch of a compiled step: the compile-ledger
        entry with the triggering geometry."""
        RECOMPILES.labels(bucket=bucket).inc()
        COMPILE_SECONDS.labels(bucket=bucket).inc(seconds)
        record = {
            "kind": "compile", "bucket": bucket, "step": step,
            "geometry": dict(geometry), "seconds": round(seconds, 6),
        }
        with self._lock:
            agg = self._buckets.setdefault(bucket, _new_agg())
            agg["recompiles"] += 1
            agg["compile_s"] += seconds
            self._append_locked(record)
        tm.emit("mesh_compile", bucket=bucket, step=step,
                seconds=round(seconds, 6), **{
                    k: v for k, v in geometry.items()
                    if isinstance(v, (str, int, float, bool))
                })

    # --------------------------------------------------------- reads

    def summary(self) -> Optional[dict]:
        """The /status "mesh" section: per-bucket occupancy/waste/
        recompile aggregates since process start. None (section
        skipped) until the first wave dispatches."""
        with self._lock:
            if not self._buckets:
                return None
            buckets = {
                b: {**agg, "step_s": round(agg["step_s"], 4),
                    "compile_s": round(agg["compile_s"], 4),
                    "waste_fraction": waste_fraction(agg)}
                for b, agg in self._buckets.items()
            }
            journal = self._path
        return {
            "buckets": buckets,
            "waves": sum(a["waves"] for a in buckets.values()),
            "recompiles": sum(a["recompiles"] for a in buckets.values()),
            "journal": journal,
        }

    def close(self) -> None:
        self.detach_journal()


#: the process-wide recorder the wave driver and /status share
RECORDER = MeshRecorder()


def attach_journal(journal_dir: str, replica: str = "host0") -> None:
    RECORDER.attach_journal(journal_dir, replica)


def detach_journal() -> None:
    RECORDER.detach_journal()


def waste_fraction(agg: dict) -> float:
    """Padded-slot fraction of one aggregate entry (0.0 when nothing
    dispatched)."""
    dispatched = agg.get("dispatched", 0)
    if not dispatched:
        return 0.0
    pads = (agg.get("pad_tail", 0) + agg.get("pad_exhausted", 0)
            + agg.get("pad_mesh", 0))
    return round(pads / dispatched, 4)


# ---------------------------------------------------------------- readers


def read_journal(path: str) -> list[dict]:
    """One journal file; tolerates torn lines (heat.py contract: every
    complete record stands, the at-most-one interrupted write is
    skipped)."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue  # torn line: every complete record stands
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out


def read_journals(root: str) -> list[dict]:
    """Every replica's wave journal under `root`, merged and ordered by
    (ts, replica, seq)."""
    records: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if name.endswith(".jsonl"):
            records.extend(read_journal(os.path.join(root, name)))
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("replica", ""),
                                r.get("seq", 0)))
    return records


def aggregate(root: str) -> dict:
    """Full-history journal rollup for mesh-top / run-report: per-bucket
    occupancy, waste, recompiles and the per-wave lane schedule, plus
    the per-record valid+pads == dispatched invariant verdict (any
    violation is a driver accounting bug, reported — never dropped)."""
    buckets: dict = {}
    schedule: dict = {}
    violations = 0
    for record in read_journals(root):
        kind = record.get("kind")
        bucket = record.get("bucket") or "?"
        agg = buckets.setdefault(bucket, _new_agg())
        if kind == "wave":
            agg["waves"] += 1
            for slot_kind in SLOT_KINDS:
                agg[slot_kind] += int(record.get(slot_kind) or 0)
            agg["dispatched"] += int(record.get("dispatched") or 0)
            agg["step_s"] += float(record.get("step_s") or 0.0)
            total = sum(int(record.get(k) or 0) for k in SLOT_KINDS)
            if total != int(record.get("dispatched") or 0):
                violations += 1
            if record.get("block") == 0:
                schedule.setdefault(bucket, []).append({
                    "wave": record.get("wave"),
                    "lanes": record.get("lanes", []),
                })
        elif kind == "compile":
            agg["recompiles"] += 1
            agg["compile_s"] += float(record.get("seconds") or 0.0)
    for bucket, agg in buckets.items():
        agg["waste_fraction"] = waste_fraction(agg)
        agg["step_s"] = round(agg["step_s"], 4)
        agg["compile_s"] = round(agg["compile_s"], 4)
    totals = _new_agg()
    for agg in buckets.values():
        for key in totals:
            totals[key] += agg[key]
    totals["waste_fraction"] = waste_fraction(totals)
    totals["step_s"] = round(totals["step_s"], 4)
    totals["compile_s"] = round(totals["compile_s"], 4)
    return {"buckets": buckets, "totals": totals, "schedule": schedule,
            "invariant_violations": violations}


def journal_stats(root: str, tail_bytes: int = 1 << 19) -> dict:
    """Cheap summary for the few-seconds-cadence surfaces (/fleet):
    total size from stat, counts parsed from each journal's TAIL;
    `sampled: true` flags a journal exceeding the tail window (the
    counts then cover the recent window — no silent cap)."""
    stats = {"files": 0, "bytes": 0, "waves": 0, "compiles": 0,
             "valid": 0, "padded": 0, "sampled": False}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return stats
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(root, name)
        try:
            size = os.stat(path).st_size
            with open(path) as f:
                if size > tail_bytes:
                    stats["sampled"] = True
                    f.seek(size - tail_bytes)
                    f.readline()  # discard the mid-record partial
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn tail (or mid-window garbage)
                    if record.get("kind") == "wave":
                        stats["waves"] += 1
                        stats["valid"] += int(record.get("valid") or 0)
                        stats["padded"] += sum(
                            int(record.get(k) or 0)
                            for k in SLOT_KINDS if k != "valid")
                    elif record.get("kind") == "compile":
                        stats["compiles"] += 1
        except OSError:
            continue
        stats["files"] += 1
        stats["bytes"] += size
    return stats


# the /status "mesh" section: registered at import so every surface that
# imports the wave driver (runs, serve, tools) exposes it for free
def _status_section(query) -> Optional[dict]:
    return RECORDER.summary()


try:
    from ..telemetry import live as _live

    _live.STATUS_PROVIDERS.setdefault("mesh", _status_section)
except ImportError:  # pragma: no cover - circular-import guard only
    pass
