"""Sharded batch execution of p03's AVPVS rescale — the product path on a
multi-device mesh.

Where the reference fans independent ffmpeg processes over a pool
(reference p03_generateAvPvs.py:190, lib/cmd_utils.py:93-101), this module
batches the *same* per-PVS rescale (models/avpvs._pump: device resize +
bit-depth quantize) over a (pvs × time) `jax.sharding.Mesh`: the PVS batch
axis is data parallelism, the frame-time axis is sequence parallelism.
The rescale is frame-local, so time sharding needs no halo (the TI halo
lives in pipeline.make_sharded_step, the features path).

Padding/bucketing policy for variable-length PVSes (SURVEY.md §7 hard
part), explicit and documented:

  * Lanes (PVS streams) batch together only when their full geometry
    matches — (src_h, src_w, dst_h, dst_w, pix_fmt) — the bucket key.
    Different geometries recompile anyway; bucketing never pads space.
  * The time axis is consumed in fixed steps of `t_step = t_loc × n_time`
    frames per lane; a lane's tail block is padded by REPEATING ITS LAST
    FRAME up to t_step (repeat, not zeros: the pad rides the same compiled
    step, and repeated real frames keep the value range — but pad outputs
    are dropped before the writer, so they never land in an artifact).
  * Lanes of unequal length: a lane that exhausts keeps contributing
    zero-valid blocks (its slot computes garbage that is discarded) until
    every lane in the bucket finishes. Waste is bounded by the length
    spread within a bucket; sort_lanes groups similar lengths per wave.
  * The batch axis pads up to a multiple of the mesh's "pvs" size with
    zero lanes (valid = 0, outputs discarded).
"""

from __future__ import annotations

import functools
import math
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from .. import telemetry as tm
from ..io import bufpool
from ..telemetry import profiling
from ..utils.device import shard_map as _shard_map
from . import meshobs

_XFER_SECONDS = tm.counter(
    "chain_device_transfer_seconds_total",
    "host<->device transfer time in the batch driver (put = assemble + "
    "dispatch — the copy itself overlaps the in-flight step; get = fetch "
    "of ready outputs)", ("direction",),
)
_XFER_BYTES = tm.counter(
    "chain_device_transfer_bytes_total",
    "host<->device bytes moved by the batch driver", ("direction",),
)
_XFER_PUT_S = _XFER_SECONDS.labels(direction="put")
_XFER_GET_S = _XFER_SECONDS.labels(direction="get")
_XFER_PUT_B = _XFER_BYTES.labels(direction="put")
_XFER_GET_B = _XFER_BYTES.labels(direction="get")


@dataclass
class Lane:
    """One PVS stream through the batch: decoded chunks in, scaled frames
    out. `chunks` yields [y, u, v] plane stacks ([T, H, W] each, chroma at
    its subsampled size); `emit` receives the scaled/quantized planes of
    each block, already trimmed to the valid frame count; `emit_features`
    (optional) receives the device-computed per-frame (si, ti) arrays of
    the same frames."""

    chunks: Iterable[list]
    emit: Callable[[list], None]
    n_frames_hint: int = 0  # for wave grouping only; 0 = unknown
    emit_features: Optional[Callable[[np.ndarray, np.ndarray], None]] = None
    #: called once, after the lane's LAST real frames have been emitted
    #: (an exhausted lane rides the wave as discarded padding until the
    #: longest lane finishes): the fused p04 fan-out flushes and closes
    #: its downstream encoders here, so open codec contexts are bounded
    #: by the live lanes, not the wave width
    on_done: Optional[Callable[[], None]] = None
    #: identity in the wave journal (parallel/meshobs.py) — the lane→wave
    #: ordering evidence; empty = positional "lane<i>" fallback
    name: str = ""


def _rechunk(
    chunks: Iterable[list], t_step: int, pool=None,
) -> Iterator[tuple[list, int]]:
    """Re-chunk a variable-size chunk stream into exact t_step blocks.
    Yields (planes, valid): the tail block pads by repeating the last
    frame, valid < t_step.

    Chunks already sized t_step (the aligned fast path: decode CHUNK ==
    t_step) pass through untouched, so a pooled decode block reaches the
    wave assembler without a copy; misaligned streams accumulate via
    concatenate, with consumed source chunks released back to the pool
    (release ignores views and foreign arrays — bufpool protocol)."""
    pool = pool or bufpool.DEFAULT_POOL
    buf: Optional[list] = None
    for ch in chunks:
        ch = [np.asarray(p) for p in ch]
        if buf is None:
            if ch[0].shape[0] == t_step:
                yield ch, t_step
                continue
            if any(pool.owns(p) for p in ch):
                # misaligned pooled chunk: slicing it into views below
                # would strand the block (release ignores views) — take
                # a private copy and recycle the block now; the copy is
                # the same cost class as the concatenate path this
                # stream is already on
                buf = [np.array(p) for p in ch]
                pool.release(*ch)
            else:
                buf = ch
        else:
            merged = [np.concatenate([b, c]) for b, c in zip(buf, ch)]
            # buf is never pool-owned here (the first-chunk branch above
            # copies-and-releases pooled arrivals); ch can be — a full
            # pooled block landing while a remainder is buffered
            pool.release(*ch)
            buf = merged
        while buf is not None and buf[0].shape[0] >= t_step:
            if buf[0].shape[0] == t_step:
                yield buf, t_step
                buf = None
            else:
                yield [b[:t_step] for b in buf], t_step
                buf = [b[t_step:] for b in buf]
    if buf is not None and buf[0].shape[0] > 0:
        n = buf[0].shape[0]
        pad = t_step - n
        yield [
            np.concatenate([b, np.repeat(b[-1:], pad, axis=0)]) for b in buf
        ], n


@functools.cache
def _sharded_resize_step(
    mesh, dst_h: int, dst_w: int, kernel: str,
    sub_h: int, sub_w: int, ten_bit: bool, donate: bool = False,
):
    """Jit the _pump math (models/avpvs) over the (pvs, time) mesh:
    [B, T, H, W] u8/u16 planes -> scaled + quantized planes PLUS per-frame
    SI/TI features of the quantized luma, sharded P("pvs", "time", ...).
    TI needs each time shard's first frame to see the previous shard's
    last frame: a one-frame halo exchanged with lax.ppermute over the
    "time" axis (ICI neighbor communication); the first time shard takes
    `prev` instead — the carried last frame of the lane's previous block
    (replicated over "time"), with `first` marking the lane's very first
    block (TI[0] = 0). Cached per (mesh, geometry)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..models import frames as fr
    from ..ops import siti as siti_ops

    n_time = mesh.shape["time"]

    def shard_fn(y, u, v, prev, first):
        b, t = y.shape[0], y.shape[1]

        def flat(p):
            return p.reshape((-1,) + p.shape[2:])

        # identical call chain to the single-device path (models/avpvs
        # _pump): scale_yuv_frames + quantize_device, on [b*t, H, W] so
        # the fused Pallas kernel stays eligible on TPU
        scaled = fr.scale_yuv_frames(
            [flat(y), flat(u), flat(v)], dst_h, dst_w, kernel, (sub_h, sub_w)
        )
        quant = fr.quantize_device(scaled, ten_bit)
        qy, qu, qv = (q.reshape((b, t) + q.shape[1:]) for q in quant)

        # device-side features on the quantized luma (what a decoder of
        # the written AVPVS would see), matching SiTiAccumulator. Both
        # features in one pass (fused on TPU; siti.siti_batch) with the
        # previous time-shard's last frame — or, on shard 0, the
        # cross-block carry `prev` — as the halo lane. The halo rides
        # ICI at container depth (1/4 the bytes of f32).
        last = qy[:, -1]
        perm = [(i, (i + 1) % n_time) for i in range(n_time)]
        halo = lax.ppermute(last, "time", perm)
        t_idx = lax.axis_index("time")
        prev_first = jnp.where(t_idx == 0, prev, halo)
        si, ti = siti_ops.siti_batch(qy, prev_first)
        # the lane's very first frame has no predecessor: TI[0] = 0
        ti = jnp.where(
            first & (t_idx == 0),
            ti.at[:, 0].set(0.0),
            ti,
        )
        return qy, qu, qv, si, ti

    spec = P("pvs", "time", None, None)
    prev_spec = P("pvs", None, None)     # replicated over "time"
    feat_spec = P("pvs", "time")
    mapped = _shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec, prev_spec, P()),
        out_specs=(spec, spec, spec, feat_spec, feat_spec),
    )
    if donate:
        # the prev carry is re-uploaded every block and never read after
        # the step: donating its buffer lets XLA reuse the HBM pages
        # instead of holding both generations live (no-op on backends
        # without donation support — gated by the caller)
        return jax.jit(mapped, donate_argnums=(3,))
    return jax.jit(mapped)


def sort_lanes(lanes: list[Lane]) -> list[Lane]:
    """Longest-first so each wave groups similar lengths (minimizes the
    exhausted-lane waste of the padding policy)."""
    return sorted(lanes, key=lambda ln: -ln.n_frames_hint)


def plan_waves(buckets: dict, n_pvs: int, group_of=None) -> list:
    """Order bucketed lane entries into an executable wave schedule:
    ``[(bucket_key, [entry, ...]), ...]``, each wave ≤ `n_pvs` entries
    from ONE bucket (waves compile per geometry).

    `group_of(entry)` -> None or ``(group_id, seq)`` pins ordered groups
    — the fused long-test fan-outs, whose per-(PVS, segment) lanes must
    reach the fan-out in stream order. The guarantee: a group's entries
    appear in strictly increasing `seq` across the schedule, at most one
    per wave. Waves execute sequentially and a wave's lanes fully drain
    before the next wave starts (run_bucket), so schedule order IS
    delivery order — segment k+1's first frame cannot reach a fan-out
    before segment k's last (zero reorder buffering; models/fused
    SegmentOrderedTap enforces the same invariant at the consumer).

    With no `group_of` (or none pinned) this reduces exactly to the
    historical per-bucket slicing, same waves in the same order. Pinned
    groups may shrink waves below `n_pvs` (a deferred segment leaves its
    slot to batch-axis padding); meshobs pad accounting stays truthful
    automatically — `pad_mesh` records the burned slots.

    A group's segments may span buckets (long tests ladder through
    quality levels, so per-segment source geometry differs): the outer
    round-robin alternates buckets until every entry is scheduled.
    Always terminates — any round with pending entries schedules at
    least one wave (each group's head is pending in some bucket, and
    scanning that bucket either takes the head or fills a wave with
    other work; both are progress)."""
    if group_of is None:
        group_of = lambda e: None  # noqa: E731
    # per-group ascending seq queue: "next" = the group's smallest
    # unscheduled seq (robust to non-contiguous numbering)
    heads: dict = {}
    for entries in buckets.values():
        for e in entries:
            g = group_of(e)
            if g is not None:
                heads.setdefault(g[0], []).append(g[1])
    for q in heads.values():
        q.sort(reverse=True)  # pop() from the tail = ascending order
    pending = {key: list(entries) for key, entries in buckets.items()}
    out: list = []
    while True:
        progressed = False
        for key in list(pending):
            entries = pending[key]
            while entries:
                wave, rest, in_wave = [], [], set()
                for e in entries:
                    g = group_of(e)
                    if len(wave) >= n_pvs:
                        rest.append(e)
                    elif g is None:
                        wave.append(e)
                    elif g[0] not in in_wave and heads[g[0]][-1] == g[1]:
                        wave.append(e)
                        in_wave.add(g[0])
                        heads[g[0]].pop()
                    else:
                        rest.append(e)  # not this group's turn yet
                if not wave:
                    break
                out.append((key, wave))
                progressed = True
                entries = rest
            pending[key] = entries
        if not any(pending.values()):
            return out
        if not progressed:  # argued unreachable above; never spin
            stuck = sum(len(v) for v in pending.values())
            raise RuntimeError(
                f"plan_waves: no schedulable lane among {stuck} pending "
                "entries (inconsistent group_of sequencing?)"
            )


#: step identities already dispatched at least once — the compile
#: ledger's first-dispatch detector. `_sharded_resize_step` is
#: functools.cached, so each compiled step lives for the process and its
#: id() is stable: a NEW id here means XLA traced+compiled, a seen id is
#: a cache hit (one geometry flip = exactly one recompile).
_DISPATCHED_STEPS: set[int] = set()


def bucket_label(dst_h: int, dst_w: int, ten_bit: bool,
                 src_h: int = 0, src_w: int = 0) -> str:
    """Canonical bucket label for the mesh metrics/journal. Callers that
    know the full bucket key (models/avpvs, serve executors) pass the
    source geometry; the driver-side fallback labels by destination."""
    src = f"{src_h}x{src_w}" if src_h and src_w else "?"
    return f"{src}->{dst_h}x{dst_w}@{'10' if ten_bit else '8'}bit"


def run_bucket(
    lanes: list[Lane],
    mesh,
    dst_h: int,
    dst_w: int,
    kernel: str = "bicubic",
    chroma_sub: tuple[int, int] = (2, 2),
    ten_bit: bool = False,
    *,
    chunk: int,
    bucket: Optional[str] = None,
) -> None:
    """Drive one geometry bucket of lanes through the sharded step in
    waves of the mesh's "pvs" size. `chunk` is the global frame budget per
    step across the time axis — callers pass their own memory knob
    (models/avpvs passes its CHUNK) so the two paths cannot silently
    diverge. Callers that must bound open decoders/encoders should pass
    wave-sized lane groups (≤ mesh "pvs" size), as models/avpvs does.
    `bucket` labels the wave journal / chain_mesh_* metrics
    (parallel/meshobs.py); callers knowing the full bucket key pass it."""
    import jax

    from .mesh import batch_sharding

    n_pvs = mesh.shape["pvs"]
    n_time = mesh.shape["time"]
    t_loc = max(1, chunk // n_time)
    t_step = t_loc * n_time
    sub_h, sub_w = chroma_sub
    sharding = batch_sharding(mesh)
    # donation is a no-op (plus a warning per trace) on backends without
    # buffer donation — only ask for it where it means something
    donate = all(d.platform in ("tpu", "gpu") for d in mesh.devices.flat)
    step = _sharded_resize_step(
        mesh, dst_h, dst_w, kernel, sub_h, sub_w, ten_bit, donate
    )
    if bucket is None:
        bucket = bucket_label(dst_h, dst_w, ten_bit)
    # compile ledger: a step id never dispatched before compiles on its
    # first call — the first block's timing is compile-inclusive and
    # lands as this bucket's ledger entry (meshobs.record_compile)
    compile_state = {
        "pending": id(step) not in _DISPATCHED_STEPS,
        "geometry": {
            "dst_h": dst_h, "dst_w": dst_w, "kernel": kernel,
            "sub_h": sub_h, "sub_w": sub_w, "ten_bit": ten_bit,
            "t_step": t_step, "mesh": "x".join(
                str(v) for v in mesh.shape.values()),
        },
    }
    _DISPATCHED_STEPS.add(id(step))

    from contextlib import ExitStack

    from ..engine import prefetch as pfe

    ordered = sort_lanes(lanes)
    for w0 in range(0, len(ordered), n_pvs):
        wave = ordered[w0: w0 + n_pvs]
        with ExitStack() as stack:
            # one decode-ahead thread per lane, like the single-device
            # path's Prefetcher: the device step runs while the next
            # blocks decode
            iters = [
                iter(stack.enter_context(
                    pfe.Prefetcher(_rechunk(ln.chunks, t_step), depth=2)
                ))
                for ln in wave
            ]
            _drive_wave(wave, iters, n_pvs, step, sharding, mesh, dst_h,
                        dst_w, ten_bit, bucket=bucket,
                        wave_index=w0 // n_pvs, t_step=t_step,
                        compile_state=compile_state,
                        lane_names=[ln.name or f"lane{w0 + i}"
                                    for i, ln in enumerate(wave)])


def _drive_wave(wave, iters, n_pvs, step, sharding, mesh,
                dst_h: int, dst_w: int, ten_bit: bool, pool=None, *,
                bucket: str = "?", wave_index: int = 0, t_step: int = 0,
                compile_state: Optional[dict] = None,
                lane_names: Optional[list] = None) -> None:
    """Fully overlapped wave loop: while the jitted step for block k is in
    flight, the next t_step blocks are pulled from the lane prefetchers,
    assembled into the OTHER of two pooled [B, T, H, W] wave buffers, and
    their device_put is issued — so host decode, H2D transfer, and device
    compute run concurrently instead of strictly alternating. Two wave
    buffers suffice: buffer A is only overwritten (at k+2) after block
    k's outputs have been fetched, which proves the compute that read A
    finished — safe even where device_put aliases host memory (CPU)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    pool = pool or bufpool.DEFAULT_POOL
    prev_sharding = NamedSharding(mesh, P("pvs", None, None))
    done = [False] * len(wave)
    notified = [False] * len(wave)

    def notify_done() -> None:
        # a lane's done flag flips while fetching the NEXT block, so by
        # the time the current block's emits ran, every real frame of a
        # done lane is out — safe to fire its on_done now
        for i, ln in enumerate(wave):
            if done[i] and not notified[i]:
                notified[i] = True
                if ln.on_done is not None:
                    ln.on_done()
    # cross-block TI carry stays at container depth (the quantized luma a
    # decoder of the artifact would see; u8/u16 device_put, not f32)
    prev = np.zeros((n_pvs, dst_h, dst_w),
                    np.uint16 if ten_bit else np.uint8)
    first = True
    wave_bufs: dict[int, list] = {}  # parity -> pooled [B, T, H, W] planes
    state = {"parity": 0}

    def gather_put():
        """Pull one block per live lane, assemble into this parity's wave
        buffer, issue the device_put. Returns (dev_planes, valids) or
        None once every lane is exhausted."""
        blocks: list[Optional[list]] = []
        valids: list[int] = []
        for i, it in enumerate(iters):
            blk = None if done[i] else next(it, None)
            if blk is None:
                done[i] = True
                blocks.append(None)
                valids.append(0)
            else:
                blocks.append(blk[0])
                valids.append(blk[1])
        if all(v == 0 for v in valids):
            return None
        tmpl = next(b for b in blocks if b is not None)
        parity = state["parity"]
        state["parity"] ^= 1
        bufs = wave_bufs.get(parity)
        if bufs is None:
            bufs = [
                pool.acquire((n_pvs,) + tuple(p.shape), p.dtype)
                for p in tmpl
            ]
            # chainlint: ownership-transfer (the wave_bufs double-buffer retains both parities for the whole wave; on exception exits they are deliberately DROPPED, not released — in-flight device DMA may still read them)
            wave_bufs[parity] = bufs
        t_put = time.perf_counter() if tm.enabled() else 0.0
        with profiling.maybe_span("transfer:device_put"):
            for p in range(3):
                dst = bufs[p]
                for i in range(n_pvs):
                    blk = blocks[i] if i < len(blocks) else None
                    if blk is None:
                        dst[i] = 0  # exhausted lane / batch-axis padding
                    else:
                        np.copyto(dst[i], blk[p])
            # lane blocks are copied out: recycle them for the decoders
            for blk in blocks:
                if blk is not None:
                    pool.release(*blk)
            dev = [jax.device_put(bufs[p], sharding) for p in range(3)]
        if tm.enabled():
            _XFER_PUT_S.inc(time.perf_counter() - t_put)
            _XFER_PUT_B.inc(sum(b.nbytes for b in bufs) + prev.nbytes)
        return dev, valids

    lane_names = lane_names or [f"lane{i}" for i in range(len(wave))]
    block = 0
    nxt = gather_put()
    while nxt is not None:
        planes, valids = nxt
        # occupancy of THIS dispatched block, from the valid mask the
        # assembly above already computed (satellite fix: the burned
        # `dst[i] = 0` slots are recorded, not discarded). t_step may be
        # 0 on direct legacy calls — derived from the device block then.
        ts = t_step or int(planes[0].shape[1])
        valid = sum(valids)
        pad_tail = sum(ts - v for v in valids if v)
        pad_exhausted = ts * sum(1 for v in valids if not v)
        pad_mesh = (n_pvs - len(wave)) * ts
        t0 = time.perf_counter()
        out = step(*planes, jax.device_put(prev, prev_sharding), first)
        # overlap: decode + assemble + upload block k+1 while the
        # step for block k runs (dispatch above is async)
        t_gather0 = time.perf_counter()
        nxt = gather_put()
        t_gather1 = time.perf_counter()
        if tm.enabled():
            with profiling.maybe_span(
                    "device:wave_step", bucket=bucket, wave=wave_index,
                    valid=valid, pad_tail=pad_tail,
                    pad_exhausted=pad_exhausted, pad_mesh=pad_mesh):
                out = jax.block_until_ready(out)
            t_get = time.perf_counter()
            with profiling.maybe_span("transfer:device_get"):
                host = [np.asarray(o) for o in out[:3]]
                si_h, ti_h = np.asarray(out[3]), np.asarray(out[4])
            _XFER_GET_S.inc(time.perf_counter() - t_get)
            _XFER_GET_B.inc(sum(h.nbytes for h in host))
        else:
            host = [np.asarray(o) for o in out[:3]]
            si_h, ti_h = np.asarray(out[3]), np.asarray(out[4])
        # dispatch→outputs-ready wall seconds, the overlapped host
        # assembly of block k+1 excluded
        step_s = max(
            0.0, (time.perf_counter() - t0) - (t_gather1 - t_gather0))
        first_dispatch = bool(compile_state
                              and compile_state.get("pending"))
        meshobs.RECORDER.record_wave(
            bucket, wave=wave_index, block=block, lanes=lane_names,
            n_pvs=n_pvs, t_step=ts, valid=valid, pad_tail=pad_tail,
            pad_exhausted=pad_exhausted, pad_mesh=pad_mesh,
            step_s=step_s, first=first_dispatch)
        if first_dispatch:
            compile_state["pending"] = False
            meshobs.RECORDER.record_compile(
                bucket, step="wave_step",
                geometry=compile_state.get("geometry", {}),
                seconds=step_s)
        block += 1
        for i, ln in enumerate(wave):
            if valids[i]:
                ln.emit([h[i][: valids[i]] for h in host])
                if ln.emit_features is not None:
                    ln.emit_features(
                        si_h[i][: valids[i]], ti_h[i][: valids[i]]
                    )
        # inter-block TI carry: the tail-repeat padding means [:, -1]
        # is the lane's last REAL frame even on a partial block
        # .copy(): a view would pin the whole previous output block
        # in host memory across the next iteration
        prev = host[0][:, -1].copy()
        first = False
        notify_done()
    # every lane is exhausted once the loop ends (covers lanes that
    # were empty from the first gather)
    for i in range(len(done)):
        done[i] = True
    notify_done()
    # clean exit only: on an exception a device_put/step may still be
    # reading a wave buffer (its outputs never fetched), so the buffers
    # are deliberately DROPPED, not released — same rule as AsyncWriter's
    # failure path (weakref bookkeeping reclaims them)
    for bufs in wave_bufs.values():
        pool.release(*bufs)


def wave_count(n_lanes: int, mesh) -> int:
    return math.ceil(n_lanes / mesh.shape["pvs"])
