"""SPMD batch pipeline: the chain's hot path as one sharded device step.

This is the execution model the north star describes (BASELINE.json): per-PVS
pixel pipelines data-parallel over the "pvs" mesh axis, and the frame-time
axis sharded over "time" — the device analog of the reference's long-video
temporal partitioning (reference test_config.py:1162-1248 + p03:88-136,
SURVEY.md §5 "long-context"). TI needs each time-shard's first frame to see
the previous shard's last frame: a one-frame halo exchanged with
`lax.ppermute` over the "time" axis — the ring-attention-style neighbor
communication, riding ICI.

`avpvs_siti_step` is the single-chip flagship step (also the bench body);
`make_sharded_step` runs the same resize+features math in shard_map over a
(pvs, time) mesh — inlined rather than calling avpvs_siti_step, because
the sharded body flattens its (pvs, time) leading dims (the fused Pallas
kernels have no vmap batching rule) and owns the TI halo. A change to the
per-frame math must be applied to both (and to
parallel/p03_batch._sharded_resize_step, the p03 product variant).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry as tm
from ..telemetry import profiling
from ..telemetry.heartbeat import HEARTBEATS
from ..ops import metrics as metrics_ops
from ..ops import resize as resize_ops
from ..ops import siti as siti_ops
from ..utils.device import shard_map as _shard_map

_STEP_SECONDS = tm.histogram(
    "chain_device_step_seconds",
    "wall time of each jitted device-step call, device compute included "
    "(the call blocks until outputs are ready when telemetry is on; the "
    "first call of a step also covers trace + XLA compile)",
    ("step",),
)


def _instrument_step(fn, step: str):
    """Wrap a jitted step so each call lands in the latency histogram and
    the first call (the compile) is flagged in the event log. Transparent
    when telemetry is off: one flag check per call. When on, the call
    blocks until outputs are ready — dispatch is async, and an unblocked
    timer would record ~0 and misattribute device compute to whatever
    blocks next (the host readback); every caller fetches the outputs to
    host right after the step, so the sync costs no real overlap."""
    bound = _STEP_SECONDS.labels(step=step)
    state = {"first": True}

    @functools.wraps(fn)
    def call(*args, **kwargs):
        if not tm.enabled():
            return fn(*args, **kwargs)
        # in-flight for the duration of the blocking call: a device step
        # stuck in compile or a wedged collective shows up in /status
        # (and eventually the watchdog) with the step's name on it
        hb = HEARTBEATS.register(step, kind="device_step")
        t0 = time.perf_counter()
        try:
            # under --profile, the device:<step> span lands in the merged
            # timeline on the tracer's perf_counter clock (same domain as
            # every host span) and TraceAnnotation labels the dispatch
            # inside a live jax.profiler capture; both no-op otherwise
            with profiling.maybe_span(f"device:{step}"), \
                    profiling.device_annotation(step):
                out = jax.block_until_ready(fn(*args, **kwargs))
        except BaseException:
            hb.finish("fail")
            raise
        hb.finish("ok")
        dur = time.perf_counter() - t0
        bound.observe(dur)
        if state["first"]:
            state["first"] = False
            tm.emit("device_step", step=step, first=True,
                    duration_s=round(dur, 4))
            # the compile ledger (parallel/meshobs.py): the first call is
            # the compile-inclusive one — one entry per instrumented step,
            # keyed by the step name (its compile identity: one jit per
            # make_* call, cached per geometry by the callers)
            from . import meshobs

            meshobs.RECORDER.record_compile(
                step, step=step, geometry={}, seconds=dur)
        return out

    return call


def iter_device_ahead(blocks, put):
    """One-deep host→device transfer pipeline: yield `(host_item,
    device_item)` pairs with the NEXT item's `put` (a `jax.device_put`
    wrapper) already ISSUED before the current pair is handed to the
    consumer — so transfer k+1 rides the DMA engines while the consumer's
    dispatched compute on k is still in flight, instead of serializing
    decode → transfer → compute per chunk.

    The host item is yielded alongside the device item so the consumer
    can hand it to `AsyncWriter.put(..., recycle=...)` — pooled blocks
    must not be reused until the compute that read them completes, and
    the writer's output fetch is the provable completion point."""
    pending = None
    for item in blocks:
        dev = put(item)
        if pending is not None:
            yield pending
        pending = (item, dev)
    if pending is not None:
        yield pending


def avpvs_siti_step(
    y: jnp.ndarray,
    u: jnp.ndarray,
    v: jnp.ndarray,
    dst_h: int,
    dst_w: int,
    prev_last: Optional[jnp.ndarray] = None,
    kernel: str = "lanczos",
):
    """One AVPVS+features step on a [T, H, W] clip (single shard / chip):
    Lanczos upscale of luma+chroma, SI per frame, TI per frame (using
    prev_last as the frame before this shard when given).

    Returns (up_y, up_u, up_v, si[T], ti[T]).
    """
    up_y = resize_ops.resize_plane(y, dst_h, dst_w, kernel)
    up_u = resize_ops.resize_plane(u, dst_h // 2, dst_w // 2, kernel)
    up_v = resize_ops.resize_plane(v, dst_h // 2, dst_w // 2, kernel)

    if prev_last is None:
        # quantized-depth input feeds the fused feature kernels directly
        # on TPU (no f32 materialization of the 4K batch)
        si, ti = siti_ops.siti(up_y)
    else:
        # same single-implementation path as the sharded steps: a 1-lane
        # batch with prev_last (the previous shard's last QUANTIZED luma)
        # as the halo frame
        si_b, ti_b = siti_ops.siti_batch(
            up_y[None], prev_last[None].astype(up_y.dtype)
        )
        si, ti = si_b[0], ti_b[0]
    return up_y, up_u, up_v, si, ti


def make_sharded_step(mesh: Mesh, dst_h: int, dst_w: int, kernel: str = "lanczos"):
    """Jit a full batched step over the (pvs, time) mesh.

    In/out: y [B, T, H, W] uint8 (+ u, v at chroma res) sharded
    P("pvs","time",None,None); returns upscaled planes and SI/TI [B, T].
    The TI halo is exchanged between neighboring time shards with ppermute;
    the first shard falls back to its own first frame (TI[0] = 0 globally).
    """
    n_time = mesh.shape["time"]

    def shard_fn(y, u, v):
        # y: [B_loc, T_loc, H, W] local block; flatten the (pvs, time)
        # leading dims so resize/SI run un-vmapped (the fused Pallas
        # kernels have no batching rule)
        b, t = y.shape[0], y.shape[1]

        def flat(p):
            return p.reshape((-1,) + p.shape[2:])

        def unflat(p):
            return p.reshape((b, t) + p.shape[1:])

        up_y = unflat(resize_ops.resize_plane(flat(y), dst_h, dst_w, kernel))
        up_u = unflat(
            resize_ops.resize_plane(flat(u), dst_h // 2, dst_w // 2, kernel)
        )
        up_v = unflat(
            resize_ops.resize_plane(flat(v), dst_h // 2, dst_w // 2, kernel)
        )

        # halo: previous time-shard's last upscaled luma frame, exchanged
        # at CONTAINER depth (u8/u16 ppermute = 1/4 the ICI bytes of f32)
        last = up_y[:, -1]
        perm = [(i, (i + 1) % n_time) for i in range(n_time)]
        prev_last = lax.ppermute(last, "time", perm)
        t_idx = lax.axis_index("time")
        # shard 0 has no predecessor: use its own first frame (diff -> 0)
        prev_last = jnp.where(t_idx == 0, up_y[:, 0], prev_last)

        # both features in one pass (fused on TPU; see siti.siti_batch)
        si, ti = siti_ops.siti_batch(up_y, prev_last)
        return up_y, up_u, up_v, si, ti

    mapped = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P("pvs", "time", None, None),
            P("pvs", "time", None, None),
            P("pvs", "time", None, None),
        ),
        out_specs=(
            P("pvs", "time", None, None),
            P("pvs", "time", None, None),
            P("pvs", "time", None, None),
            P("pvs", "time"),
            P("pvs", "time"),
        ),
    )
    return _instrument_step(jax.jit(mapped), "sharded_avpvs_step")


def make_batch_metrics_step(mesh: Mesh):
    """Sharded per-frame PSNR/SSIM vs a reference batch (BASELINE config 4),
    data-parallel over (pvs, time) — frame-local, no halo needed."""

    def shard_fn(ref, deg):
        b, t = ref.shape[0], ref.shape[1]
        r = ref.reshape((-1,) + ref.shape[2:])
        d = deg.reshape((-1,) + deg.shape[2:])
        psnr = jax.vmap(metrics_ops.psnr_frame)(r, d).reshape(b, t)
        ssim = jax.vmap(metrics_ops.ssim_frame)(r, d).reshape(b, t)
        return psnr, ssim

    mapped = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("pvs", "time", None, None), P("pvs", "time", None, None)),
        out_specs=(P("pvs", "time"), P("pvs", "time")),
    )
    return _instrument_step(jax.jit(mapped), "batch_metrics_step")
