"""Codec-prior extraction: mine MV/QP/frame-type metadata from the
bitstreams the chain already decodes (docs/PRIORS.md).

The decode loop the chain pays for anyway also computes motion vectors
and per-block QP; this package exports them through the native boundary
(`mp_decoder_open_priors`), persists them as a compact `.priors.npz`
sidecar committed to the content-addressed store, and feeds them to
device-side consumers — MV-informed temporal features next to SI/TI
(`priors.features`) and complexity classification without the CRF-23
proxy re-encode (`tools complexity --priors`).
"""

from .model import (  # noqa: F401
    PRIORS_SCHEMA_VERSION,
    SIDECAR_SUFFIX,
    PriorsData,
    ensure_priors,
    load_priors,
    priors_plan,
    save_priors,
    sidecar_path,
)
from .extract import extract_priors  # noqa: F401
