"""Streaming reader over the native codec-prior decoder.

One `mp_priors_next_batch` call per chunk (one ctypes crossing, one GIL
release — the same batch-crossing discipline as `mp_decoder_next_batch`),
records and MV rows landing in pooled numpy blocks (io/bufpool.py). No
pixel planes cross the boundary: a priors pass over a clip moves a few
hundred KB, not gigabytes, which is why complexity classification on top
of it needs no proxy re-encode.

MV coverage is decoder-dependent: FFmpeg's h264/mpegvideo families
export motion vectors; the native hevc/vp9/av1 decoders do not (their
records still carry frame types, packet sizes and QP where available).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from .. import telemetry as tm
from ..io import medialib
from ..io.bufpool import DEFAULT_POOL, BufferPool

_FRAMES = tm.counter(
    "chain_priors_frames_total", "frames whose coding metadata was extracted"
)
_MVS = tm.counter(
    "chain_priors_mvs_total", "motion vectors extracted from bitstreams"
)

#: initial MV block capacity (rows). 1<<16 rows ≈ 1.8 MB and holds ~8
#: 1080p frames' worth of 16x16-block MVs; a denser frame triggers the
#: grow-and-retry path (PriorsBufferTooSmall), nothing is lost.
_MV_CAP0 = 1 << 16


def default_chunk_frames() -> int:
    """Frames per native priors crossing. Chunk granularity never changes
    the extracted records — only how many ctypes crossings a clip costs —
    so the knob stays out of the plan (same contract as PC_CHUNK_FRAMES)."""
    # plan-exempt: (crossing granularity only; the record stream is identical at any chunking — pinned by the chunking-parity test)
    raw = os.environ.get("PC_PRIORS_CHUNK", "").strip()
    try:
        return max(1, int(raw)) if raw else 256
    except ValueError:
        return 256


def iter_priors_chunks(
    path: str,
    chunk_frames: Optional[int] = None,
    pool: Optional[BufferPool] = None,
    threads: int = 0,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Yield (records, mv_rows) per chunk: records is a PRIORS_DTYPE
    structured array of n frames, mv_rows an [m, MV_FIELDS] int32 array
    holding those frames' MVs in frame order (records["mv_count"]
    delimits per-frame spans). The yielded arrays are trimmed VIEWS of
    pooled blocks — consumers copy what they keep; the backing blocks are
    released when the generator advances."""
    chunk = chunk_frames or default_chunk_frames()
    pool = pool or DEFAULT_POOL
    handle = medialib.priors_open(path, threads=threads)
    mv_cap = _MV_CAP0
    try:
        recs = pool.acquire((chunk,), medialib.PRIORS_DTYPE)
        mv = pool.acquire((mv_cap, medialib.MV_FIELDS), np.int32)
        try:
            while True:
                try:
                    n = medialib.priors_next_batch(handle, recs, mv)
                except medialib.PriorsBufferTooSmall:
                    # one frame alone overflowed the MV block: double it
                    # and retry (the frame is parked natively)
                    pool.release(mv)
                    mv_cap *= 2
                    mv = pool.acquire((mv_cap, medialib.MV_FIELDS), np.int32)
                    continue
                if n == 0:
                    break
                rows = int(recs["mv_count"][:n].sum())
                if tm.enabled():
                    _FRAMES.inc(n)
                    _MVS.inc(rows)
                yield recs[:n], mv[:rows]
        finally:
            pool.release(recs, mv)
    finally:
        medialib.priors_close(handle)


def extract_priors(path: str, chunk_frames: Optional[int] = None,
                   pool: Optional[BufferPool] = None, threads: int = 0):
    """Extract the full per-frame prior stream of `path` into a PriorsData
    (priors/model.py). One native crossing per chunk; memory stays bounded
    by the chunk size, not the clip length."""
    from .model import PriorsData  # late: model imports store, keep cheap

    rec_parts: list[np.ndarray] = []
    mv_parts: list[np.ndarray] = []
    for recs, mv in iter_priors_chunks(
        path, chunk_frames=chunk_frames, pool=pool, threads=threads
    ):
        rec_parts.append(recs.copy())
        mv_parts.append(mv.copy())
    if rec_parts:
        records = np.concatenate(rec_parts)
        mv_rows = (
            np.concatenate(mv_parts)
            if mv_parts
            else np.empty((0, medialib.MV_FIELDS), np.int32)
        )
    else:
        records = np.empty(0, medialib.PRIORS_DTYPE)
        mv_rows = np.empty((0, medialib.MV_FIELDS), np.int32)
    offsets = np.zeros(len(records) + 1, np.int64)
    np.cumsum(records["mv_count"], out=offsets[1:])
    return PriorsData(
        width=int(records["width"][0]) if len(records) else 0,
        height=int(records["height"][0]) if len(records) else 0,
        pts=records["pts"].astype(np.float64),
        pict_type=records["pict_type"].astype(np.int8),
        key_frame=records["key_frame"].astype(np.int8),
        pkt_size=records["pkt_size"].astype(np.int64),
        qp_mean=records["qp_mean"].astype(np.float64),
        qp_var=records["qp_var"].astype(np.float64),
        qp_blocks=records["qp_blocks"].astype(np.int32),
        mv_offsets=offsets,
        mv_rows=mv_rows.astype(np.int32),
    )
