"""Device-side MV-informed temporal features (the SI/TI siblings).

Where ops/siti.py measures structure from decoded *pixels*, this module
measures it from the coding metadata the encoder already paid to
compute: per-frame MV magnitude statistics (mean / p95), the divergence
of the block motion field (expansion/contraction — zooms and dolly
moves that pure magnitude misses), and the intra-coded block fraction
(how much of each inter frame the encoder gave up predicting — a strong
occlusion/scene-change cue). ANVIL (arXiv:2603.26835) and FAST
(arXiv:1603.08968) both build on exactly these compressed-domain cues.

Shape discipline: the jit'd kernels (`mv_magnitudes`,
`field_divergence`) run on shapes that are constant per clip geometry,
so they compile once and stay hot across a corpus. The per-frame ragged
reductions in `frame_mv_stats` are deliberately host-side numpy
(`np.hypot` + `np.bincount` keyed by frame id): every clip has a
different total MV count, and a jit'd formulation would retrace and
recompile a trivial kernel once per clip — far more expensive than the
O(m) reduction itself.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: MV row field indices (io/medialib.MV_FIELDS layout)
SRC_X, SRC_Y, DST_X, DST_Y, MV_W, MV_H, MV_SOURCE = range(7)

#: pict_type values (priors/model.py)
_PICT_I = 1


@jax.jit
def mv_magnitudes(mv_rows: jnp.ndarray) -> jnp.ndarray:
    """Per-row displacement magnitude |dst - src| of [m, 7] MV rows."""
    rows = mv_rows.astype(jnp.float32)
    dx = rows[:, DST_X] - rows[:, SRC_X]
    dy = rows[:, DST_Y] - rows[:, SRC_Y]
    return jnp.sqrt(dx * dx + dy * dy)


def _segment_ids(mv_offsets: np.ndarray) -> np.ndarray:
    """Frame id per MV row from the ragged offsets table."""
    counts = np.diff(mv_offsets)
    return np.repeat(np.arange(len(counts)), counts)


def frame_mv_stats(data) -> dict[str, np.ndarray]:
    """Per-frame MV summary for a PriorsData: {"mean_mag", "p95_mag",
    "mv_count"} float32/int arrays of length n_frames (0 magnitude for
    frames without MVs — I frames, and codecs that export none).
    Host-side numpy on purpose: the ragged total-MV shape differs per
    clip, and a jit'd reduction would recompile per clip (see module
    docstring)."""
    n = data.n_frames
    if n == 0 or data.n_mvs == 0:
        zero = np.zeros(n, np.float32)
        return {"mean_mag": zero, "p95_mag": zero.copy(),
                "mv_count": np.zeros(n, np.int64)}
    seg = _segment_ids(data.mv_offsets)
    rows = data.mv_rows.astype(np.float32)
    mags = np.hypot(rows[:, DST_X] - rows[:, SRC_X],
                    rows[:, DST_Y] - rows[:, SRC_Y])
    counts = np.diff(data.mv_offsets)
    sums = np.bincount(seg, weights=mags, minlength=n)
    mean = np.where(counts > 0, sums / np.maximum(counts, 1), 0.0)
    # p95 is inherently order-statistic: compute per frame on the ragged
    # spans host-side (bounded by MV count, not pixels — cheap)
    p95 = np.zeros(n, np.float32)
    for i in np.nonzero(counts)[0]:
        p95[i] = np.percentile(mags[data.mv_offsets[i]:data.mv_offsets[i + 1]],
                               95.0)
    return {"mean_mag": mean.astype(np.float32), "p95_mag": p95,
            "mv_count": counts.astype(np.int64)}


def mv_field(data, i: int, block: int = 16) -> np.ndarray:
    """Dense block motion field of frame `i`: [gh, gw, 2] float32 of
    (dx, dy) per `block`-pixel cell (cells without an MV stay 0)."""
    gh = max(1, (data.height + block - 1) // block)
    gw = max(1, (data.width + block - 1) // block)
    field = np.zeros((gh, gw, 2), np.float32)
    rows = data.mv_for(i)
    if rows.shape[0] == 0:
        return field
    cx = np.clip(rows[:, DST_X] // block, 0, gw - 1)
    cy = np.clip(rows[:, DST_Y] // block, 0, gh - 1)
    field[cy, cx, 0] = rows[:, DST_X] - rows[:, SRC_X]
    field[cy, cx, 1] = rows[:, DST_Y] - rows[:, SRC_Y]
    return field


@jax.jit
def field_divergence(field: jnp.ndarray) -> jnp.ndarray:
    """Mean |divergence| of a [gh, gw, 2] motion field via central
    differences — near 0 for pans (uniform motion), large for zooms."""
    vx, vy = field[..., 0], field[..., 1]
    dvx = (jnp.roll(vx, -1, axis=1) - jnp.roll(vx, 1, axis=1)) * 0.5
    dvy = (jnp.roll(vy, -1, axis=0) - jnp.roll(vy, 1, axis=0)) * 0.5
    return jnp.mean(jnp.abs(dvx + dvy))


def frame_divergence(data, block: int = 16) -> np.ndarray:
    """Per-frame mean |divergence| of the block motion field."""
    out = np.zeros(data.n_frames, np.float32)
    for i in range(data.n_frames):
        if data.mv_offsets[i + 1] > data.mv_offsets[i]:
            out[i] = float(field_divergence(jnp.asarray(mv_field(data, i,
                                                                 block))))
    return out


def intra_fraction(data) -> np.ndarray:
    """Per-frame fraction of frame area NOT covered by inter-predicted
    (MV-carrying) blocks: 1.0 for I frames by definition; for P/B frames
    a high value means the encoder fell back to intra coding — occlusion,
    scene change, or motion too complex to predict."""
    n = data.n_frames
    out = np.ones(n, np.float32)
    area = float(max(1, data.width * data.height))
    for i in range(n):
        if data.pict_type[i] == _PICT_I:
            continue
        rows = data.mv_for(i)
        if rows.shape[0] == 0:
            # no MV export for this codec/frame: no coverage claim — keep
            # 1.0 only for genuine I frames, report NaN-free neutral 0
            out[i] = 0.0 if not data.has_mvs() else 1.0
            continue
        # bi-predicted blocks export one MV row PER DIRECTION (source
        # -1/+1) over the same pixels — dedup by block anchor so a B
        # frame's covered area isn't double-counted
        uniq = np.unique(rows[:, [DST_X, DST_Y, MV_W, MV_H]], axis=0)
        covered = float((uniq[:, 2].astype(np.int64)
                         * uniq[:, 3].astype(np.int64)).sum())
        out[i] = float(np.clip(1.0 - covered / area, 0.0, 1.0))
    return out


def temporal_features(data) -> dict[str, np.ndarray]:
    """The consumer-facing bundle: per-frame arrays
    mean_mag / p95_mag / mv_count / divergence / intra_fraction."""
    stats = frame_mv_stats(data)
    stats["divergence"] = frame_divergence(data)
    stats["intra_fraction"] = intra_fraction(data)
    return stats
