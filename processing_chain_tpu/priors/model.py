"""The priors sidecar: compact on-disk coding-metadata model.

`<src>.priors.npz` holds one clip's per-frame coding metadata — ragged
MV arrays via an offsets table, per-frame QP mean/variance, frame
types, compressed packet sizes — as plain npz members readable with
bare `np.load`. The writer is byte-deterministic (fixed zip metadata,
no timestamps): the sidecar is committed to the content-addressed
store as a plan-hashed artifact, and the plan-purity runtime recorder
(PC_PLAN_DEBUG) fails the suite if one plan hash ever maps to two
different byte streams — a time-stamped zip would trip it on every
warm rebuild.

The plan covers everything that determines sidecar bytes: the source
stream (by content digest via `file_ref`) and the extraction schema
version. Chunk granularity is deliberately absent — the record stream
is identical at any chunking (pinned by the chunking-parity test).
"""

from __future__ import annotations

import io
import os
import shutil
import tempfile
import time
import zipfile
from dataclasses import dataclass

import numpy as np

from .. import telemetry as tm
from ..io import medialib
from ..store import runtime as store_runtime
from ..store.keys import file_ref
from ..utils.fsio import atomic_write
from ..utils.log import get_logger

#: bump when the sidecar member set or record semantics change — part of
#: the extraction plan, so a bump rebuilds exactly the priors artifacts
PRIORS_SCHEMA_VERSION = 1

SIDECAR_SUFFIX = ".priors.npz"

#: AV_PICTURE_TYPE_* values surfaced in `pict_type`
PICT_I, PICT_P, PICT_B = 1, 2, 3

_EXTRACTS = tm.counter(
    "chain_priors_extract_total", "priors extraction passes executed"
)
_CACHE_HITS = tm.counter(
    "chain_priors_cache_hits_total",
    "priors requests served from the artifact store (no extraction)",
)
_EXTRACT_SECONDS = tm.histogram(
    "chain_priors_extract_seconds", "wall time of one priors extraction pass"
)


@dataclass
class PriorsData:
    """One clip's coding-metadata stream (arrays indexed by frame)."""

    width: int
    height: int
    pts: np.ndarray        # float64 [n] seconds
    pict_type: np.ndarray  # int8 [n] AV_PICTURE_TYPE_* (1 I, 2 P, 3 B)
    key_frame: np.ndarray  # int8 [n]
    pkt_size: np.ndarray   # int64 [n] compressed bytes per frame
    qp_mean: np.ndarray    # float64 [n], -1 when the codec exports no QP
    qp_var: np.ndarray     # float64 [n], -1 when absent
    qp_blocks: np.ndarray  # int32 [n] QP samples behind mean/var
    mv_offsets: np.ndarray  # int64 [n+1] ragged offsets into mv_rows
    mv_rows: np.ndarray     # int32 [total, MV_FIELDS]

    @property
    def n_frames(self) -> int:
        return int(len(self.pts))

    @property
    def n_mvs(self) -> int:
        return int(self.mv_rows.shape[0])

    def mv_for(self, i: int) -> np.ndarray:
        """MV rows of frame `i` (a view): [k, MV_FIELDS] int32 with fields
        src_x, src_y, dst_x, dst_y, w, h, source."""
        return self.mv_rows[self.mv_offsets[i]:self.mv_offsets[i + 1]]

    def has_mvs(self) -> bool:
        return self.n_mvs > 0

    def has_qp(self) -> bool:
        return bool((self.qp_blocks > 0).any())

    def summary(self) -> dict:
        """Operator-facing digest (tools priors show / telemetry events)."""
        qp = self.qp_mean[self.qp_blocks > 0]
        return {
            "frames": self.n_frames,
            "mvs": self.n_mvs,
            "width": self.width,
            "height": self.height,
            "i_frames": int((self.pict_type == PICT_I).sum()),
            "p_frames": int((self.pict_type == PICT_P).sum()),
            "b_frames": int((self.pict_type == PICT_B).sum()),
            "stream_bytes": int(self.pkt_size.sum()),
            "qp_mean": round(float(qp.mean()), 3) if qp.size else None,
        }


def _members(data: PriorsData) -> dict[str, np.ndarray]:
    return {
        "schema": np.array([PRIORS_SCHEMA_VERSION], np.int32),
        "geometry": np.array([data.width, data.height], np.int32),
        "pts": np.asarray(data.pts, np.float64),
        "pict_type": np.asarray(data.pict_type, np.int8),
        "key_frame": np.asarray(data.key_frame, np.int8),
        "pkt_size": np.asarray(data.pkt_size, np.int64),
        "qp_mean": np.asarray(data.qp_mean, np.float64),
        "qp_var": np.asarray(data.qp_var, np.float64),
        "qp_blocks": np.asarray(data.qp_blocks, np.int32),
        "mv_offsets": np.asarray(data.mv_offsets, np.int64),
        "mv_rows": np.ascontiguousarray(data.mv_rows, np.int32),
    }


def save_priors(path: str, data: PriorsData) -> None:
    """Write the sidecar atomically with BYTE-DETERMINISTIC zip contents:
    `np.savez` stamps members with the current time, which would hand the
    store two different byte streams for one plan hash — the exact
    corruption class the PC_PLAN_DEBUG recorder exists to catch."""
    members = _members(data)

    def _write(tmp: str) -> None:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            for name in sorted(members):
                buf = io.BytesIO()
                np.lib.format.write_array(buf, members[name],
                                          allow_pickle=False)
                info = zipfile.ZipInfo(name + ".npy",
                                       date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                info.external_attr = 0o600 << 16
                zf.writestr(info, buf.getvalue())

    atomic_write(path, _write)


def load_priors(path: str) -> PriorsData:
    with np.load(path, allow_pickle=False) as z:
        schema = int(z["schema"][0])
        if schema != PRIORS_SCHEMA_VERSION:
            raise ValueError(
                f"{path}: priors schema {schema} != supported "
                f"{PRIORS_SCHEMA_VERSION}"
            )
        geom = z["geometry"]
        return PriorsData(
            width=int(geom[0]),
            height=int(geom[1]),
            pts=z["pts"],
            pict_type=z["pict_type"],
            key_frame=z["key_frame"],
            pkt_size=z["pkt_size"],
            qp_mean=z["qp_mean"],
            qp_var=z["qp_var"],
            qp_blocks=z["qp_blocks"],
            mv_offsets=z["mv_offsets"],
            mv_rows=z["mv_rows"].reshape(-1, medialib.MV_FIELDS),
        )


def sidecar_path(src_path: str) -> str:
    return src_path + SIDECAR_SUFFIX


def priors_plan(src_path: str) -> dict:
    """The extraction plan: source stream by content digest + schema
    version. The "op" key is the plan surface's marker (chainlint
    plan-purity); anything that can change sidecar bytes belongs here."""
    return {
        "op": "priors_extract",
        "schema": PRIORS_SCHEMA_VERSION,
        "src": file_ref(src_path),
    }


def ensure_priors(
    src_path: str,
    store=None,
    force: bool = False,
    threads: int = 0,
) -> tuple[PriorsData, bool]:
    """The one entry point consumers call: (PriorsData, cache_hit).

    With a store (explicit or the process-wide active one) the sidecar is
    plan-hash addressed: a warm call plans ZERO extraction work — lookup,
    verified materialize, load. A miss extracts, writes the sidecar next
    to the source, and commits it so every later run (and every tenant of
    chain-serve sharing the store) gets it for free. Without a store the
    sidecar file next to the source is reused when present."""
    from .extract import extract_priors  # circular-import guard

    store = store if store is not None else store_runtime.active()
    side = sidecar_path(src_path)
    if store is not None and not force:
        ph = store.plan_hash(priors_plan(src_path))
        manifest = store.lookup(ph)
        if manifest is not None:
            if store.serve_hit(manifest, side):
                if tm.enabled():
                    _CACHE_HITS.inc()
                return load_priors(side), True
            # serve_hit False is EITHER corruption (manifest dropped —
            # fall through and re-extract) or a sidecar that cannot be
            # materialized next to the source (read-only corpus mount).
            # In the latter case the verified object bytes are still a
            # perfectly good warm hit: read them where they live.
            manifest = store.lookup(ph)
            if manifest is not None:
                try:
                    data = load_priors(
                        store.object_path(manifest.object["sha256"]))
                except (OSError, ValueError, KeyError):
                    pass
                else:
                    if tm.enabled():
                        _CACHE_HITS.inc()
                    return data, True
    elif store is None and not force and os.path.isfile(side):
        # make-style freshness, NOT content in the sidecar: embedding the
        # source's mtime in the artifact would give one plan hash two
        # byte streams when a source is rewritten with identical content
        # (the PC_PLAN_DEBUG violation class). A sidecar older than its
        # source is stale and re-extracted.
        try:
            fresh = os.path.getmtime(side) >= os.path.getmtime(src_path)
        except OSError:
            fresh = False
        if fresh:
            try:
                return load_priors(side), True
            except (OSError, ValueError, KeyError):
                pass  # unreadable or stale-schema sidecar: re-extract

    t0 = time.perf_counter()
    data = extract_priors(src_path, threads=threads)
    # the sidecar next to the source is a CONVENIENCE, not a requirement:
    # classification needs only the in-memory data, and read-only corpus
    # mounts are normal (proxy mode never needed write access outside its
    # tmp dir). On OSError the bytes go to a scratch file so the store
    # still gets its plan-hashed artifact — future runs warm-hit through
    # the object path above.
    commit_from = side
    scratch = None
    try:
        save_priors(side, data)
    except OSError as exc:
        if store is None:
            get_logger().warning(
                "priors: cannot write sidecar %s (%s); continuing without "
                "a cache", side, exc)
            commit_from = None
        else:
            scratch = tempfile.mkdtemp(prefix="pc-priors-")
            commit_from = os.path.join(scratch, os.path.basename(side))
            save_priors(commit_from, data)
    try:
        if store is not None and commit_from is not None:
            ph = store.plan_hash(priors_plan(src_path))
            store.commit(ph, commit_from, producer="priors",
                         provenance={"src": os.path.basename(src_path)})
    finally:
        if scratch is not None:
            shutil.rmtree(scratch, ignore_errors=True)
    if tm.enabled():
        _EXTRACTS.inc()
        _EXTRACT_SECONDS.observe(time.perf_counter() - t0)
        tm.emit(
            "priors_extract",
            src=os.path.basename(src_path),
            seconds=round(time.perf_counter() - t0, 4),
            **data.summary(),
        )
    return data, False
