"""chain-serve: the always-on processing service.

ROADMAP open item #2 — stop being a batch CLI, become a long-running
daemon. The pieces, each its own module:

    api.py        request grammar: tenant/priority validation, the
                  database/SRC/HRC ID regexes (config/ids), grid
                  expansion into per-PVS work units
    queue.py      durable, dedup-aware job queue: one atomic JSON record
                  per job (store tmp+rename idiom via utils/fsio),
                  `.inprogress` sentinels that REQUEUE on restart,
                  plan-hash attachment so overlapping requests share one
                  execution by construction
    scheduler.py  worker threads draining the queue through the engine's
                  JobRunner: stride-scheduled weighted fairness across
                  (tenant × priority class), singleflight claims, and
                  cross-request device-wave packing (parallel/p03_batch
                  bucket keys)
    executors.py  what a unit of work IS: the Executor protocol plus the
                  synthetic toy executor (CI/soak) and the device-wave
                  executor (real shared waves on the mesh)
    pressure.py   serve-side LRU pressure driving store/gc with the
                  plans of unfinished requests pinned
    service.py    the daemon: composes all of the above onto ONE
                  LiveServer (telemetry/live route registry) — /healthz,
                  /metrics, /status and /v1/* share a port

Entry point: `tools chain-serve` (tools/chain_serve.py).
API + durability + fairness semantics: docs/SERVE.md.
"""

from .api import RequestError, validate_request  # noqa: F401
from .service import ChainServeService  # noqa: F401
