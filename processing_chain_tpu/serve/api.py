"""Request grammar of the serve surface.

A processing request names a database and an SRC×HRC grid in the
P.NATS Phase 2 ID grammar the whole chain already enforces
(config/ids.py) — the serve layer validates at the front door with the
same regexes, so a malformed ID is a 400 here instead of a ConfigError
three stages deep. The grid expands into per-PVS *units*: one unit per
(database, SRC, HRC) cell plus the request's executor params, and the
unit (not the request) is the grain of queueing, dedup and execution —
two requests whose grids overlap share the overlapping units' jobs.
"""

from __future__ import annotations

import re
import secrets
from dataclasses import dataclass, field

from ..config import ids

#: priority classes and their scheduler weights (scheduler.py folds the
#: class weight into the tenant stride: interactive work drains ~4x
#: faster than normal, ~16x faster than bulk, but nothing starves)
PRIORITIES: dict[str, int] = {"interactive": 16, "normal": 4, "bulk": 1}

_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

#: client-supplied trace ids (distributed-tracing context propagation:
#: a gateway that already minted a trace can thread it through the
#: chain); server-minted ones are `tr-<hex>` and always match
_TRACE_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.:-]{3,127}$")


def new_trace_id() -> str:
    """Mint a fleet-unique trace id for one request. Every
    POST /v1/requests gets one (client-supplied `trace` wins), and it
    rides the request doc, the durable queue records, the span journal
    and the job events end to end (docs/TELEMETRY.md)."""
    return "tr-" + secrets.token_hex(8)

#: one request may expand to at most this many units (a full config-5
#: database is 1000 PVSes; anything past this is a typo'd range, and a
#: million-cell grid must arrive as many requests, not one)
MAX_UNITS = 4096


class RequestError(ValueError):
    """A request document failed validation (HTTP 400)."""


@dataclass(frozen=True)
class Unit:
    """One PVS-granular unit of work: the queue/dedup/execution grain."""

    database: str
    src: str
    hrc: str
    params: dict = field(default_factory=dict, hash=False, compare=False)

    @property
    def pvs_id(self) -> str:
        return f"{self.database}_{self.src}_{self.hrc}"


def _require(payload: dict, key: str, typ: type) -> object:
    if key not in payload:
        raise RequestError(f"missing required field {key!r}")
    value = payload[key]
    if not isinstance(value, typ):
        raise RequestError(
            f"field {key!r} must be {typ.__name__}, got {type(value).__name__}"
        )
    return value


def _id_list(payload: dict, key: str, kind: str, pattern: str) -> list[str]:
    raw = _require(payload, key, list)
    if not raw:
        raise RequestError(f"field {key!r} must name at least one {kind}")
    out: list[str] = []
    for value in raw:
        if not isinstance(value, str):
            raise RequestError(f"{key!r} entries must be strings")
        try:
            ids.validate(kind, value, pattern)
        except Exception as exc:  # ConfigError ⊂ ValueError
            raise RequestError(str(exc)) from exc
        if value not in out:  # dedup inside one request, order kept
            out.append(value)
    return out


def validate_request(payload: object) -> dict:
    """Validate a POST /v1/requests document; returns the normalized
    form {tenant, priority, database, srcs, hrcs, params}. Everything
    wrong raises RequestError with an operator-readable message."""
    if not isinstance(payload, dict):
        raise RequestError("request body must be a JSON object")
    tenant = _require(payload, "tenant", str)
    if not _TENANT_RE.match(tenant):
        raise RequestError(
            f"tenant {tenant!r} does not match {_TENANT_RE.pattern}"
        )
    priority = payload.get("priority", "normal")
    if priority not in PRIORITIES:
        raise RequestError(
            f"priority {priority!r} not one of {sorted(PRIORITIES)}"
        )
    database = _require(payload, "database", str)
    try:
        ids.validate("database", database, ids.REGEX_DATABASE_ID)
    except Exception as exc:
        raise RequestError(str(exc)) from exc
    srcs = _id_list(payload, "srcs", "SRC", ids.REGEX_SRC_ID)
    hrcs = _id_list(payload, "hrcs", "HRC", ids.REGEX_HRC_ID)
    if len(srcs) * len(hrcs) > MAX_UNITS:
        raise RequestError(
            f"grid of {len(srcs)}x{len(hrcs)} units exceeds the per-request "
            f"cap of {MAX_UNITS}; split it into several requests"
        )
    params = payload.get("params", {})
    if not isinstance(params, dict):
        raise RequestError("field 'params' must be a JSON object")
    trace = payload.get("trace")
    if trace is not None:
        if not isinstance(trace, str) or not _TRACE_RE.match(trace):
            raise RequestError(
                f"trace {trace!r} does not match {_TRACE_RE.pattern}"
            )
    return {
        "tenant": tenant,
        "priority": priority,
        "database": database,
        "srcs": srcs,
        "hrcs": hrcs,
        "params": params,
        "trace": trace,
    }


def expand_units(normalized: dict) -> list[Unit]:
    """The SRC×HRC grid as per-PVS units, row-major (src outer)."""
    return [
        Unit(
            database=normalized["database"], src=src, hrc=hrc,
            params=dict(normalized["params"]),
        )
        for src in normalized["srcs"]
        for hrc in normalized["hrcs"]
    ]
