"""The autoscale signal plane: desired replicas, with evidence.

ROADMAP item 2's remaining piece — "SLO-driven elasticity: the /fleet
queue-wait histograms become a scale signal" — lands here. The
`AutoscaleAdvisor` turns three measurements into one machine-readable
recommendation (`GET /fleet/scale-signal`):

  * **queue-wait burn**: active `slo_burn_queue_wait`/`slo_burn_e2e`
    alerts from the burn-rate engine (telemetry/alerts.py) — latency
    SLOs already breaching is the strongest "add capacity" evidence;
  * **per-class backlog**: queued record counts and predicted seconds
    per priority class (serve/queue.py `backlog()`), which pick the
    drain horizon — interactive backlog must drain inside a 2.5 s
    queue-wait band, bulk backlog gets 300 s;
  * **seconds-of-work-in-queue**: the calibrated cost model's
    predicted outstanding seconds (serve/cost.py, PR 12/14) divided by
    per-replica throughput (`workers` predicted-seconds per wall
    second) — the steady-state capacity term.

The recommendation is re-graded by every service maintenance control
tick and journaled (kind=`scale` records in the alert journal — the
same files the alerts live in) whenever the desired count changes, so
a scale decision is always attributable to the evidence that produced
it. Scale-down is held for `scale_down_hold_s` of sustained calm;
scale-up is immediate. Confidence is explicit: a cold cost model or a
young engine marks the signal as low-confidence rather than silently
guessing.

An external autoscaler consumes the signal; this module never starts
or stops replicas itself.
"""

from __future__ import annotations

import math
import time
from typing import Optional

from ..telemetry import catalog
from ..telemetry.events import emit
from ..telemetry.metrics import gauge
from ..utils import lockdebug

DESIRED = gauge(
    "chain_scale_desired_replicas",
    "replicas the autoscale advisor currently recommends",
)
BACKLOG_S = gauge(
    "chain_scale_backlog_seconds",
    "predicted seconds of queued work behind the scale signal",
)

#: alert rules whose firing is direct scale-up evidence
_BURN_RULES = ("slo_burn_queue_wait", "slo_burn_e2e")

#: sustained-calm seconds before a scale-down is recommended (scaled
#: by window_scale like the alert windows)
DEFAULT_SCALE_DOWN_HOLD_S = 120.0


class AutoscaleAdvisor:
    """Grades the desired replica count from the queue's backlog, the
    cost model's outstanding seconds, and the burn-rate engine's
    active alerts. One advisor per replica; recommendations carry the
    grading replica so concurrent graders stay attributable."""

    def __init__(self, journal, replica: str, *,
                 workers: int = 2,
                 min_replicas: int = 1,
                 max_replicas: int = 32,
                 scale_down_hold_s: float = DEFAULT_SCALE_DOWN_HOLD_S,
                 window_scale: float = 1.0) -> None:
        self.journal = journal  # shared AlertJournal (never raises)
        self.replica = replica
        self.workers = max(1, int(workers))
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.scale_down_hold_s = (float(scale_down_hold_s)
                                  * float(window_scale))
        self._lock = lockdebug.make_lock("autoscale")
        self._last: Optional[dict] = None     # guarded-by: _lock
        self._last_desired: Optional[int] = None  # guarded-by: _lock
        self._below_since: Optional[float] = None  # guarded-by: _lock
        self._evaluations = 0                 # guarded-by: _lock

    # ------------------------------------------------------- evaluation

    def evaluate(self, *, current_replicas: int, backlog: dict,
                 outstanding_s: float, active_alerts: list,
                 calibrated: bool = False,
                 now: Optional[float] = None) -> dict:
        """One grading pass; returns (and caches) the scale-signal
        document, journaling it when the desired count moves."""
        now = time.time() if now is None else now
        current = max(1, int(current_replicas))
        outstanding_s = max(0.0, float(outstanding_s))
        reasons: list[str] = []

        # drain horizon: the tightest queue-wait band among classes
        # that actually hold backlog — interactive work waiting means
        # the fleet must drain FAST
        bands = catalog.SLO_BANDS["queue_wait_s"]
        horizons = [bands[cls] for cls, b in (backlog or {}).items()
                    if cls in bands and (b.get("count") or 0) > 0]
        horizon_s = min(horizons) if horizons else max(bands.values())

        # capacity term: replicas needed to drain the predicted
        # outstanding seconds inside the horizon, at `workers`
        # predicted-seconds of throughput per replica-second
        work_based = 1
        if outstanding_s > 0:
            work_based = math.ceil(
                outstanding_s / max(1e-9, horizon_s * self.workers))
            if work_based > 1:
                reasons.append("backlog_pressure")

        # burn term: latency SLOs already breaching — add capacity now
        burning = [a for a in (active_alerts or [])
                   if a.get("rule") in _BURN_RULES]
        burn_based = 1
        if burning:
            burn_based = current + max(1, current // 2)
            reasons.append("queue_wait_burn")

        desired = max(self.min_replicas, work_based, burn_based)
        desired = min(desired, self.max_replicas)
        if desired == self.max_replicas and \
                max(work_based, burn_based) > self.max_replicas:
            reasons.append("max_ceiling")

        # scale-down hold: a quiet moment is not evidence of a quiet
        # hour — recommend fewer replicas only after sustained calm
        with self._lock:
            self._evaluations += 1
            evaluations = self._evaluations
            if desired < current:
                if self._below_since is None:
                    self._below_since = now
                if now - self._below_since < self.scale_down_hold_s:
                    desired = current
                    reasons.append("scale_down_hold")
                else:
                    reasons.append("idle_capacity")
            else:
                self._below_since = None
        if not reasons:
            reasons.append("steady")

        confidence = 0.35
        if calibrated:
            confidence += 0.25
        else:
            reasons.append("cold_cost_model")
        if evaluations >= 3:
            confidence += 0.25  # enough history to trust the windows
        if not burning or desired > current:
            confidence += 0.15  # the evidence and the verdict agree
        confidence = round(min(0.95, confidence), 2)

        signal = {
            "schema": 1,
            "generated_at": round(now, 3),
            "graded_by": self.replica,
            "replicas_current": current,
            "replicas_desired": int(desired),
            "confidence": confidence,
            "reasons": sorted(set(reasons)),
            "inputs": {
                "outstanding_s": round(outstanding_s, 3),
                "horizon_s": horizon_s,
                "workers_per_replica": self.workers,
                "backlog": backlog or {},
                "burning_alerts": [a.get("alert") for a in burning],
            },
        }
        DESIRED.set(desired)
        BACKLOG_S.set(outstanding_s)
        with self._lock:
            moved = self._last_desired != int(desired)
            self._last_desired = int(desired)
            self._last = signal
        if moved:
            self.journal.append({
                "kind": "scale",
                "desired": int(desired), "current": current,
                "confidence": confidence,
                "reasons": signal["reasons"],
                "inputs": signal["inputs"], "ts": round(now, 6),
            })
            emit("scale_signal", desired=int(desired), current=current,
                 confidence=confidence, reasons=signal["reasons"])
        return signal

    def latest(self) -> Optional[dict]:
        """The most recent recommendation (the /fleet/scale-signal
        payload), or None before the first grading pass."""
        with self._lock:
            return dict(self._last) if self._last else None
