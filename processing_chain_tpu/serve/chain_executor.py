"""The production executor: real databases through chain-serve.

`ChainExecutor` closes ROADMAP open item 2: a POSTed request whose
``params.config`` names a database YAML (the P.NATS Phase 2 grammar the
whole chain parses — config/test_config.py) expands into per-PVS units,
and each wave drives the REAL p01–p04 stages through the engine
JobRunner — segment encodes, metadata sidecars, the AVPVS render +
stalling pass, and every PostProcessing's CPVS. Every stage artifact is
committed to the content-addressed store under its own plan hash by the
engine jobs themselves (exactly as a batch `p00` run would), so
``/v1/artifacts/<hash>`` serves all four artifact families; the serve
unit's own artifact is a small deterministic **manifest** naming each
family's store hash, which is what a client walks to fetch them.

Identity: the unit plan folds ``file_ref(config)`` + ``file_ref(src)``
+ the byte-affecting knob values (effective AVPVS codec, FFV1 slices,
resize method — exactly the ``plan``-status inputs of
store/plan_schema.py). Folding the knobs is what keeps the manifest
byte-deterministic per plan hash (the PC_PLAN_DEBUG gate): the inner
artifact hashes the manifest lists are pure functions of (config bytes,
SRC bytes, knobs). A config edit re-runs the serve unit, but the inner
jobs are plan-hashed individually — everything untouched is a store
warm hit, so the re-run rebuilds only what the edit actually changed.

Execution discipline: chain waves SERIALIZE through a process-wide lock.
Two concurrent waves could otherwise both plan an encode of a segment
shared by sibling HRCs (one JobRunner dedups writers; two independent
ones cannot), and the device stages share one backend anyway. Across
replica processes the same overlap is benign-by-determinism (identical
plans produce identical bytes and the store commit is idempotent), but
deployments that hammer one database from many replicas should shard
databases per replica (docs/SERVE.md "Real database execution").

Online services (YouTube/Bitmovin segments) are refused as PERMANENT
failures: an always-on daemon must not reach for the network because a
config asked it to.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..store import keys
from ..store import runtime as store_runtime
from ..utils import lockdebug
from ..utils.fsio import atomic_write_text
from ..utils.runner import ChainError
from .api import RequestError, Unit
from .executors import record_waves

#: one chain wave at a time per process (module docstring)
_EXEC_LOCK = lockdebug.make_lock("serve_chain_exec")

#: JobRunner pool widths per phase (p01/p02 are host-pool work like the
#: batch stages; p03/p04 pipeline internally — engine/jobs caps apply)
_HOST_POOL = 4
_DEVICE_POOL = 2


class ChainExecutor:
    """Real SRC×HRC units through the full chain. Params:

        config    REQUIRED — server-side path of the database YAML;
                  the SRC files live next to it in the standard layout
                  (the operator mounts the corpus on the serving host)
    """

    kind = "chain"

    def __init__(self) -> None:
        #: parsed configs keyed by (abspath, mtime_ns, size) — reparsing
        #: per unit would probe every SRC per POST; touched only under
        #: _cache_lock (plan() runs on the HTTP thread, cost_features on
        #: scheduler workers)
        self._cache_lock = lockdebug.make_lock("serve_chain_cfgcache")
        self._configs: dict = {}       # guarded-by: _cache_lock
        self._complexity: dict = {}    # guarded-by: _cache_lock
        #: SRC digests this replica already first-contact-validated
        #: under PC_ISOLATE_DECODE (io/isolate): a clean verdict is a
        #: property of the BYTES, so one supervised decode per digest
        #: per replica, not per request
        self._validated: set = set()   # guarded-by: _cache_lock

    # ------------------------------------------------------------ config

    @staticmethod
    def _config_path(params: dict) -> str:
        return os.path.abspath(str(params.get("config", "")))

    def _config(self, path: str):
        """The parsed TestConfig for one database YAML, cached by stat
        signature (an edited config reparses, an unchanged one never
        re-probes its SRCs)."""
        from ..config import TestConfig

        st = os.stat(path)
        sig = (path, st.st_mtime_ns, st.st_size)
        with self._cache_lock:
            cached = self._configs.get(path)
            if cached is not None and cached[0] == sig:
                return cached[1]
        cfg = TestConfig(path)
        with self._cache_lock:
            self._configs[path] = (sig, cfg)
        return cfg

    def _pvs_of(self, unit: Unit):
        """The Pvs behind one unit, via the cached config. Raises
        RequestError (→ HTTP 400) when the grid names cells the
        database does not define — the front door's job, not a
        quarantine's."""
        path = self._config_path(unit.params)
        try:
            cfg = self._config(path)
        except OSError as exc:
            raise RequestError(
                f"params.config {path!r} is not readable: {exc}"
            ) from exc
        except Exception as exc:  # ConfigError ⊂ ValueError
            raise RequestError(
                f"params.config {path!r} failed to parse: {exc}"
            ) from exc
        if cfg.data.get("databaseId") != unit.database:
            raise RequestError(
                f"request database {unit.database!r} does not match "
                f"config databaseId {cfg.data.get('databaseId')!r}"
            )
        pvs = cfg.pvses.get(unit.pvs_id)
        if pvs is None:
            raise RequestError(
                f"PVS {unit.pvs_id!r} is not in the database's pvsList "
                "(check the srcs/hrcs grid against the config)"
            )
        return pvs

    # ----------------------------------------------------------- protocol

    def _knobs(self, pvs) -> dict:
        """The byte-affecting knob values (store/plan_schema.py 'plan'
        inputs), folded into the unit plan so the manifest's inner
        hashes are a pure function of the plan (module docstring)."""
        from ..io.medialib import MediaError
        from ..models import avpvs as av
        from ..ops.resize import plan_resize_method

        # unprobeable SRC (deferred poison, config/domain.py): the
        # parse already substituted a deterministic yuv420p stand-in
        # (Segment._set_pix_fmt) so the unit can ENQUEUE and let
        # execution convict the bytes through the failure taxonomy —
        # a 400 here would bypass the digest quarantine entirely
        # (docs/ROBUSTNESS.md)
        probe_deferred = pvs.src.probe_error is not None
        try:
            pix_fmt = pvs.get_pix_fmt_for_avpvs()
        except MediaError:
            # defensive: a consumer that still reaches stream_info
            pix_fmt = "yuv420p"
            probe_deferred = True
        codec = av.effective_avpvs_codec(pix_fmt)
        knobs = {
            "avpvs_codec": codec,
            "ffv1_slices": (
                av.ffv1_slices(av.ffv1_coding_threads())
                if codec == "ffv1" else None
            ),
            "resize": plan_resize_method(),
            "cpvs": {"rawvideo": False, "crf": 17},
        }
        if probe_deferred:
            # the plan was minted BLIND (fallback pix_fmt): say so in
            # the identity, so it can never collide with the clean
            # bytes' plan hash if the upload is later repaired and the
            # record re-armed — blind plans and probed plans are
            # different plans
            knobs["probe_deferred"] = True
        return knobs

    def plan(self, unit: Unit) -> dict:
        pvs = self._pvs_of(unit)
        return {
            "op": "serve.chain",
            "schema": 1,
            "database": unit.database,
            "src": unit.src,
            "hrc": unit.hrc,
            "config": keys.file_ref(self._config_path(unit.params)),
            "src_file": keys.file_ref(pvs.src.file_path),
            "knobs": self._knobs(pvs),
        }

    def output_name(self, unit: Unit, plan_hash: str) -> str:
        return f"{unit.pvs_id}_{plan_hash[:12]}.manifest.json"

    def validate_params(self, params: dict) -> None:
        config = params.get("config")
        if not isinstance(config, str) or not config:
            raise ValueError(
                "params.config must name the database YAML on the "
                "serving host"
            )
        if not os.path.isfile(config):
            raise ValueError(
                f"params.config {config!r} does not exist on the serving "
                "host"
            )

    def bucket_key(self, record_unit: dict) -> Optional[tuple]:
        try:
            params = record_unit.get("params", {})
            config = params.get("config")
            if not config:
                return None
            return ("chain", os.path.abspath(str(config)),
                    record_unit["database"])
        except (AttributeError, TypeError, ValueError, KeyError):
            return None  # pre-validation garbage record: unbatchable

    # -------------------------------------------------------- cost model

    def _src_complexity(self, src_path: str) -> Optional[float]:
        """Priors complexity of one SRC (QP-normalized rate — docs/
        PRIORS.md), memoized per path. The first request against a new
        SRC pays one extraction; the sidecar is store-committed, so
        every later request (and every replica sharing the store) is
        warm. The size/framerate facts underneath ride the shared
        post-encode packet scan (io/sharedscan.py), so a SRC the chain
        already scanned costs this executor no extra demux pass. None
        on any failure — the cost model stays total."""
        with self._cache_lock:
            if src_path in self._complexity:
                return self._complexity[src_path]
        try:
            from ..tools.complexity import get_priors_difficulty

            value = float(get_priors_difficulty(src_path)["complexity"])
        except Exception:  # noqa: BLE001 - priors are an estimate, not a gate
            value = None
        with self._cache_lock:
            self._complexity[src_path] = value
        return value

    def cost_features(self, record_unit: dict) -> Optional[dict]:
        """Predicted-cost features for serve/cost.py: encode/device/
        CPVS frame-megapixels from the config's own quality ladder,
        target codec + bitrate, priors complexity of the SRC. None (→
        the model's default cost) when the unit cannot be parsed —
        this runs inside the scheduler's packing pass and must not
        raise."""
        try:
            pvs = self._pvs_of(self._unit_from_record(record_unit))
        except Exception:  # noqa: BLE001 - totality like bucket_key
            return None
        try:
            from ..models import avpvs as av

            enc_fmpix = 0.0
            out_bytes = 0.0
            duration = 0.0
            codec = None
            for seg in pvs.segments:
                ql = seg.quality_level
                frames = float(seg.duration) * float(ql.fps)
                enc_fmpix += frames * ql.width * ql.height / 1e6
                duration += float(seg.duration)
                if codec is None:
                    codec = ql.video_codec
                if ql.video_bitrate:
                    out_bytes += float(ql.video_bitrate) * 1000.0 / 8.0 \
                        * float(seg.duration)
            w, h = av.avpvs_dimensions(pvs)
            canvas_frames = duration * av.canvas_fps(pvs)
            dev_fmpix = canvas_frames * w * h / 1e6
            cpvs_fmpix = 0.0
            for pp in pvs.test_config.post_processings:
                pp_frames = duration * float(
                    getattr(pp, "display_frame_rate", None) or
                    av.canvas_fps(pvs)
                )
                cpvs_fmpix += pp_frames * pp.display_width \
                    * pp.display_height / 1e6
            return {
                # four stage passes' worth of per-unit setup (probes,
                # JobRunner plumbing, store commits) before any pixel
                # moves — dominant for tiny units, noise for real ones
                "fixed_s": 1.0,
                "enc_fmpix": enc_fmpix,
                "dev_fmpix": dev_fmpix,
                "cpvs_fmpix": cpvs_fmpix,
                "out_bytes": out_bytes,
                "codec": codec,
                "complexity": self._src_complexity(pvs.src.file_path),
            }
        except Exception:  # noqa: BLE001 - totality like bucket_key
            return None

    @staticmethod
    def _unit_from_record(record_unit: dict) -> Unit:
        return Unit(
            database=record_unit["database"], src=record_unit["src"],
            hrc=record_unit["hrc"],
            params=dict(record_unit.get("params", {})),
        )

    def src_digest(self, record_unit: dict) -> Optional[str]:
        """Content digest of the unit's SRC file — the poison-
        quarantine key. Rides the store's stat-keyed DigestCache, so
        after the plan's own file_ref resolution this is a dict lookup,
        not a re-hash. Total like bucket_key."""
        try:
            pvs = self._pvs_of(self._unit_from_record(record_unit))
            store = store_runtime.active()
            if store is not None:
                return store.digests.digest(pvs.src.file_path)["sha256"]
            return keys.hash_file(pvs.src.file_path)["sha256"]
        except Exception:  # noqa: BLE001 - totality like bucket_key
            return None

    def _validate_first_contact(self, pvses: list) -> None:
        """PC_ISOLATE_DECODE (io/isolate, docs/ROBUSTNESS.md): every
        SRC digest this replica has not yet validated goes through one
        supervised-subprocess decode BEFORE any stage touches it — a
        hang is killed by the child's deadline, a native crash kills
        the child, and both re-raise as classified ChainErrors (poison
        / transient) instead of taking the replica down."""
        from ..io.isolate import isolate_decode_enabled, validate_src

        if not isolate_decode_enabled():
            return
        store = store_runtime.active()
        for pvs in pvses:
            path = pvs.src.file_path
            try:
                digest = (store.digests.digest(path)["sha256"]
                          if store is not None
                          else keys.hash_file(path)["sha256"])
            except OSError as exc:
                raise ChainError(
                    f"SRC {path} unreadable at first contact: {exc}",
                    kind="transient",
                ) from exc
            with self._cache_lock:
                if digest in self._validated:
                    continue
            try:
                validate_src(path)  # raises ChainError(kind=...) on verdict
            except ChainError as exc:
                # name the convicting digest on the verdict: the
                # scheduler then parks exactly this SRC's members from
                # a packed wave instead of retrying every sibling until
                # a solo wave re-convicts (docs/ROBUSTNESS.md)
                if exc.src_digest is None:
                    exc.src_digest = digest
                raise
            with self._cache_lock:
                self._validated.add(digest)

    # -------------------------------------------------------- execution

    def run_batch(self, units: list[Unit], outputs: list[str]) -> None:
        record_waves(len(units))
        store = store_runtime.active()
        if store is None:
            raise ChainError(
                "the chain executor requires an artifact store (it is "
                "what serves the stage artifacts)", kind="permanent",
            )
        # waves pack same-config units (bucket_key), but a solo wave of
        # a foreign record must still work: group defensively
        by_config: dict[str, list[int]] = {}
        for i, unit in enumerate(units):
            by_config.setdefault(
                self._config_path(unit.params), []
            ).append(i)
        with _EXEC_LOCK:
            for config_path, indices in by_config.items():
                self._run_config_group(
                    store, config_path,
                    [units[i] for i in indices],
                    [outputs[i] for i in indices],
                )

    # holds-lock: _EXEC_LOCK
    def _run_config_group(self, store, config_path: str,
                          units: list[Unit], outputs: list[str]) -> None:
        """p01–p04 for one database's units, through the engine
        JobRunner — store commits, sentinels, provenance and telemetry
        ride along exactly as in a batch run."""
        from ..config import TestConfig
        from ..engine.jobs import JobRunner
        from ..models import avpvs as av
        from ..models import cpvs as cp
        from ..models import metadata as md
        from ..models import segments as seg_model
        from ..utils.parse_args import _DEFAULT_SPINNER

        # a FRESH filtered parse (the cached one is unfiltered): the
        # chain's own planning decides from exactly these PVSes
        cfg = TestConfig(
            config_path,
            filter_pvses="|".join(u.pvs_id for u in units),
        )
        pvses = []
        for unit in units:
            pvs = cfg.pvses.get(unit.pvs_id)
            if pvs is None:
                raise ChainError(
                    f"PVS {unit.pvs_id!r} vanished from {config_path!r} "
                    "(config edited since submit?)", kind="permanent",
                )
            if pvs.is_online():
                raise ChainError(
                    f"PVS {unit.pvs_id!r} needs online services "
                    "(YouTube/Bitmovin), which chain-serve does not "
                    "execute", kind="permanent",
                )
            pvses.append(pvs)

        # first-contact hostile-input gate (PC_ISOLATE_DECODE): raises a
        # classified ChainError BEFORE any stage touches the bytes
        self._validate_first_contact(pvses)

        pool = min(_HOST_POOL, max(1, len(pvses)))
        av.set_default_fp_workers(min(_DEVICE_POOL, pool))

        # p01 — segment encodes (deduped across sibling HRCs by the
        # runner's writer table; store-warm ones skip)
        seg_model.reset_run_state()
        p01 = JobRunner(parallelism=pool, name="serve-p01")
        seg_jobs: dict = {}
        for segment in sorted(cfg.get_required_segments()):
            job = seg_model.encode_segment(segment)
            if job is not None:
                seg_jobs[segment.filename] = job
                p01.add(job)
        p01.run()

        # p02 — per-PVS metadata tables, through the pool (the jobs are
        # independent: one PVS's tables never read another's)
        p02 = JobRunner(parallelism=pool, name="serve-p02")
        md_jobs = {}
        for pvs in pvses:
            md_jobs[pvs.pvs_id] = md.metadata_job(pvs)
            p02.add(md_jobs[pvs.pvs_id])
        p02.run()

        # p03 — AVPVS render, then the stalling pass (planned only after
        # the renders exist: its plan hashes the wo_buffer bytes). Under
        # PC_FUSE_P04 (models/fused) each due AVPVS renders the stalling
        # pass + every CPVS context from the same decode — a chain wave
        # stops paying the per-stage re-decodes; warm/partial PVSes keep
        # the staged path exactly as before.
        from ..models import fused as fused_mod

        fuse = fused_mod.fused_p04_enabled()
        fanouts: dict = {}
        p03 = JobRunner(parallelism=min(_DEVICE_POOL, pool),
                        name="serve-p03")
        av_jobs = {}
        for pvs in pvses:
            fo = None
            if fuse:
                fo = fused_mod.FusedFanout(
                    pvs, spinner_path=_DEFAULT_SPINNER
                )
                fanouts[pvs.pvs_id] = fo
            av_jobs[pvs.pvs_id] = av.create_avpvs_wo_buffer(pvs, fanout=fo)
            p03.add(av_jobs[pvs.pvs_id])
        p03.run()
        p03_stall = JobRunner(parallelism=min(_DEVICE_POOL, pool),
                              name="serve-p03-stall")
        stall_jobs = {}
        for pvs in pvses:
            fo = fanouts.get(pvs.pvs_id)
            if fo is not None and fo.engaged and fo.stall_settled():
                # fused render produced + committed the stalled AVPVS;
                # its job still carries the manifest's plan identity (a
                # DEGRADED stalling member falls through to the staged
                # pass — models/fused graceful-degrade contract)
                if fo.stall_job is not None:
                    stall_jobs[pvs.pvs_id] = fo.stall_job
                continue
            job = av.apply_stalling(pvs, spinner_path=_DEFAULT_SPINNER)
            if job is not None:
                stall_jobs[pvs.pvs_id] = job
                p03_stall.add(job)
        p03_stall.run()

        # p04 — every PostProcessing context
        p04 = JobRunner(parallelism=min(_DEVICE_POOL, pool),
                        name="serve-p04")
        cpvs_jobs: dict = {}
        for pvs in pvses:
            cpvs_jobs[pvs.pvs_id] = []
            for pp in cfg.post_processings:
                job = cp.create_cpvs(pvs, pp)
                if job is not None:
                    cpvs_jobs[pvs.pvs_id].append(job)
                    p04.add(job)
        p04.run()

        # the unit manifests: every family artifact by store plan hash
        # (re-resolved NOW — the inputs exist with their final bytes)
        for unit, pvs, output in zip(units, pvses, outputs):
            manifest = self._manifest(
                store, unit, pvs,
                segment_jobs=[seg_jobs[s.filename] for s in pvs.segments
                              if s.filename in seg_jobs],
                metadata_job=md_jobs[pvs.pvs_id],
                avpvs_job=stall_jobs.get(pvs.pvs_id) or
                av_jobs[pvs.pvs_id],
                cpvs_jobs=cpvs_jobs[pvs.pvs_id],
            )
            atomic_write_text(
                output, json.dumps(manifest, sort_keys=True) + "\n"
            )

    @staticmethod
    def _artifact_entry(store, job) -> dict:
        entry = {
            "name": os.path.basename(job.output_path),
            "plan": store.plan_hash(job.plan),
            "size": os.path.getsize(job.output_path),
        }
        if job.extra_outputs:
            entry["extras"] = sorted(
                os.path.basename(p) for p in job.extra_outputs
            )
        return entry

    def _manifest(self, store, unit: Unit, pvs, segment_jobs,
                  metadata_job, avpvs_job, cpvs_jobs) -> dict:
        """One unit's deterministic artifact index: family → store plan
        hash(es). Byte-stable for a given unit plan (sort_keys +
        content-derived fields only) — the store commits it under the
        unit's plan hash, and PC_PLAN_DEBUG holds it to the same
        same-plan/same-bytes contract as every other artifact."""
        if any(job.plan is None for job in
               [*segment_jobs, metadata_job, avpvs_job, *cpvs_jobs]):
            raise ChainError(
                f"chain unit {unit.pvs_id}: a stage job carries no plan "
                "— its artifact cannot be store-addressed",
                kind="permanent",
            )
        return {
            "schema": 1,
            "op": "serve.chain",
            "pvs": unit.pvs_id,
            "database": unit.database,
            "artifacts": {
                "segments": [self._artifact_entry(store, j)
                             for j in segment_jobs],
                "metadata": self._artifact_entry(store, metadata_job),
                "avpvs": self._artifact_entry(store, avpvs_job),
                "cpvs": [self._artifact_entry(store, j)
                         for j in cpvs_jobs],
            },
        }
