"""Predicted-cost model for the serve scheduler (docs/SERVE.md
"Cost-aware scheduling & admission").

The priors subsystem (docs/PRIORS.md) predicts per-clip coding cost
from metadata the chain already decoded; this module turns that — plus
the request's own geometry/codec/bitrate facts — into *predicted
execution seconds per unit*, and the serve layer consumes the number
three ways:

  * **wave packing** — the scheduler balances predicted seconds per
    wave instead of unit counts, so one wave of four heavy clips and
    one wave of four trivial ones stop being "the same size"
    (`Scheduler.wave_budget_s`);
  * **admission control** — a request whose cold units exceed the
    per-request or per-tenant budget is refused AT POST TIME with a
    429-style forensic body naming the predicted cost, the budget and
    the heaviest units, instead of becoming hours of durable queue
    backlog (`check_admission`);
  * **accounting** — per-tenant predicted/observed seconds ride the
    metrics surface (`chain_serve_cost_*`), merged fleet-wide by
    telemetry/fleet.py into /fleet and `tools fleet-top`.

The model is deliberately a small, documented parametric formula over
features each executor extracts from its own units
(`Executor.cost_features`), because an auditable estimator beats an
opaque one: the **feedback loop** records observed execution seconds
against each unit's prediction at settle time (`CostLedger.observed`)
and reports the model error (ratio percentiles, MAPE), so an operator
can SEE when the coefficients have drifted from the hardware.

The formula (coefficients below, seconds):

    cost_s = BASE_S + fixed_s                        # per-unit overhead
           + work_s                                  # declared work (synthetic)
           + out_bytes * BYTES_S                     # artifact write
           + enc_fmpix  * ENC_S_PER_FMPIX * codec_mult * complexity_mult
           + dev_fmpix  * DEVICE_S_PER_FMPIX        # device resize/render
           + cpvs_fmpix * CPVS_S_PER_FMPIX          # per-context rewrites

where *_fmpix are frame-megapixels (frames × width × height / 1e6),
`codec_mult` scales encoder families by their measured relative cost,
and `complexity_mult` comes from the priors complexity score
(QP-normalized rate — tools/complexity.get_priors_difficulty): a clip
twice as complex as the reference point costs ~2^(Δ/2) more to encode.
"""

from __future__ import annotations

import math
from typing import Optional

from .. import telemetry as tm
from ..utils import lockdebug

_PREDICTED = tm.counter(
    "chain_serve_cost_predicted_seconds_total",
    "predicted execution seconds admitted into the queue, per tenant",
    ("tenant",),
)
_OBSERVED = tm.counter(
    "chain_serve_cost_observed_seconds_total",
    "observed execution seconds of settled units, per tenant",
    ("tenant",),
)
_ERROR_RATIO = tm.histogram(
    "chain_serve_cost_error_ratio",
    "observed/predicted execution-seconds ratio per settled unit — the "
    "cost model's audit trail (1.0 = perfect prediction)",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.1, 1.5, 2.0, 4.0, 10.0),
)
_REJECTED = tm.counter(
    "chain_serve_cost_rejected_total",
    "requests refused by cost admission control, per reason",
    ("reason",),
)
_CAL_SCALE = tm.gauge(
    "chain_serve_cost_calibration_scale",
    "current per-host calibration multiplier applied to every cost "
    "prediction (1.0 = the documented base coefficients; refit from "
    "the CostLedger's observed/predicted ratio ring)",
)

# ------------------------------------------------------- model constants
#
# Calibrated against this repo's own CPU bench numbers (docs/PERF.md:
# e2e ffv1 ~19 f/s at 160×90–640×360 scale ⇒ tens of ms per
# frame-megapixel across the four stages). Deliberately coarse — the
# feedback loop (`CostLedger.report`) is the instrument that says when
# they drift; the scheduler only needs RELATIVE ranking to pack waves
# and the admission gate only needs the right order of magnitude.

#: fixed per-unit overhead (job bookkeeping, store commit, probes);
#: executors with heavier per-unit setup (the chain's four stage
#: passes) add their own `fixed_s` feature on top
BASE_S = 0.02
#: seconds per artifact byte written (≈ 300 MB/s effective writeback)
BYTES_S = 1.0 / (300 * 1024 * 1024)
#: encode seconds per frame-megapixel (x264-class software encode;
#: from the repo's own e2e bench: ~0.3 s/fMpix across the four stages
#: on the reference container, split ~1/3 encode)
ENC_S_PER_FMPIX = 0.10
#: device resize/render seconds per frame-megapixel (AVPVS pass)
DEVICE_S_PER_FMPIX = 0.10
#: per-PostProcessing CPVS rewrite seconds per frame-megapixel
CPVS_S_PER_FMPIX = 0.08
#: encoder-family relative cost multipliers (libx264 ≡ 1.0)
CODEC_MULT = {
    "h264": 1.0, "libx264": 1.0,
    "h265": 2.5, "hevc": 2.5, "libx265": 2.5,
    "vp9": 3.0, "libvpx-vp9": 3.0,
    "av1": 4.0, "libaom-av1": 4.0, "libsvtav1": 2.0,
}
#: priors complexity score at which complexity_mult == 1.0 (the
#: reference-bitrate normalization of ops/siti puts typical SD/HD
#: content near here; see tools/complexity.py)
COMPLEXITY_REF = 5.0
#: complexity units per doubling of predicted encode cost
COMPLEXITY_PER_DOUBLING = 2.0
#: complexity_mult clamp — the model must never let one mis-probed clip
#: claim a 1000x cost
COMPLEXITY_MULT_RANGE = (0.5, 4.0)
#: predicted cost for a unit whose features are unknowable (foreign
#: record, raising feature hook): keeps packing/accounting total
DEFAULT_COST_S = 1.0

# ----------------------------------------------------- host calibration
#
# The base coefficients above were measured on ONE reference container;
# a deployment's hosts run the same formula at a different absolute
# speed (and the fused p04 path shifts execution seconds again). The
# calibration layer refits a single per-host SCALE from the ledger's
# observed/predicted ratio ring — one auditable number (reported in
# /status and /fleet, and as `chain_serve_cost_calibration_scale`)
# instead of silently re-deriving every coefficient. A scale is all the
# scheduler needs: wave packing and admission compare RELATIVE costs,
# and the absolute budget error is exactly the median ratio the refit
# removes.

#: refuse to fit from fewer settled observations than this
CALIBRATION_MIN_SAMPLES = 32
#: fitted-scale clamp: one pathological soak must not 100x the gate
CALIBRATION_SCALE_RANGE = (0.1, 10.0)

_CAL_LOCK = lockdebug.make_lock("serve_cost_cal")
_CALIBRATION: dict = {"scale": 1.0, "n": 0}   # guarded-by: _CAL_LOCK


def calibration() -> dict:
    """The calibration in force: {"scale", "n" (samples behind it)}."""
    with _CAL_LOCK:
        return dict(_CALIBRATION)


def calibration_scale() -> float:
    with _CAL_LOCK:
        return float(_CALIBRATION["scale"])


def set_calibration(scale: float, n: int = 0) -> dict:
    """Install a per-host prediction multiplier (clamped). Applied by
    `predict_unit_cost` to every later prediction."""
    lo, hi = CALIBRATION_SCALE_RANGE
    scale = float(min(hi, max(lo, scale)))
    with _CAL_LOCK:
        _CALIBRATION.update(scale=scale, n=int(n))
        doc = dict(_CALIBRATION)
    _CAL_SCALE.set(scale)
    return doc


def reset_calibration() -> None:
    with _CAL_LOCK:
        _CALIBRATION.update(scale=1.0, n=0)
    _CAL_SCALE.set(1.0)


def fit_scale(ratios: list, min_samples: int = CALIBRATION_MIN_SAMPLES
              ) -> Optional[dict]:
    """Fit a correction factor from observed/predicted ratios: the
    MEDIAN ratio (robust against the heavy tail warm-adjacent waves put
    on the mean), clamped. None when there are too few finite samples
    to trust a refit."""
    clean = sorted(
        r for r in ratios
        if isinstance(r, (int, float)) and math.isfinite(r) and r > 0
    )
    if len(clean) < max(1, min_samples):
        return None
    mid = len(clean) // 2
    median = (
        clean[mid] if len(clean) % 2
        else 0.5 * (clean[mid - 1] + clean[mid])
    )
    lo, hi = CALIBRATION_SCALE_RANGE
    return {"scale": round(min(hi, max(lo, median)), 4), "n": len(clean)}


def complexity_multiplier(complexity: Optional[float]) -> float:
    """Encode-cost multiplier from a priors complexity score (None —
    no priors available — is neutral)."""
    if complexity is None or not math.isfinite(complexity):
        return 1.0
    lo, hi = COMPLEXITY_MULT_RANGE
    # clamp the EXPONENT (an absurd score must not overflow pow)
    exponent = (complexity - COMPLEXITY_REF) / COMPLEXITY_PER_DOUBLING
    exponent = min(math.log2(hi), max(math.log2(lo), exponent))
    return float(min(hi, max(lo, 2.0 ** exponent)))


def codec_multiplier(codec: Optional[str]) -> float:
    if not codec:
        return 1.0
    return float(CODEC_MULT.get(str(codec).casefold(), 1.5))


def cost_from_features(features: Optional[dict]) -> float:
    """The documented formula (module docstring) over one unit's
    feature dict. Unknown/missing features contribute zero; a None
    feature dict costs DEFAULT_COST_S. Never raises, never negative."""
    if not isinstance(features, dict):
        return DEFAULT_COST_S
    try:
        cost = BASE_S
        cost += max(0.0, float(features.get("fixed_s", 0.0) or 0.0))
        cost += max(0.0, float(features.get("work_s", 0.0) or 0.0))
        cost += max(0.0, float(features.get("out_bytes", 0.0) or 0.0)) \
            * BYTES_S
        enc = max(0.0, float(features.get("enc_fmpix", 0.0) or 0.0))
        if enc:
            cost += (enc * ENC_S_PER_FMPIX
                     * codec_multiplier(features.get("codec"))
                     * complexity_multiplier(features.get("complexity")))
        cost += max(0.0, float(features.get("dev_fmpix", 0.0) or 0.0)) \
            * DEVICE_S_PER_FMPIX
        cost += max(0.0, float(features.get("cpvs_fmpix", 0.0) or 0.0)) \
            * CPVS_S_PER_FMPIX
        return cost
    except (TypeError, ValueError):
        return DEFAULT_COST_S


def predict_unit_cost(executor, record_unit: dict) -> float:
    """Predicted execution seconds for one unit under `executor`.
    Totality contract mirrors `bucket_key`: a unit the executor's
    feature hook cannot parse degrades to DEFAULT_COST_S, never a
    raise — this runs at the POST front door and in the scheduler's
    packing pass."""
    features = None
    hook = getattr(executor, "cost_features", None)
    if hook is not None:
        try:
            features = hook(record_unit)
        except Exception:  # noqa: BLE001 - any feature failure = default cost
            features = None
    # the per-host calibration multiplies the WHOLE prediction: the
    # observed/predicted ratio it was fitted from is a whole-cost ratio
    return cost_from_features(features) * calibration_scale()


# ----------------------------------------------------------- admission


class AdmissionError(Exception):
    """A request was refused by cost admission control (HTTP 429).
    `doc` is the forensic response body; `retryable` says whether the
    same request can succeed later (tenant budget frees as work
    settles) or is simply too big (split it)."""

    def __init__(self, message: str, doc: dict, retryable: bool) -> None:
        super().__init__(message)
        self.doc = dict(doc)
        self.doc.setdefault("error", message)
        self.doc["retryable"] = retryable
        self.retryable = retryable


def _heaviest(costed_units: list, n: int = 5) -> list[dict]:
    ranked = sorted(costed_units, key=lambda cu: -cu[1])[:n]
    return [{"pvs": pvs_id, "predicted_s": round(cost_s, 3)}
            for pvs_id, cost_s in ranked]


def check_admission(
    tenant: str,
    costed_units: list,
    request_budget_s: Optional[float],
    tenant_budget_s: Optional[float],
    tenant_outstanding_s: float,
) -> float:
    """Gate one request's COLD units (warm ones cost nothing) against
    the configured budgets. `costed_units` is [(pvs_id, cost_s), ...].
    Returns the request's total predicted seconds; raises
    AdmissionError (→ 429) when a budget is exceeded. Either budget
    being None disables that check."""
    predicted_s = sum(cost for _, cost in costed_units)
    if request_budget_s is not None and predicted_s > request_budget_s:
        _REJECTED.labels(reason="request_budget").inc()
        tm.emit("serve_admission_rejected", tenant=tenant,
                reason="request_budget",
                predicted_s=round(predicted_s, 3),
                budget_s=request_budget_s)
        raise AdmissionError(
            f"request predicted cost {predicted_s:.3g}s exceeds the "
            f"per-request budget {request_budget_s:.3g}s — split the "
            "grid into smaller requests",
            doc={
                "reason": "request_budget",
                "predicted_s": round(predicted_s, 3),
                "budget_s": request_budget_s,
                "cold_units": len(costed_units),
                "heaviest": _heaviest(costed_units),
            },
            retryable=False,
        )
    if tenant_budget_s is not None and \
            tenant_outstanding_s + predicted_s > tenant_budget_s:
        _REJECTED.labels(reason="tenant_budget").inc()
        tm.emit("serve_admission_rejected", tenant=tenant,
                reason="tenant_budget",
                predicted_s=round(predicted_s, 3),
                outstanding_s=round(tenant_outstanding_s, 3),
                budget_s=tenant_budget_s)
        raise AdmissionError(
            f"tenant {tenant!r} has {tenant_outstanding_s:.3g}s of work "
            f"outstanding; admitting {predicted_s:.3g}s more would exceed "
            f"the tenant budget {tenant_budget_s:.3g}s — retry as queued "
            "work settles",
            doc={
                "reason": "tenant_budget",
                "tenant": tenant,
                "predicted_s": round(predicted_s, 3),
                "outstanding_s": round(tenant_outstanding_s, 3),
                "budget_s": tenant_budget_s,
                "cold_units": len(costed_units),
                "heaviest": _heaviest(costed_units),
            },
            retryable=True,
        )
    return predicted_s


# ------------------------------------------------------------- feedback


class CostLedger:
    """Per-tenant cost accounting + the observed-vs-predicted feedback
    loop. Admitted predictions and settled observations land here (and
    on the `chain_serve_cost_*` counters the fleet view merges); the
    in-memory aggregates back /status and the soak report.

    The error ratios keep a bounded sample (newest-biased ring) — an
    always-on daemon must not grow an unbounded list, and model drift
    is a question about RECENT predictions anyway."""

    _MAX_RATIOS = 4096

    def __init__(self) -> None:
        self._lock = lockdebug.make_lock("serve_cost_ledger")
        self._tenants: dict[str, dict] = {}   # guarded-by: _lock
        self._ratios: list[float] = []        # guarded-by: _lock
        self._ratio_i = 0                     # guarded-by: _lock

    # holds-lock: _lock
    def _tenant(self, tenant: str) -> dict:
        return self._tenants.setdefault(tenant, {
            "predicted_s": 0.0, "observed_s": 0.0,
            "settled_units": 0, "warm_units": 0,
        })

    def admitted(self, tenant: str, predicted_s: float) -> None:
        """A request's cold units were admitted with this much
        predicted work."""
        if predicted_s <= 0:
            return
        with self._lock:
            self._tenant(tenant)["predicted_s"] += predicted_s
        _PREDICTED.labels(tenant=tenant).inc(predicted_s)

    def observed(self, tenant: str, predicted_s: float,
                 exec_s: float) -> None:
        """One unit settled after really executing for `exec_s`."""
        with self._lock:
            entry = self._tenant(tenant)
            entry["observed_s"] += exec_s
            entry["settled_units"] += 1
            if predicted_s > 0:
                ratio = exec_s / predicted_s
                if len(self._ratios) < self._MAX_RATIOS:
                    self._ratios.append(ratio)
                else:
                    self._ratios[self._ratio_i % self._MAX_RATIOS] = ratio
                self._ratio_i += 1
        _OBSERVED.labels(tenant=tenant).inc(exec_s)
        if predicted_s > 0:
            _ERROR_RATIO.observe(exec_s / predicted_s)

    def warm(self, tenant: str) -> None:
        """A unit settled from the store without executing."""
        with self._lock:
            self._tenant(tenant)["warm_units"] += 1

    def ratios(self) -> list:
        """Snapshot of the observed/predicted ratio ring."""
        with self._lock:
            return list(self._ratios)

    def calibrate(self, min_samples: int = CALIBRATION_MIN_SAMPLES
                  ) -> Optional[dict]:
        """Refit the per-host scale from the ratio ring and install it.
        The ring's ratios were observed against predictions carrying
        the scale in force at THEIR time, so the fit COMPOSES with the
        current scale (iterative refinement: a perfectly-calibrated
        host fits median ≈ 1 and the scale is a fixed point). A
        successful refit DRAINS the ring: its ratios are now stale
        (they argue against a scale no longer in force), and a
        periodic tick (--cost-calibrate) re-fitting them would
        compound the same correction exponentially. The next refit
        waits for `min_samples` fresh post-refit observations. Returns
        the installed calibration, or None when the ring is too thin."""
        fitted = fit_scale(self.ratios(), min_samples)
        if fitted is None:
            return None
        cal = set_calibration(
            calibration_scale() * fitted["scale"], fitted["n"]
        )
        with self._lock:
            self._ratios.clear()
            self._ratio_i = 0
        return cal

    def report(self) -> dict:
        """The auditable summary: per-tenant sums + model error. Error
        percentiles are over the observed/predicted ratio (1.0 =
        perfect); `mape` is mean |ratio - 1|."""
        from ..telemetry.fleet import percentile_exact

        with self._lock:
            tenants = {
                name: {
                    "predicted_s": round(entry["predicted_s"], 3),
                    "observed_s": round(entry["observed_s"], 3),
                    "settled_units": entry["settled_units"],
                    "warm_units": entry["warm_units"],
                }
                for name, entry in sorted(self._tenants.items())
            }
            ratios = list(self._ratios)
        error: Optional[dict] = None
        if ratios:
            error = {
                "n": len(ratios),
                "ratio_p50": round(percentile_exact(ratios, 0.50), 4),
                "ratio_p95": round(percentile_exact(ratios, 0.95), 4),
                "mape": round(
                    sum(abs(r - 1.0) for r in ratios) / len(ratios), 4
                ),
            }
        return {"tenants": tenants, "model_error": error}
