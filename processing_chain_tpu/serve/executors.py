"""What a serve unit of work IS: the Executor protocol + built-ins.

An executor turns a validated per-PVS unit into (a) a *plan* — the
JSON-able payload whose store hash is the unit's identity for dedup,
warm hits and artifact addressing — and (b) bytes on disk, produced by
`run_batch` for a whole wave of units at once. The batch signature is
the point: units from DIFFERENT requests that share a `bucket_key`
(geometry bucket, parallel/p03_batch semantics) are handed to one call
so the executor can pack them into one device wave.

Built-ins:

  * `synthetic` — deterministic pseudo-artifacts (bytes derived from
    the canonical plan), optional simulated work time. The toy-corpus
    executor CI smoke, the soak driver and the kill/restart test run
    against: cheap, exactly reproducible, and honest about identity
    (different params ⇒ different plan hash ⇒ different artifact).
  * `wave` — REAL shared device waves: builds a p03_batch.Lane per unit
    (deterministic synthetic YUV), drives the whole bucket through
    `run_bucket` on the process mesh, writes the scaled luma. Proof
    that cross-request work actually lands in one compiled step.
  * `chain` — the production executor (serve/chain_executor.py,
    loaded lazily): units backed by real SRC files and HRC event lists
    through the full p01–p04 stages, serving every artifact family
    from the store — see docs/SERVE.md "Real database execution".
"""

from __future__ import annotations

import hashlib
import time
from typing import Optional, Protocol

from .. import telemetry as tm
from ..store import keys
from ..utils.fsio import atomic_write, atomic_write_text
from ..utils.runner import ChainError
from .api import Unit

_WAVES = tm.counter(
    "chain_serve_waves_total", "batched executions dispatched by the scheduler"
)
_WAVE_LANES = tm.histogram(
    "chain_serve_wave_lanes", "units packed into each dispatched wave"
)


class Executor(Protocol):
    """The serve execution contract (docs/SERVE.md "Executors")."""

    kind: str

    def plan(self, unit: Unit) -> dict:
        """JSON-able identity payload: everything that determines the
        artifact's bytes. Hashed by the store (plan-hash dedup key)."""
        ...

    def output_name(self, unit: Unit, plan_hash: str) -> str:
        """Artifact filename under the serve artifacts root."""
        ...

    def bucket_key(self, unit: dict) -> Optional[tuple]:
        """Geometry bucket for wave packing; None = cannot batch.
        Called with the RECORD's unit dict (queue.JobRecord.unit).
        MUST NOT raise: it runs inside every scheduler worker's packing
        pass over the whole queued snapshot, so one unparseable record
        would poison every worker — return None instead."""
        ...

    def validate_params(self, params: dict) -> None:
        """Reject executor params this executor cannot execute
        (raise ValueError). Called at the HTTP front door so a bad
        request 400s instead of becoming a durable queue record."""
        ...

    def cost_features(self, record_unit: dict) -> Optional[dict]:
        """Feature dict for the predicted-cost model (serve/cost.py:
        work_s / out_bytes / enc_fmpix / dev_fmpix / cpvs_fmpix /
        codec / complexity). Same totality contract as bucket_key —
        it runs at the POST front door and in the scheduler's packing
        pass, so return None for an unparseable unit, never raise
        (cost.predict_unit_cost guards anyway and falls back to
        DEFAULT_COST_S)."""
        ...

    def src_digest(self, record_unit: dict) -> Optional[str]:
        """Content digest of the unit's SRC bytes — the poison-
        quarantine key (docs/SERVE.md "Failure taxonomy"): a `poison`
        settle quarantines this digest fleet-wide, so every plan
        referencing the same hostile upload fails fast. Same totality
        contract as bucket_key (None = no digest, digest quarantine
        simply never applies to the unit); never raise."""
        ...

    def run_batch(self, units: list[Unit], outputs: list[str]) -> None:
        """Produce every output. Called inside engine.Job (sentinels,
        store commit, telemetry ride along)."""
        ...


def _unit_of(record_unit: dict) -> Unit:
    return Unit(
        database=record_unit["database"], src=record_unit["src"],
        hrc=record_unit["hrc"], params=dict(record_unit.get("params", {})),
    )


def record_waves(n_units: int) -> None:
    """Wave accounting shared by every executor dispatch path."""
    _WAVES.inc()
    _WAVE_LANES.observe(float(n_units))


class SyntheticExecutor:
    """Deterministic toy processing: artifact bytes are a SHA-256
    stream over the canonical plan. Params (all optional):

        size_bytes  artifact size (default 4096)
        work_ms     simulated compute per unit (default 0)
        geometry    [w, h] — units sharing it batch into one wave
        fail_times  fault injection: the first N execution attempts of
                    this unit raise a TRANSIENT ChainError (a durable
                    counter next to the output tracks attempts across
                    replica restarts) — the chaos/soak harnesses' disk-
                    error stand-in, exercising retry + backoff
        poison      fault injection: every attempt raises a PERMANENT
                    ChainError — exercises the quarantine path
        poison_src  fault injection: every attempt raises a POISON
                    ChainError — the corrupt-upload stand-in: the unit's
                    SRC content digest is quarantined fleet-wide, so
                    sibling plans sharing the SRC fail fast without
                    executing (docs/ROBUSTNESS.md; the serve-chaos
                    --corrupt-corpus workload rides this)
    """

    kind = "synthetic"

    def plan(self, unit: Unit) -> dict:
        return {
            "op": "serve.synthetic",
            "schema": 1,
            "database": unit.database,
            "src": unit.src,
            "hrc": unit.hrc,
            "params": dict(unit.params),
        }

    def output_name(self, unit: Unit, plan_hash: str) -> str:
        return f"{unit.pvs_id}_{plan_hash[:12]}.bin"

    def validate_params(self, params: dict) -> None:
        geometry = params.get("geometry")
        if geometry is not None:
            try:
                if isinstance(geometry, (str, bytes)):
                    raise TypeError
                [int(g) for g in geometry]
            except (TypeError, ValueError):
                raise ValueError(
                    "params.geometry must be a list of integers, got "
                    f"{geometry!r}"
                ) from None
        for key, cast in (("work_ms", float), ("size_bytes", int),
                          ("fail_times", int)):
            if params.get(key) is not None:
                try:
                    cast(params[key])
                except (TypeError, ValueError):
                    raise ValueError(
                        f"params.{key} must be a number, got {params[key]!r}"
                    ) from None
        for flag in ("poison", "poison_src"):
            if not isinstance(params.get(flag, False), bool):
                raise ValueError(
                    f"params.{flag} must be a boolean, got "
                    f"{params[flag]!r}"
                )

    def bucket_key(self, record_unit: dict) -> Optional[tuple]:
        try:
            geometry = record_unit.get("params", {}).get("geometry")
            if not geometry:
                return None
            return ("synthetic", *(int(g) for g in geometry))
        except (AttributeError, TypeError, ValueError):
            # a pre-validation durable record with garbage params (null,
            # non-dict, unparseable geometry): unbatchable, never a raise
            return None

    def cost_features(self, record_unit: dict) -> Optional[dict]:
        """Synthetic units declare their cost outright: work_ms of
        simulated compute + the artifact bytes they write."""
        try:
            params = record_unit.get("params", {}) or {}
            return {
                "work_s": float(params.get("work_ms", 0) or 0) / 1e3,
                "out_bytes": float(params.get("size_bytes", 4096) or 4096),
            }
        except (AttributeError, TypeError, ValueError):
            return None

    def src_digest(self, record_unit: dict) -> Optional[str]:
        """Synthetic SRCs have no file bytes; their digest is the
        deterministic hash of the (database, src) identity — which is
        exactly what makes the poison-sweep fleet semantics testable:
        every unit naming one SRC shares one digest."""
        try:
            return hashlib.sha256(
                f"synthetic:{record_unit['database']}:{record_unit['src']}"
                .encode()
            ).hexdigest()
        except (KeyError, TypeError, AttributeError):
            return None

    def _inject_failures(self, unit: Unit, output: str) -> None:
        """Scripted fault injection (chaos/soak harnesses only; see the
        class docstring). Raises BEFORE any bytes are produced, so an
        injected failure never leaves a half-made artifact behind."""
        params = unit.params
        if params.get("poison_src"):
            # attributed verdict: naming the digest on the exception is
            # what the real executor does (first-contact validation),
            # and it is what lets the scheduler convict the SRC from a
            # packed wave instead of waiting for a solo-wave retry
            raise ChainError(
                f"injected poison SRC for {output} (corrupt upload "
                "stand-in)", kind="poison",
                src_digest=self.src_digest(
                    {"database": unit.database, "src": unit.src}),
            )
        if params.get("poison"):
            raise ChainError(
                f"injected permanent failure for {output}",
                kind="permanent",
            )
        fail_times = int(params.get("fail_times", 0) or 0)
        if fail_times > 0:
            marker = output + ".injected-failures"
            try:
                with open(marker) as f:
                    injected = int(f.read().strip() or "0")
            except (OSError, ValueError):
                injected = 0
            if injected < fail_times:
                atomic_write_text(marker, str(injected + 1))
                raise ChainError(
                    f"injected transient failure {injected + 1}/"
                    f"{fail_times} for {output}",
                    kind="transient",
                )

    def run_batch(self, units: list[Unit], outputs: list[str]) -> None:
        record_waves(len(units))
        for unit, output in zip(units, outputs):
            params = unit.params
            self._inject_failures(unit, output)
            work_ms = float(params.get("work_ms", 0) or 0)
            if work_ms > 0:
                time.sleep(work_ms / 1000.0)
            size = int(params.get("size_bytes", 4096) or 4096)
            seed = keys.canonical_json(self.plan(unit)).encode()
            chunks: list[bytes] = []
            digest = hashlib.sha256(seed).digest()
            produced = 0
            while produced < size:
                chunks.append(digest)
                produced += len(digest)
                digest = hashlib.sha256(digest).digest()
            data = b"".join(chunks)[:size]

            def _write(tmp: str, payload: bytes = data) -> None:
                with open(tmp, "wb") as f:
                    f.write(payload)

            atomic_write(output, _write)


class DeviceWaveExecutor(SyntheticExecutor):
    """Real cross-request device waves: every unit in the batch becomes
    one p03_batch.Lane over deterministic synthetic YUV, and the whole
    bucket runs through `run_bucket` on the process mesh — independent
    requests literally share compiled device steps. Params:

        frames            lane length (default 8)
        src_h/src_w       source geometry (default 36x64)
        dst_h/dst_w       target geometry (default 72x128)
    """

    kind = "wave"

    _GEO = ("src_h", "src_w", "dst_h", "dst_w")
    _DEFAULTS = {"frames": 8, "src_h": 36, "src_w": 64,
                 "dst_h": 72, "dst_w": 128}

    def _geometry(self, params: dict) -> dict:
        geo = dict(self._DEFAULTS)
        for key in ("frames", *self._GEO):
            if key in params:
                geo[key] = int(params[key])
        return geo

    def plan(self, unit: Unit) -> dict:
        plan = super().plan(unit)
        plan["op"] = "serve.wave"
        plan["geometry"] = self._geometry(unit.params)
        return plan

    def validate_params(self, params: dict) -> None:
        for key in ("frames", *self._GEO):
            if key in params:
                try:
                    value = int(params[key])
                except (TypeError, ValueError):
                    raise ValueError(
                        f"params.{key} must be an integer, got "
                        f"{params[key]!r}"
                    ) from None
                if value <= 0:
                    raise ValueError(
                        f"params.{key} must be positive, got {value}"
                    )

    def bucket_key(self, record_unit: dict) -> Optional[tuple]:
        # params=None stays unbatchable (not defaulted): _unit_of would
        # reject the record at dispatch, and a solo wave confines that
        # failure instead of letting it take healthy siblings down
        try:
            geo = self._geometry(record_unit.get("params", {}))
        except (AttributeError, TypeError, ValueError):
            return None  # pre-validation garbage record: unbatchable
        return ("wave",) + tuple(geo[k] for k in self._GEO)

    def cost_features(self, record_unit: dict) -> Optional[dict]:
        """Wave units are device resizes: frames × destination pixels."""
        try:
            geo = self._geometry(record_unit.get("params", {}))
        except (AttributeError, TypeError, ValueError):
            return None
        return {
            "dev_fmpix": geo["frames"] * geo["dst_h"] * geo["dst_w"] / 1e6,
            "out_bytes": geo["frames"] * geo["dst_h"] * geo["dst_w"] * 1.5,
        }

    def _mesh(self):
        from ..parallel.mesh import make_mesh

        return make_mesh(time_parallel=1)

    def run_batch(self, units: list[Unit], outputs: list[str]) -> None:
        import numpy as np

        from ..parallel import p03_batch

        record_waves(len(units))
        geo = self._geometry(units[0].params)
        sh, sw = geo["src_h"], geo["src_w"]
        dh, dw = geo["dst_h"], geo["dst_w"]
        collected: list[list] = [[] for _ in units]
        lanes = []
        for i, unit in enumerate(units):
            n = self._geometry(unit.params)["frames"]
            seed = int.from_bytes(
                hashlib.sha256(
                    keys.canonical_json(self.plan(unit)).encode()
                ).digest()[:8], "big",
            )
            rng = np.random.default_rng(seed)
            yuv = [
                rng.integers(0, 255, size=(n, sh, sw), dtype=np.uint8),
                rng.integers(0, 255, size=(n, sh // 2, sw // 2),
                             dtype=np.uint8),
                rng.integers(0, 255, size=(n, sh // 2, sw // 2),
                             dtype=np.uint8),
            ]
            lanes.append(p03_batch.Lane(
                chunks=iter([yuv]), emit=collected[i].append,
                n_frames_hint=n,
                name=unit.pvs_id,  # wave-journal identity (meshobs)
            ))
        p03_batch.run_bucket(
            lanes, self._mesh(), dh, dw, "bicubic", (2, 2), False, chunk=8,
            bucket=p03_batch.bucket_label(dh, dw, False, sh, sw),
        )
        for i, output in enumerate(outputs):
            planes = [
                np.concatenate([blk[p] for blk in collected[i]])
                for p in range(3)
            ]
            data = b"".join(p.tobytes() for p in planes)

            def _write(tmp: str, payload: bytes = data) -> None:
                with open(tmp, "wb") as f:
                    f.write(payload)

            atomic_write(output, _write)


EXECUTORS = {
    SyntheticExecutor.kind: SyntheticExecutor,
    DeviceWaveExecutor.kind: DeviceWaveExecutor,
}

#: kinds resolved by deferred import — the chain executor pulls in the
#: config/model layers, which must not load just to run a synthetic
#: soak (and importing it here would be a serve-package import cycle)
_LAZY_EXECUTORS = ("chain",)


def make_executor(kind: str):
    if kind == "chain":
        from .chain_executor import ChainExecutor

        return ChainExecutor()
    try:
        return EXECUTORS[kind]()
    except KeyError:
        raise ValueError(
            f"unknown serve executor {kind!r}; known: "
            f"{sorted([*EXECUTORS, *_LAZY_EXECUTORS])}"
        ) from None
