"""Serve-side LRU pressure on the artifact store.

A daemon that never exits needs the batch CLI's `tools store gc` run
FOR it: after completions, this hook checks the store's object bytes
against the operator's budget and, over budget, runs the shared
`store.gc.enforce_budget` pass with the plans of every UNFINISHED
request passed as ephemeral pins — the cache can evict any completed
cold artifact, but never one a queued request is about to claim.

Throttled (`min_interval_s`) because the budget check walks objects/;
eviction pressure is a trend, not a per-job emergency.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .. import telemetry as tm
from ..store import gc as store_gc
from ..utils import lockdebug
from ..utils.log import get_logger

_GC_EVICTED = tm.counter(
    "chain_serve_gc_evicted_bytes_total",
    "bytes freed by serve-side store GC pressure",
)


class StorePressure:
    """Budget enforcement hook wired to scheduler completions."""

    def __init__(
        self,
        store,
        budget_bytes: Optional[int],
        active_plans: Callable[[], set],
        min_interval_s: float = 5.0,
        heat=None,
    ) -> None:
        self.store = store
        self.budget_bytes = budget_bytes
        self.active_plans = active_plans
        #: store.heat.HeatLedger (optional): evictions land in the
        #: forensics journal so later re-reads count as regret
        self.heat = heat
        self.min_interval_s = float(min_interval_s)
        self._lock = lockdebug.make_lock("serve_pressure")
        self._last = 0.0          # guarded-by: _lock
        self._running = False     # guarded-by: _lock

    def _tier_overflow(self) -> bool:
        """True when any non-last tier outgrew its OWN byte budget —
        demotion pressure (store/gc.py "demote before evict") exists
        even when no TOTAL budget is configured."""
        tiers = getattr(self.store, "tiers", None)
        if tiers is None or not tiers.multi:
            return False
        return any(
            t.budget_bytes is not None
            and t.bytes_held() > t.budget_bytes
            for t in tiers.tiers[:-1]
        )

    def maybe_collect(self, force: bool = False) -> Optional[dict]:
        """One throttled budget check; the GC pass itself runs OUTSIDE
        the lock (it walks the store) with reentry suppressed. Returns
        the gc summary when a pass ran, else None. The pass runs when
        the TOTAL budget is exceeded (eviction pressure) or when any
        tier outgrew its own budget (demotion pressure)."""
        if self.store is None:
            return None
        tiers = getattr(self.store, "tiers", None)
        if not self.budget_bytes and (tiers is None or not tiers.multi):
            return None
        with self._lock:
            now = time.monotonic()
            if self._running:
                return None
            if not force and now - self._last < self.min_interval_s:
                return None
            self._last = now
            self._running = True
        try:
            stats = self.store.stats()
            over_total = bool(
                self.budget_bytes and stats["bytes"] > self.budget_bytes)
            if not force and not over_total and not self._tier_overflow():
                return None
            pins = set(self.active_plans())
            summary = store_gc.enforce_budget(
                self.store, self.budget_bytes, extra_pins=pins,
                heat=self.heat,
            )
            _GC_EVICTED.inc(summary["bytes_freed"])
            tm.emit(
                "serve_gc",
                bytes_freed=summary["bytes_freed"],
                objects_evicted=summary["objects_evicted"],
                demoted_bytes=summary.get("demoted_bytes", 0),
                pins_honored=summary["pins_honored"],
                kept_bytes=summary["kept_bytes"],
            )
            if summary["bytes_freed"] or summary.get("demoted_bytes"):
                get_logger().info(
                    "serve gc: freed %d bytes (%d objects), demoted %d "
                    "bytes, %d pin(s) honored, %d bytes kept",
                    summary["bytes_freed"], summary["objects_evicted"],
                    summary.get("demoted_bytes", 0),
                    summary["pins_honored"], summary["kept_bytes"],
                )
            return summary
        finally:
            with self._lock:
                self._running = False
