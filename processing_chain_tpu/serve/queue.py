"""Durable, dedup-aware job queue: the serve daemons' crash-proof spine.

One JSON record per job under `jobs/`, every state change an atomic
durable rewrite (utils/fsio — the store's tmp+rename idiom, plus fsync:
queue records claim SIGKILL-proofness, so a power-loss crash must not
promote an unflushed rename). The queue is safe for N concurrent
replica daemons sharing one root:

  * **Lease-fenced ownership** — while a job executes, a
    `<record>.inprogress` LEASE (replica id, monotonically-increasing
    epoch, expiry, pid/host) sits next to it, renewed by the owner's
    heartbeat thread. A lease whose holder is demonstrably dead (same
    host, pid gone) or whose expiry passed is reclaimable: any live
    replica STEALS the record back to `queued` with the epoch bumped
    (`serve_lease_stolen`). Every settle is epoch-fenced against the
    on-disk record, so a zombie replica resumed after SIGSTOP cannot
    settle a record it lost (`serve_settle_fenced`).
  * **Cross-process atomicity** — every mutation holds an exclusive
    flock on `<root>/queue.lock` (released automatically by the kernel
    when a replica dies), so claim/steal/settle/enqueue from different
    replicas never interleave mid-transition. Reads never need it:
    records are whole-file atomic replaces.
  * **Cross-replica visibility** — `poll()` merges peer record changes
    into the in-memory view (stat-keyed rescans) and runs the steal
    scan; enqueue-time dedup across replicas is eventual (a peer's
    record for the same plan attaches after the next poll), and the
    store's plan-hash commit keeps artifacts exactly-once regardless.

Dedup is identity-by-plan-hash, the store's own key: enqueueing a unit
whose plan hash already has a queued/running job ATTACHES the new
request to that record instead of minting a second execution —
overlapping requests from any number of tenants share one job by
construction (singleflight). A plan whose job already completed is the
caller's warm path (the store serves it); a failed or evicted plan
re-arms the same record. A QUARANTINED plan (permanent failure —
docs/SERVE.md "Failure taxonomy") does not: new requests are refused
until an operator re-arms it.

States: queued → running → done | failed | quarantined (failed/evicted
re-arm to queued on the next enqueue; quarantined only via rearm). The
machine is DECLARED below (STATES / INITIAL / TRANSITIONS) and that
declaration is load-bearing: chainlint's `queue-transition` rule
rejects any state write that is not an annotated declared edge, `tools
queue-crashcheck` fault-injects every atomic-write boundary against it,
and docs/SERVE.md renders it. The record keeps every request ID it
answers, `attempts`, `epoch`, `not_before` (retry backoff) and timing
for forensics.
"""

from __future__ import annotations

import fcntl
import json
import os
import secrets
import socket
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, Optional

from .. import telemetry as tm
from ..telemetry import catalog
from ..utils import lockdebug
from ..utils.fsio import atomic_write_json
from ..utils.log import get_logger
from .spans import SpanJournal, safe_replica_name

_QUEUE_DEPTH = tm.gauge(
    "chain_serve_queue_depth", "jobs waiting in the serve queue"
)
# SLO phase histograms (docs/TELEMETRY.md "Fleet observability"): the
# per-(tenant × priority-class) latency truth the fleet view aggregates
# against catalog.SLO_BANDS. Queue-wait is observed at claim time
# (enqueue-or-requeue → claim), execution at settle; the request-level
# end-to-end histogram lives in serve/service.py.
_QUEUE_WAIT = tm.histogram(
    "chain_serve_queue_wait_seconds",
    "time a unit waited in 'queued' before a claim, per tenant/priority",
    ("tenant", "priority"),
    buckets=catalog.SLO_LATENCY_BUCKETS,
)
_EXEC_SECONDS = tm.histogram(
    "chain_serve_execution_seconds",
    "claim-to-settle execution time of a unit, per tenant/priority",
    ("tenant", "priority"),
    buckets=catalog.SLO_LATENCY_BUCKETS,
)
_LEASE_STEALS = tm.counter(
    "chain_serve_lease_steals_total",
    "expired/dead leases reclaimed from peer replicas",
)
_FENCED_SETTLES = tm.counter(
    "chain_serve_fenced_settles_total",
    "settle attempts rejected because the caller's epoch was stale",
)
_CLAIM_REVERTS = tm.counter(
    "chain_serve_claim_reverts_total",
    "claims reverted to queued by a mid-claim disk failure",
)
_QUARANTINED = tm.counter(
    "chain_serve_quarantined_total",
    "plans quarantined after a permanent failure",
)
_SRC_POISONED = tm.counter(
    "chain_serve_poisoned_total",
    "SRC content digests quarantined after a poison verdict",
)

# --------------------------------------------------------------------------
# The record state machine, declared ONCE. Three consumers share this
# table (docs/SERVE.md "State machine"): chainlint's `queue-transition`
# rule verifies every `.state` write in serve code is an annotated,
# declared edge; `tools queue-crashcheck` fault-injects every
# atomic-write boundary and asserts recovery lands every record in a
# declared state; docs/SERVE.md renders it between the
# queue-transitions markers (`tools queue-crashcheck --render-table`).
# Keep every entry a literal — the linter parses this by AST.

#: every state a durable record can be in
STATES = ("queued", "running", "done", "failed", "quarantined")

#: the only state a record may be created in
INITIAL = "queued"

#: declared edges: (from, to)
TRANSITIONS = frozenset({
    ("queued", "running"),        # claim: lease down, execution owned
    ("running", "done"),          # complete: store commit landed / warm hit
    ("running", "failed"),        # fail: attempts budget exhausted
    ("running", "queued"),        # retry/steal/revert/recovery re-arm
    ("running", "quarantined"),   # permanent failure: retrying is futile
    ("queued", "quarantined"),    # poison sweep: the record's SRC content digest was quarantined fleet-wide
    ("failed", "queued"),         # re-arm: a fresh request retries the plan
    ("done", "queued"),           # re-arm: the store evicted the artifact
    ("quarantined", "queued"),    # re-arm: operator cleared the quarantine
})

#: states a new request can attach to (the singleflight window)
_ATTACHABLE = ("queued", "running")

#: states with no outstanding work (quarantine included: nothing will
#: run it until an operator re-arms)
TERMINAL = ("done", "failed", "quarantined")

_HOST = socket.gethostname()

#: replica ids of every OPEN DurableQueue in this process — the
#: same-pid liveness oracle. A lease whose pid is ours but whose
#: replica id is not here belongs to a previous (dead) incarnation:
#: reclaim it immediately instead of waiting out the expiry, which is
#: exactly what a single-replica daemon restart needs.
_REPLICAS_LOCK = lockdebug.make_lock("serve_replicas")
_LIVE_REPLICAS: set = set()  # guarded-by: _REPLICAS_LOCK


def owner_stamp(replica: str) -> dict:
    """The {replica, pid, host} liveness stamp persisted wherever a
    replica claims durable ownership outside the queue (request docs):
    peers probe it with `owner_process_dead` to adopt orphans."""
    return {"replica": replica, "pid": os.getpid(), "host": _HOST}


def owner_process_dead(owner) -> bool:
    """Best-effort: is the process behind an `owner_stamp` demonstrably
    dead? Same-host only (a pid probe means nothing across hosts —
    cross-host orphans are adopted at the next replica restart, which
    rescans everything). False on any doubt: adopting a LIVE peer's
    work is the expensive mistake, waiting is merely slow."""
    if not isinstance(owner, dict):
        return False
    if owner.get("host") != _HOST:
        return False
    try:
        pid = int(owner.get("pid", 0) or 0)
    except (TypeError, ValueError):
        return False
    if pid == os.getpid():
        with _REPLICAS_LOCK:
            return owner.get("replica") not in _LIVE_REPLICAS
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass
    return False


def _id_seq(job_id: str) -> int:
    """Numeric tail of a j-prefixed job id; 0 for foreign names."""
    try:
        return int(job_id.lstrip("j"))
    except ValueError:
        return 0


@dataclass
class JobRecord:
    """One durable unit of work, keyed by its plan hash."""

    job_id: str
    plan_hash: str
    plan: dict
    unit: dict            # {"database","src","hrc","params","pvs_id"}
    tenant: str
    priority: str
    output: str           # path RELATIVE to the artifacts root
    requests: list = field(default_factory=list)
    #: trace ids of the requests this record answers, parallel in spirit
    #: (not index) to `requests` — the durable half of the request-trace
    #: context, so a record outliving its submitter still knows its
    #: traces (docs/TELEMETRY.md "Fleet observability & tracing")
    trace_ids: list = field(default_factory=list)
    state: str = "queued"
    enqueued_at: float = 0.0
    #: when the record LAST entered 'queued' (enqueue, re-arm, retry,
    #: steal, recovery) — the queue-wait SLO phase measures from here,
    #: not from the original enqueue
    queued_at: float = 0.0
    #: when the current owner claimed it (None while queued/terminal)
    claimed_at: Optional[float] = None
    attempts: int = 0
    error: Optional[str] = None
    error_kind: Optional[str] = None  # transient | permanent (taxonomy)
    done_at: Optional[float] = None
    warm: bool = False    # completed via store hit, not execution
    epoch: int = 0        # bumped on every ownership change (claim/steal)
    owner: Optional[str] = None       # replica id of the current claimant
    not_before: float = 0.0           # retry backoff: claim-eligibility time
    settled_epoch: Optional[int] = None  # epoch the terminal write carried
    #: predicted execution seconds (serve/cost.py, stamped at enqueue):
    #: the scheduler packs waves by it, admission sums it per tenant,
    #: and the settle-time feedback loop grades it against reality
    cost_s: float = 0.0
    #: content digest of the unit's SRC (Executor.src_digest, stamped
    #: at enqueue): the poison-quarantine key — one hostile upload is
    #: quarantined by its BYTES, so every plan referencing it (any HRC,
    #: any tenant, any replica) fails fast instead of burning its own
    #: retry budget rediscovering the same poison (docs/ROBUSTNESS.md)
    src_digest: Optional[str] = None

    def to_json(self) -> dict:
        return {
            "job": self.job_id,
            "planHash": self.plan_hash,
            "plan": self.plan,
            "unit": self.unit,
            "tenant": self.tenant,
            "priority": self.priority,
            "output": self.output,
            "requests": list(self.requests),
            "traces": list(self.trace_ids),
            "state": self.state,
            "enqueuedAt": self.enqueued_at,
            "queuedAt": self.queued_at,
            "claimedAt": self.claimed_at,
            "attempts": self.attempts,
            "error": self.error,
            "errorKind": self.error_kind,
            "doneAt": self.done_at,
            "warm": self.warm,
            "epoch": self.epoch,
            "owner": self.owner,
            "notBefore": self.not_before,
            "settledEpoch": self.settled_epoch,
            "costS": self.cost_s,
            "srcDigest": self.src_digest,
        }

    @classmethod
    def from_json(cls, data: dict) -> "JobRecord":
        return cls(
            job_id=data["job"],
            plan_hash=data["planHash"],
            plan=data["plan"],
            unit=data["unit"],
            tenant=data.get("tenant", ""),
            priority=data.get("priority", "normal"),
            output=data.get("output", ""),
            requests=list(data.get("requests", [])),
            trace_ids=list(data.get("traces", [])),
            state=data.get("state", "queued"),
            enqueued_at=float(data.get("enqueuedAt", 0.0)),
            queued_at=float(data.get("queuedAt", 0.0)
                            or data.get("enqueuedAt", 0.0)),
            claimed_at=data.get("claimedAt"),
            attempts=int(data.get("attempts", 0)),
            error=data.get("error"),
            error_kind=data.get("errorKind"),
            done_at=data.get("doneAt"),
            warm=bool(data.get("warm", False)),
            epoch=int(data.get("epoch", 0)),
            owner=data.get("owner"),
            not_before=float(data.get("notBefore", 0.0)),
            settled_epoch=data.get("settledEpoch"),
            cost_s=float(data.get("costS", 0.0) or 0.0),
            src_digest=data.get("srcDigest"),
        )


class DurableQueue:
    """Crash-recoverable on-disk job queue with plan-hash dedup, safe
    for N replica processes over one root (module doc).

    Thread-safe: the scheduler's workers, the heartbeat thread and the
    HTTP submit path hit it concurrently. All disk MUTATIONS happen
    under the in-process lock AND the cross-process flock — the record
    files are small and each rewrite is one replace; a torn
    in-memory/on-disk split (or a peer interleaving mid-transition)
    would be worse than the contention."""

    def __init__(self, root: str, replica: Optional[str] = None,
                 lease_s: float = 15.0) -> None:
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self.replica = replica or (
            f"{_HOST}-{os.getpid()}-{secrets.token_hex(3)}"
        )
        self.lease_s = max(0.05, float(lease_s))
        self._lock = lockdebug.make_lock("serve_queue")
        # chainlint: disable=atomic-write (lock file: only its existence matters — flock state lives in the kernel, never in its bytes)
        self._lockfd = os.open(
            os.path.join(self.root, "queue.lock"),
            os.O_CREAT | os.O_RDWR, 0o644,
        )
        self._jobs: dict[str, JobRecord] = {}     # guarded-by: _lock
        self._by_plan: dict[str, str] = {}        # guarded-by: _lock
        self._queued: dict[str, JobRecord] = {}   # guarded-by: _lock
        self._running: dict[str, JobRecord] = {}  # guarded-by: _lock
        #: job id -> epoch THIS replica claimed; the fencing token a
        #: settle compares against the on-disk record. Kept on lease
        #: loss (the evidence a zombie's settle is fenced WITH), popped
        #: only when the settle verdict lands.
        self._claimed: dict[str, int] = {}        # guarded-by: _lock
        #: record-file stat signatures for the poll() rescan
        self._stat: dict[str, tuple] = {}         # guarded-by: _lock
        self._last_refresh = 0.0                  # guarded-by: _lock
        self._next_id = 1                         # guarded-by: _lock
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.recovery: dict = {"jobs": 0, "requeued": 0, "done": 0,
                               "failed": 0, "quarantined": 0, "peer": 0}
        with _REPLICAS_LOCK:
            _LIVE_REPLICAS.add(self.replica)
        try:
            #: incarnation counter for THIS replica id over this root,
            #: bumped durably on every open: a stable --replica-id that
            #: restarts shows up in /status and the span journal as the
            #: same name with a fresh epoch, so fleet views and traces
            #: can tell generations apart (chaos restarts, bounces)
            self.replica_epoch = self._bump_replica_epoch()
            self.spans = SpanJournal(
                os.path.join(self.root, "spans"), self.replica,
                replica_epoch=self.replica_epoch,
            )
            self._recover()
        except BaseException:
            # a constructor that dies (disk failure mid-recovery, or
            # the crashcheck harness's injected deaths) must not leak
            # its liveness claims: a name left in _LIVE_REPLICAS would
            # make this replica's stale leases look alive forever
            with _REPLICAS_LOCK:
                _LIVE_REPLICAS.discard(self.replica)
            fd, self._lockfd = self._lockfd, -1
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
            raise

    def _bump_replica_epoch(self) -> int:
        path = os.path.join(
            self.root, "replica-epochs",
            safe_replica_name(self.replica) + ".json",
        )
        with self._lock:
            with self._flock():
                try:
                    with open(path) as f:
                        epoch = int(json.load(f).get("epoch", 0)) + 1
                except (OSError, ValueError, TypeError):
                    epoch = 1
                try:
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    atomic_write_json(path, {"epoch": epoch})
                except OSError:
                    pass  # identity bookkeeping must not block startup
        return epoch

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        """Release this replica's liveness claims: stop the heartbeat,
        unregister from the in-process liveness set, drop the lock fd.
        After close() this replica's leases are reclaimable by peers
        (and by a successor queue in this same process — the restart
        path tests exercise). Idempotent; mutating calls after close
        raise OSError."""
        self.stop_heartbeat()
        with _REPLICAS_LOCK:
            _LIVE_REPLICAS.discard(self.replica)
        self.spans.close()
        fd, self._lockfd = self._lockfd, -1
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass

    def start_heartbeat(self, interval_s: Optional[float] = None) -> None:
        """Renew this replica's leases periodically (lease_s/3 default).
        Without a heartbeat a long execution outlives its lease and a
        peer may steal it mid-flight — fine for single-replica tests,
        wrong for a fleet."""
        if self._hb_thread is not None:
            return
        interval = interval_s if interval_s is not None else \
            max(0.05, self.lease_s / 3.0)
        self._hb_stop.clear()

        def _loop() -> None:
            while not self._hb_stop.wait(timeout=interval):
                try:
                    self.renew_leases()
                except Exception:  # noqa: BLE001 - heartbeat must survive disk hiccups
                    get_logger().exception(
                        "serve queue: lease renewal pass failed")

        self._hb_thread = threading.Thread(
            target=_loop, name="chain-serve-lease-heartbeat", daemon=True,
        )
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
            self._hb_thread = None

    # ----------------------------------------------------------- layout

    def _record_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id + ".json")

    def _sentinel_path(self, job_id: str) -> str:
        return self._record_path(job_id) + ".inprogress"

    @contextmanager
    def _flock(self) -> Iterator[None]:
        """Cross-process mutual exclusion for record transitions. Only
        ever taken under self._lock (one fd per process: flock on the
        same open file description is recursive, so in-process nesting
        MUST be prevented by the thread lock, not the kernel). The
        kernel releases it when the holder dies, so a SIGKILLed replica
        can never wedge the fleet; a SIGSTOPped one stalls peers only
        for the (sub-millisecond) critical sections, not for the length
        of its executions — leases cover those."""
        if self._lockfd < 0:
            raise OSError("queue is closed")
        fcntl.flock(self._lockfd, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(self._lockfd, fcntl.LOCK_UN)

    @contextmanager
    def exclusive(self) -> Iterator[None]:
        """The queue's cross-process mutual exclusion, lent out for
        fleet-level decisions that need the same fence — the service's
        orphan-request adoption claims a dead peer's request doc under
        it, so two surviving replicas cannot both adopt one orphan.
        Keep the body to a read-check-write; peers' queue mutations
        wait behind it."""
        with self._lock:
            with self._flock():
                yield

    # holds-lock: _lock
    def _persist(self, record: JobRecord) -> None:
        path = self._record_path(record.job_id)
        atomic_write_json(path, record.to_json(), durable=True,
                          sort_keys=True)
        try:
            st = os.stat(path)
        except OSError:
            return
        self._stat[record.job_id] = (st.st_mtime_ns, st.st_size, st.st_ino)

    # holds-lock: _lock
    def _read_disk(self, job_id: str) -> Optional[JobRecord]:
        """The on-disk record — the shared truth a settle is fenced
        against. None when unreadable/missing (the in-memory copy then
        stands in)."""
        try:
            with open(self._record_path(job_id)) as f:
                return JobRecord.from_json(json.load(f))
        except (OSError, ValueError, KeyError):
            return None

    # ------------------------------------------------------------ leases

    # holds-lock: _lock
    def _write_lease(self, record: JobRecord) -> None:
        now = time.time()
        atomic_write_json(self._sentinel_path(record.job_id), {
            "replica": self.replica,
            "epoch": record.epoch,
            "pid": os.getpid(),
            "host": _HOST,
            "acquiredAt": now,
            "expiresAt": now + self.lease_s,
        })

    # holds-lock: _lock
    def _read_lease(self, job_id: str) -> Optional[dict]:
        """The lease next to a record: a dict, {} for a legacy empty
        sentinel (pre-lease format: ownerless), None when absent."""
        try:
            with open(self._sentinel_path(job_id)) as f:
                text = f.read()
        except OSError:
            return None
        if not text.strip():
            return {}
        try:
            lease = json.loads(text)
        except ValueError:
            return {}
        return lease if isinstance(lease, dict) else {}

    # holds-lock: _lock
    def _lease_dead(self, lease: Optional[dict], now: float,
                    job_id: str) -> bool:
        """True when a lease no longer protects its record. Expiry is
        the universal trigger (a live-but-stuck holder loses after
        lease_s without renewal — the SIGSTOP-zombie case); same-host
        holders that are demonstrably dead (pid gone, or a previous
        incarnation in this very process) are reclaimed immediately so
        a daemon restart never waits out its own stale lease."""
        if not lease:  # absent or legacy empty sentinel: ownerless
            return True
        if lease.get("replica") == self.replica:
            # our NAME — but a stable --replica-id survives restarts,
            # so the name alone proves nothing: the lease is ours only
            # if we hold the exact claim it records. A previous
            # incarnation's lease under our name is dead NOW, not
            # after expiry.
            try:
                lease_epoch = int(lease.get("epoch", -1))
            except (TypeError, ValueError):
                return True
            return self._claimed.get(job_id) != lease_epoch
        if now >= float(lease.get("expiresAt", 0.0) or 0.0):
            return True
        if lease.get("host") == _HOST:
            try:
                pid = int(lease.get("pid", 0) or 0)
            except (TypeError, ValueError):
                return True
            if pid == os.getpid():
                with _REPLICAS_LOCK:
                    return lease.get("replica") not in _LIVE_REPLICAS
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return True
            except OSError:
                pass  # EPERM etc: the pid exists — trust the expiry
        return False

    def renew_leases(self) -> list[str]:
        """One heartbeat pass: extend every lease this replica still
        holds; report (and emit `serve_lease_lost` for) records whose
        lease moved on — the settle for those will be fenced."""
        lost: list[tuple] = []
        with self._lock:
            with self._flock():
                for job_id, record in list(self._running.items()):
                    lease = self._read_lease(job_id)
                    if (lease and lease.get("replica") == self.replica
                            and int(lease.get("epoch", -1))
                            == self._claimed.get(job_id)):
                        try:
                            self._write_lease(record)
                        except OSError:
                            get_logger().warning(
                                "serve queue: could not renew lease for %s",
                                job_id)
                    else:
                        # stolen (or vandalized): we no longer own this
                        # execution; keep _claimed so the settle fences
                        self._running.pop(job_id, None)
                        lost.append((job_id, record.plan_hash))
        for job_id, plan in lost:
            tm.emit("serve_lease_lost", job=job_id, plan=plan,
                    replica=self.replica)
        return [job_id for job_id, _ in lost]

    # ---------------------------------------------------------- indexes

    # holds-lock: _lock
    def _absorb(self, record: JobRecord) -> None:
        """Reconcile the in-memory view with one record instance (fresh
        from disk or just persisted). Ownership bookkeeping: a record
        stays in _running only while the epoch we claimed still matches
        — an epoch that moved on means a peer stole it."""
        job_id = record.job_id
        self._jobs[job_id] = record
        if record.state == "queued":
            self._queued[job_id] = record
        else:
            self._queued.pop(job_id, None)
        if (record.state == "running"
                and self._claimed.get(job_id) == record.epoch):
            self._running[job_id] = record
        else:
            self._running.pop(job_id, None)
        cur_id = self._by_plan.get(record.plan_hash)
        if cur_id is None or cur_id == job_id:
            self._by_plan[record.plan_hash] = job_id
        elif (self._jobs[cur_id].state in ("failed", "quarantined")
                and record.state not in ("failed", "quarantined")):
            # a live record for the plan beats a dead-ended one
            self._by_plan[record.plan_hash] = job_id

    # holds-lock: _lock
    def _set_depth_gauge(self) -> None:
        _QUEUE_DEPTH.set(len(self._queued))

    # --------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Rebuild the in-memory view from disk. A `running` record is
        requeued (attempts+1) only when its lease is reclaimable — the
        holder is dead or the lease expired; a record legitimately
        owned by a LIVE peer replica stays running in our view (we are
        one daemon of a fleet, not the only survivor). The artifact
        store decides at execution time whether requeued work actually
        completed (a commit that landed before the kill is a warm hit,
        zero re-execution)."""
        log = get_logger()
        events: list[dict] = []
        with self._lock:
            with self._flock():
                try:
                    names = sorted(os.listdir(self.jobs_dir))
                except OSError:
                    names = []
                max_seq = 0
                now = time.time()
                for name in names:
                    if not name.endswith(".json"):
                        continue
                    path = os.path.join(self.jobs_dir, name)
                    try:
                        with open(path) as f:
                            record = JobRecord.from_json(json.load(f))
                    except (OSError, ValueError, KeyError) as exc:
                        log.warning("serve queue: unreadable record %s "
                                    "(%s); skipping", path, exc)
                        continue
                    seq = _id_seq(record.job_id)
                    max_seq = max(max_seq, seq)
                    lease = self._read_lease(record.job_id)
                    requeue = False
                    if record.state == "running":
                        # lease dead or missing: the execution died with
                        # its daemon (a missing lease also covers a
                        # crash between the record write and the lease
                        # write). A live peer's lease keeps it running.
                        if self._lease_dead(lease, now, record.job_id):
                            requeue = True
                        else:
                            self.recovery["peer"] += 1
                    elif lease is not None:
                        # stray lease on a settled/queued record: the
                        # settle's unlink raced a crash — clear it so
                        # the steal scan never trips on it
                        self._clear_sentinel(record.job_id)
                    if requeue:
                        # queue-transition: running -> queued (crash recovery: an interrupted execution re-arms)
                        record.state = "queued"
                        record.epoch += 1  # fence the dead owner's settle
                        record.owner = None
                        record.attempts += 1
                        record.error = None
                        record.queued_at = now
                        record.claimed_at = None
                        # span BEFORE persist (spans.py ordering rule)
                        self.spans.append(
                            "requeue", job=record.job_id,
                            plan=record.plan_hash, state="queued",
                            epoch=record.epoch, requests=record.requests,
                            traces=record.trace_ids, reason="recovery",
                            attempts=record.attempts,
                        )
                        self._persist(record)
                        self._clear_sentinel(record.job_id)
                        self.recovery["requeued"] += 1
                        events.append(dict(job=record.job_id,
                                           plan=record.plan_hash,
                                           attempts=record.attempts))
                    self.recovery["jobs"] += 1
                    for state in ("done", "failed", "quarantined"):
                        if record.state == state:
                            self.recovery[state] += 1
                    self._absorb(record)
                    try:
                        st = os.stat(path)
                        self._stat[record.job_id] = (
                            st.st_mtime_ns, st.st_size, st.st_ino)
                    except OSError:
                        pass
                self._next_id = max_seq + 1
                self._set_depth_gauge()
        for fields in events:
            tm.emit("serve_requeued", **fields)
        if self.recovery["requeued"]:
            log.warning(
                "serve queue: requeued %d interrupted job(s) after restart",
                self.recovery["requeued"],
            )

    # ------------------------------------------------------------- poll

    def poll(self) -> dict:
        """Multi-replica maintenance tick: merge peer record changes
        into the in-memory view, then reclaim records whose lease died
        (work stealing). Cheap when nothing changed — one stat per
        record file. Single-replica daemons may skip it entirely."""
        with self._lock:
            changed = self._refresh_locked()
        stolen = self._steal_dead_leases()
        return {"changed": changed, "stolen": stolen}

    # holds-lock: _lock
    def _refresh_locked(self) -> int:
        changed = 0
        self._last_refresh = time.time()
        try:
            names = os.listdir(self.jobs_dir)
        except OSError:
            return 0
        seen: set = set()
        for name in names:
            if not name.endswith(".json"):
                continue
            job_id = name[:-5]
            seen.add(job_id)
            path = os.path.join(self.jobs_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            sig = (st.st_mtime_ns, st.st_size, st.st_ino)
            if self._stat.get(job_id) == sig:
                continue
            try:
                with open(path) as f:
                    record = JobRecord.from_json(json.load(f))
            except (OSError, ValueError, KeyError):
                continue  # mid-replace or poisoned: next poll retries
            self._stat[job_id] = sig
            self._absorb(record)
            if _id_seq(job_id) >= self._next_id:
                self._next_id = _id_seq(job_id) + 1
            changed += 1
        # records whose file vanished (peer retention/cleanup) leave
        # the view — except ones we have claimed, whose settle verdict
        # is still owed
        for job_id in list(self._jobs):
            if job_id in seen or job_id in self._claimed:
                continue
            record = self._jobs.pop(job_id)
            self._queued.pop(job_id, None)
            self._running.pop(job_id, None)
            self._stat.pop(job_id, None)
            if self._by_plan.get(record.plan_hash) == job_id:
                self._by_plan.pop(record.plan_hash, None)
        if changed:
            self._set_depth_gauge()
        return changed

    def _steal_dead_leases(self) -> int:
        """Reclaim running records whose lease no longer protects them:
        requeue with the epoch bumped, so the previous owner — dead, or
        a zombie about to resume — can never settle what it lost."""
        with self._lock:
            candidates = [
                job_id for job_id, rec in self._jobs.items()
                if rec.state == "running" and job_id not in self._running
            ]
        stolen: list[dict] = []
        for job_id in candidates:
            with self._lock:
                with self._flock():
                    disk = self._read_disk(job_id)
                    if disk is None:
                        continue
                    if disk.state != "running":
                        self._absorb(disk)
                        continue
                    lease = self._read_lease(job_id)
                    if not self._lease_dead(lease, time.time(), job_id):
                        continue
                    prev = (lease or {}).get("replica")
                    # queue-transition: running -> queued (lease steal: the owner died or stopped renewing)
                    disk.state = "queued"
                    disk.epoch += 1
                    disk.owner = None
                    disk.attempts += 1
                    disk.error = None
                    disk.queued_at = time.time()
                    disk.claimed_at = None
                    self.spans.append(
                        "steal", job=job_id, plan=disk.plan_hash,
                        state="queued", epoch=disk.epoch,
                        requests=disk.requests, traces=disk.trace_ids,
                        from_replica=prev, attempts=disk.attempts,
                    )
                    try:
                        self._persist(disk)
                    except OSError:
                        get_logger().exception(
                            "serve queue: could not persist steal of %s",
                            job_id)
                        continue
                    self._clear_sentinel(job_id)
                    self._absorb(disk)
                    self._set_depth_gauge()
                    stolen.append(dict(
                        job=job_id, plan=disk.plan_hash,
                        from_replica=prev, epoch=disk.epoch,
                        attempts=disk.attempts,
                    ))
        for fields in stolen:
            _LEASE_STEALS.inc()
            tm.emit("serve_lease_stolen", by=self.replica, **fields)
        return len(stolen)

    # ---------------------------------------------------------- enqueue

    def enqueue(
        self,
        plan_hash: str,
        plan: dict,
        unit: dict,
        tenant: str,
        priority: str,
        request_id: str,
        output: str,
        trace_id: Optional[str] = None,
        cost_s: float = 0.0,
        src_digest: Optional[str] = None,
    ) -> tuple[JobRecord, str]:
        """Enqueue one unit (or attach to its in-flight twin). Returns
        (record, outcome) with outcome ∈ new | attached | done |
        quarantined: `attached` = a queued/running job with this plan
        hash already exists and now also answers `request_id`; `done` =
        the record completed earlier (the caller should serve from the
        store — and re-enqueue via `rearm` if the store lost the
        bytes); `quarantined` = the plan failed permanently — or its
        SRC content digest sits in the poison registry — and will not
        retry until an operator re-arms it (the request is attached
        for forensics, nothing is scheduled)."""
        note: dict = {}
        record, outcome = self._enqueue_locked(
            plan_hash, plan, unit, tenant, priority, request_id, output,
            trace_id, cost_s, src_digest, note,
        )
        if note.get("poisoned"):
            # the record was swept through the poison edge inside the
            # locked section; telemetry is emitted HERE, outside the
            # queue lock (module convention — the span journal already
            # carries the transition)
            _QUARANTINED.inc()
            tm.emit("serve_quarantined", job=record.job_id,
                    plan=record.plan_hash, error=record.error,
                    attempts=record.attempts)
        return record, outcome

    def _enqueue_locked(
        self,
        plan_hash: str,
        plan: dict,
        unit: dict,
        tenant: str,
        priority: int,
        request_id: str,
        output: str,
        trace_id: Optional[str],
        cost_s: float,
        src_digest: Optional[str],
        note: dict,
    ) -> tuple[JobRecord, str]:

        def _attach_ids(record: JobRecord) -> bool:
            changed = False
            if request_id not in record.requests:
                record.requests.append(request_id)
                changed = True
            if trace_id and trace_id not in record.trace_ids:
                record.trace_ids.append(trace_id)
                changed = True
            if cost_s > record.cost_s:
                # a pre-cost-model record (or a fresher estimate) picks
                # up the caller's prediction on EVERY attach path, not
                # just re-arm — wave packing and outstanding_cost must
                # not treat a known-heavy in-flight unit as free
                record.cost_s = float(cost_s)
                changed = True
            if src_digest and record.src_digest != src_digest:
                record.src_digest = src_digest
                changed = True
            return changed

        with self._lock:
            with self._flock():
                poison = self._read_poison(src_digest) if src_digest \
                    else None
                existing_id = self._by_plan.get(plan_hash)
                if existing_id is None and \
                        time.time() - self._last_refresh > 0.25:
                    # unknown plan: a PEER may have minted its record
                    # since our last rescan — refresh (throttled: one
                    # stat-scan per burst, not per unit) before minting
                    # a twin. Dedup across replicas stays eventual (a
                    # miss inside the throttle window makes a duplicate
                    # record, never a duplicate artifact — the store's
                    # plan-hash commit is exactly-once regardless).
                    self._refresh_locked()
                    existing_id = self._by_plan.get(plan_hash)
                if existing_id is not None:
                    # disk is the shared truth: a peer may have moved
                    # the record since our last poll
                    record = self._read_disk(existing_id) or \
                        self._jobs[existing_id]
                    if record.state in _ATTACHABLE:
                        if poison is not None and record.state == "queued":
                            # poisoned SRC: this queued record must not
                            # wait out the scheduler just to rediscover
                            # the quarantine — fail it fast here
                            _attach_ids(record)
                            self._quarantine_poisoned_locked(record, poison)
                            note["poisoned"] = True
                            return record, "quarantined"
                        if _attach_ids(record):
                            self.spans.append(
                                "attach", job=record.job_id,
                                plan=record.plan_hash, state=record.state,
                                epoch=record.epoch,
                                requests=[request_id],
                                traces=[trace_id] if trace_id else [],
                            )
                            self._persist(record)
                        self._absorb(record)
                        return record, "attached"
                    if record.state == "done":
                        if _attach_ids(record):
                            self._persist(record)
                        self._absorb(record)
                        return record, "done"
                    if record.state == "quarantined":
                        # permanent failures do NOT auto-retry: attach
                        # for forensics, refuse until an operator rearms
                        if _attach_ids(record):
                            self._persist(record)
                        self._absorb(record)
                        return record, "quarantined"
                    # failed: re-arm the same record for a fresh attempt
                    # — with a fresh attempt BUDGET (a plan that
                    # exhausted its retries last week must not inherit
                    # the spent counter)
                    self._rearm_locked(record)
                    _attach_ids(record)  # also re-stamps cost_s
                    self.spans.append(
                        "enqueue", job=record.job_id,
                        plan=record.plan_hash, state="queued",
                        epoch=record.epoch, requests=record.requests,
                        traces=record.trace_ids, rearm=True,
                    )
                    if poison is not None:
                        # the plan would retry, but its SRC bytes are
                        # quarantined: park it through the declared
                        # poison-sweep edge instead of scheduling it
                        self._quarantine_poisoned_locked(record, poison)
                        note["poisoned"] = True
                        return record, "quarantined"
                    self._persist(record)
                    self._absorb(record)
                    self._set_depth_gauge()
                    return record, "new"
                # fresh plan: mint a record under an id no replica has
                # used (the probe matters — peers allocate from the
                # same namespace and our view of it may lag a poll)
                while os.path.exists(
                        self._record_path(f"j{self._next_id:06d}")):
                    self._next_id += 1
                now = time.time()
                record = JobRecord(
                    job_id=f"j{self._next_id:06d}",
                    plan_hash=plan_hash,
                    plan=plan,
                    unit=unit,
                    tenant=tenant,
                    priority=priority,
                    output=output,
                    requests=[request_id],
                    trace_ids=[trace_id] if trace_id else [],
                    state="queued",
                    enqueued_at=now,
                    queued_at=now,
                    cost_s=max(0.0, float(cost_s)),
                    src_digest=src_digest,
                )
                self._next_id += 1
                self.spans.append(
                    "enqueue", job=record.job_id, plan=plan_hash,
                    state="queued", epoch=record.epoch,
                    requests=record.requests, traces=record.trace_ids,
                    tenant=tenant, priority=priority,
                )
                if poison is not None:
                    # a fresh plan against a poisoned SRC: the record
                    # exists for forensics (which requests asked, what
                    # the poison verdict was) but parks immediately —
                    # fail-fast is the whole point of the digest
                    # registry (docs/ROBUSTNESS.md)
                    self._quarantine_poisoned_locked(record, poison)
                    note["poisoned"] = True
                    return record, "quarantined"
                self._persist(record)
                self._absorb(record)
                self._set_depth_gauge()
                return record, "new"

    # holds-lock: _lock
    def _rearm_locked(self, record: JobRecord) -> None:
        """Shared re-arm reset: a terminal record back to queued with a
        FRESH budget and clean forensics (no stale error/errorKind/
        settledEpoch from the settled life it just left)."""
        # queue-transition: done|failed|quarantined -> queued (re-arm: evicted artifact / fresh request / operator retry)
        record.state = "queued"
        record.error = None
        record.error_kind = None
        record.warm = False
        record.attempts = 0
        record.not_before = 0.0
        record.settled_epoch = None
        record.enqueued_at = time.time()
        record.queued_at = record.enqueued_at
        record.claimed_at = None

    def rearm(self, job_id: str) -> Optional[JobRecord]:
        """Force a terminal record back to queued: the store evicted a
        done record's artifact, or an operator cleared a quarantine
        (docs/SERVE.md "Quarantine workflow"). No-op on queued/running
        records."""
        with self._lock:
            with self._flock():
                record = self._read_disk(job_id) or self._jobs.get(job_id)
                if record is None or record.state in _ATTACHABLE:
                    return record
                self._rearm_locked(record)
                self.spans.append(
                    "enqueue", job=record.job_id, plan=record.plan_hash,
                    state="queued", epoch=record.epoch,
                    requests=record.requests, traces=record.trace_ids,
                    rearm=True,
                )
                self._persist(record)
                self._absorb(record)
                self._set_depth_gauge()
                return record

    # -------------------------------------------------- poison registry
    #
    # One JSON file per quarantined SRC content digest under
    # <root>/poison/ — durable, shared by every replica over the root
    # (reads are whole-file; writes hold the flock like any queue
    # mutation). A digest lands here when an execution settles with the
    # `poison` failure kind (docs/SERVE.md "Failure taxonomy"): the SRC
    # BYTES are hostile, so every plan referencing them — any HRC, any
    # tenant, any replica — fails fast instead of rediscovering the
    # poison one retry budget at a time. `tools serve-admin poison`
    # is the operator surface (ls / rearm).

    def _poison_path(self, digest: str) -> str:
        return os.path.join(self.root, "poison", digest + ".json")

    # holds-lock: _lock
    def _read_poison(self, digest: str) -> Optional[dict]:
        try:
            with open(self._poison_path(digest)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    # holds-lock: _lock
    def _quarantine_poisoned_locked(self, record: JobRecord,
                                    poison: dict) -> None:
        """Park one queued record whose SRC digest is poisoned, through
        the declared poison-sweep edge. Telemetry stays with the
        callers (events must not be emitted under the queue lock)."""
        # queue-transition: queued -> quarantined (poison sweep: the record's SRC digest was quarantined fleet-wide)
        record.state = "quarantined"
        record.error = (
            f"SRC digest {record.src_digest} is quarantined: "
            f"{poison.get('error', 'poisoned input')}"
        )[:500]
        record.error_kind = "poison"
        record.done_at = time.time()
        record.settled_epoch = record.epoch
        self.spans.append(
            "quarantine", job=record.job_id, plan=record.plan_hash,
            state="quarantined", epoch=record.epoch,
            requests=record.requests, traces=record.trace_ids,
            error=record.error, kind="poison",
        )
        self._persist(record)
        self._clear_sentinel(record.job_id)
        self._absorb(record)
        self._set_depth_gauge()

    def poison_src(self, digest: str, src: Optional[str] = None,
                   error: str = "", by_job: Optional[str] = None
                   ) -> list[JobRecord]:
        """Quarantine one SRC content digest fleet-wide: register it
        durably, then sweep every QUEUED record carrying it through the
        declared poison edge (running records settle on their own — the
        epoch fence makes interfering with a live execution wrong).
        Returns the swept records so the caller can fail their
        waiters. Idempotent: re-poisoning an already-registered digest
        only re-runs the sweep."""
        if not digest:
            return []
        swept: list[JobRecord] = []
        with self._lock:
            with self._flock():
                path = self._poison_path(digest)
                existing = self._read_poison(digest)
                doc = existing or {
                    "digest": digest,
                    "src": src,
                    "error": str(error)[:500],
                    "job": by_job,
                    "poisonedAt": time.time(),
                }
                os.makedirs(os.path.dirname(path), exist_ok=True)
                atomic_write_json(path, doc, durable=True, sort_keys=True)
                self._refresh_locked()  # peers' records join the sweep
                for job_id, record in list(self._queued.items()):
                    if record.src_digest != digest:
                        continue
                    disk = self._read_disk(job_id) or record
                    if disk.state != "queued" or disk.src_digest != digest:
                        self._absorb(disk)
                        continue
                    self._quarantine_poisoned_locked(disk, doc)
                    swept.append(disk)
        if existing is None:
            # one counter tick / event per DIGEST, not per convicted
            # record: re-poisoning an already-registered digest (a
            # second attributed member of the same wave, a rearm that
            # re-convicts) only re-runs the sweep — the swept records
            # below still carry their own serve_quarantined forensics
            _SRC_POISONED.inc()
            tm.emit("serve_src_poisoned", digest=digest, src=src,
                    error=str(error)[:500], job=by_job,
                    swept=[r.job_id for r in swept])
        for record in swept:
            _QUARANTINED.inc()
            tm.emit("serve_quarantined", job=record.job_id,
                    plan=record.plan_hash, error=record.error,
                    attempts=record.attempts)
        return swept

    def src_poisoned(self, digest: str) -> Optional[dict]:
        """The poison registry entry for one digest (None = clean)."""
        if not digest:
            return None
        with self._lock:
            return self._read_poison(digest)

    def poisoned_digests(self) -> list[dict]:
        """Every registered poison entry (operator/admin surface)."""
        entries: list[dict] = []
        poison_dir = os.path.join(self.root, "poison")
        try:
            names = sorted(os.listdir(poison_dir))
        except OSError:
            return entries
        with self._lock:
            for name in names:
                if name.endswith(".json"):
                    doc = self._read_poison(name[:-5])
                    if doc is not None:
                        entries.append(doc)
        return entries

    def rearm_src(self, digest: str) -> dict:
        """Operator re-arm of one poisoned digest (docs/ROBUSTNESS.md
        "Quarantine & re-arm"): drop the registry entry, then re-arm
        every quarantined record that carries the digest so a fresh
        request (or the records' own waiters) can retry against the
        repaired SRC. Returns {"digest", "was_poisoned", "rearmed"}."""
        rearmed: list[str] = []
        with self._lock:
            with self._flock():
                was = self._read_poison(digest) is not None
                try:
                    os.unlink(self._poison_path(digest))
                except FileNotFoundError:
                    pass
                self._refresh_locked()
                for job_id, record in list(self._jobs.items()):
                    if record.src_digest != digest:
                        continue
                    disk = self._read_disk(job_id) or record
                    if disk.state != "quarantined":
                        continue
                    self._rearm_locked(disk)
                    self.spans.append(
                        "enqueue", job=disk.job_id, plan=disk.plan_hash,
                        state="queued", epoch=disk.epoch,
                        requests=disk.requests, traces=disk.trace_ids,
                        rearm=True,
                    )
                    self._persist(disk)
                    self._absorb(disk)
                    rearmed.append(job_id)
                self._set_depth_gauge()
        return {"digest": digest, "was_poisoned": was, "rearmed": rearmed}

    # ------------------------------------------------------- scheduling

    def queued_snapshot(self) -> list[JobRecord]:
        """Claim-eligible records: queued, and past their retry backoff
        (`not_before` — a transient failure's re-eligibility time)."""
        now = time.time()
        with self._lock:
            return sorted(
                (r for r in self._queued.values() if r.not_before <= now),
                key=lambda r: r.enqueued_at,
            )

    def claim(self, job_ids: list[str]) -> list[JobRecord]:
        """Move jobs queued → running (epoch bumped, lease down). Jobs
        another worker or replica claimed first are silently skipped —
        the returned list is what THIS caller owns. A disk failure
        (ENOSPC/EIO on the rewrite or the lease) mid-way through the
        list reverts THAT record to queued and stops claiming
        (`serve_claim_reverted`): the caller still owns everything
        claimed before it, so no record is ever stranded in 'running'
        with no owner while enqueue attaches newcomers to it."""
        owned: list[JobRecord] = []
        reverted: list[dict] = []
        waited: list[tuple] = []
        now = time.time()
        with self._lock:
            with self._flock():
                for job_id in job_ids:
                    if job_id not in self._queued:
                        continue
                    record = self._read_disk(job_id) or self._queued[job_id]
                    if record.state != "queued" or record.not_before > now:
                        self._absorb(record)  # peer moved it meanwhile
                        continue
                    wait_s = max(
                        0.0, now - (record.queued_at or record.enqueued_at)
                    )
                    try:
                        # queue-transition: queued -> running (claim: this worker owns the execution)
                        record.state = "running"
                        record.epoch += 1
                        record.owner = self.replica
                        record.claimed_at = now
                        self.spans.append(
                            "claim", job=job_id, plan=record.plan_hash,
                            state="running", epoch=record.epoch,
                            requests=record.requests,
                            traces=record.trace_ids,
                            queue_wait_s=round(wait_s, 6),
                            wave=len(job_ids),
                        )
                        self._persist(record)
                        self._write_lease(record)
                    except OSError:
                        # queue-transition: running -> queued (claim revert: the disk refused the rewrite/lease)
                        record.state = "queued"
                        record.epoch -= 1
                        record.owner = None
                        record.claimed_at = None
                        self.spans.append(
                            "revert", job=job_id, plan=record.plan_hash,
                            state="queued", epoch=record.epoch,
                            requests=record.requests,
                            traces=record.trace_ids,
                        )
                        try:
                            self._persist(record)
                        except OSError:
                            pass  # peers' steal scan reclaims the orphan
                        try:
                            self._clear_sentinel(job_id)
                        except OSError:  # the disk is already misbehaving
                            pass  # recovery treats a stray lease as dead
                        self._absorb(record)
                        reverted.append(dict(job=job_id,
                                             plan=record.plan_hash))
                        get_logger().exception(
                            "serve queue: claim of %s failed; reverted to "
                            "queued", job_id,
                        )
                        break
                    self._claimed[job_id] = record.epoch
                    self._absorb(record)
                    owned.append(record)
                    waited.append((record.tenant, record.priority, wait_s))
                self._set_depth_gauge()
        for tenant, priority, wait_s in waited:
            _QUEUE_WAIT.labels(tenant=tenant, priority=priority) \
                .observe(wait_s)
        for fields in reverted:
            _CLAIM_REVERTS.inc()
            tm.emit("serve_claim_reverted", replica=self.replica, **fields)
        return owned

    # ----------------------------------------------------------- settle

    # holds-lock: _lock
    def _fence_check(self, job_id: str, op: str) -> tuple:
        """(base_record, fenced_fields). Every settle starts here: the
        on-disk record's epoch must match the epoch THIS replica
        claimed, or the caller lost ownership (steal, recovery by a
        peer) while it executed — its verdict is refused and the record
        left exactly as the current owner's protocol put it."""
        record = self._jobs.get(job_id)
        if record is None:
            return None, None
        disk = self._read_disk(job_id)
        ours = self._claimed.get(job_id, record.epoch)
        if disk is not None and disk.epoch != ours:
            # NOTE: the stale _claimed entry is deliberately KEPT — it
            # is the memory that we lost this record. Popping it here
            # would let a SECOND settle attempt fall back to the
            # absorbed (current) epoch and sail through the fence. It
            # clears only on a successful settle or a fresh claim.
            self._running.pop(job_id, None)
            self._absorb(disk)
            return None, dict(job=job_id, plan=record.plan_hash, op=op,
                              held_epoch=ours, current_epoch=disk.epoch)
        base = disk if disk is not None else record
        # merge request attachments a peer may have added meanwhile —
        # our in-memory copy can lag the shared record
        for req_id in record.requests:
            if req_id not in base.requests:
                base.requests.append(req_id)
        self._claimed.pop(job_id, None)
        return base, None

    def complete(self, job_id: str, warm: bool = False) -> Optional[JobRecord]:
        """Settle a claimed job as done. Epoch-fenced: returns None
        (and emits `serve_settle_fenced`) when ownership moved on —
        a zombie replica resumed after SIGSTOP cannot settle a record
        a live peer stole from it."""
        fenced = None
        exec_obs: Optional[tuple] = None
        with self._lock:
            with self._flock():
                base, fenced = self._fence_check(job_id, "complete")
                if base is None and fenced is None:
                    return None
                if fenced is None:
                    self._running.pop(job_id, None)
                    # queue-transition: running -> done (execution or warm hit settled)
                    base.state = "done"
                    base.warm = warm
                    base.error = None
                    base.error_kind = None
                    base.done_at = time.time()
                    base.settled_epoch = base.epoch
                    exec_s = None
                    if base.claimed_at:
                        exec_s = max(0.0, base.done_at - base.claimed_at)
                        if not warm:
                            exec_obs = (base.tenant, base.priority, exec_s)
                    self.spans.append(
                        "complete", job=job_id, plan=base.plan_hash,
                        state="done", epoch=base.epoch,
                        requests=base.requests, traces=base.trace_ids,
                        warm=warm,
                        exec_s=round(exec_s, 6) if exec_s is not None
                        else None,
                    )
                    self._persist(base)
                    self._clear_sentinel(job_id)
                    self._absorb(base)
                    self._set_depth_gauge()
        if exec_obs is not None:
            _EXEC_SECONDS.labels(tenant=exec_obs[0],
                                 priority=exec_obs[1]).observe(exec_obs[2])
        if fenced is not None:
            self._fenced_span(fenced)
            _FENCED_SETTLES.inc()
            tm.emit("serve_settle_fenced", replica=self.replica, **fenced)
            return None
        return base

    def _fenced_span(self, fenced: dict) -> None:
        """Forensic span for a refused stale-epoch settle: not part of
        any record's gapless chain (nothing transitioned), but `tools
        trace show` renders it so a stolen request's timeline shows the
        zombie's verdict bouncing off the fence."""
        self.spans.append(
            "fenced", job=fenced["job"], plan=fenced["plan"],
            state="", epoch=fenced["current_epoch"],
            op=fenced["op"], held_epoch=fenced["held_epoch"],
        )

    def fail(self, job_id: str, error: str, requeue: bool = False,
             backoff_s: float = 0.0,
             kind: Optional[str] = None) -> Optional[JobRecord]:
        """Settle a claimed job as failed — or requeue it for a retry,
        eligible again only after `backoff_s` (exponential backoff with
        jitter is the SCHEDULER's policy; the queue just persists
        `not_before` so the whole replica fleet honors it). Epoch-fenced
        like complete()."""
        fenced = None
        with self._lock:
            with self._flock():
                base, fenced = self._fence_check(job_id, "fail")
                if base is None and fenced is None:
                    return None
                if fenced is None:
                    self._running.pop(job_id, None)
                    base.error = str(error)[:500]
                    base.error_kind = kind
                    if requeue:
                        # queue-transition: running -> queued (retry: attempts budget not exhausted; not_before backoff)
                        base.state = "queued"
                        base.attempts += 1
                        base.owner = None
                        base.not_before = time.time() + max(0.0, backoff_s)
                        base.queued_at = time.time()
                        base.claimed_at = None
                        self.spans.append(
                            "requeue", job=job_id, plan=base.plan_hash,
                            state="queued", epoch=base.epoch,
                            requests=base.requests, traces=base.trace_ids,
                            reason="retry", attempts=base.attempts,
                            backoff_s=round(max(0.0, backoff_s), 3),
                            error=base.error, kind=kind,
                        )
                    else:
                        # queue-transition: running -> failed (attempts budget exhausted)
                        base.state = "failed"
                        base.done_at = time.time()
                        base.settled_epoch = base.epoch
                        self.spans.append(
                            "fail", job=job_id, plan=base.plan_hash,
                            state="failed", epoch=base.epoch,
                            requests=base.requests, traces=base.trace_ids,
                            error=base.error, kind=kind,
                        )
                    self._persist(base)
                    self._clear_sentinel(job_id)
                    self._absorb(base)
                    self._set_depth_gauge()
        if fenced is not None:
            self._fenced_span(fenced)
            _FENCED_SETTLES.inc()
            tm.emit("serve_settle_fenced", replica=self.replica, **fenced)
            return None
        return base

    def quarantine(self, job_id: str, error: str,
                   kind: str = "permanent") -> Optional[JobRecord]:
        """Settle a claimed job as PERMANENTLY failed: no retry will
        change the outcome (bad params, corrupt SRC), so the plan is
        parked with its forensics instead of burning the attempts
        budget. Only `rearm` (the operator workflow) resurrects it.
        Epoch-fenced like complete()."""
        fenced = None
        with self._lock:
            with self._flock():
                base, fenced = self._fence_check(job_id, "quarantine")
                if base is None and fenced is None:
                    return None
                if fenced is None:
                    self._running.pop(job_id, None)
                    # queue-transition: running -> quarantined (permanent failure: retrying is futile)
                    base.state = "quarantined"
                    base.error = str(error)[:500]
                    base.error_kind = kind
                    base.done_at = time.time()
                    base.settled_epoch = base.epoch
                    self.spans.append(
                        "quarantine", job=job_id, plan=base.plan_hash,
                        state="quarantined", epoch=base.epoch,
                        requests=base.requests, traces=base.trace_ids,
                        error=base.error, kind=kind,
                    )
                    self._persist(base)
                    self._clear_sentinel(job_id)
                    self._absorb(base)
                    self._set_depth_gauge()
        if fenced is not None:
            self._fenced_span(fenced)
            _FENCED_SETTLES.inc()
            tm.emit("serve_settle_fenced", replica=self.replica, **fenced)
            return None
        _QUARANTINED.inc()
        tm.emit("serve_quarantined", job=job_id, plan=base.plan_hash,
                error=base.error, attempts=base.attempts)
        return base

    # holds-lock: _lock
    def _clear_sentinel(self, job_id: str) -> None:
        try:
            os.unlink(self._sentinel_path(job_id))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------ views

    def record(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(job_id)

    def by_plan(self, plan_hash: str) -> Optional[JobRecord]:
        with self._lock:
            job_id = self._by_plan.get(plan_hash)
            return self._jobs.get(job_id) if job_id else None

    def counts(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for record in self._jobs.values():
                states[record.state] = states.get(record.state, 0) + 1
            return states

    def outstanding_cost(self, tenant: Optional[str] = None) -> float:
        """Predicted seconds of unfinished (queued + running) work, for
        one tenant or the whole queue — the admission gate's view of a
        tenant's backlog (serve/cost.py). Reads this replica's merged
        view of the shared records: cross-replica freshness is bounded
        by the poll interval, which is the admission contract
        (docs/SERVE.md 'Cost-aware scheduling & admission')."""
        with self._lock:
            return sum(
                record.cost_s
                for source in (self._queued, self._running)
                for record in source.values()
                if tenant is None or record.tenant == tenant
            )

    def backlog(self) -> dict:
        """Queued (not yet claimed) work per priority class — record
        counts plus predicted seconds — the autoscale advisor's
        per-class input (serve/autoscale.py): which class is waiting
        picks how fast the fleet must drain."""
        with self._lock:
            out: dict[str, dict] = {}
            for record in self._queued.values():
                cls = out.setdefault(record.priority,
                                     {"count": 0, "cost_s": 0.0})
                cls["count"] += 1
                cls["cost_s"] += record.cost_s
            for cls in out.values():
                cls["cost_s"] = round(cls["cost_s"], 3)
            return out
