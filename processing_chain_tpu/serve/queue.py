"""Durable, dedup-aware job queue: the serve daemon's crash-proof spine.

One JSON record per job under `jobs/`, every state change an atomic
rewrite (utils/fsio — the store's tmp+rename idiom), so a reader or a
restarted daemon never sees a torn record. While a job executes, a
`<record>.inprogress` sentinel sits next to it (the engine's crash
discipline, applied to queue records): a daemon SIGKILLed mid-execution
leaves the sentinel behind, and recovery REQUEUES the job instead of
stranding it — the artifact-level sentinel inside engine.Job
independently guarantees the half-written output is rebuilt, not
trusted.

Dedup is identity-by-plan-hash, the store's own key: enqueueing a unit
whose plan hash already has a queued/running job ATTACHES the new
request to that record instead of minting a second execution —
overlapping requests from any number of tenants share one job by
construction (singleflight). A plan whose job already completed is the
caller's warm path (the store serves it); a failed or evicted plan
re-arms the same record.

States: queued → running → done | failed (failed/evicted re-arm to
queued on the next enqueue). The machine is DECLARED below (STATES /
INITIAL / TRANSITIONS) and that declaration is load-bearing: chainlint's
`queue-transition` rule rejects any state write that is not an annotated
declared edge, `tools queue-crashcheck` fault-injects every atomic-write
boundary against it, and docs/SERVE.md renders it. The record keeps
every request ID it answers, `attempts`, and timing for forensics.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Optional

from .. import telemetry as tm
from ..utils import lockdebug
from ..utils.fsio import atomic_write_json
from ..utils.log import get_logger

_QUEUE_DEPTH = tm.gauge(
    "chain_serve_queue_depth", "jobs waiting in the serve queue"
)

# --------------------------------------------------------------------------
# The record state machine, declared ONCE. Three consumers share this
# table (docs/SERVE.md "State machine"): chainlint's `queue-transition`
# rule verifies every `.state` write in serve code is an annotated,
# declared edge; `tools queue-crashcheck` fault-injects every
# atomic-write boundary and asserts recovery lands every record in a
# declared state; docs/SERVE.md renders it between the
# queue-transitions markers (`tools queue-crashcheck --render-table`).
# Keep every entry a literal — the linter parses this by AST.

#: every state a durable record can be in
STATES = ("queued", "running", "done", "failed")

#: the only state a record may be created in
INITIAL = "queued"

#: declared edges: (from, to)
TRANSITIONS = frozenset({
    ("queued", "running"),   # claim: sentinel down, execution owned
    ("running", "done"),     # complete: store commit landed / warm hit
    ("running", "failed"),   # fail: attempts budget exhausted
    ("running", "queued"),   # fail(requeue) / claim revert / recovery
    ("failed", "queued"),    # re-arm: a fresh request retries the plan
    ("done", "queued"),      # re-arm: the store evicted the artifact
})

#: states a new request can attach to (the singleflight window)
_ATTACHABLE = ("queued", "running")


def _id_seq(job_id: str) -> int:
    """Numeric tail of a j-prefixed job id; 0 for foreign names."""
    try:
        return int(job_id.lstrip("j"))
    except ValueError:
        return 0


@dataclass
class JobRecord:
    """One durable unit of work, keyed by its plan hash."""

    job_id: str
    plan_hash: str
    plan: dict
    unit: dict            # {"database","src","hrc","params","pvs_id"}
    tenant: str
    priority: str
    output: str           # path RELATIVE to the artifacts root
    requests: list = field(default_factory=list)
    state: str = "queued"
    enqueued_at: float = 0.0
    attempts: int = 0
    error: Optional[str] = None
    done_at: Optional[float] = None
    warm: bool = False    # completed via store hit, not execution

    def to_json(self) -> dict:
        return {
            "job": self.job_id,
            "planHash": self.plan_hash,
            "plan": self.plan,
            "unit": self.unit,
            "tenant": self.tenant,
            "priority": self.priority,
            "output": self.output,
            "requests": list(self.requests),
            "state": self.state,
            "enqueuedAt": self.enqueued_at,
            "attempts": self.attempts,
            "error": self.error,
            "doneAt": self.done_at,
            "warm": self.warm,
        }

    @classmethod
    def from_json(cls, data: dict) -> "JobRecord":
        return cls(
            job_id=data["job"],
            plan_hash=data["planHash"],
            plan=data["plan"],
            unit=data["unit"],
            tenant=data.get("tenant", ""),
            priority=data.get("priority", "normal"),
            output=data.get("output", ""),
            requests=list(data.get("requests", [])),
            state=data.get("state", "queued"),
            enqueued_at=float(data.get("enqueuedAt", 0.0)),
            attempts=int(data.get("attempts", 0)),
            error=data.get("error"),
            done_at=data.get("doneAt"),
            warm=bool(data.get("warm", False)),
        )


class DurableQueue:
    """Crash-recoverable on-disk job queue with plan-hash dedup.

    Thread-safe: the scheduler's workers and the HTTP submit path hit it
    concurrently. All disk writes happen UNDER the queue lock — the
    record files are small and the atomic rewrite is one replace; a
    torn in-memory/on-disk split would be worse than the contention."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self._lock = lockdebug.make_lock("serve_queue")
        self._jobs: dict[str, JobRecord] = {}     # guarded-by: _lock
        self._by_plan: dict[str, str] = {}        # guarded-by: _lock
        self._queued: dict[str, JobRecord] = {}   # guarded-by: _lock
        self._running: dict[str, JobRecord] = {}  # guarded-by: _lock
        self._next_id = 1                         # guarded-by: _lock
        self.recovery: dict = {"jobs": 0, "requeued": 0, "done": 0,
                               "failed": 0}
        self._recover()

    # ----------------------------------------------------------- layout

    def _record_path(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id + ".json")

    def _sentinel_path(self, job_id: str) -> str:
        return self._record_path(job_id) + ".inprogress"

    # holds-lock: _lock
    def _persist(self, record: JobRecord) -> None:
        atomic_write_json(self._record_path(record.job_id),
                          record.to_json(), sort_keys=True)

    # holds-lock: _lock
    def _set_depth_gauge(self) -> None:
        _QUEUE_DEPTH.set(len(self._queued))

    # --------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Rebuild the in-memory view from disk. `.inprogress` sentinels
        mark executions a dead daemon never finished: requeue them
        (attempts+1) instead of stranding — the artifact store decides
        at execution time whether the work actually completed (a commit
        that landed before the kill is a warm hit, zero re-execution)."""
        log = get_logger()
        with self._lock:
            try:
                names = sorted(os.listdir(self.jobs_dir))
            except OSError:
                names = []
            max_seq = 0
            for name in names:
                if not name.endswith(".json"):
                    continue
                path = os.path.join(self.jobs_dir, name)
                try:
                    with open(path) as f:
                        record = JobRecord.from_json(json.load(f))
                except (OSError, ValueError, KeyError) as exc:
                    log.warning("serve queue: unreadable record %s (%s); "
                                "skipping", path, exc)
                    continue
                seq = _id_seq(record.job_id)
                max_seq = max(max_seq, seq)
                requeue = False
                if os.path.isfile(self._sentinel_path(record.job_id)):
                    requeue = True
                    try:
                        os.unlink(self._sentinel_path(record.job_id))
                    except OSError:
                        pass
                if record.state == "running":
                    # state says running but no sentinel: the rewrite to
                    # done/failed never landed either — same verdict
                    requeue = True
                if requeue:
                    if record.state != "queued":
                        # queue-transition: running -> queued (crash recovery: an interrupted execution re-arms)
                        record.state = "queued"
                    record.attempts += 1
                    record.error = None
                    self._persist(record)
                    self.recovery["requeued"] += 1
                    tm.emit("serve_requeued", job=record.job_id,
                            plan=record.plan_hash,
                            attempts=record.attempts)
                self._jobs[record.job_id] = record
                self.recovery["jobs"] += 1
                if record.state == "queued":
                    self._queued[record.job_id] = record
                elif record.state == "done":
                    self.recovery["done"] += 1
                elif record.state == "failed":
                    self.recovery["failed"] += 1
                # index preference: a live (queued/running/done) record
                # wins over a failed one for the same plan
                prior = self._by_plan.get(record.plan_hash)
                if prior is None or self._jobs[prior].state == "failed":
                    self._by_plan[record.plan_hash] = record.job_id
            self._next_id = max_seq + 1
            self._set_depth_gauge()
        if self.recovery["requeued"]:
            log.warning(
                "serve queue: requeued %d interrupted job(s) after restart",
                self.recovery["requeued"],
            )

    # ---------------------------------------------------------- enqueue

    def enqueue(
        self,
        plan_hash: str,
        plan: dict,
        unit: dict,
        tenant: str,
        priority: str,
        request_id: str,
        output: str,
    ) -> tuple[JobRecord, str]:
        """Enqueue one unit (or attach to its in-flight twin). Returns
        (record, outcome) with outcome ∈ new | attached | done:
        `attached` = a queued/running job with this plan hash already
        exists and now also answers `request_id`; `done` = the record
        completed earlier (the caller should serve from the store —
        and re-enqueue via `rearm=True` if the store lost the bytes)."""
        with self._lock:
            existing_id = self._by_plan.get(plan_hash)
            if existing_id is not None:
                record = self._jobs[existing_id]
                if record.state in _ATTACHABLE:
                    if request_id not in record.requests:
                        record.requests.append(request_id)
                        self._persist(record)
                    return record, "attached"
                if record.state == "done":
                    if request_id not in record.requests:
                        record.requests.append(request_id)
                        self._persist(record)
                    return record, "done"
                # failed: re-arm the same record for a fresh attempt —
                # with a fresh attempt BUDGET (a plan that exhausted its
                # retries last week must not inherit the spent counter)
                # queue-transition: failed -> queued (a fresh request retries the plan)
                record.state = "queued"
                record.error = None
                record.warm = False
                record.attempts = 0
                record.enqueued_at = time.time()
                if request_id not in record.requests:
                    record.requests.append(request_id)
                self._persist(record)
                self._queued[record.job_id] = record
                self._set_depth_gauge()
                return record, "new"
            record = JobRecord(
                job_id=f"j{self._next_id:06d}",
                plan_hash=plan_hash,
                plan=plan,
                unit=unit,
                tenant=tenant,
                priority=priority,
                output=output,
                requests=[request_id],
                state="queued",
                enqueued_at=time.time(),
            )
            self._next_id += 1
            self._persist(record)
            self._jobs[record.job_id] = record
            self._by_plan[plan_hash] = record.job_id
            self._queued[record.job_id] = record
            self._set_depth_gauge()
            return record, "new"

    def rearm(self, job_id: str) -> Optional[JobRecord]:
        """Force a done-but-evicted record back to queued (the store no
        longer holds its artifact and a request needs it again)."""
        with self._lock:
            record = self._jobs.get(job_id)
            if record is None or record.state in _ATTACHABLE:
                return record
            # queue-transition: done|failed -> queued (re-arm: store evicted / retry requested)
            record.state = "queued"
            record.error = None
            record.warm = False
            record.attempts = 0
            record.enqueued_at = time.time()
            self._persist(record)
            self._queued[record.job_id] = record
            self._set_depth_gauge()
            return record

    # ------------------------------------------------------- scheduling

    def queued_snapshot(self) -> list[JobRecord]:
        with self._lock:
            return sorted(self._queued.values(), key=lambda r: r.enqueued_at)

    def claim(self, job_ids: list[str]) -> list[JobRecord]:
        """Move jobs queued → running (sentinel down). Jobs another
        worker claimed first are silently skipped — the returned list is
        what THIS caller owns. A disk failure (ENOSPC/EIO on the
        sentinel or the rewrite) mid-way through the list reverts THAT
        record to queued and stops claiming: the caller still owns
        everything claimed before it, so no record is ever stranded in
        'running' with no owner while enqueue attaches newcomers to it."""
        owned: list[JobRecord] = []
        with self._lock:
            for job_id in job_ids:
                record = self._queued.pop(job_id, None)
                if record is None:
                    continue
                try:
                    # queue-transition: queued -> running (claim: this worker owns the execution)
                    record.state = "running"
                    self._running[job_id] = record
                    # chainlint: disable=atomic-write (sentinel: only its EXISTENCE signals an unfinished execution — same contract as the engine's .inprogress)
                    with open(self._sentinel_path(job_id), "w"):
                        pass
                    self._persist(record)
                except OSError:
                    # queue-transition: running -> queued (claim revert: the disk refused the sentinel/rewrite)
                    record.state = "queued"
                    self._running.pop(job_id, None)
                    self._queued[job_id] = record
                    try:
                        self._clear_sentinel(job_id)
                    except OSError:  # the disk is already misbehaving
                        pass         # recovery treats a stray sentinel as requeue
                    get_logger().exception(
                        "serve queue: claim of %s failed; reverted to "
                        "queued", job_id,
                    )
                    break
                owned.append(record)
            self._set_depth_gauge()
        return owned

    def complete(self, job_id: str, warm: bool = False) -> Optional[JobRecord]:
        with self._lock:
            record = self._jobs.get(job_id)
            if record is None:
                return None
            self._running.pop(job_id, None)
            self._queued.pop(job_id, None)
            # queue-transition: running -> done (execution or warm hit settled)
            record.state = "done"
            record.warm = warm
            record.error = None
            record.done_at = time.time()
            self._persist(record)
            self._clear_sentinel(job_id)
            self._set_depth_gauge()
            return record

    def fail(self, job_id: str, error: str,
             requeue: bool = False) -> Optional[JobRecord]:
        with self._lock:
            record = self._jobs.get(job_id)
            if record is None:
                return None
            self._running.pop(job_id, None)
            record.error = str(error)[:500]
            if requeue:
                # queue-transition: running -> queued (retry: attempts budget not exhausted)
                record.state = "queued"
                record.attempts += 1
                self._queued[job_id] = record
            else:
                # queue-transition: running -> failed (attempts budget exhausted)
                record.state = "failed"
                record.done_at = time.time()
            self._persist(record)
            self._clear_sentinel(job_id)
            self._set_depth_gauge()
            return record

    # holds-lock: _lock
    def _clear_sentinel(self, job_id: str) -> None:
        try:
            os.unlink(self._sentinel_path(job_id))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------ views

    def record(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(job_id)

    def by_plan(self, plan_hash: str) -> Optional[JobRecord]:
        with self._lock:
            job_id = self._by_plan.get(plan_hash)
            return self._jobs.get(job_id) if job_id else None

    def counts(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for record in self._jobs.values():
                states[record.state] = states.get(record.state, 0) + 1
            return states
